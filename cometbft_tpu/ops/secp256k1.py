"""Vectorized secp256k1 ECDSA batch verification for TPU
(ROADMAP item 4; the FPGA verification-engine staging of PAPERS.md
arXiv:2112.02229: deep batching + amortized modular inversion +
parallel point multiplication, re-targeted at the vector unit).

This generalizes the word-wise Montgomery limb arithmetic proven for
BLS12-381 in ops/bls381.py to the secp256k1 base field AND its scalar
field: p256k1 = 2^256 - 2^32 - 977 is (like p381, unlike 2^255-19)
not close enough to a power of two for the ops/field.py carry-fold, so
field elements are 22 signed 12-bit limbs in int32 (batch axis
leading, limbs minor), R = 2^264, and every op returns canonical limbs
in [0, m).  The 44-limb product is one outer-product + one constant
anti-diagonal matmul; the reduction is a fori_loop (O(1) jaxpr in the
limb count).  int32 bounds: conv sums <= 22*4095^2 ~ 3.7e8, reduction
adds <= the same again — peak < 7.4e8 < 2^31.  The interval
interpreter (analysis/rangecheck.py) proves the tight version of that
estimate: peak |intermediate| = 716,255,216 across all five secp
kernels (1.58 bits of int32 headroom; certificate entries
``secp256k1_*`` in analysis/range_fingerprints.json).

The ECDSA batch (one fused program per bucket shape):

* **range / low-s validation on device** — r, s enter as raw 256-bit
  limb vectors; 1 <= r < n, 1 <= s < n and the Cosmos/Ethereum low-s
  rule s <= n/2 are borrow-chain compares over the batch.
* **Montgomery batch inversion** — the per-signature s^-1 (mod n) and
  the final affine normalization z^-1 (mod p) are amortized across the
  whole batch: log-depth Hillis-Steele prefix/suffix products, ONE
  Fermat inversion chain of the total product, two muls per row —
  instead of a 256-step exponentiation ladder of full-width batched
  muls per modulus.  Rows that would poison the shared product (s = 0,
  z = 0 from invalid inputs) are sanitized to 1 BEFORE the prefix
  products — the exact latent bug PR 11 found in the ed25519 comb
  table build; a malformed row can never corrupt a valid row's
  inverse (pinned by tests/test_secp_ops.py).
* **GLV quad-scalar multiplication** (the default; ``glv=False`` keeps
  the plain Shamir chain as the bit-exactness witness, the PR-1
  ``COMB_TREE`` pattern) — u1*G + u2*Q with one shared doubling chain.
  The secp256k1 endomorphism phi(x, y) = (beta*x, y) acts as
  multiplication by lambda (a cube root of 1 mod n), so each scalar
  splits as k = k1 + lambda*k2 with |k1|, |k2| < ~2^129 (lattice
  basis from the extended Euclid run on (n, lambda); the rounding is
  two 384-bit-shift multiplies by precomputed constants, Algorithm
  3.74 of Guide to ECC).  The walk then covers 33 4-bit windows over
  FOUR points (G, phi(G), Q, phi(Q) — the phi tables are one
  beta-multiply of the X rows) instead of 66 windows over two: the
  doubling chain that dominates the kernel halves (132 doublings vs
  264; adds stay 132).  Signs fold into per-row conditional Y
  negation of the table lookups.
* **Shamir's-trick double-scalar multiplication** (the witness path) —
  66 4-bit windows: per window 4 doublings + one add from the fixed G
  window table + one add from the per-signature Q table (built on
  device, 1 dbl + 13 adds).  The G table (j*G for j = 0..15, Jacobian
  Montgomery limbs) is precomputed host-side and `jax.device_put` once
  per process — the PR-11 table-residency pattern: no table-build
  program ever compiles, and the resident buffer is passed as a kernel
  argument, never re-staged per call.  Lookups are one-hot matmuls
  (gathers serialize on TPU).
* **verdict** — cosmos rows check x(R') mod n == r (x == r or
  x == r + n when r + n < p, exactly the host's `pt[0] % N == r`);
  eth rows (65-byte R||S||V signatures) check x(R') == r exactly plus
  the recovery-id parity y(R') & 1 == v, which is equivalent to
  Ecrecover(h, sig) == Q (s*R == e*G + r*Q  <=>  R == u1*G + u2*Q).
* **true ecrecover rows** (``recover=True``, a trace-time flag so
  verify-only batches never pay for it) — Ethereum txs carry no
  pubkey, only the 20-byte sender address.  Marked rows lift
  R = (r, sqrt(r^3 + 7)) with the parity v (one batched Fermat
  sqrt chain, x^((p+1)/4)), walk Q = (-e/r)*G + (s/r)*R through the
  SAME quad-scalar chain (u1 = -e*r^-1, u2 = s*r^-1, point = R), and
  compare Keccak256(x || y)[12:] of the recovered point against the
  address — bit-identical to crypto/secp256k1eth.recover_pubkey +
  address() in every edge (non-residue r, infinity, high-s, v > 1).

``hash_verify_batch`` fuses the message hashing in front of all of the
above: cosmos rows through ops/sha2.sha256_blocks, eth/ecrecover rows
through ops/keccak.keccak256_blocks, digests multiplexed per row — one
device program from padded payload bytes to verdict bits, so firehose
ingest never serializes a per-tx host hash loop.

All paths are branch-free selects, so the verdict is bit-identical to
the pure-host crypto/secp256k1 / crypto/secp256k1eth lane in every
edge (tampered rows, high-s, r/s = 0, off-curve keys, infinity
results) — the host lane is the fallback verdict oracle of the
MODE_SECP verify-service lane (models/secp_verifier).
"""

from __future__ import annotations

import threading

import numpy as np
import jax.numpy as jnp
from jax import lax

from ..crypto import secp256k1 as host_secp

NLIMBS = 22
BITS = 12
RADIX = 1 << BITS
MASK = RADIX - 1
NWINDOWS = NLIMBS * BITS // 4  # 66 4-bit windows span the 264 limb bits

P = host_secp.P  # 2^256 - 2^32 - 977
N = host_secp.N  # the group order (the ECDSA scalar field)
R_MONT = 1 << (NLIMBS * BITS)  # 2^264


def _int_to_limbs(x: int, n: int = NLIMBS) -> np.ndarray:
    out = np.zeros(n, dtype=np.int32)
    for i in range(n):
        out[i] = x & MASK
        x >>= BITS
    assert x == 0, "value too wide for limb count"
    return out


class _Mod:
    """Host-side constant bundle for one odd modulus m < 2^264: the limb
    decompositions and Montgomery constants the device ops close over."""

    def __init__(self, m: int):
        self.m = m
        self.limbs = _int_to_limbs(m)
        self.limbs23 = _int_to_limbs(m, NLIMBS + 1)
        self.prime = (-pow(m, -1, RADIX)) % RADIX  # -m^-1 mod 2^12
        self.r2 = _int_to_limbs(R_MONT * R_MONT % m)  # to-Montgomery mul
        self.one_plain = _int_to_limbs(1)  # from-Montgomery mul
        self.one_mont = _int_to_limbs(R_MONT % m)
        # m - 2 bits MSB-first: the Fermat inversion ladder of the ONE
        # total-product inverse in the batch-inversion trick
        self.inv_bits = np.array(
            [b == "1" for b in bin(m - 2)[2:]], dtype=bool
        )

    def to_mont(self, x: int) -> int:
        return x * R_MONT % self.m

    def from_mont(self, x: int) -> int:
        return x * pow(R_MONT, self.m - 2, self.m) % self.m


FP = _Mod(P)
FN = _Mod(N)


# ------------------------------------------------------- GLV decomposition
# The secp256k1 endomorphism: beta is a nontrivial cube root of 1 mod p,
# lambda the matching cube root of 1 mod n, with
# lambda * (x, y) = (beta * x, y) for every curve point.  All constants
# are DERIVED here from the curve parameters (not pasted): beta/lambda
# from small-base exponentiation, the short lattice basis from the
# extended Euclid run on (n, lambda), the rounding multipliers g_i from
# one 384-bit-shift division — and the pairing + decomposition bounds
# are asserted at import, so a wrong constant cannot survive to trace
# time.


def _find_glv() -> tuple[int, int]:
    beta = lam = None
    g = 2
    while beta is None:
        c = pow(g, (P - 1) // 3, P)
        if c != 1:
            beta = c
        g += 1
    g = 2
    while lam is None:
        c = pow(g, (N - 1) // 3, N)
        if c != 1:
            lam = c
        g += 1
    # the two cube roots come with an arbitrary choice each; pick the
    # pair that actually satisfies lambda*G == (beta*Gx, Gy)
    for lc in (lam, lam * lam % N):
        got = host_secp._mul(lc, host_secp.G)
        for bc in (beta, beta * beta % P):
            if got == (bc * host_secp.G[0] % P, host_secp.G[1]):
                return bc, lc
    raise AssertionError("secp256k1 GLV beta/lambda pairing not found")


_BETA, _LAM = _find_glv()


def _glv_basis() -> tuple[int, int, int, int]:
    """Two short lattice vectors (a, b) with a + b*lambda == 0 (mod n)
    (extended Euclid on (n, lambda), stopping at the sqrt(n) crossing —
    Guide to ECC, Alg. 3.74); normalized so det == +n."""
    rs, ts = [N, _LAM], [0, 1]
    while rs[-1] * rs[-1] >= N:
        q = rs[-2] // rs[-1]
        rs.append(rs[-2] - q * rs[-1])
        ts.append(ts[-2] - q * ts[-1])
    q = rs[-2] // rs[-1]
    rs.append(rs[-2] - q * rs[-1])
    ts.append(ts[-2] - q * ts[-1])
    a1, b1 = rs[-2], -ts[-2]
    cand_a = (rs[-3], -ts[-3])
    cand_b = (rs[-1], -ts[-1])
    a2, b2 = min(cand_a, cand_b, key=lambda v: v[0] * v[0] + v[1] * v[1])
    det = a1 * b2 - a2 * b1
    assert abs(det) == N
    if det < 0:
        a2, b2 = -a2, -b2
    assert (a1 + b1 * _LAM) % N == 0 and (a2 + b2 * _LAM) % N == 0
    return a1, b1, a2, b2


_A1, _B1, _A2, _B2 = _glv_basis()

# rounding multipliers: c_i = round(k * |b_j| / n) computed on device as
# (k * g_i + 2^383) >> 384 with g_i = round(2^384 * |b_j| / n) — wide
# enough that the +-1 rounding slack only nudges |k1|, |k2| within their
# ~2^129 bound, never the k1 + lambda*k2 == k identity (k1 is computed
# FROM k2, so the identity holds by construction for every k)
_G1 = ((1 << 384) * abs(_B2) + N // 2) // N
_G2 = ((1 << 384) * abs(_B1) + N // 2) // N
_S1 = 1 if _B2 > 0 else -1  # sign(b2):  c1 = _S1 * round(k*|b2|/n)
_S2 = 1 if _B1 < 0 else -1  # sign(-b1): c2 = _S2 * round(k*|b1|/n)
# k2 = -c1*b1 - c2*b2 folded into unsigned device constants:
# k2 = c1' * M1 + c2' * M2 (mod n) with c_i' the unsigned roundings
_M1 = (-_S1 * _B1) % N
_M2 = (-_S2 * _B2) % N

_G1_LIMBS = _int_to_limbs(_G1)
_G2_LIMBS = _int_to_limbs(_G2)
# Montgomery-form multipliers: mul(plain, const*R) -> plain product
_M1R = _int_to_limbs(_M1 * R_MONT % N)
_M2R = _int_to_limbs(_M2 * R_MONT % N)
_LAMR = _int_to_limbs(_LAM * R_MONT % N)
_BETA_M = _int_to_limbs(_BETA * R_MONT % P)

# signed-halves boundary: the true halves satisfy |k_i| < ~2^129, so a
# canonical k_i in [0, 2^132) is the half itself and anything else is
# k_i - n (2^132 is a clean 11-limb edge -> 33 4-bit windows)
_GLV_SIGN_BOUND = 1 << 132
NWINDOWS_GLV = 33


def _split_host(k: int) -> tuple[int, int]:
    """Host-int mirror of the device split (the import self-check and
    the tests' oracle): k -> signed (k1, k2) with k1 + lambda*k2 == k
    (mod n)."""
    c1 = (k * _G1 + (1 << 383)) >> 384
    c2 = (k * _G2 + (1 << 383)) >> 384
    k2 = (c1 * _M1 + c2 * _M2) % N
    k1 = (k - _LAM * k2) % N
    s1 = k1 if k1 < _GLV_SIGN_BOUND else k1 - N
    s2 = k2 if k2 < _GLV_SIGN_BOUND else k2 - N
    return s1, s2


def _selfcheck_glv() -> None:
    samples = [0, 1, 2, N - 1, N - 2, N // 2, _LAM, N - _LAM]
    x = 1
    for _ in range(56):
        x = x * 3 % N
        samples.append(x)
    for k in samples:
        s1, s2 = _split_host(k)
        assert (s1 + _LAM * s2) % N == k % N, k
        assert abs(s1) < 1 << 130 and abs(s2) < 1 << 130, k


_selfcheck_glv()

# anti-diagonal collector: outer(a, b).reshape @ _DIAG == conv(a, b)
_DIAG = np.zeros((NLIMBS * NLIMBS, 2 * NLIMBS), dtype=np.int32)
for _i in range(NLIMBS):
    for _j in range(NLIMBS):
        _DIAG[_i * NLIMBS + _j, _i + _j] = 1


# ------------------------------------------------------------- primitives
# Identical staging to ops/bls381 (the proven idiom), parameterized by
# the modulus bundle: lax.scan carries keep the jaxpr O(1) in the limb
# count, the Montgomery reduction is a fori_loop of dynamic slices.
# Representation: canonical digits everywhere — every op returns limbs
# in [0, 2^12) with value in [0, m), so limb-wise equality IS value
# equality and window extraction reads digits directly.
#
# Compile-cost note: like the bls381 kernels, the rolled Montgomery
# graphs are expensive to compile cold on the CPU backend (one bucket
# shape ~2 min); the persistent XLA compile cache
# (COMETBFT_TPU_COMPILE_CACHE, on by default in tests and bench — the
# same mitigation the ed25519 verify kernel already relies on) makes
# every later process a cache hit, and the power-of-two bucketing
# keeps the shape set small.


def _carry23(a):
    """Carry chain into 23 canonical-width limbs (signed input limbs;
    any value in (-2^264, 2^265) fits)."""
    aT = jnp.moveaxis(a, -1, 0)  # (L, ...)

    def step(c, limb):
        v = limb + c
        return v >> BITS, v & MASK

    c, outT = lax.scan(step, jnp.zeros_like(aT[0]), aT)
    out = jnp.moveaxis(outT, 0, -1)
    if a.shape[-1] < NLIMBS + 1:
        out = jnp.concatenate([out, c[..., None]], axis=-1)
    return out


def _cond_sub_m(a23, mod: _Mod):
    """One round: subtract m if a >= m (borrow-chain compare+select)."""
    aT = jnp.moveaxis(a23, -1, 0)
    ml = jnp.asarray(mod.limbs23)

    def step(borrow, inp):
        limb, m_i = inp
        v = limb - m_i - borrow
        b = (v < 0).astype(v.dtype)
        return b, v + b * RADIX

    borrow, dT = lax.scan(step, jnp.zeros_like(aT[0]), (aT, ml))
    d = jnp.moveaxis(dT, 0, -1)
    ge = borrow == 0  # no final borrow -> a >= m
    return jnp.where(ge[..., None], d, a23)


def _normalize2m(a, mod: _Mod):
    """Limb vector with value in (-m, 2m) -> canonical [0, m)."""
    return _cond_sub_m(_carry23(a), mod)[..., :NLIMBS]


def add(a, b, mod: _Mod):
    return _normalize2m(a + b, mod)


def sub(a, b, mod: _Mod):
    """a - b (canonical inputs): a + m - b lands in (0, 2m); the signed
    carry chain absorbs the negative intermediate limbs."""
    return _normalize2m(a - b + jnp.asarray(mod.limbs), mod)


def mul(a, b, mod: _Mod):
    """Montgomery product a*b*R^-1 mod m.  Canonical output; inputs may
    be any canonical-DIGIT vectors as long as a*b < R*m (both < m, or
    one < m and the other < R — the raw-input to-Montgomery case).

    int32 bounds: conv limbs <= 22*4095^2 ~ 3.7e8; the reduction adds
    <= the same again (limb j is touched by <= 22 of the 22 q*m adds)
    — peak < 7.4e8 < 2^31; forwarded carries are < 2^18 on top."""
    outer = (a[..., :, None] * b[..., None, :]).reshape(
        a.shape[:-1] + (NLIMBS * NLIMBS,)
    )
    t = outer @ jnp.asarray(_DIAG)  # (..., 44) conv limbs
    pl = jnp.asarray(mod.limbs)
    pprime = mod.prime

    # word-wise reduction: clear limb i by adding q*m at weight i.
    def body(i, t):
        ti = lax.dynamic_index_in_dim(t, i, axis=-1, keepdims=False)
        c = ti >> BITS
        low = ti & MASK
        q = (low * pprime) & MASK
        seg = lax.dynamic_slice_in_dim(t, i, NLIMBS, axis=-1)
        seg = seg + q[..., None] * pl
        t = lax.dynamic_update_slice_in_dim(t, seg, i, axis=-1)
        nxt = lax.dynamic_index_in_dim(t, i + 1, axis=-1, keepdims=False)
        # limb i is (c<<12 + low + q*m0); low + q*m0 ≡ 0 mod 2^12 —
        # forward the whole /2^12 quotient, the final slice drops limb i
        nxt = nxt + c + ((low + q * pl[0]) >> BITS)
        return lax.dynamic_update_index_in_dim(t, nxt, i + 1, axis=-1)

    t = lax.fori_loop(0, NLIMBS, body, t)
    return _normalize2m(t[..., NLIMBS:], mod)


def sqr(a, mod: _Mod):
    return mul(a, a, mod)


def to_mont(a, mod: _Mod):
    """Raw canonical-limb value (< 2^264) -> Montgomery domain, reduced
    mod m (the mul's own reduction absorbs values >= m)."""
    return mul(a, jnp.asarray(mod.r2), mod)


def from_mont(a, mod: _Mod):
    """Montgomery domain -> plain canonical value in [0, m)."""
    return mul(a, jnp.asarray(mod.one_plain), mod)


def select(cond, a, b):
    return jnp.where(cond[..., None], a, b)


def is_zero(a) -> jnp.ndarray:
    """(...,) bool — canonical-input zero test (0 is 0 in Montgomery)."""
    return jnp.all(a == 0, axis=-1)


def _lt_const(a, climbs) -> jnp.ndarray:
    """(..., 22) canonical digits < host constant?  Unrolled
    borrow-chain compare."""
    borrow = jnp.zeros(a.shape[:-1], dtype=a.dtype)
    for i in range(NLIMBS):
        d = a[..., i] - jnp.int32(int(climbs[i])) - borrow
        borrow = lax.shift_right_logical(d, 31) & 1
    return borrow == 1


def _add_const(a, climbs):
    """(..., 22) + host constant, carried back to canonical digits (the
    sum must stay < 2^264; used for r + n < 2^257)."""
    return _carry23(a + jnp.asarray(climbs))[..., :NLIMBS]


# ------------------------------------------------ Montgomery batch inverse


def _mont_pow(x, bits, mod: _Mod):
    """x^E in the Montgomery domain for a fixed host exponent given as
    its MSB-first bit vector: lax.scan keeps the jaxpr one
    square+conditional-multiply body regardless of the bit count.  Used
    for the batch-inversion Fermat chain (E = m - 2) and the ecrecover
    square-root chain (E = (p+1)/4)."""
    one = jnp.broadcast_to(jnp.asarray(mod.one_mont), x.shape)

    def step(acc, bit):
        acc = sqr(acc, mod)
        return jnp.where(bit, mul(acc, x, mod), acc), None

    acc, _ = lax.scan(step, one, jnp.asarray(bits))
    return acc


def _mont_pow_inv(x, mod: _Mod):
    """x^(m-2) — the single Fermat chain of the batch-inversion trick."""
    return _mont_pow(x, mod.inv_bits, mod)


def _shifted(x, k: int, fill):
    """x shifted k rows toward higher indices along axis 0, `fill` rows
    entering at the top (static k: unrolled at trace time)."""
    pad = jnp.broadcast_to(fill, (k,) + x.shape[1:])
    return jnp.concatenate([pad, x[:-k]], axis=0)


def batch_inverse(x, mod: _Mod):
    """Montgomery batch inversion of a (B, 22) Montgomery-domain batch:
    every row's inverse for the price of ONE Fermat chain.

    Hillis-Steele inclusive prefix and suffix products (log2(B)
    full-width batched muls each, unrolled at trace time), one
    exponentiation of the total product, then
    inv_i = exclusive_prefix_i * exclusive_suffix_i * total^-1.

    EVERY row must be nonzero: callers sanitize poisonable rows to 1
    (with their verdict masked off) BEFORE calling — a zero row would
    zero the total product and corrupt every other row's inverse.
    """
    one = jnp.asarray(mod.one_mont)
    n = x.shape[0]
    pre = x
    suf = x[::-1]
    k = 1
    while k < n:
        pre = mul(pre, _shifted(pre, k, one), mod)
        suf = mul(suf, _shifted(suf, k, one), mod)
        k *= 2
    suf = suf[::-1]  # inclusive suffix products
    total = pre[-1]
    tinv = _mont_pow_inv(total, mod)
    left = jnp.concatenate([one[None], pre[:-1]], axis=0)
    right = jnp.concatenate([suf[1:], one[None]], axis=0)
    part = mul(left, right, mod)  # prod of all rows but i
    return mul(part, jnp.broadcast_to(tinv, x.shape), mod)


# ------------------------------------------------------------- group ops
# y^2 = x^3 + 7, a = 0: the same complete-by-selects Jacobian formulas
# as ops/bls381 (both curves are a = 0 short Weierstrass).  Infinity is
# Z = 0; all coordinates Montgomery-domain canonical limbs mod p.

_B7_M = _int_to_limbs(FP.to_mont(host_secp.B))  # curve b = 7


def pt_double(X, Y, Z):
    A = sqr(X, FP)
    Bb = sqr(Y, FP)
    Cc = sqr(Bb, FP)
    t = sqr(add(X, Bb, FP), FP)
    D = sub(t, add(A, Cc, FP), FP)
    D = add(D, D, FP)
    E = add(add(A, A, FP), A, FP)
    F = sqr(E, FP)
    X3 = sub(F, add(D, D, FP), FP)
    eight_c = add(add(Cc, Cc, FP), add(Cc, Cc, FP), FP)
    eight_c = add(eight_c, eight_c, FP)
    Y3 = sub(mul(E, sub(D, X3, FP), FP), eight_c, FP)
    Z3 = mul(add(Y, Y, FP), Z, FP)
    return X3, Y3, Z3


def pt_add(X1, Y1, Z1, X2, Y2, Z2):
    """Branch-free complete addition over the batch via selects."""
    z1z = sqr(Z1, FP)
    z2z = sqr(Z2, FP)
    U1 = mul(X1, z2z, FP)
    U2 = mul(X2, z1z, FP)
    S1 = mul(mul(Y1, Z2, FP), z2z, FP)
    S2 = mul(mul(Y2, Z1, FP), z1z, FP)
    H = sub(U2, U1, FP)
    Rr = sub(S2, S1, FP)
    h_zero = is_zero(H)
    r_zero = is_zero(Rr)
    inf1 = is_zero(Z1)
    inf2 = is_zero(Z2)

    I = sqr(add(H, H, FP), FP)
    J = mul(H, I, FP)
    r2 = add(Rr, Rr, FP)
    V = mul(U1, I, FP)
    X3 = sub(sqr(r2, FP), add(J, add(V, V, FP), FP), FP)
    Y3 = sub(
        mul(r2, sub(V, X3, FP), FP), mul(add(S1, S1, FP), J, FP), FP
    )
    Z3 = mul(mul(Z1, Z2, FP), H, FP)
    Z3 = add(Z3, Z3, FP)

    dX, dY, dZ = pt_double(X1, Y1, Z1)
    same = h_zero & r_zero & ~inf1 & ~inf2
    neg = h_zero & ~r_zero & ~inf1 & ~inf2
    X3 = select(same, dX, X3)
    Y3 = select(same, dY, Y3)
    Z3 = select(same, dZ, Z3)
    X3 = select(neg, jnp.zeros_like(X3), X3)
    Y3 = select(neg, jnp.zeros_like(Y3), Y3)
    Z3 = select(neg, jnp.zeros_like(Z3), Z3)
    X3 = select(inf1, X2, X3)
    Y3 = select(inf1, Y2, Y3)
    Z3 = select(inf1, Z2, Z3)
    X3 = select(inf2 & ~inf1, X1, X3)
    Y3 = select(inf2 & ~inf1, Y1, Y3)
    Z3 = select(inf2 & ~inf1, Z1, Z3)
    return X3, Y3, Z3


def on_curve(X_m, Y_m) -> jnp.ndarray:
    """(..., 22) affine Montgomery limbs -> (...,) bool: y^2 == x^3 + 7.
    Canonical-limb equality is value equality (both sides in [0, p))."""
    lhs = sqr(Y_m, FP)
    rhs = add(mul(sqr(X_m, FP), X_m, FP), jnp.asarray(_B7_M), FP)
    return jnp.all(lhs == rhs, axis=-1)


# --------------------------------------------------- fixed G window table


def _build_g_table() -> np.ndarray:
    """(16, 66) int32: j*G for j = 0..15 as flattened Jacobian triples
    (X | Y | Z, 22 Montgomery limbs each; j = 0 -> infinity, Z = 0).
    Pure host bigint — the PR-11 residency pattern: NO table-build
    program ever compiles; `g_table()` device_puts this once."""
    out = np.zeros((16, 3 * NLIMBS), dtype=np.int32)
    out[0, :NLIMBS] = _int_to_limbs(FP.to_mont(1))
    out[0, NLIMBS : 2 * NLIMBS] = _int_to_limbs(FP.to_mont(1))
    acc = None
    for j in range(1, 16):
        acc = host_secp._add(acc, host_secp.G)
        out[j, :NLIMBS] = _int_to_limbs(FP.to_mont(acc[0]))
        out[j, NLIMBS : 2 * NLIMBS] = _int_to_limbs(FP.to_mont(acc[1]))
        out[j, 2 * NLIMBS :] = _int_to_limbs(FP.to_mont(1))
    return out


_G_TABLE_NP = _build_g_table()
_G_TABLE_DEV = None
_G_TABLE_MTX = threading.Lock()


def g_table():
    """The resident device copy of the G window table: host-precomputed,
    `device_put` once per process, passed to the kernel as an argument
    so it is never re-staged per dispatch (PR-11 table residency)."""
    global _G_TABLE_DEV
    if _G_TABLE_DEV is None:
        with _G_TABLE_MTX:
            if _G_TABLE_DEV is None:
                import jax

                _G_TABLE_DEV = jax.device_put(_G_TABLE_NP)
    return _G_TABLE_DEV


def _lookup_g(gtab, idx):
    """One-hot select from the (16, 66) flat G table by (B,) idx."""
    onehot = (
        idx[:, None] == jnp.arange(16, dtype=jnp.int32)[None, :]
    ).astype(jnp.int32)  # (B, 16)
    sel = onehot @ gtab  # (B, 66)
    return (
        sel[:, :NLIMBS],
        sel[:, NLIMBS : 2 * NLIMBS],
        sel[:, 2 * NLIMBS :],
    )


def _build_q_table(Qx, Qy, Qz):
    """Stacked (16, B, 22) Jacobian window table [0..15]*Q, built as a
    14-step lax.scan of one complete add (the addition law's own
    same-point branch makes entry 2 a doubling), so the jaxpr carries
    ONE add body instead of 13 unrolled ones.  Sanitized rows enter
    with Z = 0, so every multiple of them stays infinity."""
    one = jnp.broadcast_to(jnp.asarray(FP.one_mont), Qx.shape)
    inf = (one, one, jnp.zeros_like(Qx))

    def step(acc, _):
        nxt = pt_add(acc[0], acc[1], acc[2], Qx, Qy, Qz)
        return nxt, nxt

    _, tail = lax.scan(step, (Qx, Qy, Qz), None, length=14)  # 2Q..15Q
    return (
        jnp.concatenate([inf[0][None], Qx[None], tail[0]], axis=0),
        jnp.concatenate([inf[1][None], Qy[None], tail[1]], axis=0),
        jnp.concatenate([inf[2][None], Qz[None], tail[2]], axis=0),
    )


def _lookup_q(qtab, idx):
    """One-hot select from a stacked (16, B, 22) table by (B,) idx."""
    onehot = (
        idx[None, :] == jnp.arange(16, dtype=jnp.int32)[:, None]
    ).astype(jnp.int32)[..., None]  # (16, B, 1)
    tX, tY, tZ = qtab
    return (
        jnp.sum(tX * onehot, axis=0),
        jnp.sum(tY * onehot, axis=0),
        jnp.sum(tZ * onehot, axis=0),
    )


def _windows(a):
    """(B, 22) canonical limbs -> (66, B) int32 4-bit windows, MSB
    first (each 12-bit limb is three windows)."""
    w = jnp.stack([a & MASK, a >> 4, a >> 8], axis=-1) & 15  # (B, 22, 3)
    w = w.reshape(a.shape[0], NWINDOWS)
    return w[:, ::-1].T


# ------------------------------------------------------ GLV device half


def _carry_all(a):
    """Signed conv limbs -> canonical digits at the SAME width (the
    final carry must be provably zero: callers bound the value below
    2^(12*width))."""
    aT = jnp.moveaxis(a, -1, 0)

    def step(c, limb):
        v = limb + c
        return v >> BITS, v & MASK

    _, outT = lax.scan(step, jnp.zeros_like(aT[0]), aT)
    return jnp.moveaxis(outT, 0, -1)


def _mul_shift_384(k, glimbs):
    """round(k * g / 2^384) for a (B, 22) canonical scalar and a host
    constant g < 2^264: one outer-product conv (the mul staging, no
    reduction), +2^383 into the conv limbs (limb 31, weight 2^372,
    value 2^11), a full carry chain (product + rounder < 2^521 < 2^528
    so the 44-digit carry is exact), then the digits above bit 384
    (limb 32 up) — 12 digits, zero-padded back to a (B, 22) scalar."""
    outer = (k[..., :, None] * jnp.asarray(glimbs)[None, :]).reshape(
        k.shape[:-1] + (NLIMBS * NLIMBS,)
    )
    t = outer @ jnp.asarray(_DIAG)  # (B, 44) conv limbs
    t = t.at[..., 31].add(1 << 11)  # + 2^383 = round-half-up
    t = _carry_all(t)
    hi = t[..., 32:]  # digits of weight >= 2^384
    pad = jnp.zeros(k.shape[:-1] + (NLIMBS - hi.shape[-1],), dtype=k.dtype)
    return jnp.concatenate([hi, pad], axis=-1)


def _signed_abs(k):
    """Canonical k in [0, n) holding a signed half -> (|half|, neg):
    halves are < 2^130 in magnitude, so k < 2^132 IS the half and
    anything else encodes k - n."""
    neg = ~_lt_const(k, _int_to_limbs(_GLV_SIGN_BOUND))
    kabs = select(neg, sub(jnp.zeros_like(k), k, FN), k)
    return kabs, neg


def _glv_split(k):
    """(B, 22) plain canonical scalar mod n -> the quad-walk's signed
    halves (|k1|, k1_neg, |k2|, k2_neg) with k1 + lambda*k2 == k (mod
    n).  Mirrors :func:`_split_host` limb for limb."""
    c1 = _mul_shift_384(k, _G1_LIMBS)
    c2 = _mul_shift_384(k, _G2_LIMBS)
    k2 = add(
        mul(c1, jnp.asarray(_M1R), FN), mul(c2, jnp.asarray(_M2R), FN), FN
    )
    k1 = sub(k, mul(k2, jnp.asarray(_LAMR), FN), FN)
    k1a, k1n = _signed_abs(k1)
    k2a, k2n = _signed_abs(k2)
    return k1a, k1n, k2a, k2n


def _windows_glv(a):
    """(B, 22) canonical |half| (< 2^132, limbs 11+ all zero) ->
    (33, B) 4-bit windows, MSB first."""
    h = a[:, : NWINDOWS_GLV // 3]  # 11 limbs cover the 132 live bits
    w = jnp.stack([h & MASK, h >> 4, h >> 8], axis=-1) & 15
    w = w.reshape(a.shape[0], NWINDOWS_GLV)
    return w[:, ::-1].T


def _neg_y(Y, flag):
    """Per-row conditional point negation (Jacobian: negate Y).  Folded
    signs of the GLV halves; canonical 0 stays 0."""
    return select(flag, sub(jnp.zeros_like(Y), Y, FP), Y)


# ------------------------------------------------- the two walk variants


def _walk_shamir(u1, u2, qtab, gtab):
    """The non-GLV bit-exactness witness: 66 shared windows, per window
    4 doublings (rolled scan) + one G-table add + one Q-table add."""
    u1w = _windows(u1)
    u2w = _windows(u2)
    one_m = jnp.broadcast_to(jnp.asarray(FP.one_mont), u1.shape)

    def step(i, acc):
        # 4 doublings as a rolled scan: one doubling body in the jaxpr
        # instead of four (compile cost, not semantics)
        (X, Y, Z), _ = lax.scan(
            lambda p, _: (pt_double(*p), None), acc, None, length=4
        )
        gX, gY, gZ = _lookup_g(
            gtab, lax.dynamic_index_in_dim(u1w, i, axis=0, keepdims=False)
        )
        X, Y, Z = pt_add(X, Y, Z, gX, gY, gZ)
        qX, qY, qZ = _lookup_q(
            qtab, lax.dynamic_index_in_dim(u2w, i, axis=0, keepdims=False)
        )
        X, Y, Z = pt_add(X, Y, Z, qX, qY, qZ)
        return (X, Y, Z)

    inf = (one_m, one_m, jnp.zeros_like(u1))
    return lax.fori_loop(0, NWINDOWS, step, inf)


def _walk_glv(u1, u2, qtab, gtab):
    """The GLV quad-scalar walk: both scalars split into signed halves,
    33 shared windows over G, phi(G), Q, phi(Q) — half the doubling
    chain of :func:`_walk_shamir` for the same four adds per window.
    The phi tables are one beta-multiply of the X rows (phi is
    (beta*X, Y, Z) in Jacobian too: x_aff = X/Z^2 scales by beta);
    negative halves negate the looked-up Y per row."""
    k1a, k1n, k2a, k2n = _glv_split(u1)
    l1a, l1n, l2a, l2n = _glv_split(u2)
    wg, wpg = _windows_glv(k1a), _windows_glv(k2a)
    wq, wpq = _windows_glv(l1a), _windows_glv(l2a)

    beta16 = jnp.broadcast_to(jnp.asarray(_BETA_M), (16, NLIMBS))
    pg_tab = jnp.concatenate(
        [mul(gtab[:, :NLIMBS], beta16, FP), gtab[:, NLIMBS:]], axis=-1
    )
    tX, tY, tZ = qtab
    pq_tab = (
        mul(tX, jnp.broadcast_to(jnp.asarray(_BETA_M), tX.shape), FP),
        tY,
        tZ,
    )
    one_m = jnp.broadcast_to(jnp.asarray(FP.one_mont), u1.shape)

    def step(i, acc):
        (X, Y, Z), _ = lax.scan(
            lambda p, _: (pt_double(*p), None), acc, None, length=4
        )
        for tab, w, neg, look in (
            (gtab, wg, k1n, _lookup_g),
            (pg_tab, wpg, k2n, _lookup_g),
            (qtab, wq, l1n, _lookup_q),
            (pq_tab, wpq, l2n, _lookup_q),
        ):
            aX, aY, aZ = look(
                tab, lax.dynamic_index_in_dim(w, i, axis=0, keepdims=False)
            )
            X, Y, Z = pt_add(X, Y, Z, aX, _neg_y(aY, neg), aZ)
        return (X, Y, Z)

    inf = (one_m, one_m, jnp.zeros_like(u1))
    return lax.fori_loop(0, NWINDOWS_GLV, step, inf)


# ------------------------------------------- ecrecover / hashing helpers

# (p+1)/4 MSB-first: the Fermat square-root chain of the R-lift
_SQRT_BITS = np.array([b == "1" for b in bin((P + 1) // 4)[2:]], dtype=bool)

# canonical 12-bit limbs (LE) <-> 32 big-endian bytes, as static gathers:
# BE byte j is LE byte k = 31-j, which spans limbs q = 2k//3 and q+1 at
# in-limb shift 8k - 12q in {0, 4, 8}
_BE_Q = np.array([(2 * (31 - j)) // 3 for j in range(32)], dtype=np.int32)
_BE_SH = np.array(
    [8 * (31 - j) - 12 * ((2 * (31 - j)) // 3) for j in range(32)],
    dtype=np.int32,
)
# digest bytes (BE) -> limbs: limb i spans LE bytes k0 = 12i//8 and
# k0+1 at shift 12i - 8*k0 in {0, 4} (top limb reads past byte 31 ->
# two zero pad bytes)
_E_K0 = np.array([(12 * i) // 8 for i in range(NLIMBS)], dtype=np.int32)
_E_SH = np.array(
    [12 * i - 8 * ((12 * i) // 8) for i in range(NLIMBS)], dtype=np.int32
)


def _limbs_to_bytes_be(a):
    """(B, 22) plain canonical limbs (< 2^256) -> (B, 32) uint8, big
    endian — the recovered point's coordinates as Keccak input."""
    lo = a[..., _BE_Q]
    hi = a[..., _BE_Q + 1]
    val = lo + (hi << 12)  # <= 4095 + 4095*4096 < 2^24: int32-safe
    return ((val >> jnp.asarray(_BE_SH)) & 255).astype(jnp.uint8)


def _digest_to_limbs(dig):
    """(B, 32) uint8 big-endian digest -> (B, 22) int32 canonical limbs
    (the raw 256-bit e the verify path expects)."""
    le = dig[..., ::-1].astype(jnp.int32)
    pad = jnp.zeros(dig.shape[:-1] + (2,), dtype=jnp.int32)
    le = jnp.concatenate([le, pad], axis=-1)
    val = le[..., _E_K0] + (le[..., _E_K0 + 1] << 8)
    return (val >> jnp.asarray(_E_SH)) & MASK


# Keccak block for the 64-byte x || y preimage: pad10*1 tail as a host
# constant (0x01 at offset 64, 0x80 at 135; 136-byte rate, one block)
_ADDR_PAD = np.zeros(72, dtype=np.uint8)
_ADDR_PAD[0] = 0x01
_ADDR_PAD[-1] = 0x80


def _address_from_affine(x_aff, y_aff):
    """Plain affine limbs -> (B, 20) uint8 Ethereum address:
    Keccak256(x_be || y_be)[12:], one single-block batched permutation
    (ops/keccak)."""
    from . import keccak as _keccak

    xb = _limbs_to_bytes_be(x_aff)
    yb = _limbs_to_bytes_be(y_aff)
    tail = jnp.broadcast_to(
        jnp.asarray(_ADDR_PAD), x_aff.shape[:-1] + (_ADDR_PAD.shape[0],)
    )
    block = jnp.concatenate([xb, yb, tail], axis=-1)
    dig = _keccak.keccak256_blocks(block[..., None, :])
    return dig[..., 12:32]


# ----------------------------------------------------------- verification


def verify_batch(
    qx, qy, q_valid, e, r, s, is_eth, v, is_rec, addr, gtab,
    *, glv=True, recover=False,
):
    """Batched ECDSA verification, one fused device program.

    qx, qy  : (B, 22) int32 — affine pubkey coordinates, PLAIN canonical
              limbs (host decode/decompress already rejected malformed
              encodings via q_valid; garbage limbs on invalid rows are
              harmless — they feed only multiplications)
    q_valid : (B,) bool — host-side decode verdict
    e       : (B, 22) int32 — raw 256-bit message-hash value (SHA-256
              for cosmos rows, Keccak-256 for eth/ecrecover rows); the
              Montgomery conversion reduces it mod n like the host's % N
    r, s    : (B, 22) int32 — raw signature scalars
    is_eth  : (B,) bool — row wire format: eth R||S||V recovery
              semantics vs cosmos compressed-key semantics
    v       : (B,) int32 — recovery id (0/1); ignored on cosmos rows
    is_rec  : (B,) bool — true ecrecover rows (no pubkey: recover the
              signer from r/v and compare addresses).  Only honored
              under ``recover=True``; callers without such rows pass
              all-False and the cheaper program
    addr    : (B, 20) uint8 — expected sender address on ecrecover rows
    gtab    : (16, 66) int32 — the resident G window table
              (:func:`g_table`), an ARGUMENT so the device_put buffer is
              reused across dispatches instead of re-staged as a baked
              constant
    glv     : trace-time: GLV quad-scalar walk (default) vs the plain
              Shamir witness walk — bit-identical by contract
              (tests/test_secp_glv.py), knob-selected like COMB_TREE
    recover : trace-time: compile the R-lift sqrt chain + the on-device
              address Keccak.  False keeps verify-only batches on a
              program that never pays for either

    Returns (B,) bool, bit-identical to the host verifiers.

    Manifest kernels ``secp256k1_verify_batch[_recover][ _noglv]``
    (analysis/kernel_manifest): eqn-budgeted and fingerprint-pinned per
    (glv, recover) variant; the jit site is the bridge's module-cached
    ``jax.jit(verify_batch, static_argnames=...)`` in JIT_SITES.
    """
    # ---- validation (device half): on-curve + scalar ranges + low-s
    qx_m = to_mont(qx, FP)
    qy_m = to_mont(qy, FP)
    n_l = FN.limbs
    r_ok = ~is_zero(r) & _lt_const(r, n_l)
    s_ok = (
        ~is_zero(s)
        & _lt_const(s, n_l)
        & _lt_const(s, _int_to_limbs(N // 2 + 1))  # low-s: s <= n/2
    )
    if recover:
        v_ok = jnp.where(is_eth | is_rec, v <= 1, True)
        # R-lift: x = r, y = sqrt(x^3 + 7) via x^((p+1)/4), flipped to
        # the parity v — exactly host recover_pubkey's lift (which
        # rejects r >= n before lifting, as r_ok does here)
        rx_m = to_mont(r, FP)
        y2 = add(mul(sqr(rx_m, FP), rx_m, FP), jnp.asarray(_B7_M), FP)
        y_m = _mont_pow(y2, _SQRT_BITS, FP)
        lift_ok = jnp.all(sqr(y_m, FP) == y2, axis=-1)  # y2 was a QR
        y_plain = from_mont(y_m, FP)
        flip = (y_plain[:, 0] & 1) != v
        ry_m = select(flip, sub(jnp.zeros_like(y_m), y_m, FP), y_m)
        q_ok = jnp.where(is_rec, lift_ok, q_valid & on_curve(qx_m, qy_m))
        Px_m = select(is_rec, rx_m, qx_m)
        Py_m = select(is_rec, ry_m, qy_m)
    else:
        v_ok = jnp.where(is_eth, v <= 1, True)
        q_ok = q_valid & on_curve(qx_m, qy_m)
        Px_m, Py_m = qx_m, qy_m
    row_pre = q_ok & r_ok & s_ok & v_ok

    # ---- scalars, the shared denominator amortized across the batch:
    # verify rows    u1 = e/s,  u2 = r/s  (mod n)
    # ecrecover rows u1 = -e/r, u2 = s/r  (Q = r^-1 (s*R - e*G))
    # Sanitize BEFORE the shared product: a zero denominator row would
    # zero the total and poison every valid row's inverse.
    one_plain = jnp.asarray(FN.one_plain)
    if recover:
        w_in = select(is_rec, r, s)
        w_in_ok = jnp.where(is_rec, r_ok, s_ok)
    else:
        w_in = s
        w_in_ok = s_ok
    w_safe = select(w_in_ok, w_in, jnp.broadcast_to(one_plain, s.shape))
    w_m = batch_inverse(to_mont(w_safe, FN), FN)
    e_m = to_mont(e, FN)  # to-Montgomery reduces mod n (host: e % N)
    u1_m = mul(e_m, w_m, FN)
    if recover:
        u1_m = select(
            is_rec, sub(jnp.zeros_like(u1_m), u1_m, FN), u1_m
        )
        u2_src_m = select(is_rec, to_mont(s, FN), to_mont(r, FN))
    else:
        u2_src_m = to_mont(r, FN)
    u1 = from_mont(u1_m, FN)
    u2 = from_mont(mul(u2_src_m, w_m, FN), FN)

    # ---- the double-scalar walk: u1*G + u2*P with P the pubkey (or
    # the lifted R on ecrecover rows); invalid rows enter as infinity
    one_m = jnp.broadcast_to(jnp.asarray(FP.one_mont), qx.shape)
    Pz = select(q_ok, one_m, jnp.zeros_like(qx))
    qtab = _build_q_table(Px_m, Py_m, Pz)
    if glv:
        X, Y, Z = _walk_glv(u1, u2, qtab, gtab)
    else:
        X, Y, Z = _walk_shamir(u1, u2, qtab, gtab)

    # ---- affine normalization, z^-1 amortized across the batch (the
    # second shared inversion; Z = 0 rows sanitized exactly like s = 0)
    z_nonzero = ~is_zero(Z)
    z_safe = select(z_nonzero, Z, jnp.broadcast_to(jnp.asarray(FP.one_mont), Z.shape))
    zinv = batch_inverse(z_safe, FP)
    zi2 = sqr(zinv, FP)
    x_aff = from_mont(mul(X, zi2, FP), FP)
    y_aff = from_mont(mul(mul(Y, zi2, FP), zinv, FP), FP)

    # ---- verdict
    rn = _add_const(r, n_l)  # r + n (< 2^257, fits the limb vector)
    x_eq_r = jnp.all(x_aff == r, axis=-1)
    cosmos_ok = x_eq_r | (
        _lt_const(rn, FP.limbs) & jnp.all(x_aff == rn, axis=-1)
    )
    eth_ok = x_eq_r & ((y_aff[:, 0] & 1) == v)
    if recover:
        # the walked point IS the recovered pubkey: address-compare it
        rec_ok = jnp.all(_address_from_affine(x_aff, y_aff) == addr, axis=-1)
        verdict = jnp.where(
            is_rec, rec_ok, jnp.where(is_eth, eth_ok, cosmos_ok)
        )
    else:
        verdict = jnp.where(is_eth, eth_ok, cosmos_ok)
    return row_pre & z_nonzero & verdict


def hash_verify_batch(
    sha_blocks, sha_active, kec_blocks, kec_active,
    qx, qy, q_valid, r, s, is_eth, v, is_rec, addr, gtab,
    *, glv=True, recover=False,
):
    """The fused hash->verify program: padded message bytes in, verdict
    bits out — ONE dispatch, so firehose ingest never serializes a
    per-tx host hash loop (the hashing-residency seam documented in
    docs/verify_service.md).

    sha_blocks / sha_active : (B, nb, 64) uint8 + (B,) int32 — every
        row's message SHA-256-padded (ops/sha2.pad_messages_sha256)
    kec_blocks / kec_active : (B, nb', 136) uint8 + (B,) int32 — the
        SAME messages Keccak-padded (ops/keccak.pad_messages_keccak)
    remaining args/kwargs   : exactly :func:`verify_batch` minus ``e``

    Both digests are computed for every row (branch-free batch; the
    loser is masked per row), then multiplexed: Keccak-256 for
    eth/ecrecover rows, SHA-256 for cosmos rows — matching the host
    hash choice bit for bit.

    Manifest kernels ``secp256k1_hash_verify[_recover]``; jit site is
    the module-cached bridge below.
    """
    from . import keccak as _keccak
    from . import sha2 as _sha2

    sha_d = _sha2.sha256_blocks(sha_blocks, sha_active)
    kec_d = _keccak.keccak256_blocks(kec_blocks, kec_active)
    dig = jnp.where((is_eth | is_rec)[..., None], kec_d, sha_d)
    e = _digest_to_limbs(dig)
    return verify_batch(
        qx, qy, q_valid, e, r, s, is_eth, v, is_rec, addr, gtab,
        glv=glv, recover=recover,
    )


# ------------------------------------------------------------ host bridge


_VERIFY_JIT = None
_HASH_VERIFY_JIT = None
_JIT_MTX = threading.Lock()


def ints_to_limbs_np(vals) -> np.ndarray:
    """Vectorized host packer: a sequence of plain ints (< 2^264) ->
    (B, 22) int32 limb array — one numpy pass over the little-endian
    bytes (3 bytes = 2 limbs), same staging as ops/bls381."""
    n = len(vals)
    if n == 0:
        return np.zeros((0, NLIMBS), dtype=np.int32)
    raw = np.frombuffer(
        b"".join(v.to_bytes(33, "little") for v in vals), dtype=np.uint8
    ).reshape(n, 33)
    trip = raw.reshape(n, NLIMBS // 2, 3).astype(np.int32)
    out = np.empty((n, NLIMBS), dtype=np.int32)
    out[:, 0::2] = trip[..., 0] | ((trip[..., 1] & 0xF) << 8)
    out[:, 1::2] = (trip[..., 1] >> 4) | (trip[..., 2] << 4)
    return out


def from_limbs(a) -> np.ndarray:
    """Host-side limb decoder (plain, NON-Montgomery limbs) -> object
    array of Python ints; receives already-fetched device results."""
    a = np.asarray(a)
    flat = a.reshape(-1, a.shape[-1])
    out = np.empty(flat.shape[0], dtype=object)
    for i, row in enumerate(flat):
        val = 0
        for k in range(len(row) - 1, -1, -1):
            val = (val << BITS) + int(row[k])
        out[i] = val
    return out.reshape(a.shape[:-1])


def _rec_defaults(b: int, is_rec, addr):
    if is_rec is None:
        is_rec = np.zeros((b,), dtype=bool)
    if addr is None:
        addr = np.zeros((b, 20), dtype=np.uint8)
    return is_rec, addr


def verify_batch_device(
    qx, qy, q_valid, e, r, s, is_eth, v,
    is_rec=None, addr=None, glv=True, timings=None,
) -> np.ndarray:
    """One device dispatch of the batched ECDSA kernel over pre-packed
    host arrays; the blocking result fetch is this bridge's declared
    collect point (analysis/kernel_manifest.COLLECT_BOUNDARIES).

    The ``recover`` trace flag is derived here: batches without
    ecrecover rows ride the cheaper program (no sqrt chain, no address
    Keccak).  When ``timings`` is a dict the bridge splits its wall
    time into h2d / kernel / fetch milliseconds (additive — repeated
    dispatches accumulate) for the bench/profiler phase attribution."""
    import time

    import jax

    global _VERIFY_JIT
    if _VERIFY_JIT is None:
        with _JIT_MTX:
            if _VERIFY_JIT is None:
                _VERIFY_JIT = jax.jit(
                    verify_batch, static_argnames=("glv", "recover")
                )
    is_rec, addr = _rec_defaults(qx.shape[0], is_rec, addr)
    t0 = time.perf_counter()
    dev_args = (
        jnp.asarray(qx),
        jnp.asarray(qy),
        jnp.asarray(q_valid),
        jnp.asarray(e),
        jnp.asarray(r),
        jnp.asarray(s),
        jnp.asarray(is_eth),
        jnp.asarray(v),
        jnp.asarray(is_rec),
        jnp.asarray(addr),
        g_table(),
    )
    t1 = time.perf_counter()
    ok = _VERIFY_JIT(
        *dev_args, glv=bool(glv), recover=bool(np.any(is_rec))
    )
    ok.block_until_ready()
    t2 = time.perf_counter()
    out = np.asarray(ok)
    if timings is not None:
        t3 = time.perf_counter()
        timings["h2d_ms"] = timings.get("h2d_ms", 0.0) + (t1 - t0) * 1e3
        timings["kernel_ms"] = (
            timings.get("kernel_ms", 0.0) + (t2 - t1) * 1e3
        )
        timings["fetch_ms"] = timings.get("fetch_ms", 0.0) + (t3 - t2) * 1e3
    return out


def hash_verify_batch_device(
    sha_blocks, sha_active, kec_blocks, kec_active,
    qx, qy, q_valid, r, s, is_eth, v,
    is_rec=None, addr=None, glv=True, timings=None,
) -> np.ndarray:
    """The fused hash->verify dispatch (device-resident hashing); same
    collect-point and ``timings`` contract as
    :func:`verify_batch_device`."""
    import time

    import jax

    global _HASH_VERIFY_JIT
    if _HASH_VERIFY_JIT is None:
        with _JIT_MTX:
            if _HASH_VERIFY_JIT is None:
                _HASH_VERIFY_JIT = jax.jit(
                    hash_verify_batch, static_argnames=("glv", "recover")
                )
    is_rec, addr = _rec_defaults(qx.shape[0], is_rec, addr)
    t0 = time.perf_counter()
    dev_args = (
        jnp.asarray(sha_blocks),
        jnp.asarray(sha_active),
        jnp.asarray(kec_blocks),
        jnp.asarray(kec_active),
        jnp.asarray(qx),
        jnp.asarray(qy),
        jnp.asarray(q_valid),
        jnp.asarray(r),
        jnp.asarray(s),
        jnp.asarray(is_eth),
        jnp.asarray(v),
        jnp.asarray(is_rec),
        jnp.asarray(addr),
        g_table(),
    )
    t1 = time.perf_counter()
    ok = _HASH_VERIFY_JIT(
        *dev_args, glv=bool(glv), recover=bool(np.any(is_rec))
    )
    ok.block_until_ready()
    t2 = time.perf_counter()
    out = np.asarray(ok)
    if timings is not None:
        t3 = time.perf_counter()
        timings["h2d_ms"] = timings.get("h2d_ms", 0.0) + (t1 - t0) * 1e3
        timings["kernel_ms"] = (
            timings.get("kernel_ms", 0.0) + (t2 - t1) * 1e3
        )
        timings["fetch_ms"] = timings.get("fetch_ms", 0.0) + (t3 - t2) * 1e3
    return out
