"""Comb-based cached Ed25519 verification: the validator-set fast path.

The Straus kernel (ops/ed25519.verify_prepared) spends most of its time in
the 256 shared doublings.  For commit verification the pubkeys are known
long in advance — the validator set changes rarely — so this module trades
HBM for those doublings entirely:

  - per-validator comb tables  T[i][j][v] = j * 16^i * (-A_v),  i<64,
    j<=8 (SIGNED digits: negative digits negate the entry at lookup, so
    only half the entries are stored), in affine Niels form
    (y+x, y-x, 2dxy), built once per validator set and kept
    device-resident (~152 KB/validator; a 10k-validator set is 1.5 GB
    of the chip's 16 GB HBM).  This is the TPU analogue of the reference's
    expanded-pubkey LRU (crypto/ed25519/ed25519.go:43,68), scaled to the
    whole validator set.  Layout (64, 9, 3, 22, V): the validator axis is
    MINOR so every select/add runs with full lane utilization (see
    ops/field.py module doc).
  - a shared radix-4096 comb for the base point B:
    B_TAB[i] = (66, 4096) f32 with column j holding j*4096^i*B, looked up
    with one (66, 4096) x (4096, V) matmul per position on the MXU.

verify_cached then needs NO doublings and NO per-signature table build:
   acc = sum_i T[i][k_i][v]  +  sum_i B_TAB[i][s_i]  - R,   check [8]acc = 0
64 + 22 + 1 additions and one point decompression (R) per signature,
versus 256 doublings + 128 additions + 2 decompressions + table build for
the uncached kernel.

Verification semantics are identical (ZIP-215 / cofactored; see
ops/ed25519.py module doc); tests/test_comb.py checks agreement against
both the uncached kernel and the host verifier.

Range contracts (analysis/rangecheck.py; certificate entries
``comb_*`` in analysis/range_fingerprints.json): the f32 comb planes
never carry more than a single 12-bit digit per partial sum — the
one-hot table lookups are proved to select, not accumulate, so the
peak |f32 value| is 4095, leaving ~12 bits of slack under the 2^24
exact-integer envelope (docs/limb_headroom.md: that slack is what
funds wider comb digits).  The int32 plane peaks at 1,252,794,005 in
the shared field walk.  One proved-adversarial hazard shapes this
module: comb tables are attacker-influenced device inputs (a hostile
validator key produces arbitrary canonical table coords), and the
TREE accumulation path sums two lifted Niels points before the first
field mul — without the F.carry in ed25519.niels_to_extended those
sums exceed the MULIN mul-input bound and the conv partial sums
clear 2^31.  The certificate pins the carried version; the rangecheck
gate fails any regression.
"""

from __future__ import annotations

import threading

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from . import ed25519 as E
from . import field as F
from . import scalar
from ..crypto import _ref25519 as ref

NPOS_A = 64  # radix-16 comb positions for the k*(-A) part
NENT_A = 9  # SIGNED digits: entries 0..8, sign applied at lookup
NPOS_B = 22  # radix-4096 comb positions for the s*B part
NENT_B = 4096

_D2_C = F.to_limbs(ref.D2)[:, None]  # (22, 1) broadcastable constant


# --------------------------------------------------- A-table construction


def build_a_tables(a_enc):
    """(V, 32) uint8 compressed pubkeys ->
       (tables (64, 9, 3, 22, V) int32 affine-Niels, valid (V,) bool).

    Runs once per validator set.  Signed-digit comb: only entries
    j = 0..8 are stored (the lookup negates for digits < 0), halving
    both the HBM footprint and the per-position build work vs a 0..15
    table.  Entries come from a scanned sequential-add chain
    (j*P = (j-1)*P + P; 16*P for the next position is one double of the
    8*P entry).  Entries are normalized to affine with a two-level
    Montgomery batch inversion (3 muls/entry amortized instead of a
    ~265-mul chain each), so the per-verify additions are the cheap
    7-multiply add_niels.

    Manifest kernel ``comb_build_a_tables``: shape/dtype/jaxpr contract
    enforced by analysis/kernelcheck, INCLUDING the compile-cost budget
    (``max_eqns``) every kernel now carries — the normalize pass is
    scan-rolled so the jaxpr stays thousands of equations, not the
    ~85k-equation unrolled build whose XLA compile ran 2m34s
    (MULTICHIP_r05).  Output limbs are FROZEN canonical, bit-identical
    to :func:`build_a_tables_host` (the compile-free production path).
    """
    pt, valid = E.decompress(a_enc)
    # Invalid encodings are sanitized to the identity BEFORE the chain.
    # Their table rows are never consulted (``a_valid`` masks the
    # verdict), but the garbage off-curve coordinates used to flow into
    # the shared Montgomery batch inversion below — where an
    # attacker-chosen encoding whose chain hits Z ≡ 0 (mod p) would
    # corrupt every VALID validator's inverse through the shared prefix
    # product.  Identity rows keep every Z nonzero (complete formulas on
    # curve points) and make the host build trivially bit-identical on
    # invalid rows too.
    pt = E.select(valid, pt, E.identity((a_enc.shape[0],)))
    p0 = E.neg(pt)  # tables hold multiples of -A
    V = a_enc.shape[0]

    def position_entries(p):
        """[0..8]*p as stacked extended coords (9, 22, V) per coord,
        plus 16*p for the next position.  The entry chain is a scanned
        sequential add (j*p = (j-1)*p + p) — one rolled add body instead
        of an unrolled double/add ladder, for the compile-cost budget;
        affine output is identical (representatives differ, the final
        canonical freeze does not)."""

        def astep(acc, _):
            nxt = E.add(acc, p)
            return nxt, nxt

        e8, rest = lax.scan(astep, p, None, length=NENT_A - 2)  # 2p..8p
        ident = E.identity((V,))
        stack = lambda c: jnp.concatenate(
            [getattr(ident, c)[None], getattr(p, c)[None], getattr(rest, c)]
        )
        return stack("x"), stack("y"), stack("z"), stack("t"), E.double(e8)

    def body(i, carry):
        p, tx, ty, tz, tt = carry
        ex, ey, ez, et, p16 = position_entries(p)
        tx = lax.dynamic_update_index_in_dim(tx, ex, i, axis=0)
        ty = lax.dynamic_update_index_in_dim(ty, ey, i, axis=0)
        tz = lax.dynamic_update_index_in_dim(tz, ez, i, axis=0)
        tt = lax.dynamic_update_index_in_dim(tt, et, i, axis=0)
        return p16, tx, ty, tz, tt

    shape = (NPOS_A, NENT_A, F.NLIMBS, V)
    init = (p0,) + tuple(jnp.zeros(shape, dtype=jnp.int32) for _ in range(4))
    _, tx, ty, tz, tt = lax.fori_loop(0, NPOS_A, body, init)

    niels = _normalize_to_niels(tx, ty, tz)
    # (3, NPOS_A, NENT_A, 22, V) -> (NPOS_A, NENT_A, 3, 22, V)
    tables = jnp.transpose(niels, (1, 2, 0, 3, 4))
    return tables, valid


_BUILD_A_JIT = None
_BUILD_A_MTX = threading.Lock()


def build_a_tables_jit(a_enc):
    """Process-wide jitted build_a_tables so every call site (cache build,
    incremental churn, benches) shares one compiled program per shape.

    Publication is lock-guarded (the parallel/verify._publish_program
    discipline): two threads racing the first verify used to each
    install their OWN ``jax.jit`` wrapper here, guaranteeing two traces
    (and two multi-minute XLA compiles before the scan-rolled rework) of
    the same table build.  The dispatch itself runs outside the lock."""
    global _BUILD_A_JIT
    fn = _BUILD_A_JIT
    if fn is None:
        with _BUILD_A_MTX:
            if _BUILD_A_JIT is None:
                _BUILD_A_JIT = jax.jit(build_a_tables)
            fn = _BUILD_A_JIT
    return fn(a_enc)


def _normalize_to_niels(tx, ty, tz):
    """Extended (pos, ent, 22, V) coords -> stacked affine Niels
    (3, pos, ent, 22, V): (y+x, y-x, 2dxy), limbs FROZEN canonical.

    Batch inversion: Montgomery's trick over the entry axis, then over the
    position axis, so only (22, V) values go through the full inversion
    chain.  Zero Z never occurs (Z=2 after add, Z>0 always on this
    curve's complete formulas; invalid rows are sanitized to identity
    chains before this runs), except entry 0 (identity, Z=1) — safe.

    Every prefix/unwind pass is a ``lax.scan`` — the pre-PR-11 Python
    loops unrolled ~460 field multiplies into ~85k flat jaxpr equations,
    the direct cause of the 2m34s ``jit_build_a_tables`` XLA compile.
    The scans compute the SAME products in the same order; the final
    :func:`ops.field.freeze` canonicalizes the limb representation, so
    the restructure is invisible downstream and the device tables agree
    bit-for-bit with the host-precomputed ones
    (:func:`build_a_tables_host`).
    """

    def mul_carry(c, z):
        p = F.mul(c, z)
        return p, p

    def unwind(running, xs):
        pref_prev, z = xs
        return F.mul(running, z), F.mul(running, pref_prev)

    # level 1: prefix products over the entry axis (batched over pos)
    zs = jnp.moveaxis(tz, 1, 0)  # (ent, pos, 22, V)
    _, pref1_rest = lax.scan(mul_carry, zs[0], zs[1:])
    prefix1 = jnp.concatenate([zs[:1], pref1_rest], axis=0)
    tot1 = prefix1[-1]  # (pos, 22, V)

    # level 2: prefix products over the position axis
    _, pref2_rest = lax.scan(mul_carry, tot1[0], tot1[1:])
    prefix2 = jnp.concatenate([tot1[:1], pref2_rest], axis=0)

    inv_tot2 = F.invert(prefix2[-1])  # (22, V)

    # unwind level 2: inv_tot1[i] = inverse of tot1[i] (reverse scan over
    # positions NPOS_A-1 .. 1; outputs land at their original indices)
    running, inv1_rest = lax.scan(
        unwind, inv_tot2, (prefix2[:-1], tot1[1:]), reverse=True
    )
    inv_tot1 = jnp.concatenate([running[None], inv1_rest], axis=0)

    # unwind level 1: entry-axis inverses, batched over all positions
    run, invz_rest = lax.scan(
        unwind, inv_tot1, (prefix1[:-1], zs[1:]), reverse=True
    )
    inv_z = jnp.moveaxis(
        jnp.concatenate([run[None], invz_rest], axis=0), 0, 1
    )  # (pos, ent, 22, V)

    x = F.mul(tx, inv_z)
    y = F.mul(ty, inv_z)
    xy = F.mul(x, y)
    return F.freeze(
        jnp.stack([F.add(y, x), F.sub(y, x), F.mul(xy, jnp.asarray(_D2_C))])
    )


# ------------------------------------------- host A-table precomputation


def _host_decompress_zip215(pk: bytes):
    """ZIP-215 decompression on host ints with EXACTLY the device
    kernel's semantics (ops/ed25519.decompress): non-canonical y
    accepted, x = 0 with sign 1 accepted, validity = the on-curve check.
    Returns ((x, y, 1, x*y) extended coords, ok)."""
    P = ref.P
    enc = int.from_bytes(pk, "little")
    sign = (enc >> 255) & 1
    y = (enc & ((1 << 255) - 1)) % P
    u = (y * y - 1) % P
    v = (ref.D * y % P * y + 1) % P
    x = u * pow(v, 3, P) % P * pow(u * pow(v, 7, P) % P, (P - 5) // 8, P) % P
    vxx = v * x % P * x % P
    flipped = vxx == (P - u) % P
    ok = vxx == u or flipped
    if flipped:
        x = x * ref.SQRT_M1 % P
    if (x & 1) != sign:
        x = (P - x) % P
    return (x, y, 1, x * y % P), ok


def build_a_tables_host(a_enc: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """Host-precomputed A-tables: exact-bigint build of the same
    (64, 9, 3, 22, V) int32 tables + (V,) valid that
    :func:`build_a_tables` produces — bit-identical (the device path
    freezes its output to canonical limbs; affine coordinates are
    projective invariants, so both paths land on the same canonical
    field elements), with NO XLA program anywhere.

    This is the cold-start fix of ROADMAP item 1: the jitted build's
    XLA compile ran 2m34s (MULTICHIP_r05) before the scan-rolled
    rework, and even compile-cached it costs a device round trip per
    new shape.  The host build is pure Python/NumPy — a few ms per
    validator — and its output is ``device_put`` with the entry's
    ``NamedSharding`` (models/comb_verifier._finish_entry), so the
    tables land already sharded over the mesh without tracing anything.
    models/comb_verifier routes builds of up to
    ``COMETBFT_TPU_COMB_HOST_BUILD_MAX`` validators here; the jitted
    kernel remains for bigger sets and as the bit-exactness witness
    (tests/test_comb_hostbuild.py).

    Invalid pubkey rows build from the identity, mirroring the device
    kernel's sanitization (their rows are masked by ``valid``
    downstream).
    """
    P = ref.P
    a_enc = np.ascontiguousarray(np.asarray(a_enc, dtype=np.uint8))
    V = int(a_enc.shape[0])
    valid = np.zeros((V,), dtype=bool)
    p0: list[tuple] = []
    for vrow in range(V):
        pt, ok = _host_decompress_zip215(a_enc[vrow].tobytes())
        valid[vrow] = ok
        p0.append(ref.pt_neg(pt) if ok else ref.IDENT)

    # entries[i][j][v] = j * 16^i * (-A_v) in extended coords
    ext: list[list[list[tuple]]] = [
        [[None] * V for _ in range(NENT_A)] for _ in range(NPOS_A)
    ]
    for vrow in range(V):
        base = p0[vrow]
        for i in range(NPOS_A):
            row = ext[i]
            row[0][vrow] = ref.IDENT
            acc = base
            row[1][vrow] = acc
            for j in range(2, NENT_A):
                acc = ref.pt_add(acc, base)
                row[j][vrow] = acc
            for _ in range(4):
                base = ref.pt_add(base, base)

    # one flat Montgomery batch inversion over every Z (all nonzero:
    # identity Z=1, on-curve chains Z != 0 by completeness)
    flat = [p for row in ext for col in row for p in col]
    prefix = [1]
    for p in flat:
        prefix.append(prefix[-1] * p[2] % P)
    inv = pow(prefix[-1], P - 2, P)
    inv_z = [0] * len(flat)
    for k in range(len(flat) - 1, -1, -1):
        inv_z[k] = inv * prefix[k] % P
        inv = inv * flat[k][2] % P

    # canonical Niels values, serialized LE then decoded to limbs in one
    # vectorized pass (33 bytes cover the 22x12-bit limb span)
    buf = bytearray()
    k = 0
    for i in range(NPOS_A):
        for j in range(NENT_A):
            vals = [bytearray(), bytearray(), bytearray()]
            for vrow in range(V):
                X, Y, _, _ = ext[i][j][vrow]
                iz = inv_z[k]
                k += 1
                x = X * iz % P
                y = Y * iz % P
                vals[0] += ((y + x) % P).to_bytes(33, "little")
                vals[1] += ((y - x) % P).to_bytes(33, "little")
                vals[2] += (x * y % P * ref.D2 % P).to_bytes(33, "little")
            for c in vals:
                buf += c
    raw = np.frombuffer(bytes(buf), dtype=np.uint8).reshape(
        NPOS_A, NENT_A, 3, V, 33
    )
    bits = np.unpackbits(raw, axis=-1, bitorder="little")  # (..., V, 264)
    limbs = bits.reshape(NPOS_A, NENT_A, 3, V, F.NLIMBS, F.BITS).astype(
        np.int32
    )
    limbs = (limbs * (1 << np.arange(F.BITS, dtype=np.int32))).sum(axis=-1)
    # (pos, ent, 3, V, 22) -> (pos, ent, 3, 22, V)
    tables = np.ascontiguousarray(
        limbs.transpose(0, 1, 2, 4, 3), dtype=np.int32
    )
    return tables, valid


# --------------------------------------------------- B-table construction

_B_TABLES = None  # device (NPOS_B, 66, NENT_B) f32, built lazily
_B_TABLES_MTX = threading.Lock()


def build_b_tables() -> np.ndarray:
    """(22, 66, 4096) f32: column j of slab i holds j * 4096^i * B in
    flattened affine Niels.

    Built on HOST with exact integer arithmetic: the table is a pure
    constant (~24 MB), and building it as an XLA program constant-folds
    multi-gigabyte scatters on the CPU backend (minutes of compile).  The
    host build is ~90k extended-coordinate additions plus one Montgomery
    batch inversion over all entries — a couple of seconds of Python,
    once per process.  f32 because the one-hot lookup is an MXU matmul;
    limb values < 2^12 are exact in f32.
    """
    P = ref.P
    out = np.zeros((NPOS_B, NENT_B, 3, F.NLIMBS), dtype=np.int32)
    pts: list[list[tuple]] = []
    base = ref.BASE
    for _ in range(NPOS_B):
        row = [(0, 1, 1, 0), base]
        for j in range(2, NENT_B):
            row.append(ref.pt_add(row[-1], base))
        pts.append(row)
        for _ in range(12):
            base = ref.pt_add(base, base)

    # Montgomery batch inversion of every Z at once
    flat = [p for row in pts for p in row]
    prefix = [1]
    for p in flat:
        prefix.append(prefix[-1] * p[2] % P)
    inv = pow(prefix[-1], P - 2, P)
    inv_z = [0] * len(flat)
    for i in range(len(flat) - 1, -1, -1):
        inv_z[i] = inv * prefix[i] % P
        inv = inv * flat[i][2] % P

    for i in range(NPOS_B):
        for j in range(NENT_B):
            X, Y, _, _ = pts[i][j]
            iz = inv_z[i * NENT_B + j]
            x, y = X * iz % P, Y * iz % P
            out[i, j, 0] = F.to_limbs((y + x) % P)
            out[i, j, 1] = F.to_limbs((y - x) % P)
            out[i, j, 2] = F.to_limbs(x * y % P * ref.D2 % P)
    # (pos, ent, 3, 22) -> (pos, 66, ent): coords flattened, entry minor
    return (
        out.reshape(NPOS_B, NENT_B, 3 * F.NLIMBS)
        .transpose(0, 2, 1)
        .astype(np.float32)
        .copy()
    )


def get_b_tables():
    global _B_TABLES
    if _B_TABLES is None:
        # publish under a lock (same discipline as build_a_tables_jit):
        # two first-verify threads would otherwise both run the ~2s host
        # build and the 24 MB transfer.  The device constant is cached
        # process-wide, so it must never be born inside somebody's jit
        # trace (a stored tracer poisons every later program); force
        # eager creation even when first called under tracing.
        with _B_TABLES_MTX:
            if _B_TABLES is None:
                with jax.ensure_compile_time_eval():
                    _B_TABLES = jnp.asarray(_b_tables_cached())
    return _B_TABLES


def _b_tables_cached() -> np.ndarray:
    """Disk-cache the constant table next to the JAX compile cache."""
    import os

    from ..utils import envknobs

    cache = envknobs.get_str(envknobs.BTAB_CACHE)
    if cache and not cache.endswith(".npy"):
        cache += ".npy"  # np.save appends it; np.load would miss the file
    if cache:
        try:
            tab = np.load(cache)
            # reject stale caches from an older table layout
            if tab.shape == (NPOS_B, 3 * F.NLIMBS, NENT_B) and tab.dtype == np.float32:
                return tab
        except (OSError, ValueError):
            pass
    tab = build_b_tables()
    if cache:
        try:
            os.makedirs(os.path.dirname(cache) or ".", exist_ok=True)
            np.save(cache, tab)
        except OSError:
            pass
    return tab


# ------------------------------------------------------------ verification


def tree_enabled() -> bool:
    """COMETBFT_TPU_COMB_TREE = "0" selects the sequential fori_loop
    accumulation (the cross-check path); anything else (default) the
    log-depth tree reduction.  Read at TRACE time: programs already
    compiled keep the path they were traced with, so flip the flag
    before the first verify of a process (or use a fresh jit wrapper)."""
    from ..utils import envknobs

    return envknobs.get_bool(envknobs.COMB_TREE)


def accumulation_depth() -> int:
    """Dependent point-add rounds in the active accumulation path —
    the number the profile/bench scripts report.  Tree: ceil(log2) fold
    of the 64 + 22 + 1 point stack; sequential: one add per position
    plus the R fold."""
    if not tree_enabled():
        return NPOS_A + NPOS_B + 1  # 87 dependent adds
    n, depth = NPOS_A + NPOS_B + 1, 0
    while n > 1:
        n = (n + 1) // 2
        depth += 1
    return depth  # 7


def verify_cached(tables, a_valid, r_enc, s_bytes, k_digest, b_tables, tree=None):
    """Batched cofactored verification against cached comb tables.

    tables   : (64, 9, 3, 22, V) int32 — build_a_tables output
    a_valid  : (V,) bool — per-row pubkey decompression success
    r_enc    : (V, 32) uint8 — signature R halves
    s_bytes  : (V, 32) uint8 — signature s halves
    k_digest : (V, 64) uint8 — SHA-512(R || A || M)
    b_tables : (22, 66, 4096) f32 — get_b_tables()
    tree     : None (resolve tree_enabled() at trace time) or a Python
               bool; close over it rather than passing through jit args.

    Returns (V,) bool.  Rows whose validator did not sign carry dummy
    inputs; callers mask the result.

    Manifest kernels ``comb_verify_cached_tree`` / ``_seq`` (one per
    accumulation path — both fingerprints are pinned, since the
    sequential path is the tree path's bit-exactness witness).  As the
    shard_map body of ``sharded_verify_cached`` this must stay
    lane-local over the validator axis: any collective it grows is
    caught by the sharded census (analysis/shardcheck,
    docs/sharding_contracts.md).
    """
    k_limbs = scalar.reduce_mod_l(scalar.bytes_to_limbs(k_digest, scalar.NL_X))
    # signed radix-16 digits in [-8, 7]: |d| selects the entry, the sign
    # flips the Niels point ((y+x, y-x, 2dxy) -> (y-x, y+x, -2dxy))
    k_dig = scalar.signed_digits_radix16(k_limbs, NPOS_A)  # (64, V)
    s_ok = scalar.s_lt_l(s_bytes)
    # s as 22 x 12-bit digits, LSB first: exactly its base-2^12 limbs
    s_dig = scalar.bytes_to_limbs(s_bytes, NPOS_B)  # (22, V)

    r_pt, r_valid = E.decompress(r_enc)

    if tree is None:
        tree = tree_enabled()
    acc_fn = _accumulate_tree if tree else _accumulate_sequential
    acc = acc_fn(tables, k_dig, s_dig, b_tables, r_pt)

    # ---- clear cofactor, check identity
    acc = E.double(E.double(E.double(acc)))
    return E.is_identity(acc) & a_valid & r_valid & s_ok


def _accumulate_sequential(tables, k_dig, s_dig, b_tables, r_pt):
    """The original accumulation: 64 + 22 dependent position adds in two
    fori_loops, then the R fold — an 87-step serial chain.  Kept as the
    bit-exact cross-check for the tree path (COMETBFT_TPU_COMB_TREE=0)."""
    V = k_dig.shape[-1]

    # ---- A part: acc += T[i][|k_i|][v] (sign-adjusted), 64 adds
    ents_a = jnp.arange(NENT_A, dtype=jnp.int32)[:, None]

    def a_body(i, acc):
        slab = lax.dynamic_index_in_dim(tables, i, axis=0, keepdims=False)
        dig = lax.dynamic_index_in_dim(k_dig, i, axis=0, keepdims=False)
        neg = dig < 0
        absd = jnp.abs(dig)
        # int32 one-hot: the select stays in the tables' own dtype end to
        # end (no float round trip; dtype-closure audited, no promotion)
        onehot = (ents_a == absd[None, :]).astype(jnp.int32)  # (9, V)
        sel = jnp.sum(slab * onehot[:, None, None, :], axis=0)  # (3, 22, V)
        yplusx = F.select(neg, sel[1], sel[0])
        yminusx = F.select(neg, sel[0], sel[1])
        t2d = F.select(neg, -sel[2], sel[2])
        return E.add_niels(acc, E.Niels(yplusx, yminusx, t2d))

    acc = lax.fori_loop(0, NPOS_A, a_body, E.identity((V,)))

    # ---- B part: acc += B_TAB[i][:, s_i], 22 adds, MXU one-hot matmul
    ents_b = jnp.arange(NENT_B, dtype=jnp.int32)[:, None]

    def b_body(i, acc):
        slab = lax.dynamic_index_in_dim(b_tables, i, axis=0, keepdims=False)
        dig = lax.dynamic_index_in_dim(s_dig, i, axis=0, keepdims=False)
        onehot = (ents_b == dig[None, :]).astype(jnp.float32)  # (4096, V)
        # HIGHEST: the TPU MXU default is bf16 passes (8 mantissa bits);
        # the Niels limbs are 12-bit values and must come through exact.
        sel = jnp.matmul(
            slab, onehot, precision=lax.Precision.HIGHEST
        ).astype(jnp.int32)  # (66, V)
        return E.add_niels(
            acc, E.Niels(sel[0:22], sel[22:44], sel[44:66])
        )

    acc = lax.fori_loop(0, NPOS_B, b_body, acc)
    return E.add(acc, E.neg(r_pt))


def _accumulate_tree(tables, k_dig, s_dig, b_tables, r_pt):
    """Log-depth accumulation: select every position's partial point at
    once (leading position axis), convert to extended, and fold the
    64 A + 22 B partials together with -R in a binary tree of batched
    unified adds (E.tree_reduce_points) — 7 dependent rounds instead of
    the 87-step serial chain of _accumulate_sequential.

    The selects do the same total work as the sequential loops but carry
    no loop dependence, so XLA can schedule them freely; only the tree's
    7 add rounds are serial.  Extra cost vs sequential: unified add
    (9 muls) instead of mixed add_niels (7 muls) per fold, plus one mul
    per partial for the Niels->extended lift — ~45% more multiplies for
    a 12x shorter dependency chain, a clear win on a latency-bound chip.
    """
    # ---- A part: all 64 sign-adjusted selections in one shot
    neg_d = k_dig < 0
    absd = jnp.abs(k_dig)
    ents_a = jnp.arange(NENT_A, dtype=jnp.int32)[None, :, None]
    onehot_a = (ents_a == absd[:, None, :]).astype(jnp.int32)  # (64, 9, V)
    sel = jnp.sum(
        tables * onehot_a[:, :, None, None, :], axis=1
    )  # (64, 3, 22, V)
    na = E.Niels(
        F.select(neg_d, sel[:, 1], sel[:, 0]),
        F.select(neg_d, sel[:, 0], sel[:, 1]),
        F.select(neg_d, -sel[:, 2], sel[:, 2]),
    )
    pa = E.niels_to_extended(na)  # coords (64, 22, V)

    # ---- B part: 22 independent one-hot MXU matmuls (no add chain);
    # unrolled so each keeps the (4096, V) onehot transient of the
    # sequential path instead of one (22, 4096, V) monster
    # f32 one-hot for the MXU path: int32 -> float32 -> int32 is exact
    # for the 12-bit Niels limbs (both conversions are in the manifest's
    # justified ALLOWED_CONVERSIONS set; HIGHEST forbids bf16 passes)
    ents_b = jnp.arange(NENT_B, dtype=jnp.int32)[:, None]
    sels = []
    for i in range(NPOS_B):
        onehot = (ents_b == s_dig[i][None, :]).astype(jnp.float32)
        sels.append(
            jnp.matmul(
                b_tables[i], onehot, precision=lax.Precision.HIGHEST
            ).astype(jnp.int32)
        )  # (66, V)
    selb = jnp.stack(sels)  # (22, 66, V)
    pb = E.niels_to_extended(
        E.Niels(selb[:, 0:22], selb[:, 22:44], selb[:, 44:66])
    )

    # ---- fold A partials + B partials + (-R) in one tree
    nr = E.neg(r_pt)
    stack = E.Point(
        jnp.concatenate([pa.x, pb.x, nr.x[None]], axis=0),
        jnp.concatenate([pa.y, pb.y, nr.y[None]], axis=0),
        jnp.concatenate([pa.z, pb.z, nr.z[None]], axis=0),
        jnp.concatenate([pa.t, pb.t, nr.t[None]], axis=0),
    )
    return E.tree_reduce_points(stack)
