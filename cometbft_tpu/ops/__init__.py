"""TPU compute kernels (JAX/XLA; Pallas where profitable).

The verification data plane of the framework: vectorized GF(2^255-19) limb
arithmetic, Edwards25519 group ops, SHA-256/SHA-512, scalar arithmetic mod L,
and RFC-6962 Merkle tree hashing.  Everything here is batch-first: arrays are
shaped (batch..., limbs/words) and every op is branch-free so XLA can tile it
onto the VPU/MXU (reference hot path: types/validation.go:265
verifyCommitBatch → crypto/ed25519 batch verify).
"""
