"""Vectorized arithmetic mod L (the Ed25519 group order) for TPU.

L = 2^252 + 27742317777372353535851937790883648493.

The challenge scalar k = SHA-512(R || A || M) is a 512-bit value that must
be reduced mod L on device (10k Python-int reductions per commit would cost
more than the whole TPU kernel).  Reduction is Barrett with base-2^12 limbs:
  q = floor(x * MU / 2^516),  MU = floor(2^516 / L),  r = x - q*L,
then two conditional subtractions.  All intermediates fit int32 (unsigned
12-bit limbs, products accumulate to < 2^29).

Limb layout matches ops/field.py: (..., nlimbs, L) with the limb axis
second-minor and the lane/batch axis minor (see field.py module doc for
the TPU tiling rationale).  Byte arrays stay batch-first (..., nbytes);
the conversion helpers transpose.

Also provides s-range checking (s < L, ZIP-215 requirement) and 4-bit
window extraction for the Straus scalar-multiplication loop.
"""

from __future__ import annotations

import numpy as np
import jax.numpy as jnp
from jax import lax

from . import field as F

L = (1 << 252) + 27742317777372353535851937790883648493
BITS = 12
MASK = (1 << BITS) - 1

NL_X = 43  # limbs for a 512-bit hash value (43*12 = 516)
NL_S = 22  # limbs for scalars < 2^264
_MU = (1 << 516) // L  # 265 bits -> 23 limbs


def _const_limbs(v: int, n: int) -> np.ndarray:
    out = np.zeros(n, dtype=np.int32)
    for i in range(n):
        out[i] = v & MASK
        v >>= BITS
    assert v == 0
    return out


_L_LIMBS = _const_limbs(L, NL_S)
_MU_LIMBS = _const_limbs(_MU, 23)


def bytes_to_limbs(b, nlimbs: int):
    """(..., nbytes) uint8 LE -> (..., nlimbs, L) int32 base-2^12 limbs
    (L = the last batch axis of b; a lone 1-D input yields lane size 1)."""
    b = b.astype(jnp.int32)
    nbits = b.shape[-1] * 8
    bits = jnp.stack(
        [lax.shift_right_logical(b, k) & 1 for k in range(8)], axis=-1
    ).reshape(b.shape[:-1] + (nbits,))
    pad = nlimbs * BITS - nbits
    if pad:
        bits = jnp.pad(bits, [(0, 0)] * (bits.ndim - 1) + [(0, pad)])
    bits = bits.reshape(bits.shape[:-1] + (nlimbs, BITS))
    limbs = jnp.sum(
        bits * jnp.asarray([1 << k for k in range(BITS)], dtype=jnp.int32), axis=-1
    ).astype(jnp.int32)
    if limbs.ndim == 1:
        return limbs[:, None]
    return jnp.swapaxes(limbs, -1, -2)


def _seq_carry(c, nlimbs: int):
    """Sequential signed carry; value must be known non-negative < 2^(12n)."""
    out = jnp.zeros_like(c)
    k = jnp.zeros(c.shape[:-2] + c.shape[-1:], dtype=jnp.int32)
    for i in range(nlimbs):
        t = c[..., i, :] + k
        out = out.at[..., i, :].set(t & MASK)
        k = lax.shift_right_arithmetic(t, BITS)
    return out


def _cond_sub(c, mod_limbs: np.ndarray):
    """One conditional subtract of mod_limbs via borrow chain (branch-free)."""
    n = c.shape[-2]
    borrow = jnp.zeros(c.shape[:-2] + c.shape[-1:], dtype=jnp.int32)
    w = jnp.zeros_like(c)
    for i in range(n):
        m = int(mod_limbs[i]) if i < len(mod_limbs) else 0
        d = c[..., i, :] - jnp.int32(m) - borrow
        borrow = lax.shift_right_logical(d, 31) & 1
        w = w.at[..., i, :].set(d + lax.shift_left(borrow, BITS))
    return jnp.where((borrow == 0)[..., None, :], w, c)


def reduce_mod_l(x_limbs):
    """(..., 43, L) limbs of a value < 2^512 -> (..., 22, L) limbs in [0, L)."""
    # q1 = x * MU (43x23 conv, unsigned, partial sums < 23*2^24 < 2^29)
    mu = jnp.asarray(_MU_LIMBS)[:, None]
    prod = F._conv(x_limbs, mu, NL_X, 23)  # 65 limbs
    # Normalize so the >>516 (drop 43 limbs) is exact.
    prod = _seq_carry(prod, prod.shape[-2])
    q = prod[..., NL_X:, :]  # (..., 22, L) limbs, q < 2^261... fits 22 limbs
    # r = x - q*L; r < 3L < 2^254 -> only low 22 limbs relevant.
    ql = F._conv(q, jnp.asarray(_L_LIMBS)[:, None], 22, 22)  # 43 limbs
    r = x_limbs[..., :NL_S, :] - ql[..., :NL_S, :]
    # Low 22 limbs of (x - q*L) represent r exactly mod 2^264; r >= 0 < 2^264.
    r = _seq_carry(r, NL_S)
    r = _cond_sub(r, _L_LIMBS)
    r = _cond_sub(r, _L_LIMBS)
    return r


def s_lt_l(s_bytes):
    """(..., 32) uint8 LE -> (...,) bool: s < L (ZIP-215 mandatory check)."""
    s = bytes_to_limbs(s_bytes, NL_S)  # (..., 22, L)
    borrow = jnp.zeros(s.shape[:-2] + s.shape[-1:], dtype=jnp.int32)
    for i in range(NL_S):
        m = int(_L_LIMBS[i])
        d = s[..., i, :] - jnp.int32(m) - borrow
        borrow = lax.shift_right_logical(d, 31) & 1
    out = borrow == 1
    if s_bytes.ndim == 1:
        return out[..., 0]
    return out


def nibbles_lsb(limbs, n: int):
    """(..., 22, L) base-2^12 limbs -> (..., n, L) 4-bit digits, LSB first
    (digit i has weight 16^i)."""
    n0 = limbs & 15
    n1 = lax.shift_right_logical(limbs, 4) & 15
    n2 = lax.shift_right_logical(limbs, 8) & 15
    nib = jnp.stack([n0, n1, n2], axis=-2)  # (..., 22, 3, L)
    nib = nib.reshape(nib.shape[:-3] + (3 * limbs.shape[-2],) + nib.shape[-1:])
    return nib[..., :n, :]


def signed_digits_radix16(limbs, n: int):
    """(..., 22, L) limbs -> (n, ..., L) signed radix-16 digits, LSB
    first: value == sum d_i 16^i with d_i in [-8, 7].

    The signed recode halves the comb table (entries 1..8 plus sign
    instead of 0..15): d = nibble + carry; d >= 8 borrows 16 from the
    next digit.  For scalars < 2^253 (k mod L) the top nibble is <= 2,
    so the final carry never overflows into a 65th digit.
    """
    nib = jnp.moveaxis(nibbles_lsb(limbs, n), -2, 0)  # (n, ..., L)

    def step(c, nv):
        d = nv + c
        ge = (d >= 8).astype(nv.dtype)
        return ge, d - 16 * ge

    carry0 = jnp.zeros(nib.shape[1:], nib.dtype)
    _, ds = lax.scan(step, carry0, nib)
    return ds


def limbs_to_windows(limbs):
    """(..., 22, L) base-2^12 limbs -> (..., 64, L) 4-bit windows, MSB first.

    Each 12-bit limb is three nibbles; 66 nibbles cover 264 bits, of which
    the top two are zero for scalars < 2^256.
    """
    return nibbles_lsb(limbs, 64)[..., ::-1, :]


def bytes_to_windows(b):
    """(..., 32) uint8 LE scalar -> (..., 64, L) 4-bit windows, MSB first
    (L = last batch axis of b)."""
    b = b.astype(jnp.int32)
    lo = b & 15
    hi = lax.shift_right_logical(b, 4) & 15
    nibbles = jnp.stack([lo, hi], axis=-1).reshape(b.shape[:-1] + (64,))
    nibbles = nibbles[..., ::-1]
    if nibbles.ndim == 1:
        return nibbles[:, None]
    return jnp.swapaxes(nibbles, -1, -2)
