"""Batched Keccak-256 on device (the pre-standardization Ethereum
variant: original 0x01 multi-rate padding, NOT SHA3-256's 0x06 —
bit-identical to the host reference crypto/keccak.py).

At firehose ingest rates the per-tx host hash loop in
models/secp_verifier is the wall the FPGA verification-engine study
(PAPERS.md arXiv:2112.02229) pipelines away; this kernel moves the
Keccak-256 half of the secp lane's message hashing onto the device so
the fused hash->verify dispatch (ops/secp256k1.hash_verify_batch) never
touches the host between payload bytes and verdict.

Layout: the 5x5x64-bit Keccak state rides as TWO (..., 25) uint32
arrays (hi, lo) — 64-bit integers are FORBIDDEN on TPU (see
analysis/kernel_manifest.FORBIDDEN_DTYPES); every step is XOR/AND/NOT/
static-rotate, so the split costs two ops per logical one and no
carries (unlike sha2's (hi, lo) adds).  Flat lane index l = x + 5*y
matches the host absorb order (lane i of a block lands at a[i%5][i//5],
which IS flat index i).  The 24 rounds run as ONE lax.fori_loop body
(round constants indexed dynamically), so the jaxpr stays O(1) in
rounds (range contract: the whole state plane is uint32 XOR/AND/NOT/
rotate — wrap-defined, no signed overflow surface — and every shift
amount is a host constant; certificate ``keccak256_blocks`` in
analysis/range_fingerprints.json pins the proof, and the
unchecked-shift-width linter check keeps the amounts static); the
rho/pi lane permutation is statically unrolled inside the
body (fixed per-lane offsets).

Multi-block messages use the same blocks+active contract as
ops/sha2.sha256_blocks: a static Python loop over the padded block
axis, rows with fewer live blocks stop updating state after their own
final block.
"""

from __future__ import annotations

import threading

import numpy as np

import jax.numpy as jnp
from jax import lax

from ..crypto.keccak import _RC, _ROT

RATE = 136  # 1088-bit rate for 256-bit output; 17 lanes absorbed/block

# round constants split into uint32 halves for the (hi, lo) state
_RC_HI = np.array([rc >> 32 for rc in _RC], dtype=np.uint32)
_RC_LO = np.array([rc & 0xFFFFFFFF for rc in _RC], dtype=np.uint32)

# rho+pi as flat permutation: output lane dst absorbs input lane
# src = x + 5*y rotated by _ROT[x][y]; dst = y + 5*((2x + 3y) % 5)
_PI_DST = np.zeros(25, dtype=np.int64)
_RHO_N = np.zeros(25, dtype=np.int64)
for _x in range(5):
    for _y in range(5):
        _PI_DST[_x + 5 * _y] = _y + 5 * ((2 * _x + 3 * _y) % 5)
        _RHO_N[_x + 5 * _y] = _ROT[_x][_y]


def _rol(h, l, n: int):
    """Rotate the (hi, lo) uint32 pair left by STATIC n in [0, 64)."""
    n %= 64
    if n == 0:
        return h, l
    if n >= 32:
        h, l, n = l, h, n - 32
        if n == 0:
            return h, l
    rh = lax.shift_left(h, np.uint32(n)) | lax.shift_right_logical(
        l, np.uint32(32 - n)
    )
    rl = lax.shift_left(l, np.uint32(n)) | lax.shift_right_logical(
        h, np.uint32(32 - n)
    )
    return rh, rl


def _keccak_f(hi, lo):
    """One Keccak-f[1600] permutation over (..., 25) uint32 halves —
    24 rounds as a fori_loop (round constants indexed by the loop
    counter; everything else in the body is static)."""
    rc_hi = jnp.asarray(_RC_HI)
    rc_lo = jnp.asarray(_RC_LO)

    def round_body(t, carry):
        hi, lo = carry
        # theta: c[x] = xor_y a[x][y]; the (..., 5, 5) view is [y][x]
        h5 = hi.reshape(hi.shape[:-1] + (5, 5))
        l5 = lo.reshape(lo.shape[:-1] + (5, 5))
        ch = h5[..., 0, :] ^ h5[..., 1, :] ^ h5[..., 2, :] ^ h5[..., 3, :] ^ h5[..., 4, :]
        cl = l5[..., 0, :] ^ l5[..., 1, :] ^ l5[..., 2, :] ^ l5[..., 3, :] ^ l5[..., 4, :]
        # d[x] = c[x-1] ^ rol(c[x+1], 1)
        r1h, r1l = _rol(jnp.roll(ch, -1, axis=-1), jnp.roll(cl, -1, axis=-1), 1)
        dh = jnp.roll(ch, 1, axis=-1) ^ r1h
        dl = jnp.roll(cl, 1, axis=-1) ^ r1l
        h5 = h5 ^ dh[..., None, :]
        l5 = l5 ^ dl[..., None, :]
        hi = h5.reshape(hi.shape)
        lo = l5.reshape(lo.shape)
        # rho + pi: static per-lane rotate into the permuted position
        bh = [None] * 25
        bl = [None] * 25
        for src in range(25):
            rh, rl = _rol(hi[..., src], lo[..., src], int(_RHO_N[src]))
            bh[int(_PI_DST[src])] = rh
            bl[int(_PI_DST[src])] = rl
        hi = jnp.stack(bh, axis=-1)
        lo = jnp.stack(bl, axis=-1)
        # chi: a[x][y] = b[x][y] ^ (~b[x+1][y] & b[x+2][y]) over x
        h5 = hi.reshape(hi.shape[:-1] + (5, 5))
        l5 = lo.reshape(lo.shape[:-1] + (5, 5))
        h5 = h5 ^ (~jnp.roll(h5, -1, axis=-1) & jnp.roll(h5, -2, axis=-1))
        l5 = l5 ^ (~jnp.roll(l5, -1, axis=-1) & jnp.roll(l5, -2, axis=-1))
        hi = h5.reshape(hi.shape)
        lo = l5.reshape(lo.shape)
        # iota
        hi = hi.at[..., 0].set(hi[..., 0] ^ rc_hi[t])
        lo = lo.at[..., 0].set(lo[..., 0] ^ rc_lo[t])
        return hi, lo

    return lax.fori_loop(0, 24, round_body, (hi, lo))


def _lanes(block):
    """(..., 136) uint8 block -> little-endian (hi, lo) uint32 lane
    halves, each (..., 17)."""
    b = block.astype(jnp.uint32).reshape(block.shape[:-1] + (17, 8))
    lo = (
        b[..., 0]
        | lax.shift_left(b[..., 1], np.uint32(8))
        | lax.shift_left(b[..., 2], np.uint32(16))
        | lax.shift_left(b[..., 3], np.uint32(24))
    )
    hi = (
        b[..., 4]
        | lax.shift_left(b[..., 5], np.uint32(8))
        | lax.shift_left(b[..., 6], np.uint32(16))
        | lax.shift_left(b[..., 7], np.uint32(24))
    )
    return hi, lo


def keccak256_blocks(blocks, active_blocks=None):
    """(..., nblocks, 136) uint8 padded message -> (..., 32) uint8 digest.

    active_blocks: optional (...,) int32 per-row live block count (rows
    with shorter messages stop updating state after their own final
    block — Keccak padding is per-message while the array shape is
    static; the sha2.sha256_blocks contract).

    Manifest kernel ``keccak256_blocks`` (jitted via
    ops/secp256k1.hash_verify_batch and keccak256_device).
    """
    nblocks = blocks.shape[-2]
    hi = jnp.zeros(blocks.shape[:-2] + (25,), dtype=jnp.uint32)
    lo = jnp.zeros_like(hi)
    for blk in range(nblocks):
        lh, ll = _lanes(blocks[..., blk, :])
        ah = jnp.concatenate([hi[..., :17] ^ lh, hi[..., 17:]], axis=-1)
        al = jnp.concatenate([lo[..., :17] ^ ll, lo[..., 17:]], axis=-1)
        nh, nl = _keccak_f(ah, al)
        if active_blocks is None:
            hi, lo = nh, nl
        else:
            live = (active_blocks > blk)[..., None]
            hi = jnp.where(live, nh, hi)
            lo = jnp.where(live, nl, lo)
    return squeeze_bytes(hi, lo)


def squeeze_bytes(hi, lo):
    """First 4 state lanes -> (..., 32) uint8 digest (little-endian per
    lane, the host squeeze order).  Split out so the fused secp kernel
    can squeeze a state it permuted itself."""
    out = []
    for lane in range(4):
        for half in (lo[..., lane], hi[..., lane]):
            for s in (0, 8, 16, 24):
                out.append(
                    lax.shift_right_logical(half, np.uint32(s)).astype(jnp.uint8)
                )
    return jnp.stack(out, axis=-1)


# ------------------------------------------------------------ host bridge


_KECCAK_JIT = None
_JIT_MTX = threading.Lock()


def keccak256_device(blocks, active=None) -> np.ndarray:
    """One device dispatch of the batched Keccak kernel over padded
    host blocks; the blocking result fetch is this bridge's declared
    collect point (analysis/kernel_manifest.COLLECT_BOUNDARIES)."""
    import jax

    global _KECCAK_JIT
    if _KECCAK_JIT is None:
        with _JIT_MTX:
            if _KECCAK_JIT is None:
                _KECCAK_JIT = jax.jit(keccak256_blocks)
    if active is None:
        active = np.full(blocks.shape[:-2], blocks.shape[-2], np.int32)
    return np.asarray(_KECCAK_JIT(jnp.asarray(blocks), jnp.asarray(active)))


def pad_messages_keccak(msgs: list[bytes], max_len: int | None = None):
    """Host: variable-length messages -> (buf, active) for
    keccak256_blocks.  Original Keccak pad10*1 (0x01 ... 0x80; the two
    bytes XOR into 0x81 when the padding is a single byte)."""
    n = len(msgs)
    longest = max((len(m) for m in msgs), default=0)
    if max_len is not None:
        longest = max(longest, max_len)
    nblocks = max(1, longest // RATE + 1)
    buf = np.zeros((n, nblocks * RATE), dtype=np.uint8)
    active = np.zeros(n, dtype=np.int32)
    for i, m in enumerate(msgs):
        ln = len(m)
        nb = ln // RATE + 1
        active[i] = nb
        buf[i, :ln] = np.frombuffer(m, dtype=np.uint8)
        buf[i, ln] ^= 0x01
        buf[i, nb * RATE - 1] ^= 0x80
    return buf.reshape(n, nblocks, RATE), active
