"""Vectorized BLS12-381 base-field + G1 group ops for TPU
(native checklist #3, SURVEY §2.1: the reference binds blst's C/assembly
for these — crypto/bls12381/key_bls12381.go:40-41).

Scope, honestly staged (SURVEY §7 marks full pairings "genuinely hard;
stage last, keep host fallback"): this kernel covers the
*data-parallel* part of BLS verification — batched G1 point arithmetic
and the tree-reduction aggregation of validator pubkeys that
FastAggregateVerify needs (sum of N pubkeys; blst's P1 aggregate).  The
Miller loop + final exponentiation remain on host (crypto/bls12381.py),
exactly as the reference keeps them inside native blst behind a build
tag.

Field design: p381 is nowhere near a power of two, so the 25519-style
carry-fold (ops/field.py) does not apply; this is word-wise Montgomery
arithmetic (R = 2^384) over 32 signed 12-bit limbs in int32.  The
64-limb product comes from one outer-product + one constant
anti-diagonal matmul (so XLA sees 2 ops, not ~2000 scalar muls), the
Montgomery reduction is 32 unrolled multiply-add steps, and every op
returns canonical limbs in [0, p) so int32 bounds hold everywhere:
conv sums <= 32*4095^2 ~ 5.4e8, reduction adds <= 32*4095^2 more —
peak < 1.1e9 < 2^31.

All device values are in the Montgomery domain; the host bridge
converts with to_mont/from_mont.
"""

from __future__ import annotations

import numpy as np
import jax.numpy as jnp

NLIMBS = 32
BITS = 12
RADIX = 1 << BITS
MASK = RADIX - 1

P = 0x1A0111EA397FE69A4B1BA7B6434BACD764774B84F38512BF6730D2A0F6B0F6241EABFFFEB153FFFFB9FEFFFFFFFFAAAB
R_MONT = 1 << (NLIMBS * BITS)  # 2^384
R_INV = pow(R_MONT, P - 2, P)
# -p^-1 mod 2^12, the per-word Montgomery multiplier
P_PRIME = (-pow(P, -1, RADIX)) % RADIX


def _int_to_limbs(x: int, n: int = NLIMBS) -> np.ndarray:
    out = np.zeros(n, dtype=np.int32)
    for i in range(n):
        out[i] = x & MASK
        x >>= BITS
    assert x == 0, "value too wide for limb count"
    return out


P_LIMBS = _int_to_limbs(P)
P_LIMBS33 = _int_to_limbs(P, NLIMBS + 1)
_TWO_P33 = _int_to_limbs(2 * P, NLIMBS + 1)

# anti-diagonal collector: outer(a, b).reshape @ _DIAG == conv(a, b)
_DIAG = np.zeros((NLIMBS * NLIMBS, 2 * NLIMBS), dtype=np.int32)
for _i in range(NLIMBS):
    for _j in range(NLIMBS):
        _DIAG[_i * NLIMBS + _j, _i + _j] = 1


def to_mont(x: int) -> int:
    return x * R_MONT % P


def from_mont(x: int) -> int:
    return x * R_INV % P


def to_limbs(x: int) -> np.ndarray:
    """Host: plain int -> Montgomery-domain limb vector."""
    return _int_to_limbs(to_mont(x))


def from_limbs(a) -> np.ndarray:
    """Device/host limb array (Montgomery domain) -> object array of
    plain Python ints."""
    a = np.asarray(a)
    flat = a.reshape(-1, a.shape[-1])
    out = np.empty(flat.shape[0], dtype=object)
    for i, row in enumerate(flat):
        v = 0
        for k in range(len(row) - 1, -1, -1):
            v = (v << BITS) + int(row[k])
        out[i] = from_mont(v % P)
    return out.reshape(a.shape[:-1])


# ------------------------------------------------------------- primitives


def _carry33(a):
    """Carry chain into 33 canonical-width limbs (values < 4p fit).
    lax.scan keeps the XLA graph O(1) in limb count — fully unrolled
    chains made CPU-backend compiles pathological."""
    from jax import lax

    aT = jnp.moveaxis(a, -1, 0)  # (L, ...)

    def step(c, limb):
        v = limb + c
        return v >> BITS, v & MASK

    c, outT = lax.scan(step, jnp.zeros_like(aT[0]), aT)
    out = jnp.moveaxis(outT, 0, -1)
    if a.shape[-1] < NLIMBS + 1:
        out = jnp.concatenate([out, c[..., None]], axis=-1)
    # 33-limb inputs carry no further: every caller's value is < 4p < 2^396
    return out


def _cond_sub_p(a33):
    """One round: subtract p if a >= p (borrow-chain compare+select)."""
    from jax import lax

    aT = jnp.moveaxis(a33, -1, 0)
    pl = jnp.asarray(P_LIMBS33)

    def step(borrow, inp):
        limb, p_i = inp
        v = limb - p_i - borrow
        b = (v < 0).astype(v.dtype)
        return b, v + b * RADIX
    borrow, dT = lax.scan(step, jnp.zeros_like(aT[0]), (aT, pl))
    d = jnp.moveaxis(dT, 0, -1)
    ge = borrow == 0  # no final borrow -> a >= p
    return jnp.where(ge[..., None], d, a33)


def normalize(a):
    """Any limb vector with value in [0, 4p) -> canonical [0, p), 32
    limbs."""
    a33 = _carry33(a)
    a33 = _cond_sub_p(a33)
    a33 = _cond_sub_p(a33)
    a33 = _cond_sub_p(a33)
    return a33[..., :NLIMBS]


_TWO_P32 = _int_to_limbs(2 * P)  # 2p < 2^382 fits 32 limbs


def add(a, b):
    return normalize(a + b)


def sub(a, b):
    """a - b (canonical inputs): a + 2p - b stays positive; the signed
    carry chain in normalize handles the negative intermediate limbs."""
    return normalize(a - b + jnp.asarray(_TWO_P32))


def mul(a, b):
    """Montgomery product: canonical inputs, canonical output."""
    outer = (a[..., :, None] * b[..., None, :]).reshape(
        a.shape[:-1] + (NLIMBS * NLIMBS,)
    )
    t = outer @ jnp.asarray(_DIAG)  # (..., 64) conv limbs
    from jax import lax

    pl = jnp.asarray(P_LIMBS)

    # word-wise reduction: clear limb i by adding m*p at weight i.
    # fori_loop + dynamic slices keep the graph O(1) in limb count.
    def body(i, t):
        ti = lax.dynamic_index_in_dim(t, i, axis=-1, keepdims=False)
        c = ti >> BITS
        low = ti & MASK
        m = (low * P_PRIME) & MASK
        seg = lax.dynamic_slice_in_dim(t, i, NLIMBS, axis=-1)
        seg = seg + m[..., None] * pl
        t = lax.dynamic_update_slice_in_dim(t, seg, i, axis=-1)
        nxt = lax.dynamic_index_in_dim(t, i + 1, axis=-1, keepdims=False)
        # limb i is (c<<12 + low + m*p0); low + m*p0 ≡ 0 mod 2^12 — forward
        # the whole /2^12 quotient and let the final slice drop limb i
        nxt = nxt + c + ((low + m * pl[0]) >> BITS)
        return lax.dynamic_update_index_in_dim(t, nxt, i + 1, axis=-1)

    t = lax.fori_loop(0, NLIMBS, body, t)
    out = t[..., NLIMBS:]
    return normalize(out)


def sqr(a):
    return mul(a, a)


def select(cond, a, b):
    return jnp.where(cond[..., None], a, b)


def is_zero(a) -> jnp.ndarray:
    """(...,) bool — canonical-input zero test."""
    return jnp.all(a == 0, axis=-1)


# --------------------------------------------------------------- G1 group
# y^2 = x^3 + 4, a = 0.  Jacobian (X, Y, Z); infinity encoded Z = 0.
# All coordinates in the Montgomery domain, canonical limbs.


def g1_double(X, Y, Z):
    A = sqr(X)
    B = sqr(Y)
    Cc = sqr(B)
    t = sqr(add(X, B))
    D = sub(t, add(A, Cc))
    D = add(D, D)
    E = add(add(A, A), A)
    F = sqr(E)
    X3 = sub(F, add(D, D))
    eight_c = add(add(Cc, Cc), add(Cc, Cc))
    eight_c = add(eight_c, eight_c)
    Y3 = sub(mul(E, sub(D, X3)), eight_c)
    Z3 = mul(add(Y, Y), Z)
    return X3, Y3, Z3


def g1_add(X1, Y1, Z1, X2, Y2, Z2):
    """Branch-free complete addition over the batch via selects."""
    z1z = sqr(Z1)
    z2z = sqr(Z2)
    U1 = mul(X1, z2z)
    U2 = mul(X2, z1z)
    S1 = mul(mul(Y1, Z2), z2z)
    S2 = mul(mul(Y2, Z1), z1z)
    H = sub(U2, U1)
    Rr = sub(S2, S1)
    h_zero = is_zero(H)
    r_zero = is_zero(Rr)
    inf1 = is_zero(Z1)
    inf2 = is_zero(Z2)

    I = sqr(add(H, H))
    J = mul(H, I)
    r2 = add(Rr, Rr)
    V = mul(U1, I)
    X3 = sub(sqr(r2), add(J, add(V, V)))
    Y3 = sub(mul(r2, sub(V, X3)), mul(add(S1, S1), J))
    Z3 = mul(mul(Z1, Z2), H)
    Z3 = add(Z3, Z3)

    dX, dY, dZ = g1_double(X1, Y1, Z1)
    same = h_zero & r_zero & ~inf1 & ~inf2
    neg = h_zero & ~r_zero & ~inf1 & ~inf2
    X3 = select(same, dX, X3)
    Y3 = select(same, dY, Y3)
    Z3 = select(same, dZ, Z3)
    X3 = select(neg, jnp.zeros_like(X3), X3)
    Y3 = select(neg, jnp.zeros_like(Y3), Y3)
    Z3 = select(neg, jnp.zeros_like(Z3), Z3)
    X3 = select(inf1, X2, X3)
    Y3 = select(inf1, Y2, Y3)
    Z3 = select(inf1, Z2, Z3)
    X3 = select(inf2 & ~inf1, X1, X3)
    Y3 = select(inf2 & ~inf1, Y1, Y3)
    Z3 = select(inf2 & ~inf1, Z1, Z3)
    return X3, Y3, Z3


def aggregate_g1(X, Y, Z):
    """Tree-reduce a (N, 32) batch of Jacobian points to one sum — the
    device analogue of blst P1 aggregate.  N must be a power of two
    (callers pad with infinities).

    Manifest kernel ``bls381_aggregate_g1`` (analysis/kernel_manifest):
    the contract checker traces this signature and pins its jaxpr
    fingerprint; jit sites must stay registered in JIT_SITES.
    """
    n = X.shape[0]
    while n > 1:
        half = n // 2
        X, Y, Z = g1_add(
            X[:half], Y[:half], Z[:half], X[half:n], Y[half:n], Z[half:n]
        )
        n = half
    return X[0], Y[0], Z[0]


# ------------------------------------------------------------ host bridge


_AGG_JIT = None


def aggregate_pubkeys_device(points):
    """Tree-reduce affine (x, y) int pairs (or compressed 48-byte keys)
    on device.  Returns the aggregate as an affine (x, y) pair, or None
    for infinity.  The jitted reducer is module-cached so compilation
    amortizes across calls of the same padded size."""
    import jax

    global _AGG_JIT
    if _AGG_JIT is None:
        _AGG_JIT = jax.jit(aggregate_g1)

    pts = []
    for pk in points:
        if isinstance(pk, (bytes, bytearray)):
            from ..crypto import bls12381 as host_bls

            aff = host_bls._g1_decompress(bytes(pk))
        else:
            aff = pk
        if aff is not None:
            pts.append(aff)
    if not pts:
        return None
    n = 1 << (len(pts) - 1).bit_length()
    X = np.zeros((n, NLIMBS), dtype=np.int32)
    Y = np.zeros((n, NLIMBS), dtype=np.int32)
    Z = np.zeros((n, NLIMBS), dtype=np.int32)
    for i, (x, y) in enumerate(pts):
        X[i] = to_limbs(x)
        Y[i] = to_limbs(y)
        Z[i] = to_limbs(1)

    Xa, Ya, Za = _AGG_JIT(jnp.asarray(X), jnp.asarray(Y), jnp.asarray(Z))
    xi = int(from_limbs(np.asarray(Xa))[()])
    yi = int(from_limbs(np.asarray(Ya))[()])
    zi = int(from_limbs(np.asarray(Za))[()])
    if zi == 0:
        return None
    z_inv = pow(zi, P - 2, P)
    z2 = z_inv * z_inv % P
    return (xi * z2 % P, yi * z2 % P * z_inv % P)
