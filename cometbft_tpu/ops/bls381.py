"""Vectorized BLS12-381 base-field + G1 group ops for TPU
(native checklist #3, SURVEY §2.1: the reference binds blst's C/assembly
for these — crypto/bls12381/key_bls12381.go:40-41).

Scope, honestly staged (SURVEY §7 marks full pairings "genuinely hard;
stage last, keep host fallback"): this kernel covers the
*data-parallel* part of BLS verification — batched G1 point arithmetic
and the tree-reduction aggregation of validator pubkeys that
FastAggregateVerify needs (sum of N pubkeys; blst's P1 aggregate).  The
Miller loop + final exponentiation remain on host (crypto/bls12381.py),
exactly as the reference keeps them inside native blst behind a build
tag.

Field design: p381 is nowhere near a power of two, so the 25519-style
carry-fold (ops/field.py) does not apply; this is word-wise Montgomery
arithmetic (R = 2^384) over 32 signed 12-bit limbs in int32.  The
64-limb product comes from one outer-product + one constant
anti-diagonal matmul (so XLA sees 2 ops, not ~2000 scalar muls), the
Montgomery reduction is 32 unrolled multiply-add steps, and every op
returns canonical limbs in [0, p) so int32 bounds hold everywhere:
conv sums <= 32*4095^2 ~ 5.4e8, reduction adds <= 32*4095^2 more —
peak < 1.1e9 < 2^31.  The interval interpreter confirms the hand
bound: the proved peak over the whole G1 kernel set is 836,038,240
(1.36 bits of int32 headroom; analysis/range_fingerprints.json
entries ``bls381_*``) — and the scaling law in
docs/limb_headroom.md shows 12-bit limbs are already the widest safe
width for this conv depth, so the headroom funds deeper adds, not
wider limbs.

All device values are in the Montgomery domain; the host bridge
converts with to_mont/from_mont.
"""

from __future__ import annotations

import numpy as np
import jax.numpy as jnp

NLIMBS = 32
BITS = 12
RADIX = 1 << BITS
MASK = RADIX - 1

P = 0x1A0111EA397FE69A4B1BA7B6434BACD764774B84F38512BF6730D2A0F6B0F6241EABFFFEB153FFFFB9FEFFFFFFFFAAAB
# the r-order of the G1/G2 subgroups (the BLS scalar field), used by the
# batched subgroup check: P is in the subgroup iff [r]P == infinity
R_ORDER = 0x73EDA753299D7D483339D80809A1D80553BDA402FFFE5BFEFFFFFFFF00000001
R_MONT = 1 << (NLIMBS * BITS)  # 2^384
R_INV = pow(R_MONT, P - 2, P)
# -p^-1 mod 2^12, the per-word Montgomery multiplier
P_PRIME = (-pow(P, -1, RADIX)) % RADIX


def _int_to_limbs(x: int, n: int = NLIMBS) -> np.ndarray:
    out = np.zeros(n, dtype=np.int32)
    for i in range(n):
        out[i] = x & MASK
        x >>= BITS
    assert x == 0, "value too wide for limb count"
    return out


P_LIMBS = _int_to_limbs(P)
P_LIMBS33 = _int_to_limbs(P, NLIMBS + 1)
_TWO_P33 = _int_to_limbs(2 * P, NLIMBS + 1)

# anti-diagonal collector: outer(a, b).reshape @ _DIAG == conv(a, b)
_DIAG = np.zeros((NLIMBS * NLIMBS, 2 * NLIMBS), dtype=np.int32)
for _i in range(NLIMBS):
    for _j in range(NLIMBS):
        _DIAG[_i * NLIMBS + _j, _i + _j] = 1


def to_mont(x: int) -> int:
    return x * R_MONT % P


def from_mont(x: int) -> int:
    return x * R_INV % P


def to_limbs(x: int) -> np.ndarray:
    """Host: plain int -> Montgomery-domain limb vector."""
    return _int_to_limbs(to_mont(x))


def from_limbs(a) -> np.ndarray:
    """Device/host limb array (Montgomery domain) -> object array of
    plain Python ints."""
    a = np.asarray(a)
    flat = a.reshape(-1, a.shape[-1])
    out = np.empty(flat.shape[0], dtype=object)
    for i, row in enumerate(flat):
        v = 0
        for k in range(len(row) - 1, -1, -1):
            v = (v << BITS) + int(row[k])
        out[i] = from_mont(v % P)
    return out.reshape(a.shape[:-1])


# ------------------------------------------------------------- primitives


def _carry33(a):
    """Carry chain into 33 canonical-width limbs (values < 4p fit).
    lax.scan keeps the XLA graph O(1) in limb count — fully unrolled
    chains made CPU-backend compiles pathological."""
    from jax import lax

    aT = jnp.moveaxis(a, -1, 0)  # (L, ...)

    def step(c, limb):
        v = limb + c
        return v >> BITS, v & MASK

    c, outT = lax.scan(step, jnp.zeros_like(aT[0]), aT)
    out = jnp.moveaxis(outT, 0, -1)
    if a.shape[-1] < NLIMBS + 1:
        out = jnp.concatenate([out, c[..., None]], axis=-1)
    # 33-limb inputs carry no further: every caller's value is < 4p < 2^396
    return out


def _cond_sub_p(a33):
    """One round: subtract p if a >= p (borrow-chain compare+select)."""
    from jax import lax

    aT = jnp.moveaxis(a33, -1, 0)
    pl = jnp.asarray(P_LIMBS33)

    def step(borrow, inp):
        limb, p_i = inp
        v = limb - p_i - borrow
        b = (v < 0).astype(v.dtype)
        return b, v + b * RADIX
    borrow, dT = lax.scan(step, jnp.zeros_like(aT[0]), (aT, pl))
    d = jnp.moveaxis(dT, 0, -1)
    ge = borrow == 0  # no final borrow -> a >= p
    return jnp.where(ge[..., None], d, a33)


def normalize(a):
    """Any limb vector with value in [0, 4p) -> canonical [0, p), 32
    limbs."""
    a33 = _carry33(a)
    a33 = _cond_sub_p(a33)
    a33 = _cond_sub_p(a33)
    a33 = _cond_sub_p(a33)
    return a33[..., :NLIMBS]


_TWO_P32 = _int_to_limbs(2 * P)  # 2p < 2^382 fits 32 limbs


def add(a, b):
    return normalize(a + b)


def sub(a, b):
    """a - b (canonical inputs): a + 2p - b stays positive; the signed
    carry chain in normalize handles the negative intermediate limbs."""
    return normalize(a - b + jnp.asarray(_TWO_P32))


def mul(a, b):
    """Montgomery product: canonical inputs, canonical output."""
    outer = (a[..., :, None] * b[..., None, :]).reshape(
        a.shape[:-1] + (NLIMBS * NLIMBS,)
    )
    t = outer @ jnp.asarray(_DIAG)  # (..., 64) conv limbs
    from jax import lax

    pl = jnp.asarray(P_LIMBS)

    # word-wise reduction: clear limb i by adding m*p at weight i.
    # fori_loop + dynamic slices keep the graph O(1) in limb count.
    def body(i, t):
        ti = lax.dynamic_index_in_dim(t, i, axis=-1, keepdims=False)
        c = ti >> BITS
        low = ti & MASK
        m = (low * P_PRIME) & MASK
        seg = lax.dynamic_slice_in_dim(t, i, NLIMBS, axis=-1)
        seg = seg + m[..., None] * pl
        t = lax.dynamic_update_slice_in_dim(t, seg, i, axis=-1)
        nxt = lax.dynamic_index_in_dim(t, i + 1, axis=-1, keepdims=False)
        # limb i is (c<<12 + low + m*p0); low + m*p0 ≡ 0 mod 2^12 — forward
        # the whole /2^12 quotient and let the final slice drop limb i
        nxt = nxt + c + ((low + m * pl[0]) >> BITS)
        return lax.dynamic_update_index_in_dim(t, nxt, i + 1, axis=-1)

    t = lax.fori_loop(0, NLIMBS, body, t)
    out = t[..., NLIMBS:]
    return normalize(out)


def sqr(a):
    return mul(a, a)


def select(cond, a, b):
    return jnp.where(cond[..., None], a, b)


def is_zero(a) -> jnp.ndarray:
    """(...,) bool — canonical-input zero test."""
    return jnp.all(a == 0, axis=-1)


# --------------------------------------------------------------- G1 group
# y^2 = x^3 + 4, a = 0.  Jacobian (X, Y, Z); infinity encoded Z = 0.
# All coordinates in the Montgomery domain, canonical limbs.


def g1_double(X, Y, Z):
    A = sqr(X)
    B = sqr(Y)
    Cc = sqr(B)
    t = sqr(add(X, B))
    D = sub(t, add(A, Cc))
    D = add(D, D)
    E = add(add(A, A), A)
    F = sqr(E)
    X3 = sub(F, add(D, D))
    eight_c = add(add(Cc, Cc), add(Cc, Cc))
    eight_c = add(eight_c, eight_c)
    Y3 = sub(mul(E, sub(D, X3)), eight_c)
    Z3 = mul(add(Y, Y), Z)
    return X3, Y3, Z3


def g1_add(X1, Y1, Z1, X2, Y2, Z2):
    """Branch-free complete addition over the batch via selects."""
    z1z = sqr(Z1)
    z2z = sqr(Z2)
    U1 = mul(X1, z2z)
    U2 = mul(X2, z1z)
    S1 = mul(mul(Y1, Z2), z2z)
    S2 = mul(mul(Y2, Z1), z1z)
    H = sub(U2, U1)
    Rr = sub(S2, S1)
    h_zero = is_zero(H)
    r_zero = is_zero(Rr)
    inf1 = is_zero(Z1)
    inf2 = is_zero(Z2)

    I = sqr(add(H, H))
    J = mul(H, I)
    r2 = add(Rr, Rr)
    V = mul(U1, I)
    X3 = sub(sqr(r2), add(J, add(V, V)))
    Y3 = sub(mul(r2, sub(V, X3)), mul(add(S1, S1), J))
    Z3 = mul(mul(Z1, Z2), H)
    Z3 = add(Z3, Z3)

    dX, dY, dZ = g1_double(X1, Y1, Z1)
    same = h_zero & r_zero & ~inf1 & ~inf2
    neg = h_zero & ~r_zero & ~inf1 & ~inf2
    X3 = select(same, dX, X3)
    Y3 = select(same, dY, Y3)
    Z3 = select(same, dZ, Z3)
    X3 = select(neg, jnp.zeros_like(X3), X3)
    Y3 = select(neg, jnp.zeros_like(Y3), Y3)
    Z3 = select(neg, jnp.zeros_like(Z3), Z3)
    X3 = select(inf1, X2, X3)
    Y3 = select(inf1, Y2, Y3)
    Z3 = select(inf1, Z2, Z3)
    X3 = select(inf2 & ~inf1, X1, X3)
    Y3 = select(inf2 & ~inf1, Y1, Y3)
    Z3 = select(inf2 & ~inf1, Z1, Z3)
    return X3, Y3, Z3


def aggregate_g1(X, Y, Z):
    """Tree-reduce a (N, 32) batch of Jacobian points to one sum — the
    device analogue of blst P1 aggregate, folded with log-depth batched
    adds exactly like ``ops/ed25519.tree_reduce_points`` (an odd level's
    carry row is concatenated back, so any N works; the addition law is
    complete, so identity rows are safe anywhere in the tree).

    Manifest kernel ``bls381_aggregate_g1`` (analysis/kernel_manifest):
    the contract checker traces this signature and pins its jaxpr
    fingerprint; jit sites must stay registered in JIT_SITES.
    """
    n = X.shape[0]
    while n > 1:
        half = n // 2
        sX, sY, sZ = g1_add(
            X[:half], Y[:half], Z[:half],
            X[half : 2 * half], Y[half : 2 * half], Z[half : 2 * half],
        )
        if n & 1:
            sX = jnp.concatenate([sX, X[2 * half :]], axis=0)
            sY = jnp.concatenate([sY, Y[2 * half :]], axis=0)
            sZ = jnp.concatenate([sZ, Z[2 * half :]], axis=0)
        X, Y, Z = sX, sY, sZ
        n = (n + 1) // 2
    return X[0], Y[0], Z[0]


# ---------------------------------------------------- batched validation
# The KeyValidate half of FastAggregateVerify
# (draft-irtf-cfrg-bls-signature §2.5: reject off-curve, out-of-subgroup,
# and infinite pubkeys), data-parallel over the validator axis.  The
# host keeps decompression (one Fp square root per NEW pubkey, cached by
# models/bls_verifier); the ~4 ms/key subgroup scalar mult — the part
# that is pure group arithmetic over all N keys at once — runs here.

_ONE_M = _int_to_limbs(to_mont(1))
_B_M = _int_to_limbs(to_mont(4))  # curve constant b = 4, Montgomery domain
_R_BITS = np.array([b == "1" for b in bin(R_ORDER)[2:]], dtype=bool)


def g1_on_curve(X, Y):
    """(..., 32) affine Montgomery limbs -> (...,) bool: y^2 == x^3 + 4.
    Canonical-limb equality is value equality (both sides in [0, p))."""
    lhs = sqr(Y)
    rhs = add(mul(sqr(X), X), jnp.asarray(_B_M))
    return jnp.all(lhs == rhs, axis=-1)


def _g1_mul_order(X, Y, Z):
    """[r]P for a batch of Jacobian points, left-to-right double-and-add
    over the 255 fixed bits of the group order.  lax.scan keeps the
    jaxpr O(1) in the bit count (one body: double + conditional add) —
    the 255-step chain is sequential by nature, but every step is
    batched over all N validators, which is where the win lives."""
    from jax import lax

    one = jnp.broadcast_to(jnp.asarray(_ONE_M), X.shape)
    acc0 = (one, one, jnp.zeros_like(X))

    def step(acc, bit):
        aX, aY, aZ = acc
        dX, dY, dZ = g1_double(aX, aY, aZ)
        sX, sY, sZ = g1_add(dX, dY, dZ, X, Y, Z)
        return (
            jnp.where(bit, sX, dX),
            jnp.where(bit, sY, dY),
            jnp.where(bit, sZ, dZ),
        ), None

    (aX, aY, aZ), _ = lax.scan(step, acc0, jnp.asarray(_R_BITS))
    return aX, aY, aZ


def validate_g1(X, Y, valid):
    """Batched pubkey validation: (N, 32) affine Montgomery limbs +
    (N,) host-decode mask -> (N,) bool (on curve AND in the r-subgroup
    AND host-valid).  Rows the host already rejected (malformed
    encoding, infinity, padding) are sanitized to the identity BEFORE
    any shared arithmetic — the PR-11 lesson — and can never read True:
    an off-curve row's [r]·identity == identity would vacuously pass the
    subgroup test, so the on-curve bit masks it.

    Manifest kernel ``bls381_validate_g1``; jit site registered in
    JIT_SITES.
    """
    oncurve = valid & g1_on_curve(X, Y)
    one = jnp.broadcast_to(jnp.asarray(_ONE_M), X.shape)
    Z = select(oncurve, one, jnp.zeros_like(X))
    _, _, rZ = _g1_mul_order(X, Y, Z)
    return oncurve & is_zero(rZ)


def validate_aggregate_g1(X, Y, valid):
    """The fused FastAggregateVerify data plane: batched validation plus
    the tree-reduced G1 pubkey sum in ONE device program (one dispatch
    per aggregate-commit).  Invalid rows aggregate as the identity; the
    caller uses the sum only when every row validated (the verdict
    procedure in models/bls_verifier), so the sanitized rows are
    belt-and-suspenders, not semantics.

    Manifest kernel ``bls381_validate_aggregate_g1``; jit site
    registered in JIT_SITES.
    """
    ok = validate_g1(X, Y, valid)
    one = jnp.broadcast_to(jnp.asarray(_ONE_M), X.shape)
    Z = select(ok, one, jnp.zeros_like(X))
    Xa, Ya, Za = aggregate_g1(X, Y, Z)
    return ok, Xa, Ya, Za


# ------------------------------------------------------------ host bridge


_AGG_JIT = None
_VALIDATE_JIT = None
_VALIDATE_AGG_JIT = None
_JIT_MTX = None  # lazily a threading.Lock: concurrent first calls race


def _jit_lock():
    global _JIT_MTX
    if _JIT_MTX is None:
        import threading

        _JIT_MTX = threading.Lock()
    return _JIT_MTX


def ints_to_limbs_np(vals) -> np.ndarray:
    """Vectorized host packer: a sequence of field ints (already in the
    Montgomery domain) -> (N, 32) int32 limb array.  The per-int Python
    loop of to_limbs costs ~32 ops/value; at 10k validators x 2
    coordinates per commit that is real assembly time, so the 12-bit
    unpack is one numpy pass over the little-endian bytes (3 bytes = 2
    limbs)."""
    n = len(vals)
    if n == 0:
        return np.zeros((0, NLIMBS), dtype=np.int32)
    raw = np.frombuffer(
        b"".join(v.to_bytes(48, "little") for v in vals), dtype=np.uint8
    ).reshape(n, 48)
    trip = raw.reshape(n, NLIMBS // 2, 3).astype(np.int32)
    out = np.empty((n, NLIMBS), dtype=np.int32)
    out[:, 0::2] = trip[..., 0] | ((trip[..., 1] & 0xF) << 8)
    out[:, 1::2] = (trip[..., 1] >> 4) | (trip[..., 2] << 4)
    return out


def _pack_affine(points, bucket: int | None = None):
    """Affine (x, y) int pairs (None = invalid/infinity/padding) ->
    (X, Y, valid) host arrays in the Montgomery domain, padded to
    ``bucket`` rows (power-of-two >= 8 by default, so jit compiles a
    handful of shapes)."""
    n = len(points)
    if bucket is None:
        bucket = 8
        while bucket < n:
            bucket *= 2
    xs, ys, rows = [], [], []
    for i, aff in enumerate(points):
        if aff is None:
            continue
        xs.append(to_mont(aff[0]))
        ys.append(to_mont(aff[1]))
        rows.append(i)
    X = np.zeros((bucket, NLIMBS), dtype=np.int32)
    Y = np.zeros((bucket, NLIMBS), dtype=np.int32)
    valid = np.zeros((bucket,), dtype=bool)
    if rows:
        X[rows] = ints_to_limbs_np(xs)
        Y[rows] = ints_to_limbs_np(ys)
        valid[rows] = True
    return X, Y, valid


def _jac_to_affine_host(Xa, Ya, Za):
    """One fetched (32,) Jacobian limb triple -> affine int pair or None
    (infinity).  Exact bigint math; the single inversion runs on host."""
    xi = int(from_limbs(np.asarray(Xa))[()])
    yi = int(from_limbs(np.asarray(Ya))[()])
    zi = int(from_limbs(np.asarray(Za))[()])
    if zi == 0:
        return None
    z_inv = pow(zi, P - 2, P)
    z2 = z_inv * z_inv % P
    return (xi * z2 % P, yi * z2 % P * z_inv % P)


def validate_pubkeys_device(points) -> list[bool]:
    """Batched on-curve + subgroup validation of affine (x, y) int pairs
    (None rows = host-rejected, always False).  One device dispatch;
    the blocking result fetch is this bridge's declared collect point."""
    import jax

    global _VALIDATE_JIT
    if _VALIDATE_JIT is None:
        with _jit_lock():
            if _VALIDATE_JIT is None:
                _VALIDATE_JIT = jax.jit(validate_g1)
    n = len(points)
    if n == 0:
        return []
    X, Y, valid = _pack_affine(points)
    ok = _VALIDATE_JIT(jnp.asarray(X), jnp.asarray(Y), jnp.asarray(valid))
    return [bool(b) for b in np.asarray(ok)[:n]]


def validate_aggregate_device(points):
    """The fused FastAggregateVerify data plane in one dispatch:
    returns (per-row ok list, aggregate affine pair or None).  The
    aggregate sums exactly the rows that validated (invalid rows ride
    as the identity)."""
    import jax

    global _VALIDATE_AGG_JIT
    if _VALIDATE_AGG_JIT is None:
        with _jit_lock():
            if _VALIDATE_AGG_JIT is None:
                _VALIDATE_AGG_JIT = jax.jit(validate_aggregate_g1)
    n = len(points)
    if n == 0:
        return [], None
    X, Y, valid = _pack_affine(points)
    ok, Xa, Ya, Za = _VALIDATE_AGG_JIT(
        jnp.asarray(X), jnp.asarray(Y), jnp.asarray(valid)
    )
    return [bool(b) for b in np.asarray(ok)[:n]], _jac_to_affine_host(Xa, Ya, Za)


def aggregate_pubkeys_device(points):
    """Tree-reduce affine (x, y) int pairs (or compressed 48-byte keys)
    on device.  Returns the aggregate as an affine (x, y) pair, or None
    for infinity.  The jitted reducer is module-cached so compilation
    amortizes across calls of the same padded size."""
    import jax

    global _AGG_JIT
    if _AGG_JIT is None:
        with _jit_lock():
            if _AGG_JIT is None:
                _AGG_JIT = jax.jit(aggregate_g1)

    pts = []
    for pk in points:
        if isinstance(pk, (bytes, bytearray)):
            from ..crypto import bls12381 as host_bls

            aff = host_bls._g1_decompress(bytes(pk))
        else:
            aff = pk
        if aff is not None:
            pts.append(aff)
    if not pts:
        return None
    n = 1 << (len(pts) - 1).bit_length()
    X = np.zeros((n, NLIMBS), dtype=np.int32)
    Y = np.zeros((n, NLIMBS), dtype=np.int32)
    Z = np.zeros((n, NLIMBS), dtype=np.int32)
    X[: len(pts)] = ints_to_limbs_np([to_mont(x) for x, _ in pts])
    Y[: len(pts)] = ints_to_limbs_np([to_mont(y) for _, y in pts])
    Z[: len(pts)] = np.asarray(_ONE_M, dtype=np.int32)

    Xa, Ya, Za = _AGG_JIT(jnp.asarray(X), jnp.asarray(Y), jnp.asarray(Z))
    return _jac_to_affine_host(Xa, Ya, Za)
