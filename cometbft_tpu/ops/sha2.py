"""Vectorized SHA-256 / SHA-512 for TPU.

SHA-256 runs natively in uint32 (the TPU VPU's word size).  SHA-512 needs
64-bit words, which don't exist on TPU — each word is an (hi, lo) uint32
pair with explicit carry on addition.  Both kernels process a batch of
fixed-block-count padded messages with a lax.fori_loop over rounds (one
round body in the compiled graph) and a Python loop over the static block
count.

Host-side helpers pad variable-length messages into the fixed block layout
(numpy, vectorized) — message assembly is control-plane work; the digest
loop is the data plane.

Round constants are derived at import time from their public definition
(fractional parts of cube/square roots of the first primes) rather than
embedded as magic tables.

Reference workloads served by these kernels:
  - SHA-512: Ed25519 challenge hash k = H(R || A || M) per signature
    (crypto/ed25519 verification; RFC 8032 §5.1).
  - SHA-256: tmhash (crypto/tmhash/hash.go:22-37) and the RFC-6962 Merkle
    tree (crypto/merkle/tree.go:11, hash.go:21-44).
"""

from __future__ import annotations

import numpy as np
import jax.numpy as jnp
from jax import lax


def _primes(n: int):
    out, c = [], 2
    while len(out) < n:
        if all(c % q for q in out):
            out.append(c)
        c += 1
    return out


def _icbrt(x: int) -> int:
    r = int(round(x ** (1 / 3)))
    while r * r * r > x:
        r -= 1
    while (r + 1) ** 3 <= x:
        r += 1
    return r


def _isqrt(x: int) -> int:
    import math

    return math.isqrt(x)


_P64 = _primes(80)
K512 = np.array(
    [[(v := _icbrt(p << 192) & ((1 << 64) - 1)) >> 32, v & 0xFFFFFFFF] for p in _P64],
    dtype=np.uint32,
)
H512 = np.array(
    [
        [(v := _isqrt(p << 128) & ((1 << 64) - 1)) >> 32, v & 0xFFFFFFFF]
        for p in _P64[:8]
    ],
    dtype=np.uint32,
)
K256 = np.array([_icbrt(p << 96) & 0xFFFFFFFF for p in _P64[:64]], dtype=np.uint32)
H256 = np.array([_isqrt(p << 64) & 0xFFFFFFFF for p in _P64[:8]], dtype=np.uint32)


# --------------------------------------------------------------- SHA-256


def _rotr32(x, n):
    return lax.shift_right_logical(x, np.uint32(n)) | lax.shift_left(
        x, np.uint32(32 - n)
    )


def sha256_blocks(blocks, active_blocks=None):
    """(..., nblocks, 64) uint8 padded message -> (..., 32) uint8 digest.

    active_blocks: optional (...,) int32 per-row live block count (rows with
    shorter messages stop updating state after their own final block, since
    SHA-2 padding is minimal per message while the array shape is static).

    Manifest kernel ``sha256_blocks`` (jitted via models//crypto callers).
    """
    nblocks = blocks.shape[-2]
    w0 = blocks.astype(jnp.uint32).reshape(blocks.shape[:-1] + (16, 4))
    # big-endian words
    words = (
        lax.shift_left(w0[..., 0], np.uint32(24))
        | lax.shift_left(w0[..., 1], np.uint32(16))
        | lax.shift_left(w0[..., 2], np.uint32(8))
        | w0[..., 3]
    )  # (..., nblocks, 16)
    state = jnp.broadcast_to(
        jnp.asarray(H256), blocks.shape[:-2] + (8,)
    ).astype(jnp.uint32)
    kt = jnp.asarray(K256)

    def round_body(t, carry):
        st, w = carry
        a, b, c, d, e, f, g, h = [st[..., i] for i in range(8)]
        s1 = _rotr32(e, 6) ^ _rotr32(e, 11) ^ _rotr32(e, 25)
        ch = (e & f) ^ (~e & g)
        wt = w[..., 0]
        t1 = h + s1 + ch + kt[t] + wt
        s0 = _rotr32(a, 2) ^ _rotr32(a, 13) ^ _rotr32(a, 22)
        maj = (a & b) ^ (a & c) ^ (b & c)
        t2 = s0 + maj
        st = jnp.stack([t1 + t2, a, b, c, d + t1, e, f, g], axis=-1)
        # message schedule: w16 = σ1(w14) + w9 + σ0(w1) + w0
        w14, w9, w1, w0_ = w[..., 14], w[..., 9], w[..., 1], w[..., 0]
        sg0 = _rotr32(w1, 7) ^ _rotr32(w1, 18) ^ lax.shift_right_logical(
            w1, np.uint32(3)
        )
        sg1 = _rotr32(w14, 17) ^ _rotr32(w14, 19) ^ lax.shift_right_logical(
            w14, np.uint32(10)
        )
        w16 = sg1 + w9 + sg0 + w0_
        w = jnp.concatenate([w[..., 1:], w16[..., None]], axis=-1)
        return st, w

    for blk in range(nblocks):
        w = words[..., blk, :]
        st, _ = lax.fori_loop(0, 64, round_body, (state, w))
        new_state = state + st
        if active_blocks is None:
            state = new_state
        else:
            live = (active_blocks > blk)[..., None]
            state = jnp.where(live, new_state, state)

    b = jnp.stack(
        [
            lax.shift_right_logical(state, np.uint32(s)).astype(jnp.uint8)
            for s in (24, 16, 8, 0)
        ],
        axis=-1,
    )
    return b.reshape(state.shape[:-1] + (32,))


# --------------------------------------------------------------- SHA-512


def _rotr64(hi, lo, n):
    if n < 32:
        nh = np.uint32(n)
        inv = np.uint32(32 - n)
        rh = lax.shift_right_logical(hi, nh) | lax.shift_left(lo, inv)
        rl = lax.shift_right_logical(lo, nh) | lax.shift_left(hi, inv)
    elif n == 32:
        rh, rl = lo, hi
    else:
        m = np.uint32(n - 32)
        inv = np.uint32(64 - n)
        rh = lax.shift_right_logical(lo, m) | lax.shift_left(hi, inv)
        rl = lax.shift_right_logical(hi, m) | lax.shift_left(lo, inv)
    return rh, rl


def _shr64(hi, lo, n):
    nh = np.uint32(n)
    inv = np.uint32(32 - n)
    rh = lax.shift_right_logical(hi, nh)
    rl = lax.shift_right_logical(lo, nh) | lax.shift_left(hi, inv)
    return rh, rl


def _add64(ah, al, bh, bl):
    lo = al + bl
    # bool -> uint32 is the justified carry conversion of the (hi, lo)
    # pair representation (kernel_manifest.ALLOWED_CONVERSIONS)
    carry = (lo < al).astype(jnp.uint32)
    return ah + bh + carry, lo


def _add64_many(*pairs):
    h, l = pairs[0]
    for ph, pl in pairs[1:]:
        h, l = _add64(h, l, ph, pl)
    return h, l


def sha512_blocks(blocks, active_blocks=None):
    """(..., nblocks, 128) uint8 padded message -> (..., 64) uint8 digest.

    active_blocks: optional (...,) int32 per-row live block count (see
    sha256_blocks).

    Manifest kernel ``sha512_blocks``.
    """
    nblocks = blocks.shape[-2]
    w0 = blocks.astype(jnp.uint32).reshape(blocks.shape[:-1] + (16, 8))

    def be32(b0, b1, b2, b3):
        return (
            lax.shift_left(b0, np.uint32(24))
            | lax.shift_left(b1, np.uint32(16))
            | lax.shift_left(b2, np.uint32(8))
            | b3
        )

    w_hi = be32(w0[..., 0], w0[..., 1], w0[..., 2], w0[..., 3])
    w_lo = be32(w0[..., 4], w0[..., 5], w0[..., 6], w0[..., 7])
    # (..., nblocks, 16) each

    state = jnp.broadcast_to(
        jnp.asarray(H512), blocks.shape[:-2] + (8, 2)
    ).astype(jnp.uint32)
    kt = jnp.asarray(K512)  # (80, 2)

    def round_body(t, carry):
        st, wh, wl = carry  # st: (..., 8, 2); wh/wl: (..., 16)
        ah, al = st[..., 0, 0], st[..., 0, 1]
        bh, bl = st[..., 1, 0], st[..., 1, 1]
        ch_, cl = st[..., 2, 0], st[..., 2, 1]
        dh, dl = st[..., 3, 0], st[..., 3, 1]
        eh, el = st[..., 4, 0], st[..., 4, 1]
        fh, fl = st[..., 5, 0], st[..., 5, 1]
        gh, gl = st[..., 6, 0], st[..., 6, 1]
        hh, hl = st[..., 7, 0], st[..., 7, 1]

        x1 = _rotr64(eh, el, 14)
        x2 = _rotr64(eh, el, 18)
        x3 = _rotr64(eh, el, 41)
        s1h, s1l = x1[0] ^ x2[0] ^ x3[0], x1[1] ^ x2[1] ^ x3[1]
        chh = (eh & fh) ^ (~eh & gh)
        chl = (el & fl) ^ (~el & gl)
        t1h, t1l = _add64_many(
            (hh, hl),
            (s1h, s1l),
            (chh, chl),
            (kt[t, 0], kt[t, 1]),
            (wh[..., 0], wl[..., 0]),
        )
        y1 = _rotr64(ah, al, 28)
        y2 = _rotr64(ah, al, 34)
        y3 = _rotr64(ah, al, 39)
        s0h, s0l = y1[0] ^ y2[0] ^ y3[0], y1[1] ^ y2[1] ^ y3[1]
        mjh = (ah & bh) ^ (ah & ch_) ^ (bh & ch_)
        mjl = (al & bl) ^ (al & cl) ^ (bl & cl)
        t2h, t2l = _add64(s0h, s0l, mjh, mjl)
        nah, nal = _add64(t1h, t1l, t2h, t2l)
        neh, nel = _add64(dh, dl, t1h, t1l)
        st = jnp.stack(
            [
                jnp.stack([nah, nal], axis=-1),
                jnp.stack([ah, al], axis=-1),
                jnp.stack([bh, bl], axis=-1),
                jnp.stack([ch_, cl], axis=-1),
                jnp.stack([neh, nel], axis=-1),
                jnp.stack([eh, el], axis=-1),
                jnp.stack([fh, fl], axis=-1),
                jnp.stack([gh, gl], axis=-1),
            ],
            axis=-2,
        )
        # schedule: w16 = σ1(w14) + w9 + σ0(w1) + w0
        a1 = _rotr64(wh[..., 14], wl[..., 14], 19)
        a2 = _rotr64(wh[..., 14], wl[..., 14], 61)
        a3 = _shr64(wh[..., 14], wl[..., 14], 6)
        sg1h, sg1l = a1[0] ^ a2[0] ^ a3[0], a1[1] ^ a2[1] ^ a3[1]
        b1 = _rotr64(wh[..., 1], wl[..., 1], 1)
        b2 = _rotr64(wh[..., 1], wl[..., 1], 8)
        b3 = _shr64(wh[..., 1], wl[..., 1], 7)
        sg0h, sg0l = b1[0] ^ b2[0] ^ b3[0], b1[1] ^ b2[1] ^ b3[1]
        w16h, w16l = _add64_many(
            (sg1h, sg1l),
            (wh[..., 9], wl[..., 9]),
            (sg0h, sg0l),
            (wh[..., 0], wl[..., 0]),
        )
        wh = jnp.concatenate([wh[..., 1:], w16h[..., None]], axis=-1)
        wl = jnp.concatenate([wl[..., 1:], w16l[..., None]], axis=-1)
        return st, wh, wl

    for blk in range(nblocks):
        st, _, _ = lax.fori_loop(
            0, 80, round_body, (state, w_hi[..., blk, :], w_lo[..., blk, :])
        )
        # state += st (64-bit lane-wise)
        sh, sl = _add64(
            state[..., 0], state[..., 1], st[..., 0], st[..., 1]
        )
        new_state = jnp.stack([sh, sl], axis=-1)
        if active_blocks is None:
            state = new_state
        else:
            live = (active_blocks > blk)[..., None, None]
            state = jnp.where(live, new_state, state)

    flat = state.reshape(state.shape[:-2] + (16,))  # hi,lo interleaved BE order
    b = jnp.stack(
        [
            lax.shift_right_logical(flat, np.uint32(s)).astype(jnp.uint8)
            for s in (24, 16, 8, 0)
        ],
        axis=-1,
    )
    return b.reshape(state.shape[:-2] + (64,))


# ------------------------------------------- device-side R||A||M assembly


def ram_blocks_from_parts(r, a, m, mlen, nblocks: int):
    """Assemble SHA-512-padded R || A || M blocks ON DEVICE.

    r, a    : (V, 32) uint8 — signature R half / compressed pubkey
    m       : (V, maxm) uint8 — messages, zero-padded to the static width
    mlen    : (V,) int32 — per-row live message length (<= maxm)
    nblocks : static block count; maxm + 81 <= nblocks*128 must hold

    Returns (blocks (V, nblocks, 128) uint8, active (V,) int32).  The host
    used to ship fully padded 128-byte blocks per row (64 bytes of R+A
    repeated, zero padding, trailers); over a ~10 MB/s device link the
    padding itself dominated the verify call, so only the tight payload
    crosses the wire and the minimal per-row SHA padding (0x80 trailer +
    128-bit big-endian bit length in the row's own final block) is
    reconstructed here with static-offset writes + iota masks.
    """
    V, maxm = m.shape
    width = nblocks * 128
    assert maxm + 64 + 17 <= width, (maxm, nblocks)
    total = mlen + 64  # live bytes before padding
    pos = jnp.arange(width, dtype=jnp.int32)[None, :]  # (1, width)
    buf = jnp.zeros((V, width), dtype=jnp.uint8)
    buf = buf.at[:, :32].set(r)
    buf = buf.at[:, 32:64].set(a)
    buf = buf.at[:, 64 : 64 + maxm].set(m)
    # zero any stale bytes beyond each row's message, then the 0x80 marker
    live = pos < total[:, None]
    buf = jnp.where(live, buf, 0)
    buf = buf | ((pos == total[:, None]) * jnp.uint8(0x80)).astype(jnp.uint8)
    # 128-bit big-endian bit length in the last 16 bytes of the row's own
    # final block; bitlen < 2^32 here so only the last 4 bytes are nonzero
    nbr = (total + 17 + 127) // 128  # (V,) per-row block count
    shift = (nbr[:, None] * 128 - 1 - pos) * 8  # BE byte shift at each col
    bitlen = (total * 8)[:, None]
    lb = jnp.where(
        (shift >= 0) & (shift < 32),
        lax.shift_right_logical(bitlen, jnp.minimum(jnp.maximum(shift, 0), 31))
        & 0xFF,
        0,
    ).astype(jnp.uint8)
    buf = buf | lb
    return buf.reshape(V, nblocks, 128), nbr


def parse_verify_payload(payload, pubs):
    """Decode the tight verify payload and assemble its SHA-512 blocks.

    payload : (V, 68 + maxm) uint8 — R(32) | s(32) | mlen(3B LE) |
              live(1B) | msg (models/comb_verifier.assemble_payload)
    pubs    : (V, 32) uint8 — device-resident compressed pubkeys

    Returns (r, s, blocks, active, live): the single source of truth for
    the payload row layout, shared by the single-device program
    (models/comb_verifier._device_verify) and the mesh-sharded one
    (parallel/verify).  active is 0 for non-live rows.

    Manifest kernel ``sha2_parse_verify_payload``.
    """
    maxm = payload.shape[1] - 68
    nblocks = (64 + maxm + 17 + 127) // 128
    r = payload[:, :32]
    s = payload[:, 32:64]
    mlen = (
        payload[:, 64].astype(jnp.int32)
        | (payload[:, 65].astype(jnp.int32) << 8)
        | (payload[:, 66].astype(jnp.int32) << 16)
    )
    live = payload[:, 67] == 1
    blocks, nbr = ram_blocks_from_parts(r, pubs, payload[:, 68:], mlen, nblocks)
    active = jnp.where(live, nbr, 0)
    return r, s, blocks, active, live


# ------------------------------------------------------- host-side padding


def pad_messages_sha512(msgs: list[bytes], max_len: int | None = None):
    """Host: variable-length messages -> (buf, active) for sha512_blocks.

    buf is (n, nblocks, 128) uint8 with *minimal* per-row SHA-512 padding
    (0x80, zeros, 128-bit big-endian bit length at the end of the row's own
    final block); active is (n,) int32 per-row live block counts.
    """
    n = len(msgs)
    longest = max((len(m) for m in msgs), default=0)
    if max_len is not None:
        longest = max(longest, max_len)
    nblocks = max(1, (longest + 17 + 127) // 128)
    buf = np.zeros((n, nblocks * 128), dtype=np.uint8)
    active = np.zeros(n, dtype=np.int32)
    for i, m in enumerate(msgs):
        ln = len(m)
        nb = (ln + 17 + 127) // 128
        active[i] = nb
        buf[i, :ln] = np.frombuffer(m, dtype=np.uint8)
        buf[i, ln] = 0x80
        buf[i, nb * 128 - 16 : nb * 128] = np.frombuffer(
            (ln * 8).to_bytes(16, "big"), dtype=np.uint8
        )
    return buf.reshape(n, nblocks, 128), active


def pad_messages_sha256(msgs: list[bytes], max_len: int | None = None):
    """Host: variable-length messages -> (buf, active) for sha256_blocks."""
    n = len(msgs)
    longest = max((len(m) for m in msgs), default=0)
    if max_len is not None:
        longest = max(longest, max_len)
    nblocks = max(1, (longest + 9 + 63) // 64)
    buf = np.zeros((n, nblocks * 64), dtype=np.uint8)
    active = np.zeros(n, dtype=np.int32)
    for i, m in enumerate(msgs):
        ln = len(m)
        nb = (ln + 9 + 63) // 64
        active[i] = nb
        buf[i, :ln] = np.frombuffer(m, dtype=np.uint8)
        buf[i, ln] = 0x80
        buf[i, nb * 64 - 8 : nb * 64] = np.frombuffer(
            (ln * 8).to_bytes(8, "big"), dtype=np.uint8
        )
    return buf.reshape(n, nblocks, 64), active
