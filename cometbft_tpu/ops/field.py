"""Vectorized GF(2^255-19) arithmetic for TPU.

Field elements are little-endian arrays of 22 signed 12-bit limbs held in
int32, shaped (..., NLIMBS, L): the limb axis is SECOND-MINOR and the
batch ("lane") axis L is minor.  TPU vector registers tile the two minor
dims as (8 sublanes x 128 lanes); with limbs on the minor axis (the
previous layout) every element-wise op used 22 of 128 lanes (83% waste).
Limbs-on-sublanes puts the big batch axis on lanes (full utilization) and
the 22 limbs on sublanes (22 of 24, 8% pad) — measured ~7x faster per
field mul on the CPU backend and the same argument applies to the VPU.
All intermediate products and accumulations fit in int32 (no int64 on
device), and every operation is element-wise/branch-free over arbitrary
leading batch axes, so a 10k-signature commit verification maps onto the
vector unit as one fused program (reference workload:
crypto/ed25519/ed25519.go:188-222 BatchVerifier — curve25519-voi's
CPU-SIMD equivalent, re-designed for TPU).

Bound contract (|limb| bounds; exercised adversarially in tests/test_field.py):

  TIGHT: output of mul/square/carry/mul_small —
         |limb 0| <= 3584, |limbs 1..21| <= 2051.
  MULIN: mul/square accept sums of up to FOUR tight elements
         (|limb 0| <= 14336, others <= 8204).

  Conv safety: for output limb k, at most one product involves a_0 and one
  involves b_0, so |conv_k| <= 22*8204^2 + 2*14336*8204 = 1.72e9 < 2^31-1.

Radix 2^12 ⇒ 22 limbs span 264 bits; 2^264 ≡ 19·2^9 = 9728 (mod p).  The
top-limb carry (weight 2^264) folds back as q·19·2^9, decomposed as
(19q mod 8)·2^9 into limb 0 plus (19q div 8) into limb 1 so the addend never
exceeds int32 range even for large q.
"""

from __future__ import annotations

import functools

import numpy as np
import jax.numpy as jnp
from jax import lax

NLIMBS = 22
BITS = 12
RADIX = 1 << BITS  # 4096
MASK = RADIX - 1
FOLD = 19 << (NLIMBS * BITS - 255)  # 2^264 mod p = 19*2^9 = 9728
FOLD2_SHIFTED = 361 * 64  # 2^528 mod p = 361*2^18 = 23104 * 2^12

P = (1 << 255) - 19

_POW2 = np.array([1 << i for i in range(BITS)], dtype=np.int32)

# Limb decomposition of 2^9 * p = 2^264 - 9728 with every limb in
# [2^11, 2^13): added before the unsigned carry chain in freeze() so that
# signed limbs become non-negative without changing the value mod p.
_BIAS = np.full(NLIMBS, MASK, dtype=np.int32)  # all-4095 = 2^264 - 1
_BIAS[0] = MASK - 9727 + RADIX * 3  # borrow 3 from limb 1
_BIAS[1] = MASK - 3
assert sum(int(_BIAS[i]) << (BITS * i) for i in range(NLIMBS)) == (P << 9)

_P_LIMBS = np.zeros(NLIMBS, dtype=np.int32)
_tmp = P
for _i in range(NLIMBS):
    _P_LIMBS[_i] = _tmp & MASK
    _tmp >>= BITS


def to_limbs(x: int, batch_shape=()) -> np.ndarray:
    """Host-side: Python int -> (22,) limb vector (numpy int32); with a
    batch_shape, broadcast to batch_shape[:-1] + (22, batch_shape[-1])."""
    x %= P
    out = np.zeros(NLIMBS, dtype=np.int32)
    for i in range(NLIMBS):
        out[i] = x & MASK
        x >>= BITS
    if batch_shape:
        out = np.broadcast_to(
            out[:, None], batch_shape[:-1] + (NLIMBS, batch_shape[-1])
        ).copy()
    return out


@functools.lru_cache(maxsize=64)
def cl(x: int):
    """Device constant: (22, 1) limbs of x, broadcastable against any
    (..., 22, L) element."""
    return jnp.asarray(to_limbs(x)[:, None])


def from_limbs(limbs) -> int:
    """Host-side: (22,) limb vector -> Python int (not reduced mod p)."""
    limbs = np.asarray(limbs)
    return sum(int(limbs[i]) << (BITS * i) for i in range(limbs.shape[0]))


def _el_shape(batch_shape):
    if not batch_shape:
        return (NLIMBS, 1)
    return tuple(batch_shape[:-1]) + (NLIMBS, batch_shape[-1])


def zero(batch_shape=()):
    return jnp.zeros(_el_shape(batch_shape), dtype=jnp.int32)


def one(batch_shape=()):
    z = np.zeros(_el_shape(batch_shape), dtype=np.int32)
    z[..., 0, :] = 1
    return jnp.asarray(z)


def add(a, b):
    """Limb-wise add; no carry. Caller tracks the bound budget."""
    return a + b


def sub(a, b):
    """Limb-wise subtract; no carry (signed limbs make this exact)."""
    return a - b


def neg(a):
    return -a


def _pad_limb_axis(x, lo: int, hi: int):
    pad = [(0, 0)] * (x.ndim - 2) + [(lo, hi), (0, 0)]
    return jnp.pad(x, pad)


def _carry_round(c):
    """One parallel signed carry round over the limb axis (-2).

    q = round(c / 2^12); limbs land in [-2048, 2047] before carry-ins.
    Returns (c', top_carry) where top_carry has weight 2^(12*nlimbs).
    """
    q = lax.shift_right_arithmetic(c + (RADIX >> 1), BITS)
    c = c - lax.shift_left(q, BITS)
    carry_in = _pad_limb_axis(q[..., :-1, :], 1, 0)
    return c + carry_in, q[..., -1, :]


def _fold_top(c, q):
    """Add q * 2^264 ≡ q*19*2^9 (mod p) into limbs 0/1 without overflow.

    v = 19q (|v| < 2^26 for any carry q seen here); v*2^9 decomposes as
    (v mod 8)*2^9 at limb 0 plus (v div 8) at limb 1 — both small.
    """
    v = q * 19
    lo = (v & 7) * (1 << 9)
    hi = lax.shift_right_arithmetic(v, 3)
    c = c.at[..., 0, :].add(lo)
    c = c.at[..., 1, :].add(hi)
    return c


def carry(a, rounds: int = 3):
    """Reduce a 22-limb signed value (|limb| < 2^30.8) to TIGHT bounds."""
    c = a
    for _ in range(rounds):
        c, top = _carry_round(c)
        c = _fold_top(c, top)
    return c


def _conv(a, b, n: int, m: int):
    """Schoolbook product of n-limb a and m-limb b -> (n+m-1)-limb conv.

    Unrolled static loop: m shifted multiply-adds, each a width-n vector op
    over the lane axis.
    """
    out_len = n + m - 1
    shape = jnp.broadcast_shapes(a.shape[:-2], b.shape[:-2]) + (
        out_len,
        jnp.broadcast_shapes(a.shape[-1:], b.shape[-1:])[0],
    )
    c = jnp.zeros(shape, dtype=jnp.int32)
    for i in range(m):
        c = c.at[..., i : i + n, :].add(a * b[..., i : i + 1, :])
    return c


def _reduce_conv(c):
    """Reduce a 43-limb signed conv (|limb| <= 1.72e9) to TIGHT limbs."""
    lo = c[..., :NLIMBS, :]
    hi = c[..., NLIMBS:, :]  # 21 limbs, weight offset 2^264
    # Carry hi independently (pad so round-carries stay inside; top carry of
    # the padded array is provably zero with 3 pad limbs / 3 rounds).
    hi = _pad_limb_axis(hi, 0, 3)
    for _ in range(3):
        hi, _ = _carry_round(hi)
    # Fold: limbs 0..21 of hi (abs positions 22..43) scale by 2^264 ≡ 9728;
    # pad limbs 22/23 (abs 44/45) scale by 2^528 ≡ 23104·2^12 → limbs 1/2.
    lo = lo + hi[..., :NLIMBS, :] * FOLD
    lo = lo.at[..., 1, :].add(hi[..., NLIMBS, :] * FOLD2_SHIFTED)
    lo = lo.at[..., 2, :].add(hi[..., NLIMBS + 1, :] * FOLD2_SHIFTED)
    return carry(lo, rounds=3)


def mul(a, b):
    """Field multiply. Inputs within MULIN contract; output TIGHT."""
    return _reduce_conv(_conv(a, b, NLIMBS, NLIMBS))


def square(a):
    """Field square (XLA CSEs the shared operand in the conv)."""
    return _reduce_conv(_conv(a, a, NLIMBS, NLIMBS))


def mul_small(a, k: int):
    """Multiply by a small host constant; |a·k| limbs must stay < 2^30.8."""
    return carry(a * jnp.int32(k), rounds=3)


def pow2k(a, k: int):
    """a^(2^k) by k squarings.

    Long runs use lax.fori_loop so the traced graph stays one square body
    regardless of k (XLA compiles once, loops on device).
    """
    if k <= 4:
        for _ in range(k):
            a = square(a)
        return a
    return lax.fori_loop(0, k, lambda _, x: square(x), a)


def _chain_250(x):
    """x^(2^250 - 1) — shared prefix of the invert and sqrt chains.

    Classic curve25519 square-and-multiply ladder (public-domain structure).
    Returns (x^(2^250-1), x^11).
    """
    z2 = square(x)                        # 2
    z8 = pow2k(z2, 2)                     # 8
    z9 = mul(x, z8)                       # 9
    z11 = mul(z2, z9)                     # 11
    z22 = square(z11)                     # 22
    z_5_0 = mul(z9, z22)                  # 2^5 - 1 = 31
    z_10_5 = pow2k(z_5_0, 5)
    z_10_0 = mul(z_10_5, z_5_0)           # 2^10 - 1
    z_20_10 = pow2k(z_10_0, 10)
    z_20_0 = mul(z_20_10, z_10_0)         # 2^20 - 1
    z_40_20 = pow2k(z_20_0, 20)
    z_40_0 = mul(z_40_20, z_20_0)         # 2^40 - 1
    z_50_10 = pow2k(z_40_0, 10)
    z_50_0 = mul(z_50_10, z_10_0)         # 2^50 - 1
    z_100_50 = pow2k(z_50_0, 50)
    z_100_0 = mul(z_100_50, z_50_0)       # 2^100 - 1
    z_200_100 = pow2k(z_100_0, 100)
    z_200_0 = mul(z_200_100, z_100_0)     # 2^200 - 1
    z_250_50 = pow2k(z_200_0, 50)
    z_250_0 = mul(z_250_50, z_50_0)       # 2^250 - 1
    return z_250_0, z11


def invert(x):
    """x^(p-2);  p-2 = 2^255 - 21 = (2^250-1)·2^5 + 11."""
    z_250_0, z11 = _chain_250(x)
    return mul(pow2k(z_250_0, 5), z11)


def pow_p58(x):
    """x^((p-5)/8);  (p-5)/8 = 2^252 - 3 = (2^250-1)·2^2 + 1."""
    z_250_0, _ = _chain_250(x)
    return mul(pow2k(z_250_0, 2), x)


def freeze(a):
    """Fully reduce to canonical limbs in [0, 2^12), value in [0, p)."""
    c = carry(a, rounds=3)
    # Make non-negative: add 2^9 * p (limb-wise bias keeps limbs >= 0).
    c = c + jnp.asarray(_BIAS)[:, None]
    c = _unsigned_carry(c)
    # Two rounds of top-bit folding: value < 2^264 -> < 2^255 + eps -> < 2^255.
    for _ in range(2):
        hi = lax.shift_right_logical(c[..., -1, :], 3)  # bits >= 255
        c = c.at[..., -1, :].set(c[..., -1, :] & 7)
        c = c.at[..., 0, :].add(hi * 19)
        c = _unsigned_carry(c)
    # Conditional subtract p (value in [0, 2^255) -> canonical [0, p)).
    borrow = jnp.zeros(c.shape[:-2] + c.shape[-1:], dtype=jnp.int32)
    w = jnp.zeros_like(c)
    for i in range(NLIMBS):
        d = c[..., i, :] - jnp.int32(int(_P_LIMBS[i])) - borrow
        borrow = lax.shift_right_logical(d, 31) & 1  # 1 if negative
        w = w.at[..., i, :].set(d + lax.shift_left(borrow, BITS))
    ge_p = borrow == 0
    return jnp.where(ge_p[..., None, :], w, c)


def _unsigned_carry(c):
    """Sequential carry for non-negative limbs; top carry folds via 9728.

    Top carry here is < 2^4 (values < 2^268), so q*FOLD fits trivially.
    """
    out = jnp.zeros_like(c)
    k = jnp.zeros(c.shape[:-2] + c.shape[-1:], dtype=jnp.int32)
    for i in range(NLIMBS):
        t = c[..., i, :] + k
        out = out.at[..., i, :].set(t & MASK)
        k = lax.shift_right_logical(t, BITS)
    out = out.at[..., 0, :].add(k * FOLD)
    # Local ripple in case limb 0/1 overflowed (addend < 2^18).
    for i in range(2):
        ki = lax.shift_right_logical(out[..., i, :], BITS)
        out = out.at[..., i, :].set(out[..., i, :] & MASK)
        out = out.at[..., i + 1, :].add(ki)
    return out


def eq(a, b):
    """Field equality (branch-free): freeze both, compare limbs."""
    return jnp.all(freeze(a) == freeze(b), axis=-2)


def is_zero(a):
    return jnp.all(freeze(a) == 0, axis=-2)


def is_negative(a):
    """RFC 8032 sign: lowest bit of the canonical encoding."""
    return (freeze(a)[..., 0, :] & 1).astype(jnp.bool_)


def select(cond, a, b):
    """Branch-free select: cond ? a : b.  cond shape = batch shape
    (leading axes + lane axis)."""
    return jnp.where(cond[..., None, :], a, b)


def from_bytes(b):
    """(..., 32) uint8 LE -> (..., 22, L) limbs, where L is the last
    batch axis of b (a lone (32,) input yields (22, 1)).

    All 256 bits are taken; callers that need the sign bit (point
    decompression) mask it off first.  Value may exceed p — ZIP-215
    tolerates non-canonical y encodings, and the limb form handles
    values up to 2^264 transparently.
    """
    b = b.astype(jnp.int32)
    bits = jnp.stack(
        [lax.shift_right_logical(b, k) & 1 for k in range(8)], axis=-1
    )  # (..., 32, 8)
    bits = bits.reshape(bits.shape[:-2] + (256,))
    pad = [(0, 0)] * (bits.ndim - 1) + [(0, NLIMBS * BITS - 256)]
    bits = jnp.pad(bits, pad)
    bits = bits.reshape(bits.shape[:-1] + (NLIMBS, BITS))
    limbs = jnp.sum(bits * jnp.asarray(_POW2), axis=-1).astype(jnp.int32)
    if limbs.ndim == 1:
        return limbs[:, None]
    return jnp.swapaxes(limbs, -1, -2)


def to_bytes(a):
    """(..., 22, L) limbs -> canonical (..., L, 32) uint8 LE encoding."""
    c = jnp.swapaxes(freeze(a), -1, -2)  # (..., L, 22)
    bits = jnp.stack(
        [lax.shift_right_logical(c, k) & 1 for k in range(BITS)], axis=-1
    )  # (..., L, 22, 12)
    bits = bits.reshape(bits.shape[:-2] + (NLIMBS * BITS,))[..., :256]
    bits = bits.reshape(bits.shape[:-1] + (32, 8))
    return jnp.sum(
        bits * jnp.asarray([1 << k for k in range(8)], dtype=jnp.int32), axis=-1
    ).astype(jnp.uint8)
