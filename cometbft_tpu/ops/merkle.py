"""Vectorized RFC-6962 Merkle tree kernel for TPU.

The reference hashes a Merkle tree per block over txs, evidence, commit
signatures, and validator sets (crypto/merkle/tree.go:11-27 recursive,
tree.go:68 iterative; domain-separated leaf/inner hashing at
crypto/merkle/hash.go:21-44).  Its recursive split at the largest power of
two below n (tree.go:101 getSplitPoint) is equivalent to a level-by-level
reduction where an odd trailing node is promoted unchanged — which is the
shape a TPU wants: each level is one batched 2-block SHA-256 over all
sibling pairs, log2(n) levels total, no recursion and no data-dependent
control flow.

Leaf hashing (0x00 || leaf over variable-length leaves) is padded on host
(ops/sha2.pad_messages_sha256) and digested as one batch; inner levels are
assembled entirely on device (fixed 65-byte messages -> exactly 2 SHA-256
blocks).
"""

from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from . import sha2

_LEAF_PREFIX = b"\x00"
_INNER_PREFIX = b"\x01"

# Precomputed SHA-256 padding tail for the fixed 65-byte inner message:
# 0x01 || left(32) || right(32) || 0x80 || zeros || bitlen(520, 8B BE).
_INNER_TAIL = np.zeros(63, dtype=np.uint8)
_INNER_TAIL[0] = 0x80
_INNER_TAIL[-8:] = np.frombuffer((65 * 8).to_bytes(8, "big"), dtype=np.uint8)

_EMPTY_HASH = None  # filled lazily (sha256 of b"" on host)


def _inner_blocks(left, right):
    """(m, 32), (m, 32) -> (m, 2, 64) padded inner-node messages."""
    m = left.shape[0]
    prefix = jnp.full((m, 1), 0x01, dtype=jnp.uint8)
    tail = jnp.broadcast_to(jnp.asarray(_INNER_TAIL), (m, 63))
    msg = jnp.concatenate([prefix, left, right, tail], axis=-1)  # (m, 128)
    return msg.reshape(m, 2, 64)


def hash_level(nodes):
    """One tree level: (n, 32) -> (ceil(n/2), 32).

    Adjacent pairs are inner-hashed in one batch; an odd trailing node is
    promoted unchanged (equivalent to the reference's power-of-two split,
    tree.go:101).  n is static under jit, so the promotion is trace-time
    Python, not device control flow.
    """
    n = nodes.shape[0]
    if n == 1:
        return nodes
    pairs = n // 2
    left = nodes[: 2 * pairs : 2]
    right = nodes[1 : 2 * pairs : 2]
    hashed = sha2.sha256_blocks(_inner_blocks(left, right))
    if n % 2:
        hashed = jnp.concatenate([hashed, nodes[-1:]], axis=0)
    return hashed


def root_from_leaf_hashes(leaf_hashes):
    """(n, 32) leaf hashes -> (32,) RFC-6962 root.  n >= 1, static."""
    nodes = leaf_hashes
    while nodes.shape[0] > 1:
        nodes = hash_level(nodes)
    return nodes[0]


def leaf_hashes_from_padded(blocks, active):
    """Device leaf hashing: padded (n, nb, 64) 0x00-prefixed messages -> (n, 32)."""
    return sha2.sha256_blocks(blocks, active)


def pad_leaves(leaves: list[bytes]):
    """Host: raw leaves -> (blocks, active) with the 0x00 leaf prefix applied."""
    return sha2.pad_messages_sha256([_LEAF_PREFIX + l for l in leaves])


def root_from_leaves(blocks, active):
    """Full device pipeline: host-padded leaves -> root.  Jit-friendly.

    Manifest kernel ``merkle_root_from_leaves`` (jitted from
    crypto/merkle.py); per-device subtree body of
    ``sharded_merkle_root`` (census: one all_gather of the D subtree
    roots — analysis/shardcheck)."""
    return root_from_leaf_hashes(leaf_hashes_from_padded(blocks, active))
