"""Vectorized RFC-6962 Merkle tree kernel for TPU.

The reference hashes a Merkle tree per block over txs, evidence, commit
signatures, and validator sets (crypto/merkle/tree.go:11-27 recursive,
tree.go:68 iterative; domain-separated leaf/inner hashing at
crypto/merkle/hash.go:21-44).  Its recursive split at the largest power of
two below n (tree.go:101 getSplitPoint) is equivalent to a level-by-level
reduction where an odd trailing node is promoted unchanged — which is the
shape a TPU wants: each level is one batched 2-block SHA-256 over all
sibling pairs, log2(n) levels total, no recursion and no data-dependent
control flow.

Leaf hashing (0x00 || leaf over variable-length leaves) is padded on host
(ops/sha2.pad_messages_sha256) and digested as one batch; inner levels are
assembled entirely on device (fixed 65-byte messages -> exactly 2 SHA-256
blocks).
"""

from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from . import sha2

_LEAF_PREFIX = b"\x00"
_INNER_PREFIX = b"\x01"

# Precomputed SHA-256 padding tail for the fixed 65-byte inner message:
# 0x01 || left(32) || right(32) || 0x80 || zeros || bitlen(520, 8B BE).
_INNER_TAIL = np.zeros(63, dtype=np.uint8)
_INNER_TAIL[0] = 0x80
_INNER_TAIL[-8:] = np.frombuffer((65 * 8).to_bytes(8, "big"), dtype=np.uint8)

_EMPTY_HASH = None  # filled lazily (sha256 of b"" on host)


def _inner_blocks(left, right):
    """(m, 32), (m, 32) -> (m, 2, 64) padded inner-node messages."""
    m = left.shape[0]
    prefix = jnp.full((m, 1), 0x01, dtype=jnp.uint8)
    tail = jnp.broadcast_to(jnp.asarray(_INNER_TAIL), (m, 63))
    msg = jnp.concatenate([prefix, left, right, tail], axis=-1)  # (m, 128)
    return msg.reshape(m, 2, 64)


def hash_level(nodes):
    """One tree level: (n, 32) -> (ceil(n/2), 32).

    Adjacent pairs are inner-hashed in one batch; an odd trailing node is
    promoted unchanged (equivalent to the reference's power-of-two split,
    tree.go:101).  n is static under jit, so the promotion is trace-time
    Python, not device control flow.
    """
    n = nodes.shape[0]
    if n == 1:
        return nodes
    pairs = n // 2
    left = nodes[: 2 * pairs : 2]
    right = nodes[1 : 2 * pairs : 2]
    hashed = sha2.sha256_blocks(_inner_blocks(left, right))
    if n % 2:
        hashed = jnp.concatenate([hashed, nodes[-1:]], axis=0)
    return hashed


def root_from_leaf_hashes(leaf_hashes):
    """(n, 32) leaf hashes -> (32,) RFC-6962 root.  n >= 1, static."""
    nodes = leaf_hashes
    while nodes.shape[0] > 1:
        nodes = hash_level(nodes)
    return nodes[0]


def leaf_hashes_from_padded(blocks, active):
    """Device leaf hashing: padded (n, nb, 64) 0x00-prefixed messages -> (n, 32)."""
    return sha2.sha256_blocks(blocks, active)


def pad_leaves(leaves: list[bytes]):
    """Host: raw leaves -> (blocks, active) with the 0x00 leaf prefix applied."""
    return sha2.pad_messages_sha256([_LEAF_PREFIX + l for l in leaves])


def root_from_leaves(blocks, active):
    """Full device pipeline: host-padded leaves -> root.  Jit-friendly.

    Manifest kernel ``merkle_root_from_leaves`` (jitted from
    crypto/merkle.py); per-device subtree body of
    ``sharded_merkle_root`` (census: one all_gather of the D subtree
    roots — analysis/shardcheck)."""
    return root_from_leaf_hashes(leaf_hashes_from_padded(blocks, active))


# ------------------------------------------------------- batched proofs
#
# Proof generation retains every interior level of the reduction and
# gathers each query's audit path with one-hot sibling selection per
# level.  Sibling positions are computed on HOST (crypto/merkle.proof_plan)
# because query indices are known at dispatch time: the device never sees
# an xor or shift, only an (== iota) one-hot and an MXU matmul — static
# depth, no data-dependent control flow, and rangecheck-friendly jaxprs.


def _all_levels(blocks, active):
    """Leaf hashes plus every interior level up to the root.

    levels[0] is (n, 32) leaf hashes; levels[l+1] = hash_level(levels[l])
    with the odd trailing node promoted (so sizes are n, ceil(n/2), ..., 1
    — exactly the shape crypto/merkle.proof_plan assumes)."""
    levels = [leaf_hashes_from_padded(blocks, active)]
    while levels[-1].shape[0] > 1:
        levels.append(hash_level(levels[-1]))
    return levels


def _onehot_gather(nodes, pos):
    """(n, 32) u8 nodes, (k,) i32 positions -> (k, 32) u8 gathered rows.

    A position of -1 (no aunt at this level: the query's ancestor was the
    promoted odd trailing node) matches nothing and yields a zero row,
    which the host side drops by its own plan mask.  The gather is an MXU
    matmul; uint8 is not directly convertible to float32 under the
    conversion allowlist, so the chain is u8 -> i32 -> f32 and back."""
    n = nodes.shape[0]
    iota = jnp.arange(n, dtype=jnp.int32)
    mask = (pos[:, None] == iota[None, :]).astype(jnp.float32)
    vals = nodes.astype(jnp.int32).astype(jnp.float32)
    out = jnp.matmul(mask, vals, precision="highest")
    return out.astype(jnp.int32).astype(jnp.uint8)


def proofs_from_leaves(blocks, active, indices, sib_pos):
    """Batched audit paths for K query indices against one tree.

    blocks/active: host-padded leaves (pad_leaves); indices: (K,) i32
    queried leaf positions; sib_pos: (K, D) i32 per-level sibling
    positions from crypto/merkle.proof_plan (-1 = no aunt at that level).

    Returns (root (32,), leaf_sel (K, 32) queried leaf hashes,
    aunts (K, D, 32) leaf-to-root audit nodes, zero rows where
    sib_pos is -1).  Manifest kernel ``merkle_proofs_from_leaves``."""
    levels = _all_levels(blocks, active)
    root = levels[-1][0]
    leaf_sel = _onehot_gather(levels[0], indices)
    depth = len(levels) - 1
    if depth == 0:
        aunts = jnp.zeros((indices.shape[0], 0, 32), dtype=jnp.uint8)
    else:
        aunts = jnp.stack(
            [_onehot_gather(levels[l], sib_pos[:, l]) for l in range(depth)],
            axis=1,
        )
    return root, leaf_sel, aunts


def multiproof_from_leaves(blocks, active, coords):
    """Multiproof: M deduplicated tree nodes answering many indices at once.

    coords: (M,) i32 flat coordinates into the level-concatenated node
    array (level 0 first; static offsets are level-size prefix sums —
    crypto/merkle.multiproof_plan).  Shared aunts across queries appear
    once in coords, so one gather serves the whole query swarm.

    Returns (root (32,), nodes (M, 32)).  Manifest kernel
    ``merkle_multiproof_from_leaves``."""
    levels = _all_levels(blocks, active)
    flat = jnp.concatenate(levels, axis=0)
    return levels[-1][0], _onehot_gather(flat, coords)
