"""Sharded-program contract checker: multi-device abstract tracing,
collective census, and a compile-cost budget gate for the mesh plane.

``kernelcheck.py`` pins every kernel's numeric contract on a 1-device
trace; this module is its sharded sibling.  Every mesh-parameterized
kernel declared in ``kernel_manifest.SHARDED_KERNELS`` is traced under a
**real 8-way CPU mesh** — a subprocess with
``XLA_FLAGS=--xla_force_host_platform_device_count=8`` and
``JAX_PLATFORMS=cpu`` — so CPU-only CI exercises the genuine sharded
program (shard_map + collectives), not a 1-device stand-in.  On the
traced/lowered program three contracts hold:

1. **sharding closure** — the shard_map's in/out names must match the
   manifest's declared ``in_specs``/``out_specs``, and the collective
   census (psum / all_gather / all_to_all / ppermute / resharding
   ``sharding_constraint`` copies, ...) must match the declared census
   exactly.  An undeclared collective is how silent reshard-per-stage
   lands: a pipelined stage that should hand off device-resident shards
   quietly grows a gather+scatter.
2. **compile-cost budget** — per-kernel ceilings on total jaxpr
   equation count, nested-loop depth, and a per-device peak-bytes
   estimate from the shard_map body's (already per-device) avals.  This
   is the static gate that flags a ``jit_build_a_tables``-class
   unrolled table build in milliseconds instead of a 2m34s XLA compile.
3. **donation discipline** — arguments the manifest declares donated
   must actually be donated in the lowered program (``donated_invars``
   on the pjit), and nothing else may be; the companion AST check
   (``donated_read.py``) keeps host code from reading a donated buffer
   after dispatch.

Alongside the contracts, a drift gate: the traced signature, shardings,
donation vector, and collective census are held to the checked-in
golden ``analysis/shard_fingerprints.json``.  Regenerate after a
DELIBERATE change with::

    python scripts/lint.py regen-shardings

which refuses while any contract finding is open — regeneration blesses
drift, never a broken contract (the PR-4 fingerprint policy).

JAX imports are deferred to call time; the module is importable
anywhere the stdlib runs.  In-process tracing requires the host to
already expose ``SHARD_MESH_DEVICES`` devices (the test suite forces 8
host devices); every other consumer goes through :func:`run_subprocess`.
"""

from __future__ import annotations

import hashlib
import json
import os
import re
import subprocess
import sys
import time
from dataclasses import dataclass, field

from . import kernel_manifest as manifest
from .kernelcheck import UNTRACEABLE_SIG, _aval_str, _pinned_trace_env, _walk_jaxprs
from .linter import Finding

SHARD_FINGERPRINTS_PATH = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "shard_fingerprints.json"
)

#: Every Finding.check id this module emits — scripts/lint.py's
#: stale-entry filter for --check sharding imports this.
FINDING_CHECK_IDS = frozenset(
    {"shard-contract", "shard-fingerprint", "shard-manifest"}
)

# Collective / cross-device primitives counted by the census.  Matched
# on exact names plus family prefixes so versioned spellings
# (all_gather_invariant, ...) still land in the census rather than
# slipping past it.
_COLLECTIVE_PRIMS = frozenset(
    {
        "psum", "pmax", "pmin", "pgather", "pbroadcast", "ppermute",
        "all_gather", "all_to_all", "reduce_scatter", "psum_scatter",
        "collective_permute", "sharding_constraint",
    }
)
_COLLECTIVE_PREFIXES = (
    "all_gather", "all_to_all", "reduce_scatter", "psum", "ppermute",
    "collective_permute",
)

# Control-flow primitives whose body nesting the loop-depth budget
# counts (pjit/shard_map wrappers add structure, not iteration).
_LOOP_PRIMS = frozenset({"scan", "while", "cond"})


def is_collective(prim_name: str) -> bool:
    return prim_name in _COLLECTIVE_PRIMS or prim_name.startswith(
        _COLLECTIVE_PREFIXES
    )


# ------------------------------------------------------------ normalization


def declared_spec_map(spec: tuple) -> dict[str, str]:
    """Manifest spec tuple -> {"dim": "axis"} with unsharded dims
    dropped — the canonical, JSON-able form both sides compare in."""
    out: dict[str, str] = {}
    for dim, name in enumerate(spec):
        if name is None:
            continue
        if isinstance(name, (tuple, list)):
            name = "+".join(name)
        out[str(dim)] = name
    return out


def traced_names_map(names: dict) -> dict[str, str]:
    """A shard_map in_names/out_names entry ({dim: (axis, ...)}) in the
    same canonical form as :func:`declared_spec_map`."""
    return {
        str(dim): "+".join(axes) for dim, axes in sorted(names.items()) if axes
    }


def _fmt_spec(m: dict[str, str]) -> str:
    if not m:
        return "replicated"
    return "{" + ", ".join(f"{d}:{a}" for d, a in sorted(m.items())) + "}"


# ----------------------------------------------------------------- tracing


@dataclass
class ShardTrace:
    """One sharded kernel's 8-way abstract interpretation."""

    sharded: manifest.ShardedKernel
    signature: str
    collectives: dict[str, int]
    in_specs: list[dict[str, str]]  # observed, canonical form
    out_specs: list[dict[str, str]]
    donated: list[int]  # observed donated arg indices
    eqns: int
    loop_depth: int
    device_bytes: int
    findings: list[Finding] = field(default_factory=list)

    def fingerprint(self) -> dict:
        payload = {
            "signature": self.signature,
            "collectives": dict(sorted(self.collectives.items())),
            "in_specs": self.in_specs,
            "out_specs": self.out_specs,
            "donated": list(self.donated),
        }
        digest = hashlib.sha256(
            json.dumps(payload, sort_keys=True).encode()
        ).hexdigest()
        # costs ride along for operators reading the golden but stay out
        # of the digest: they are budget-gated (hard ceilings in the
        # manifest), not drift-gated, so an innocuous +1 eqn never forces
        # a regen ceremony
        return {
            **payload,
            "digest": digest,
            "costs": {
                "eqns": self.eqns,
                "loop_depth": self.loop_depth,
                "device_bytes": self.device_bytes,
            },
        }


def _aval_bytes(aval) -> int:
    n = 1
    for d in getattr(aval, "shape", ()):
        n *= int(d)
    dt = getattr(aval, "dtype", None)
    return n * (dt.itemsize if dt is not None else 1)


def _resolve_sharded(sk: manifest.ShardedKernel, row: manifest.Kernel, mesh):
    import importlib

    mod_name, _, fn_name = row.fn.partition(":")
    fn = getattr(importlib.import_module(mod_name), fn_name)
    return fn(mesh, *row.mesh_static, **dict(row.static_kwargs))


def _loop_depth(jaxpr) -> int:
    """Deepest nesting of scan/while/cond bodies, iteratively (the comb
    jaxpr nests thousands deep in eqns but shallow in control flow)."""
    try:
        from jax.extend.core import ClosedJaxpr, Jaxpr
    except ImportError:  # pragma: no cover - older jax spelling
        from jax.core import ClosedJaxpr, Jaxpr  # type: ignore

    best = 0
    stack = [(jaxpr, 0)]
    seen: set[tuple[int, int]] = set()
    while stack:
        j, depth = stack.pop()
        if isinstance(j, ClosedJaxpr):
            j = j.jaxpr
        if (id(j), depth) in seen:
            continue
        seen.add((id(j), depth))
        best = max(best, depth)
        for eqn in j.eqns:
            inc = 1 if eqn.primitive.name in _LOOP_PRIMS else 0
            for p in eqn.params.values():
                if isinstance(p, (ClosedJaxpr, Jaxpr)):
                    stack.append((p, depth + inc))
                elif isinstance(p, (list, tuple)):
                    stack.extend(
                        (q, depth + inc)
                        for q in p
                        if isinstance(q, (ClosedJaxpr, Jaxpr))
                    )
    return best


def _device_peak_bytes(body_jaxpr) -> int:
    """Per-device peak-bytes estimate from the shard_map body's avals.

    Inside shard_map every aval is already the LOCAL (per-device) shape,
    so no division by mesh size is needed.  The estimate is
    max(resident inputs+consts, largest single equation's in+out) — a
    floor on true peak liveness, cheap and deterministic; the budget is
    a blowup tripwire, not an allocator."""
    resident = 0
    for v in list(body_jaxpr.invars) + list(body_jaxpr.constvars):
        resident += _aval_bytes(v.aval)
    peak_eqn = 0
    for j in _walk_jaxprs(body_jaxpr):
        for eqn in j.eqns:
            b = sum(
                _aval_bytes(v.aval)
                for v in list(eqn.invars) + list(eqn.outvars)
                if hasattr(v, "aval")
            )
            peak_eqn = max(peak_eqn, b)
    return max(resident, peak_eqn)


def trace_sharded(
    sk: manifest.ShardedKernel, row: manifest.Kernel, mesh
) -> ShardTrace:
    """Trace one sharded kernel under ``mesh`` and run the three
    contract passes over its jaxpr."""
    import jax

    path = manifest.module_path(row)
    findings: list[Finding] = []

    def add(msg: str) -> None:
        findings.append(
            Finding("shard-contract", path, 1, 0, f"[{sk.name}] {msg}")
        )

    def structs():
        import numpy as np

        return [
            jax.ShapeDtypeStruct(a.shape, np.dtype(a.dtype)) for a in sk.args
        ]

    try:
        with _pinned_trace_env():
            fn = _resolve_sharded(sk, row, mesh)
            closed = jax.make_jaxpr(fn)(*structs())
    except Exception as e:  # noqa: BLE001 - failing to trace IS the finding
        add(f"failed to trace under the {mesh.devices.size}-way mesh: "
            f"{type(e).__name__}: {e}")
        return ShardTrace(sk, UNTRACEABLE_SIG, {}, [], [], [], 0, 0, 0, findings)

    in_sig = ", ".join(_aval_str(a) for a in closed.in_avals)
    out_sig = ", ".join(_aval_str(a) for a in closed.out_avals)
    signature = f"({in_sig}) -> ({out_sig})"

    got = [(tuple(a.shape), str(a.dtype)) for a in closed.out_avals]
    want = [(a.shape, a.dtype) for a in sk.out]
    if got != want:
        add(f"output spec mismatch: manifest declares {want}, trace "
            f"produced {got}")

    # ---- census + budgets over the whole program
    prims: dict[str, int] = {}
    total_eqns = 0
    shard_maps = []
    pjit_eqn = None
    for eqn in closed.jaxpr.eqns:
        if eqn.primitive.name == "pjit" and pjit_eqn is None:
            pjit_eqn = eqn
    for j in _walk_jaxprs(closed.jaxpr):
        for eqn in j.eqns:
            total_eqns += 1
            name = eqn.primitive.name
            prims[name] = prims.get(name, 0) + 1
            if name == "shard_map":
                shard_maps.append(eqn)

    census = {k: v for k, v in prims.items() if is_collective(k)}
    declared = {k: v for k, v in sk.collectives}
    for prim in sorted(set(census) | set(declared)):
        have, want_n = census.get(prim, 0), declared.get(prim, 0)
        if have > want_n:
            add(
                f"undeclared collective {prim!r}: traced program contains "
                f"{have}, census declares {want_n} ({have - want_n:+d}) — "
                "a silent reshard/new collective; update the manifest "
                "census only if the extra communication is intended"
            )
        elif have < want_n:
            add(
                f"stale collective census: {prim!r} declared {want_n} but "
                f"the traced program contains {have} — shrink the census"
            )

    # ---- donation discipline on the lowered pjit
    donated_idx: list[int] = []
    if pjit_eqn is not None:
        donated = pjit_eqn.params.get("donated_invars", ())
        donated_idx = [i for i, d in enumerate(donated) if d]
    declared_don = set(sk.donate_argnums)
    if pjit_eqn is None and declared_don:
        add(
            "program is not jitted at the top level — declared donations "
            f"{sorted(declared_don)} cannot be honored"
        )
    else:
        for i in sorted(declared_don - set(donated_idx)):
            add(
                f"donation contract: arg {i} is declared donated but the "
                "lowered program does not donate it (missing "
                "donate_argnums on the jit?)"
            )
        for i in sorted(set(donated_idx) - declared_don):
            add(
                f"donation contract: arg {i} is donated by the lowered "
                "program but not declared in the manifest — an undeclared "
                "donation invalidates a buffer host code may still hold"
            )

    # ---- sharding closure on the shard_map
    in_specs_obs: list[dict[str, str]] = []
    out_specs_obs: list[dict[str, str]] = []
    device_bytes = 0
    if not shard_maps:
        add(
            "no shard_map in the traced program — the kernel does not "
            "actually run under the mesh; per-device budgets and the "
            "sharding closure are unverifiable"
        )
        device_bytes = max(
            (_aval_bytes(a) for a in list(closed.in_avals) + list(closed.out_avals)),
            default=0,
        )
    else:
        if len(shard_maps) > 1:
            add(
                f"{len(shard_maps)} shard_map applications in one program "
                "— the contract covers exactly one mesh entry per kernel"
            )
        sm = shard_maps[0]
        # closed-over constants (SHA round tables, the basepoint comb)
        # are hoisted as LEADING shard_map operands; the user arguments
        # are the trailing len(sk.args) entries.  Constants must be
        # replicated — a sharded closure constant would be a hidden
        # resharding input the manifest cannot describe.
        all_in = [traced_names_map(n) for n in sm.params["in_names"]]
        n_args = len(sk.args)
        n_const = max(0, len(all_in) - n_args)
        for i, obs in enumerate(all_in[:n_const]):
            if obs:
                add(
                    f"sharding closure: closed-over constant {i} is "
                    f"{_fmt_spec(obs)} — closure constants must be "
                    "replicated; pass sharded values as arguments"
                )
        in_specs_obs = all_in[n_const:]
        out_specs_obs = [traced_names_map(n) for n in sm.params["out_names"]]
        in_specs_decl = [declared_spec_map(s) for s in sk.in_specs]
        out_specs_decl = [declared_spec_map(s) for s in sk.out_specs]
        if in_specs_obs != in_specs_decl:
            for i, (obs, decl) in enumerate(
                zip(in_specs_obs, in_specs_decl)
            ):
                if obs != decl:
                    add(
                        f"sharding closure: input {i} is {_fmt_spec(obs)} "
                        f"but the manifest declares {_fmt_spec(decl)} — a "
                        "respec here means a silent reshard at every call"
                    )
            if len(in_specs_obs) != len(in_specs_decl):
                add(
                    f"sharding closure: program takes {len(in_specs_obs)} "
                    f"inputs, manifest declares {len(in_specs_decl)}"
                )
        if out_specs_obs != out_specs_decl:
            for i, (obs, decl) in enumerate(
                zip(out_specs_obs, out_specs_decl)
            ):
                if obs != decl:
                    add(
                        f"sharding closure: output {i} is {_fmt_spec(obs)} "
                        f"but the manifest declares {_fmt_spec(decl)}"
                    )
            if len(out_specs_obs) != len(out_specs_decl):
                add(
                    f"sharding closure: program returns {len(out_specs_obs)} "
                    f"outputs, manifest declares {len(out_specs_decl)}"
                )
        device_bytes = _device_peak_bytes(sm.params["jaxpr"])

    # ---- compile-cost budget
    depth = _loop_depth(closed.jaxpr)
    if total_eqns > sk.max_eqns:
        add(
            f"compile-cost budget: {total_eqns} jaxpr equations exceeds "
            f"the budget of {sk.max_eqns} ({total_eqns - sk.max_eqns:+d}) "
            "— an unrolled loop or table build lands here in milliseconds "
            "instead of as a minutes-long XLA compile; restructure the "
            "kernel (roll the loop / precompute host-side) or raise the "
            "budget with justification"
        )
    if depth > sk.max_loop_depth:
        add(
            f"compile-cost budget: control-flow nesting depth {depth} "
            f"exceeds the budget of {sk.max_loop_depth} "
            f"({depth - sk.max_loop_depth:+d})"
        )
    if device_bytes > sk.max_device_bytes:
        add(
            f"compile-cost budget: per-device peak-bytes estimate "
            f"{device_bytes} exceeds the budget of {sk.max_device_bytes} "
            f"({device_bytes - sk.max_device_bytes:+d})"
        )

    return ShardTrace(
        sk, signature, census, in_specs_obs, out_specs_obs, donated_idx,
        total_eqns, depth, device_bytes, findings,
    )


# -------------------------------------------------------------- drift gate


def load_fingerprints(path: str = SHARD_FINGERPRINTS_PATH) -> dict:
    try:
        with open(path, encoding="utf-8") as f:
            return json.load(f)
    except FileNotFoundError:
        return {}


def write_fingerprints(
    traces: list[ShardTrace], path: str = SHARD_FINGERPRINTS_PATH
) -> None:
    data = {t.sharded.name: t.fingerprint() for t in traces}
    with open(path, "w", encoding="utf-8") as f:
        json.dump(data, f, indent=2, sort_keys=True)
        f.write("\n")


def _diff_report(name: str, golden: dict, fresh: dict) -> str:
    lines = [f"sharded kernel {name!r} drifted from its checked-in golden:"]
    for key in ("signature", "in_specs", "out_specs", "donated"):
        if golden.get(key) != fresh.get(key):
            lines.append(f"  {key} before: {golden.get(key)}")
            lines.append(f"  {key} after : {fresh.get(key)}")
    gc = golden.get("collectives", {})
    fc = fresh.get("collectives", {})
    for prim in sorted(set(gc) | set(fc)):
        b, a = gc.get(prim, 0), fc.get(prim, 0)
        if b != a:
            lines.append(f"  collective {prim}: {b} -> {a} ({a - b:+d})")
    lines.append(
        "  deliberate change? regenerate with "
        "`python scripts/lint.py regen-shardings`"
    )
    return "\n".join(lines)


def compare_fingerprints(
    traces: list[ShardTrace], golden: dict
) -> list[Finding]:
    findings: list[Finding] = []
    fresh_names = set()
    for t in traces:
        fresh_names.add(t.sharded.name)
        if t.signature == UNTRACEABLE_SIG:
            continue  # 'failed to trace' is already the finding
        row = manifest.by_name().get(t.sharded.name)
        path = manifest.module_path(row) if row else "cometbft_tpu/parallel/verify.py"
        fresh = t.fingerprint()
        have = golden.get(t.sharded.name)
        if have is None:
            findings.append(Finding(
                "shard-fingerprint", path, 1, 0,
                f"sharded kernel {t.sharded.name!r} has no checked-in "
                "golden — run `python scripts/lint.py regen-shardings`",
            ))
        elif have.get("digest") != fresh["digest"]:
            findings.append(Finding(
                "shard-fingerprint", path, 1, 0,
                _diff_report(t.sharded.name, have, fresh),
            ))
    known = fresh_names | set(manifest.sharded_by_name())
    for name in sorted(set(golden) - known):
        findings.append(Finding(
            "shard-fingerprint",
            "cometbft_tpu/analysis/shard_fingerprints.json", 1, 0,
            f"golden {name!r} names no sharded manifest kernel — stale "
            "entry; regenerate the goldens",
        ))
    return findings


# ------------------------------------------------------- manifest findings


def _manifest_findings() -> list[Finding]:
    """Internal consistency of the sharding extension itself."""
    findings: list[Finding] = []
    mpath = "cometbft_tpu/analysis/kernel_manifest.py"

    def add(msg: str) -> None:
        findings.append(Finding("shard-manifest", mpath, 1, 0, msg))

    rows = manifest.by_name()
    seen: set[str] = set()
    for sk in manifest.SHARDED_KERNELS:
        if sk.name in seen:
            add(f"duplicate ShardedKernel {sk.name!r}")
        seen.add(sk.name)
        row = rows.get(sk.name)
        if row is None:
            add(f"ShardedKernel {sk.name!r} names no manifest Kernel row")
            continue
        if not row.needs_mesh:
            add(f"ShardedKernel {sk.name!r}: Kernel row is not needs_mesh")
        if len(sk.in_specs) != len(sk.args):
            add(
                f"ShardedKernel {sk.name!r}: {len(sk.in_specs)} in_specs "
                f"for {len(sk.args)} args"
            )
        if len(sk.out_specs) != len(sk.out):
            add(
                f"ShardedKernel {sk.name!r}: {len(sk.out_specs)} out_specs "
                f"for {len(sk.out)} outputs"
            )
        for spec, arg in zip(sk.in_specs, sk.args):
            if len(spec) > len(arg.shape):
                add(
                    f"ShardedKernel {sk.name!r}: in_spec {spec} longer "
                    f"than the arg rank {len(arg.shape)}"
                )
        for i in sk.donate_argnums:
            if not (0 <= i < len(sk.args)):
                add(f"ShardedKernel {sk.name!r}: donate_argnums {i} out of range")
        for pname, pos in sk.entry_donated_params:
            if not pname or pos < 0:
                add(
                    f"ShardedKernel {sk.name!r}: bad entry_donated_params "
                    f"({pname!r}, {pos})"
                )
        if sk.entry_donated_params and not sk.donate_argnums:
            add(
                f"ShardedKernel {sk.name!r}: entry_donated_params declared "
                "but no donate_argnums"
            )
        if min(sk.max_eqns, sk.max_loop_depth, sk.max_device_bytes) <= 0:
            add(f"ShardedKernel {sk.name!r}: budgets must be positive")
    return findings


# ----------------------------------------------------------------- driver


def _build_mesh():
    """The real 8-way mesh, or a shard-manifest finding when the host
    cannot provide it (callers then go through run_subprocess)."""
    import jax

    from ..parallel.mesh import make_mesh

    have = len(jax.devices())
    if have < manifest.SHARD_MESH_DEVICES:
        return None, [Finding(
            "shard-manifest", "cometbft_tpu/analysis/shardcheck.py", 1, 0,
            f"host exposes {have} device(s); the sharded gate needs "
            f"{manifest.SHARD_MESH_DEVICES} — run via "
            "shardcheck.run_subprocess (forced host devices)",
        )]
    return make_mesh(manifest.SHARD_MESH_DEVICES, axis=manifest.SHARD_AXIS), []


def run_check(
    fingerprints_path: str = SHARD_FINGERPRINTS_PATH,
    sharded: tuple[manifest.ShardedKernel, ...] | None = None,
    kernel_rows: dict[str, manifest.Kernel] | None = None,
    allowlist=None,
    skip_goldens: bool = False,
) -> tuple[list[Finding], list[ShardTrace]]:
    """The full sharded static pass.  Returns (findings, traces); empty
    findings is the green gate.  ``sharded``/``kernel_rows`` swap in a
    fixture manifest (tests); manifest-consistency findings only run
    against the real manifest.  ``skip_goldens`` limits the run to the
    contract passes (fixture runs have no checked-in golden)."""
    fixture_run = sharded is not None
    sharded = sharded if sharded is not None else manifest.SHARDED_KERNELS
    rows = kernel_rows if kernel_rows is not None else manifest.by_name()
    findings = [] if fixture_run else _manifest_findings()
    mesh, mesh_findings = _build_mesh()
    if mesh is None:
        return findings + mesh_findings, []
    traces: list[ShardTrace] = []
    for sk in sharded:
        row = rows.get(sk.name)
        if row is None:
            findings.append(Finding(
                "shard-manifest", "cometbft_tpu/analysis/kernel_manifest.py",
                1, 0, f"ShardedKernel {sk.name!r} has no Kernel row to trace",
            ))
            continue
        traces.append(trace_sharded(sk, row, mesh))
    for t in traces:
        findings.extend(t.findings)
    if not skip_goldens:
        findings.extend(
            compare_fingerprints(traces, load_fingerprints(fingerprints_path))
        )
    if allowlist is not None:
        findings = [f for f in findings if not allowlist.suppresses(f)]
    return findings, traces


def regenerate(
    fingerprints_path: str = SHARD_FINGERPRINTS_PATH,
    sharded: tuple[manifest.ShardedKernel, ...] | None = None,
    kernel_rows: dict[str, manifest.Kernel] | None = None,
) -> tuple[list[Finding], list[ShardTrace]]:
    """Re-trace and rewrite the golden file.  Contract findings
    (closure/census/budget/donation) still fail — regeneration only
    blesses DRIFT, never a broken contract.  Justified allowlist entries
    don't block, so a blessed state stays regenerable."""
    from .kernelcheck import default_allowlist

    findings, traces = run_check(
        fingerprints_path, sharded=sharded, kernel_rows=kernel_rows,
        skip_goldens=True,
    )
    allow = default_allowlist()
    findings = [f for f in findings if not allow.suppresses(f)]
    if not findings:
        write_fingerprints(traces, fingerprints_path)
    return findings, traces


def summary(findings: list[Finding], traces: list[ShardTrace]) -> dict:
    """Machine-readable result (bench.py embeds this on backend-less
    rounds, the same pattern as the PR-4 "kernelcheck" field)."""
    return {
        "ok": not findings,
        "kernels": {
            t.sharded.name: {
                "eqns": t.eqns,
                "loop_depth": t.loop_depth,
                "device_bytes": t.device_bytes,
                "collectives": dict(sorted(t.collectives.items())),
            }
            for t in traces
        },
        "findings": [
            {"check": f.check, "path": f.path, "line": f.line,
             "col": f.col, "message": f.message}
            for f in findings
        ],
    }


# ------------------------------------------------------------- subprocess
#
# The production entry: CPU-only CI (and any host whose jax is already
# initialized with the wrong device count) runs the gate in a child
# interpreter with the 8-device CPU environment forced BEFORE jax's
# first import, so the traced program is the genuine sharded one and a
# wedged accelerator tunnel is never touched.

_DEV_FLAG_RE = re.compile(r"--xla_force_host_platform_device_count=\d+")


def _forced_env(base: dict) -> dict:
    env = dict(base)
    env.pop("PALLAS_AXON_POOL_IPS", None)  # never touch the device tunnel
    env["JAX_PLATFORMS"] = "cpu"
    flags = _DEV_FLAG_RE.sub("", env.get("XLA_FLAGS", ""))
    env["XLA_FLAGS"] = (
        flags
        + f" --xla_force_host_platform_device_count={manifest.SHARD_MESH_DEVICES}"
    ).strip()
    return env


def _repo_root() -> str:
    return os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )


def run_subprocess(
    *,
    regen: bool = False,
    fixtures: str | None = None,
    only: tuple[str, ...] = (),
    fingerprints_path: str | None = None,
    skip_goldens: bool = False,
    timeout: float = 1800.0,
) -> tuple[list[Finding], dict]:
    """Run the gate in a forced-environment child; returns
    (findings, summary).  A child that dies or emits unparseable output
    is itself a finding — the gate must never silently read green."""
    repo = _repo_root()
    env = _forced_env(os.environ)
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    argv = [sys.executable, "-m", "cometbft_tpu.analysis.shardcheck", "--json"]
    if regen:
        argv.append("--regen")
    if fixtures:
        argv += ["--fixtures", fixtures]
    for name in only:
        argv += ["--only", name]
    if fingerprints_path:
        argv += ["--fingerprints", fingerprints_path]
    if skip_goldens:
        argv.append("--no-goldens")
    try:
        proc = subprocess.run(
            argv, capture_output=True, text=True, env=env, cwd=repo,
            timeout=timeout,
        )
    except subprocess.TimeoutExpired:
        f = Finding(
            "shard-contract", "cometbft_tpu/analysis/shardcheck.py", 1, 0,
            f"sharded trace child timed out after {timeout:.0f}s — a "
            "compile-cost blowup or a hung backend; the gate is RED",
        )
        return [f], {"ok": False, "error": "timeout", "findings": []}
    for line in reversed(proc.stdout.strip().splitlines() or [""]):
        line = line.strip()
        if line.startswith("{"):
            try:
                data = json.loads(line)
            except json.JSONDecodeError:
                continue
            findings = [
                Finding(d["check"], d["path"], d["line"], d["col"], d["message"])
                for d in data.get("findings", ())
            ]
            return findings, data
    f = Finding(
        "shard-contract", "cometbft_tpu/analysis/shardcheck.py", 1, 0,
        f"sharded trace child failed (rc={proc.returncode}) with no "
        f"parseable report; stderr tail: {proc.stderr[-400:]!r}",
    )
    return [f], {"ok": False, "error": f"child rc={proc.returncode}",
                 "findings": []}


def _child_main(argv: list[str] | None = None) -> int:
    """The forced-environment child body (``python -m
    cometbft_tpu.analysis.shardcheck``).  Pins the CPU platform and the
    8-device flag BEFORE jax's first import so direct invocations work
    without the wrapper too."""
    import argparse

    os.environ.pop("PALLAS_AXON_POOL_IPS", None)  # never touch the tunnel
    for k, v in _forced_env(
        {"XLA_FLAGS": os.environ.get("XLA_FLAGS", "")}
    ).items():
        os.environ[k] = v
    if "jax" in sys.modules:  # pragma: no cover - defensive
        import jax

        if len(jax.devices()) < manifest.SHARD_MESH_DEVICES:
            print(json.dumps({
                "ok": False,
                "error": "jax already initialized with too few devices",
                "findings": [],
            }))
            return 2

    ap = argparse.ArgumentParser(description="sharded-program contract gate")
    ap.add_argument("--json", action="store_true")
    ap.add_argument("--regen", action="store_true")
    ap.add_argument("--fixtures", default=None,
                    help="module exporting SHARDED_KERNELS + KERNEL_ROWS")
    ap.add_argument("--only", action="append", default=[])
    ap.add_argument("--fingerprints", default=None)
    ap.add_argument("--no-goldens", action="store_true")
    args = ap.parse_args(argv)

    sharded = None
    rows = None
    if args.fixtures:
        import importlib

        mod = importlib.import_module(args.fixtures)
        sharded = tuple(mod.SHARDED_KERNELS)
        rows = dict(mod.KERNEL_ROWS)
    if args.only:
        pool = sharded if sharded is not None else manifest.SHARDED_KERNELS
        sharded = tuple(s for s in pool if s.name in set(args.only))
        if not sharded:
            # a typo'd --only tracing zero kernels must not read as a
            # clean pass (the PR-3 nonexistent-lint-path rule)
            print(json.dumps({
                "ok": False,
                "error": f"--only {args.only} matched no sharded kernel",
                "findings": [{
                    "check": "shard-manifest",
                    "path": "cometbft_tpu/analysis/kernel_manifest.py",
                    "line": 1, "col": 0,
                    "message": f"--only {args.only} matched no sharded "
                    "kernel — nothing was checked",
                }],
            }))
            return 2
    fp = args.fingerprints or SHARD_FINGERPRINTS_PATH

    t0 = time.monotonic()
    if args.regen:
        findings, traces = regenerate(fp, sharded=sharded, kernel_rows=rows)
        written = not findings
    else:
        # check runs report RAW findings: the CALLER owns allowlist
        # policy (scripts/lint.py applies its --allowlist/--config
        # choice and tracks stale entries; bench applies the default) —
        # filtering here too would hide a live finding from the
        # parent's used-entry bookkeeping.  Only regen (above) consults
        # the checked-in allowlist itself, for its refusal semantics.
        findings, traces = run_check(
            fp, sharded=sharded, kernel_rows=rows,
            skip_goldens=args.no_goldens,
        )
        written = False

    import jax

    result = {
        **summary(findings, traces),
        "device_count": len(jax.devices()),
        "elapsed_s": round(time.monotonic() - t0, 1),
        "regen_written": written,
    }
    if args.json:
        print(json.dumps(result))
    else:
        for f in findings:
            print(f.render())
        print(
            f"traced {len(traces)} sharded kernel(s) on "
            f"{result['device_count']} devices in {result['elapsed_s']}s"
            + (" (goldens written)" if written else "")
        )
    return 0 if result["ok"] else 1


if __name__ == "__main__":
    sys.exit(_child_main())
