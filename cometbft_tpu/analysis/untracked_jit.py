"""Check: untracked-jit.

Every ``jax.jit`` site in the kernel plane (``ops/``, ``parallel/``,
``models/``, ``crypto/``) must be registered in
``kernel_manifest.JIT_SITES``.  Registration is what gives a jit entry
point a traced contract: a manifest row pins its canonical shapes and
dtypes, and the kernelcheck drift gate pins its jaxpr fingerprint —
an unregistered site is a compiled program with no static verification
at all, exactly the gap this pass exists to close.

A site is keyed ``path::target``: the jitted function's own name when it
is jitted by name (``jax.jit(build_a_tables)``, decorator forms), or the
enclosing factory when the jitted expression is composed
(``jax.jit(shard_map(local))`` — the factory is the stable name).  Fix a
finding by adding the site to ``JIT_SITES`` and, for a new entry point,
a ``Kernel`` row + regenerated fingerprint; there is no allowlist escape
that skips the manifest, by design.
"""

from __future__ import annotations

from . import kernel_manifest as manifest
from ._jitscan import iter_jit_sites
from .linter import Finding, Module

CHECK_ID = "untracked-jit"
SUMMARY = "jax.jit site in the kernel plane not registered in the kernel manifest"

# The driver refuses allowlist suppression for this check: an entry in
# allowlist.txt would let a compiled program ship with no traced
# contract — registration in the manifest is the only way out.
ALLOWLIST_EXEMPT = True

SCOPE_DIRS = {"ops", "parallel", "models", "crypto"}


def check(mod: Module) -> list[Finding]:
    if not SCOPE_DIRS.intersection(mod.parts[:-1]):
        return []
    findings: list[Finding] = []
    for site in iter_jit_sites(mod.tree):
        target = site.target or "<module>"
        if manifest.site_registered(mod.path, target):
            continue
        findings.append(
            Finding(
                CHECK_ID, mod.path, site.lineno, site.col,
                f"jit site {mod.path}::{target} ({site.via}) is not in "
                "kernel_manifest.JIT_SITES — register it and declare a "
                "manifest Kernel so the contract checker traces it",
            )
        )
    return findings
