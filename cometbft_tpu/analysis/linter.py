"""Static-analysis driver: file discovery, check dispatch, allowlist.

The Python analogue of the ``go vet`` wiring the reference codebase gets
for free: each check module (one per check, same directory) exports
``CHECK_ID``, ``SUMMARY``, and ``check(module) -> list[Finding]``; this
driver parses each source file once, fans it out to the enabled checks,
and filters findings through the explicit checked-in allowlist
(``analysis/allowlist.txt``) so suppressions are loud, reviewed debt —
never an inline comment that silently rots.

Machine entry points: :func:`lint_paths` (used by ``scripts/lint.py``
and the gate test in ``tests/test_static_analysis.py``).
"""

from __future__ import annotations

import ast
import os
from dataclasses import dataclass


@dataclass(frozen=True)
class Finding:
    check: str
    path: str  # posix-style, as discovered (relative when input was)
    line: int
    col: int
    message: str

    def render(self) -> str:
        return f"{self.path}:{self.line}:{self.col}: [{self.check}] {self.message}"


class Module:
    """One parsed source file, shared across checks."""

    def __init__(self, path: str, source: str):
        self.path = path.replace(os.sep, "/")
        self.source = source
        self.tree = ast.parse(source, filename=path)

    @property
    def parts(self) -> tuple[str, ...]:
        return tuple(self.path.split("/"))


# ------------------------------------------------------------- allowlist

@dataclass
class AllowEntry:
    check: str
    path: str
    line: int | None  # None = whole file for this check
    lineno: int  # where in allowlist.txt, for stale-entry reports
    used: bool = False

    def matches(self, f: Finding) -> bool:
        if self.check != f.check:
            return False
        if self.line is not None and self.line != f.line:
            return False
        # suffix match on a '/' boundary: entries are repo-relative but
        # the linter may be invoked with absolute or differently-rooted
        # paths
        return f.path == self.path or f.path.endswith("/" + self.path)


class Allowlist:
    """``check-id path[:line]  # justification`` per line.  Blank lines
    and ``#`` comments ignored.  A justification comment is REQUIRED by
    policy (docs/static_analysis.md); the gate test enforces it."""

    def __init__(self, entries: list[AllowEntry], raw_lines: list[str]):
        self.entries = entries
        self.raw_lines = raw_lines

    @classmethod
    def load(cls, path: str) -> "Allowlist":
        try:
            with open(path, encoding="utf-8") as f:
                text = f.read()
        except FileNotFoundError:
            return cls([], [])
        return cls.parse(text)

    @classmethod
    def parse(cls, text: str) -> "Allowlist":
        entries: list[AllowEntry] = []
        lines = text.splitlines()
        for lineno, raw in enumerate(lines, 1):
            body = raw.split("#", 1)[0].strip()
            if not body:
                continue
            fields = body.split()
            if len(fields) != 2:
                raise ValueError(
                    f"allowlist line {lineno}: expected "
                    f"'check-id path[:line]', got {raw!r}"
                )
            check, target = fields
            line: int | None = None
            if ":" in target:
                target, _, linestr = target.rpartition(":")
                try:
                    line = int(linestr)
                except ValueError:
                    raise ValueError(
                        f"allowlist line {lineno}: bad line number in {raw!r}"
                    ) from None
            entries.append(
                AllowEntry(check, target.replace(os.sep, "/"), line, lineno)
            )
        return cls(entries, lines)

    def suppresses(self, f: Finding) -> bool:
        hit = False
        for e in self.entries:
            if e.matches(f):
                e.used = True
                hit = True
        return hit

    def unused(self) -> list[AllowEntry]:
        """Stale suppressions: entries that matched nothing this run.
        Reported (not fatal) so the allowlist shrinks as debt is paid."""
        return [e for e in self.entries if not e.used]


# --------------------------------------------------------------- checks

def all_checks() -> dict[str, object]:
    """check-id -> check module, discovery order stable."""
    from . import (
        donated_read,
        host_sync,
        jax_purity,
        lock_blocking,
        metrics_registry,
        raw_env,
        socket_timeout,
        swallowed_exc,
        thread_names,
        unchecked_shift_width,
        undocumented_metric,
        untracked_jit,
        weak_type_literal,
        wire_length,
    )

    mods = (
        lock_blocking,
        swallowed_exc,
        raw_env,
        jax_purity,
        metrics_registry,
        undocumented_metric,
        thread_names,
        untracked_jit,
        host_sync,
        weak_type_literal,
        unchecked_shift_width,
        donated_read,
        socket_timeout,
        wire_length,
    )
    return {m.CHECK_ID: m for m in mods}


#: The kernel-plane subset: the three checks that feed the kernel
#: contract gate (scripts/lint.py --check kernel) alongside the
#: kernelcheck trace pass.
KERNEL_CHECK_IDS = ("untracked-jit", "host-sync-in-hot-path", "weak-type-literal")

#: The sharded-plane subset: the AST half of the sharding contract gate
#: (scripts/lint.py --check sharding) alongside the shardcheck
#: multi-device trace pass.
SHARDING_CHECK_IDS = ("donated-read-after-dispatch",)

#: The range-plane subset: the AST half of the limb-range contract gate
#: (scripts/lint.py --check range) alongside the rangecheck interval
#: interpreter pass.
RANGE_CHECK_IDS = ("unchecked-shift-width",)

#: The Byzantine-input subset: the AST half of the taint contract gate
#: (scripts/lint.py --check taint) alongside the taintcheck dataflow
#: pass over the taint_manifest source/sanitizer/sink registry.
TAINT_CHECK_IDS = ("unbounded-wire-length",)


def iter_py_files(paths: list[str]) -> list[str]:
    """Expand dirs to their .py files.  A path that is neither a
    directory nor an existing .py file raises: a typo'd CI invocation
    linting zero files must not read as a clean pass."""
    out: list[str] = []
    for p in paths:
        if os.path.isdir(p):
            for root, dirs, files in os.walk(p):
                dirs[:] = sorted(
                    d for d in dirs
                    if not d.startswith(".") and d != "__pycache__"
                )
                for f in sorted(files):
                    if f.endswith(".py"):
                        out.append(os.path.join(root, f))
        elif p.endswith(".py") and os.path.isfile(p):
            out.append(p)
        else:
            raise FileNotFoundError(
                f"lint path {p!r} is neither a directory nor a .py file"
            )
    return out


def lint_paths(
    paths: list[str],
    checks: dict[str, object] | None = None,
    allowlist: Allowlist | None = None,
    disable: set[str] | frozenset[str] = frozenset(),
) -> tuple[list[Finding], list[AllowEntry]]:
    """Run every enabled check over every file; returns
    ``(non-allowlisted findings, stale allowlist entries)``."""
    checks = checks if checks is not None else all_checks()
    allowlist = allowlist if allowlist is not None else Allowlist([], [])
    enabled = [m for cid, m in checks.items() if cid not in disable]
    findings: list[Finding] = []
    for path in iter_py_files(paths):
        try:
            with open(path, encoding="utf-8") as f:
                source = f.read()
            mod = Module(path, source)
        except (SyntaxError, UnicodeDecodeError, OSError) as e:
            findings.append(
                Finding("parse-error", path.replace(os.sep, "/"), 1, 0, str(e))
            )
            continue
        for m in enabled:
            findings.extend(m.check(mod))
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.check))
    # a check module may declare ALLOWLIST_EXEMPT = True: its findings
    # are never suppressible (untracked-jit — the manifest is the only
    # way out, by design); entries targeting such a check read as stale
    exempt = {m.CHECK_ID for m in enabled if getattr(m, "ALLOWLIST_EXEMPT", False)}
    kept = [
        f for f in findings
        if f.check in exempt or not allowlist.suppresses(f)
    ]
    return kept, allowlist.unused()


def default_allowlist_path() -> str:
    return os.path.join(os.path.dirname(__file__), "allowlist.txt")


# ----------------------------------------------------- shared AST helpers

def terminal_name(node: ast.expr) -> str | None:
    """The rightmost identifier of a Name/Attribute chain, else None."""
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return None


def dotted_name(node: ast.expr) -> str | None:
    """``a.b.c`` for a pure Name/Attribute chain, else None."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def keyword_names(call: ast.Call) -> set[str]:
    return {k.arg for k in call.keywords if k.arg is not None}
