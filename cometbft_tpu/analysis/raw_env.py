"""Check: raw-env-read.

Any ``os.environ``/``os.getenv`` READ of a ``COMETBFT_TPU_*`` name
outside ``utils/envknobs.py``.  Every knob must be declared once in the
registry (type, default, one-line doc) and read through its typed
getters — that is what keeps ``docs/knobs.md`` the complete inventory
and gives every reader the same malformed-value fallback.  Writes
(``os.environ[k] = v``, ``pop``) are not flagged: the e2e runner
legitimately scrubs and injects knobs into child-process environments.
"""

from __future__ import annotations

import ast

from .linter import Finding, Module, dotted_name

CHECK_ID = "raw-env-read"
SUMMARY = "COMETBFT_TPU_* env read outside utils/envknobs.py"

PREFIX = "COMETBFT_TPU_"
_EXEMPT_SUFFIX = "utils/envknobs.py"


def _knob_literal(node: ast.expr) -> str | None:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        if node.value.startswith(PREFIX):
            return node.value
    return None


def _is_environ(node: ast.expr) -> bool:
    d = dotted_name(node)
    return d is not None and (d == "environ" or d.endswith(".environ"))


def check(mod: Module) -> list[Finding]:
    if mod.path.endswith(_EXEMPT_SUFFIX):
        return []
    findings: list[Finding] = []

    def add(node: ast.AST, name: str, how: str) -> None:
        findings.append(
            Finding(
                CHECK_ID, mod.path, node.lineno, node.col_offset,
                f"raw {how} of {name!r} — declare it in "
                "utils/envknobs.py and read via the typed getters",
            )
        )

    for node in ast.walk(mod.tree):
        if isinstance(node, ast.Call):
            d = dotted_name(node.func)
            if d is not None and node.args:
                name = _knob_literal(node.args[0])
                if name is None:
                    continue
                if d == "getenv" or d.endswith(".getenv"):
                    add(node, name, "os.getenv")
                elif (d == "environ.get" or d.endswith(".environ.get")):
                    add(node, name, "os.environ.get")
        elif isinstance(node, ast.Subscript):
            if (
                isinstance(node.ctx, ast.Load)
                and _is_environ(node.value)
            ):
                name = _knob_literal(node.slice)
                if name is not None:
                    add(node, name, "os.environ[...] read")
        elif isinstance(node, ast.Compare):
            if (
                len(node.ops) == 1
                and isinstance(node.ops[0], (ast.In, ast.NotIn))
                and _is_environ(node.comparators[0])
            ):
                name = _knob_literal(node.left)
                if name is not None:
                    add(node, name, "`in os.environ` membership test")
    return findings
