"""Check: host-sync-in-hot-path.

A host<->device synchronization inside ``ops/`` or ``parallel/`` —
``.block_until_ready()``, ``jax.device_get``, ``.item()``, or
``np.asarray``/``np.array`` materializing a device value — stalls the
dispatch pipeline: over the remote device tunnel one stray fetch costs
~85 ms, and even locally it serializes work the async dispatch model
exists to overlap.  The verify plane's contract is that device results
are fetched at ONE declared place per pipeline (the collect boundary);
everywhere else in the hot path a sync is a bug.

Declared boundaries live in ``kernel_manifest.COLLECT_BOUNDARIES``
(``path::function`` with a justification); anything inside such a
function is exempt.  ``np.asarray``/``np.array`` over a literal
(list/tuple/comprehension/constant) is host constant construction — the
SHA round-constant tables, limb weights — and never flagged; neither is
``np.array`` over a host device list (an expression containing a
``devices()`` call, or a local name assigned from one — the
``parallel/mesh.py`` factories), which wraps host objects, not device
arrays.  The jitted counterpart ``jnp.asarray`` is an async H2D
transfer, not a sync, and is not this check's business.
"""

from __future__ import annotations

import ast

from . import kernel_manifest as manifest
from .linter import Finding, Module, dotted_name, terminal_name

CHECK_ID = "host-sync-in-hot-path"
SUMMARY = "device sync/fetch in ops//parallel/ outside a declared collect boundary"

SCOPE_DIRS = {"ops", "parallel"}

_NP_MODULES = {"np", "numpy"}
_NP_MATERIALIZERS = {"asarray", "array"}
_LITERAL_NODES = (
    ast.Constant, ast.List, ast.Tuple, ast.Set, ast.Dict,
    ast.ListComp, ast.SetComp, ast.DictComp, ast.GeneratorExp,
)


class _Visitor(ast.NodeVisitor):
    def __init__(self, mod: Module):
        self.mod = mod
        self.findings: list[Finding] = []
        self._fn_stack: list[str] = []
        # per-scope names assigned from a host device list (module scope
        # at index 0, one set per enclosing function above it)
        self._device_names: list[set[str]] = [set()]

    def _is_device_list(self, node: ast.expr) -> bool:
        """True when the expression builds or references a host device
        list: a ``devices()`` call anywhere in the subtree, or a name a
        visible scope assigned from one."""
        for n in ast.walk(node):
            if isinstance(n, ast.Call) and terminal_name(n.func) == "devices":
                return True
            if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Load) and any(
                n.id in scope for scope in self._device_names
            ):
                return True
        return False

    def visit_Assign(self, node: ast.Assign):  # noqa: N802
        names = [
            n.id
            for t in node.targets
            for n in ast.walk(t)
            if isinstance(n, ast.Name)
        ]
        if self._is_device_list(node.value):
            self._device_names[-1].update(names)
        else:
            # reassignment to anything else ends the exemption
            self._device_names[-1].difference_update(names)
        self.generic_visit(node)

    def _exempt(self) -> bool:
        return any(
            manifest.collect_boundary(self.mod.path, name)
            for name in self._fn_stack
        )

    def _add(self, node: ast.AST, what: str) -> None:
        where = self._fn_stack[-1] if self._fn_stack else "<module>"
        self.findings.append(
            Finding(
                CHECK_ID, self.mod.path, node.lineno, node.col_offset,
                f"{what} in {where!r} — hot-path host sync; move the fetch "
                "to a declared collect boundary (or register this function "
                "in kernel_manifest.COLLECT_BOUNDARIES with a justification)",
            )
        )

    def _visit_fn(self, node):
        self._fn_stack.append(node.name)
        self._device_names.append(set())
        self.generic_visit(node)
        self._device_names.pop()
        self._fn_stack.pop()

    visit_FunctionDef = _visit_fn  # noqa: N815
    visit_AsyncFunctionDef = _visit_fn  # noqa: N815

    def visit_Call(self, node: ast.Call):  # noqa: N802
        if not self._exempt():
            tn = terminal_name(node.func)
            d = dotted_name(node.func) or ""
            if tn == "block_until_ready":
                self._add(node, ".block_until_ready()")
            elif tn == "device_get" and (
                d in ("jax.device_get", "device_get") or d.endswith(".device_get")
            ):
                self._add(node, "jax.device_get()")
            elif tn == "item" and not node.args:
                self._add(node, ".item()")
            elif (
                tn in _NP_MATERIALIZERS
                and isinstance(node.func, ast.Attribute)
                and dotted_name(node.func.value) in _NP_MODULES
                and node.args
                and not isinstance(node.args[0], _LITERAL_NODES)
                and not self._is_device_list(node.args[0])
            ):
                self._add(node, f"np.{tn}() on a non-literal value")
        self.generic_visit(node)


def check(mod: Module) -> list[Finding]:
    if not SCOPE_DIRS.intersection(mod.parts[:-1]):
        return []
    v = _Visitor(mod)
    v.visit(mod.tree)
    return v.findings
