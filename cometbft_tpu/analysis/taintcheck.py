"""Byzantine-input taint checker: prove every untrusted-bytes value is
validated before it reaches a consensus/state/store/dispatch sink.

The dataflow half of the ``taint`` gate (``scripts/lint.py --check
taint``), driven entirely by :mod:`taint_manifest`:

1. **Decode-surface exhaustiveness** — rediscover every proto/envelope
   decode call site in the package syntactically and diff it against
   ``DECODE_SITES`` in both directions: an unregistered decode surface
   is a ``taint-unregistered-decode`` finding (new wire entry points
   must declare their source + typed-error contract), and a manifest
   row matching nothing is ``taint-manifest-stale`` (the registry never
   outlives the code, the kernel_manifest JIT_SITES discipline).

2. **Validate-before-use dataflow** — for every manifest source with
   ``dataflow=True``, an abstract interpretation of the entry function
   over a taint lattice: the declared ``tainted_params`` (and results of
   ``tainted_calls``) seed the tainted set; taint propagates through
   assignment, attribute/subscript access, arithmetic, collection
   construction, f-strings, and calls; a declared SANITIZER call
   (``validate_*_message(msg)``, ``x.validate_basic()``, ``parsed =
   parse_signed_tx(tx)``) launders its argument/receiver/result; a
   tainted value reaching a declared non-validating SINK call is a
   ``tainted-sink`` finding.  The pass is module-local interprocedural:
   calls into same-module functions with tainted arguments are analyzed
   under those tainted parameters (memoized, cycle-tolerant), the
   collect_functions/terminal_name machinery shared with ``_jitscan``.

Branches join by union (taint survives if EITHER arm leaves it
tainted), loops run their body twice (enough for the single-level
loop-carried dependences reactor code exhibits), and ``len()``-style
scalar builtins are laundering (a size derived from attacker bytes is
a number, not attacker-shaped data).  The analysis is deliberately
unsound-toward-noise rather than complete: its job is to hold the
decode surfaces to the reference's decode-then-ValidateBasic shape
(types/validation.go, conS.Receive), not to model Python.

Runtime counterpart: tests/test_decode_gauntlet.py feeds every declared
source truncated/oversized/bit-flipped/type-confused frames and holds
each to its declared typed-error contract.
"""

from __future__ import annotations

import ast
import os
from dataclasses import dataclass

from . import taint_manifest as tm
from ._jitscan import collect_functions
from .linter import Finding, terminal_name

#: Finding check ids this pass emits (scripts/lint.py uses these for
#: stale-allowlist accounting, mirroring rangecheck.FINDING_CHECK_IDS).
FINDING_CHECK_IDS = frozenset(
    {"tainted-sink", "taint-unregistered-decode", "taint-manifest-stale"}
)

MANIFEST_PATH = "cometbft_tpu/analysis/taint_manifest.py"

#: Call names whose RESULT is untrusted bytes/structures wherever they
#: appear — the envelope/stream decoders of this codebase.  ``.decode``
#: attribute calls are recognized separately (proto Message classes).
DECODER_CALL_NAMES = frozenset(
    {
        "decode_records",
        "parse_signed_tx",
        "parse_validator_tx",
        "decode_delimited",
        "decode_varint_stream",
    }
)

#: Directories whose decode calls are the codec itself, not a surface.
_SCAN_EXCLUDE_PARTS = ("wire", "analysis")


# ------------------------------------------------------- site discovery


@dataclass(frozen=True)
class DecodeSite:
    path: str  # repo-relative posix path
    func: str  # enclosing function name, "<module>" at top level
    lineno: int
    col: int
    callee: str  # the decode call's terminal name


def _is_proto_decode(call: ast.Call) -> bool:
    """``Owner.decode(...)`` / ``pb.Owner.decode(...)`` where the owner
    chain terminates in a CapWords name — a proto Message classmethod,
    never ``somebytes.decode("utf-8")`` (lowercase owner)."""
    f = call.func
    if not (isinstance(f, ast.Attribute) and f.attr == "decode"):
        return False
    owner = terminal_name(f.value)
    return bool(owner) and owner[:1].isupper()


class _SiteScanner(ast.NodeVisitor):
    def __init__(self, path: str):
        self.path = path
        self.sites: list[DecodeSite] = []
        self._stack: list[str] = []

    def _visit_fn(self, node):
        self._stack.append(node.name)
        self.generic_visit(node)
        self._stack.pop()

    visit_FunctionDef = _visit_fn  # noqa: N815
    visit_AsyncFunctionDef = _visit_fn  # noqa: N815

    def visit_Call(self, node: ast.Call):  # noqa: N802
        tn = terminal_name(node.func)
        if _is_proto_decode(node) or tn in DECODER_CALL_NAMES:
            self.sites.append(
                DecodeSite(
                    self.path,
                    self._stack[-1] if self._stack else "<module>",
                    node.lineno,
                    node.col_offset,
                    tn or "decode",
                )
            )
        self.generic_visit(node)


def _repo_root() -> str:
    return os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _package_root() -> str:
    return os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def discover_decode_sites(pkg_root: str | None = None) -> list[DecodeSite]:
    """Every decode call site under the package, excluding the codec
    (wire/) and this analysis layer."""
    pkg_root = pkg_root or _package_root()
    base = os.path.dirname(os.path.abspath(pkg_root))
    sites: list[DecodeSite] = []
    for root, dirs, files in os.walk(pkg_root):
        dirs[:] = sorted(
            d
            for d in dirs
            if not d.startswith(".")
            and d != "__pycache__"
            and d not in _SCAN_EXCLUDE_PARTS
        )
        for fname in sorted(files):
            if not fname.endswith(".py"):
                continue
            fpath = os.path.join(root, fname)
            rel = os.path.relpath(fpath, base).replace(os.sep, "/")
            try:
                with open(fpath, encoding="utf-8") as f:
                    tree = ast.parse(f.read(), filename=fpath)
            except (SyntaxError, OSError):
                continue  # the plain linter reports parse errors
            sc = _SiteScanner(rel)
            sc.visit(tree)
            sites.extend(sc.sites)
    return sites


# --------------------------------------------------------- taint engine


class _Interp:
    """Module-local interprocedural taint interpreter for one source."""

    def __init__(self, path: str, funcs: dict, source: tm.Source):
        self.path = path
        self.funcs = funcs
        self.source = source
        self.findings: list[Finding] = []
        self._memo: dict[tuple[str, frozenset], bool] = {}
        self._active: set[tuple[str, frozenset]] = set()
        self._reported: set[tuple[int, str]] = set()

    # -- entry ---------------------------------------------------------

    def analyze(self, fname: str, tainted_params: frozenset[str]) -> bool:
        """Interpret ``fname`` with the given parameters tainted; returns
        whether its return value is tainted."""
        key = (fname, tainted_params)
        if key in self._memo:
            return self._memo[key]
        if key in self._active:
            return False  # optimistic cycle break; reactor code is acyclic
        self._active.add(key)
        env = set(tainted_params)
        ret = self._exec_block(self.funcs[fname].body, env)
        self._active.discard(key)
        self._memo[key] = ret
        return ret

    # -- statements ----------------------------------------------------

    def _exec_block(self, body: list, env: set[str]) -> bool:
        ret = False
        for stmt in body:
            ret = self._exec_stmt(stmt, env) or ret
        return ret

    def _bind(self, target: ast.expr, tainted: bool, env: set[str]) -> None:
        if isinstance(target, ast.Name):
            (env.add if tainted else env.discard)(target.id)
        elif isinstance(target, (ast.Tuple, ast.List)):
            for el in target.elts:
                self._bind(el, tainted, env)
        elif isinstance(target, ast.Starred):
            self._bind(target.value, tainted, env)
        # attribute/subscript targets: no per-field tracking; the owner's
        # taint already covers reads back out of it

    def _exec_stmt(self, stmt, env: set[str]) -> bool:
        if isinstance(stmt, ast.Assign):
            t = self._eval(stmt.value, env)
            for tgt in stmt.targets:
                self._bind(tgt, t, env)
            return False
        if isinstance(stmt, ast.AnnAssign):
            if stmt.value is not None:
                self._bind(stmt.target, self._eval(stmt.value, env), env)
            return False
        if isinstance(stmt, ast.AugAssign):
            t = self._eval(stmt.value, env) or self._eval(stmt.target, env)
            self._bind(stmt.target, t, env)
            return False
        if isinstance(stmt, ast.Expr):
            self._exec_expr_stmt(stmt.value, env)
            return False
        if isinstance(stmt, ast.Return):
            return self._eval(stmt.value, env) if stmt.value else False
        if isinstance(stmt, ast.If):
            self._eval(stmt.test, env)
            e1, e2 = set(env), set(env)
            r1 = self._exec_block(stmt.body, e1)
            r2 = self._exec_block(stmt.orelse, e2)
            env.clear()
            env.update(e1 | e2)
            return r1 or r2
        if isinstance(stmt, (ast.For, ast.AsyncFor)):
            it = self._eval(stmt.iter, env)
            self._bind(stmt.target, it, env)
            # two passes: enough to stabilize single-level loop-carried taint
            r = self._exec_block(stmt.body, env)
            self._bind(stmt.target, it or self._eval(stmt.iter, set(env)), env)
            r = self._exec_block(stmt.body, env) or r
            return self._exec_block(stmt.orelse, env) or r
        if isinstance(stmt, ast.While):
            self._eval(stmt.test, env)
            r = self._exec_block(stmt.body, env)
            self._eval(stmt.test, env)
            r = self._exec_block(stmt.body, env) or r
            return self._exec_block(stmt.orelse, env) or r
        if isinstance(stmt, ast.Try):
            r = self._exec_block(stmt.body, env)
            for h in stmt.handlers:
                he = set(env)
                r = self._exec_block(h.body, he) or r
                env.update(he)
            r = self._exec_block(stmt.orelse, env) or r
            return self._exec_block(stmt.finalbody, env) or r
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            for item in stmt.items:
                t = self._eval(item.context_expr, env)
                if item.optional_vars is not None:
                    self._bind(item.optional_vars, t, env)
            return self._exec_block(stmt.body, env)
        if isinstance(stmt, ast.Raise):
            self._eval(stmt.exc, env)
            return False
        if isinstance(stmt, ast.Assert):
            self._eval(stmt.test, env)
            return False
        if isinstance(stmt, ast.Delete):
            for tgt in stmt.targets:
                if isinstance(tgt, ast.Name):
                    env.discard(tgt.id)
            return False
        # nested defs/classes, imports, pass/break/continue/global: no flow
        return False

    def _exec_expr_stmt(self, v: ast.expr, env: set[str]) -> None:
        """Statement-position expression: the place sanitizer calls
        launder their arguments (``validate_pex_message(msg)``,
        ``part.validate_basic()``)."""
        if isinstance(v, ast.Call):
            tn = terminal_name(v.func)
            if tn in tm.SANITIZER_FUNCS:
                self._eval(v, env)  # still scan nested calls for sinks
                for a in v.args:
                    if isinstance(a, ast.Name):
                        env.discard(a.id)
                return
            if (
                isinstance(v.func, ast.Attribute)
                and v.func.attr in tm.SANITIZER_METHODS
                and isinstance(v.func.value, ast.Name)
            ):
                self._eval(v, env)
                env.discard(v.func.value.id)
                return
        self._eval(v, env)

    # -- expressions ---------------------------------------------------

    def _eval(self, node, env: set[str]) -> bool:
        if node is None or isinstance(node, ast.Constant):
            return False
        if isinstance(node, ast.Name):
            return node.id in env
        if isinstance(node, ast.Attribute):
            return self._eval(node.value, env)
        if isinstance(node, ast.Subscript):
            t = self._eval(node.value, env)
            self._eval(node.slice, env)
            return t
        if isinstance(node, ast.Slice):
            for part in (node.lower, node.upper, node.step):
                self._eval(part, env)
            return False
        if isinstance(node, ast.Call):
            return self._eval_call(node, env)
        if isinstance(node, ast.BinOp):
            return self._eval(node.left, env) | self._eval(node.right, env)
        if isinstance(node, ast.BoolOp):
            return any([self._eval(v, env) for v in node.values])
        if isinstance(node, ast.UnaryOp):
            return self._eval(node.operand, env)
        if isinstance(node, ast.Compare):
            self._eval(node.left, env)
            for c in node.comparators:
                self._eval(c, env)
            return False  # a bool verdict is a scalar, not attacker data
        if isinstance(node, ast.IfExp):
            self._eval(node.test, env)
            return self._eval(node.body, env) | self._eval(node.orelse, env)
        if isinstance(node, (ast.Tuple, ast.List, ast.Set)):
            return any([self._eval(el, env) for el in node.elts])
        if isinstance(node, ast.Dict):
            tk = any([self._eval(k, env) for k in node.keys if k is not None])
            tv = any([self._eval(v, env) for v in node.values])
            return tk or tv
        if isinstance(node, ast.JoinedStr):
            return any([self._eval(v, env) for v in node.values])
        if isinstance(node, ast.FormattedValue):
            return self._eval(node.value, env)
        if isinstance(node, ast.Starred):
            return self._eval(node.value, env)
        if isinstance(node, ast.NamedExpr):
            t = self._eval(node.value, env)
            self._bind(node.target, t, env)
            return t
        if isinstance(node, (ast.ListComp, ast.SetComp, ast.GeneratorExp, ast.DictComp)):
            envc = set(env)
            t = False
            for gen in node.generators:
                ti = self._eval(gen.iter, envc)
                self._bind(gen.target, ti, envc)
                for cond in gen.ifs:
                    self._eval(cond, envc)
                t = t or ti
            if isinstance(node, ast.DictComp):
                t = self._eval(node.key, envc) | self._eval(node.value, envc) or t
            else:
                t = self._eval(node.elt, envc) or t
            return t
        if isinstance(node, (ast.Await, ast.YieldFrom)):
            return self._eval(node.value, env)
        if isinstance(node, ast.Yield):
            return self._eval(node.value, env) if node.value else False
        if isinstance(node, ast.Lambda):
            return False  # not called here; no flow to model
        return False

    def _eval_call(self, node: ast.Call, env: set[str]) -> bool:
        func = node.func
        tn = terminal_name(func)
        arg_taints = [self._eval(a, env) for a in node.args]
        kw_taints = {
            k.arg: self._eval(k.value, env) for k in node.keywords
        }
        recv_tainted = (
            self._eval(func.value, env) if isinstance(func, ast.Attribute) else False
        )
        any_tainted = any(arg_taints) or any(kw_taints.values())

        # sink gate: a tainted argument reaching a declared sink with no
        # sanitizer on the path is THE finding this pass exists for
        if tn in tm.SINK_NAMES and any_tainted and tn not in tm.VALIDATING_SINKS:
            dedup = (node.lineno, tn)
            if dedup not in self._reported:
                self._reported.add(dedup)
                self.findings.append(
                    Finding(
                        "tainted-sink",
                        self.path,
                        node.lineno,
                        node.col_offset,
                        f"[{self.source.name}] tainted value reaches sink "
                        f"{tn}() with no sanitizer on the path — validate "
                        "before use (docs/byzantine_inputs.md)",
                    )
                )

        if tn in tm.SANITIZER_FUNCS:
            return False  # validated-or-raised result
        if isinstance(func, ast.Attribute) and func.attr in tm.SANITIZER_METHODS:
            return False
        if tn in tm.UNTAINTING_BUILTINS:
            return False
        if tn in self.source.tainted_calls:
            return True

        # module-local interprocedural step: follow the call under the
        # tainted parameter set (self.method resolves by terminal name,
        # the _jitscan convention)
        fn = self.funcs.get(tn)
        if fn is not None:
            params = [a.arg for a in fn.args.args]
            if params and params[0] == "self" and isinstance(func, ast.Attribute):
                params = params[1:]
            tainted_params = {
                params[i]
                for i, t in enumerate(arg_taints)
                if t and i < len(params)
            }
            tainted_params |= {
                k for k, t in kw_taints.items() if t and k in set(params)
            }
            if tainted_params:
                return self.analyze(tn, frozenset(tainted_params))
            return False

        return any_tainted or recv_tainted


def _analyze_source(src: tm.Source, base: str) -> list[Finding]:
    fpath = os.path.join(base, src.path)
    try:
        with open(fpath, encoding="utf-8") as f:
            tree = ast.parse(f.read(), filename=fpath)
    except (OSError, SyntaxError):
        return [
            Finding(
                "taint-manifest-stale",
                MANIFEST_PATH,
                1,
                0,
                f"source {src.name!r}: cannot parse {src.path}",
            )
        ]
    funcs = collect_functions(tree)
    if src.func not in funcs:
        return [
            Finding(
                "taint-manifest-stale",
                MANIFEST_PATH,
                1,
                0,
                f"source {src.name!r}: no function {src.func!r} in {src.path}",
            )
        ]
    interp = _Interp(src.path, funcs, src)
    seeds = frozenset(p for p in src.tainted_params if p != "self")
    interp.analyze(src.func, seeds)
    return interp.findings


# ------------------------------------------------------------ run_check


def run_check(pkg_root: str | None = None, allowlist=None) -> tuple[list[Finding], dict]:
    """The full taint pass: decode-surface exhaustiveness both
    directions + validate-before-use dataflow from every source.
    Returns (findings, report); empty findings is the green gate.

    ``allowlist`` filters findings when given (the kernelcheck policy:
    raw by default so scripts/lint.py can track stale entries)."""
    pkg_root = pkg_root or _package_root()
    base = os.path.dirname(os.path.abspath(pkg_root))
    findings: list[Finding] = []

    sites = discover_decode_sites(pkg_root)
    matched_keys: set[str] = set()
    unregistered = 0
    for site in sites:
        entry = tm.site_registered(site.path, site.func)
        if entry is None:
            unregistered += 1
            findings.append(
                Finding(
                    "taint-unregistered-decode",
                    site.path,
                    site.lineno,
                    site.col,
                    f"decode surface {site.callee}() in {site.func}() is not "
                    "registered in taint_manifest.DECODE_SITES — declare its "
                    "source (and gauntlet coverage) or mark it trusted with "
                    "a justification",
                )
            )
        else:
            key_tail = f"{site.path}::{site.func}"
            for key in tm.DECODE_SITES:
                if key_tail == key or key_tail.endswith("/" + key):
                    matched_keys.add(key)

    source_names = {s.name for s in tm.SOURCES}
    for key, val in tm.DECODE_SITES.items():
        if key not in matched_keys:
            findings.append(
                Finding(
                    "taint-manifest-stale",
                    MANIFEST_PATH,
                    1,
                    0,
                    f"DECODE_SITES entry {key!r} matches no decode call — "
                    "remove it or fix the path::function key",
                )
            )
        if not val.startswith("trusted:") and val not in source_names:
            findings.append(
                Finding(
                    "taint-manifest-stale",
                    MANIFEST_PATH,
                    1,
                    0,
                    f"DECODE_SITES entry {key!r} names unknown source {val!r}",
                )
            )

    analyzed = 0
    for src in tm.dataflow_sources():
        findings.extend(_analyze_source(src, base))
        analyzed += 1

    findings.sort(key=lambda f: (f.path, f.line, f.col, f.check))
    report = {
        "decode_sites": len(sites),
        "unregistered": unregistered,
        "sources": len(tm.SOURCES),
        "dataflow_sources": analyzed,
        "sinks": len(tm.SINKS),
    }
    if allowlist is not None:
        findings = [f for f in findings if not allowlist.suppresses(f)]
    return findings, report


def summary(findings: list[Finding], report: dict) -> dict:
    """Machine-readable result for the scripts/lint.py --json block."""
    return {
        "ok": not findings,
        **report,
        "findings": [
            {"check": f.check, "path": f.path, "line": f.line, "message": f.message}
            for f in findings
        ],
    }
