"""Check: jax-purity.

Host side effects inside jitted bodies in ``ops/`` and ``parallel/``:
``print`` (runs at trace time only, then never again), env reads (baked
into the compiled program — recompiles silently keep the stale value),
file I/O, host clock reads, ``.item()``/``float(arg)`` on traced values
(forces a device sync mid-trace or a ConcretizationTypeError).  These
are the bug class the XLA layer cannot diagnose for us: the program
traces fine once and then behaves differently on the cached executable.

Jitted bodies are found statically (the shared ``_jitscan`` machinery):
functions decorated with ``jax.jit``/``jit``/``partial(jax.jit, ...)``,
functions passed to ``jax.jit(...)`` by name, bodies handed to ``lax``
control flow (``fori_loop``/``while_loop``/``scan``/``cond``/``switch``/
``map``), plus the kernel manifest's declared entry points — functions
jitted from ANOTHER module (``ops/sha2.sha512_blocks`` is jitted via
``models/``) are invisible to a per-module site scan but not to
``kernel_manifest.traced_roots`` — then closed transitively over
same-module calls.  Statements under
``with jax.ensure_compile_time_eval():`` are exempt (explicitly marked
host-side constant folding).
"""

from __future__ import annotations

import ast

from . import kernel_manifest as manifest
from ._jitscan import traced_closure
from .linter import Finding, Module, dotted_name, terminal_name

CHECK_ID = "jax-purity"
SUMMARY = "host side effect / env read / device sync inside a jitted body"

SCOPE_DIRS = {"ops", "parallel"}

_CLOCK_CALLS = {
    "time", "perf_counter", "perf_counter_ns", "monotonic", "monotonic_ns",
    "sleep",
}


class _BodyVisitor(ast.NodeVisitor):
    def __init__(self, mod: Module, fn: ast.FunctionDef):
        self.mod = mod
        self.fn = fn
        self.params = {
            a.arg
            for a in (
                list(fn.args.posonlyargs) + list(fn.args.args)
                + list(fn.args.kwonlyargs)
            )
        }
        self.findings: list[Finding] = []

    def _add(self, node: ast.AST, msg: str) -> None:
        self.findings.append(
            Finding(
                CHECK_ID, self.mod.path, node.lineno, node.col_offset,
                f"{msg} inside jitted body {self.fn.name!r}",
            )
        )

    def visit_With(self, node: ast.With):  # noqa: N802
        for item in node.items:
            d = dotted_name(
                item.context_expr.func
                if isinstance(item.context_expr, ast.Call)
                else item.context_expr
            )
            if d and d.endswith("ensure_compile_time_eval"):
                return  # explicitly-marked host-side constant folding
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call):  # noqa: N802
        d = dotted_name(node.func) or ""
        tn = terminal_name(node.func)
        if isinstance(node.func, ast.Name):
            if node.func.id == "print":
                self._add(node, "host print() (use jax.debug.print)")
            elif node.func.id == "open":
                self._add(node, "host file I/O")
            elif (
                node.func.id in ("float", "int", "bool")
                and len(node.args) == 1
                and isinstance(node.args[0], ast.Name)
                and node.args[0].id in self.params
            ):
                self._add(
                    node,
                    f"{node.func.id}() on parameter "
                    f"{node.args[0].id!r} (concretizes a traced value)",
                )
        if d == "getenv" or d.endswith(".getenv") or ".environ" in d or d.startswith("environ"):
            self._add(node, "env read (baked in at trace time)")
        elif "envknobs." in d and (tn or "").startswith(("get", "raw")):
            self._add(node, "envknobs read (baked in at trace time)")
        elif isinstance(node.func, ast.Attribute):
            base = dotted_name(node.func.value)
            if tn == "item":
                self._add(node, ".item() device sync")
            elif tn in _CLOCK_CALLS and base == "time":
                self._add(node, f"host clock/time.{tn}()")
            elif tn in ("save", "load") and base in ("np", "numpy"):
                self._add(node, f"host file I/O (np.{tn})")
        self.generic_visit(node)

    def visit_Subscript(self, node: ast.Subscript):  # noqa: N802
        d = dotted_name(node.value)
        if d and (d == "environ" or d.endswith(".environ")):
            self._add(node, "env read (baked in at trace time)")
        self.generic_visit(node)


def check(mod: Module) -> list[Finding]:
    if not SCOPE_DIRS.intersection(mod.parts[:-1]):
        return []
    findings: list[Finding] = []
    closure = traced_closure(mod.tree, manifest.traced_roots(mod.path))
    for fn in closure.values():
        v = _BodyVisitor(mod, fn)
        for stmt in fn.body:
            v.visit(stmt)
        findings.extend(v.findings)
    return findings
