"""Concurrency & invariant analysis for the cometbft_tpu codebase.

Three halves:

* a stdlib-``ast`` static linter (``linter.py`` + one module per check)
  with repo-specific checks — lock held across a blocking call,
  swallowed exceptions in thread run-loops, raw ``COMETBFT_TPU_*`` env
  reads outside the knob registry, host side effects inside jitted
  kernel bodies, metric construction outside the Registry factories,
  unnamed threads, and the kernel-plane trio (unregistered ``jax.jit``
  sites, host syncs outside declared collect boundaries, dtype-changing
  literal arithmetic in jitted bodies).  Entry point: ``scripts/lint.py``
  (the single CLI — it owns the ``[tool.cometbft-tpu-lint]`` config,
  stale-entry reporting, and exit-code contract).

* the kernel contract checker (``kernelcheck.py`` over the declarations
  in ``kernel_manifest.py``): every jitted verify-plane entry point is
  abstract-interpreted via ``jax.make_jaxpr`` under ``JAX_PLATFORMS=cpu``
  and held to dtype closure, jaxpr purity, and the checked-in
  fingerprint goldens (``kernel_fingerprints.json``) — see
  docs/kernel_contracts.md.  ``scripts/lint.py --check kernel`` runs it;
  ``scripts/lint.py regen-fingerprints`` re-blesses deliberate drift.

* a runtime lock-order witness (``lockwitness.py``), enabled by
  ``COMETBFT_TPU_LOCKCHECK=1``: every ``threading.Lock``/``RLock``
  acquisition feeds a per-process acquisition-order graph, and an order
  inversion (potential deadlock) or a ``time.sleep`` while holding a
  witnessed lock is reported with both stacks.  The test conftest
  installs it, so every suite run doubles as a deadlock hunt.

The linter half imports nothing heavyweight (no JAX, no numpy) so it
runs anywhere the stdlib does; only ``kernelcheck`` defers to JAX, at
call time.
"""
