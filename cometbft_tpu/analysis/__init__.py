"""Concurrency & invariant analysis for the cometbft_tpu codebase.

Two halves:

* a stdlib-``ast`` static linter (``linter.py`` + one module per check)
  with repo-specific checks — lock held across a blocking call,
  swallowed exceptions in thread run-loops, raw ``COMETBFT_TPU_*`` env
  reads outside the knob registry, host side effects inside jitted
  kernel bodies, metric construction outside the Registry factories,
  and unnamed threads.  Entry point: ``scripts/lint.py`` (the single
  CLI — it owns the ``[tool.cometbft-tpu-lint]`` config, stale-entry
  reporting, and exit-code contract).

* a runtime lock-order witness (``lockwitness.py``), enabled by
  ``COMETBFT_TPU_LOCKCHECK=1``: every ``threading.Lock``/``RLock``
  acquisition feeds a per-process acquisition-order graph, and an order
  inversion (potential deadlock) or a ``time.sleep`` while holding a
  witnessed lock is reported with both stacks.  The test conftest
  installs it, so every suite run doubles as a deadlock hunt.

This package imports nothing heavyweight (no JAX, no numpy) so the
linter runs anywhere the stdlib does.
"""
