"""Kernel contract checker: static shape/dtype/jaxpr analysis for the
TPU verify plane.

Abstract-interprets every kernel declared in ``kernel_manifest.py`` via
``jax.make_jaxpr`` (no device execution — runs on CPU-only hosts with
``JAX_PLATFORMS=cpu``) and enforces three contracts:

1. **dtype closure** — no 64-bit/complex dtype anywhere in the traced
   program, no weak-typed KERNEL OUTPUT (a weak output means the public
   contract's dtype is at the mercy of promotion rules), no weak-typed
   FLOATING intermediate (the signature of a bare float literal leaking
   into integer kernel arithmetic — the dtype-changing kind of
   promotion; weak int/bool intermediates from loop counters and index
   math are idiomatic, dtype-preserving against any strong operand, and
   deliberately NOT findings), and every ``convert_element_type`` drawn
   from the justified allowlist in the manifest.
2. **purity** — no host-callback primitive (``pure_callback``,
   ``io_callback``, ``debug_callback``, infeed/outfeed) anywhere in the
   jaxpr, including nested control-flow bodies.
3. **drift gate** — the traced signature (input/output avals) and the
   primitive census of each kernel must match the checked-in golden
   (``analysis/kernel_fingerprints.json``).  A mismatch fails with a
   readable before/after report: accidental jaxpr drift is how silent
   recompiles (seconds of wall clock per shape) and numeric changes land.

Regenerate goldens after a DELIBERATE kernel change with::

    python scripts/lint.py regen-fingerprints

JAX imports are deferred to call time so the analysis package itself
stays importable everywhere the stdlib runs.
"""

from __future__ import annotations

import hashlib
import json
import os
import sys
from dataclasses import dataclass, field

from . import kernel_manifest as manifest
from .linter import Finding

FINGERPRINTS_PATH = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "kernel_fingerprints.json"
)

#: Every Finding.check id this module emits — scripts/lint.py's
#: stale-entry filter for --check kernel imports this instead of
#: duplicating the set.
FINDING_CHECK_IDS = frozenset(
    {"kernel-contract", "kernel-fingerprint", "kernel-manifest"}
)

#: Sentinel signature for a kernel that failed to trace: the failure is
#: its own finding, and the drift gate skips it.
UNTRACEABLE_SIG = "<untraceable>"

# Host-callback / host-transfer primitives that must never appear inside
# a verify-plane kernel.  Matched on the primitive NAME so new jax
# spellings of the same escape hatch (e.g. versioned callback prims)
# still trip the substring rules below.
_FORBIDDEN_PRIMS = frozenset(
    {"infeed", "outfeed", "host_local_array_to_global_array"}
)
_FORBIDDEN_PRIM_SUBSTRINGS = ("callback",)


# Knobs that are read at TRACE time and change the traced program.  The
# checker unsets them while tracing so the checked-in fingerprints always
# describe the DEFAULT program, whatever the ambient environment
# (models/comb_verifier._device_verify resolves comb.tree_enabled() during
# its trace; a stray COMETBFT_TPU_COMB_TREE=0 would silently regenerate
# the sequential-path fingerprint).
_TRACE_ENV_PINS = ("COMETBFT_TPU_COMB_TREE",)


class _pinned_trace_env:
    """Context manager: default trace environment for deterministic
    fingerprints; restores whatever the caller had on exit."""

    def __enter__(self):
        self._saved = {k: os.environ.pop(k, None) for k in _TRACE_ENV_PINS}
        return self

    def __exit__(self, *exc):
        for k, v in self._saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
        return False


def _ensure_cpu_backend() -> None:
    """Force the CPU backend when jax has not been imported yet — even
    over an ambient JAX_PLATFORMS=tpu: the gate must run (and stay
    deterministic) on hosts with no TPU, and must never touch a real
    accelerator when one exists (a wedged device tunnel hangs backend
    init indefinitely).  When jax is already initialized (pytest's
    conftest), the caller owns the platform choice."""
    if "jax" not in sys.modules:
        os.environ["JAX_PLATFORMS"] = "cpu"


def _aval_str(aval) -> str:
    shape = ",".join(str(d) for d in aval.shape)
    return f"{aval.dtype}[{shape}]"


@dataclass
class Trace:
    """One kernel's abstract interpretation."""

    kernel: manifest.Kernel
    signature: str  # "(in avals) -> (out avals)"
    primitives: dict[str, int]
    findings: list[Finding] = field(default_factory=list)
    eqns: int = 0  # total jaxpr equations, nested bodies included

    def fingerprint(self) -> dict:
        payload = {
            "signature": self.signature,
            "primitives": dict(sorted(self.primitives.items())),
        }
        digest = hashlib.sha256(
            json.dumps(payload, sort_keys=True).encode()
        ).hexdigest()
        # eqns ride along for operators reading the golden but stay out
        # of the digest: they are budget-gated (manifest max_eqns hard
        # ceilings), not drift-gated — the shardcheck "costs" policy
        return {**payload, "digest": digest, "costs": {"eqns": self.eqns}}


def _resolve(kernel: manifest.Kernel):
    """Manifest fn ref -> the traceable callable (factories get a
    1-device CPU mesh; static kwargs are bound as Python constants)."""
    import functools
    import importlib

    mod_name, _, fn_name = kernel.fn.partition(":")
    fn = getattr(importlib.import_module(mod_name), fn_name)
    if kernel.needs_mesh:
        from ..parallel.mesh import make_mesh

        return fn(
            make_mesh(1), *kernel.mesh_static, **dict(kernel.static_kwargs)
        )
    if kernel.static_kwargs:
        return functools.partial(fn, **dict(kernel.static_kwargs))
    return fn


def _arg_structs(kernel: manifest.Kernel):
    import jax
    import numpy as np

    return [
        jax.ShapeDtypeStruct(a.shape, np.dtype(a.dtype)) for a in kernel.args
    ]


def _walk_jaxprs(jaxpr):
    """Yield jaxpr and every nested jaxpr (pjit/scan/while/cond bodies,
    shard_map, custom-call sub-programs) exactly once each."""
    try:
        from jax.extend.core import ClosedJaxpr, Jaxpr
    except ImportError:  # pragma: no cover - older jax spelling
        from jax.core import ClosedJaxpr, Jaxpr  # type: ignore

    seen: set[int] = set()
    stack = [jaxpr]
    while stack:
        j = stack.pop()
        if isinstance(j, ClosedJaxpr):
            j = j.jaxpr
        if id(j) in seen:
            continue
        seen.add(id(j))
        yield j
        for eqn in j.eqns:
            for p in eqn.params.values():
                if isinstance(p, (ClosedJaxpr, Jaxpr)):
                    stack.append(p)
                elif isinstance(p, (list, tuple)):
                    stack.extend(
                        q for q in p if isinstance(q, (ClosedJaxpr, Jaxpr))
                    )


def trace_kernel(kernel: manifest.Kernel) -> Trace:
    """Trace one manifest kernel and run the dtype-closure and purity
    passes over its jaxpr."""
    _ensure_cpu_backend()
    import jax

    path = manifest.module_path(kernel)
    findings: list[Finding] = []

    def add(msg: str) -> None:
        findings.append(Finding("kernel-contract", path, 1, 0,
                                f"[{kernel.name}] {msg}"))

    try:
        with _pinned_trace_env():
            fn = _resolve(kernel)
            closed = jax.make_jaxpr(fn)(*_arg_structs(kernel))
    except Exception as e:  # noqa: BLE001 - a kernel that fails to trace IS the finding
        add(f"failed to trace: {type(e).__name__}: {e}")
        return Trace(kernel, UNTRACEABLE_SIG, {}, findings)

    in_sig = ", ".join(_aval_str(a) for a in closed.in_avals)
    out_sig = ", ".join(_aval_str(a) for a in closed.out_avals)
    signature = f"({in_sig}) -> ({out_sig})"

    for a in closed.out_avals:
        if getattr(a, "weak_type", False):
            add(
                f"weak-typed kernel output {_aval_str(a)} — the contract "
                "dtype is at the mercy of promotion rules; pin it "
                "(jnp.int32(...)/.astype(...)) at the return"
            )

    # output spec: declared in the manifest, checked before fingerprints
    def leaf_strs(leaves):
        return [
            d + "[" + ",".join(str(x) for x in s) + "]" for s, d in leaves
        ]

    got = [(tuple(a.shape), str(a.dtype)) for a in closed.out_avals]
    want = [(a.shape, a.dtype) for a in kernel.out]
    if got != want:
        add(
            "output spec mismatch: manifest declares "
            f"{leaf_strs(want)}, trace produced {leaf_strs(got)}"
        )

    prims: dict[str, int] = {}
    total_eqns = 0
    for jaxpr in _walk_jaxprs(closed.jaxpr):
        for eqn in jaxpr.eqns:
            total_eqns += 1
            name = eqn.primitive.name
            prims[name] = prims.get(name, 0) + 1

            if name in _FORBIDDEN_PRIMS or any(
                s in name for s in _FORBIDDEN_PRIM_SUBSTRINGS
            ):
                add(
                    f"impure primitive {name!r} in the jaxpr — host "
                    "callbacks/transfers are forbidden inside verify-plane "
                    "kernels"
                )

            if name == "convert_element_type":
                src = str(eqn.invars[0].aval.dtype)
                dst = str(eqn.params.get("new_dtype"))
                if src != dst and (src, dst) not in manifest.ALLOWED_CONVERSIONS:
                    add(
                        f"unjustified convert_element_type {src} -> {dst} — "
                        "add the pair to kernel_manifest.ALLOWED_CONVERSIONS "
                        "with a justification, or fix the promotion"
                    )

            for v in eqn.outvars:
                aval = v.aval
                dt = str(getattr(aval, "dtype", ""))
                if dt in manifest.FORBIDDEN_DTYPES:
                    add(
                        f"{dt} value produced by {name!r} — 64-bit/complex "
                        "dtypes are outside the kernel contract"
                    )
                if getattr(aval, "weak_type", False) and dt.startswith(
                    ("float", "complex", "bfloat")
                ):
                    # weak int/bool intermediates (loop counters, index
                    # math) are dtype-preserving and not findings; a weak
                    # FLOAT is a bare float literal changing dtypes
                    add(
                        f"weak-typed {dt} output of {name!r} — a bare float "
                        "literal leaked into kernel arithmetic; pin it "
                        "(np.float32(...)/jnp.float32(...)) so promotion "
                        "cannot drift"
                    )

    # compile-cost budget: the static face of a minutes-long XLA compile
    # (the pre-PR-11 comb table build hit 2m34s at ~84k eqns).  A kernel
    # with no declared budget skips the gate here but fails the manifest
    # consistency pass below — no production kernel rides unbudgeted.
    if kernel.max_eqns > 0 and total_eqns > kernel.max_eqns:
        add(
            f"compile-cost budget: {total_eqns} jaxpr equations exceeds "
            f"the budget of {kernel.max_eqns} "
            f"({total_eqns - kernel.max_eqns:+d}) — an unrolled loop or "
            "table build lands here in milliseconds instead of as a "
            "minutes-long XLA compile; restructure the kernel (roll the "
            "loop with lax.scan / precompute host-side) or raise the "
            "budget with justification"
        )
    return Trace(kernel, signature, prims, findings, total_eqns)


# -------------------------------------------------------------- drift gate


def load_fingerprints(path: str = FINGERPRINTS_PATH) -> dict:
    try:
        with open(path, encoding="utf-8") as f:
            return json.load(f)
    except FileNotFoundError:
        return {}


def write_fingerprints(traces: list[Trace], path: str = FINGERPRINTS_PATH) -> None:
    data = {t.kernel.name: t.fingerprint() for t in traces}
    with open(path, "w", encoding="utf-8") as f:
        json.dump(data, f, indent=2, sort_keys=True)
        f.write("\n")


def _diff_report(name: str, golden: dict, fresh: dict) -> str:
    """Readable before/after for one drifted kernel."""
    lines = [f"kernel {name!r} drifted from its checked-in fingerprint:"]
    if golden.get("signature") != fresh.get("signature"):
        lines.append(f"  signature before: {golden.get('signature')}")
        lines.append(f"  signature after : {fresh.get('signature')}")
    gp = golden.get("primitives", {})
    fp = fresh.get("primitives", {})
    for prim in sorted(set(gp) | set(fp)):
        b, a = gp.get(prim, 0), fp.get(prim, 0)
        if b != a:
            lines.append(f"  {prim}: {b} -> {a} ({a - b:+d})")
    lines.append(
        "  deliberate change? regenerate with "
        "`python scripts/lint.py regen-fingerprints`"
    )
    return "\n".join(lines)


def compare_fingerprints(
    traces: list[Trace], golden: dict
) -> list[Finding]:
    """Fingerprint drift findings for traces against the golden file."""
    findings: list[Finding] = []
    fresh_names = set()
    for t in traces:
        fresh_names.add(t.kernel.name)
        if t.signature == UNTRACEABLE_SIG:
            # 'failed to trace' is already the finding; an every-prim
            # "N -> 0" drift diff (with its regen hint) would only bury it
            continue
        path = manifest.module_path(t.kernel)
        fresh = t.fingerprint()
        have = golden.get(t.kernel.name)
        if have is None:
            findings.append(Finding(
                "kernel-fingerprint", path, 1, 0,
                f"kernel {t.kernel.name!r} has no checked-in fingerprint — "
                "run `python scripts/lint.py regen-fingerprints`",
            ))
        elif have.get("digest") != fresh["digest"]:
            findings.append(Finding(
                "kernel-fingerprint", path, 1, 0,
                _diff_report(t.kernel.name, have, fresh),
            ))
    # stale = names neither traced THIS run nor declared in the manifest:
    # a targeted run_check(kernels=<subset>) must not call the untraced
    # manifest kernels' goldens stale
    known = fresh_names | set(manifest.by_name())
    for name in sorted(set(golden) - known):
        findings.append(Finding(
            "kernel-fingerprint", "cometbft_tpu/analysis/kernel_fingerprints.json",
            1, 0,
            f"golden fingerprint {name!r} names no manifest kernel — "
            "stale entry; regenerate the goldens",
        ))
    return findings


def _manifest_findings() -> list[Finding]:
    """Internal consistency: every JIT_SITES value must name a kernel,
    and every kernel must carry a positive compile-cost budget — the
    grandfather clause that let ``comb_build_a_tables`` ride unbudgeted
    into a 2m34s XLA compile is deleted."""
    findings: list[Finding] = []
    names = manifest.by_name()
    for site, kernel in manifest.JIT_SITES.items():
        if kernel not in names:
            findings.append(Finding(
                "kernel-manifest",
                "cometbft_tpu/analysis/kernel_manifest.py", 1, 0,
                f"JIT_SITES[{site!r}] names unknown kernel {kernel!r}",
            ))
    for k in manifest.KERNELS:
        if k.max_eqns <= 0:
            findings.append(Finding(
                "kernel-manifest",
                "cometbft_tpu/analysis/kernel_manifest.py", 1, 0,
                f"kernel {k.name!r} declares no compile-cost budget "
                "(max_eqns) — unbudgeted kernels are how multi-minute "
                "XLA compiles land; declare a measured ceiling",
            ))
    return findings


def default_allowlist():
    """The checked-in repo allowlist (``analysis/allowlist.txt``)."""
    from .linter import Allowlist, default_allowlist_path

    return Allowlist.load(default_allowlist_path())


def run_check(
    fingerprints_path: str = FINGERPRINTS_PATH,
    kernels: tuple[manifest.Kernel, ...] | None = None,
    allowlist=None,
) -> tuple[list[Finding], list[Trace]]:
    """The full static pass: trace every manifest kernel, enforce the
    contracts, and diff against the checked-in fingerprints.  Returns
    (findings, traces); an empty findings list is the green gate.

    ``allowlist`` (an :class:`analysis.linter.Allowlist`) filters the
    findings when given.  The default is raw so callers that do their
    own allowlist bookkeeping (scripts/lint.py tracks stale entries)
    see every finding exactly once; standalone consumers (bench.py)
    pass :func:`default_allowlist` so a justified entry reads green
    everywhere the gate does."""
    traces = [trace_kernel(k) for k in (kernels or manifest.KERNELS)]
    findings = _manifest_findings()
    for t in traces:
        findings.extend(t.findings)
    findings.extend(
        compare_fingerprints(traces, load_fingerprints(fingerprints_path))
    )
    if allowlist is not None:
        findings = [f for f in findings if not allowlist.suppresses(f)]
    return findings, traces


def regenerate(fingerprints_path: str = FINGERPRINTS_PATH) -> tuple[list[Finding], list[Trace]]:
    """Re-trace everything and rewrite the golden file.  Contract
    findings (dtype/purity) still fail — regeneration only blesses
    DRIFT, never a broken contract.  Findings suppressed by a justified
    entry in the checked-in allowlist don't block: a blessed state that
    passes the lint gate must stay regenerable."""
    traces = [trace_kernel(k) for k in manifest.KERNELS]
    findings = _manifest_findings()
    for t in traces:
        findings.extend(t.findings)
    allow = default_allowlist()
    findings = [f for f in findings if not allow.suppresses(f)]
    if not findings:
        write_fingerprints(traces, fingerprints_path)
    return findings, traces


def summary(findings: list[Finding], traces: list[Trace]) -> dict:
    """Machine-readable result (bench.py embeds this when the device
    backend is unavailable, so a bench round still carries signal)."""
    return {
        "ok": not findings,
        "kernels": len(traces),
        "primitive_total": sum(
            sum(t.primitives.values()) for t in traces
        ),
        # per-kernel eqn counts next to their budgets: the acceptance
        # surface for "the table path fits the budget" on backend-less
        # rounds (bench.py embeds this summary)
        "eqns": {
            t.kernel.name: {"eqns": t.eqns, "max_eqns": t.kernel.max_eqns}
            for t in traces
        },
        "findings": [
            {"check": f.check, "path": f.path, "message": f.message}
            for f in findings
        ],
    }
