"""Check: unchecked-shift-width.

A shift whose amount is itself traced data defeats range analysis: the
interval interpreter (analysis/rangecheck.py) can bound ``x >> 12`` or
``lax.shift_left(borrow, BITS)`` exactly, but a data-dependent amount
makes the result's bit-width unknowable — and in these kernels a dynamic
shift is never intentional (limb widths, carry cut points, and window
sizes are all host constants).  This check flags shift sites inside
jitted bodies (same traced-closure scan as weak-type-literal, seeded
with the manifest's cross-module entry points) whose amount expression
contains traced computation:

* a call into ``jnp``/``jax``/``lax`` (the amount is a device value);
* a subscript (indexing into an array of shift counts).

Host-static amounts — int literals, module constants (``BITS``), python
loop variables from an unrolled ``for k in range(8)`` — are fine: they
are concrete at trace time and the range interpreter sees them as
literals in the jaxpr.  Statements under
``jax.ensure_compile_time_eval()`` are host-side folding and exempt.
"""

from __future__ import annotations

import ast

from . import kernel_manifest as manifest
from ._jitscan import traced_closure
from .linter import Finding, Module, dotted_name, terminal_name

CHECK_ID = "unchecked-shift-width"
SUMMARY = "data-dependent shift amount inside a jitted body"

SCOPE_DIRS = {"ops", "parallel", "models"}

#: lax shift primitives whose second argument is the shift amount.
_SHIFT_CALLS = {
    "shift_left",
    "shift_right_logical",
    "shift_right_arithmetic",
    "left_shift",
    "right_shift",
}

#: dtype/array constructors: wrapping host data in one is the repo's
#: standard "pin the dtype" idiom, so the wrapper itself is static —
#: only its ARGUMENTS can make the amount dynamic.
_CONST_WRAPPERS = {
    "asarray", "array", "arange",
    "uint8", "uint16", "uint32", "uint64",
    "int8", "int16", "int32", "int64", "float32",
}

#: host builtins that fold at trace time.
_HOST_FNS = {"int", "len", "min", "max", "abs", "range", "sum"}


def _dynamic_reason(amount: ast.expr) -> str | None:
    """Why the shift amount is traced data, or None when host-static.

    A pure-AST check can't do dataflow, so the rule is syntactic: device
    computation (a non-constructor jnp/jax/lax call, or any subscript)
    anywhere in the amount expression flags it; literals, names, host
    arithmetic, and dtype-pinning constructors over static arguments
    pass.  The interval interpreter is the semantic backstop."""
    if isinstance(amount, ast.Call):
        d = dotted_name(amount.func) or terminal_name(amount.func) or "?"
        root = d.split(".", 1)[0]
        leaf = d.rsplit(".", 1)[-1]
        if root in ("np", "numpy") or leaf in _CONST_WRAPPERS or d in _HOST_FNS:
            for a in list(amount.args) + [kw.value for kw in amount.keywords]:
                r = _dynamic_reason(a)
                if r:
                    return r
            return None
        return f"computed by {d}(...)"
    if isinstance(amount, ast.Subscript):
        return "indexed from an array"
    if isinstance(amount, ast.BinOp):
        return _dynamic_reason(amount.left) or _dynamic_reason(amount.right)
    if isinstance(amount, ast.UnaryOp):
        return _dynamic_reason(amount.operand)
    if isinstance(amount, (ast.List, ast.Tuple)):
        for e in amount.elts:
            r = _dynamic_reason(e)
            if r:
                return r
    return None


class _BodyVisitor(ast.NodeVisitor):
    def __init__(self, mod: Module, fn_name: str):
        self.mod = mod
        self.fn_name = fn_name
        self.findings: list[Finding] = []

    def _add(self, node: ast.AST, desc: str, reason: str) -> None:
        self.findings.append(
            Finding(
                CHECK_ID, self.mod.path, node.lineno, node.col_offset,
                f"{desc} with data-dependent amount ({reason}) inside "
                f"jitted body {self.fn_name!r} — dynamic shift widths "
                "defeat range analysis; hoist the amount to a host "
                "constant",
            )
        )

    def visit_With(self, node: ast.With):  # noqa: N802
        for item in node.items:
            d = dotted_name(
                item.context_expr.func
                if isinstance(item.context_expr, ast.Call)
                else item.context_expr
            )
            if d and d.endswith("ensure_compile_time_eval"):
                return  # host-side constant folding
        self.generic_visit(node)

    def visit_BinOp(self, node: ast.BinOp):  # noqa: N802
        if isinstance(node.op, (ast.LShift, ast.RShift)):
            reason = _dynamic_reason(node.right)
            if reason:
                op = "<<" if isinstance(node.op, ast.LShift) else ">>"
                self._add(node, f"shift '{op}'", reason)
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call):  # noqa: N802
        name = terminal_name(node.func)
        if name in _SHIFT_CALLS and len(node.args) >= 2:
            reason = _dynamic_reason(node.args[1])
            if reason:
                self._add(node, f"{name}()", reason)
        self.generic_visit(node)


def check(mod: Module) -> list[Finding]:
    if not SCOPE_DIRS.intersection(mod.parts[:-1]):
        return []
    findings: list[Finding] = []
    closure = traced_closure(mod.tree, manifest.traced_roots(mod.path))
    for name, fn in closure.items():
        v = _BodyVisitor(mod, name)
        for stmt in fn.body:
            v.visit(stmt)
        findings.extend(v.findings)
    return findings
