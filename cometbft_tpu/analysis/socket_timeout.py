"""Check: socket-without-timeout.

A socket without a configured timeout is an unbounded blocking call
waiting to strand a thread: the BENCH r03-r05 wedged-tunnel rounds, the
healthmon hang-proof probe, and the verify-plane breaker all exist
because "it will answer eventually" is not an invariant this codebase
gets to assume.  This check makes the discipline lexical:

  * ``socket.create_server(...)`` / ``socket.socket(...)`` creations
    and ``socket.create_connection(...)`` without a timeout argument
    (2nd positional or ``timeout=``) are flagged unless the enclosing
    function — or any method of the enclosing class — configures a
    timeout (``settimeout`` / ``setdefaulttimeout``): the common idioms
    are create-then-settimeout in one function, or a connection class
    whose constructor dials with a timeout and whose other methods
    read.
  * ``.recv(...)`` / ``.recv_into(...)`` calls, and ``.connect(...)``
    on a socket-named receiver, are flagged under the same scope rule —
    a read helper in a class that never configures a timeout is exactly
    the stranded-thread shape.

``settimeout(None)`` clears the check too: deliberately blocking IO is
allowed, but it must be DECLARED, not inherited silently from the
socket default.  The intentional blocking accept-loop listeners
(p2p/abci/rpc/privval) are suppressed via justified allowlist entries
per policy — an accept loop woken by ``netutil.close_socket``'s
shutdown() is a reviewed pattern, not an accident.
"""

from __future__ import annotations

import ast

from .linter import Finding, Module, dotted_name, keyword_names, terminal_name

CHECK_ID = "socket-without-timeout"
SUMMARY = "socket created or read without a configured timeout in scope"

_RECV_NAMES = ("recv", "recv_into")
_CONFIG_NAMES = ("settimeout", "setdefaulttimeout")
_SOCKY = ("sock", "listener", "conn")


def _has_timeout_arg(call: ast.Call) -> bool:
    """create_connection((host, port), timeout) / timeout= kw."""
    return len(call.args) >= 2 or "timeout" in keyword_names(call)


def _configures_timeout(scope: ast.AST) -> bool:
    for n in ast.walk(scope):
        if not isinstance(n, ast.Call):
            continue
        t = terminal_name(n.func)
        if t in _CONFIG_NAMES:
            return True
        if t == "create_connection" and _has_timeout_arg(n):
            return True
    return False


def _receiver_is_socky(call: ast.Call) -> bool:
    """``x.connect(...)`` where x's terminal name smells like a socket —
    keeps sqlite3.connect / pg.connect / db-handle false positives out
    while still catching ``self._sock.connect(...)``."""
    if not isinstance(call.func, ast.Attribute):
        return False
    recv = terminal_name(call.func.value)
    if recv is None:
        return False
    low = recv.lower()
    return any(s in low for s in _SOCKY)


def check(mod: Module) -> list[Finding]:
    findings: list[Finding] = []
    clears: dict[int, bool] = {}  # id(scope node) -> configures a timeout

    def cleared(stack: list[ast.AST]) -> bool:
        for scope in stack:
            key = id(scope)
            if key not in clears:
                clears[key] = _configures_timeout(scope)
            if clears[key]:
                return True
        return False

    def visit(node: ast.AST, stack: list[ast.AST]) -> None:
        if isinstance(
            node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)
        ):
            stack = stack + [node]
        if isinstance(node, ast.Call):
            dn = dotted_name(node.func)
            t = terminal_name(node.func)
            msg = None
            if dn == "socket.socket":
                msg = (
                    "socket.socket(...) with no settimeout() in the "
                    "enclosing function/class — an unbounded blocking "
                    "socket; declare the timeout (settimeout(None) if "
                    "blocking is intended)"
                )
            elif t == "create_server" and (
                dn is None or dn.startswith("socket.")
            ):
                msg = (
                    "socket.create_server(...) listener with no "
                    "settimeout() in scope — accept() will block "
                    "unboundedly; set a poll timeout or allowlist the "
                    "intentional blocking accept loop"
                )
            elif t == "create_connection" and not _has_timeout_arg(node):
                msg = (
                    "socket.create_connection(...) without a timeout "
                    "argument — the dial can hang a thread forever"
                )
            elif t in _RECV_NAMES and isinstance(node.func, ast.Attribute):
                msg = (
                    f".{t}(...) with no timeout configured in the "
                    "enclosing function/class — a dead peer strands "
                    "this thread; settimeout() first (None if blocking "
                    "is deliberate)"
                )
            elif t == "connect" and _receiver_is_socky(node):
                msg = (
                    ".connect(...) on a socket with no timeout "
                    "configured in scope — the dial can hang forever"
                )
            if msg is not None and not cleared(stack):
                findings.append(
                    Finding(CHECK_ID, mod.path, node.lineno,
                            node.col_offset, msg)
                )
        for child in ast.iter_child_nodes(node):
            visit(child, stack)

    # the stack starts EMPTY (not the module): a settimeout in one
    # class must not launder every other class in the same file
    visit(mod.tree, [])
    return findings
