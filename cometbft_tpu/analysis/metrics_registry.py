"""Check: metrics-via-registry.

Direct construction of ``Counter``/``Gauge``/``Histogram`` from
``utils.metrics`` anywhere outside that module.  PR 2 made the Registry
factories (``registry.counter(...)`` etc.) get-or-create with type- and
bucket-conflict detection precisely because two bare instances exposing
the same series produce an unscrapable ``/metrics``; constructing the
classes directly bypasses that de-duplication.  Import tracking keeps
``collections.Counter`` and friends out of scope — only names actually
imported from the metrics module (or attribute access on an import of
it) are flagged.
"""

from __future__ import annotations

import ast

from .linter import Finding, Module, dotted_name

CHECK_ID = "metrics-via-registry"
SUMMARY = "metric constructed directly instead of via Registry factories"

_CLASSES = {"Counter", "Gauge", "Histogram"}
_EXEMPT_SUFFIX = "utils/metrics.py"


def _metrics_bindings(tree: ast.AST) -> tuple[set[str], set[str]]:
    """(names bound to metric classes, names bound to the metrics module)."""
    class_names: set[str] = set()
    module_names: set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom) and node.module:
            if node.module == "metrics" or node.module.endswith(".metrics") \
                    or node.module.endswith("utils.metrics"):
                for alias in node.names:
                    if alias.name in _CLASSES:
                        class_names.add(alias.asname or alias.name)
            if node.module.endswith("utils") or node.module == "utils":
                for alias in node.names:
                    if alias.name == "metrics":
                        module_names.add(alias.asname or "metrics")
        elif isinstance(node, ast.Import):
            for alias in node.names:
                if alias.name.endswith(".metrics"):
                    module_names.add(alias.asname or alias.name)
    return class_names, module_names


def check(mod: Module) -> list[Finding]:
    if mod.path.endswith(_EXEMPT_SUFFIX):
        return []
    class_names, module_names = _metrics_bindings(mod.tree)
    if not class_names and not module_names:
        return []
    findings: list[Finding] = []
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Call):
            continue
        hit: str | None = None
        if isinstance(node.func, ast.Name) and node.func.id in class_names:
            hit = node.func.id
        elif isinstance(node.func, ast.Attribute) and node.func.attr in _CLASSES:
            base = dotted_name(node.func.value)
            if base in module_names:
                hit = f"{base}.{node.func.attr}"
        if hit is not None:
            findings.append(
                Finding(
                    CHECK_ID, mod.path, node.lineno, node.col_offset,
                    f"direct {hit}(...) construction — use the Registry "
                    "factories (registry.counter/gauge/histogram) so "
                    "declarations de-duplicate and conflicts raise",
                )
            )
    return findings
