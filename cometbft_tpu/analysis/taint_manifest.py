"""Byzantine-input taint manifest: every untrusted-bytes SOURCE, every
SANITIZER, every SINK.

A BFT engine's threat model makes every byte arriving from a peer, an
RPC caller, or a CheckTx envelope attacker-chosen; the reference
codebase encodes that as a pervasive decode-then-``ValidateBasic``
discipline (types/validation.go, consensus/reactor.go Receive).  This
manifest is the machine-checkable registry of that discipline for the
host half of this repo — the analogue of ``kernel_manifest`` for the
device half:

* :data:`SOURCES` — where untrusted bytes enter (reactor ``receive``
  payloads, wire frame readers, CheckTx envelopes, RPC params, on-disk
  documents).  Each row names the entry function, which of its
  parameters (or which calls inside it) carry attacker bytes, and the
  typed-error contract its decoder must honor under the adversarial
  decode gauntlet (tests/test_decode_gauntlet.py).
* :data:`SANITIZER_FUNCS` / :data:`SANITIZER_METHODS` — the calls that
  make a tainted value safe: the wire-level ``validate_*_message``
  validators (types/msg_validation.py), envelope parsers that enforce
  their own length/shape contracts, and ``validate_basic`` methods.
* :data:`SINKS` — calls no tainted value may reach: consensus state
  transitions, pool/store/evidence writes, and the verify-service
  device-dispatch seams.  A sink marked ``validating`` performs its own
  validation internally and is a permitted destination.
* :data:`DECODE_SITES` — the exhaustive map of every proto/envelope
  decode call site in the package to its source (or an explicit trusted
  justification).  ``analysis/taintcheck.py`` re-discovers the sites
  from the AST and diffs both directions, so an unregistered decode
  surface and a stale manifest row are both findings (the
  kernel_manifest JIT_SITES pattern).

Plain data only — importable with no heavy dependencies.
"""

from __future__ import annotations

from dataclasses import dataclass


@dataclass(frozen=True)
class Source:
    """One untrusted-bytes entry point."""

    name: str
    path: str  # repo-relative module path (suffix-matched)
    func: str  # the entry function holding the decode
    #: parameters of ``func`` that arrive attacker-controlled
    tainted_params: tuple[str, ...] = ()
    #: terminal call names inside ``func`` whose results are attacker
    #: bytes (stream readers: conn.read/read_exact/recv)
    tainted_calls: tuple[str, ...] = ()
    #: run the interprocedural dataflow pass from this entry; False for
    #: stream framing and file loads whose contract is bounds + typed
    #: errors rather than sanitize-before-sink (gauntlet still covers
    #: them)
    dataflow: bool = True
    #: exception class names the decode path may raise on malformed
    #: input — the gauntlet's typed-error contract (anything else, or a
    #: hang/unbounded allocation, is a failure)
    errors: tuple[str, ...] = ("ValueError",)
    notes: str = ""


SOURCES: tuple[Source, ...] = (
    # ---------------------------------------------------- p2p reactors
    Source(
        name="consensus-receive",
        path="cometbft_tpu/consensus/reactor.py",
        func="receive",
        tainted_params=("msg_bytes",),
        notes="sanitized by validate_consensus_message + typed "
        "validate_basic at Proposal/Vote/Part conversion",
    ),
    Source(
        name="blocksync-receive",
        path="cometbft_tpu/blocksync/reactor.py",
        func="receive",
        tainted_params=("msg_bytes",),
        notes="sanitized by validate_blocksync_message; blocks "
        "additionally pass Block.validate_basic before pool.add_block",
    ),
    Source(
        name="statesync-receive",
        path="cometbft_tpu/statesync/reactor.py",
        func="receive",
        tainted_params=("msg_bytes",),
        notes="sanitized by validate_statesync_message",
    ),
    Source(
        name="mempool-receive",
        path="cometbft_tpu/mempool/reactor.py",
        func="receive",
        tainted_params=("msg_bytes",),
        notes="sanitized by validate_mempool_message; check_tx is a "
        "validating sink (size caps + app CheckTx)",
    ),
    Source(
        name="evidence-receive",
        path="cometbft_tpu/evidence/reactor.py",
        func="receive",
        tainted_params=("msg_bytes",),
        notes="sanitized by validate_evidence_list; add_evidence is a "
        "validating sink (ev.validate_basic + verify)",
    ),
    Source(
        name="pex-receive",
        path="cometbft_tpu/p2p/pex/reactor.py",
        func="receive",
        tainted_params=("msg_bytes",),
        notes="sanitized by validate_pex_message (addr count cap + "
        "id@host:port shape)",
    ),
    # ------------------------------------------------- p2p wire framing
    Source(
        name="p2p-packet",
        path="cometbft_tpu/p2p/conn/connection.py",
        func="_read_packet",
        tainted_calls=("read",),
        dataflow=False,
        errors=("ValueError", "ConnectionError"),
        notes="length prefix bounded by MAX_PACKET_WIRE_SIZE before "
        "read_exact; stream reassembly bounded by recv_message_capacity; "
        "a truncated stream is ConnectionError by contract",
    ),
    Source(
        name="secretconn-frame",
        path="cometbft_tpu/p2p/conn/secret_connection.py",
        func="read",
        tainted_calls=("_read_exact",),
        dataflow=False,
        errors=("SecretConnectionError",),
        notes="fixed 1044-byte sealed frames; AEAD-authenticated before "
        "the length field is trusted; length bounded by DATA_MAX_SIZE",
    ),
    Source(
        name="nodeinfo-handshake",
        path="cometbft_tpu/p2p/transport.py",
        func="_exchange_node_info",
        tainted_calls=("read_exact",),
        errors=(
            "ValueError",
            "TransportError",
            "NodeInfoError",
            "SecretConnectionError",  # truncation surfaces from the conn
        ),
        notes="length prefix bounded by MAX_NODE_INFO_SIZE before "
        "read_exact; NodeInfo.validate_basic sanitizes the result",
    ),
    # --------------------------------------------- verify-plane framing
    Source(
        name="verifysvc-frame",
        path="cometbft_tpu/verifysvc/wire.py",
        func="_try_decode",
        tainted_params=("self",),
        dataflow=False,
        notes="FrameReader bounds the varint length against max_frame "
        "before buffering the payload",
    ),
    Source(
        name="checktx-envelope",
        path="cometbft_tpu/verifysvc/checktx.py",
        func="verify_tx_signature",
        tainted_params=("tx",),
        notes="parse_signed_tx is the sanitizer: fixed-width envelope "
        "slices per key type; malformed envelopes return None "
        "(pass-through-unsigned) and never reach submit()",
    ),
    # -------------------------------------------------- ABCI tx payloads
    Source(
        name="kvstore-validator-tx",
        path="cometbft_tpu/abci/kvstore.py",
        func="parse_validator_tx",
        tainted_params=("tx",),
        dataflow=False,
        notes="the PR-8 lesson: parse_validator_tx IS the sanitizer — "
        "base64(validate=True), power >= 0, ed25519 pubkey length "
        "pinned to 32 before any validator update is emitted",
    ),
    # ------------------------------------------------------ ABCI framing
    Source(
        name="abci-server-frame",
        path="cometbft_tpu/abci/server.py",
        func="_handle_conn",
        tainted_calls=("recv",),
        dataflow=False,
        notes="length-delimited Request frames; malformed prefix or "
        "frame answers an exception response and drops the connection",
    ),
    Source(
        name="abci-client-frame",
        path="cometbft_tpu/abci/client.py",
        func="_recv_routine",
        tainted_calls=("recv",),
        dataflow=False,
        errors=("ValueError", "ClientError"),
        notes="app responses; slices bounded by buffered bytes",
    ),
    # ------------------------------------------------ proof-serving plane
    Source(
        name="verifysvc-proof-request",
        path="cometbft_tpu/verifysvc/wire.py",
        func="validate_proof_request",
        tainted_params=("req",),
        dataflow=False,
        notes="the ONE gate between a decoded ProofRequest and the proof "
        "data plane: tree/index bounds checked BEFORE any struct.pack, "
        "digest recomputed; only ValueError escapes (the server answers "
        "it as bad_request)",
    ),
    # -------------------------------------------------------- RPC surface
    Source(
        name="rpc-merkle-proof",
        path="cometbft_tpu/rpc/core.py",
        func="merkle_proof",
        tainted_params=("height", "indices"),
        errors=("ValueError", "RPCError"),
        notes="JSON-RPC proof fan-out: height/indices parse to bounded "
        "ints (count capped by COMETBFT_TPU_PROOF_QUERY_MAX, every index "
        "bounds-checked against the block's tx count) before any leaf "
        "hashing or service submit",
    ),
    Source(
        name="rpc-broadcast-evidence",
        path="cometbft_tpu/rpc/core.py",
        func="broadcast_evidence",
        tainted_params=("evidence",),
        errors=("ValueError", "RPCError"),
        notes="base64 proto evidence from a JSON-RPC caller; "
        "pool.add_evidence is the validating sink",
    ),
    Source(
        name="rpc-services-frame",
        path="cometbft_tpu/rpc/services.py",
        func="_serve_conn",
        tainted_calls=("read",),
        dataflow=False,
        notes="_read_frame bounds the varint length against _MAX_MSG; "
        "handler payload decodes answer errors in-band",
    ),
    # ---------------------------------------------------- privval framing
    Source(
        name="privval-frame",
        path="cometbft_tpu/privval/signer.py",
        func="_recv_msg",
        tainted_calls=("read",),
        errors=("ValueError", "RemoteSignerError"),
        dataflow=False,
        notes="length prefix bounded by MAX_PRIVVAL_MSG_SIZE before the "
        "read loop allocates",
    ),
    # ------------------------------------------------ block reassembly
    Source(
        name="block-assembly",
        path="cometbft_tpu/consensus/state.py",
        func="_add_proposal_block_part",
        dataflow=False,
        notes="Block.decode over assemble()d parts: every part's merkle "
        "proof was verified against the proposal's PartSetHeader hash "
        "in PartSet.add_part, so the bytes are proposer-committed; "
        "decode errors surface as ValueError to the receive wrapper",
    ),
    # ------------------------------------------------------- file loads
    Source(
        name="wal-replay",
        path="cometbft_tpu/consensus/wal.py",
        func="decode_records",
        tainted_params=("buf",),
        dataflow=False,
        errors=("CorruptWALError",),
        notes="CRC + length-bounded records; every malformation is "
        "CorruptWALError so replay can repair the tail",
    ),
    Source(
        name="genesis-file",
        path="cometbft_tpu/types/genesis.py",
        func="from_json",
        tainted_params=("data",),
        dataflow=False,
        notes="operator-supplied JSON; every malformation (missing key, "
        "type confusion, bad hex) is re-raised as ValueError and "
        "validate_and_complete gates the result",
    ),
    Source(
        name="addrbook-file",
        path="cometbft_tpu/p2p/pex/addrbook.py",
        func="_load",
        dataflow=False,
        notes="on-disk JSON built from gossip; corrupt documents raise "
        "ValueError, records re-enter through add_address",
    ),
    # ----------------------------------------------------- light client
    Source(
        name="light-proof",
        path="cometbft_tpu/light/rpc.py",
        func="abci_query",
        dataflow=False,
        errors=("VerificationFailed", "ValueError"),
        notes="untrusted provider's proof ops; the whole parse is "
        "wrapped fail-closed into VerificationFailed (the inner proto "
        "decode raises ValueError)",
    ),
)


#: Free functions whose return value is SAFE given tainted arguments —
#: they validate internally and raise (or return None) on garbage.
SANITIZER_FUNCS = frozenset(
    {
        "validate_consensus_message",
        "validate_blocksync_message",
        "validate_statesync_message",
        "validate_mempool_message",
        "validate_pex_message",
        "validate_evidence_list",
        "validate_peer_address",
        "parse_signed_tx",
        "parse_validator_tx",
    }
)

#: Method names that sanitize their receiver: ``x.validate_basic()``
#: makes ``x`` safe (raising on garbage), per the reference's
#: ValidateBasic contract.
SANITIZER_METHODS = frozenset({"validate_basic", "validate_and_complete"})


@dataclass(frozen=True)
class Sink:
    """A call no tainted value may reach (terminal attribute name)."""

    name: str
    #: the sink validates its arguments internally — tainted values are
    #: permitted to reach it, with the justification recorded here
    validating: bool = False
    reason: str = ""


SINKS: tuple[Sink, ...] = (
    # consensus state transitions (consensus/state.py)
    Sink("set_proposal"),
    Sink("add_vote"),
    Sink("add_proposal_block_part"),
    # blocksync pool feeds (blocksync/pool.py)
    Sink("add_block"),
    Sink("set_peer_range"),
    # statesync pool feeds (statesync/syncer.py)
    Sink("add_snapshot"),
    Sink("add_chunk"),
    # address book writes (p2p/pex/addrbook.py)
    Sink("add_address"),
    # state/execution apply + store writes
    Sink("apply_block"),
    Sink("save_block"),
    # verify-service device-dispatch seams (verifysvc/service.py,
    # models/*verifier add()/submit())
    Sink("submit"),
    Sink("add_evidence", validating=True,
         reason="EvidencePool.add_evidence runs ev.validate_basic() + "
                "full verification before persisting"),
    Sink("check_tx", validating=True,
         reason="CListMempool.check_tx enforces max_tx_bytes, cache "
                "dedup, signature admission, and the app's CheckTx"),
)

SINK_NAMES = frozenset(s.name for s in SINKS)
VALIDATING_SINKS = frozenset(s.name for s in SINKS if s.validating)

#: Call results that stay untainted even with tainted arguments:
#: fixed-range scalars (sizes, predicates), not attacker-shaped data.
UNTAINTING_BUILTINS = frozenset(
    {"len", "bool", "isinstance", "hash", "id", "monotonic", "time"}
)


# ------------------------------------------------------------ decode map

#: Every proto/envelope decode call site in the package, keyed
#: ``"path::enclosing-function"``, mapped to its Source name or an
#: explicit ``"trusted: <why>"`` justification.  taintcheck re-discovers
#: the sites syntactically and diffs both directions.
DECODE_SITES: dict[str, str] = {
    # ------------------------------------------------- wire surfaces
    "cometbft_tpu/consensus/reactor.py::receive": "consensus-receive",
    "cometbft_tpu/blocksync/reactor.py::receive": "blocksync-receive",
    "cometbft_tpu/statesync/reactor.py::receive": "statesync-receive",
    "cometbft_tpu/mempool/reactor.py::receive": "mempool-receive",
    "cometbft_tpu/evidence/reactor.py::receive": "evidence-receive",
    "cometbft_tpu/p2p/pex/reactor.py::receive": "pex-receive",
    "cometbft_tpu/p2p/conn/connection.py::_read_packet": "p2p-packet",
    "cometbft_tpu/p2p/transport.py::_exchange_node_info": "nodeinfo-handshake",
    "cometbft_tpu/verifysvc/wire.py::_try_decode": "verifysvc-frame",
    "cometbft_tpu/verifysvc/checktx.py::verify_tx_signature": "checktx-envelope",
    "cometbft_tpu/abci/server.py::_handle_conn": "abci-server-frame",
    "cometbft_tpu/abci/client.py::_recv_routine": "abci-client-frame",
    "cometbft_tpu/privval/signer.py::_recv_msg": "privval-frame",
    "cometbft_tpu/consensus/state.py::_add_proposal_block_part": "block-assembly",
    "cometbft_tpu/light/rpc.py::abci_query": "light-proof",
    # ------------------------------------------------- ABCI tx payloads
    "cometbft_tpu/abci/kvstore.py::check_tx": "kvstore-validator-tx",
    "cometbft_tpu/abci/kvstore.py::process_proposal": "kvstore-validator-tx",
    "cometbft_tpu/abci/kvstore.py::finalize_block": "kvstore-validator-tx",
    # ------------------------------------------------------ RPC surface
    "cometbft_tpu/rpc/core.py::broadcast_evidence": "rpc-broadcast-evidence",
    "cometbft_tpu/rpc/services.py::_serve_conn": "rpc-services-frame",
    "cometbft_tpu/rpc/services.py::_get_by_height": "rpc-services-frame",
    "cometbft_tpu/rpc/services.py::_get_block_results": "rpc-services-frame",
    "cometbft_tpu/rpc/services.py::_set_block_retain": "rpc-services-frame",
    "cometbft_tpu/rpc/services.py::_set_block_results_retain": "rpc-services-frame",
    "cometbft_tpu/rpc/services.py::_set_tx_indexer_retain": "rpc-services-frame",
    "cometbft_tpu/rpc/services.py::_set_block_indexer_retain": "rpc-services-frame",
    # client side of the block/pruning service: responses from the node
    # we dialed; still length-bounded and decoded under the same codec
    "cometbft_tpu/rpc/services.py::_call": "rpc-services-frame",
    "cometbft_tpu/rpc/services.py::get_by_height": "rpc-services-frame",
    "cometbft_tpu/rpc/services.py::latest_height_stream": "rpc-services-frame",
    "cometbft_tpu/rpc/services.py::get_block_results": "rpc-services-frame",
    "cometbft_tpu/rpc/services.py::get_version": "rpc-services-frame",
    "cometbft_tpu/rpc/services.py::get_block_retain_height": "rpc-services-frame",
    "cometbft_tpu/rpc/services.py::get_block_results_retain_height": "rpc-services-frame",
    "cometbft_tpu/rpc/services.py::get_tx_indexer_retain_height": "rpc-services-frame",
    "cometbft_tpu/rpc/services.py::get_block_indexer_retain_height": "rpc-services-frame",
    # ------------------------------------------------------- file loads
    "cometbft_tpu/consensus/wal.py::decode_records": "wal-replay",
    "cometbft_tpu/consensus/wal.py::iter_records": "wal-replay",
    # -------------------------------------------------- trusted locals
    # Our own DB bytes: written by this process via the store layer;
    # corruption is a crash-worthy operator problem, not peer input.
    "cometbft_tpu/store/block_store.py::load_block_meta": "trusted: local block DB",
    "cometbft_tpu/store/block_store.py::load_block": "trusted: local block DB",
    "cometbft_tpu/store/block_store.py::load_block_part": "trusted: local block DB",
    "cometbft_tpu/store/block_store.py::load_block_commit": "trusted: local block DB",
    "cometbft_tpu/store/block_store.py::load_seen_commit": "trusted: local block DB",
    "cometbft_tpu/store/block_store.py::load_block_extended_commit": "trusted: local block DB",
    "cometbft_tpu/state/store.py::load": "trusted: local state DB",
    "cometbft_tpu/state/store.py::load_validators": "trusted: local state DB",
    "cometbft_tpu/state/store.py::load_consensus_params": "trusted: local state DB",
    "cometbft_tpu/state/store.py::load_finalize_block_response": "trusted: local state DB",
    "cometbft_tpu/light/store.py::light_block": "trusted: local light-client DB; blocks were verified before store",
    "cometbft_tpu/light/store.py::latest_light_block": "trusted: local light-client DB; blocks were verified before store",
    "cometbft_tpu/light/store.py::first_light_block": "trusted: local light-client DB; blocks were verified before store",
    "cometbft_tpu/light/store.py::light_block_before": "trusted: local light-client DB; blocks were verified before store",
    "cometbft_tpu/evidence/pool.py::evidence_from_proto_bytes": "trusted: local evidence DB reload; wire entry is add_evidence",
    "cometbft_tpu/privval/file_pv.py::_only_differ_by_timestamp": "trusted: local last-sign state file written by this process",
    "cometbft_tpu/types/block.py::decode": "trusted: codec helper; untrusted callers are registered at their own sites",
    "cometbft_tpu/e2e/firehose.py::_storm_pool": "trusted: in-process load generator parsing its own generated txs",
}


def site_registered(path: str, func: str) -> str | None:
    """The DECODE_SITES entry for a discovered site, suffix-matching the
    path the same way the allowlist does (absolute or repo-relative
    invocations must resolve identically)."""
    key_tail = f"{path}::{func}"
    for key, val in DECODE_SITES.items():
        if key_tail == key or key_tail.endswith("/" + key):
            return val
    return None


def source_by_name(name: str) -> Source | None:
    for s in SOURCES:
        if s.name == name:
            return s
    return None


def dataflow_sources() -> tuple[Source, ...]:
    return tuple(s for s in SOURCES if s.dataflow)


def gauntlet_sources() -> tuple[Source, ...]:
    """Every source the adversarial decode gauntlet must cover."""
    return SOURCES
