"""Runtime lock-order witness — the dynamic half of the analysis pass.

When installed (``COMETBFT_TPU_LOCKCHECK=1`` via :func:`maybe_install`,
or unconditionally via :func:`install` — the test conftest does the
latter), ``threading.Lock``/``threading.RLock`` construction is wrapped
so every acquisition feeds a per-process *acquisition-order graph*:
holding lock A while acquiring lock B records the edge A→B, with the
full stack captured the first time each edge appears.  Two detectors
run on that graph:

* **order cycle**: recording an edge that closes a cycle (the classic
  A→B vs B→A inversion, any length) means two threads can deadlock;
  the violation carries the stack that recorded the new edge AND the
  stacks stored for every edge on the pre-existing return path.  Edges
  are recorded when a blocking acquire is *attempted*, not when it
  succeeds — so an inversion that is actually deadlocking right now
  still reports (both threads are parked inside the inner acquire and
  would never reach a post-acquire hook).

* **blocking while locked**: ``time.sleep`` called while the thread
  holds any witnessed lock — the runtime mirror of the static
  ``lock-held-across-blocking-call`` check, catching locks the lexical
  naming heuristic can't see.

Violations are recorded (:func:`violations`) and printed to stderr once
each; ``COMETBFT_TPU_LOCKCHECK=raise`` raises in the acquiring thread
instead, for pinpointing in a debugger.  The witness never takes any
lock other than its own private raw mutex, so it cannot deadlock the
program it watches.

Nodes are lock *instances* (labelled by creation site), not creation
sites: a reported cycle involves the very same objects acquired in
inverted order — no site-aliasing false positives, at the cost of not
generalizing across instances the way kernel lockdep does.

Locks created *before* :func:`install` are invisible; install early
(the conftest installs before any ``cometbft_tpu`` import).  ``RLock``
wrappers implement the ``_release_save``/``_acquire_restore``/
``_is_owned`` protocol so ``threading.Condition`` (and therefore
``queue.Queue``) keeps the held-set bookkeeping exact across ``wait()``.
"""

from __future__ import annotations

import sys
import threading
import traceback
import weakref
from dataclasses import dataclass, field

# Bool spellings for the raw COMETBFT_TPU_LOCKCHECK read (maybe_install
# here, and tests/conftest.py): must stay identical to envknobs._TRUE/
# _FALSE, which this module cannot import (install must precede the
# registry's import closure) — a test asserts the two stay in sync.
TRUE_SPELLINGS = frozenset({"1", "true", "yes", "on"})
FALSE_SPELLINGS = frozenset({"0", "false", "no", "off"})

# raw mutex allocated before any patching can occur; the witness's own
# state is guarded by an UNwitnessed lock by construction
_state_mtx = threading.Lock()
_tls = threading.local()

_installed = False
_raise_on_violation = False
_orig_lock = None
_orig_rlock = None
_orig_sleep = None

_edges: dict[int, set[int]] = {}  # adjacency: lock id -> set of lock ids
_edge_stacks: dict[tuple[int, int], str] = {}
_names: dict[int, str] = {}  # lock id -> creation site "file:line"
_violations: list["Violation"] = []
_violations_dropped = 0
_sleep_seen: set[tuple[str, str]] = set()  # (lock site, sleep site) dedup
_MAX_VIOLATIONS = 200  # a long-lived node must not grow stacks unboundedly


@dataclass
class Violation:
    kind: str  # "order-cycle" | "blocking-while-locked"
    message: str
    stacks: list[str] = field(default_factory=list)  # labelled stacks

    def render(self) -> str:
        out = [f"[lockwitness:{self.kind}] {self.message}"]
        out.extend(self.stacks)
        return "\n".join(out)


# ------------------------------------------------------------- internals

def _site(depth_hint: int = 2) -> str:
    """file:line of the nearest caller frame outside this module."""
    f = sys._getframe(depth_hint)
    while f is not None and f.f_code.co_filename == __file__:
        f = f.f_back
    if f is None:
        return "<unknown>"
    return f"{f.f_code.co_filename}:{f.f_lineno}"


def _held() -> list:
    h = getattr(_tls, "held", None)
    if h is None:
        h = _tls.held = []
    return h


def _emit(v: Violation) -> None:
    global _violations_dropped
    if len(_violations) >= _MAX_VIOLATIONS:
        _violations_dropped += 1
        if _raise_on_violation:
            raise RuntimeError(v.render())
        return
    _violations.append(v)
    try:
        print(v.render(), file=sys.stderr)
    except (OSError, ValueError):  # closed/broken stderr — keep the record
        pass
    if _raise_on_violation:
        raise RuntimeError(v.render())


def _find_path(src: int, dst: int) -> list[int] | None:
    """DFS over _edges; caller holds _state_mtx."""
    stack = [(src, [src])]
    seen = {src}
    while stack:
        node, path = stack.pop()
        if node == dst:
            return path
        for nxt in _edges.get(node, ()):
            if nxt not in seen:
                seen.add(nxt)
                stack.append((nxt, path + [nxt]))
    return None


def _record_edge(held_lock, new_lock) -> None:
    hid, nid = id(held_lock), id(new_lock)
    key = (hid, nid)
    # lock-free fast path for the steady state (edge already known):
    # a GIL-atomic dict read; a racy miss just takes the slow path,
    # which re-checks under the mutex.  Keeps the expensive stack
    # capture off every nested acquisition after the first.
    if key in _edge_stacks:
        return
    here = "".join(traceback.format_stack(sys._getframe(2)))
    violation = None
    with _state_mtx:
        _flush_dead()
        if key in _edge_stacks:
            return
        # does nid already reach hid?  then hid -> nid closes a cycle
        path = _find_path(nid, hid)
        _edge_stacks[key] = here
        _edges.setdefault(hid, set()).add(nid)
        if path is not None:
            cyc = path + [nid]
            labels = " -> ".join(_names.get(i, f"<lock {i:#x}>") for i in cyc)
            stacks = [
                f"--- stack recording new edge "
                f"{_names.get(hid, hex(hid))} -> {_names.get(nid, hex(nid))} "
                f"(this thread, {threading.current_thread().name}):\n{here}"
            ]
            for a, b in zip(path, path[1:]):
                st = _edge_stacks.get((a, b))
                if st:
                    stacks.append(
                        f"--- stack that recorded prior edge "
                        f"{_names.get(a, hex(a))} -> {_names.get(b, hex(b))}:"
                        f"\n{st}"
                    )
            violation = Violation(
                "order-cycle",
                f"lock acquisition order cycle: {labels} (potential "
                "deadlock between these call sites)",
                stacks,
            )
    if violation is not None:
        _emit(violation)


def _note_attempt(wl) -> None:
    """Record an order edge from every held lock to ``wl``."""
    held = getattr(_tls, "held", None)
    if held:
        wid = id(wl)
        for h, _s in held:
            if id(h) != wid:
                _record_edge(h, wl)


def _remove_held(lst: list, wl) -> None:
    for i in range(len(lst) - 1, -1, -1):
        if lst[i][0] is wl:
            del lst[i]
            return


def _note_release(wl) -> None:
    held = getattr(_tls, "held", None)
    if held:
        _remove_held(held, wl)


_dead: list[int] = []


def _prune(lock_id: int) -> None:
    """Queue a GC'd lock for removal from the graph.  CPython recycles
    object ids, so keeping a dead lock's edges could alias them onto a
    newly allocated lock and fabricate a cycle no live pair can form.

    This is a weakref.finalize callback: it can fire during ANY
    allocation, including one made while _state_mtx is already held by
    this very thread — so it must only do a lock-free list append; the
    actual graph surgery happens in _flush_dead under the mutex."""
    _dead.append(lock_id)


def _flush_dead() -> None:
    """Apply queued prunes.  Caller holds _state_mtx."""
    while _dead:
        lock_id = _dead.pop()
        _names.pop(lock_id, None)
        _edges.pop(lock_id, None)
        for dsts in _edges.values():
            dsts.discard(lock_id)
        for key in [k for k in _edge_stacks if lock_id in k]:
            del _edge_stacks[key]


class _WitnessLock:
    __slots__ = ("_inner", "_held_in", "__weakref__")

    def __init__(self, inner):
        self._inner = inner
        self._held_in = None  # the held-list the last acquire landed in
        with _state_mtx:
            _flush_dead()  # creation ~ death rate: keeps churn bounded
            kind = type(self).__name__.replace("_Witness", "")
            _names[id(self)] = f"{kind}@{_site()}"
        weakref.finalize(self, _prune, id(self))

    def acquire(self, blocking: bool = True, timeout: float = -1):
        # Order edges are recorded BEFORE a blocking acquire: when an
        # inversion is deadlocking RIGHT NOW, both threads are parked
        # inside inner.acquire, so a post-acquire note would never run
        # and the one run that most needs the report would hang
        # silently.  The attempt establishes the order (kernel lockdep
        # semantics); in raise mode the cycle raises before the lock
        # is touched, so there is nothing to hand back.
        if blocking:
            _note_attempt(self)
        ok = self._inner.acquire(blocking, timeout)
        if ok:
            if not blocking:
                # try-acquire cannot deadlock; record the order only
                # once it actually holds, handing the lock back if
                # raise mode fires on the recorded edge
                try:
                    _note_attempt(self)
                except BaseException:
                    self._inner.release()
                    raise
            held = _held()
            held.append((self, _site()))
            # remember WHICH thread's held-list the entry went into: a
            # plain Lock may legally be released by a different thread
            # (handoff), and scrubbing the wrong thread's list would
            # leave a phantom hold generating bogus edges forever
            self._held_in = held
        return ok

    def release(self) -> None:
        # scrub bookkeeping BEFORE the inner release: the moment the
        # inner lock frees, a blocked acquirer can run and set
        # self._held_in to ITS list — reading it afterwards would scrub
        # the new owner's entry and leave ours as a phantom hold.
        # (A plain Lock has at most one outstanding hold, so the single
        # slot is exact; double-release finds None and changes nothing.)
        lst = self._held_in
        self._held_in = None
        if lst is not None:
            _remove_held(lst, self)
        self._inner.release()

    def locked(self) -> bool:
        return self._inner.locked()

    def __enter__(self):
        return self.acquire()

    def __exit__(self, *exc) -> None:
        self.release()

    def __getattr__(self, name):
        return getattr(object.__getattribute__(self, "_inner"), name)

    def __repr__(self) -> str:
        return f"<{_names.get(id(self), 'witnessed lock')} {self._inner!r}>"


class _WitnessRLock(_WitnessLock):
    """Adds the Condition protocol so ``Condition(RLock())`` — and
    everything built on it, ``queue.Queue`` included — keeps the
    held-set exact across ``wait()`` (which fully releases and later
    reacquires the underlying lock outside acquire()/release())."""

    __slots__ = ()

    def release(self) -> None:
        # RLock release is owner-thread-only by contract, so the
        # current thread's held-list is always the right one — and the
        # reentrant case needs one entry removed per release, which the
        # base class's single _held_in slot cannot express.
        self._inner.release()
        _note_release(self)

    def _is_owned(self) -> bool:
        return self._inner._is_owned()

    def _release_save(self):
        held = getattr(_tls, "held", None) or []
        count = sum(1 for h, _s in held if h is self)
        for _ in range(count):
            _note_release(self)
        return (self._inner._release_save(), count)

    def _acquire_restore(self, state):
        inner_state, count = state
        self._inner._acquire_restore(inner_state)
        held = _held()
        site = _site()
        for _ in range(count):
            held.append((self, site))
        self._held_in = held


def _witness_sleep(secs):
    held = getattr(_tls, "held", None)
    if held:
        wl, acq_site = held[-1]
        name = _names.get(id(wl), "<lock>")
        f = sys._getframe(1)
        sleep_site = f"{f.f_code.co_filename}:{f.f_lineno}"
        # one report per (lock site, sleep site): a benign recurring
        # backoff loop must not grow _violations (and spam stderr) on
        # every iteration.  GIL-atomic set ops; a racy duplicate emit
        # is harmless.
        key = (name, sleep_site)
        if key not in _sleep_seen:
            _sleep_seen.add(key)
            here = "".join(traceback.format_stack(f))
            _emit(
                Violation(
                    "blocking-while-locked",
                    f"time.sleep({secs!r}) while holding {name} "
                    f"(acquired at {acq_site}) on thread "
                    f"{threading.current_thread().name}",
                    [f"--- sleeping thread stack:\n{here}"],
                )
            )
    return _orig_sleep(secs)


# ------------------------------------------------------------ public API

def install(raise_on_violation: bool = False) -> None:
    """Patch threading.Lock/RLock and time.sleep.  Idempotent."""
    global _installed, _raise_on_violation, _orig_lock, _orig_rlock, _orig_sleep
    if _installed:
        _raise_on_violation = raise_on_violation
        return
    import time as _time

    _orig_lock = threading.Lock
    _orig_rlock = threading.RLock
    _orig_sleep = _time.sleep
    threading.Lock = lambda: _WitnessLock(_orig_lock())
    threading.RLock = lambda: _WitnessRLock(_orig_rlock())
    _time.sleep = _witness_sleep
    _raise_on_violation = raise_on_violation
    _installed = True


def uninstall() -> None:
    """Restore the originals.  Already-created witness locks keep
    working (they wrap real locks); they just stop feeding the graph
    once released, since notes are cheap no-ops on an empty held set."""
    global _installed
    if not _installed:
        return
    import time as _time

    threading.Lock = _orig_lock
    threading.RLock = _orig_rlock
    _time.sleep = _orig_sleep
    _installed = False


def maybe_install() -> bool:
    """Install iff the COMETBFT_TPU_LOCKCHECK knob asks for it
    (production entry points call this; the test conftest installs
    unconditionally).

    The knob is read raw, NOT via utils.envknobs: importing the registry
    executes ``utils/__init__`` (service, logging) BEFORE threading.Lock
    is patched, so any module-level lock those modules ever grow would be
    silently unwitnessed in production while the test conftest (which
    reads raw for the same reason) covers it — coverage drift with no
    signal.  The knob stays declared in the registry for docs/knobs.md;
    TRUE_SPELLINGS mirrors envknobs.get_bool exactly (empty = unset =
    default off)."""
    import os

    raw = os.environ.get("COMETBFT_TPU_LOCKCHECK", "").strip().lower()
    if raw == "raise":
        install(raise_on_violation=True)
        return True
    if raw in TRUE_SPELLINGS:
        install()
        return True
    return False


def installed() -> bool:
    return _installed


def violations() -> list[Violation]:
    with _state_mtx:
        return list(_violations)


def clear() -> None:
    """Drop recorded violations AND the order graph (tests isolate
    scenarios with this; edges from torn-down locks would otherwise
    link unrelated scenarios through recycled ids)."""
    global _violations_dropped
    with _state_mtx:
        _flush_dead()
        _violations.clear()
        _violations_dropped = 0
        _sleep_seen.clear()
        _edges.clear()
        _edge_stacks.clear()
