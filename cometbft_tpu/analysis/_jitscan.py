"""Shared static discovery of jitted code: jit sites, jit roots, and the
module-local traced closure.

Three checks need the same answers from a parsed module — "where are the
``jax.jit`` sites and what do they jit?" (untracked-jit), "which function
bodies end up inside a trace?" (jax-purity, weak-type-literal) — so the
machinery lives here once.  Discovery is purely syntactic:

* a *jit site* is a ``jax.jit``/``jit``/``partial(jax.jit, ...)``
  decorator or call.  A call site's target is the jitted function's own
  name when it is passed by name (``jax.jit(build_a_tables)``,
  ``jax.jit(E.verify_batch)``) and the ENCLOSING function otherwise
  (``jax.jit(shard_map(local))`` inside a factory — the factory is the
  stable, manifest-addressable name).
* *jit roots* are the module-local functions those sites jit, plus
  bodies handed to ``lax`` control flow; checks may seed EXTRA roots
  (``kernel_manifest.traced_roots``) for functions jitted from another
  module, which a per-module scan cannot see.
* the *traced closure* follows same-module calls (and by-reference uses,
  e.g. into ``lax.fori_loop``) transitively from the roots.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass

from .linter import dotted_name, terminal_name

LAX_HOFS = {"fori_loop", "while_loop", "scan", "cond", "switch", "map"}


def is_jit_expr(node: ast.expr) -> bool:
    """jax.jit / jit / partial(jax.jit, ...) / functools.partial(jit, ...)"""
    d = dotted_name(node)
    if d in ("jax.jit", "jit"):
        return True
    if isinstance(node, ast.Call) and terminal_name(node.func) == "partial":
        return bool(node.args) and is_jit_expr(node.args[0])
    return False


@dataclass(frozen=True)
class JitSite:
    """One ``jax.jit`` decorator or call in a module."""

    lineno: int
    col: int
    target: str | None  # manifest-addressable name; None when unresolvable
    via: str  # "decorator" | "call"


class _SiteVisitor(ast.NodeVisitor):
    """Collect jit sites with enclosing-function attribution."""

    def __init__(self) -> None:
        self.sites: list[JitSite] = []
        self._stack: list[str] = []

    def _visit_fn(self, node):
        for dec in node.decorator_list:
            if is_jit_expr(dec):
                self.sites.append(
                    JitSite(dec.lineno, dec.col_offset, node.name, "decorator")
                )
        self._stack.append(node.name)
        self.generic_visit(node)
        self._stack.pop()

    visit_FunctionDef = _visit_fn  # noqa: N815
    visit_AsyncFunctionDef = _visit_fn  # noqa: N815

    def visit_Call(self, node: ast.Call):  # noqa: N802
        if is_jit_expr(node.func):
            target: str | None = None
            if node.args:
                arg = node.args[0]
                if isinstance(arg, (ast.Name, ast.Attribute)):
                    target = terminal_name(arg)
            if target is None and self._stack:
                # composed site (jax.jit(shard_map(local))): the enclosing
                # factory is the registrable name
                target = self._stack[-1]
            self.sites.append(
                JitSite(node.lineno, node.col_offset, target, "call")
            )
        self.generic_visit(node)


def iter_jit_sites(tree: ast.AST) -> list[JitSite]:
    v = _SiteVisitor()
    v.visit(tree)
    return v.sites


def collect_functions(tree: ast.AST) -> dict[str, ast.FunctionDef]:
    funcs: dict[str, ast.FunctionDef] = {}
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # later defs shadow earlier same-named ones; fine for linting
            funcs[node.name] = node
    return funcs


def jit_roots(tree: ast.AST, funcs: dict[str, ast.FunctionDef]) -> set[str]:
    """Module-local functions that are jitted or handed to lax control
    flow — the trace entry points a per-module scan can see."""
    roots: set[str] = set()
    for name, fn in funcs.items():
        if any(is_jit_expr(dec) for dec in fn.decorator_list):
            roots.add(name)
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        if is_jit_expr(node.func):
            for arg in node.args[:1]:
                if isinstance(arg, ast.Name) and arg.id in funcs:
                    roots.add(arg.id)
        tn = terminal_name(node.func)
        if tn in LAX_HOFS:
            d = dotted_name(node.func) or ""
            if d.startswith(("lax.", "jax.lax.")) or d in LAX_HOFS:
                for arg in node.args:
                    if isinstance(arg, ast.Name) and arg.id in funcs:
                        roots.add(arg.id)
    return roots


def call_edges(funcs: dict[str, ast.FunctionDef]) -> dict[str, set[str]]:
    edges: dict[str, set[str]] = {}
    for name, fn in funcs.items():
        callees: set[str] = set()
        for node in ast.walk(fn):
            if isinstance(node, ast.Call):
                tn = terminal_name(node.func)
                if tn in funcs:
                    callees.add(tn)
            elif isinstance(node, ast.Name) and node.id in funcs:
                # passed by reference (e.g. into lax control flow)
                callees.add(node.id)
        callees.discard(name)
        edges[name] = callees
    return edges


def traced_closure(
    tree: ast.AST, extra_roots: set[str] | frozenset[str] = frozenset()
) -> dict[str, ast.FunctionDef]:
    """name -> FunctionDef for every module-local function reachable from
    a jit root (or an extra seed, e.g. a manifest-declared entry point
    jitted from another module) via same-module calls."""
    funcs = collect_functions(tree)
    roots = jit_roots(tree, funcs) | {r for r in extra_roots if r in funcs}
    edges = call_edges(funcs)
    traced: set[str] = set()
    stack = list(roots)
    while stack:
        n = stack.pop()
        if n in traced:
            continue
        traced.add(n)
        stack.extend(edges.get(n, ()))
    return {n: funcs[n] for n in traced}
