"""unbounded-wire-length: a peer-supplied length prefix must be bounds-
checked before it drives a read or an allocation.

The privval lesson: ``n = decode_varint_stream(conn)`` followed by
``conn.read(n - len(buf))`` hands the remote side an arbitrary
allocation — and the read loop's ``while len(buf) < n`` COMPARE is not a
guard, it's the amplifier.  A guard is an ``if`` whose test compares the
length variable and whose body raises, returns, or breaks (the
``if length > MAX_...: raise`` shape every framing site in this repo
uses: transport MAX_NODE_INFO_SIZE, connection MAX_PACKET_WIRE_SIZE,
secret_connection DATA_MAX_SIZE, rpc/services _MAX_MSG, wal
MAX_WAL_MSG_SIZE_BYTES, privval MAX_PRIVVAL_MSG_SIZE).

Flagged: a variable bound from a wire-length decoder
(``decode_varint``/``decode_varint_stream``/``struct.unpack*``) that
reaches a read/recv call argument or a ``bytearray``/``bytes``
allocation in a function with no such guard on it.
"""

from __future__ import annotations

import ast

from .linter import Finding, terminal_name

CHECK_ID = "unbounded-wire-length"
SUMMARY = (
    "wire-decoded length prefix drives a read/allocation with no "
    "bounds check (if-compare + raise/return/break) in the function"
)

#: Calls whose results are wire-supplied integers (length prefixes).
_LENGTH_DECODERS = frozenset(
    {"decode_varint", "decode_varint_stream", "unpack", "unpack_from"}
)

#: Calls where an unbounded length becomes an attacker-sized read or
#: allocation.
_RISKY_CALLS = frozenset({"read", "read_exact", "_read_exact", "recv", "bytearray"})


def _names_in(node: ast.AST) -> set[str]:
    return {n.id for n in ast.walk(node) if isinstance(n, ast.Name)}


def _bound_names(target: ast.expr) -> set[str]:
    out: set[str] = set()
    for n in ast.walk(target):
        if isinstance(n, ast.Name):
            out.add(n.id)
    return out


def _guarded_vars(fn: ast.AST) -> set[str]:
    """Variables some ``if`` in the function compares and then
    raises/returns/breaks on — the bounds-check shape."""
    guarded: set[str] = set()
    for node in ast.walk(fn):
        if not isinstance(node, ast.If):
            continue
        compared: set[str] = set()
        for sub in ast.walk(node.test):
            if isinstance(sub, ast.Compare):
                compared |= _names_in(sub)
        if not compared:
            continue
        if any(
            isinstance(s, (ast.Raise, ast.Return, ast.Break))
            for b in node.body
            for s in ast.walk(b)
        ):
            guarded |= compared
    return guarded


def check(mod) -> list[Finding]:
    findings: list[Finding] = []
    for fn in ast.walk(mod.tree):
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        length_vars: dict[str, int] = {}  # name -> lineno bound
        for node in ast.walk(fn):
            if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
                if terminal_name(node.value.func) in _LENGTH_DECODERS:
                    for tgt in node.targets:
                        for name in _bound_names(tgt):
                            length_vars.setdefault(name, node.lineno)
        if not length_vars:
            continue
        guarded = _guarded_vars(fn)
        unguarded = set(length_vars) - guarded
        if not unguarded:
            continue
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            if terminal_name(node.func) not in _RISKY_CALLS:
                continue
            used = set()
            for a in list(node.args) + [k.value for k in node.keywords]:
                used |= _names_in(a)
            for name in sorted(used & unguarded):
                findings.append(
                    Finding(
                        CHECK_ID,
                        mod.path,
                        node.lineno,
                        node.col_offset,
                        f"wire-decoded length {name!r} (bound at line "
                        f"{length_vars[name]}) drives "
                        f"{terminal_name(node.func)}() with no bounds "
                        "check in the function — cap it before reading/"
                        "allocating (docs/byzantine_inputs.md)",
                    )
                )
    return findings
