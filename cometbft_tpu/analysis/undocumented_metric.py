"""Check: undocumented-metric.

Every metric the Hub registers in ``utils/metrics.py`` must have a row
in ``docs/observability.md``'s metric inventory (a ``| `cometbft_<name>`
| ...`` table row), and every documented row must correspond to a
registered metric.  The inventory is the operator-facing contract — a
series that ships without a row is invisible to whoever builds the
dashboard, and a row whose series was renamed away is worse: it
documents a metric that silently stopped existing.

Scope: registration call sites (``r.counter/gauge/histogram("name",
...)``) inside ``class Hub`` of ``utils/metrics.py``; the staleness
direction additionally accepts any name registered elsewhere in the
module (``NodeMetrics``) so shared rows don't read as stale.  The check
fires only while linting ``utils/metrics.py`` itself — one module, one
documentation diff.
"""

from __future__ import annotations

import ast
import os
import re

from .linter import Finding, Module

CHECK_ID = "undocumented-metric"
SUMMARY = "Hub metric without a docs/observability.md inventory row (or a stale row)"

_TARGET_SUFFIX = "utils/metrics.py"
_DOC_RELPATH = "docs/observability.md"
_FACTORIES = {"counter", "gauge", "histogram"}
_ROW_RE = re.compile(r"^\|\s*`cometbft_([A-Za-z0-9_]+)`")


def _registrations(tree: ast.AST) -> tuple[list[tuple[str, int]], set[str]]:
    """(Hub registrations as (metric name, line), every registered name
    module-wide).  A registration is ``<anything>.counter|gauge|
    histogram("literal", ...)`` — the Registry factory idiom the
    metrics-via-registry check already enforces."""
    hub: list[tuple[str, int]] = []
    everywhere: set[str] = set()
    for cls in ast.walk(tree):
        if not isinstance(cls, ast.ClassDef):
            continue
        for node in ast.walk(cls):
            if not (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in _FACTORIES
                and node.args
                and isinstance(node.args[0], ast.Constant)
                and isinstance(node.args[0].value, str)
            ):
                continue
            everywhere.add(node.args[0].value)
            if cls.name == "Hub":
                hub.append((node.args[0].value, node.lineno))
    return hub, everywhere


def _doc_path(metrics_path: str) -> str:
    # <root>/cometbft_tpu/utils/metrics.py -> <root>/docs/observability.md
    root = os.path.dirname(os.path.dirname(os.path.dirname(metrics_path)))
    return os.path.join(root, *_DOC_RELPATH.split("/"))


def check(mod: Module) -> list[Finding]:
    if not mod.path.endswith(_TARGET_SUFFIX):
        return []
    hub, everywhere = _registrations(mod.tree)
    doc_path = _doc_path(mod.path)
    try:
        with open(doc_path, encoding="utf-8") as f:
            doc_lines = f.read().splitlines()
    except OSError:
        return [
            Finding(
                CHECK_ID, mod.path, 1, 0,
                f"cannot read {_DOC_RELPATH}: the metric inventory the "
                "Hub's series are documented in is missing",
            )
        ]
    documented: dict[str, int] = {}
    for lineno, line in enumerate(doc_lines, 1):
        m = _ROW_RE.match(line)
        if m:
            documented.setdefault(m.group(1), lineno)

    findings: list[Finding] = []
    for name, lineno in hub:
        if name not in documented:
            findings.append(
                Finding(
                    CHECK_ID, mod.path, lineno, 0,
                    f"Hub metric `cometbft_{name}` has no inventory row "
                    f"in {_DOC_RELPATH} — add `| \\`cometbft_{name}\\` | "
                    "type | labels | meaning |`",
                )
            )
    for name, lineno in sorted(documented.items(), key=lambda kv: kv[1]):
        if name not in everywhere:
            findings.append(
                Finding(
                    CHECK_ID, _DOC_RELPATH, lineno, 0,
                    f"stale inventory row: `cometbft_{name}` is not "
                    f"registered anywhere in {_TARGET_SUFFIX}",
                )
            )
    return findings
