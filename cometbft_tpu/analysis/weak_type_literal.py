"""Check: weak-type-literal.

Bare Python literal arithmetic inside a jitted body is how dtype drift
lands: a literal is WEAK-typed, so the result dtype is decided by
promotion rules instead of the kernel author.  This check flags the
dtype-CHANGING cases statically, in the kernel plane's jitted bodies
(found via the same traced-closure scan as jax-purity, seeded with the
manifest's cross-module entry points):

* a float literal in arithmetic (``x * 0.5``) — promotes integer kernel
  data to float, the exact creep the dtype-closure trace gate exists to
  catch, reported here at the offending source line;
* true division ``/`` — produces float whatever the operands; integer
  kernels must use ``//``;
* an int literal outside int32 range — silently wraps under the
  x64-disabled config the kernels are contracted to (or promotes to
  int64 where it isn't).

In-range int literals (``i + 1``, ``total * 8``) are deliberately NOT
findings: a weak int against any strongly-typed array adopts the array's
dtype, which is the intended, deterministic behavior — and the jaxpr
pass double-checks the residue (weak-typed kernel OUTPUTS and forbidden
64-bit dtypes both fail the trace gate).  Statements under
``jax.ensure_compile_time_eval()`` are host-side folding and exempt.
"""

from __future__ import annotations

import ast

from . import kernel_manifest as manifest
from ._jitscan import traced_closure
from .linter import Finding, Module, dotted_name

CHECK_ID = "weak-type-literal"
SUMMARY = "dtype-changing bare literal arithmetic inside a jitted body"

SCOPE_DIRS = {"ops", "parallel", "models"}

_ARITH_OPS = (
    ast.Add, ast.Sub, ast.Mult, ast.Div, ast.FloorDiv, ast.Mod, ast.Pow,
)
_I32_MAX = 2**31 - 1


def _literal(node: ast.expr):
    """The numeric constant under an optional unary +/- , else None."""
    if isinstance(node, ast.UnaryOp) and isinstance(node.op, (ast.USub, ast.UAdd)):
        node = node.operand
    if isinstance(node, ast.Constant) and isinstance(node.value, (int, float)):
        if isinstance(node.value, bool):
            return None
        return node.value
    return None


class _BodyVisitor(ast.NodeVisitor):
    def __init__(self, mod: Module, fn_name: str):
        self.mod = mod
        self.fn_name = fn_name
        self.findings: list[Finding] = []

    def _add(self, node: ast.AST, msg: str) -> None:
        self.findings.append(
            Finding(
                CHECK_ID, self.mod.path, node.lineno, node.col_offset,
                f"{msg} inside jitted body {self.fn_name!r}",
            )
        )

    def visit_With(self, node: ast.With):  # noqa: N802
        for item in node.items:
            d = dotted_name(
                item.context_expr.func
                if isinstance(item.context_expr, ast.Call)
                else item.context_expr
            )
            if d and d.endswith("ensure_compile_time_eval"):
                return  # explicitly-marked host-side constant folding
        self.generic_visit(node)

    def visit_BinOp(self, node: ast.BinOp):  # noqa: N802
        if isinstance(node.op, _ARITH_OPS):
            lits = [_literal(n) for n in (node.left, node.right)]
            for v in lits:
                if isinstance(v, float):
                    self._add(
                        node,
                        f"bare float literal {v!r} in arithmetic — promotes "
                        "to float by weak-type rules; pin it "
                        "(np.float32(...)/jnp.float32(...))",
                    )
                elif isinstance(v, int) and abs(v) > _I32_MAX:
                    self._add(
                        node,
                        f"int literal {v!r} exceeds int32 — wraps under the "
                        "x64-disabled kernel config; restructure or pin an "
                        "explicit wide representation",
                    )
            if (
                isinstance(node.op, ast.Div)
                and None in lits
                and not any(isinstance(v, float) for v in lits)
            ):
                # const/const folds on host; anything else makes floats.
                # A float literal operand was already reported above —
                # one finding per offending line, not two
                self._add(
                    node,
                    "true division '/' produces float whatever the "
                    "operands; integer kernels must use '//'",
                )
        self.generic_visit(node)


def check(mod: Module) -> list[Finding]:
    if not SCOPE_DIRS.intersection(mod.parts[:-1]):
        return []
    findings: list[Finding] = []
    closure = traced_closure(mod.tree, manifest.traced_roots(mod.path))
    for name, fn in closure.items():
        v = _BodyVisitor(mod, name)
        for stmt in fn.body:
            v.visit(stmt)
        findings.extend(v.findings)
    return findings
