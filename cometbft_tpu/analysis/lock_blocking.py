"""Check: lock-held-across-blocking-call.

A ``with <lock>:`` body that performs an unbounded blocking operation —
``join()`` with no timeout, socket ``recv``/``sendall``/``accept``/
``connect``, ``queue.get()``/``Event.wait()``/``Future.result()``
without a timeout, ``time.sleep``, or a device sync
(``block_until_ready``) — serializes every other thread contending for
that lock behind I/O, and is one hung peer away from a deadlock.  The
consensus hot path (VerifyCommit staging, vote routing) runs under
small mutexes; none of them may ever wait on the outside world.

Lock recognition is lexical: a ``with`` context expression whose
terminal identifier contains ``lock``, ``mtx``, or ``mutex`` — the
repo's naming convention, enforced cheaply here.  Nested ``def``/
``lambda`` bodies are skipped (they execute later, not under the lock).
The runtime half of this check is analysis/lockwitness, which catches
``time.sleep`` under any witnessed lock no matter how it was named.
"""

from __future__ import annotations

import ast

from .linter import Finding, Module, dotted_name, keyword_names, terminal_name

CHECK_ID = "lock-held-across-blocking-call"
SUMMARY = "a `with lock:` body calls an unbounded blocking operation"

_LOCK_HINTS = ("lock", "mtx", "mutex")

# attribute calls that block regardless of arguments
_ALWAYS_BLOCKING = {
    "recv", "recvfrom", "recv_into", "sendall", "accept", "connect",
    "block_until_ready",
}
# attribute calls that block only when called with no bounding timeout.
# (`.acquire()` is deliberately absent: nested lock acquisition is the
# lock-order witness's territory, and `with a: with b:` — the same
# shape — can't be flagged here either.)
_NO_TIMEOUT_BLOCKING = {"get", "wait", "result"}


def _is_lockish(expr: ast.expr) -> str | None:
    name = terminal_name(expr)
    if name is None:
        return None
    low = name.lower()
    if any(h in low for h in _LOCK_HINTS):
        return dotted_name(expr) or name
    return None


def _blocking_reason(call: ast.Call) -> str | None:
    func = call.func
    name = terminal_name(func)
    if name is None:
        return None
    if name == "sleep":
        # time.sleep / from time import sleep — jitter-sleep helpers too
        return "sleep()"
    if name == "join":
        # unbounded join() only: str.join always takes an argument, and
        # join(timeout) is bounded
        if not call.args and "timeout" not in keyword_names(call):
            return "join() with no timeout"
        return None
    if name == "select" and len(call.args) < 4:
        return "select() with no timeout"
    if isinstance(func, ast.Attribute):
        if name in _ALWAYS_BLOCKING:
            return f"{name}()"
        if name in _NO_TIMEOUT_BLOCKING:
            kws = keyword_names(call)
            if "timeout" in kws:
                return None
            if not call.args and not kws:
                return f"{name}() with no timeout"
            # get(True) / wait(True) / acquire(True): blocking flag set,
            # still unbounded
            if (
                len(call.args) == 1
                and isinstance(call.args[0], ast.Constant)
                and call.args[0].value is True
            ):
                return f"{name}(True) with no timeout"
    return None


class _Visitor(ast.NodeVisitor):
    def __init__(self, mod: Module):
        self.mod = mod
        self.findings: list[Finding] = []
        self._held: list[tuple[str, int]] = []  # (lock name, acquire line)

    # deferred bodies never run under the enclosing with
    def visit_FunctionDef(self, node):  # noqa: N802
        held, self._held = self._held, []
        self.generic_visit(node)
        self._held = held

    visit_AsyncFunctionDef = visit_FunctionDef
    visit_Lambda = visit_FunctionDef
    visit_ClassDef = visit_FunctionDef

    def visit_With(self, node: ast.With):  # noqa: N802
        n_acquired = 0
        for item in node.items:
            # item N's context expression is evaluated with items 1..N-1
            # (and any enclosing with-locks) already held: a blocking
            # call used AS a context manager — `with lock: with
            # closing(sock.accept()): ...` — blocks right here
            self.visit(item.context_expr)
            lock = _is_lockish(item.context_expr)
            if lock is not None:
                self._held.append((lock, node.lineno))
                n_acquired += 1
        for stmt in node.body:
            self.visit(stmt)
        if n_acquired:
            del self._held[-n_acquired:]

    def visit_Call(self, node: ast.Call):  # noqa: N802
        if self._held:
            reason = _blocking_reason(node)
            if reason is not None:
                lock, line = self._held[-1]
                self.findings.append(
                    Finding(
                        CHECK_ID,
                        self.mod.path,
                        node.lineno,
                        node.col_offset,
                        f"{reason} while holding {lock!r} "
                        f"(acquired line {line})",
                    )
                )
        self.generic_visit(node)


def check(mod: Module) -> list[Finding]:
    v = _Visitor(mod)
    v.visit(mod.tree)
    return v.findings
