"""Limb-range abstract interpreter: prove every field-arithmetic
intermediate overflow-free.

An interval abstract interpreter over the jaxprs of every manifest
kernel (the PR-4 ``kernel_manifest`` trace machinery), propagating
per-element ``[lo, hi]`` bounds through the primitive vocabulary the
kernels actually use.  Two contracts per kernel:

1. **No intermediate exceeds its dtype's safe range** — signed int32
   magnitude (a wrapped carry chain is a wrong verdict), and the 2^24
   exact-integer threshold for every float32 value (the MXU one-hot
   matmul trick is exact only below 2^24, including each partial sum
   of a dot_general contraction).  Unsigned dtypes wrap by design
   (SHA/Keccak mod-2^32 adds) and are modelled, not flagged.
2. **Declared output ranges hold** — canonical limb digits out means
   limb-equality-is-value-equality stays true downstream.

Abstract domain: per-element int64 interval arrays saturating at
``SAT``.  Per-element (not whole-array) bounds are load-bearing: the
ed25519 conv bound is provable only because limb 0's larger fold bound
(<= 14336) multiplies into at most one product per output limb — a
uniform whole-array interval would claim 22*14336^2 ~ 4.5e9 and
falsely flag the kernel.

Loop strategy ladder, per ``scan`` (all repo loops lower to scan —
there is no ``while`` in the vocabulary):

* **fixpoint** — join-iterate the carry until it stabilizes (with
  widening to the dtype range after ``FIXPOINT_MAX_ITERS`` joins);
  accepted when the converged body evaluates finding-free.  Handles
  the long chains (the 255-bit BLS subgroup walk) whose carries are
  re-normalized to canonical digits every iteration.
* **exact unroll** — for static lengths <= ``UNROLL_MAX``: loop
  counters become concrete carries, so dynamic_slice starts concretize
  and Montgomery accumulator windows are tracked exactly (join-fixpoint
  diverges on them by construction).
* **declared invariant** — assume-guarantee via
  ``Kernel.loop_invariants``: seed the carry with the declared bound
  and verify one body evaluation preserves it.
* otherwise the loop is a ``range-contract`` finding.

A small provenance-pattern layer recovers the correlations plain
intervals lose: the carry-round residue ``x - (((x + c) >> k) << k)``
is ``[-c, 2^k - 1 - c]``, and conditional add/sub through a comparison
on the same variable (``d - 16 * (d >= 8)``, ``v + 4096 * (v < 0)``,
``d + (borrow(d) << k)``) evaluates piecewise.

Results are pinned as checked-in certificates
(``analysis/range_fingerprints.json``, kernelcheck drift-gate style:
``scripts/lint.py regen-ranges`` refuses while findings are open) plus
a per-kernel headroom report — bits of slack at the tightest
intermediate and the computed max safe limb width per field (the
ROADMAP item-4 instrument, docs/limb_headroom.md).
"""

from __future__ import annotations

import hashlib
import json
import math
import os
from dataclasses import dataclass

import numpy as np

from . import kernel_manifest as km
from .linter import Finding

#: Finding check ids this pass emits (scripts/lint.py uses these for
#: stale-allowlist accounting, mirroring kernelcheck.FINDING_CHECK_IDS).
FINDING_CHECK_IDS = frozenset(
    {"range-contract", "range-fingerprint", "range-manifest"}
)

RANGE_FINGERPRINTS_PATH = os.path.join(
    os.path.dirname(__file__), "range_fingerprints.json"
)

INT32_MIN, INT32_MAX = -(2**31), 2**31 - 1
F32_EXACT = 2**24  # last exactly-representable contiguous integer in f32
#: Interval saturation cap: far above every contract threshold (2^31,
#: 2^24) and low enough that sums of saturated products stay inside
#: int64 (4096 * 2^40 = 2^52).
SAT = 1 << 40
FIXPOINT_MAX_ITERS = 8
UNROLL_MAX = 96  # sha512's 80-round fori must stay unrollable
DSLICE_ENUM_MAX = 128  # dynamic_slice start-enumeration cap
_MAX_FINDINGS_PER_KERNEL = 8


# ------------------------------------------------------------- intervals


def _np64(x) -> np.ndarray:
    return np.asarray(x, dtype=np.int64)


class IVal:
    """One abstract value: elementwise [lo, hi] int64 bounds + dtype."""

    __slots__ = ("lo", "hi", "dtype")

    def __init__(self, lo, hi, dtype):
        self.lo = _np64(lo)
        self.hi = _np64(hi)
        self.dtype = np.dtype(dtype)

    @property
    def shape(self):
        return self.lo.shape

    def concrete(self) -> bool:
        return bool(np.all(self.lo == self.hi))

    def max_abs(self) -> int:
        if self.lo.size == 0:
            return 0
        return int(max(abs(int(self.lo.min())), abs(int(self.hi.max()))))


def _const_ival(arr, dtype) -> IVal:
    a = np.asarray(arr)
    if a.dtype.kind == "b":
        a = a.astype(np.int64)
    elif a.dtype.kind == "f":
        # float consts in these kernels are integral (one-hot tables);
        # round outward so a non-integral constant stays sound
        lo = _np64(np.floor(a))
        hi = _np64(np.ceil(a))
        return IVal(lo, hi, dtype)
    v = _np64(a)
    return IVal(v, v, dtype)


def _join(a: IVal, b: IVal) -> IVal:
    return IVal(np.minimum(a.lo, b.lo), np.maximum(a.hi, b.hi), a.dtype)


def _contains(outer: IVal, inner: IVal) -> bool:
    return bool(np.all(outer.lo <= inner.lo) and np.all(outer.hi >= inner.hi))


def _dtype_range(dtype) -> tuple[int, int]:
    dt = np.dtype(dtype)
    if dt.kind == "b":
        return 0, 1
    if dt.kind == "u":
        return 0, (1 << (8 * dt.itemsize)) - 1
    if dt.kind == "i":
        b = 8 * dt.itemsize
        return -(1 << (b - 1)), (1 << (b - 1)) - 1
    # floats: the exactness envelope is the only meaningful default
    return -F32_EXACT, F32_EXACT


def _bithull(h: np.ndarray) -> np.ndarray:
    """Smallest all-ones mask >= h (elementwise, h >= 0)."""
    v = _np64(np.maximum(h, 0))
    for s in (1, 2, 4, 8, 16, 32):
        v = v | (v >> s)
    return v


def _sat_mul(x: np.ndarray, y: np.ndarray) -> np.ndarray:
    """Exact product where |x*y| < SAT, +-SAT beyond (elementwise)."""
    pf = x.astype(np.float64) * y.astype(np.float64)
    big = np.abs(pf) >= float(SAT)
    xs = np.where(big, 0, x)
    ys = np.where(big, 0, y)
    exact = xs * ys
    return np.where(big, np.where(pf > 0, SAT, -SAT), exact)


def _mul_bounds(a: IVal, b: IVal) -> tuple[np.ndarray, np.ndarray]:
    c1 = _sat_mul(a.lo, b.lo)
    c2 = _sat_mul(a.lo, b.hi)
    c3 = _sat_mul(a.hi, b.lo)
    c4 = _sat_mul(a.hi, b.hi)
    return (
        np.minimum(np.minimum(c1, c2), np.minimum(c3, c4)),
        np.maximum(np.maximum(c1, c2), np.maximum(c3, c4)),
    )


# ------------------------------------------------------- interpreter state


class _Frame:
    """Per-jaxpr scope: values + defining eqns (for pattern matching)."""

    __slots__ = ("env", "defs")

    def __init__(self):
        self.env: dict = {}
        self.defs: dict = {}


class _Ctx:
    """One kernel interpretation: journal of (stat|finding) events with
    rollback (speculative scan strategies must not leak findings), the
    scan-strategy cache, and the active shard_map mesh sizes."""

    def __init__(self, kernel_name: str, invariants=()):
        self.kernel = kernel_name
        self.events: list = []  # ("finding", msg) | ("stat", cls, v, label)
        self.path: list[str] = []
        self.mesh_sizes: dict[str, int] = {}
        self.cache: dict = {}
        self.cache_refs: list = []  # keep jaxprs alive so id() keys stay valid
        self.eqn_count = 0
        self.scan_ordinal = 0
        self.invariants = {(i[0], i[1]): (i[2], i[3]) for i in invariants}
        self._best = {"int32": 0, "f32": 0}

    def mark(self) -> int:
        return len(self.events)

    def rollback(self, mark: int) -> None:
        del self.events[mark:]
        for cls in self._best:
            self._best[cls] = 0
        for ev in self.events:
            if ev[0] == "stat" and ev[2] > self._best[ev[1]]:
                self._best[ev[1]] = ev[2]

    def finding(self, msg: str) -> None:
        self.events.append(("finding", msg))

    def stat(self, cls: str, value: int, prim: str) -> None:
        if value > self._best[cls]:
            self._best[cls] = value
            self.events.append(
                ("stat", cls, value, f"{'/'.join(self.path) or '.'}:{prim}")
            )

    def label(self, prim: str) -> str:
        return f"{'/'.join(self.path) or '.'}:{prim}"


def _settle(ctx: _Ctx, lo, hi, dtype, prim: str) -> IVal:
    """Normalize a raw transfer result: wrap unsigned, flag+clamp signed
    overflow and f32 exactness loss, saturate, record headroom stats."""
    dt = np.dtype(dtype)
    lo = _np64(lo)
    hi = _np64(hi)
    if dt.kind == "b":
        return IVal(np.clip(lo, 0, 1), np.clip(hi, 0, 1), dt)
    if dt.kind == "u":
        m = 1 << (8 * dt.itemsize)
        span = hi - lo
        lom = lo % m
        him = lom + span
        wide = (span >= m) | (him >= m)
        return IVal(
            np.where(wide, 0, lom), np.where(wide, m - 1, him), dt
        )
    if dt.kind == "f":
        v = int(max(abs(int(lo.min())), abs(int(hi.max())))) if lo.size else 0
        ctx.stat("f32", v, prim)
        if v > F32_EXACT:
            ctx.finding(
                f"f32 exactness: |bound| {v} > 2^24 at {ctx.label(prim)}"
            )
        return IVal(np.clip(lo, -SAT, SAT), np.clip(hi, -SAT, SAT), dt)
    # signed int
    dmin, dmax = _dtype_range(dt)
    v = int(max(abs(int(lo.min())), abs(int(hi.max())))) if lo.size else 0
    ctx.stat("int32", v, prim)
    if lo.size and (int(lo.min()) < dmin or int(hi.max()) > dmax):
        ctx.finding(
            f"{dt.name} overflow: bounds [{int(lo.min())}, {int(hi.max())}] "
            f"exceed [{dmin}, {dmax}] at {ctx.label(prim)}"
        )
        lo = np.clip(lo, dmin, dmax)
        hi = np.clip(hi, dmin, dmax)
    return IVal(lo, hi, dt)


def _out_dtype(eqn):
    return eqn.outvars[0].aval.dtype


def _read(frame: _Frame, atom) -> IVal:
    if hasattr(atom, "val"):  # Literal
        return _const_ival(atom.val, atom.aval.dtype)
    return frame.env[atom]


def _concrete_scalar(frame: _Frame, atom):
    """The concrete integer value of a scalar-or-uniform atom, or None."""
    if hasattr(atom, "val"):
        v = np.asarray(atom.val)
        if v.size and np.all(v.flat[0] == v):
            return int(np.asarray(v.flat[0]).astype(np.int64))
        return None
    iv = frame.env.get(atom)
    if iv is None or not iv.concrete() or iv.lo.size == 0:
        return None
    if np.all(iv.lo.flat[0] == iv.lo):
        return int(iv.lo.flat[0])
    return None


def _peel(frame: _Frame, atom):
    """Follow an atom back through broadcast_in_dim/copy wrappers to the
    var the provenance patterns care about.  Literals (unhashable) are
    returned as-is."""
    seen = 0
    while not hasattr(atom, "val") and atom in frame.defs and seen < 4:
        eqn = frame.defs[atom]
        if eqn.primitive.name in ("broadcast_in_dim", "copy", "squeeze"):
            atom = eqn.invars[0]
            seen += 1
        else:
            break
    return atom

# ------------------------------------------------- provenance patterns
#
# Plain intervals lose correlations between a variable and functions of
# itself.  Three idioms in the field kernels need them back; each match
# INTERSECTS its piecewise bound with the plain transfer (sound both
# ways, tighter together).

_CMP_PRIMS = {"lt", "le", "ge", "gt"}


def _match_def(frame: _Frame, atom, names):
    """The defining eqn of atom when its primitive is in names."""
    atom = _peel(frame, atom)
    if hasattr(atom, "val"):  # Literal: no defining eqn
        return None
    eqn = frame.defs.get(atom)
    if eqn is not None and eqn.primitive.name in names:
        return eqn
    return None


def _const_axes(frame: _Frame, atom, depth: int = 0) -> set:
    """Axes of `atom` along which the value provably does not vary
    (size-1 axes, broadcast-introduced axes, or concrete constants that
    happen to be uniform along the axis)."""
    if hasattr(atom, "val"):
        v = np.asarray(atom.val)
        return {
            i
            for i, s in enumerate(v.shape)
            if s == 1 or (v == np.take(v, [0], axis=i)).all()
        }
    shape = tuple(atom.aval.shape)
    axes = {i for i, s in enumerate(shape) if s == 1}
    iv = frame.env.get(atom)
    if iv is not None and iv.concrete():
        for i, s in enumerate(shape):
            if s > 1 and (iv.lo == np.take(iv.lo, [0], axis=i)).all():
                axes.add(i)
    eqn = frame.defs.get(atom)
    if eqn is not None and depth < 4:
        prim = eqn.primitive.name
        if prim in ("convert_element_type", "copy"):
            axes |= _const_axes(frame, eqn.invars[0], depth + 1)
        elif prim == "broadcast_in_dim":
            bd = eqn.params["broadcast_dimensions"]
            src = eqn.invars[0]
            src_shape = (
                np.shape(src.val)
                if hasattr(src, "val")
                else tuple(src.aval.shape)
            )
            inner = _const_axes(frame, src, depth + 1)
            for d in range(len(shape)):
                if d not in bd:
                    axes.add(d)
                else:
                    i = bd.index(d)
                    if src_shape[i] == 1 or i in inner:
                        axes.add(d)
    return axes


def _distinct_axes(frame: _Frame, atom) -> set:
    """Axes of a CONCRETE `atom` along which every fiber has pairwise-
    distinct values (an iota/arange ramp, possibly broadcast)."""
    if hasattr(atom, "val"):
        v = np.asarray(atom.val)
    else:
        iv = frame.env.get(atom)
        if iv is None or not iv.concrete():
            return set()
        v = iv.lo
    out = set()
    for d, s in enumerate(v.shape):
        if s > 1:
            srt = np.sort(v, axis=d)
            if (np.diff(srt, axis=d) != 0).all():
                out.add(d)
    return out


def _onehot_axes(frame: _Frame, atom, depth: int = 0) -> set:
    """Axes along which `atom` provably has at most one nonzero element,
    all elements in {0, 1}: the one-hot-select idiom
    ``eq(distinct-constant, axis-constant)``, traced through
    convert_element_type and non-replicating broadcast_in_dim.

    This is the relational fact plain intervals lose at every table
    lookup: without it, a 16-entry one-hot matmul is bounded by the
    16x-inflated contraction abs-sum instead of the table entry hull,
    and every downstream conv appears to overflow int32."""
    if hasattr(atom, "val") or depth > 5:
        return set()
    eqn = frame.defs.get(atom)
    if eqn is None:
        return set()
    prim = eqn.primitive.name
    if prim in ("convert_element_type", "copy"):
        return _onehot_axes(frame, eqn.invars[0], depth + 1)
    if prim == "broadcast_in_dim":
        bd = eqn.params["broadcast_dimensions"]
        src = eqn.invars[0]
        src_shape = (
            np.shape(src.val) if hasattr(src, "val") else tuple(src.aval.shape)
        )
        inner = _onehot_axes(frame, src, depth + 1)
        return {
            bd[i]
            for i in inner
            if eqn.params["shape"][bd[i]] == src_shape[i]
        }
    if prim == "eq":
        a, b = eqn.invars
        out = set()
        for x, y in ((a, b), (b, a)):
            out |= _distinct_axes(frame, x) & _const_axes(frame, y)
        return out
    return set()


def _carry_round_bound(frame: _Frame, eqn):
    """sub(x, shl(shra(add(x, c), k), k)) -> [-c, 2^k - 1 - c]."""
    x_atom, y_atom = eqn.invars
    shl = _match_def(frame, y_atom, ("shift_left",))
    if shl is None:
        return None
    k = _concrete_scalar(frame, shl.invars[1])
    if k is None or not (0 < k < 62):
        return None
    shra = _match_def(frame, shl.invars[0], ("shift_right_arithmetic",))
    if shra is None or _concrete_scalar(frame, shra.invars[1]) != k:
        return None
    add = _match_def(frame, shra.invars[0], ("add",))
    if add is None:
        return None
    x_var = _peel(frame, x_atom)
    for xi, ci in ((0, 1), (1, 0)):
        if _peel(frame, add.invars[xi]) is x_var:
            c = _concrete_scalar(frame, add.invars[ci])
            if c is not None:
                return -c, (1 << k) - 1 - c
    return None


def _cond_delta_bound(frame: _Frame, eqn, sign: int):
    """add/sub(v, K * [v cmp C]) evaluated piecewise on the comparison.

    Covers ``d - 16 * (d >= 8)`` (signed radix-16 digits),
    ``v + 4096 * (v < 0)`` (borrow re-add via a compare), and
    ``d + (borrow << k)`` where borrow = shrl(d, 31) [& 1] (borrow
    re-add via the sign bit).  sign is +1 for add, -1 for sub.
    """
    v_atom, w_atom = eqn.invars
    v_var = _peel(frame, v_atom)
    if hasattr(v_var, "val"):  # Literal base: nothing correlated to find
        return None
    v = frame.env.get(v_var)
    if v is None:
        return None

    k_val = None
    cmp_prim = None
    cmp_c = None
    # form A: w = mul(K, convert(cmp(v, C)))  (either operand order)
    mul = _match_def(frame, w_atom, ("mul",))
    if mul is not None:
        for gi, ki in ((0, 1), (1, 0)):
            g = _match_def(frame, mul.invars[gi], ("convert_element_type",))
            kc = _concrete_scalar(frame, mul.invars[ki])
            if g is None or kc is None:
                continue
            cmp_eqn = _match_def(frame, g.invars[0], _CMP_PRIMS)
            if cmp_eqn is None:
                continue
            if _peel(frame, cmp_eqn.invars[0]) is not v_var:
                continue
            c = _concrete_scalar(frame, cmp_eqn.invars[1])
            if c is None:
                continue
            k_val, cmp_prim, cmp_c = kc, cmp_eqn.primitive.name, c
            break
    # form B: w = shift_left(borrow, k), borrow = [and(.,1) of] shrl(v, 31)
    if k_val is None:
        shl = _match_def(frame, w_atom, ("shift_left",))
        if shl is not None:
            ks = _concrete_scalar(frame, shl.invars[1])
            b_atom = shl.invars[0]
            band = _match_def(frame, b_atom, ("and",))
            if band is not None and (
                _concrete_scalar(frame, band.invars[1]) == 1
                or _concrete_scalar(frame, band.invars[0]) == 1
            ):
                b_atom = (
                    band.invars[0]
                    if _concrete_scalar(frame, band.invars[1]) == 1
                    else band.invars[1]
                )
            shrl = _match_def(frame, b_atom, ("shift_right_logical",))
            if (
                ks is not None
                and shrl is not None
                and _peel(frame, shrl.invars[0]) is v_var
                and _concrete_scalar(frame, shrl.invars[1]) == 31
                and np.dtype(v.dtype).itemsize == 4
            ):
                k_val, cmp_prim, cmp_c = 1 << ks, "lt", 0
    if k_val is None:
        return None

    # piecewise: true branch gets +sign*K, false branch +0, on the
    # restriction of v to each side of the comparison
    if cmp_prim == "lt":
        t_lo, t_hi = v.lo, np.minimum(v.hi, cmp_c - 1)
        f_lo, f_hi = np.maximum(v.lo, cmp_c), v.hi
    elif cmp_prim == "le":
        t_lo, t_hi = v.lo, np.minimum(v.hi, cmp_c)
        f_lo, f_hi = np.maximum(v.lo, cmp_c + 1), v.hi
    elif cmp_prim == "ge":
        t_lo, t_hi = np.maximum(v.lo, cmp_c), v.hi
        f_lo, f_hi = v.lo, np.minimum(v.hi, cmp_c - 1)
    else:  # gt
        t_lo, t_hi = np.maximum(v.lo, cmp_c + 1), v.hi
        f_lo, f_hi = v.lo, np.minimum(v.hi, cmp_c)
    d = sign * k_val
    big = np.int64(1) << 62
    t_valid = t_lo <= t_hi
    f_valid = f_lo <= f_hi
    lo = np.minimum(
        np.where(t_valid, t_lo + d, big), np.where(f_valid, f_lo, big)
    )
    hi = np.maximum(
        np.where(t_valid, t_hi + d, -big), np.where(f_valid, f_hi, -big)
    )
    if not bool(np.all(t_valid | f_valid)):
        return None
    return lo, hi


# --------------------------------------------------------------- rules

_RULES: dict = {}


def _rule(name):
    def deco(fn):
        _RULES[name] = fn
        return fn

    return deco


@_rule("add")
def _r_add(ctx, frame, eqn, ins):
    a, b = ins
    lo, hi = a.lo + b.lo, a.hi + b.hi
    pw = _cond_delta_bound(frame, eqn, +1)
    if pw is not None:
        lo, hi = np.maximum(lo, pw[0]), np.minimum(hi, pw[1])
    return [_settle(ctx, lo, hi, _out_dtype(eqn), "add")]


@_rule("sub")
def _r_sub(ctx, frame, eqn, ins):
    a, b = ins
    lo, hi = a.lo - b.hi, a.hi - b.lo
    cr = _carry_round_bound(frame, eqn)
    if cr is not None:
        lo, hi = np.maximum(lo, cr[0]), np.minimum(hi, cr[1])
    pw = _cond_delta_bound(frame, eqn, -1)
    if pw is not None:
        lo, hi = np.maximum(lo, pw[0]), np.minimum(hi, pw[1])
    return [_settle(ctx, lo, hi, _out_dtype(eqn), "sub")]


@_rule("mul")
def _r_mul(ctx, frame, eqn, ins):
    lo, hi = _mul_bounds(*ins)
    return [_settle(ctx, lo, hi, _out_dtype(eqn), "mul")]


@_rule("neg")
def _r_neg(ctx, frame, eqn, ins):
    (a,) = ins
    return [_settle(ctx, -a.hi, -a.lo, _out_dtype(eqn), "neg")]


@_rule("abs")
def _r_abs(ctx, frame, eqn, ins):
    (a,) = ins
    crosses = (a.lo <= 0) & (a.hi >= 0)
    lo = np.where(crosses, 0, np.minimum(np.abs(a.lo), np.abs(a.hi)))
    hi = np.maximum(np.abs(a.lo), np.abs(a.hi))
    return [_settle(ctx, lo, hi, _out_dtype(eqn), "abs")]


@_rule("sign")
def _r_sign(ctx, frame, eqn, ins):
    (a,) = ins
    return [
        _settle(ctx, np.sign(a.lo), np.sign(a.hi), _out_dtype(eqn), "sign")
    ]


@_rule("max")
def _r_max(ctx, frame, eqn, ins):
    a, b = ins
    return [
        _settle(
            ctx,
            np.maximum(a.lo, b.lo),
            np.maximum(a.hi, b.hi),
            _out_dtype(eqn),
            "max",
        )
    ]


@_rule("min")
def _r_min(ctx, frame, eqn, ins):
    a, b = ins
    return [
        _settle(
            ctx,
            np.minimum(a.lo, b.lo),
            np.minimum(a.hi, b.hi),
            _out_dtype(eqn),
            "min",
        )
    ]


@_rule("div")
def _r_div(ctx, frame, eqn, ins):
    a, b = ins

    def tdiv(x, y):
        y = np.where(y == 0, 1, y)
        return (np.abs(x) // np.abs(y)) * np.sign(x) * np.sign(y)

    if bool(np.any((b.lo <= 0) & (b.hi >= 0))):
        # divisor may be zero somewhere: conservative
        m = np.maximum(np.abs(a.lo), np.abs(a.hi))
        return [_settle(ctx, -m, m, _out_dtype(eqn), "div")]
    cands = []
    for x in (a.lo, a.hi):
        for y in (b.lo, b.hi):
            cands.append(tdiv(x, y))
    # a sign change inside the dividend interval adds the 0 quotient
    if bool(np.any((a.lo < 0) & (a.hi > 0))):
        cands.append(np.zeros_like(a.lo))
    lo = cands[0]
    hi = cands[0]
    for c in cands[1:]:
        lo = np.minimum(lo, c)
        hi = np.maximum(hi, c)
    return [_settle(ctx, lo, hi, _out_dtype(eqn), "div")]


@_rule("rem")
def _r_rem(ctx, frame, eqn, ins):
    a, b = ins
    cap = np.maximum(np.maximum(np.abs(b.lo), np.abs(b.hi)) - 1, 0)
    lo = np.where(a.lo >= 0, 0, np.maximum(a.lo, -cap))
    hi = np.where(a.hi <= 0, 0, np.minimum(a.hi, cap))
    return [_settle(ctx, lo, hi, _out_dtype(eqn), "rem")]

def _cmp_bounds(a: IVal, b: IVal, lo_true, hi_true):
    """Generic comparison: lo = 1 when it MUST hold, hi = 1 when it CAN."""
    return _np64(lo_true(a, b)), _np64(hi_true(a, b))


@_rule("lt")
def _r_lt(ctx, frame, eqn, ins):
    a, b = ins
    lo = (a.hi < b.lo).astype(np.int64)
    hi = (a.lo < b.hi).astype(np.int64)
    return [IVal(lo, hi, _out_dtype(eqn))]


@_rule("le")
def _r_le(ctx, frame, eqn, ins):
    a, b = ins
    lo = (a.hi <= b.lo).astype(np.int64)
    hi = (a.lo <= b.hi).astype(np.int64)
    return [IVal(lo, hi, _out_dtype(eqn))]


@_rule("gt")
def _r_gt(ctx, frame, eqn, ins):
    a, b = ins
    lo = (a.lo > b.hi).astype(np.int64)
    hi = (a.hi > b.lo).astype(np.int64)
    return [IVal(lo, hi, _out_dtype(eqn))]


@_rule("ge")
def _r_ge(ctx, frame, eqn, ins):
    a, b = ins
    lo = (a.lo >= b.hi).astype(np.int64)
    hi = (a.hi >= b.lo).astype(np.int64)
    return [IVal(lo, hi, _out_dtype(eqn))]


@_rule("eq")
def _r_eq(ctx, frame, eqn, ins):
    a, b = ins
    both_fixed = (a.lo == a.hi) & (b.lo == b.hi)
    lo = (both_fixed & (a.lo == b.lo)).astype(np.int64)
    overlap = (a.lo <= b.hi) & (b.lo <= a.hi)
    return [IVal(lo, overlap.astype(np.int64), _out_dtype(eqn))]


@_rule("ne")
def _r_ne(ctx, frame, eqn, ins):
    a, b = ins
    both_fixed = (a.lo == a.hi) & (b.lo == b.hi)
    overlap = (a.lo <= b.hi) & (b.lo <= a.hi)
    lo = (~overlap).astype(np.int64)
    hi = (~(both_fixed & (a.lo == b.lo))).astype(np.int64)
    return [IVal(lo, hi, _out_dtype(eqn))]


def _is_boolish(dt) -> bool:
    return np.dtype(dt).kind == "b"


@_rule("and")
def _r_and(ctx, frame, eqn, ins):
    a, b = ins
    dt = _out_dtype(eqn)
    if _is_boolish(dt):
        return [IVal(a.lo & b.lo, a.hi & b.hi, dt)]
    # x & y <= min(x, y) and >= 0 when either side is provably >= 0
    a_nn = a.lo >= 0
    b_nn = b.lo >= 0
    dmin, dmax = _dtype_range(dt)
    lo = np.where(a_nn | b_nn, 0, dmin)
    hi = np.where(
        a_nn & b_nn,
        np.minimum(a.hi, b.hi),
        np.where(b_nn, b.hi, np.where(a_nn, a.hi, dmax)),
    )
    return [IVal(lo, hi, dt)]


@_rule("or")
def _r_or(ctx, frame, eqn, ins):
    a, b = ins
    dt = _out_dtype(eqn)
    if _is_boolish(dt):
        return [IVal(a.lo | b.lo, a.hi | b.hi, dt)]
    a_nn = a.lo >= 0
    b_nn = b.lo >= 0
    dmin, dmax = _dtype_range(dt)
    both = a_nn & b_nn
    lo = np.where(both, np.maximum(a.lo, b.lo), dmin)
    hi = np.where(both, _bithull(np.maximum(a.hi, b.hi)), dmax)
    return [IVal(lo, np.minimum(hi, dmax), dt)]


@_rule("xor")
def _r_xor(ctx, frame, eqn, ins):
    a, b = ins
    dt = _out_dtype(eqn)
    if _is_boolish(dt):
        fixed = (a.lo == a.hi) & (b.lo == b.hi)
        v = a.lo ^ b.lo
        return [IVal(np.where(fixed, v, 0), np.where(fixed, v, 1), dt)]
    a_nn = a.lo >= 0
    b_nn = b.lo >= 0
    dmin, dmax = _dtype_range(dt)
    both = a_nn & b_nn
    lo = np.where(both, 0, dmin)
    hi = np.where(both, _bithull(np.maximum(a.hi, b.hi)), dmax)
    return [IVal(lo, np.minimum(hi, dmax), dt)]


@_rule("not")
def _r_not(ctx, frame, eqn, ins):
    (a,) = ins
    dt = np.dtype(_out_dtype(eqn))
    if dt.kind == "b":
        return [IVal(1 - a.hi, 1 - a.lo, dt)]
    if dt.kind == "u":
        m = (1 << (8 * dt.itemsize)) - 1
        return [IVal(m - a.hi, m - a.lo, dt)]
    return [IVal(-a.hi - 1, -a.lo - 1, dt)]


@_rule("shift_left")
def _r_shl(ctx, frame, eqn, ins):
    a, s = ins
    slo = np.clip(s.lo, 0, 62)
    shi = np.clip(s.hi, 0, 62)
    f = IVal(np.int64(1) << slo, np.int64(1) << shi, a.dtype)
    lo, hi = _mul_bounds(a, f)
    return [_settle(ctx, lo, hi, _out_dtype(eqn), "shift_left")]


@_rule("shift_right_arithmetic")
def _r_shra(ctx, frame, eqn, ins):
    a, s = ins
    slo = np.clip(s.lo, 0, 62)
    shi = np.clip(s.hi, 0, 62)
    c = (a.lo >> slo, a.lo >> shi, a.hi >> slo, a.hi >> shi)
    lo = np.minimum(np.minimum(c[0], c[1]), np.minimum(c[2], c[3]))
    hi = np.maximum(np.maximum(c[0], c[1]), np.maximum(c[2], c[3]))
    return [
        _settle(ctx, lo, hi, _out_dtype(eqn), "shift_right_arithmetic")
    ]


@_rule("shift_right_logical")
def _r_shrl(ctx, frame, eqn, ins):
    a, s = ins
    dt = np.dtype(a.dtype)
    bits = 8 * dt.itemsize
    slo = np.clip(s.lo, 0, bits)
    shi = np.clip(s.hi, 0, bits)
    # nonneg elements behave arithmetically; possibly-negative elements
    # reinterpret two's-complement: value in [2^bits + lo, 2^bits - 1]
    m = np.int64(1) << bits
    nn_lo = np.minimum(a.lo >> shi, a.lo >> slo)
    nn_hi = np.maximum(a.hi >> slo, a.hi >> shi)
    neg_any = a.lo < 0
    all_neg = a.hi < 0
    wrap_lo = np.where(all_neg, (m + a.lo) >> shi, 0)
    wrap_hi = np.where(
        all_neg, (m + a.hi) >> slo, (m - 1) >> slo
    )
    lo = np.where(neg_any, wrap_lo, nn_lo)
    hi = np.where(neg_any, wrap_hi, nn_hi)
    return [_settle(ctx, lo, hi, _out_dtype(eqn), "shift_right_logical")]


@_rule("convert_element_type")
def _r_convert(ctx, frame, eqn, ins):
    (a,) = ins
    dst = np.dtype(eqn.params["new_dtype"])
    if dst.kind == "b":
        nonzero = (a.lo > 0) | (a.hi < 0)
        fixed_zero = (a.lo == 0) & (a.hi == 0)
        return [
            IVal(
                nonzero.astype(np.int64),
                (~fixed_zero).astype(np.int64),
                dst,
            )
        ]
    return [_settle(ctx, a.lo, a.hi, dst, "convert_element_type")]


@_rule("select_n")
def _r_select_n(ctx, frame, eqn, ins):
    pred, *cases = ins
    big = np.int64(1) << 62
    lo = np.full(cases[0].lo.shape, big, dtype=np.int64)
    hi = np.full(cases[0].hi.shape, -big, dtype=np.int64)
    for idx, c in enumerate(cases):
        m = (pred.lo <= idx) & (pred.hi >= idx)
        lo = np.where(m, np.minimum(lo, c.lo), lo)
        hi = np.where(m, np.maximum(hi, c.hi), hi)
    return [IVal(lo, hi, _out_dtype(eqn))]


@_rule("iota")
def _r_iota(ctx, frame, eqn, ins):
    p = eqn.params
    shape, dim = p["shape"], p["dimension"]
    ar = np.arange(shape[dim], dtype=np.int64)
    view = [1] * len(shape)
    view[dim] = shape[dim]
    arr = np.broadcast_to(ar.reshape(view), shape)
    return [IVal(arr, arr, p["dtype"])]

def _both(fn, a: IVal, dtype) -> IVal:
    return IVal(fn(a.lo), fn(a.hi), dtype)


@_rule("broadcast_in_dim")
def _r_broadcast(ctx, frame, eqn, ins):
    (a,) = ins
    p = eqn.params
    shape, bd = p["shape"], p["broadcast_dimensions"]

    def go(x):
        view = [1] * len(shape)
        for i, d in enumerate(bd):
            view[d] = x.shape[i] if x.ndim else 1
        return np.broadcast_to(x.reshape(view), shape)

    return [_both(go, a, _out_dtype(eqn))]


@_rule("reshape")
def _r_reshape(ctx, frame, eqn, ins):
    (a,) = ins
    p = eqn.params
    dims = p.get("dimensions")

    def go(x):
        if dims is not None:
            x = np.transpose(x, dims)
        return np.reshape(x, p["new_sizes"])

    return [_both(go, a, _out_dtype(eqn))]


@_rule("transpose")
def _r_transpose(ctx, frame, eqn, ins):
    (a,) = ins
    perm = eqn.params["permutation"]
    return [_both(lambda x: np.transpose(x, perm), a, _out_dtype(eqn))]


@_rule("rev")
def _r_rev(ctx, frame, eqn, ins):
    (a,) = ins
    dims = tuple(eqn.params["dimensions"])
    return [_both(lambda x: np.flip(x, dims), a, _out_dtype(eqn))]


@_rule("squeeze")
def _r_squeeze(ctx, frame, eqn, ins):
    (a,) = ins
    dims = tuple(eqn.params["dimensions"])
    return [_both(lambda x: np.squeeze(x, dims), a, _out_dtype(eqn))]


@_rule("slice")
def _r_slice(ctx, frame, eqn, ins):
    (a,) = ins
    p = eqn.params
    strides = p["strides"] or (1,) * len(p["start_indices"])
    sl = tuple(
        slice(s, l, st)
        for s, l, st in zip(p["start_indices"], p["limit_indices"], strides)
    )
    return [_both(lambda x: x[sl], a, _out_dtype(eqn))]


@_rule("concatenate")
def _r_concat(ctx, frame, eqn, ins):
    dim = eqn.params["dimension"]
    lo = np.concatenate([i.lo for i in ins], axis=dim)
    hi = np.concatenate([i.hi for i in ins], axis=dim)
    return [IVal(lo, hi, _out_dtype(eqn))]


@_rule("pad")
def _r_pad(ctx, frame, eqn, ins):
    a, pv = ins
    cfg = eqn.params["padding_config"]
    out_shape = tuple(
        lo + hi + d + max(d - 1, 0) * interior
        for d, (lo, hi, interior) in zip(a.shape, cfg)
    )

    def go(x, fill):
        out = np.full(out_shape, np.asarray(fill).reshape(()), dtype=np.int64)
        idx = []
        src = []
        for d, (lo, _hi, interior) in zip(x.shape, cfg):
            pos = lo + np.arange(d, dtype=np.int64) * (interior + 1)
            ok = (pos >= 0) & (pos < out.shape[len(idx)])
            idx.append(pos[ok])
            src.append(np.arange(d)[ok])
        if x.size and all(len(i) for i in idx):
            out[np.ix_(*idx)] = x[np.ix_(*src)]
        elif not cfg:
            out = _np64(x).reshape(out_shape)
        return out

    return [
        IVal(go(a.lo, pv.lo), go(a.hi, pv.hi), _out_dtype(eqn))
    ]


@_rule("reduce_sum")
def _r_reduce_sum(ctx, frame, eqn, ins):
    (a,) = ins
    axes = tuple(eqn.params["axes"])
    # one-hot select: sum(x * onehot, axis) picks at most one term along
    # the one-hot axis -- hull that axis (joined with 0) instead of
    # summing it
    oh_ax = None
    src = eqn.invars[0]
    d = None if hasattr(src, "val") else frame.defs.get(src)
    if d is not None and d.primitive.name == "mul":
        for f in d.invars:
            fiv = None if hasattr(f, "val") else frame.env.get(f)
            if (
                fiv is None
                or not (np.all(fiv.lo >= 0) and np.all(fiv.hi <= 1))
            ):
                continue
            cand = _onehot_axes(frame, f) & set(axes)
            if cand:
                oh_ax = min(cand)
                break
    if oh_ax is not None:
        lo = np.minimum(0, a.lo.min(axis=oh_ax))
        hi = np.maximum(0, a.hi.max(axis=oh_ax))
        rest = tuple(ax - (ax > oh_ax) for ax in axes if ax != oh_ax)
        if rest:
            lo, hi = lo.sum(axis=rest), hi.sum(axis=rest)
        return [_settle(ctx, lo, hi, _out_dtype(eqn), "reduce_sum")]
    return [
        _settle(
            ctx, a.lo.sum(axis=axes), a.hi.sum(axis=axes),
            _out_dtype(eqn), "reduce_sum",
        )
    ]


@_rule("reduce_and")
def _r_reduce_and(ctx, frame, eqn, ins):
    (a,) = ins
    axes = tuple(eqn.params["axes"])
    return [
        IVal(a.lo.min(axis=axes), a.hi.min(axis=axes), _out_dtype(eqn))
    ]


@_rule("reduce_or")
def _r_reduce_or(ctx, frame, eqn, ins):
    (a,) = ins
    axes = tuple(eqn.params["axes"])
    return [
        IVal(a.lo.max(axis=axes), a.hi.max(axis=axes), _out_dtype(eqn))
    ]


@_rule("reduce_max")
def _r_reduce_max(ctx, frame, eqn, ins):
    (a,) = ins
    axes = tuple(eqn.params["axes"])
    return [
        IVal(a.lo.max(axis=axes), a.hi.max(axis=axes), _out_dtype(eqn))
    ]


@_rule("reduce_min")
def _r_reduce_min(ctx, frame, eqn, ins):
    (a,) = ins
    axes = tuple(eqn.params["axes"])
    return [
        IVal(a.lo.min(axis=axes), a.hi.min(axis=axes), _out_dtype(eqn))
    ]


@_rule("device_put")
def _r_device_put(ctx, frame, eqn, ins):
    return list(ins)


@_rule("copy")
def _r_copy(ctx, frame, eqn, ins):
    return list(ins)


@_rule("psum")
def _r_psum(ctx, frame, eqn, ins):
    factor = 1
    for ax in eqn.params["axes"]:
        factor *= ctx.mesh_sizes.get(ax, 1)
    out = []
    for a, ov in zip(ins, eqn.outvars):
        out.append(
            _settle(ctx, a.lo * factor, a.hi * factor, ov.aval.dtype, "psum")
        )
    return out


@_rule("all_gather")
def _r_all_gather(ctx, frame, eqn, ins):
    (a,) = ins
    p = eqn.params
    dim = p["all_gather_dimension"]
    n = p["axis_size"]

    def go(x):
        if p["tiled"]:
            reps = [1] * x.ndim
            reps[dim] = n
            return np.tile(x, reps)
        return np.repeat(np.expand_dims(x, dim), n, axis=dim)

    return [_both(go, a, _out_dtype(eqn))]

@_rule("dot_general")
def _r_dot_general(ctx, frame, eqn, ins):
    a, b = ins
    (ca, cb), (ba, bb) = eqn.params["dimension_numbers"]
    out_dt = _out_dtype(eqn)

    def canon(x, contract, batch):
        free = [
            d for d in range(x.ndim) if d not in contract and d not in batch
        ]
        perm = list(batch) + free + list(contract)
        y = np.transpose(x, perm)
        nb = len(batch)
        nf = len(free)
        bshape = y.shape[:nb]
        fshape = y.shape[nb:nb + nf]
        k = int(np.prod(y.shape[nb + nf:], dtype=np.int64)) if x.ndim else 1
        return (
            y.reshape(
                (int(np.prod(bshape, dtype=np.int64)) if nb else 1,
                 int(np.prod(fshape, dtype=np.int64)) if nf else 1,
                 k)
            ),
            bshape,
            fshape,
        )

    alo, bsh, afsh = canon(a.lo, ca, ba)
    ahi, _, _ = canon(a.hi, ca, ba)
    blo, _, bfsh = canon(b.lo, cb, bb)
    bhi, _, _ = canon(b.hi, cb, bb)
    A_lo = alo[:, :, None, :]
    A_hi = ahi[:, :, None, :]
    B_lo = blo[:, None, :, :]
    B_hi = bhi[:, None, :, :]
    c1 = _sat_mul(A_lo, B_lo)
    c2 = _sat_mul(A_lo, B_hi)
    c3 = _sat_mul(A_hi, B_lo)
    c4 = _sat_mul(A_hi, B_hi)
    pmin = np.minimum(np.minimum(c1, c2), np.minimum(c3, c4))
    pmax = np.maximum(np.maximum(c1, c2), np.maximum(c3, c4))
    # one-hot contraction: when an operand is provably one-hot along its
    # (single) contracted axis, the sum selects at most one product term
    # -- bound by the term hull (joined with 0 for the no-match row)
    # instead of the contraction abs-sum
    onehot = any(
        len(cd) == 1
        and cd[0] in _onehot_axes(frame, atom)
        and np.all(v.lo >= 0)
        and np.all(v.hi <= 1)
        for atom, v, cd in (
            (eqn.invars[0], a, ca),
            (eqn.invars[1], b, cb),
        )
    )
    if onehot:
        lo = np.minimum(0, pmin.min(axis=-1))
        hi = np.maximum(0, pmax.max(axis=-1))
        absum = np.maximum(np.abs(lo), np.abs(hi))
    else:
        lo = pmin.sum(axis=-1)
        hi = pmax.sum(axis=-1)
        # the exactness contract is on PARTIAL sums too: bound them by
        # the sum of absolute product bounds over the contraction
        absum = np.maximum(np.abs(pmin), np.abs(pmax)).sum(axis=-1)
    peak = int(absum.max()) if absum.size else 0
    out_shape = tuple(bsh) + tuple(afsh) + tuple(bfsh)
    lo = lo.reshape(out_shape)
    hi = hi.reshape(out_shape)
    dt = np.dtype(out_dt)
    if dt.kind == "f":
        ctx.stat("f32", peak, "dot_general")
        if peak > F32_EXACT:
            ctx.finding(
                f"f32 dot_general partial sums: |bound| {peak} > 2^24 "
                f"at {ctx.label('dot_general')}"
            )
    elif dt.kind == "i":
        ctx.stat("int32", peak, "dot_general")
        dmin, dmax = _dtype_range(dt)
        if peak > dmax:
            ctx.finding(
                f"{dt.name} dot_general partial sums: |bound| {peak} "
                f"exceeds {dmax} at {ctx.label('dot_general')}"
            )
    return [_settle(ctx, lo, hi, out_dt, "dot_general")]


def _jnp():
    # deferred: the interpreter itself never traces, but the gather /
    # scatter index-map trick executes the primitive eagerly (tiny int32
    # id arrays) to recover the exact index mapping
    import jax  # noqa: F401
    import jax.numpy as jnp

    return jnp


@_rule("gather")
def _r_gather(ctx, frame, eqn, ins):
    op, idx = ins
    p = eqn.params
    out_aval = eqn.outvars[0].aval
    if idx.concrete() and op.lo.size < (1 << 24):
        from jax import lax

        ids = np.arange(op.lo.size, dtype=np.int32).reshape(op.shape)
        jnp = _jnp()
        mode = p["mode"]
        try:
            mapped = np.asarray(
                lax.gather(
                    jnp.asarray(ids),
                    jnp.asarray(idx.lo.astype(np.int32)),
                    dimension_numbers=p["dimension_numbers"],
                    slice_sizes=p["slice_sizes"],
                    unique_indices=p["unique_indices"],
                    indices_are_sorted=p["indices_are_sorted"],
                    mode="fill",
                    fill_value=-1,
                )
            )
            in_b = mapped >= 0
            safe = np.where(in_b, mapped, 0)
            lo = np.where(in_b, op.lo.reshape(-1)[safe], 0)
            hi = np.where(in_b, op.hi.reshape(-1)[safe], 0)
            return [IVal(lo, hi, out_aval.dtype)]
        except Exception:
            # eager replay can reject shapes jax accepted at trace time;
            # the operand hull below is the sound fallback either way
            return _gather_hull(op, out_aval)
        finally:
            del mode
    return _gather_hull(op, out_aval)


def _gather_hull(op: IVal, out_aval):
    # non-concrete (or un-replayable) indices: hull of the operand,
    # joined with the out-of-bounds fill value 0
    lo = np.minimum(int(op.lo.min()) if op.lo.size else 0, 0)
    hi = np.maximum(int(op.hi.max()) if op.hi.size else 0, 0)
    return [
        IVal(
            np.full(out_aval.shape, lo, np.int64),
            np.full(out_aval.shape, hi, np.int64),
            out_aval.dtype,
        )
    ]


def _scatter_map(ctx, p, op_shape, idx, upd_shape):
    """Update-element id landing on each operand element (-1 = none),
    recovered by running an overwrite scatter of ids eagerly."""
    from jax import lax

    jnp = _jnp()
    base = np.full(op_shape, -1, dtype=np.int32)
    uids = np.arange(
        int(np.prod(upd_shape, dtype=np.int64)), dtype=np.int32
    ).reshape(upd_shape)
    return np.asarray(
        lax.scatter(
            jnp.asarray(base),
            jnp.asarray(idx.lo.astype(np.int32)),
            jnp.asarray(uids),
            dimension_numbers=p["dimension_numbers"],
            indices_are_sorted=p["indices_are_sorted"],
            unique_indices=p["unique_indices"],
            mode="drop",
        )
    )


@_rule("scatter")
def _r_scatter(ctx, frame, eqn, ins):
    op, idx, upd = ins
    p = eqn.params
    if idx.concrete() and p["unique_indices"]:
        try:
            rid = _scatter_map(ctx, p, op.shape, idx, upd.shape)
            hit = rid >= 0
            safe = np.where(hit, rid, 0)
            lo = np.where(hit, upd.lo.reshape(-1)[safe], op.lo)
            hi = np.where(hit, upd.hi.reshape(-1)[safe], op.hi)
            return [IVal(lo, hi, _out_dtype(eqn))]
        except Exception:
            # index-map replay rejected: the hull below is sound anyway
            return _scatter_hull(op, upd, _out_dtype(eqn))
    return _scatter_hull(op, upd, _out_dtype(eqn))


def _scatter_hull(op: IVal, upd: IVal, dt):
    # unknown indices: any element may keep the operand or take any update
    u_lo = int(upd.lo.min()) if upd.lo.size else 0
    u_hi = int(upd.hi.max()) if upd.hi.size else 0
    return [IVal(np.minimum(op.lo, u_lo), np.maximum(op.hi, u_hi), dt)]


@_rule("scatter-add")
def _r_scatter_add(ctx, frame, eqn, ins):
    op, idx, upd = ins
    p = eqn.params
    dt = _out_dtype(eqn)
    if idx.concrete() and p["unique_indices"]:
        try:
            rid = _scatter_map(ctx, p, op.shape, idx, upd.shape)
            hit = rid >= 0
            safe = np.where(hit, rid, 0)
            lo = op.lo + np.where(hit, upd.lo.reshape(-1)[safe], 0)
            hi = op.hi + np.where(hit, upd.hi.reshape(-1)[safe], 0)
            return [_settle(ctx, lo, hi, dt, "scatter-add")]
        except Exception:
            # index-map replay rejected: the all-collide hull is sound
            return _scatter_add_hull(ctx, op, upd, dt)
    return _scatter_add_hull(ctx, op, upd, dt)


def _scatter_add_hull(ctx, op: IVal, upd: IVal, dt):
    # unknown indices: every update may land on the same element
    add_lo = int(np.minimum(upd.lo, 0).sum()) if upd.lo.size else 0
    add_hi = int(np.maximum(upd.hi, 0).sum()) if upd.hi.size else 0
    return [_settle(ctx, op.lo + add_lo, op.hi + add_hi, dt, "scatter-add")]


def _start_candidates(starts, sizes, op_shape):
    """Clamped candidate start tuples for dynamic slice/update; None when
    the enumeration would exceed DSLICE_ENUM_MAX combinations."""
    axes = []
    total = 1
    for s, size, dim in zip(starts, sizes, op_shape):
        lo = int(np.clip(s.lo, 0, dim - size))
        hi = int(np.clip(s.hi, 0, dim - size))
        n = hi - lo + 1
        total *= n
        if total > DSLICE_ENUM_MAX:
            return None
        axes.append(range(lo, hi + 1))
    import itertools

    return list(itertools.product(*axes))


@_rule("dynamic_slice")
def _r_dynamic_slice(ctx, frame, eqn, ins):
    op = ins[0]
    starts = ins[1:]
    sizes = eqn.params["slice_sizes"]
    cands = _start_candidates(starts, sizes, op.shape)
    out_aval = eqn.outvars[0].aval
    if cands is not None:
        lo = None
        hi = None
        for tup in cands:
            sl = tuple(
                slice(s, s + z) for s, z in zip(tup, sizes)
            )
            clo, chi = op.lo[sl], op.hi[sl]
            lo = clo if lo is None else np.minimum(lo, clo)
            hi = chi if hi is None else np.maximum(hi, chi)
        return [IVal(lo, hi, out_aval.dtype)]
    # too many possible windows: hull of the whole operand
    lo = int(op.lo.min()) if op.lo.size else 0
    hi = int(op.hi.max()) if op.hi.size else 0
    return [
        IVal(
            np.full(out_aval.shape, lo, np.int64),
            np.full(out_aval.shape, hi, np.int64),
            out_aval.dtype,
        )
    ]


@_rule("dynamic_update_slice")
def _r_dynamic_update_slice(ctx, frame, eqn, ins):
    op, upd = ins[0], ins[1]
    starts = ins[2:]
    sizes = upd.shape
    cands = _start_candidates(starts, sizes, op.shape)
    if cands is not None and len(cands) == 1:
        sl = tuple(slice(s, s + z) for s, z in zip(cands[0], sizes))
        lo = op.lo.copy()
        hi = op.hi.copy()
        lo[sl] = upd.lo
        hi[sl] = upd.hi
        return [IVal(lo, hi, _out_dtype(eqn))]
    # uncertain start: every covered position may keep op or take the
    # update's hull
    lo = op.lo.copy()
    hi = op.hi.copy()
    u_lo = int(upd.lo.min()) if upd.lo.size else 0
    u_hi = int(upd.hi.max()) if upd.hi.size else 0
    if cands is not None:
        region = tuple(
            slice(min(t[d] for t in cands),
                  max(t[d] for t in cands) + sizes[d])
            for d in range(len(sizes))
        )
    else:
        region = tuple(slice(None) for _ in sizes)
    lo[region] = np.minimum(lo[region], u_lo)
    hi[region] = np.maximum(hi[region], u_hi)
    return [IVal(lo, hi, _out_dtype(eqn))]


# ------------------------------------------------------ composite prims


def _bounds_digest(ins) -> str:
    h = hashlib.sha256()
    for v in ins:
        h.update(v.dtype.str.encode())
        h.update(str(v.shape).encode())
        h.update(v.lo.tobytes())
        h.update(v.hi.tobytes())
    return h.hexdigest()


def _replay(ctx, events) -> None:
    ctx.events.extend(events)
    for ev in events:
        if ev[0] == "stat" and ev[2] > ctx._best[ev[1]]:
            ctx._best[ev[1]] = ev[2]


def _cached_call(ctx, jaxpr, consts, ins, runner):
    """Memoize sub-jaxpr interpretation on (jaxpr identity, input
    bounds); replays the journal events the original run produced."""
    key = (id(jaxpr), _bounds_digest(ins))
    hit = ctx.cache.get(key)
    if hit is not None:
        outs, events = hit
        _replay(ctx, events)
        return [IVal(o.lo, o.hi, o.dtype) for o in outs]
    start = len(ctx.events)
    outs = runner()
    ctx.cache[key] = (
        [IVal(o.lo, o.hi, o.dtype) for o in outs],
        list(ctx.events[start:]),
    )
    ctx.cache_refs.append(jaxpr)
    return outs


@_rule("pjit")
def _r_pjit(ctx, frame, eqn, ins):
    closed = eqn.params["jaxpr"]
    name = eqn.params.get("name") or "pjit"
    ctx.path.append(name)
    try:
        return _cached_call(
            ctx, closed.jaxpr, closed.consts, ins,
            lambda: _interp_closed(ctx, closed, ins),
        )
    finally:
        ctx.path.pop()


@_rule("shard_map")
def _r_shard_map(ctx, frame, eqn, ins):
    """Interpret the per-shard body on per-shard bounds: split each
    sharded axis (k, inner), hull over the shard axis in, tile back out.
    Saves mesh axis sizes so psum knows its multiplier."""
    p = eqn.params
    jaxpr = p["jaxpr"]  # open jaxpr (no consts) in current jax
    mesh = p["mesh"]
    in_names = p["in_names"]
    out_names = p["out_names"]
    sizes = dict(mesh.shape)

    def shard_in(v, names):
        lo, hi = v.lo, v.hi
        for dim in sorted(names):
            k = 1
            for ax in names[dim]:
                k *= sizes[ax]
            if k == 1:
                continue
            n = lo.shape[dim]
            newshape = lo.shape[:dim] + (k, n // k) + lo.shape[dim + 1:]
            lo = lo.reshape(newshape).min(axis=dim)
            hi = hi.reshape(newshape).max(axis=dim)
        return IVal(lo, hi, v.dtype)

    def unshard_out(v, names):
        lo, hi = v.lo, v.hi
        for dim in sorted(names):
            k = 1
            for ax in names[dim]:
                k *= sizes[ax]
            if k == 1:
                continue
            reps = [1] * lo.ndim
            reps[dim] = k
            lo = np.tile(lo, reps)
            hi = np.tile(hi, reps)
        return IVal(lo, hi, v.dtype)

    body_ins = [shard_in(v, n) for v, n in zip(ins, in_names)]
    saved = ctx.mesh_sizes
    ctx.mesh_sizes = sizes
    ctx.path.append("shard_map")
    try:
        if hasattr(jaxpr, "jaxpr"):  # ClosedJaxpr in some jax versions
            outs = _cached_call(
                ctx, jaxpr.jaxpr, jaxpr.consts, body_ins,
                lambda: _interp_closed(ctx, jaxpr, body_ins),
            )
        else:
            outs = _cached_call(
                ctx, jaxpr, (), body_ins,
                lambda: _interp_jaxpr(ctx, jaxpr, (), body_ins),
            )
    finally:
        ctx.path.pop()
        ctx.mesh_sizes = saved
    return [unshard_out(v, n) for v, n in zip(outs, out_names)]


# ---------------------------------------------------------------- scan


def _widen_to_dtype(v: IVal) -> IVal:
    lo, hi = _dtype_range(v.dtype)
    return IVal(
        np.full(v.shape, lo, np.int64), np.full(v.shape, hi, np.int64), v.dtype
    )


def _run_scan_body(ctx, closed, consts_iv, carry_iv, xs_slice_iv):
    ins = list(consts_iv) + list(carry_iv) + list(xs_slice_iv)
    return _cached_call(
        ctx, closed.jaxpr, closed.consts, ins,
        lambda: _interp_closed(ctx, closed, ins),
    )


def _xs_hull_slices(xs_ivs):
    """Per-step hull of each scanned input (axis 0 removed)."""
    out = []
    for v in xs_ivs:
        out.append(
            IVal(v.lo.min(axis=0), v.hi.max(axis=0), v.dtype)
            if v.lo.size
            else IVal(
                np.zeros(v.shape[1:], np.int64),
                np.zeros(v.shape[1:], np.int64),
                v.dtype,
            )
        )
    return out


def _affine_counters(closed, n_consts: int, n_carry: int) -> dict:
    """Carry slots whose body update is exactly ``carry + literal``
    (the fori_loop counter shape) -> {carry_ordinal: step}.

    Detected statically from the body jaxpr, so the bound is sound by
    induction: the value at iteration t is exactly ``init + t*step``,
    which lets the fixpoint rung pin the counter to its trip-count hull
    instead of widening it to the full dtype range (the widened counter's
    ``i + 1`` would otherwise surface as a false int32-overflow finding
    on every long fori_loop).
    """
    jx = closed.jaxpr
    carry_invars = jx.invars[n_consts:n_consts + n_carry]
    out: dict = {}
    for j, ov in enumerate(jx.outvars[:n_carry]):
        if hasattr(ov, "val"):
            continue
        eqn = next(
            (e for e in jx.eqns if any(o is ov for o in e.outvars)), None
        )
        if eqn is None or eqn.primitive.name != "add":
            continue
        a, b = eqn.invars
        for x, y in ((a, b), (b, a)):
            if (
                hasattr(x, "val")
                and np.ndim(x.val) == 0
                and np.issubdtype(np.asarray(x.val).dtype, np.integer)
                and not hasattr(y, "val")
                and y is carry_invars[j]
            ):
                out[j] = int(x.val)
                break
    return out


@_rule("scan")
def _r_scan(ctx, frame, eqn, ins):
    p = eqn.params
    closed = p["jaxpr"]
    n_consts = p["num_consts"]
    n_carry = p["num_carry"]
    length = p["length"]
    reverse = p["reverse"]
    ordinal = ctx.scan_ordinal
    ctx.scan_ordinal += 1

    consts_iv = ins[:n_consts]
    carry0 = ins[n_consts:n_consts + n_carry]
    xs_iv = ins[n_consts + n_carry:]
    n_ys = len(eqn.outvars) - n_carry
    label = ctx.label(f"scan#{ordinal}")
    counters = _affine_counters(closed, n_consts, n_carry)

    def _pin_counters(carry):
        """In-loop hull for counter carries: init + [0, step*(length-1)]."""
        for j, step in counters.items():
            c0 = carry0[j]
            span = step * (length - 1)
            carry[j] = IVal(
                c0.lo + min(0, span), c0.hi + max(0, span), c0.dtype
            )
        return carry

    def _counter_finals(carry):
        """Exact post-loop counter value: init + step*length."""
        for j, step in counters.items():
            c0 = carry0[j]
            carry[j] = IVal(
                c0.lo + step * length, c0.hi + step * length, c0.dtype
            )
        return carry

    # ladder rung 1: join-iterate to a fixpoint on the per-step hull.
    # Intermediate (non-converged) body runs are rolled back so their
    # transient bounds never surface as findings; only the converged
    # run's events remain in the journal.
    def try_fixpoint():
        xs_hull = _xs_hull_slices(xs_iv)
        carry = _pin_counters([IVal(c.lo, c.hi, c.dtype) for c in carry0])
        for _ in range(FIXPOINT_MAX_ITERS):
            m = ctx.mark()
            outs = _run_scan_body(ctx, closed, consts_iv, carry, xs_hull)
            new_carry = list(outs[:n_carry])
            for j in counters:  # pinned: exact by induction, never joined
                new_carry[j] = carry[j]
            if all(_contains(c, nc) for c, nc in zip(carry, new_carry)):
                return _counter_finals(list(carry)), outs[n_carry:]
            ctx.rollback(m)
            carry = [_join(c, nc) for c, nc in zip(carry, new_carry)]
        # widen every still-moving carry to its dtype range, re-check once
        m = ctx.mark()
        outs = _run_scan_body(ctx, closed, consts_iv, carry, xs_hull)
        widened = [
            c if j in counters or _contains(c, nc) else _widen_to_dtype(c)
            for j, (c, nc) in enumerate(zip(carry, outs[:n_carry]))
        ]
        ctx.rollback(m)
        m = ctx.mark()
        final = _run_scan_body(ctx, closed, consts_iv, widened, xs_hull)
        new_carry = list(final[:n_carry])
        for j in counters:
            new_carry[j] = widened[j]
        if all(_contains(c, nc) for c, nc in zip(widened, new_carry)):
            return _counter_finals(list(widened)), final[n_carry:]
        ctx.rollback(m)
        return None

    # ladder rung 2: exact unroll (concretizes loop counters; the only
    # strategy that tracks Montgomery accumulator windows)
    def try_unroll():
        if length == 0 or length > UNROLL_MAX:
            return None
        carry = [IVal(c.lo, c.hi, c.dtype) for c in carry0]
        ys_steps: list[list[IVal]] = []
        steps = range(length - 1, -1, -1) if reverse else range(length)
        for t in steps:
            xs_t = [IVal(v.lo[t], v.hi[t], v.dtype) for v in xs_iv]
            outs = _run_scan_body(ctx, closed, consts_iv, carry, xs_t)
            carry = outs[:n_carry]
            ys_steps.append(outs[n_carry:])
        if reverse:
            ys_steps.reverse()
        ys = []
        for j in range(n_ys):
            lo = np.stack([st[j].lo for st in ys_steps])
            hi = np.stack([st[j].hi for st in ys_steps])
            ys.append(IVal(lo, hi, ys_steps[0][j].dtype))
        return carry, ys

    # ladder rung 3: declared invariant (assume-guarantee)
    def try_invariant():
        decl = {
            co: bound
            for (so, co), bound in ctx.invariants.items()
            if so == ordinal
        }
        if not decl:
            return None
        carry = []
        for i, c in enumerate(carry0):
            if i in decl:
                lo, hi = decl[i]
                carry.append(
                    IVal(
                        np.full(c.shape, lo, np.int64),
                        np.full(c.shape, hi, np.int64),
                        c.dtype,
                    )
                )
            else:
                carry.append(c)
        _pin_counters_undecl = {
            j: s for j, s in counters.items() if j not in decl
        }
        for j, step in _pin_counters_undecl.items():
            c0 = carry0[j]
            span = step * (length - 1)
            carry[j] = IVal(
                c0.lo + min(0, span), c0.hi + max(0, span), c0.dtype
            )
        if not all(_contains(inv, c0) for inv, c0 in zip(carry, carry0)):
            ctx.finding(
                f"loop invariant at {label} does not cover the initial "
                f"carry"
            )
            return None
        xs_hull = _xs_hull_slices(xs_iv)
        outs = _run_scan_body(ctx, closed, consts_iv, carry, xs_hull)
        new_carry = list(outs[:n_carry])
        for j in _pin_counters_undecl:
            new_carry[j] = carry[j]
        if not all(_contains(inv, nc) for inv, nc in zip(carry, new_carry)):
            ctx.finding(
                f"declared loop invariant at {label} is not inductive"
            )
            return None
        final = list(carry)
        for j, step in _pin_counters_undecl.items():
            c0 = carry0[j]
            final[j] = IVal(
                c0.lo + step * length, c0.hi + step * length, c0.dtype
            )
        return final, outs[n_carry:]

    # unroll FIRST: for short scans it dominates the fixpoint — exact
    # per-step xs bounds (the fixpoint's per-step hull smears one loose
    # limb's bound over every step of a carry chain) and concrete loop
    # counters.  The fixpoint rung exists for the long chains (the
    # 255-bit subgroup walk) that exceed UNROLL_MAX.
    best = None  # (n_findings, (carry, ys), events-suffix)
    for attempt in (try_unroll, try_fixpoint, try_invariant):
        mark = ctx.mark()
        res = attempt()
        if res is None:
            ctx.rollback(mark)
            continue
        events = list(ctx.events[mark:])
        nf = sum(1 for ev in events if ev[0] == "finding")
        if nf == 0:
            # clean strategy: its events stay in the journal as-is
            return _finish_scan(res, eqn, n_carry)
        if best is None or nf < best[0]:
            best = (nf, res, events)
        ctx.rollback(mark)
    if best is not None:
        # every strategy had findings: surface the least-bad set
        _replay(ctx, best[2])
        return _finish_scan(best[1], eqn, n_carry)
    ctx.finding(f"scan at {label}: no strategy converged")
    carry = [_widen_to_dtype(c) for c in carry0]
    ys = []
    for ov in eqn.outvars[n_carry:]:
        lo, hi = _dtype_range(ov.aval.dtype)
        ys.append(
            IVal(
                np.full(ov.aval.shape, lo, np.int64),
                np.full(ov.aval.shape, hi, np.int64),
                ov.aval.dtype,
            )
        )
    return _finish_scan((carry, ys), eqn, n_carry)


def _finish_scan(res, eqn, n_carry):
    carry, ys = res
    fixed = []
    for v, ov in zip(list(carry) + list(ys), eqn.outvars):
        shape = ov.aval.shape
        if v.shape != shape:
            v = IVal(
                np.broadcast_to(v.lo, shape),
                np.broadcast_to(v.hi, shape),
                ov.aval.dtype,
            )
        fixed.append(v)
    return fixed


# ------------------------------------------------------- interpreter loop


def _interp_jaxpr(ctx, jaxpr, consts, ins):
    frame = _Frame()
    for var, c in zip(jaxpr.constvars, consts):
        frame.env[var] = _const_ival(np.asarray(c), np.asarray(c).dtype)
    for var, v in zip(jaxpr.invars, ins):
        frame.env[var] = v
    for eqn in jaxpr.eqns:
        ctx.eqn_count += 1
        prim = eqn.primitive.name
        rule = _RULES.get(prim)
        in_vals = [_read(frame, a) for a in eqn.invars]
        if rule is None:
            ctx.finding(
                f"no transfer rule for primitive {prim!r} at "
                f"{ctx.label(prim)}"
            )
            outs = []
            for ov in eqn.outvars:
                lo, hi = _dtype_range(ov.aval.dtype)
                outs.append(
                    IVal(
                        np.full(ov.aval.shape, lo, np.int64),
                        np.full(ov.aval.shape, hi, np.int64),
                        ov.aval.dtype,
                    )
                )
        else:
            outs = rule(ctx, frame, eqn, in_vals)
        for ov, val in zip(eqn.outvars, outs):
            if type(ov).__name__ == "DropVar":
                continue
            shape = ov.aval.shape
            if val.shape != shape:
                val = IVal(
                    np.broadcast_to(val.lo, shape),
                    np.broadcast_to(val.hi, shape),
                    val.dtype,
                )
            frame.env[ov] = val
            frame.defs[ov] = eqn
    return [_read(frame, a) for a in jaxpr.outvars]


def _interp_closed(ctx, closed, ins):
    return _interp_jaxpr(ctx, closed.jaxpr, closed.consts, ins)


def _input_ivals(kernel) -> list[IVal]:
    """Abstract inputs from the manifest row: the declared arg_ranges
    entry when present, else the full dtype range (f32 defaults to the
    exactness envelope +-2^24)."""
    ranges = getattr(kernel, "arg_ranges", None) or (None,) * len(kernel.args)
    if len(ranges) != len(kernel.args):
        raise ValueError(
            f"{kernel.name}: arg_ranges has {len(ranges)} entries for "
            f"{len(kernel.args)} args"
        )
    out = []
    for arg, rng in zip(kernel.args, ranges):
        dt = np.dtype(arg.dtype)
        lo, hi = rng if rng is not None else _dtype_range(dt)
        out.append(
            IVal(
                np.full(arg.shape, lo, np.int64),
                np.full(arg.shape, hi, np.int64),
                dt,
            )
        )
    return out


# ---------------------------------------------------------- kernel check


@dataclass
class RangeReport:
    """Interpretation result for one kernel."""

    kernel: str
    ok: bool
    messages: list  # finding strings (deduped, capped)
    peak_int32: int
    peak_int32_at: str
    peak_f32: int
    peak_f32_at: str
    headroom_int32_bits: float
    headroom_f32_bits: float
    eqns: int

    def fingerprint(self) -> dict:
        return {
            "ok": self.ok,
            "findings": list(self.messages),
            "peak_int32": self.peak_int32,
            "peak_int32_at": self.peak_int32_at,
            "peak_f32": self.peak_f32,
            "peak_f32_at": self.peak_f32_at,
            "headroom_int32_bits": self.headroom_int32_bits,
            "headroom_f32_bits": self.headroom_f32_bits,
        }


def _headroom_bits(peak: int, limit: int) -> float:
    if peak <= 0:
        return float(math.log2(limit))
    return round(math.log2(limit / peak), 2) if peak <= limit else 0.0


def _trace_closed(kernel):
    """The kernel's ClosedJaxpr under the PR-4 deterministic trace
    environment (CPU backend pinned, trace-time knobs unset)."""
    from . import kernelcheck

    kernelcheck._ensure_cpu_backend()
    import jax

    with kernelcheck._pinned_trace_env():
        fn = kernelcheck._resolve(kernel)
        return jax.make_jaxpr(fn)(*kernelcheck._arg_structs(kernel))


def check_kernel(kernel) -> RangeReport:
    """Trace one manifest kernel and interpret its jaxpr abstractly."""
    ctx = _Ctx(kernel.name, getattr(kernel, "loop_invariants", ()) or ())
    outs = []
    try:
        closed = _trace_closed(kernel)
        ins = _input_ivals(kernel)
        outs = _interp_jaxpr(ctx, closed.jaxpr, closed.consts, ins)
    except Exception as e:  # an interpreter crash is a finding, not a pass
        ctx.finding(f"interpreter error: {type(e).__name__}: {e}")

    # contract 2: declared output ranges hold
    out_ranges = getattr(kernel, "out_ranges", None)
    if out_ranges is not None and outs:
        if len(out_ranges) != len(outs):
            ctx.finding(
                f"out_ranges has {len(out_ranges)} entries for "
                f"{len(outs)} outputs"
            )
        else:
            for i, (rng, v) in enumerate(zip(out_ranges, outs)):
                if rng is None:
                    continue
                lo, hi = rng
                vlo = int(v.lo.min()) if v.lo.size else lo
                vhi = int(v.hi.max()) if v.hi.size else hi
                if vlo < lo or vhi > hi:
                    ctx.finding(
                        f"output {i} range [{vlo}, {vhi}] escapes the "
                        f"declared [{lo}, {hi}]"
                    )

    messages: list[str] = []
    for ev in ctx.events:
        if ev[0] == "finding" and ev[1] not in messages:
            messages.append(ev[1])
    extra = len(messages) - _MAX_FINDINGS_PER_KERNEL
    if extra > 0:
        messages = messages[:_MAX_FINDINGS_PER_KERNEL]
        messages.append(f"... and {extra} more")

    peaks = {"int32": (0, ""), "f32": (0, "")}
    for ev in ctx.events:
        if ev[0] == "stat" and ev[2] > peaks[ev[1]][0]:
            peaks[ev[1]] = (ev[2], ev[3])
    pi, pi_at = peaks["int32"]
    pf, pf_at = peaks["f32"]
    return RangeReport(
        kernel=kernel.name,
        ok=not messages,
        messages=messages,
        peak_int32=pi,
        peak_int32_at=pi_at,
        peak_f32=pf,
        peak_f32_at=pf_at,
        headroom_int32_bits=_headroom_bits(pi, INT32_MAX),
        headroom_f32_bits=_headroom_bits(pf, F32_EXACT),
        eqns=ctx.eqn_count,
    )


# ----------------------------------------------------------- certificates


def load_fingerprints(path: str = RANGE_FINGERPRINTS_PATH) -> dict:
    try:
        with open(path, encoding="utf-8") as f:
            return json.load(f)
    except FileNotFoundError:
        return {}


def write_fingerprints(
    reports: list, path: str = RANGE_FINGERPRINTS_PATH
) -> None:
    data = {r.kernel: r.fingerprint() for r in reports}
    with open(path, "w", encoding="utf-8") as f:
        json.dump(data, f, indent=2, sort_keys=True)
        f.write("\n")


def _diff_report(name: str, golden: dict, fresh: dict) -> str:
    lines = [f"kernel {name!r} drifted from its range certificate:"]
    for key in (
        "ok",
        "peak_int32",
        "peak_int32_at",
        "peak_f32",
        "peak_f32_at",
        "headroom_int32_bits",
        "headroom_f32_bits",
        "findings",
    ):
        b, a = golden.get(key), fresh.get(key)
        if b != a:
            lines.append(f"  {key}: {b!r} -> {a!r}")
    lines.append(
        "  deliberate change? regenerate with "
        "`python scripts/lint.py regen-ranges`"
    )
    return "\n".join(lines)


def compare_fingerprints(reports: list, golden: dict) -> list[Finding]:
    """Certificate drift findings for reports against the golden file."""
    findings: list[Finding] = []
    fresh_names = set()
    for r in reports:
        fresh_names.add(r.kernel)
        kernel = km.by_name().get(r.kernel)
        path = (
            km.module_path(kernel)
            if kernel is not None
            else "cometbft_tpu/analysis/kernel_manifest.py"
        )
        fresh = r.fingerprint()
        have = golden.get(r.kernel)
        if have is None:
            findings.append(Finding(
                "range-fingerprint", path, 1, 0,
                f"kernel {r.kernel!r} has no checked-in range certificate"
                " — run `python scripts/lint.py regen-ranges`",
            ))
        elif have != fresh:
            findings.append(Finding(
                "range-fingerprint", path, 1, 0,
                _diff_report(r.kernel, have, fresh),
            ))
    # stale = certificate names neither checked this run nor in the
    # manifest (targeted runs must not call unchecked goldens stale)
    known = fresh_names | set(km.by_name())
    for name in sorted(set(golden) - known):
        findings.append(Finding(
            "range-fingerprint",
            "cometbft_tpu/analysis/range_fingerprints.json", 1, 0,
            f"range certificate {name!r} names no manifest kernel — "
            "stale entry; regenerate the certificates",
        ))
    return findings


def _manifest_findings(kernels) -> list[Finding]:
    """Declared-spec shape errors (arity mismatches) are manifest bugs,
    not kernel findings."""
    findings: list[Finding] = []
    for k in kernels:
        ranges = getattr(k, "arg_ranges", None)
        if ranges is not None and len(ranges) != len(k.args):
            findings.append(Finding(
                "range-manifest",
                "cometbft_tpu/analysis/kernel_manifest.py", 1, 0,
                f"kernel {k.name!r}: arg_ranges has {len(ranges)} entries "
                f"for {len(k.args)} args",
            ))
        for rng in (ranges or ()):  # each entry None or (lo, hi)
            if rng is not None and rng[0] > rng[1]:
                findings.append(Finding(
                    "range-manifest",
                    "cometbft_tpu/analysis/kernel_manifest.py", 1, 0,
                    f"kernel {k.name!r}: empty declared range {rng}",
                ))
    return findings


def default_allowlist():
    from .linter import Allowlist, default_allowlist_path

    return Allowlist.load(default_allowlist_path())


def run_check(
    fingerprints_path: str = RANGE_FINGERPRINTS_PATH,
    kernels=None,
    allowlist=None,
) -> tuple[list[Finding], list]:
    """The full range pass: interpret every manifest kernel, enforce
    both contracts, and diff against the checked-in certificates.
    Returns (findings, reports); empty findings is the green gate.

    ``allowlist`` filters findings when given (the kernelcheck policy:
    raw by default so scripts/lint.py can track stale entries)."""
    kernels = tuple(kernels) if kernels is not None else km.KERNELS
    findings = _manifest_findings(kernels)
    reports = [check_kernel(k) for k in kernels]
    for r in reports:
        kernel = km.by_name().get(r.kernel)
        path = (
            km.module_path(kernel)
            if kernel is not None
            else "cometbft_tpu/analysis/kernel_manifest.py"
        )
        for msg in r.messages:
            findings.append(Finding(
                "range-contract", path, 1, 0, f"[{r.kernel}] {msg}"
            ))
    findings.extend(
        compare_fingerprints(reports, load_fingerprints(fingerprints_path))
    )
    if allowlist is not None:
        findings = [f for f in findings if not allowlist.suppresses(f)]
    return findings, reports


def regenerate(
    fingerprints_path: str = RANGE_FINGERPRINTS_PATH,
) -> tuple[list[Finding], list]:
    """Re-interpret everything and rewrite the certificate file.
    Contract findings still fail — regeneration only blesses drift,
    never an open overflow (the PR-6 policy)."""
    findings = _manifest_findings(km.KERNELS)
    reports = [check_kernel(k) for k in km.KERNELS]
    for r in reports:
        kernel = km.by_name().get(r.kernel)
        path = (
            km.module_path(kernel)
            if kernel is not None
            else "cometbft_tpu/analysis/kernel_manifest.py"
        )
        for msg in r.messages:
            findings.append(Finding(
                "range-contract", path, 1, 0, f"[{r.kernel}] {msg}"
            ))
    allow = default_allowlist()
    findings = [f for f in findings if not allow.suppresses(f)]
    if not findings:
        write_fingerprints(reports, fingerprints_path)
    return findings, reports


def summary(findings: list[Finding], reports: list) -> dict:
    """Machine-readable result (bench.py embeds this on backend-less
    rounds next to the kernelcheck/shardcheck summaries)."""
    return {
        "ok": not findings,
        "kernels": len(reports),
        "headroom": {
            r.kernel: {
                "ok": r.ok,
                "peak_int32": r.peak_int32,
                "peak_f32": r.peak_f32,
                "headroom_int32_bits": r.headroom_int32_bits,
                "headroom_f32_bits": r.headroom_f32_bits,
            }
            for r in reports
        },
        "findings": [
            {"check": f.check, "path": f.path, "message": f.message}
            for f in findings
        ],
    }


#: The fast hash-plane subset a bench round can afford to re-interpret
#: live (each under a second; the field kernels are minutes of CPU).
SPOT_KERNELS = (
    "sha256_blocks",
    "sha512_blocks",
    "keccak256_blocks",
    "merkle_root_from_leaves",
)


def bench_summary(spot_kernels=SPOT_KERNELS) -> dict:
    """Certificate-backed summary for bench embedding.

    The full interval pass is minutes of CPU (the ed25519/secp walks
    dominate), far over a bench round's patience, so headroom comes from
    the checked-in certificates; a LIVE spot-check re-interprets the
    hash-plane subset and diffs it against the same certificates, so a
    drifted tree still trips the round's ok bit."""
    golden = load_fingerprints()
    spot = [k for k in km.KERNELS if k.name in set(spot_kernels)]
    findings, reports = run_check(
        kernels=spot, allowlist=default_allowlist()
    )
    certs_ok = bool(golden) and all(
        v.get("ok") and not v.get("findings") for v in golden.values()
    )
    return {
        "ok": certs_ok and not findings,
        "mode": "certificates+spot",
        "certificates": len(golden),
        "certificates_ok": certs_ok,
        "spot_kernels": [k.name for k in spot],
        "spot_findings": [
            {"check": f.check, "path": f.path, "message": f.message}
            for f in findings
        ],
        "headroom": {
            name: {
                "ok": v.get("ok"),
                "peak_int32": v.get("peak_int32"),
                "peak_f32": v.get("peak_f32"),
                "headroom_int32_bits": v.get("headroom_int32_bits"),
                "headroom_f32_bits": v.get("headroom_f32_bits"),
            }
            for name, v in sorted(golden.items())
        },
    }


# ------------------------------------------------------- field headroom


#: Per-field conv structure for the max-safe-limb-width scaling law:
#: (bits, current limb width, dtype limit for the conv partial sums).
_FIELDS = {
    "ed25519": {"bits": 255, "width": 12, "limit": F32_EXACT},
    "secp256k1": {"bits": 256, "width": 12, "limit": INT32_MAX},
    "bls12-381": {"bits": 381, "width": 12, "limit": INT32_MAX},
}


def max_safe_limb_width(
    peak: int, bits: int, width: int = 12, limit: int = INT32_MAX
) -> int:
    """Widest limb w for which the measured conv peak, rescaled from
    ``width``-bit digits to w-bit digits, still fits ``limit``.

    The conv peak scales as the per-product magnitude (2^w - 1)^2 times
    the contraction depth ceil(bits / w): widening limbs grows each
    product quadratically but shrinks the number of products linearly.
    """
    if peak <= 0:
        return width
    depth0 = math.ceil(bits / width)
    per0 = ((1 << width) - 1) ** 2
    best = 0
    for w in range(1, 32):
        scale = (((1 << w) - 1) ** 2 / per0) * (math.ceil(bits / w) / depth0)
        if peak * scale <= limit:
            best = w
    return best


def field_headroom(reports: list) -> dict:
    """Per-field tightest-intermediate table: the max conv peak across
    that field's kernels, bits of slack, and the computed max safe limb
    width (the docs/limb_headroom.md payload)."""
    groups = {
        "ed25519": ("ed25519", "comb"),
        "secp256k1": ("secp",),
        "bls12-381": ("bls381",),
    }
    out = {}
    for fieldname, prefixes in groups.items():
        cfg = _FIELDS[fieldname]
        peak = 0
        at = ""
        for r in reports:
            if not any(p in r.kernel for p in prefixes):
                continue
            p, where = (
                (r.peak_f32, r.peak_f32_at)
                if cfg["limit"] == F32_EXACT
                else (r.peak_int32, r.peak_int32_at)
            )
            if p > peak:
                peak, at = p, f"{r.kernel} {where}"
        out[fieldname] = {
            "peak": peak,
            "at": at,
            "limit": cfg["limit"],
            "headroom_bits": _headroom_bits(peak, cfg["limit"]),
            "limb_width": cfg["width"],
            "max_safe_limb_width": max_safe_limb_width(
                peak, cfg["bits"], cfg["width"], cfg["limit"]
            ),
        }
    return out
