"""Check: unnamed-thread.

Every ``threading.Thread(...)`` must pass ``name=`` and every
``ThreadPoolExecutor(...)`` must pass ``thread_name_prefix=``: the
lock-witness reports, flight-recorder thread dumps
(utils/debugdump), and Perfetto traces (utils/tracing exports thread
name metadata) are unreadable when half the rows say ``Thread-7``.
This check makes the one-time naming sweep a permanent invariant.
"""

from __future__ import annotations

import ast

from .linter import Finding, Module, keyword_names, terminal_name

CHECK_ID = "unnamed-thread"
SUMMARY = "thread spawned without a human-readable name"


def check(mod: Module) -> list[Finding]:
    findings: list[Finding] = []
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Call):
            continue
        tn = terminal_name(node.func)
        if tn == "Thread" and "name" not in keyword_names(node):
            findings.append(
                Finding(
                    CHECK_ID, mod.path, node.lineno, node.col_offset,
                    "threading.Thread(...) without name= — witness "
                    "reports and trace exports need readable thread names",
                )
            )
        elif (
            tn == "ThreadPoolExecutor"
            and "thread_name_prefix" not in keyword_names(node)
        ):
            findings.append(
                Finding(
                    CHECK_ID, mod.path, node.lineno, node.col_offset,
                    "ThreadPoolExecutor(...) without thread_name_prefix=",
                )
            )
    return findings
