"""Check: swallowed-exception-in-thread.

A bare ``except:`` anywhere, or a broad ``except Exception/BaseException``
whose body is nothing but ``pass``/``...``.  In a daemon-thread run-loop
this is the worst failure mode the repo has: the thread dies or skips
work silently, consensus stalls, and nothing is logged, counted, or
dumped — the exact bug class PR 2's flight recorder exists to expose.
The fix is always one of: narrow the exception type, log at warning
with context and bump an error counter, or both.

Bare ``except:`` is flagged even with a non-trivial body because it also
catches ``SystemExit``/``KeyboardInterrupt`` and breaks shutdown.
Broad handlers that log/re-raise/record are fine and not flagged.
"""

from __future__ import annotations

import ast

from .linter import Finding, Module, terminal_name

CHECK_ID = "swallowed-exception-in-thread"
SUMMARY = "bare `except:` or broad except-with-`pass`-only body"

_BROAD = {"Exception", "BaseException"}


def _is_trivial_body(body: list[ast.stmt]) -> bool:
    for stmt in body:
        if isinstance(stmt, (ast.Pass, ast.Continue, ast.Break)):
            # `except Exception: continue` in a loop drops the error just
            # as silently as `pass` — the iteration vanishes untraced
            continue
        if isinstance(stmt, ast.Expr) and isinstance(stmt.value, ast.Constant):
            continue  # docstring / `...`
        if isinstance(stmt, ast.Return) and (
            stmt.value is None or isinstance(stmt.value, ast.Constant)
        ):
            # bailing out with a bare/constant return hides the error the
            # same way; returning a computed fallback is a real handler
            continue
        return False
    return True


def check(mod: Module) -> list[Finding]:
    findings: list[Finding] = []
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.ExceptHandler):
            continue
        if node.type is None:
            findings.append(
                Finding(
                    CHECK_ID, mod.path, node.lineno, node.col_offset,
                    "bare `except:` also swallows SystemExit/"
                    "KeyboardInterrupt — name the exception type",
                )
            )
            continue
        if terminal_name(node.type) in _BROAD and _is_trivial_body(node.body):
            findings.append(
                Finding(
                    CHECK_ID, mod.path, node.lineno, node.col_offset,
                    f"`except {terminal_name(node.type)}` swallows the "
                    "error with a pass-only body — log at warning with "
                    "context and bump an error counter, or narrow the type",
                )
            )
    return findings
