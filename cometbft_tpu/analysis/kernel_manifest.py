"""The kernel manifest: every jitted entry point of the TPU verify
plane, declared once.

This file is the single source of truth three consumers share:

* ``analysis/kernelcheck.py`` abstract-interprets each declared kernel
  (``jax.make_jaxpr`` under ``JAX_PLATFORMS=cpu``) and enforces the
  numeric contract — dtype closure, jaxpr purity, primitive-budget /
  fingerprint drift (``analysis/kernel_fingerprints.json``).
* the ``untracked-jit`` AST check fails any ``jax.jit`` site in the
  kernel plane that is not registered in :data:`JIT_SITES` — a new jit
  entry point cannot land without a manifest row (and therefore without
  a traced fingerprint).
* the ``weak-type-literal`` / ``jax-purity`` checks seed their traced
  closures from :func:`traced_roots` — functions jitted from *another*
  module (``ops/sha2.sha512_blocks`` is jitted via ``models/``) are
  invisible to a per-module jit-root scan, but not to the manifest.

Deliberately stdlib-only (no jax, no numpy): the AST linter half must
run anywhere the stdlib does.  Shapes here are the CANONICAL trace
shapes — small enough to trace in milliseconds, shaped exactly like
production (batch lane minor, limbs on sublanes) so the traced program
is the production program at a smaller lane count.
"""

from __future__ import annotations

from dataclasses import dataclass

# Canonical trace sizes.  V = validator lanes for the comb path, N =
# signature batch for the uncached path.  Small on purpose: jaxpr shape
# and primitive mix do not depend on the lane count, only trace time
# does.
V = 4  # comb-path validator lanes
N = 8  # uncached-path signature lanes
MAXM = 32  # payload message bucket (models/comb_verifier._bucket_mlen floor)
PAYLOAD_W = 68 + MAXM  # R(32) | s(32) | mlen(3) | live(1) | msg


@dataclass(frozen=True)
class Arg:
    """One traced input/output leaf: shape + dtype name."""

    shape: tuple[int, ...]
    dtype: str


def u8(*shape: int) -> Arg:
    return Arg(shape, "uint8")


def i32(*shape: int) -> Arg:
    return Arg(shape, "int32")


def f32(*shape: int) -> Arg:
    return Arg(shape, "float32")


def boolean(*shape: int) -> Arg:
    return Arg(shape, "bool")


@dataclass(frozen=True)
class Kernel:
    """One jitted entry point: where it lives, how to trace it, what it
    must produce.

    fn            : "package.module:function".  With needs_mesh, the
                    function is a FACTORY taking (mesh, *mesh_static)
                    and returning the jitted callable (the
                    parallel/verify.py pattern).
    args          : canonical input leaves, in call order.
    out           : expected output leaves (flattened pytree order) —
                    checked against the traced out_avals, so an output
                    shape/dtype drift fails before any fingerprint
                    comparison.
    static_kwargs : Python-level keyword arguments bound before tracing
                    (trace-time constants: the comb tree flag, churn V);
                    with needs_mesh they are bound onto the factory call.
    needs_mesh    : build a 1-device CPU mesh and call fn as a factory.
    mesh_static   : extra factory positionals after the mesh.
    max_eqns      : compile-cost budget — hard ceiling on the traced
                    jaxpr's total equation count (nested bodies
                    included).  EVERY production kernel must declare a
                    positive budget (kernelcheck fails the manifest
                    otherwise): the old ``comb_build_a_tables`` rode
                    unbudgeted past the PR-6 gate straight into a 2m34s
                    XLA compile (MULTICHIP_r05); that grandfather clause
                    is gone.  Budgets are measured counts plus ~30%
                    headroom — an unrolled-loop blowup fails in
                    milliseconds, an innocuous +1 eqn does not.
    arg_ranges    : declared input value ranges for the range abstract
                    interpreter (analysis/rangecheck.py), one entry per
                    arg: ``(lo, hi)`` inclusive, or None for the full
                    dtype range.  These are the ASSUMPTIONS the range
                    certificates are proved under — callers owe them
                    (canonical limb digits [0, 2^12), flags {0, 1},
                    active block counts).  None for the whole tuple =
                    every arg at its dtype range.
    out_ranges    : declared output ranges, same shape as ``out`` —
                    the checker PROVES these hold (canonical digits out
                    means limb-equality-is-value-equality downstream).
                    None entries are unchecked.
    loop_invariants : assume-guarantee bounds for scan carries where
                    widening is too coarse: ``(scan_ordinal,
                    carry_ordinal, lo, hi)`` tuples, ordinals in
                    interpretation (pre-order) encounter order.  The
                    checker verifies each declared bound covers the
                    initial carry and is inductive before using it.
    """

    name: str
    fn: str
    args: tuple[Arg, ...]
    out: tuple[Arg, ...]
    static_kwargs: tuple[tuple[str, object], ...] = ()
    needs_mesh: bool = False
    mesh_static: tuple = ()
    max_eqns: int = 0  # fixture rows may omit; production rows may not
    arg_ranges: tuple | None = None
    out_ranges: tuple | None = None
    loop_invariants: tuple = ()


_TABLES = i32(64, 9, 3, 22, V)  # ops/comb.py layout: validator axis minor
_B_TABLES = f32(22, 66, 4096)  # shared radix-4096 base-point comb

# Declared value ranges (analysis/rangecheck.py input specs).
DIGITS = (0, 4095)  # canonical 12-bit limb digit, ops/field.py freeze()
FLAG = (0, 1)  # bit-packed / boolean-as-int payload field


KERNELS: tuple[Kernel, ...] = (
    # ---- ops/comb.py — the validator-set fast path
    Kernel(
        # scan-rolled since PR 11 (measured 25,359 eqns; the unrolled
        # pre-rework build was ~84k and compiled for 2m34s) — this budget
        # is the deleted grandfather clause
        name="comb_build_a_tables",
        fn="cometbft_tpu.ops.comb:build_a_tables",
        args=(u8(V, 32),),
        out=(_TABLES, boolean(V)),
        max_eqns=32_000,
        out_ranges=(DIGITS, None),
    ),
    Kernel(
        name="comb_verify_cached_tree",
        fn="cometbft_tpu.ops.comb:verify_cached",
        args=(_TABLES, boolean(V), u8(V, 32), u8(V, 32), u8(V, 64), _B_TABLES),
        out=(boolean(V),),
        static_kwargs=(("tree", True),),
        max_eqns=50_000,  # measured 38,618
        arg_ranges=(DIGITS, None, None, None, None, DIGITS),
    ),
    Kernel(
        # the sequential cross-check path must stay pinned too: it is the
        # bit-exactness witness for the tree path (COMETBFT_TPU_COMB_TREE=0)
        name="comb_verify_cached_seq",
        fn="cometbft_tpu.ops.comb:verify_cached",
        args=(_TABLES, boolean(V), u8(V, 32), u8(V, 32), u8(V, 64), _B_TABLES),
        out=(boolean(V),),
        static_kwargs=(("tree", False),),
        max_eqns=36_000,  # measured 27,633
        arg_ranges=(DIGITS, None, None, None, None, DIGITS),
    ),
    # ---- ops/ed25519.py — the uncached Straus kernel
    Kernel(
        name="ed25519_verify_batch",
        fn="cometbft_tpu.ops.ed25519:verify_batch",
        args=(u8(N, 32), u8(N, 32), u8(N, 32), u8(N, 2, 128), i32(N)),
        out=(boolean(N),),
        max_eqns=100_000,  # measured 76,880
        arg_ranges=(None, None, None, None, (0, 2)),
    ),
    # ---- ops/sha2.py — challenge hashing + device payload assembly
    Kernel(
        name="sha256_blocks",
        fn="cometbft_tpu.ops.sha2:sha256_blocks",
        args=(u8(N, 2, 64), i32(N)),
        out=(u8(N, 32),),
        max_eqns=1_000,  # measured 153
        arg_ranges=(None, (0, 2)),
    ),
    Kernel(
        name="sha512_blocks",
        fn="cometbft_tpu.ops.sha2:sha512_blocks",
        args=(u8(N, 2, 128), i32(N)),
        out=(u8(N, 64),),
        max_eqns=1_000,  # measured 376
        arg_ranges=(None, (0, 2)),
    ),
    Kernel(
        name="sha2_parse_verify_payload",
        fn="cometbft_tpu.ops.sha2:parse_verify_payload",
        args=(u8(N, PAYLOAD_W), u8(N, 32)),
        out=(u8(N, 32), u8(N, 32), u8(N, 1, 128), i32(N), boolean(N)),
        max_eqns=500,  # measured 79
    ),
    # ---- ops/merkle.py — the block-hash pass
    Kernel(
        name="merkle_root_from_leaves",
        fn="cometbft_tpu.ops.merkle:root_from_leaves",
        args=(u8(N, 1, 64), i32(N)),
        out=(u8(32),),
        max_eqns=2_000,  # measured 628
        arg_ranges=(None, (0, 1)),
    ),
    # the proof-serving plane: ONE dispatch retains every interior level
    # and one-hot-gathers K audit paths.  Sibling positions are computed
    # on HOST (crypto/merkle.proof_plan) so the traced program carries no
    # data-dependent control flow and no xor/shift index arithmetic —
    # the gathers are MXU matmuls over {0,1} masks (exact in f32).
    # Trace shape: n=8 leaves (depth 3), K=4 queries.
    Kernel(
        name="merkle_proofs_from_leaves",
        fn="cometbft_tpu.ops.merkle:proofs_from_leaves",
        args=(u8(N, 1, 64), i32(N), i32(4), i32(4, 3)),
        out=(u8(32), u8(4, 32), u8(4, 3, 32)),
        max_eqns=1_500,  # measured 990
        # indices are valid leaf positions; sib_pos carries -1 as the
        # "no aunt at this level" sentinel (promoted odd trailing node)
        arg_ranges=(None, (0, 1), (0, N - 1), (-1, N - 1)),
    ),
    # the multiproof shape: M deduplicated nodes gathered from the flat
    # level concatenation (n + ceil(n/2) + ... + 1 = 15 nodes at n=8);
    # shared aunts appear once however many queries need them.
    Kernel(
        name="merkle_multiproof_from_leaves",
        fn="cometbft_tpu.ops.merkle:multiproof_from_leaves",
        args=(u8(N, 1, 64), i32(N), i32(6)),
        out=(u8(32), u8(6, 32)),
        max_eqns=1_500,  # measured 951
        arg_ranges=(None, (0, 1), (0, 14)),
    ),
    # ---- ops/bls381.py — the FastAggregateVerify data plane: batched
    # KeyValidate (on-curve + subgroup) and the tree-reduced G1 pubkey
    # sum; Miller loop + final exponentiation stay on host
    # (crypto/bls12381), exactly as the reference keeps them in blst
    Kernel(
        name="bls381_aggregate_g1",
        fn="cometbft_tpu.ops.bls381:aggregate_g1",
        args=(i32(N, 32), i32(N, 32), i32(N, 32)),
        out=(i32(32), i32(32), i32(32)),
        max_eqns=18_000,  # measured 12,966
        arg_ranges=(DIGITS, DIGITS, DIGITS),
        out_ranges=(DIGITS, DIGITS, DIGITS),
    ),
    Kernel(
        # subgroup check = [r]P via lax.scan over the 255 order bits: the
        # jaxpr is O(1) in the bit count (one double+add body), so the
        # budget is small despite the 255-step runtime chain
        name="bls381_validate_g1",
        fn="cometbft_tpu.ops.bls381:validate_g1",
        args=(i32(N, 32), i32(N, 32), boolean(N)),
        out=(boolean(N),),
        max_eqns=8_500,  # measured 6,474
        arg_ranges=(DIGITS, DIGITS, None),
    ),
    Kernel(
        # validation + tree-reduced aggregation fused into ONE dispatch —
        # the aggregate-commit hot path (one device call per commit)
        name="bls381_validate_aggregate_g1",
        fn="cometbft_tpu.ops.bls381:validate_aggregate_g1",
        args=(i32(N, 32), i32(N, 32), boolean(N)),
        out=(boolean(N), i32(32), i32(32), i32(32)),
        max_eqns=26_000,  # measured 19,445
        arg_ranges=(DIGITS, DIGITS, None),
        out_ranges=(None, DIGITS, DIGITS, DIGITS),
    ),
    # ---- ops/secp256k1.py — the batched ECDSA lane (MODE_SECP):
    # range/low-s validation, Montgomery batch inversion (s^-1 mod n and
    # the affine z^-1 mod p, one Fermat chain each), the scalar walk
    # u1*G + u2*Q, and the cosmos/eth/ecrecover verdicts — ONE fused
    # program.  The G window table is host-precomputed and
    # device_put-resident (PR-11 pattern: never a table-build compile),
    # passed as the last tensor argument.  TWO static axes, each the
    # COMB_TREE witness pattern: ``glv`` selects the GLV endomorphism
    # quad-scalar walk over 33 windows (True, the default) vs the plain
    # 66-window Shamir chain (False, the bit-exactness witness —
    # COMETBFT_TPU_SECP_GLV=0), and ``recover`` adds the ecrecover
    # R-lift (sqrt chain) + recovered-address Keccak, traced only when
    # a batch actually carries ecrecover rows.  All four combinations
    # are declared so none can drift unfingerprinted.
    Kernel(
        name="secp256k1_verify_batch",
        fn="cometbft_tpu.ops.secp256k1:verify_batch",
        args=(
            i32(N, 22), i32(N, 22), boolean(N),  # pubkey x, y, decode-ok
            i32(N, 22), i32(N, 22), i32(N, 22),  # e, r, s (raw 256-bit)
            boolean(N), i32(N),  # eth-row flag, recovery id
            boolean(N), u8(N, 20),  # ecrecover-row flag, sender address
            i32(16, 66),  # resident G window table (flat Jacobian rows)
        ),
        out=(boolean(N),),
        static_kwargs=(("glv", True), ("recover", False)),
        max_eqns=28_000,  # measured 21,248
        arg_ranges=(DIGITS, DIGITS, None, DIGITS, DIGITS, DIGITS, None, FLAG, None, None, DIGITS),
    ),
    Kernel(
        name="secp256k1_verify_batch_recover",
        fn="cometbft_tpu.ops.secp256k1:verify_batch",
        args=(
            i32(N, 22), i32(N, 22), boolean(N),
            i32(N, 22), i32(N, 22), i32(N, 22),
            boolean(N), i32(N), boolean(N), u8(N, 20),
            i32(16, 66),
        ),
        out=(boolean(N),),
        static_kwargs=(("glv", True), ("recover", True)),
        max_eqns=29_500,  # measured 22,694
        arg_ranges=(DIGITS, DIGITS, None, DIGITS, DIGITS, DIGITS, None, FLAG, None, None, DIGITS),
    ),
    Kernel(
        name="secp256k1_verify_batch_noglv",
        fn="cometbft_tpu.ops.secp256k1:verify_batch",
        args=(
            i32(N, 22), i32(N, 22), boolean(N),
            i32(N, 22), i32(N, 22), i32(N, 22),
            boolean(N), i32(N), boolean(N), u8(N, 20),
            i32(16, 66),
        ),
        out=(boolean(N),),
        static_kwargs=(("glv", False), ("recover", False)),
        max_eqns=18_000,  # measured 13,688 (the pre-GLV program, unchanged)
        arg_ranges=(DIGITS, DIGITS, None, DIGITS, DIGITS, DIGITS, None, FLAG, None, None, DIGITS),
    ),
    Kernel(
        name="secp256k1_verify_batch_noglv_recover",
        fn="cometbft_tpu.ops.secp256k1:verify_batch",
        args=(
            i32(N, 22), i32(N, 22), boolean(N),
            i32(N, 22), i32(N, 22), i32(N, 22),
            boolean(N), i32(N), boolean(N), u8(N, 20),
            i32(16, 66),
        ),
        out=(boolean(N),),
        static_kwargs=(("glv", False), ("recover", True)),
        max_eqns=20_000,  # measured 15,134
        arg_ranges=(DIGITS, DIGITS, None, DIGITS, DIGITS, DIGITS, None, FLAG, None, None, DIGITS),
    ),
    # the fused hash->verify program: padded message bytes in, verdicts
    # out — SHA-256 (cosmos) and Keccak-256 (eth/ecrecover) digests
    # computed on device and multiplexed per row, then the verify_batch
    # body.  Trace shape = the CheckTx envelope bucket
    # (COMETBFT_TPU_SECP_HASH_MAX_LEN=119: 2 SHA blocks, 1 Keccak block).
    Kernel(
        name="secp256k1_hash_verify",
        fn="cometbft_tpu.ops.secp256k1:hash_verify_batch",
        args=(
            u8(N, 2, 64), i32(N),  # SHA-256-padded blocks + active
            u8(N, 1, 136), i32(N),  # Keccak-padded blocks + active
            i32(N, 22), i32(N, 22), boolean(N),  # pubkey x, y, decode-ok
            i32(N, 22), i32(N, 22),  # r, s
            boolean(N), i32(N), boolean(N), u8(N, 20),
            i32(16, 66),
        ),
        out=(boolean(N),),
        static_kwargs=(("glv", True), ("recover", False)),
        max_eqns=29_000,  # measured 22,111
        arg_ranges=(None, (0, 2), None, FLAG, DIGITS, DIGITS, None, DIGITS, DIGITS, None, FLAG, None, None, DIGITS),
    ),
    Kernel(
        name="secp256k1_hash_verify_recover",
        fn="cometbft_tpu.ops.secp256k1:hash_verify_batch",
        args=(
            u8(N, 2, 64), i32(N),
            u8(N, 1, 136), i32(N),
            i32(N, 22), i32(N, 22), boolean(N),
            i32(N, 22), i32(N, 22),
            boolean(N), i32(N), boolean(N), u8(N, 20),
            i32(16, 66),
        ),
        out=(boolean(N),),
        static_kwargs=(("glv", True), ("recover", True)),
        max_eqns=30_500,  # measured 23,557
        arg_ranges=(None, (0, 2), None, FLAG, DIGITS, DIGITS, None, DIGITS, DIGITS, None, FLAG, None, None, DIGITS),
    ),
    # ---- ops/keccak.py — batched Keccak-256 (the Ethereum 0x01-padded
    # variant): (hi, lo) uint32 lane halves, 24 rounds as ONE fori_loop
    # body, rho/pi statically unrolled — the hashing half the fused secp
    # program inlines, also dispatched standalone via keccak256_device.
    Kernel(
        name="keccak256_blocks",
        fn="cometbft_tpu.ops.keccak:keccak256_blocks",
        args=(u8(N, 1, 136), i32(N)),
        out=(u8(N, 32),),
        max_eqns=700,  # measured 577 (fori-rolled: O(1) in round count)
        arg_ranges=(None, (0, 1)),
    ),
    # ---- models/comb_verifier.py — cache assembly + the device program
    Kernel(
        name="comb_assemble_churn",
        fn="cometbft_tpu.models.comb_verifier:_assemble_churn",
        args=(
            _TABLES, boolean(V),
            i32(64, 9, 3, 22, 2), boolean(2),  # freshly built bucket (2 keys)
            i32(2), i32(2), i32(2),  # new_rows, base_rows, fresh_rows
        ),
        out=(_TABLES, boolean(V)),
        static_kwargs=(("V", V),),
        max_eqns=500,  # measured 32
        arg_ranges=(DIGITS, None, DIGITS, None, (0, V - 1), (0, V - 1),
                    (0, V - 1)),
        out_ranges=(DIGITS, None),
    ),
    Kernel(
        name="comb_device_verify",
        fn="cometbft_tpu.models.comb_verifier:_device_verify",
        args=(_TABLES, boolean(V), u8(V, 32), u8(V, PAYLOAD_W)),
        out=(u8(2),),  # packbits(V=4 lanes) -> 1 byte, + the all-ok byte
        max_eqns=50_000,  # measured 39,068
        arg_ranges=(DIGITS, None, None, None),
    ),
    # ---- parallel/verify.py — the mesh-sharded programs (1-device CPU
    # mesh for the trace; the collective mix is what the fingerprint pins)
    Kernel(
        name="sharded_verify_batch",
        fn="cometbft_tpu.parallel.verify:_verify_fn",
        args=(u8(N, 32), u8(N, 32), u8(N, 32), u8(N, 2, 128), i32(N)),
        out=(boolean(), boolean(N)),
        needs_mesh=True,
        max_eqns=100_000,  # measured 76,888
        arg_ranges=(None, None, None, None, (0, 2)),
    ),
    Kernel(
        name="sharded_verify_cached",
        fn="cometbft_tpu.parallel.verify:_comb_verify_fn",
        args=(_TABLES, boolean(V), u8(V, 32), u8(V, PAYLOAD_W)),
        out=(u8(2),),
        needs_mesh=True,
        mesh_static=(True,),  # tree=True, part of the jit cache key
        max_eqns=50_000,  # measured 39,075
        arg_ranges=(DIGITS, None, None, None),
    ),
    Kernel(
        name="sharded_merkle_root",
        fn="cometbft_tpu.parallel.verify:_merkle_fn",
        args=(u8(N, 1, 64), i32(N)),
        out=(u8(32),),
        needs_mesh=True,
        max_eqns=2_000,  # measured 633
        arg_ranges=(None, (0, 1)),
    ),
    Kernel(
        # query axis sharded, tree replicated: every device holds the
        # whole (small) tree and answers its own K/devices queries with
        # ZERO collectives — the proof fan-out scaling shape
        name="sharded_merkle_proofs",
        fn="cometbft_tpu.parallel.verify:_merkle_proofs_fn",
        args=(u8(N, 1, 64), i32(N), i32(4), i32(4, 3)),
        out=(u8(32), u8(4, 32), u8(4, 3, 32)),
        needs_mesh=True,
        max_eqns=1_500,  # measured 995
        arg_ranges=(None, (0, 1), (0, N - 1), (-1, N - 1)),
    ),
)


# --------------------------------------------------------------- jit sites
#
# Every ``jax.jit`` call/decorator site in the kernel plane (ops/,
# parallel/, models/, crypto/), keyed "path::target" where target is the
# jitted function's name (or the enclosing factory for composed sites
# like ``jax.jit(shard_map(local))``).  The value names the manifest
# kernel whose trace covers the site.  The ``untracked-jit`` check fails
# any site missing here; kernelcheck fails any value naming no kernel.

JIT_SITES: dict[str, str] = {
    "cometbft_tpu/ops/comb.py::build_a_tables": "comb_build_a_tables",
    "cometbft_tpu/ops/bls381.py::aggregate_g1": "bls381_aggregate_g1",
    "cometbft_tpu/ops/bls381.py::validate_g1": "bls381_validate_g1",
    "cometbft_tpu/ops/bls381.py::validate_aggregate_g1": (
        "bls381_validate_aggregate_g1"
    ),
    "cometbft_tpu/ops/secp256k1.py::verify_batch": "secp256k1_verify_batch",
    "cometbft_tpu/ops/secp256k1.py::hash_verify_batch": "secp256k1_hash_verify",
    "cometbft_tpu/ops/keccak.py::keccak256_blocks": "keccak256_blocks",
    # models/verifier.py jits ops/ed25519.verify_batch (the uncached path)
    "cometbft_tpu/models/verifier.py::verify_batch": "ed25519_verify_batch",
    "cometbft_tpu/models/comb_verifier.py::_assemble_churn": "comb_assemble_churn",
    "cometbft_tpu/models/comb_verifier.py::_device_verify": "comb_device_verify",
    # parallel factories: jax.jit(shard_map(local)) — registered under the
    # enclosing factory name, traced through a 1-device mesh
    "cometbft_tpu/parallel/verify.py::_verify_fn": "sharded_verify_batch",
    "cometbft_tpu/parallel/verify.py::_comb_verify_fn": "sharded_verify_cached",
    "cometbft_tpu/parallel/verify.py::_merkle_fn": "sharded_merkle_root",
    "cometbft_tpu/parallel/verify.py::_merkle_proofs_fn": "sharded_merkle_proofs",
    # crypto/merkle.py jits ops/merkle.root_from_leaves for host callers
    "cometbft_tpu/crypto/merkle.py::root_from_leaves": "merkle_root_from_leaves",
    # crypto/merkle.py jits the proof kernels for the proof-serving plane
    "cometbft_tpu/crypto/merkle.py::proofs_from_leaves": (
        "merkle_proofs_from_leaves"
    ),
    "cometbft_tpu/crypto/merkle.py::multiproof_from_leaves": (
        "merkle_multiproof_from_leaves"
    ),
}


# ------------------------------------------------------ collect boundaries
#
# Functions in ops//parallel/ that are DECLARED host<->device collect
# points: the documented places where a device value is fetched to host
# (np.asarray on a device array, the one blocking sync of a pipeline).
# The ``host-sync-in-hot-path`` check exempts these; anywhere else in
# the hot path a sync is a finding.

COLLECT_BOUNDARIES: dict[str, str] = {
    "cometbft_tpu/ops/comb.py::build_a_tables_host": (
        "the host-precomputed A-table build: pure host bigint/numpy by "
        "design (the compile-free cold-start path); its np.asarray "
        "normalizes the caller's host pubkey array, never a device fetch"
    ),
    "cometbft_tpu/ops/bls381.py::aggregate_pubkeys_device": (
        "the BLS host bridge: one blocking fetch of the aggregated point"
    ),
    "cometbft_tpu/ops/bls381.py::validate_pubkeys_device": (
        "the BLS validation bridge: one blocking fetch of the per-row "
        "validity bits"
    ),
    "cometbft_tpu/ops/bls381.py::validate_aggregate_device": (
        "the fused FastAggregateVerify bridge: one blocking fetch of "
        "(validity bits, aggregate point)"
    ),
    "cometbft_tpu/ops/bls381.py::_jac_to_affine_host": (
        "host-side Jacobian->affine converter for an already-computed "
        "device aggregate; its np.asarray is THE one result fetch"
    ),
    "cometbft_tpu/ops/bls381.py::from_limbs": (
        "host-side limb decoder; receives the already-fetched aggregate"
    ),
    "cometbft_tpu/ops/field.py::from_limbs": (
        "host-side limb decoder used by tests and host bridges"
    ),
    "cometbft_tpu/ops/secp256k1.py::verify_batch_device": (
        "the secp ECDSA bridge: one blocking fetch of the per-row "
        "verdict bits"
    ),
    "cometbft_tpu/ops/secp256k1.py::hash_verify_batch_device": (
        "the fused hash->verify bridge: one blocking fetch of the "
        "per-row verdict bits"
    ),
    "cometbft_tpu/ops/keccak.py::keccak256_device": (
        "the batched Keccak-256 bridge: one blocking fetch of the "
        "digests"
    ),
    "cometbft_tpu/ops/secp256k1.py::from_limbs": (
        "host-side limb decoder (tests); receives already-fetched "
        "results"
    ),
}
# NOT boundaries: the parallel/mesh.py factories' np.array calls wrap
# the host device list — the host-sync check recognizes devices()
# dataflow itself, so the fetch-boundary registry stays exactly the
# set of real host<->device collect points.


def collect_boundary(path: str, target: str) -> bool:
    """True when ``path::target`` is a declared host boundary (suffix
    match on a '/' boundary, same rule as :func:`site_registered`)."""
    for site in COLLECT_BOUNDARIES:
        rpath, _, rtarget = site.partition("::")
        if target != rtarget:
            continue
        if path == rpath or path.endswith("/" + rpath):
            return True
    return False


# ------------------------------------------------------- dtype conversions
#
# Every ``convert_element_type`` a manifest kernel is allowed to contain,
# as (src, dst) dtype-name pairs.  Anything outside this set fails the
# dtype-closure gate: an unlisted conversion is exactly how silent
# promotion creep lands.  Keep each pair justified.

ALLOWED_CONVERSIONS: frozenset[tuple[str, str]] = frozenset(
    {
        # byte <-> word unpacking at kernel edges
        ("uint8", "int32"),  # payload/scalar bytes -> limb arithmetic
        ("uint8", "uint32"),  # SHA message bytes -> 32-bit words
        ("uint32", "uint8"),  # digest words -> output bytes
        ("int32", "uint8"),  # packed flags / byte stores
        # the one-hot MXU matmul round trip (ops/comb.py b-part lookup:
        # 12-bit Niels limbs are exact in f32; HIGHEST precision)
        ("int32", "float32"),
        ("float32", "int32"),
        # masks and validity plumbing
        ("bool", "uint32"),  # SHA-512 (hi, lo) pair addition: the carry
        #   of each 32-bit lane add is (lo < al).astype(uint32)
        #   (ops/sha2._add64) — 64-bit words don't exist on TPU
        ("bool", "int32"),  # invalid-count psum accumulators
        ("bool", "uint8"),  # the all-ok byte of the packed result
        ("bool", "float32"),  # one-hot select masks on the MXU path
        ("int32", "bool"),  # borrow-chain compare results
        ("uint8", "bool"),  # live-row flags decoded from the payload
    }
)

# Jaxpr-level dtypes that must NEVER appear in a kernel: 64-bit creep
# either silently doubles HBM traffic or (under the default x64-disabled
# config) silently truncates — both are contract violations.
FORBIDDEN_DTYPES: frozenset[str] = frozenset(
    {"int64", "uint64", "float64", "complex64", "complex128"}
)


# ------------------------------------------------------- sharded kernels
#
# The sharding extension of the manifest: every mesh-parameterized
# kernel (the parallel/verify.py factories) declares, next to its trace
# shapes, the SHARDED-PLANE contract ``analysis/shardcheck.py`` enforces
# under a real 8-way CPU mesh (subprocess with
# ``XLA_FLAGS=--xla_force_host_platform_device_count=8``):
#
# * ``in_specs``/``out_specs`` — the intended PartitionSpec per
#   argument/output, spelled stdlib-only as one tuple per array with an
#   axis name (or None) per dimension; ``()`` = fully replicated.  The
#   checker compares them against the traced shard_map's in/out names,
#   so a silent respec (a stage suddenly receiving replicated rows it
#   expected sharded) fails statically.
# * ``donate_argnums`` — arguments the lowered program must actually
#   donate (and nothing else): the staging-slab HBM-reuse discipline of
#   ROADMAP item 1, checked on the pjit's ``donated_invars``.
#   ``entry_donated_params`` names the same arguments as (param-name,
#   positional-index) of the PUBLIC wrapper, for the
#   ``donated-read-after-dispatch`` AST check.
# * ``collectives`` — the declared collective census.  Any collective
#   primitive (psum / all_gather / all_to_all / ppermute /
#   sharding_constraint resharding copies, ...) the traced program
#   contains beyond this census is a finding: silent reshard-per-stage
#   is exactly how a pipelined handoff degrades to gather+scatter.
# * ``max_eqns`` / ``max_loop_depth`` / ``max_device_bytes`` — the
#   compile-cost budget: total jaxpr equation count (an unrolled table
#   build lands thousands of flat equations — the static face of the
#   2m34s ``jit_build_a_tables`` XLA compile), deepest nested
#   control-flow loop, and a per-device peak-bytes estimate from the
#   shard_map body's (already per-device) avals.
#
# ``name`` must match a ``needs_mesh`` Kernel row above (same fn ref) so
# the two declarations cannot drift apart; ``args``/``out`` here are the
# 8-way trace shapes (every sharded axis divisible by the mesh).

SHARD_MESH_DEVICES = 8  # the CI mesh: forced host devices in the child
SHARD_AXIS = "sig"

V8 = 8  # validator lanes under the 8-way mesh (1 per device)
_TABLES8 = i32(64, 9, 3, 22, V8)


@dataclass(frozen=True)
class ShardedKernel:
    """One mesh-parameterized kernel's sharded-plane contract."""

    name: str  # the needs_mesh Kernel row this extends
    entrypoint: str  # public wrapper in parallel/verify.py
    args: tuple[Arg, ...]  # 8-way trace shapes
    out: tuple[Arg, ...]
    in_specs: tuple[tuple, ...]  # per arg: axis-or-None per dim
    out_specs: tuple[tuple, ...]
    collectives: tuple[tuple[str, int], ...]  # declared census
    max_eqns: int  # compile-cost budget: total equation count
    max_loop_depth: int  # deepest nested scan/while body
    max_device_bytes: int  # per-device peak-bytes estimate ceiling
    donate_argnums: tuple[int, ...] = ()
    # (wrapper param name, wrapper positional index) per donated arg
    entry_donated_params: tuple[tuple[str, int], ...] = ()


SHARDED_KERNELS: tuple[ShardedKernel, ...] = (
    ShardedKernel(
        name="sharded_verify_batch",
        entrypoint="sharded_verify_batch",
        args=(u8(N, 32), u8(N, 32), u8(N, 32), u8(N, 2, 128), i32(N)),
        out=(boolean(), boolean(N)),
        in_specs=(
            (SHARD_AXIS,),
            (SHARD_AXIS,),
            (SHARD_AXIS,),
            (SHARD_AXIS, None, None),
            (SHARD_AXIS,),
        ),
        out_specs=((), ()),
        # one psum folds the per-device bad counts, one all_gather
        # replicates the blame vector; anything else is a reshard
        collectives=(("all_gather", 1), ("psum", 1)),
        # measured 76,888 eqns / loop depth 1 / ~11 KB per device at the
        # 8-lane trace; budgets leave headroom for kernel evolution but
        # fail an unrolled-table-build-class blowup immediately
        max_eqns=100_000,
        max_loop_depth=4,
        max_device_bytes=8 << 20,
        # every argument is a per-call staging transfer, dead after
        # dispatch — all five donated (PR-11: "finish the set")
        donate_argnums=(0, 1, 2, 3, 4),
        entry_donated_params=(
            ("a_enc", 1), ("r_enc", 2), ("s_bytes", 3),
            ("msg_blocks", 4), ("msg_active", 5),
        ),
    ),
    ShardedKernel(
        name="sharded_verify_cached",
        entrypoint="sharded_verify_cached",
        args=(_TABLES8, boolean(V8), u8(V8, 32), u8(V8, PAYLOAD_W)),
        out=(u8(2),),
        in_specs=(
            (None, None, None, None, SHARD_AXIS),  # tables: lanes minor
            (SHARD_AXIS,),
            (SHARD_AXIS, None),  # pubs
            (SHARD_AXIS, None),  # payload rows
        ),
        out_specs=((),),
        collectives=(("all_gather", 1), ("psum", 1)),
        # measured 39,075 eqns / loop depth 1 / ~24.9 MB per device at
        # the 8-lane trace (the replicated radix-4096 basepoint comb is
        # ~23.8 MB on EVERY device — the estimate is dominated by it)
        max_eqns=50_000,
        max_loop_depth=4,
        max_device_bytes=48 << 20,
        # the per-call staging payload is consumed by the dispatch;
        # tables/valid/pubs persist in the cache entry — never donated
        donate_argnums=(3,),
        entry_donated_params=(("payload", 4),),  # wrapper: (mesh, t, v, p, payload)
    ),
    ShardedKernel(
        name="sharded_merkle_root",
        entrypoint="sharded_merkle_root",
        args=(u8(N, 1, 64), i32(N)),
        out=(u8(32),),
        in_specs=((SHARD_AXIS, None, None), (SHARD_AXIS,)),
        out_specs=((),),
        collectives=(("all_gather", 1),),
        # measured 633 eqns / loop depth 1 / ~4 KB per device
        max_eqns=2_000,
        max_loop_depth=4,
        max_device_bytes=1 << 20,
        # per-call leaf staging transfers, dead after dispatch
        donate_argnums=(0, 1),
        entry_donated_params=(("leaf_blocks", 1), ("leaf_active", 2)),
    ),
    ShardedKernel(
        name="sharded_merkle_proofs",
        entrypoint="sharded_merkle_proofs",
        # 8-way trace: n=8 leaves replicated, K=8 queries (1 per device)
        args=(u8(N, 1, 64), i32(N), i32(V8), i32(V8, 3)),
        out=(u8(32), u8(V8, 32), u8(V8, 3, 32)),
        in_specs=(
            (),  # leaf blocks: replicated (every device holds the tree)
            (),  # active counts: replicated
            (SHARD_AXIS,),  # query indices: sharded
            (SHARD_AXIS, None),  # per-level sibling positions: sharded
        ),
        out_specs=((), (SHARD_AXIS, None), (SHARD_AXIS, None, None)),
        # ZERO collectives: the tree is replicated, each device answers
        # its own query slice locally — any collective here is a reshard
        collectives=(),
        # measured 995 eqns / loop depth 0 / ~4 KB per device
        max_eqns=1_500,
        max_loop_depth=4,
        max_device_bytes=1 << 20,
        # the per-call query plan is dead after dispatch; the leaf
        # blocks are NOT donated — callers reuse a registered tree
        # across dispatches
        donate_argnums=(2, 3),
        entry_donated_params=(("indices", 3), ("sib_pos", 4)),
    ),
)


def sharded_by_name() -> dict[str, ShardedKernel]:
    return {s.name: s for s in SHARDED_KERNELS}


def donated_entrypoints() -> dict[str, tuple[tuple[str, int], ...]]:
    """Wrapper-function name -> ((param name, positional index), ...)
    for every sharded kernel with declared donations — the
    ``donated-read-after-dispatch`` AST check's worklist."""
    out: dict[str, tuple[tuple[str, int], ...]] = {}
    for s in SHARDED_KERNELS:
        if s.entry_donated_params:
            out[s.entrypoint] = s.entry_donated_params
    return out


# ----------------------------------------------------------------- helpers


def by_name() -> dict[str, Kernel]:
    return {k.name: k for k in KERNELS}


def module_path(k: Kernel) -> str:
    """'package.module:fn' -> 'package/module.py' (repo-relative)."""
    mod = k.fn.split(":", 1)[0]
    return mod.replace(".", "/") + ".py"


def fn_name(k: Kernel) -> str:
    return k.fn.split(":", 1)[1]


def traced_roots(path: str) -> set[str]:
    """Manifest-declared traced entry points living in ``path`` (a
    repo-relative or absolute module path) — the extra closure roots the
    AST checks seed beyond per-module ``jax.jit`` discovery."""
    roots: set[str] = set()
    for k in KERNELS:
        mp = module_path(k)
        if path == mp or path.endswith("/" + mp):
            roots.add(fn_name(k))
    return roots


def site_registered(path: str, target: str) -> bool:
    """True when ``path::target`` matches a JIT_SITES entry (suffix match
    on a '/' boundary, same rule as the allowlist)."""
    for site in JIT_SITES:
        rpath, _, rtarget = site.partition("::")
        if target != rtarget:
            continue
        if path == rpath or path.endswith("/" + rpath):
            return True
    return False
