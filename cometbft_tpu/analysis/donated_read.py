"""Check: donated-read-after-dispatch.

The sharded manifest (``kernel_manifest.SHARDED_KERNELS``) declares
which arguments of each mesh entry point are DONATED to the device
program (``donate_argnums``): their device buffers are consumed by the
dispatch and may be aliased for the outputs.  Host code that reads such
a value after the dispatch call races the device for memory the program
already owns — on CPU it happens to work (donation is a no-op there),
on TPU it is a use-after-free that corrupts results silently.

This check walks every function that calls a donated entry point by
name (``sharded_verify_cached(...)``) — or through a same-scope
``functools.partial`` alias (``fn = partial(sharded_verify_cached,
mesh); fn(tables, ..., payload)``, with the donated position shifted by
the bound arguments) — and flags any later read of the variable passed
in a donated position.  Rebinding the name (assignment, ``del``, a
fresh loop target) clears the taint — the name no longer refers to the
donated buffer.  The analysis is lexical (source order within one
function body); a read that only executes before the dispatch at
runtime but appears after it in source still flags, which is the
conservative direction for a use-after-free class.

KNOWN LIMIT: a dispatch handle that crosses a function boundary (the
models/comb_verifier pattern — the partial is stored on the cache entry
in one method and invoked in another) is invisible to a lexical
single-scope scan; there the discipline is held by the shardcheck
donation contract plus convention (stage the donated value inline in
the call expression, never bind it).

Fix a finding by staging a fresh array per dispatch (the
models/comb_verifier pattern: the donated value is a ``jnp.asarray``
created inside the call expression, never bound) or by dropping the
donation from the manifest + kernel together (`regen-shardings`).
"""

from __future__ import annotations

import ast

from . import kernel_manifest as manifest
from .linter import Finding, Module, terminal_name

CHECK_ID = "donated-read-after-dispatch"
SUMMARY = "host read of a buffer already donated to a device dispatch"


def _donated_names_of_call(call: ast.Call, spec) -> list[str]:
    """Names passed in donated positions of ``call`` (positional index
    or keyword), per the manifest's (param name, position) spec."""
    names: list[str] = []
    for pname, pos in spec:
        arg = None
        if pos < len(call.args):
            arg = call.args[pos]
        else:
            for kw in call.keywords:
                if kw.arg == pname:
                    arg = kw.value
                    break
        if isinstance(arg, ast.Name):
            names.append(arg.id)
    return names


class _FnVisitor(ast.NodeVisitor):
    """One function body: collect donated-name taints at dispatch calls,
    flag later loads, clear taints on rebinding."""

    def __init__(self, entrypoints: dict, findings: list[Finding], path: str):
        self.entrypoints = entrypoints
        self.findings = findings
        self.path = path
        # name -> (dispatch lineno, entrypoint) — live taints
        self.tainted: dict[str, tuple[int, str]] = {}
        # name -> (entrypoint, shifted donated spec) — same-scope
        # functools.partial aliases of a donated entry point
        self.aliases: dict[str, tuple[str, tuple]] = {}

    def _partial_alias(self, value) -> tuple[str, tuple] | None:
        """(entrypoint, shifted spec) when ``value`` is
        ``[functools.]partial(<donated entrypoint>, <bound args...>)``."""
        if not (
            isinstance(value, ast.Call)
            and terminal_name(value.func) == "partial"
            and value.args
        ):
            return None
        target = terminal_name(value.args[0])
        spec = self.entrypoints.get(target)
        if not spec:
            return None
        shift = len(value.args) - 1
        shifted = tuple(
            (pname, pos - shift) for pname, pos in spec if pos - shift >= 0
        )
        return (target, shifted) if shifted else None

    def visit_Call(self, node: ast.Call) -> None:  # noqa: N802
        fn = terminal_name(node.func)
        spec = self.entrypoints.get(fn)
        label = fn
        if spec is None and fn in self.aliases:
            label, spec = self.aliases[fn]
        # arguments are evaluated (read) before the call taints them
        self.generic_visit(node)
        if spec:
            for name in _donated_names_of_call(node, spec):
                self.tainted[name] = (node.lineno, label)

    def _flag_read(self, name: str, lineno: int, col: int) -> None:
        hit = self.tainted.get(name)
        if hit and lineno > hit[0]:
            at, fn = hit
            self.findings.append(Finding(
                CHECK_ID, self.path, lineno, col,
                f"{name!r} was donated to {fn}() at line {at} "
                "and must not be read afterwards — the device owns "
                "the buffer; stage a fresh array per dispatch or drop "
                "the donation from the sharded manifest",
            ))

    def visit_Name(self, node: ast.Name) -> None:  # noqa: N802
        if isinstance(node.ctx, ast.Load):
            self._flag_read(node.id, node.lineno, node.col_offset)
        elif isinstance(node.ctx, (ast.Store, ast.Del)):
            self.tainted.pop(node.id, None)
            self.aliases.pop(node.id, None)
        self.generic_visit(node)

    # Python evaluates the RHS before binding the target, but ast.Assign
    # lists targets first — visiting in field order would clear the
    # taint before the Load on the value is seen, hiding
    # `payload = payload.sum()` after a dispatch.  Visit in evaluation
    # order instead.
    def visit_Assign(self, node: ast.Assign) -> None:  # noqa: N802
        self.visit(node.value)
        for t in node.targets:
            self.visit(t)  # Store clears any stale taint/alias
        alias = self._partial_alias(node.value)
        if alias is not None:
            for t in node.targets:
                if isinstance(t, ast.Name):
                    self.aliases[t.id] = alias

    def visit_AnnAssign(self, node: ast.AnnAssign) -> None:  # noqa: N802
        if node.value is not None:
            self.visit(node.value)
        self.visit(node.target)

    def visit_AugAssign(self, node: ast.AugAssign) -> None:  # noqa: N802
        # `payload += x` both reads and rebinds: the read of the donated
        # buffer is the finding; the rebind then clears the taint
        self.visit(node.value)
        if isinstance(node.target, ast.Name):
            self._flag_read(
                node.target.id, node.target.lineno, node.target.col_offset
            )
            self.tainted.pop(node.target.id, None)
        else:
            self.visit(node.target)

    # nested defs get their own scope/visitor; don't leak taints in
    def _skip(self, node) -> None:
        _check_function(node, self.entrypoints, self.findings, self.path)

    visit_FunctionDef = _skip  # noqa: N815
    visit_AsyncFunctionDef = _skip  # noqa: N815


def _check_function(fn, entrypoints, findings, path) -> None:
    v = _FnVisitor(entrypoints, findings, path)
    for stmt in fn.body:
        v.visit(stmt)


def check(mod: Module) -> list[Finding]:
    entrypoints = manifest.donated_entrypoints()
    if not entrypoints:
        return []
    # cheap pre-filter: no donated entry point named in the source
    if not any(name in mod.source for name in entrypoints):
        return []
    findings: list[Finding] = []
    # the module-level visitor covers top-level dispatches (scripts);
    # every FunctionDef it meets — top-level, method, nested — gets its
    # own fresh-scoped visitor via the _skip interception
    v = _FnVisitor(entrypoints, findings, mod.path)
    for stmt in mod.tree.body:
        v.visit(stmt)
    return findings
