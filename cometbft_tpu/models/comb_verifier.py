"""Validator-set-keyed comb-table cache + the cached batch verifier.

This is the device-resident fast path for commit verification: the TPU
analogue of the reference's per-pubkey expanded-key LRU
(crypto/ed25519/ed25519.go:43,68), scaled to whole validator sets.  A
validator set's pubkeys are decompressed ONCE into per-validator comb
tables (ops/comb.build_a_tables) and kept on device; every subsequent
VerifyCommit against that set ships only the per-call data — R halves,
s halves, and the SHA-512-padded R || A || M blocks — and runs
ops/comb.verify_cached, which needs no doublings and no decompression of
the pubkeys.  The challenge digests k = SHA-512(R || A || M) are computed
on device (ops/sha2.sha512_blocks) so the host never runs a per-signature
hash loop, and the result comes back as one packed bitmap + one all-ok
scalar instead of a per-row bool array.

Like the uncached verifier, CombBatchVerifier is data plane only:
production consumers reach it through the unified verify service
(verifysvc/ — a request bound to a cache entry via
``mode=("comb", entry)`` dispatches as one solo batch on the scheduler).

Shapes are keyed by the validator-set size V, not a power-of-two bucket:
commits verify against a fixed known set, so one compiled program per
chain (10,000 lanes for the 10k-validator config, not 16,384).  Rows for
validators that did not sign carry zeros and are masked out of the
result, preserving the per-signature blame contract of
types/validation.go:384-399.
"""

from __future__ import annotations

import hashlib
import threading
import time as _time
from collections import OrderedDict

import numpy as np

from ..utils import tracing
from ..utils.metrics import hub as _mhub


class _CacheEntry:
    __slots__ = (
        "tables", "valid", "pubs", "index", "size", "vpad", "mesh",
        "verify_fn", "_slabs", "_slab_mtx",
    )

    def __init__(self, tables, valid, pubs, index: dict[bytes, int], mesh=None):
        self.tables = tables  # device (64, 9, 3, 22, Vpad) int32 — V minor
        self.valid = valid  # device (Vpad,) bool
        self.pubs = pubs  # device (Vpad, 32) uint8 — the raw pubkeys, so
        # the per-call payload never re-ships A (it's in every SHA-512
        # challenge digest R || A || M)
        self.index = index  # pubkey bytes -> row
        self.size = len(index)
        self.vpad = int(tables.shape[-1])  # size padded to the mesh width
        self.mesh = mesh  # jax Mesh when the sharded path is active
        self.verify_fn = None  # jitted verify, bound at first use
        # reusable host staging buffers, keyed by payload width; two per
        # width = the double-buffer the pipelined submit() path needs
        self._slabs: dict[int, list[_PayloadSlab]] = {}
        self._slab_mtx = threading.Lock()

    def acquire_slab(self, width: int) -> "_PayloadSlab":
        with self._slab_mtx:
            pool = self._slabs.get(width)
            if pool:
                slab = pool.pop()
                _mhub().verify_slab_requests.inc(result="hit")
                return slab
        _mhub().verify_slab_requests.inc(result="miss")
        return _PayloadSlab(self.vpad, width)

    def release_slab(self, slab: "_PayloadSlab") -> None:
        with self._slab_mtx:
            pool = self._slabs.setdefault(slab.buf.shape[1], [])
            if len(pool) < 2:
                pool.append(slab)


class _PayloadSlab:
    """One reusable (vpad, 68 + maxm) host staging buffer for payload
    assembly (the "pinned buffer" of the zero-copy submit path).

    Allocated once per (entry, width bucket) and recycled through the
    entry's two-slab pool, so steady-state assembly never allocates.  A
    full clear between uses is unnecessary: the device masks every byte
    past a row's mlen (ops/sha2.ram_blocks_from_parts) and every row
    whose live flag is 0, so a reuse only needs the PREVIOUS call's live
    flags retired — and when the next call writes the exact same row
    layout (the steady blocksync/consensus case: same signer rows, same
    sign-bytes length), the constant mlen/live columns are already
    correct and are not touched at all; only the R | s | msg columns are
    rewritten."""

    __slots__ = ("buf", "dirty", "layout")

    def __init__(self, vpad: int, width: int):
        self.buf = np.zeros((vpad, width), dtype=np.uint8)
        self.dirty = None  # previous use's live rows (array or slice)
        self.layout = None  # (kind, n, mlen) of the previous use

    def retire(self) -> None:
        """Forget every previous/partial fill: all live flags cleared,
        full header rewrite forced on next use.  The safe state for
        returning a slab to the pool from an ERROR path, where a partial
        fill may have set live flags the dirty bookkeeping doesn't
        cover."""
        self.buf[:, 67] = 0
        self.dirty = None
        self.layout = None


def active_mesh():
    """Device mesh for the sharded comb path.

    COMETBFT_TPU_MESH = N (N > 1) shards comb tables + signature rows
    over the first N devices (parallel/verify.sharded_verify_cached);
    unset/<=1 keeps the single-device program.  Resolved once per
    process — consensus builds one cache per validator set and the mesh
    must be identical across entries.
    """
    global _MESH
    if _MESH is _UNSET:
        from ..utils import envknobs

        n = envknobs.get_int(envknobs.MESH)
        if n <= 1:
            _MESH = None
        else:
            from ..parallel import make_mesh

            _MESH = make_mesh(n)
    return _MESH


_UNSET = object()
_MESH = _UNSET


def set_active_mesh(mesh) -> None:
    """Explicitly bind (or clear, with None) the comb-path mesh —
    overrides the COMETBFT_TPU_MESH env resolution.  Entries built
    before the change keep their placement; callers flush the cache
    when re-binding."""
    global _MESH
    _MESH = mesh


class ValsetCombCache:
    """LRU of device-resident comb tables, keyed by the pubkey list.

    A 10k-validator entry is ~1.5 GB of HBM (152 KB/validator), so the
    LRU is small; consensus only ever needs the current set and, briefly,
    the previous one across a validator-set change.
    """

    def __init__(self, max_entries: int = 2):
        self._entries: OrderedDict[bytes, _CacheEntry] = OrderedDict()
        self._max = max_entries
        self._mtx = threading.Lock()
        self._building: dict[bytes, threading.Lock] = {}
        self._async_inflight: set[bytes] = set()

    @staticmethod
    def fingerprint(pubkeys: list[bytes]) -> bytes:
        h = hashlib.sha256()
        for pk in pubkeys:
            h.update(pk)
        return h.digest()

    def get(self, fp: bytes) -> _CacheEntry | None:
        with self._mtx:
            e = self._entries.get(fp)
            if e is not None:
                self._entries.move_to_end(fp)
            return e

    def ensure(self, pubkeys: list[bytes], _count: bool = True) -> _CacheEntry:
        """Return the entry for this exact pubkey list, building the
        tables on first sight (one-time per validator set).  Concurrent
        first calls for the same set serialize on a per-fingerprint lock —
        a 10k-validator build must never race a duplicate.  When an entry
        for a *different* pubkey list already exists, its rows are reused
        for the unchanged validators (incremental churn update): only the
        new/changed pubkeys go through the table-build kernel."""
        fp = self.fingerprint(pubkeys)
        e = self.get(fp)
        if e is not None:
            if _count:
                _mhub().comb_table_cache.inc(result="hit")
            return e
        with self._mtx:
            build_lock = self._building.setdefault(fp, threading.Lock())
        with build_lock:
            e = self.get(fp)  # the race loser finds the winner's entry
            if e is not None:
                if _count:
                    # served by a build another caller performed — a
                    # "building" wait, not a second miss: misses must
                    # stay 1:1 with actual table builds
                    _mhub().comb_table_cache.inc(result="building")
                return e
            if _count:
                _mhub().comb_table_cache.inc(result="miss")
            base = self._newest()
            entry = self._build(pubkeys, base)
            with self._mtx:
                self._entries[fp] = entry
                while len(self._entries) > self._max:
                    self._entries.popitem(last=False)
                self._building.pop(fp, None)
            return entry

    def ensure_async(self, pubkeys: list[bytes]) -> _CacheEntry | None:
        """Non-blocking ensure: the entry if it's ready, else None with a
        background build kicked off (once per fingerprint).  The caller
        verifies through the uncached Straus kernel until the tables are
        warm — the analog of the reference's lazily-filling expanded-key
        LRU (ed25519.go:43,68), where the first verification under a new
        key also pays an expansion the cache then amortizes.  A validator
        -set change therefore never stalls consensus behind a table
        build: the new set's tables (an incremental churn build when the
        previous set's entry exists) land a few blocks later."""
        fp = self.fingerprint(pubkeys)
        e = self.get(fp)
        if e is not None:
            _mhub().comb_table_cache.inc(result="hit")
            return e
        with self._mtx:
            if fp in self._async_inflight:
                _mhub().comb_table_cache.inc(result="building")
                return None  # background build already running
            self._async_inflight.add(fp)
        _mhub().comb_table_cache.inc(result="miss")
        pubkeys = list(pubkeys)

        def build():
            try:
                # ensure() owns the per-fingerprint build lock, so a
                # concurrent synchronous caller can never duplicate the
                # build — whoever wins, the loser finds the entry
                # (_count=False: this lookup was already tallied above)
                self.ensure(pubkeys, _count=False)
            finally:
                with self._mtx:
                    self._async_inflight.discard(fp)

        threading.Thread(target=build, name="comb-build", daemon=True).start()
        return None

    def _newest(self) -> _CacheEntry | None:
        with self._mtx:
            if not self._entries:
                return None
            return next(reversed(self._entries.values()))

    @staticmethod
    def _build(
        pubkeys: list[bytes], base: _CacheEntry | None = None
    ) -> _CacheEntry:
        import jax.numpy as jnp

        mesh = active_mesh()
        index = {pk: i for i, pk in enumerate(pubkeys)}
        if mesh is not None:
            # pad the lane count to the mesh width; pad lanes carry a
            # repeated real key but are never scattered into (valid rows
            # only come from `index`), so they do dead-but-defined work
            d = mesh.devices.size
            pad = (-len(pubkeys)) % d
            pubkeys = list(pubkeys) + [pubkeys[0]] * pad
        reuse: list[tuple[int, int]] = []  # (new row, base row)
        fresh: list[int] = []
        if base is not None:
            for i, pk in enumerate(pubkeys):
                j = base.index.get(pk)
                if j is None:
                    fresh.append(i)
                else:
                    reuse.append((i, j))
        pub_arr = np.frombuffer(b"".join(pubkeys), dtype=np.uint8).reshape(-1, 32)
        if base is None or not reuse:
            tables, valid = _build_tables(pub_arr)
            return _finish_entry(tables, valid, pub_arr, index, mesh)

        # Incremental churn: gather unchanged rows from the previous set's
        # device tables, build only the new keys.  A single-validator swap
        # reuses the other V-1 rows (the expensive part of a table row is
        # its doubling chain, ~64 * 4 point doubles).  Fresh keys are padded
        # to a power-of-two bucket so churn of any size hits a handful of
        # compiled build shapes rather than one compile per distinct count,
        # and the gather/scatter assembly runs as one jitted program so XLA
        # fuses it instead of materializing intermediate full-size copies
        # (an entry is ~1.5 GB at V=10k; transient copies would OOM HBM).
        V = len(pubkeys)
        if fresh:
            bucket = 1 << (len(fresh) - 1).bit_length()
            padded = [pubkeys[i] for i in fresh]
            padded += [padded[0]] * (bucket - len(fresh))
            a = np.frombuffer(b"".join(padded), dtype=np.uint8).reshape(-1, 32)
            t_new, v_new = _build_tables(a)
            t_new, v_new = jnp.asarray(t_new), jnp.asarray(v_new)
        else:
            t_new = base.tables[..., :0]
            v_new = base.valid[:0]
        tables, valid = _assemble_churn_jit(
            base.tables,
            base.valid,
            t_new,
            v_new,
            jnp.asarray(np.asarray([i for i, _ in reuse], np.int32)),
            jnp.asarray(np.asarray([j for _, j in reuse], np.int32)),
            jnp.asarray(np.asarray(fresh, np.int32)),
            V,
        )
        return _finish_entry(tables, valid, pub_arr, index, mesh)


def _build_tables(pub_arr: np.ndarray):
    """One table build, routed: sets up to COMETBFT_TPU_COMB_HOST_BUILD_MAX
    validators (or churn buckets that size) are precomputed on HOST
    (ops/comb.build_a_tables_host — exact bigint, bit-identical, ~10 ms
    per validator, NO XLA program, so a cold pod never pays the
    table-build compile); bigger builds run the scan-rolled jitted
    kernel (ops/comb.build_a_tables_jit), whose compile the persistent
    XLA cache amortizes and whose arithmetic the device wins at scale.
    Returns (tables, valid) — host numpy or device arrays; callers
    device_put with their placement (_finish_entry).

    The default threshold (2048) matches COMETBFT_TPU_COMB_ASYNC_MIN:
    foreground builds stay host/compile-free, while the giant sets that
    would be slow on host already build in the background behind the
    uncached fallback (ensure_async)."""
    from ..ops import comb
    from ..utils import envknobs

    lim = envknobs.get_int(envknobs.COMB_HOST_BUILD_MAX)
    t0 = _time.perf_counter()
    if 0 < pub_arr.shape[0] <= lim:
        with tracing.span(
            "verify.table_build", {"backend": "host"} if tracing.enabled() else None
        ):
            out = comb.build_a_tables_host(pub_arr)
        _mhub().verify_phase_seconds.observe(
            _time.perf_counter() - t0, phase="table_build_host"
        )
        return out
    import jax.numpy as jnp

    with tracing.span(
        "verify.table_build", {"backend": "device"} if tracing.enabled() else None
    ):
        out = comb.build_a_tables_jit(jnp.asarray(pub_arr))
        # the jit dispatch is async: wait for the arithmetic so the
        # phase is the COMPLETED build (the host counterpart measures
        # completed work; comparing the two is this split's purpose)
        out[0].block_until_ready()
    _mhub().verify_phase_seconds.observe(
        _time.perf_counter() - t0, phase="table_build_device"
    )
    return out


def _finish_entry(tables, valid, pub_arr, index, mesh) -> _CacheEntry:
    """Place the built tables: sharded over the mesh's lane axis when the
    multi-chip path is active, resident on the default device otherwise.
    ``tables``/``valid`` may be host numpy (the precomputed path) or
    device arrays (the jitted build) — ``device_put`` with the explicit
    ``NamedSharding`` covers both, landing host tables directly in their
    sharded layout with no resharding copy."""
    import jax

    if mesh is not None:
        from jax.sharding import NamedSharding, PartitionSpec as P

        axis = mesh.axis_names[0]
        tables = jax.device_put(
            tables, NamedSharding(mesh, P(None, None, None, None, axis))
        )
        valid = jax.device_put(valid, NamedSharding(mesh, P(axis)))
        pubs = jax.device_put(pub_arr, NamedSharding(mesh, P(axis, None)))
    else:
        tables = jax.device_put(tables)
        valid = jax.device_put(valid)
        pubs = jax.device_put(pub_arr)
    tables.block_until_ready()
    return _CacheEntry(tables, valid, pubs, index, mesh)


def _assemble_churn(base_t, base_v, new_t, new_v, new_rows, base_rows, fresh_rows, V):
    """One fused gather/scatter: reused rows from the old tables + freshly
    built rows into a V-lane table.  The validator axis is the tables'
    LAST axis (ops/comb.py layout); new_t may carry bucket padding beyond
    len(fresh_rows) lanes, which the scatter never reads.

    Manifest kernel ``comb_assemble_churn`` (V is the static argument)."""
    import jax.numpy as jnp

    tables = jnp.zeros(tuple(base_t.shape[:-1]) + (V,), base_t.dtype)
    valid = jnp.zeros((V,), bool)
    tables = tables.at[..., new_rows].set(base_t[..., base_rows])
    valid = valid.at[new_rows].set(base_v[base_rows])
    nf = fresh_rows.shape[0]
    if nf:
        tables = tables.at[..., fresh_rows].set(new_t[..., :nf])
        valid = valid.at[fresh_rows].set(new_v[:nf])
    return tables, valid


_ASSEMBLE_CHURN = None


def _assemble_churn_jit(*args):
    global _ASSEMBLE_CHURN
    if _ASSEMBLE_CHURN is None:
        import jax

        _ASSEMBLE_CHURN = jax.jit(_assemble_churn, static_argnums=(7,))
    return _ASSEMBLE_CHURN(*args)


_GLOBAL_CACHE = ValsetCombCache()


def global_cache() -> ValsetCombCache:
    return _GLOBAL_CACHE


_STAGING_POOL = None
_STAGING_POOL_MTX = threading.Lock()


def _staging_executor():
    """One process-wide staging thread for submit(): a single worker
    keeps host->device transfers and kernel dispatches in submission
    order (so pipelined tickets resolve FIFO on the device queue) while
    still unblocking every submitter immediately.  Assembly itself is
    numpy and releases the GIL for the big writes, so the caller's
    Python thread runs concurrently.  Creation is locked: a first-use
    race (blocksync pool thread vs consensus thread) must not spawn two
    workers, which would break the FIFO ordering guarantee."""
    global _STAGING_POOL
    if _STAGING_POOL is None:
        from concurrent.futures import ThreadPoolExecutor

        with _STAGING_POOL_MTX:
            if _STAGING_POOL is None:
                _STAGING_POOL = ThreadPoolExecutor(
                    max_workers=1, thread_name_prefix="comb-stage"
                )
    return _STAGING_POOL


def _fill_payload(
    slab: _PayloadSlab, items: list[tuple[bytes, bytes, bytes]], rows: np.ndarray
) -> np.ndarray:
    """Fill a staging slab with the tight device payload: row layout
    R(32) | s(32) | mlen(3B LE) | live(1B) | msg.

    items are (pubkey, msg, sig) in add() order; rows maps each item to
    its validator row.  Pure NumPy slice/scatter writes — no per-row
    Python loop on any commit-shaped batch.  Fast paths, in order:

      - same layout as the slab's previous use (row set + message
        length): the constant mlen/live columns survive verbatim; only
        R | s | msg are rewritten.
      - contiguous rows 0..n-1 (every validator signed, commit order):
        plain slice writes instead of fancy-index scatters.
      - all-equal message lengths (canonical vote sign-bytes): one
        reshaped block write for the messages.
    """
    buf = slab.buf
    n = len(items)
    sig_arr = np.frombuffer(
        b"".join(s for _, _, s in items), dtype=np.uint8
    ).reshape(n, 64)
    msgs = [m for _, m, _ in items]
    lens = np.fromiter((len(m) for m in msgs), np.int64, n)
    l0 = int(lens[0]) if n else 0
    same_len = bool((lens == l0).all()) if n else True

    contig = bool(
        n
        and int(rows[0]) == 0
        and int(rows[-1]) == n - 1
        and (rows == np.arange(n, dtype=rows.dtype)).all()
    )
    target = slice(0, n) if contig else rows
    layout = ("contig", n, l0) if (contig and same_len) else None

    if layout is None or slab.layout != layout:
        # retire the previous use's live rows, then write the header
        # columns fresh (stale bytes beyond a live row's mlen are masked
        # on device, so only the live flags need clearing)
        if slab.dirty is not None:
            buf[slab.dirty, 67] = 0
        if same_len:
            buf[target, 64] = l0 & 0xFF
            buf[target, 65] = (l0 >> 8) & 0xFF
            buf[target, 66] = (l0 >> 16) & 0xFF
        else:
            buf[target, 64] = lens & 0xFF
            buf[target, 65] = (lens >> 8) & 0xFF
            buf[target, 66] = (lens >> 16) & 0xFF
        buf[target, 67] = 1  # live-row flag (mlen == 0 is legal)

    buf[target, :64] = sig_arr
    if same_len:
        if l0:
            buf[target, 68 : 68 + l0] = np.frombuffer(
                b"".join(msgs), np.uint8
            ).reshape(n, l0)
    else:
        for row, m in zip(rows, msgs):
            buf[row, 68 : 68 + len(m)] = np.frombuffer(m, np.uint8)
    slab.dirty = target if contig else rows
    slab.layout = layout
    return buf


def _payload_width(items: list[tuple[bytes, bytes, bytes]]) -> int:
    return 68 + _bucket_mlen(max((len(m) for _, m, _ in items), default=0))


def assemble_payload(
    items: list[tuple[bytes, bytes, bytes]], rows: np.ndarray, vpad: int
) -> np.ndarray:
    """One-shot payload assembly into a fresh buffer (profiling/compat
    entry point); the hot path recycles per-entry slabs instead
    (CombBatchVerifier.submit)."""
    slab = _PayloadSlab(vpad, _payload_width(items))
    return _fill_payload(slab, items, np.asarray(rows, dtype=np.int64))


class CombBatchVerifier:
    """BatchVerifier (crypto/crypto.go:47-55) bound to a cached set.

    add() expects pubkeys that are members of the bound validator set; a
    foreign key silently demotes the whole batch to the uncached kernel
    (TpuEd25519BatchVerifier), preserving results and blame order.  add()
    only appends — all assembly, hashing, and transfer happen in one
    vectorized verify() call.
    """

    def __init__(self, entry: _CacheEntry):
        self._entry = entry
        self._rows: list[int] = []
        self._row_set: set[int] = set()
        self._items: list[tuple[bytes, bytes, bytes]] = []
        self._fallback = None
        self.last_timings: dict[str, float] = {}  # ms per phase, set by verify()

    def __len__(self) -> int:
        return len(self._items)

    def add(self, pub_key: bytes, msg: bytes, sig: bytes) -> None:
        if len(pub_key) != 32 or len(sig) != 64:
            raise ValueError("malformed ed25519 pubkey or signature")
        if len(msg) >= 1 << 24:
            # the payload's mlen field is 3 bytes; a silent wrap would
            # verify against a truncated message (vote sign-bytes are
            # ~100 B — anything near 16 MiB is caller error)
            raise ValueError("message too large for batch verification")
        self._items.append((pub_key, msg, sig))
        if self._fallback is not None:
            self._fallback.add(pub_key, msg, sig)
            return
        row = self._entry.index.get(pub_key)
        if row is None or row in self._row_set:
            # key outside the cached set, or a second signature under the
            # same key (the scatter is one row per validator): demote to
            # the uncached kernel, replaying everything added so far
            from .verifier import TpuEd25519BatchVerifier

            self._fallback = TpuEd25519BatchVerifier()
            for p, m, s in self._items:
                self._fallback.add(p, m, s)
            return
        self._row_set.add(row)
        self._rows.append(row)

    def submit(self):
        """Dispatch the batch WITHOUT waiting for the result, and without
        even blocking on host assembly: the slab fill + transfer + kernel
        dispatch run on a dedicated staging thread, so the caller's
        thread is free the moment the ticket exists and call N+1's host
        work (vote decoding, batch building, the next submit) genuinely
        overlaps call N's assembly AND device execution — the double
        buffer the blocksync verify-ahead pipeline (blocksync/reactor.py,
        blocksync/replay.py) is built around.  Returns an opaque ticket
        for collect(); tickets resolve in submission order."""
        if self._fallback is not None:
            return ("sync", self._fallback.verify())
        n = len(self._rows)
        if n == 0:
            return ("sync", (False, []))
        _mhub().verify_batch_width.observe(float(n))
        # Link-aware routing, same rule as the uncached kernel: through a
        # remote device tunnel a call pays ~170 ms of round trips, so a
        # small batch (few signers of a large cached set) finishes sooner
        # on the host even though the tables are warm.
        from .verifier import CpuEd25519BatchVerifier, _device_batch_min

        if n < _device_batch_min():
            cpu = CpuEd25519BatchVerifier()
            cpu._items = self._items
            with tracing.span("verify.host_route"):
                return ("sync", cpu.verify())

        idx = np.asarray(self._rows, dtype=np.int64)
        # real snapshot for the staging thread: a verifier is one batch
        # (every call site builds a fresh one per commit); copying makes
        # a stray post-submit add() harmless to the in-flight ticket
        items = list(self._items)
        entry = self._entry
        fn = self._verify_fn()  # bind outside the worker (mutates entry)
        m = _mhub()
        m.verify_submit_queue_depth.add(1)

        def stage():
            import time

            import jax.numpy as jnp

            timings = {}
            slab = None
            try:
                t0 = time.perf_counter()
                # One TIGHT (V, 68 + maxm) row: R | s | mlen(3B LE) | live |
                # msg.  The device link runs ~10 MB/s with ~85 ms/transfer
                # latency, so the call ships only irreducible bytes in ONE
                # transfer: no SHA padding (rebuilt on device,
                # ops/sha2.ram_blocks_from_parts), no pubkeys (device-resident
                # in the cache entry), no zero blocks.  The slab is recycled
                # host memory — steady state allocates nothing.
                with tracing.span("verify.slab_fill"):
                    slab = entry.acquire_slab(_payload_width(items))
                    payload = _fill_payload(slab, items, idx)
                t1 = time.perf_counter()
                with tracing.span("verify.h2d_dispatch"):
                    out = fn(
                        entry.tables, entry.valid, entry.pubs,
                        jnp.asarray(payload),
                    )
                t2 = time.perf_counter()
                timings["assembly_ms"] = (t1 - t0) * 1e3
                timings["h2d_dispatch_ms"] = (t2 - t1) * 1e3
                m.verify_phase_seconds.observe(t1 - t0, phase="assembly")
                m.verify_phase_seconds.observe(t2 - t1, phase="h2d_dispatch")
                m.verify_staging_busy.inc(t2 - t0)
                return out, slab, timings
            except BaseException:
                # a failed fill/dispatch must not leak the pooled slab —
                # each loss would put steady state back on fresh
                # allocations
                if slab is not None:
                    slab.retire()
                    entry.release_slab(slab)
                raise
            finally:
                m.verify_submit_queue_depth.add(-1)

        try:
            fut = _staging_executor().submit(stage)
        except BaseException:
            m.verify_submit_queue_depth.add(-1)  # stage() never ran
            raise
        return ("dev", (fut, idx))

    def collect(self, ticket) -> tuple[bool, list[bool]]:
        """Wait for a submit() ticket and unpack (all_ok, per-signature).

        One device->host fetch: the program returns a single packed array
        [ok bitmap | all_ok byte] — a second fetch would cost another
        ~85 ms tunnel round trip.  The blame bitmap is indexed with the
        row order captured at submit time, so per-signature ordering is
        preserved however deep the pipeline runs."""
        kind, payload = ticket
        if kind == "sync":
            return payload
        fut, idx = payload
        import time as _time

        # Two distinct waits, measured separately: fut.result() blocks
        # until the STAGING thread finishes (queue + slab fill + H2D +
        # dispatch — in the submit-then-collect-immediately pattern this
        # covers the whole staging pass, which must not be billed to the
        # device), then np.asarray blocks until the KERNEL's result lands.
        t0 = _time.perf_counter()
        with tracing.span("verify.staging_wait"):
            out, slab, timings = fut.result()
        t1 = _time.perf_counter()
        try:
            with tracing.span("verify.device_wait"):
                host = np.asarray(out)  # the one blocking device fetch
        except BaseException:
            # async dispatch errors surface at this fetch (dropped
            # tunnel, device OOM): same no-leak invariant as stage()
            slab.retire()
            self._entry.release_slab(slab)
            raise
        t2 = _time.perf_counter()
        timings["staging_wait_ms"] = (t1 - t0) * 1e3
        timings["device_wait_ms"] = (t2 - t1) * 1e3
        m = _mhub()
        m.verify_phase_seconds.observe(t1 - t0, phase="staging_wait")
        m.verify_phase_seconds.observe(t2 - t1, phase="device_wait")
        # the kernel has consumed the staged payload; recycle the slab
        self._entry.release_slab(slab)
        self.last_timings.update(timings)
        with tracing.span("verify.blame_unpack"):
            all_ok = bool(host[-1])
            picked = (
                np.unpackbits(host[:-1], count=self._entry.vpad)
                .astype(bool)[idx]
            )
            return all_ok, picked.tolist()

    def verify(self) -> tuple[bool, list[bool]]:
        import time

        self.last_timings = {}
        t0 = time.perf_counter()
        with tracing.span("verify.submit"):
            ticket = self.submit()
        t1 = time.perf_counter()
        result = self.collect(ticket)
        t2 = time.perf_counter()
        if ticket[0] == "sync":
            # host-routed (small batch / fallback): all work happened
            # inside submit(); labeling it assembly_ms would corrupt the
            # phase breakdowns the measurement scripts record
            self.last_timings = {"host_ms": (t1 - t0) * 1e3}
        else:
            # collect() merged the staging thread's assembly_ms /
            # h2d_dispatch_ms into last_timings already; kernel_ms is the
            # caller-visible wait (device execution minus what overlapped)
            self.last_timings["submit_ms"] = (t1 - t0) * 1e3
            self.last_timings["kernel_ms"] = (t2 - t1) * 1e3
        return result

    def _verify_fn(self):
        if self._entry.verify_fn is None:
            if self._entry.mesh is not None:
                # multi-chip: tables + rows sharded over the mesh's lane
                # axis, psum/all_gather combine (parallel/verify.py)
                import functools

                from ..parallel.verify import sharded_verify_cached

                self._entry.verify_fn = functools.partial(
                    sharded_verify_cached, self._entry.mesh
                )
                return self._entry.verify_fn
            import jax

            from ..ops import comb

            # materialize the process-global B table OUTSIDE any trace:
            # created lazily inside the jit it would be a leaked tracer
            comb.get_b_tables()
            self._entry.verify_fn = jax.jit(_device_verify)
        return self._entry.verify_fn


def _device_verify(tables, valid, pubs, payload):
    """The single-device comb verify program on a tight payload.

    payload rows: R(32) | s(32) | mlen(3B LE) | live(1B) | msg(maxm).
    Returns ONE uint8 array [packbits(ok & live) | all_ok] so the caller
    pays a single device->host fetch.

    Manifest kernel ``comb_device_verify``.  The trace resolves
    comb.tree_enabled() (the kernelcheck gate pins the knob to its
    default while fingerprinting, so goldens always describe the tree
    path).
    """
    import jax.numpy as jnp

    from ..ops import comb, sha2

    bt = comb.get_b_tables()
    r, s, blocks, active, live = sha2.parse_verify_payload(payload, pubs)
    k_digest = sha2.sha512_blocks(blocks, active)
    ok = comb.verify_cached(tables, valid, r, s, k_digest, bt)
    bits = jnp.packbits(ok & live)
    all_ok = jnp.all(ok | ~live).astype(jnp.uint8)
    return jnp.concatenate([bits, all_ok[None]])


def _bucket_mlen(mlen: int) -> int:
    """Round a max message length up to a small set of compiled widths:
    one program per (valset, bucket) rather than one per distinct length
    (vote sign-bytes drift by a byte when heights/timestamps cross varint
    boundaries)."""
    if mlen <= 32:
        return 32
    return -(-mlen // 64) * 64
