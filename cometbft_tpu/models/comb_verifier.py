"""Validator-set-keyed comb-table cache + the cached batch verifier.

This is the device-resident fast path for commit verification: the TPU
analogue of the reference's per-pubkey expanded-key LRU
(crypto/ed25519/ed25519.go:43,68), scaled to whole validator sets.  A
validator set's pubkeys are decompressed ONCE into per-validator comb
tables (ops/comb.build_a_tables) and kept on device; every subsequent
VerifyCommit against that set ships only the per-call data — R halves,
s halves, and SHA-512 challenge digests, ~128 bytes/signature — and runs
ops/comb.verify_cached, which needs no doublings and no decompression of
the pubkeys.

Shapes are keyed by the validator-set size V, not a power-of-two bucket:
commits verify against a fixed known set, so one compiled program per
chain (10,000 lanes for the 10k-validator config, not 16,384).  Rows for
validators that did not sign carry zeros and are masked out of the
result, preserving the per-signature blame contract of
types/validation.go:384-399.
"""

from __future__ import annotations

import hashlib
import threading
from collections import OrderedDict

import numpy as np


class _CacheEntry:
    __slots__ = ("tables", "valid", "index", "size", "verify_fn")

    def __init__(self, tables, valid, index: dict[bytes, int]):
        self.tables = tables  # device (V, 64, 16, 3, 22) int32
        self.valid = valid  # device (V,) bool
        self.index = index  # pubkey bytes -> row
        self.size = len(index)
        self.verify_fn = None  # jitted verify, bound at first use


class ValsetCombCache:
    """LRU of device-resident comb tables, keyed by the pubkey list.

    A 10k-validator entry is ~2.7 GB of HBM (270 KB/validator), so the
    LRU is small; consensus only ever needs the current set and, briefly,
    the previous one across a validator-set change.
    """

    def __init__(self, max_entries: int = 4):
        self._entries: OrderedDict[bytes, _CacheEntry] = OrderedDict()
        self._max = max_entries
        self._mtx = threading.Lock()
        self._building: dict[bytes, threading.Lock] = {}

    @staticmethod
    def fingerprint(pubkeys: list[bytes]) -> bytes:
        h = hashlib.sha256()
        for pk in pubkeys:
            h.update(pk)
        return h.digest()

    def get(self, fp: bytes) -> _CacheEntry | None:
        with self._mtx:
            e = self._entries.get(fp)
            if e is not None:
                self._entries.move_to_end(fp)
            return e

    def ensure(self, pubkeys: list[bytes]) -> _CacheEntry:
        """Return the entry for this exact pubkey list, building the
        tables on first sight (one-time per validator set).  Concurrent
        first calls for the same set serialize on a per-fingerprint lock —
        a 10k-validator build is minutes of compile + GBs of HBM, so a
        duplicate build must never race."""
        fp = self.fingerprint(pubkeys)
        e = self.get(fp)
        if e is not None:
            return e
        with self._mtx:
            build_lock = self._building.setdefault(fp, threading.Lock())
        with build_lock:
            e = self.get(fp)  # the race loser finds the winner's entry
            if e is not None:
                return e
            entry = self._build(pubkeys)
            with self._mtx:
                self._entries[fp] = entry
                while len(self._entries) > self._max:
                    self._entries.popitem(last=False)
                self._building.pop(fp, None)
            return entry

    @staticmethod
    def _build(pubkeys: list[bytes]) -> _CacheEntry:
        import jax
        import jax.numpy as jnp

        from ..ops import comb

        a = np.frombuffer(b"".join(pubkeys), dtype=np.uint8).reshape(-1, 32)
        tables, valid = jax.jit(comb.build_a_tables)(jnp.asarray(a))
        tables.block_until_ready()
        index = {pk: i for i, pk in enumerate(pubkeys)}
        return _CacheEntry(tables, valid, index)


_GLOBAL_CACHE = ValsetCombCache()


def global_cache() -> ValsetCombCache:
    return _GLOBAL_CACHE


class CombBatchVerifier:
    """BatchVerifier (crypto/crypto.go:47-55) bound to a cached set.

    add() expects pubkeys that are members of the bound validator set; a
    foreign key silently demotes the whole batch to the uncached kernel
    (TpuEd25519BatchVerifier), preserving results and blame order.
    """

    def __init__(self, entry: _CacheEntry):
        self._entry = entry
        self._rows: list[int] = []
        self._row_set: set[int] = set()
        self._sigs: list[bytes] = []
        self._digest_parts: list[bytes] = []
        self._items: list[tuple[bytes, bytes, bytes]] = []
        self._fallback = None

    def __len__(self) -> int:
        return len(self._items)

    def add(self, pub_key: bytes, msg: bytes, sig: bytes) -> None:
        if len(pub_key) != 32 or len(sig) != 64:
            raise ValueError("malformed ed25519 pubkey or signature")
        self._items.append((pub_key, msg, sig))
        if self._fallback is not None:
            self._fallback.add(pub_key, msg, sig)
            return
        row = self._entry.index.get(pub_key)
        if row is None or row in self._row_set:
            # key outside the cached set, or a second signature under the
            # same key (the scatter is one row per validator): demote to
            # the uncached kernel, replaying everything added so far
            from .verifier import TpuEd25519BatchVerifier

            self._fallback = TpuEd25519BatchVerifier()
            for p, m, s in self._items:
                self._fallback.add(p, m, s)
            return
        self._row_set.add(row)
        self._rows.append(row)
        self._sigs.append(sig)
        # k = SHA-512(R || A || M); hashlib releases the GIL and runs the
        # C core — the host cost is ~0.5 us/sig, vs ~25 us/sig to verify
        # on the reference's CPU path.
        self._digest_parts.append(
            hashlib.sha512(sig[:32] + pub_key + msg).digest()
        )

    def verify(self) -> tuple[bool, list[bool]]:
        if self._fallback is not None:
            return self._fallback.verify()
        n = len(self._rows)
        if n == 0:
            return False, []
        import jax.numpy as jnp

        V = self._entry.size
        sig_arr = np.frombuffer(b"".join(self._sigs), dtype=np.uint8).reshape(
            n, 64
        )
        dig_arr = np.frombuffer(
            b"".join(self._digest_parts), dtype=np.uint8
        ).reshape(n, 64)
        idx = np.asarray(self._rows, dtype=np.int64)

        # one packed (V, 128) row: R | s | SHA-512 digest — a single
        # host->device transfer per call, sliced apart on device
        packed = np.zeros((V, 128), dtype=np.uint8)
        packed[idx, :32] = sig_arr[:, :32]
        packed[idx, 32:64] = sig_arr[:, 32:]
        packed[idx, 64:] = dig_arr

        fn = self._verify_fn()
        ok_all = np.asarray(fn(self._entry.tables, self._entry.valid, jnp.asarray(packed)))
        picked = ok_all[idx]
        return bool(picked.all()), picked.tolist()

    def _verify_fn(self):
        if self._entry.verify_fn is None:
            import jax

            from ..ops import comb

            bt = comb.get_b_tables()

            @jax.jit
            def run(tables, valid, packed):
                return comb.verify_cached(
                    tables,
                    valid,
                    packed[:, :32],
                    packed[:, 32:64],
                    packed[:, 64:],
                    bt,
                )

            self._entry.verify_fn = run
        return self._entry.verify_fn
