"""Batched Merkle proof serving: the data plane of the PROOF class.

Light-client fan-out is millions of tiny read-only queries — "prove leaf
i of tree T" — and the device answer is one dispatch per tree, however
many queries coalesced against it (ops/merkle.proofs_from_leaves one-hot
sibling gathers; crypto/merkle.device_proofs_from_byte_slices).  This
module adapts that kernel to the verify service's BatchVerifier seam so
proof requests ride the existing (tenant, class) scheduler, wire, and
breaker machinery unchanged:

  - a query is an item triple ``(tree_digest, index_be8, b"")`` — the
    same 3-tuple shape every other mode submits, so _Request, blame
    slicing, and the re-verify paths need no new cases;
  - trees are registered once in a bounded digest -> leaves cache and
    referenced by digest; a query against an unknown/evicted digest gets
    a None result row (a typed miss), never a wrong proof;
  - results are crypto/merkle.Proof rows (or None), and EVERY route —
    device, host fallback, remote plane — resolves to byte-identical
    Proofs because the host oracle proofs_from_byte_slices defines the
    bytes and the device kernels are pinned bit-identical to it by test.

CpuProofProver is the pure-host plane (cpu_verifier_for_mode("proof")):
every degraded path — trip, breaker-open, backpressure, collect timeout
— funnels through it.  TpuProofProver is the device plane; its submit()
runs the dispatch inline and is therefore routed through the service's
class-priority host worker (``_entry = None`` -> _submit_is_offloaded),
so a wide proof batch can never occupy the scheduler thread.
"""

from __future__ import annotations

import hashlib
import struct
import threading
from collections import OrderedDict

from ..crypto import merkle as cmerkle
from ..utils import envknobs, tracing
from ..utils.metrics import hub as _metrics_hub

# ------------------------------------------------------------ tree cache

_INDEX_WIDTH = 8  # query index wire width (big-endian, unsigned)


def tree_digest(leaves) -> bytes:
    """Canonical digest naming a tree by its raw leaves: SHA-256 over
    length-prefixed leaves (the verifysvc/wire.batch_digest idiom), NOT
    the Merkle root — naming the preimage means two leaf lists that
    happen to share a root still cache separately."""
    h = hashlib.sha256()
    h.update(struct.pack("<I", len(leaves)))
    for leaf in leaves:
        h.update(struct.pack("<I", len(leaf)))
        h.update(leaf)
    return h.digest()


class _TreeCache:
    """Bounded LRU of digest -> leaves (COMETBFT_TPU_PROOF_TREE_CACHE)."""

    def __init__(self) -> None:
        self._mtx = threading.Lock()
        self._trees: OrderedDict[bytes, tuple[bytes, ...]] = OrderedDict()

    def _cap(self) -> int:
        return max(1, envknobs.get_int(envknobs.PROOF_TREE_CACHE))

    def put(self, leaves) -> bytes:
        d = tree_digest(leaves)
        with self._mtx:
            self._trees[d] = tuple(leaves)
            self._trees.move_to_end(d)
            cap = self._cap()
            while len(self._trees) > cap:
                self._trees.popitem(last=False)
        return d

    def get(self, digest: bytes):
        with self._mtx:
            t = self._trees.get(digest)
            if t is not None:
                self._trees.move_to_end(digest)
        _metrics_hub().verify_proof_tree_cache.inc(
            result="hit" if t is not None else "miss"
        )
        return t


_CACHE = _TreeCache()


def register_tree(leaves) -> bytes:
    """Pin a tree (list of raw leaf byte strings) into the proof server's
    cache and return the digest proof queries reference it by."""
    return _CACHE.put(leaves)


def tree_leaves(digest: bytes):
    """The cached leaves for a digest, or None after eviction/unknown."""
    return _CACHE.get(digest)


# --------------------------------------------------------- query items


def encode_query(digest: bytes, index: int):
    """(tree digest, leaf index) -> the service item triple."""
    if len(digest) != 32:
        raise ValueError("tree digest must be 32 bytes")
    if index < 0 or index >= 1 << 63:
        raise ValueError("proof index out of range")
    return (digest, int(index).to_bytes(_INDEX_WIDTH, "big"), b"")


def decode_query(item) -> tuple[bytes, int]:
    """Item triple -> (digest, index); malformed shapes raise ValueError
    (submit-side validation; the provers themselves judge bad rows None
    like the cpu verifiers judge malformed rows False)."""
    digest, idx_b, tail = item
    if len(digest) != 32 or len(idx_b) != _INDEX_WIDTH or tail != b"":
        raise ValueError("malformed proof query item")
    return digest, int.from_bytes(idx_b, "big")


def _prove_items(items, device: bool):
    """Shared prover body: group query items by tree digest, answer each
    group in one pass, scatter rows back into the caller's add() order.

    Every row is a crypto/merkle.Proof or None (unknown digest, index
    out of range, malformed item).  The host and device passes are
    bit-identical by construction (pinned by tests/test_merkle_proofs)."""
    rows: list = [None] * len(items)
    by_digest: dict[bytes, list[tuple[int, int]]] = {}
    for pos, item in enumerate(items):
        try:
            digest, idx = decode_query(item)
        except (ValueError, TypeError):
            continue  # malformed row -> None, like cpu verifiers' False
        by_digest.setdefault(digest, []).append((pos, idx))
    m = _metrics_hub()
    for digest, queries in by_digest.items():
        leaves = tree_leaves(digest)
        if leaves is None:
            continue  # typed miss: None rows for every query of this tree
        total = len(leaves)
        good = [(pos, idx) for pos, idx in queries if 0 <= idx < total]
        if not good:
            continue
        idxs = [idx for _, idx in good]
        use_device = (
            device
            and len(idxs) >= max(1, envknobs.get_int(envknobs.PROOF_DEVICE_MIN))
        )
        if use_device:
            try:
                with tracing.span(
                    "verify.proof.device_dispatch",
                    {"queries": len(idxs), "total": total}
                    if tracing.enabled() else None,
                ):
                    _, proofs = cmerkle.device_proofs_from_byte_slices(
                        list(leaves), idxs
                    )
                m.verify_proof_queries.inc(len(idxs), route="device")
            except ImportError:
                use_device = False
        if not use_device:
            with tracing.span(
                "verify.proof.host_route",
                {"queries": len(idxs)} if tracing.enabled() else None,
            ):
                _, all_proofs = cmerkle.proofs_from_byte_slices(list(leaves))
                proofs = [all_proofs[i] for i in idxs]
            m.verify_proof_queries.inc(len(idxs), route="host")
        for (pos, _), proof in zip(good, proofs):
            rows[pos] = proof
    ok = bool(rows) and all(r is not None for r in rows)
    return ok, rows


class CpuProofProver:
    """Pure-host proof plane: proofs_from_byte_slices per referenced tree
    — the bit-identity oracle every fallback path resolves to.  Exposes
    the cpu-verifier seam (`_items`, add, verify) so _HostBatchVerifier
    and _host_verify_items wrap it unchanged."""

    def __init__(self) -> None:
        self._items: list = []

    def __len__(self) -> int:
        return len(self._items)

    def add(self, pub_key: bytes, msg: bytes, sig: bytes) -> None:
        decode_query((pub_key, msg, sig))  # shape-validate like add() peers
        self._items.append((pub_key, msg, sig))

    def verify(self):
        return _prove_items(self._items, device=False)


class TpuProofProver:
    """Device proof plane behind the BatchVerifier seam.  ``_entry =
    None`` routes submit() through the service's class-priority host
    worker (the dispatch pads, compiles on cold shapes, and fetches
    inline), so PROOF-class batches run strictly below every signature
    class there too."""

    _entry = None
    _fallback = None

    def __init__(self) -> None:
        self._items: list = []

    def __len__(self) -> int:
        return len(self._items)

    def add(self, pub_key: bytes, msg: bytes, sig: bytes) -> None:
        decode_query((pub_key, msg, sig))
        self._items.append((pub_key, msg, sig))

    def verify(self):
        return self.collect(self.submit())

    def submit(self):
        if not self._items:
            return ("sync", (False, []))
        return ("sync", _prove_items(self._items, device=True))

    def collect(self, ticket):
        return ticket[1]


# ------------------------------------------------------------ front door


def prove(
    leaves,
    indices,
    *,
    tenant: str | None = None,
    svc=None,
):
    """Serve inclusion proofs for ``indices`` of the tree over ``leaves``
    through the PROOF class of the verify service: queries coalesce with
    every other caller's into one device batch behind the scheduler, and
    results come back in THIS caller's index order.

    Returns (root, [Proof, ...]).  Backpressure, a collect deadline, or
    a scheduler stop all degrade to the host oracle inline — same bytes,
    by construction.  Raises ValueError for an index out of range (the
    caller's bug, not a degraded mode)."""
    from ..verifysvc import service as S

    leaves = list(leaves)
    total = len(leaves)
    if total < 1:
        raise ValueError("cannot prove against an empty tree")
    indices = [int(i) for i in indices]
    for i in indices:
        if i < 0 or i >= total:
            raise ValueError(f"proof index {i} out of range for total {total}")
    digest = register_tree(leaves)
    items = [encode_query(digest, i) for i in indices]
    if svc is None:
        svc = S.global_service()
    rows = None
    with tracing.span(
        "verify.proof.prove",
        {"queries": len(indices), "total": total}
        if tracing.enabled() else None,
    ):
        try:
            ticket = svc.submit(items, S.Klass.PROOF, S.MODE_PROOF, tenant=tenant)
            _, rows = ticket.collect(S.collect_timeout_s())
        except (S.VerifyServiceBackpressure, TimeoutError):
            with tracing.span("verify.proof.host_fallback"):
                _, rows = _prove_items(items, device=False)
    root, proofs = _assemble(leaves, indices, rows)
    return root, proofs


def _assemble(leaves, indices, rows):
    """Post-collect check: a None row at this level means the tree was
    evicted between register and dispatch — re-prove on host from the
    leaves we still hold (identical bytes, the oracle defines them)."""
    if rows is None or len(rows) != len(indices) or any(r is None for r in rows):
        root, all_proofs = cmerkle.proofs_from_byte_slices(leaves)
        return root, [all_proofs[i] for i in indices]
    root = rows[0].compute_root_hash() if rows else cmerkle.empty_hash()
    return root, list(rows)
