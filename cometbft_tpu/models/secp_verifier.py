"""Batched secp256k1/ECDSA verifiers — the MODE_SECP data plane behind
the verify-service seam (verifysvc/service.MODE_SECP).

This is the lane real user traffic uses (ROADMAP item 4; PAPERS.md
arXiv:2112.02229): Ethereum-shaped CheckTx ingest is, by transaction
volume, the biggest workload class, and its signatures are ECDSA over
secp256k1 — Cosmos-style (33-byte compressed pubkey, 64-byte r||s over
SHA-256, ``crypto/secp256k1``), Ethereum-style (65-byte uncompressed
pubkey, 65-byte R||S||V over Keccak-256, ``crypto/secp256k1eth``), or
true ecrecover (20-byte sender ADDRESS, 65-byte R||S||V — no pubkey on
the wire at all; the verifier recovers the signer and compares the
derived address, ``crypto/secp256k1eth.verify_address_signature``).
One lane serves all three: rows are told apart by their pubkey length,
exactly as the host modules are told apart by their wire shapes.

Verdict procedure (identical on every path — the bit-identity contract
the failover/remote fallbacks inherit, same shape as models/bls_verifier):

1. host half: the pubkey encoding decodes (compressed decompression /
   uncompressed parse; cached per key — decoding costs a field sqrt;
   ecrecover rows skip decode, their "pubkey" is the target address),
   the signature has the right length for the key's wire format, and
   the message hash (SHA-256 / Keccak-256) is computed — ON DEVICE,
   fused into the verify dispatch (ops/secp256k1.hash_verify_batch),
   when the batch clears ``COMETBFT_TPU_SECP_HASH_DEVICE_MIN`` and
   every message fits ``COMETBFT_TPU_SECP_HASH_MAX_LEN``; the host
   hash loop otherwise (the hashing-residency seam,
   docs/verify_service.md).
2. data half: range + low-s checks, s^-1 and the affine normalization
   (Montgomery batch inversion), u1*G + u2*Q — the GLV endomorphism
   quad-scalar walk by default, the plain Shamir witness under
   ``COMETBFT_TPU_SECP_GLV=0`` — and the x(R') mod n == r /
   Ecrecover-parity / recovered-address verdict — on device
   (ops/secp256k1.verify_batch) when the batch clears
   ``COMETBFT_TPU_SECP_DEVICE_MIN``, on host (the crypto modules'
   own ``verify_signature``) otherwise.  The kernel is constructed to
   be bit-identical to the host lane in every edge
   (tests/test_secp_ops.py pins it over an adversarial corpus).

Unlike BLS there is no aggregate claim: rows are independent, so
MODE_SECP batches COALESCE in the scheduler like plain ed25519 ones
(same-mode requests only) and blame is exactly per-row.

Split of labor: ``CpuSecpBatchVerifier`` is pure host (never imports
jax — the PR-8 failover / PR-13 breaker fallback path);
``TpuSecpBatchVerifier`` routes the batch through the ops/secp256k1
kernel.  Both are DATA PLANE only: production consumers reach them
through the verify service.
"""

from __future__ import annotations

import hashlib
import threading

from ..crypto import secp256k1 as host_secp
from ..crypto import secp256k1eth as host_eth
from ..crypto.keccak import keccak256
from ..utils import envknobs, tracing
from ..utils.metrics import hub as _mhub
from .bls_verifier import _FactCache

COSMOS_PUB = host_secp.PUBKEY_SIZE  # 33: compressed
COSMOS_SIG = host_secp.SIGNATURE_SIZE  # 64: r || s
ETH_PUB = host_eth.PUBKEY_SIZE  # 65: 0x04 || x || y
ETH_SIG = host_eth.SIGNATURE_SIZE  # 65: R || S || V
ECR_PUB = host_eth.ADDRESS_SIZE  # 20: sender address (no pubkey on wire)
ECR_SIG = host_eth.SIGNATURE_SIZE  # 65: R || S || V

_MISS = object()

# Phase attribution of the LAST device dispatch: "*_ms" keys
# (hash / decode / assembly / h2d / kernel / fetch) plus rows /
# hash_device / glv markers.  Overwritten on every device dispatch —
# consumed by bench.py (BENCH_WORKLOAD=secp phase_attribution) and
# scripts/profile_secp_phases.py, which run one dispatch at a time, so
# no thread merging.  ``hash_ms`` is the HOST side of hashing: the
# digest loop on the host-hash path, just the block padding on the
# fused path (the digests themselves then ride inside kernel_ms).
LAST_PHASES: dict[str, float] = {}

# pubkey bytes -> affine (x, y) int pair | None (malformed encoding).
# Decoding a compressed key costs one field sqrt (~pow mod p); CheckTx
# ingest repeats senders, so the fact caches like the BLS lane's.
_PK_CACHE: _FactCache | None = None
_PK_CACHE_MTX = threading.Lock()


def _pk_cache() -> _FactCache:
    global _PK_CACHE
    if _PK_CACHE is None:
        with _PK_CACHE_MTX:
            if _PK_CACHE is None:
                _PK_CACHE = _FactCache(
                    max(0, envknobs.get_int(envknobs.SECP_PUBKEY_CACHE))
                )
    return _PK_CACHE


def reset_caches() -> None:
    """Tests and the bench's cold rounds: drop every cached decode (and
    re-read the cache-size knob on next use)."""
    global _PK_CACHE
    _PK_CACHE = None


def _decode_pub(pub: bytes):
    """Pubkey bytes -> affine (x, y) int pair, or None for malformed /
    wrong-length encodings.  Cache-backed; decoding is a per-key FACT
    (same value on every path), so caching can never split verdicts."""
    cache = _pk_cache()
    hit = cache.get(pub, _MISS)
    if hit is not _MISS:
        _mhub().secp_pubkey_cache.inc(result="hit")
        return hit
    _mhub().secp_pubkey_cache.inc(result="miss")
    aff = None
    try:
        if len(pub) == COSMOS_PUB:
            aff = host_secp._decompress(pub)
        elif len(pub) == ETH_PUB:
            aff = host_eth._parse_uncompressed(pub)
    except ValueError:
        aff = None
    cache.put(pub, aff)
    return aff


def _host_verify_one(pub: bytes, msg: bytes, sig: bytes) -> bool:
    """The pure-host verdict oracle: EXACTLY the crypto modules' own
    key-construction + verify gauntlet, selected by pubkey length.
    Malformed anything judges False — a fallback re-verify must never
    raise out of the service's worker loops."""
    try:
        if len(pub) == COSMOS_PUB:
            return host_secp.PubKey(pub).verify_signature(msg, sig)
        if len(pub) == ETH_PUB:
            return host_eth.PubKey(pub).verify_signature(msg, sig)
        if len(pub) == ECR_PUB:
            return host_eth.verify_address_signature(pub, msg, sig)
    except ValueError:
        return False
    return False


def _device_min() -> int:
    return max(1, envknobs.get_int(envknobs.SECP_DEVICE_MIN))


def _bucket(n: int) -> int:
    b = 8
    while b < n:
        b *= 2
    return b


def _verify_items(items, use_device: bool) -> tuple[bool, list[bool]]:
    """The ONE verdict procedure both verifier classes run;
    ``use_device`` only moves the batch's field/group arithmetic."""
    n = len(items)
    if n == 0:
        return (False, [])
    if not use_device or n < _device_min():
        res = [_host_verify_one(p, m, s) for (p, m, s) in items]
        return (all(res) and bool(res), res)

    import time as _time

    import numpy as np

    from ..ops import secp256k1 as dev

    t0 = _time.perf_counter()
    b = _bucket(n)
    qx = np.zeros((b, dev.NLIMBS), dtype=np.int32)
    qy = np.zeros((b, dev.NLIMBS), dtype=np.int32)
    valid = np.zeros((b,), dtype=bool)
    e = np.zeros((b, dev.NLIMBS), dtype=np.int32)
    r = np.zeros((b, dev.NLIMBS), dtype=np.int32)
    s = np.zeros((b, dev.NLIMBS), dtype=np.int32)
    is_eth = np.zeros((b,), dtype=bool)
    v = np.zeros((b,), dtype=np.int32)
    is_rec = np.zeros((b,), dtype=bool)
    addr = np.zeros((b, ECR_PUB), dtype=np.uint8)

    # hashing residency: fuse SHA-256/Keccak-256 into the device
    # dispatch when the batch is wide enough to amortize it and every
    # message fits the padded block shape the program compiled for
    hmin = envknobs.get_int(envknobs.SECP_HASH_DEVICE_MIN)
    hmax = envknobs.get_int(envknobs.SECP_HASH_MAX_LEN)
    hash_dev = (
        hmin > 0
        and n >= hmin
        and all(len(msg) <= hmax for (_, msg, _) in items)
    )
    msgs: list[bytes] = [b""] * b
    phases = {"decode_ms": 0.0, "hash_ms": 0.0}

    qxs, qys, es, rs, ss, rows = [], [], [], [], [], []
    for i, (pub, msg, sig) in enumerate(items):
        eth = len(pub) == ETH_PUB
        rec = len(pub) == ECR_PUB
        if rec:
            # no pubkey on the wire: the kernel recovers the signer and
            # compares the derived address — nothing to decode or cache
            if len(sig) != ECR_SIG:
                continue
            aff = (0, 0)
            addr[i] = np.frombuffer(pub, dtype=np.uint8)
        else:
            td = _time.perf_counter()
            aff = _decode_pub(pub)
            phases["decode_ms"] += (_time.perf_counter() - td) * 1e3
            # the signature wire shape must match the KEY's wire format
            # — the host modules' own length gate
            sig_len = ETH_SIG if eth else COSMOS_SIG
            if aff is None or len(sig) != sig_len:
                continue  # row stays valid=False / s=0 -> judged False
            valid[i] = True
        is_eth[i] = eth
        is_rec[i] = rec
        if eth or rec:
            v[i] = sig[64]
        if hash_dev:
            msgs[i] = msg
        else:
            th = _time.perf_counter()
            h = keccak256(msg) if (eth or rec) else hashlib.sha256(msg).digest()
            phases["hash_ms"] += (_time.perf_counter() - th) * 1e3
            es.append(int.from_bytes(h, "big"))
        qxs.append(aff[0])
        qys.append(aff[1])
        rs.append(int.from_bytes(sig[:32], "big"))
        ss.append(int.from_bytes(sig[32:64], "big"))
        rows.append(i)
    if rows:
        qx[rows] = dev.ints_to_limbs_np(qxs)
        qy[rows] = dev.ints_to_limbs_np(qys)
        r[rows] = dev.ints_to_limbs_np(rs)
        s[rows] = dev.ints_to_limbs_np(ss)
        if not hash_dev:
            e[rows] = dev.ints_to_limbs_np(es)
    glv = envknobs.get_bool(envknobs.SECP_GLV)
    m = _mhub()
    assembly_s = _time.perf_counter() - t0
    m.verify_phase_seconds.observe(assembly_s, phase="secp_assembly")
    phases["assembly_ms"] = (
        assembly_s * 1e3 - phases["decode_ms"] - phases["hash_ms"]
    )
    t1 = _time.perf_counter()
    with tracing.span(
        "verify.secp_batch",
        {"sigs": n, "where": "device", "hash": "device" if hash_dev else "host"}
        if tracing.enabled() else None,
    ):
        if hash_dev:
            from ..ops import keccak as kops
            from ..ops import sha2 as sops

            tp = _time.perf_counter()
            sha_blocks, sha_active = sops.pad_messages_sha256(
                msgs, max_len=hmax
            )
            kec_blocks, kec_active = kops.pad_messages_keccak(
                msgs, max_len=hmax
            )
            phases["hash_ms"] += (_time.perf_counter() - tp) * 1e3
            ok = dev.hash_verify_batch_device(
                sha_blocks, sha_active, kec_blocks, kec_active,
                qx, qy, valid, r, s, is_eth, v,
                is_rec=is_rec, addr=addr, glv=glv, timings=phases,
            )
        else:
            ok = dev.verify_batch_device(
                qx, qy, valid, e, r, s, is_eth, v,
                is_rec=is_rec, addr=addr, glv=glv, timings=phases,
            )
    m.verify_phase_seconds.observe(
        _time.perf_counter() - t1, phase="secp_device"
    )
    phases["rows"] = float(n)
    phases["hash_device"] = 1.0 if hash_dev else 0.0
    phases["glv"] = 1.0 if glv else 0.0
    LAST_PHASES.clear()
    LAST_PHASES.update(phases)
    res = [bool(x) for x in ok[:n]]
    return (all(res) and bool(res), res)


def _check_item(pub: bytes, msg: bytes, sig: bytes) -> None:
    if len(pub) not in (ECR_PUB, COSMOS_PUB, ETH_PUB) or len(sig) not in (
        COSMOS_SIG,
        ETH_SIG,
    ):
        raise ValueError("malformed secp256k1 pubkey or signature")


class CpuSecpBatchVerifier:
    """Pure-host ECDSA verification — never imports jax; the
    degraded-mode / breaker-open data plane, bit-identical to the
    device-assisted verifier by construction (the kernel replicates the
    host gauntlet edge for edge)."""

    def __init__(self) -> None:
        self._items: list[tuple[bytes, bytes, bytes]] = []

    def __len__(self) -> int:
        return len(self._items)

    def add(self, pub_key: bytes, msg: bytes, sig: bytes) -> None:
        _check_item(pub_key, msg, sig)
        self._items.append((pub_key, msg, sig))

    def verify(self) -> tuple[bool, list[bool]]:
        return _verify_items(self._items, use_device=False)


class TpuSecpBatchVerifier:
    """Device-assisted ECDSA verification: the whole range-check /
    batch-inversion / Shamir pipeline in one fused kernel dispatch
    (ops/secp256k1.verify_batch) above COMETBFT_TPU_SECP_DEVICE_MIN
    rows, the host loop below it.

    ``_entry = None`` routes submit() through the verify service's
    class-priority host worker (assembly and any cold bucket-shape
    compile are real submit-time work that must never run on the
    scheduler thread).  The ticket is synchronous: a wedged device
    inside the kernel parks the host worker, where the health
    sentinel's trip re-verifies the tracked batch on host."""

    _entry = None
    _fallback = None

    def __init__(self) -> None:
        self._items: list[tuple[bytes, bytes, bytes]] = []

    def __len__(self) -> int:
        return len(self._items)

    def add(self, pub_key: bytes, msg: bytes, sig: bytes) -> None:
        _check_item(pub_key, msg, sig)
        self._items.append((pub_key, msg, sig))

    def submit(self):
        return ("sync", _verify_items(self._items, use_device=True))

    def collect(self, ticket) -> tuple[bool, list[bool]]:
        return ticket[1]

    def verify(self) -> tuple[bool, list[bool]]:
        return self.collect(self.submit())
