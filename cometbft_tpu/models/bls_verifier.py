"""Batched BLS12-381 aggregate-commit verifiers — the BLS data plane
behind the verify-service seam (verifysvc/service.MODE_BLS).

The cost model this plane exists for (PAPERS.md arXiv:2302.00418): an
ed25519 commit costs N independent verifies; a BLS aggregate commit
costs ONE pairing-product check plus a data-parallel pubkey sum.  The
BatchVerifier seam still receives per-validator (pub, msg, sig) rows, so
the verifier groups rows into **units** keyed by exact (msg, sig) bytes:

* an aggregate commit arrives as N rows sharing one message and one
  aggregate signature -> one unit, one signature decode, one tree-
  reduced pubkey sum (device), one pairing check;
* individually signed rows are N singleton units -> the whole batch is
  still ONE pairing-product check (N+1 Miller loops, one final
  exponentiation in the native core) with exact per-row blame on
  failure.

Verdict procedure (identical on every path — this is the bit-identity
contract the failover/remote fallbacks inherit):

1. well-formedness: pubkeys decompress, are finite, on curve, and in
   the r-subgroup (cached across calls — per-key facts); unit
   signatures decompress, are finite, on curve, and in the r-subgroup.
2. if every row is well-formed: ONE pairing-product check
   ``prod e(agg_pk_u, H(m_u)) * e(-g1, sum sigs) == 1`` decides the
   batch; pass -> every row True.  On failure (or when any row is
   malformed) each unit is re-checked individually and every row of a
   failing unit reads False.

Like every batch verifier, a passing batch check certifies the batch,
not each element (aggregate semantics — within a unit, blame is
inherently unit-granular).  FastAggregateVerify over same-message rows
is SOUND ONLY for proof-of-possession-checked keys (crypto/bls12381
.pop_verify at key registration; the rogue-key caveat documented
there).

Split of labor: ``CpuBlsBatchVerifier`` is pure host (never imports
jax — the PR-8 failover / PR-13 breaker fallback path);
``BlsAggregateVerifier`` routes pubkey validation and unit aggregation
through the ops/bls381 kernels when batch sizes clear the
``COMETBFT_TPU_BLS_*`` thresholds.  Miller loop + final exponentiation
stay on host (crypto/bls12381, native pairing core) exactly as the
reference keeps them inside blst.

These classes are the DATA PLANE only: production consumers reach them
through the verify service (verifysvc/service.py routes MODE_BLS
batches here; crypto/batch.create_batch_verifier selects the mode off
the validator key type).
"""

from __future__ import annotations

import threading
import time

from ..crypto import bls12381 as host_bls
from ..utils import envknobs, tracing
from ..utils.metrics import hub as _mhub

PUBKEY_SIZE = host_bls.PUBKEY_SIZE  # 48: compressed G1
SIG_SIZE = host_bls.SIG_SIZE  # 96: compressed G2

_NEG_G1 = (host_bls.G1_GEN[0], (-host_bls.G1_GEN[1]) % host_bls.P)

# cache-miss sentinel: None is a legitimate cached value ("invalid key")
_MISS = object()


class _FactCache:
    """Bounded FIFO cache of per-input FACTS (deterministic, path-
    independent values), shared by the host and device paths — caching
    can therefore never split their verdicts.  Thread-safe: the verify
    service's host worker and clients' inline fallbacks both read it."""

    def __init__(self, max_size: int):
        self._d: dict = {}
        self._max = max_size
        self._mtx = threading.Lock()

    def get(self, key, default=None):
        with self._mtx:
            return self._d.get(key, default)

    def put(self, key, value) -> None:
        if self._max <= 0:
            return
        with self._mtx:
            if key not in self._d and len(self._d) >= self._max:
                self._d.pop(next(iter(self._d)))
            self._d[key] = value

    def clear(self) -> None:
        with self._mtx:
            self._d.clear()

    def __len__(self) -> int:
        with self._mtx:
            return len(self._d)


# pubkey bytes -> affine (x, y) int pair (fully validated: finite, on
# curve, in subgroup) | None (invalid).  Sized by COMETBFT_TPU_BLS_PUBKEY
# _CACHE at first use; validator sets repeat every commit, so steady
# state never re-runs the ~4 ms/key subgroup check.
_PK_CACHE: _FactCache | None = None
_PK_CACHE_MTX = threading.Lock()

# msg -> hash_to_g2 affine point (the ~28 ms hash-to-curve per distinct
# message; light/verify passes re-hash the same sign-bytes)
_H2_CACHE = _FactCache(1024)


def _pk_cache() -> _FactCache:
    global _PK_CACHE
    if _PK_CACHE is None:
        with _PK_CACHE_MTX:
            if _PK_CACHE is None:
                _PK_CACHE = _FactCache(
                    max(0, envknobs.get_int(envknobs.BLS_PUBKEY_CACHE))
                )
    return _PK_CACHE


def reset_caches() -> None:
    """Tests and the bench's cold rounds: drop every cached fact (and
    re-read the cache-size knob on next use)."""
    global _PK_CACHE
    with _PK_CACHE_MTX:
        _PK_CACHE = None
    _H2_CACHE.clear()


def _hash_g2(msg: bytes):
    h = _H2_CACHE.get(msg)
    if h is None:
        h = host_bls.hash_to_g2(msg)
        _H2_CACHE.put(msg, h)
    return h


def _decode_pub(pub: bytes):
    """Compressed G1 pubkey -> affine pair, or None for malformed /
    infinite encodings.  Decompression guarantees on-curve; the
    subgroup check is the batched half (device or host)."""
    try:
        aff = host_bls._g1_decompress(pub)
    except ValueError:
        return None
    return aff  # None here = infinity: rejected like key_bls12381.go:166


def _decode_sig(sig: bytes):
    """Compressed G2 signature -> affine pair, or None for malformed /
    infinite / off-curve / out-of-subgroup encodings — exactly the
    gauntlet PubKey.verify_signature runs."""
    try:
        s = host_bls._g2_decompress(sig)
    except ValueError:
        return None
    if (
        s is None
        or not host_bls._on_curve(host_bls._FP2, s)
        or not host_bls._in_subgroup(host_bls._FP2, s)
    ):
        return None
    return s


def _validated_pubkeys(pubs, use_device: bool):
    """-> list of affine | None (None = invalid), cache-backed.  The
    uncached keys' subgroup checks batch on device when ``use_device``
    and the batch clears COMETBFT_TPU_BLS_VALIDATE_DEVICE_MIN; the host
    loop is the bit-identical fallback."""
    cache = _pk_cache()
    out: list = [_MISS] * len(pubs)
    fresh: dict[bytes, list[int]] = {}
    for i, pub in enumerate(pubs):
        hit = cache.get(pub, _MISS)
        if hit is not _MISS:
            out[i] = hit
        else:
            fresh.setdefault(pub, []).append(i)
    if not fresh:
        return out
    order = list(fresh.keys())
    decoded = [_decode_pub(pub) for pub in order]
    t0 = time.perf_counter()
    candidates = [aff for aff in decoded if aff is not None]
    if (
        use_device
        and len(candidates)
        >= max(1, envknobs.get_int(envknobs.BLS_VALIDATE_DEVICE_MIN))
    ):
        from ..ops import bls381 as dev

        with tracing.span(
            "verify.bls_validate",
            {"keys": len(decoded), "where": "device"}
            if tracing.enabled() else None,
        ):
            ok = dev.validate_pubkeys_device(decoded)
        checked = [aff if o else None for aff, o in zip(decoded, ok)]
        where = "device"
    else:
        checked = [
            aff
            if aff is not None and host_bls._in_subgroup(host_bls._FP, aff)
            else None
            for aff in decoded
        ]
        where = "host"
    _mhub().verify_phase_seconds.observe(
        time.perf_counter() - t0, phase=f"bls_validate_{where}"
    )
    for pub, aff in zip(order, checked):
        cache.put(pub, aff)
        for i in fresh[pub]:
            out[i] = aff
    return out


def _aggregate_unit(affs, use_device: bool):
    """Sum a unit's (already validated) affine pubkeys -> affine pair or
    None (identity).  Device tree-reduce above COMETBFT_TPU_BLS_AGG
    _DEVICE_MIN, host Jacobian sum below — the same group element, and
    affine coordinates are unique, so the paths cannot diverge."""
    if len(affs) == 1:
        # singleton unit (individually-signed row): the sum IS the
        # point — skip the Jacobian round trip, whose _to_affine costs
        # one ~381-bit field inversion PER ROW at batch scale
        return affs[0]
    if (
        use_device
        and len(affs) >= max(1, envknobs.get_int(envknobs.BLS_AGG_DEVICE_MIN))
    ):
        from ..ops import bls381 as dev

        with tracing.span(
            "verify.bls_aggregate",
            {"keys": len(affs), "where": "device"}
            if tracing.enabled() else None,
        ):
            return dev.aggregate_pubkeys_device(affs)
    acc = (host_bls._FP.one, host_bls._FP.one, host_bls._FP.zero)
    for aff in affs:
        acc = host_bls._jac_add(host_bls._FP, acc, host_bls._from_affine(host_bls._FP, aff))
    return host_bls._to_affine(host_bls._FP, acc)


def _verify_items(items, use_device: bool) -> tuple[bool, list[bool]]:
    """The ONE verdict procedure (module docstring) both verifier
    classes run; ``use_device`` only moves the G1 arithmetic."""
    n = len(items)
    if n == 0:
        return (False, [])

    # units: rows grouped by exact (msg, sig) bytes, in first-seen order
    units: dict[tuple[bytes, bytes], list[int]] = {}
    for i, (_, msg, sig) in enumerate(items):
        units.setdefault((msg, sig), []).append(i)

    pubs = [pub for pub, _, _ in items]
    agg_memo: dict[tuple[bytes, bytes], object] = {}
    cache = _pk_cache()
    fresh = sum(1 for p in set(pubs) if cache.get(p, _MISS) is _MISS)
    if (
        use_device
        and len(units) == 1
        and fresh >= max(1, envknobs.get_int(envknobs.BLS_VALIDATE_DEVICE_MIN))
    ):
        # the aggregate-commit cold path: validation + tree-reduced
        # pubkey sum FUSED into one device dispatch
        # (ops/bls381.validate_aggregate_g1); the fused aggregate sums
        # exactly the valid rows, so when the batch turns out
        # all-well-formed it IS the unit aggregate
        from ..ops import bls381 as dev

        decoded = [_decode_pub(p) for p in pubs]
        t0 = time.perf_counter()
        with tracing.span(
            "verify.bls_validate",
            {"keys": n, "where": "device", "fused": True}
            if tracing.enabled() else None,
        ):
            ok, agg = dev.validate_aggregate_device(decoded)
        _mhub().verify_phase_seconds.observe(
            time.perf_counter() - t0, phase="bls_validate_device"
        )
        pub_affs = [aff if o else None for aff, o in zip(decoded, ok)]
        for p, aff in zip(pubs, pub_affs):
            cache.put(p, aff)
        if all(ok):
            (key,) = units
            agg_memo[key] = agg
    else:
        pub_affs = _validated_pubkeys(pubs, use_device)

    t0 = time.perf_counter()
    sig_pts = {key: _decode_sig(key[1]) for key in units}
    _mhub().verify_phase_seconds.observe(
        time.perf_counter() - t0, phase="bls_sig_decode"
    )

    wellformed: dict[tuple[bytes, bytes], bool] = {
        key: sig_pts[key] is not None
        and all(pub_affs[i] is not None for i in rows)
        for key, rows in units.items()
    }

    def unit_pairs(key):
        # memoized: the blame path must reuse the hot path's (possibly
        # device-computed) aggregations, never re-dispatch them
        if key not in agg_memo:
            agg_memo[key] = _aggregate_unit(
                [pub_affs[i] for i in units[key]], use_device
            )
        return (agg_memo[key], _hash_g2(key[0]))

    verdict: dict[tuple[bytes, bytes], bool] = {}
    batch_ok = None
    if all(wellformed.values()):
        # the hot path: ONE pairing-product check for the whole batch
        pairs = [unit_pairs(key) for key in units]
        acc = (host_bls._FP2.one, host_bls._FP2.one, host_bls._FP2.zero)
        for key in units:
            acc = host_bls._jac_add(
                host_bls._FP2, acc,
                host_bls._from_affine(host_bls._FP2, sig_pts[key]),
            )
        pairs.append((_NEG_G1, host_bls._to_affine(host_bls._FP2, acc)))
        t0 = time.perf_counter()
        with tracing.span(
            "verify.bls_pairing",
            {"units": len(units)} if tracing.enabled() else None,
        ):
            batch_ok = host_bls._pairings_product_is_one(pairs)
        _mhub().verify_phase_seconds.observe(
            time.perf_counter() - t0, phase="bls_pairing"
        )
        if batch_ok:
            return (True, [True] * n)

    # blame: each well-formed unit re-checked individually; every row of
    # a malformed or failing unit reads False
    for key in units:
        if not wellformed[key]:
            verdict[key] = False
        elif batch_ok is not None and len(units) == 1:
            # a single well-formed unit's individual check IS the batch
            # product that just failed — no second pairing needed
            verdict[key] = batch_ok
        else:
            verdict[key] = host_bls._pairings_product_is_one(
                [unit_pairs(key), (_NEG_G1, sig_pts[key])]
            )
    res = [False] * n
    for key, rows in units.items():
        for i in rows:
            res[i] = verdict[key]
    return (all(res) and bool(res), res)


def _check_item(pub: bytes, msg: bytes, sig: bytes) -> None:
    if len(pub) != PUBKEY_SIZE or len(sig) != SIG_SIZE:
        raise ValueError("malformed bls12-381 pubkey or signature")


class CpuBlsBatchVerifier:
    """Pure-host BLS verification — never imports jax; the degraded-mode
    / breaker-open data plane, bit-identical to the device-assisted
    verifier by construction (one shared verdict procedure)."""

    def __init__(self) -> None:
        self._items: list[tuple[bytes, bytes, bytes]] = []

    def __len__(self) -> int:
        return len(self._items)

    def add(self, pub_key: bytes, msg: bytes, sig: bytes) -> None:
        _check_item(pub_key, msg, sig)
        self._items.append((pub_key, msg, sig))

    def verify(self) -> tuple[bool, list[bool]]:
        return _verify_items(self._items, use_device=False)


class BlsAggregateVerifier:
    """Device-assisted BLS verification: batched pubkey validation and
    tree-reduced unit aggregation on the accelerator, pairing on host.

    ``_entry = None`` routes submit() through the verify service's
    class-priority host worker (the pairing and any cold kernel compile
    are real submit-time work that must never run on the scheduler
    thread).  The ticket is synchronous: a wedged device inside the G1
    kernels parks the host worker, where the health sentinel's trip —
    not the batch-deadline clock — re-verifies the tracked batch on
    host (service._trip_to_cpu snapshots EVERY in-flight record)."""

    _entry = None
    _fallback = None

    def __init__(self) -> None:
        self._items: list[tuple[bytes, bytes, bytes]] = []

    def __len__(self) -> int:
        return len(self._items)

    def add(self, pub_key: bytes, msg: bytes, sig: bytes) -> None:
        _check_item(pub_key, msg, sig)
        self._items.append((pub_key, msg, sig))

    def submit(self):
        return ("sync", _verify_items(self._items, use_device=True))

    def collect(self, ticket) -> tuple[bool, list[bool]]:
        return ticket[1]

    def verify(self) -> tuple[bool, list[bool]]:
        return self.collect(self.submit())
