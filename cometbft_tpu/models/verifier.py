"""Batch signature verifiers: the TPU data plane behind the crypto seam.

Implements the BatchVerifier contract of the reference
(crypto/crypto.go:47-55): add(pubkey, msg, sig) accumulates work, verify()
returns (all_valid, per_signature_validity) — per-signature blame is what
lets commit verification tally honest voting power even when some
signatures are bad (types/validation.go:384-399).

The TPU provider assembles the batch on host (numpy), pads to a
power-of-two bucket so XLA compiles a handful of shapes, and runs the
fully fused kernel from ops/ed25519.verify_batch.  A CPU provider with
identical semantics backs tests and TPU-less hosts.

These classes are the DATA PLANE only: production consumers never
submit to them directly — all scheduling, batching, and dispatch goes
through the unified verify service (verifysvc/service.py), whose
scheduler constructs these verifiers per dispatched batch
(docs/verify_service.md).
"""

from __future__ import annotations

from typing import Protocol

import numpy as np

from ..crypto import ed25519 as host_ed25519
from ..utils import tracing
from ..utils.metrics import hub as _metrics_hub

_VERIFY_JIT = None


class BatchVerifier(Protocol):
    def add(self, pub_key: bytes, msg: bytes, sig: bytes) -> None: ...

    def verify(self) -> tuple[bool, list[bool]]: ...


class CpuEd25519BatchVerifier:
    """Sequential ZIP-215 verification (host fallback)."""

    def __init__(self) -> None:
        self._items: list[tuple[bytes, bytes, bytes]] = []

    def __len__(self) -> int:
        return len(self._items)

    def add(self, pub_key: bytes, msg: bytes, sig: bytes) -> None:
        if len(pub_key) != 32 or len(sig) != 64:
            raise ValueError("malformed ed25519 pubkey or signature")
        self._items.append((pub_key, msg, sig))

    def verify(self) -> tuple[bool, list[bool]]:
        res = [
            host_ed25519.verify_signature(p, m, s) for (p, m, s) in self._items
        ]
        return all(res) and bool(res), res


def _next_bucket(n: int) -> int:
    b = 16
    while b < n:
        b *= 2
    return b


def _device_batch_min() -> int:
    import os

    from ..utils import envknobs

    v = envknobs.get_opt_int(envknobs.DEVICE_BATCH_MIN)
    if v is not None:
        return v
    # Default is link-aware: through a remote device tunnel (axon) every
    # call pays ~85 ms host->device latency plus ~85 ms per result fetch
    # (measured, scripts/profile_tunnel.py), so batches under ~2k
    # signatures finish sooner on the host (~0.14 ms/sig sequential).  A
    # locally attached chip has microsecond dispatch and wins from a few
    # dozen signatures.
    return 2048 if os.environ.get("PALLAS_AXON_POOL_IPS") else 32



class TpuEd25519BatchVerifier:
    """Batched ZIP-215 verification on the default JAX device.

    One jitted program per (bucket, nblocks) shape; buckets are powers of
    two so a 10k-validator commit and a 150-validator light-client check
    each compile once and are then cache hits (the TPU analogue of the
    reference's expanded-key LRU, ed25519.go:43,68).
    """

    def __init__(self) -> None:
        self._items: list[tuple[bytes, bytes, bytes]] = []

    def __len__(self) -> int:
        return len(self._items)

    def add(self, pub_key: bytes, msg: bytes, sig: bytes) -> None:
        if len(pub_key) != 32 or len(sig) != 64:
            raise ValueError("malformed ed25519 pubkey or signature")
        self._items.append((pub_key, msg, sig))

    @staticmethod
    def _compiled():
        """One jitted entry point; jax.jit caches per input shape, and the
        power-of-two bucketing above keeps the shape set small.  The jit
        site is registered in kernel_manifest.JIT_SITES (manifest kernel
        ``ed25519_verify_batch``)."""
        global _VERIFY_JIT
        if _VERIFY_JIT is None:
            import jax
            from ..ops import ed25519 as E

            _VERIFY_JIT = jax.jit(E.verify_batch)
        return _VERIFY_JIT

    def verify(self) -> tuple[bool, list[bool]]:
        return self.collect(self.submit())

    def submit(self):
        """Dispatch without waiting — the same async seam the comb-cached
        verifier exposes (models/comb_verifier.CombBatchVerifier.submit),
        so the blocksync verify-ahead pipeline can overlap host work with
        device execution even while comb tables are still warming (the
        async-build window) or for foreign-key sets.  Returns an opaque
        ticket for collect()."""
        n = len(self._items)
        if n == 0:
            return ("sync", (False, []))
        _metrics_hub().verify_batch_width.observe(float(n))
        # Below the device threshold the dispatch overhead (and, on first
        # use, compile time) dwarfs the arithmetic — verify on host.  The
        # hot configs (150-val light blocks, 10k-val commits) always take
        # the device path.
        if n < _device_batch_min():
            cpu = CpuEd25519BatchVerifier()
            cpu._items = self._items
            with tracing.span("verify.host_route"):
                return ("sync", cpu.verify())
        return ("dev", (self._submit_device(n), n))

    def collect(self, ticket) -> tuple[bool, list[bool]]:
        kind, payload = ticket
        if kind == "sync":
            return payload
        out, n = payload
        import time as _time

        t0 = _time.perf_counter()
        with tracing.span("verify.device_wait"):
            ok = np.asarray(out)[:n]  # blocks until the device result lands
        _metrics_hub().verify_phase_seconds.observe(
            _time.perf_counter() - t0, phase="device_wait"
        )
        res = [bool(x) for x in ok]
        return all(res), res

    def _submit_device(self, n: int):
        import time as _time

        import jax.numpy as jnp
        from ..ops import sha2

        t0 = _time.perf_counter()
        with tracing.span("verify.uncached_assemble"):
            bucket = _next_bucket(n)
            a = np.zeros((bucket, 32), dtype=np.uint8)
            r = np.zeros((bucket, 32), dtype=np.uint8)
            s = np.zeros((bucket, 32), dtype=np.uint8)
            hashed = []
            for i, (pub, msg, sig) in enumerate(self._items):
                a[i] = np.frombuffer(pub, dtype=np.uint8)
                r[i] = np.frombuffer(sig[:32], dtype=np.uint8)
                s[i] = np.frombuffer(sig[32:], dtype=np.uint8)
                hashed.append(sig[:32] + pub + msg)
            # Pad rows repeat row 0 so padded lanes do real-but-ignored work.
            for i in range(n, bucket):
                a[i], r[i], s[i] = a[0], r[0], s[0]
                hashed.append(hashed[0])
            blocks, active = sha2.pad_messages_sha512(hashed)
        fn = self._compiled()
        t1 = _time.perf_counter()
        # device dispatch is asynchronous: the returned array is a future.
        # NOTE: a first call at a new bucket shape compiles inside fn(...),
        # so that one observation (span and histogram alike) carries the
        # XLA compile — same caveat as the comb path; warm calls are pure
        # transfer+dispatch.
        with tracing.span("verify.h2d_dispatch"):
            out = fn(
                jnp.asarray(a),
                jnp.asarray(r),
                jnp.asarray(s),
                jnp.asarray(blocks),
                jnp.asarray(active),
            )
        m = _metrics_hub()
        m.verify_phase_seconds.observe(t1 - t0, phase="assembly")
        m.verify_phase_seconds.observe(
            _time.perf_counter() - t1, phase="h2d_dispatch"
        )
        return out
