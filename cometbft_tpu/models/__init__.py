"""Flagship verification-plane pipelines.

The "model" of this framework is the commit-verification pipeline: batched
Ed25519 signature verification plus Merkle tree hashing compiled as fused
XLA programs, optionally sharded over a device mesh (cometbft_tpu.parallel).
bench.py and __graft_entry__.py drive these.
"""
