"""ABCI conformance grammar checker
(reference: test/e2e/pkg/grammar/checker.go:19 + the ABCI grammar in
spec/abci — the generated GLL parser there reduces to this small
recursive-descent checker over the consensus-connection call trace).

Grammar (consensus connection only; CheckTx/Info/Query ride other
connections and snapshot calls are free):

  clean-start  = InitChain state-sync? consensus-exec
  recovery     = consensus-exec
  state-sync   = OfferSnapshot ApplySnapshotChunk*
  consensus-exec = height+
  height       = proposer-calls* FinalizeBlock Commit
  proposer-calls = PrepareProposal | ProcessProposal
                 | ExtendVote | VerifyVoteExtension
"""

from __future__ import annotations

PROPOSER_CALLS = {
    "prepare_proposal",
    "process_proposal",
    "extend_vote",
    "verify_vote_extension",
}
SNAPSHOT_CALLS = {"offer_snapshot", "apply_snapshot_chunk"}
FREE_CALLS = {"info", "query", "check_tx", "list_snapshots", "load_snapshot_chunk", "echo", "flush"}


class GrammarError(Exception):
    def __init__(self, pos: int, call: str, reason: str):
        super().__init__(f"call #{pos} ({call}): {reason}")
        self.pos = pos
        self.call = call


def check_execution(calls: list[str], clean_start: bool) -> None:
    """Validate one execution trace (checker.go Verify)."""
    seq = [c for c in calls if c not in FREE_CALLS]
    i = 0

    def peek():
        return seq[i] if i < len(seq) else None

    if clean_start:
        if peek() != "init_chain":
            raise GrammarError(i, peek() or "<end>", "clean start must begin with InitChain")
        i += 1
        # optional state sync restore
        if peek() == "offer_snapshot":
            i += 1
            while peek() == "apply_snapshot_chunk":
                i += 1
    else:
        if peek() == "init_chain":
            raise GrammarError(i, "init_chain", "recovery must not re-run InitChain")

    heights = 0
    while i < len(seq):
        # proposer phase
        while peek() in PROPOSER_CALLS:
            i += 1
        if peek() is None:
            break  # trace may end mid-height (crash) — allowed
        if peek() != "finalize_block":
            raise GrammarError(i, peek(), "expected FinalizeBlock after proposer calls")
        i += 1
        if peek() is None:
            break  # crashed between FinalizeBlock and Commit — allowed
        if peek() != "commit":
            raise GrammarError(i, peek(), "expected Commit after FinalizeBlock")
        i += 1
        heights += 1

    if clean_start and heights == 0 and i >= len(seq) and len(seq) <= 1:
        # an InitChain with no heights is fine (fresh node, short run)
        return


class RecordingApp:
    """Wraps an Application and records the consensus-connection call
    sequence (the e2e app's recording side, test/e2e/app/app.go)."""

    _CONSENSUS = (
        "init_chain",
        "prepare_proposal",
        "process_proposal",
        "extend_vote",
        "verify_vote_extension",
        "finalize_block",
        "commit",
        "offer_snapshot",
        "apply_snapshot_chunk",
    )

    def __init__(self, app):
        self._app = app
        self.calls: list[str] = []

    def __getattr__(self, name):
        fn = getattr(self._app, name)
        if name in self._CONSENSUS and callable(fn):
            def wrapper(*a, **k):
                self.calls.append(name)
                return fn(*a, **k)

            return wrapper
        return fn
