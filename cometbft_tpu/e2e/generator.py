"""Randomized testnet manifest generator (reference:
test/e2e/generator/generate.go, 520 LoC — explores the config space so
CI exercises combinations no hand-written manifest covers).

Deterministic per seed: generate(seed) always returns the same
manifest, so a failing CI run is reproducible by seed alone (the
reference CLI takes -seed the same way).
"""

from __future__ import annotations

import random

from .runner import Manifest, NodeSpec

# weighted choices mirroring generate.go's testnetCombinations shape
_TOPOLOGIES = [(2, 0.2), (3, 0.3), (4, 0.4), (5, 0.1)]
_PERTURBATIONS = ["kill", "pause", "restart", "disconnect", None, None, None]
# config-space axes (generate.go sweeps ABCI transports, DB backends,
# and validator key types the same way)
_ABCI = [("local", 0.6), ("socket", 0.25), ("grpc", 0.15)]
_DB = [("", 0.55), ("native", 0.15), ("sqlite", 0.15), ("memdb", 0.15)]
# per-net validator key type (generate.go keyType): secp256k1 nets run
# the sequential verify fallback end to end; bls is excluded here (pure-
# Python signing is too slow for a multi-process localnet on 1 core)
_KEY_TYPES = [("ed25519", 0.8), ("secp256k1", 0.2)]


def _weighted(rng: random.Random, pairs):
    r = rng.random()
    acc = 0.0
    for val, w in pairs:
        acc += w
        if r <= acc:
            return val
    return pairs[-1][0]


def generate(seed: int) -> Manifest:
    """One random manifest: 2-5 validators, up to one late-starting
    node, random perturbations, randomized load + target height."""
    rng = random.Random(seed)
    n = _weighted(rng, _TOPOLOGIES)
    nodes = []
    late_slot = rng.randrange(n) if n >= 3 and rng.random() < 0.5 else -1
    # half of late joiners bootstrap via statesync instead of blocksync
    # (generate.go's stateSync node axis)
    late_statesync = late_slot >= 0 and rng.random() < 0.5
    for i in range(n):
        perturbations = []
        p = rng.choice(_PERTURBATIONS)
        # never perturb the late node and at most half the net
        if p and i != late_slot and sum(bool(s.perturbations) for s in nodes) < n // 2:
            perturbations = [p]
        # WAN-link emulation on ~1/4 of nodes (the reference generator
        # assigns per-zone latencies for tc-netem the same way,
        # generator/generate.go latency handling)
        latency = 0.0
        jitter = 0.0
        if rng.random() < 0.25:
            latency = float(rng.choice([20, 50, 100]))
            jitter = latency / 3
        nodes.append(
            NodeSpec(
                name=f"node{i:02d}",
                start_at=rng.randint(3, 6) if i == late_slot else 0,
                state_sync=(i == late_slot and late_statesync),
                perturbations=perturbations,
                latency_ms=latency,
                latency_jitter_ms=jitter,
                abci=_weighted(rng, _ABCI),
                db_backend=_weighted(rng, _DB),
            )
        )
    return Manifest(
        chain_id=f"gen-{seed}",
        nodes=nodes,
        load_tx_per_round=rng.choice([0, 2, 5, 10]),
        target_height=rng.randint(8, 14),
        key_type=_weighted(rng, _KEY_TYPES),
    )


def generate_batch(group_seed: int, count: int) -> list[Manifest]:
    """A reproducible batch (generator CLI's -g groups)."""
    return [generate(group_seed * 1000 + i) for i in range(count)]
