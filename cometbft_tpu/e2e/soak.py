"""Sustained-load multi-tenant SLO soak over one shared verify plane.

The endurance proof of ROADMAP item 5: M in-process chains
(e2e/tenants.py) share ONE VerifyService for minutes-to-hours of mixed
load — per-tenant consensus commit verification plus signed-envelope
CheckTx traffic — while a rogue tenant floods the mempool class and
PR-8 faults fire mid-soak (device wedge → failover trip → probation →
restore; optionally a full chaos scenario — node crash + WAL replay —
running as a concurrent subprocess via scripts/chaos.py).  The run
emits one machine-readable SLO artifact whose assertions are the
multi-tenant contract:

  * **no starvation** — the rogue tenant's mempool flood degrades no
    other tenant's consensus verify p99 by more than a bounded factor
    (default 2x baseline), and every tenant's consensus batches keep
    dispatching throughout;
  * **quota isolation** — backpressure rejects land on the flooding
    tenant only (per-tenant reject tallies: rogue > 0, victims == 0);
  * **no leak** — RSS / thread-count / queue-depth watermarks stay flat
    across the run (utils/leaktest.ResourceWatermarks);
  * **no drift** — every verdict bitmap is bit-identical to its
    construction-time expectation, across every failover trip/restore
    cycle (degraded-mode host re-verification included);
  * **fault endurance** — every scheduled wedge cycle actually tripped
    the service to cpu_fallback AND restored via probation.

Phases (fractions of the configured duration): warmup (discarded) →
baseline (normal load) → flood (rogue mempool flood; wedge cycles fire
in both baseline and flood) → recovery (flood stops; queues must
drain).  Consensus latency samples are tagged with phase and wedge
windows so the starvation comparison only uses clean (un-wedged)
baseline vs clean flood samples.

**Remote-plane mode** (``remote_plane=True`` / ``scripts/soak.py
--remote-plane``): the tenants' shared service routes every batch to a
spawned **verifyd subprocess** (verifysvc/server.py) over the RPC
surface, so the whole soak crosses a real process boundary — quotas
are enforced SERVER-side (the client service's own quota is opened to
the class bound; rejections ride the wire back as backpressure with
tenant/scope intact), and the mid-soak fault becomes the real thing:
each cycle **kill -9s the verifyd** with batches in flight, waits for
the circuit breaker to trip (host fallback keeps every ticket
settling, bit-identical), restarts the plane at the same address, and
waits for probation to restore the remote path.  The SLO artifact then
additionally asserts the plane actually served traffic and that quota
isolation held in the PLANE's own tallies.  Pair with
``chaos_scenarios=("plane_crash",)`` for the real-node-process version
of the same fault running concurrently.

Driven by ``scripts/soak.py``; the fast two-tenant smoke configuration
runs in tier-1 (tests/test_soak.py), the real >=5-minute soak in the
slow tier and standalone.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import threading
import time
from dataclasses import asdict, dataclass, field

from ..crypto import ed25519 as host
from ..utils import fail, leaktest
from ..utils.healthmon import ProbeResult
from ..utils.log import get_logger
from ..verifysvc import checktx
from ..verifysvc.service import (
    MODE_CPU_FALLBACK,
    MODE_TPU,
    Klass,
    VerifyService,
    VerifyServiceBackpressure,
)
from .tenants import TenantChain, build_chains

_log = get_logger("e2e.soak")


@dataclass
class SoakConfig:
    """Knobs of one soak run.  The defaults are the fast smoke shape;
    scripts/soak.py overrides them for the real >=5-minute run."""

    tenants: int = 3
    validators_per_chain: int = 16
    duration_s: float = 60.0
    seed: int = 7
    rogue: str = ""  # "" = the last chain floods
    flood_senders: int = 2
    # flood batch width: a lower-class batch is the scheduler's
    # preemption granularity — one in-flight batch is the bounded
    # head-of-line delay a queued consensus batch can see, so the
    # starvation SLO's headroom scales inversely with this
    flood_batch_sigs: int = 8
    flood_burst: int = 24  # submits per sender burst before collecting
    commit_pause_s: float = 0.01
    checktx_period_s: float = 0.08
    wedge_cycles: int = 2
    wedge_hold_s: float = 2.0
    tenant_quota: int = 128
    queue_max: int = 1 << 20  # class bound way above quota: quota binds first
    tenant_weights: dict = field(default_factory=dict)
    batch_max: int = 16
    data_plane: str = "fake"  # "fake" (CPU-only, deterministic) | "real"
    collect_timeout_s: float = 30.0
    batch_deadline_s: float = 1.0
    probation_ok: int = 2
    probe_period_s: float = 0.2
    starvation_factor: float = 2.0
    starvation_floor_ms: float = 0.0  # extra slack for sub-second smokes
    leak_check: bool = True
    chaos_scenarios: tuple = ()  # e.g. ("crash_replay",): subprocess mid-soak
    chaos_base_port: int = 29400
    artifact_dir: str = ""
    json_path: str = ""
    # ---- out-of-process plane mode (module docstring, "Remote-plane")
    remote_plane: bool = False
    remote_budget_s: float = 3.0  # per-request wire budget
    remote_breaker_fails: int = 2
    remote_probe_period_s: float = 0.25
    verifyd_port: int = 29900  # 0 = ephemeral

    def phase_plan(self) -> dict[str, tuple[float, float]]:
        """Phase windows as (start, end) offsets from t0."""
        d = self.duration_s
        warm = min(2.0, 0.06 * d)
        base_end = warm + 0.35 * d
        flood_end = base_end + 0.45 * d
        return {
            "warmup": (0.0, warm),
            "baseline": (warm, base_end),
            "flood": (base_end, flood_end),
            "recovery": (flood_end, d),
        }


def _host_verdicts(items) -> tuple[bool, list[bool]]:
    res = [host.verify_signature(p, m, s) for (p, m, s) in items]
    return all(res) and bool(res), res


class _FakeDeviceBV:
    """The soak's deterministic CPU 'device': real host crypto, but
    shaped exactly like the production sub-threshold path — ``_entry =
    None`` routes submit() through the service's class-priority host
    worker (so the contention under test is the production contention),
    while the returned ticket is NON-sync, so the collector's device
    wait — where the wedge fault bites and the failover deadline runs —
    stays on the code path a real device exercises."""

    _entry = None
    _fallback = None

    def __init__(self):
        self._items = []

    def add(self, pub, msg, sig):
        self._items.append((pub, msg, sig))

    def submit(self):
        # the "device compute" runs here, on the host worker, governed
        # by the class-priority queue exactly like production host work
        return ("fakedev", _host_verdicts(self._items))

    def collect(self, ticket):
        return ticket[1]


def _percentile(vals: list[float], q: float):
    if not vals:
        return None
    s = sorted(vals)
    return s[min(len(s) - 1, int(q * len(s)))]


class SoakRun:
    """One soak execution; :func:`run_soak` is the entry point."""

    def __init__(self, cfg: SoakConfig):
        self.cfg = cfg
        self.chains: list[TenantChain] = build_chains(
            cfg.tenants, n_validators=cfg.validators_per_chain, seed=cfg.seed
        )
        self.rogue = cfg.rogue or self.chains[-1].name
        self._verifyd = None
        self.plane_addr: str | None = None
        self._plane_gen = 0  # verifyd incarnation counter (trace file names)
        if cfg.remote_plane:
            # the PLANE owns admission control: its env carries the real
            # quota/batch shape, while the client-side service's quota is
            # opened to the class bound so every rejection is genuinely
            # server-side and rides the wire back with tenant/scope
            self._verifyd_env = {
                "COMETBFT_TPU_VERIFYSVC_TENANT_QUOTA": str(cfg.tenant_quota),
                "COMETBFT_TPU_VERIFYSVC_QUEUE_MAX": str(cfg.queue_max),
                "COMETBFT_TPU_VERIFYSVC_BATCH_MAX": str(cfg.batch_max),
            }
            self._spawn_plane()
        client_quota = cfg.queue_max if cfg.remote_plane else cfg.tenant_quota
        self.svc = VerifyService(
            batch_max=cfg.batch_max,
            queue_max=cfg.queue_max,
            tenant_quota=client_quota,
            tenant_weights=dict(cfg.tenant_weights),
            deadlines_ms={
                Klass.CONSENSUS: 0, Klass.BLOCKSYNC: 2,
                Klass.MEMPOOL: 5, Klass.BACKGROUND: 25,
            },
            batch_deadline_s=cfg.batch_deadline_s,
            probation_ok=cfg.probation_ok,
            probe_period_s=cfg.probe_period_s,
            probe_fn=self._probe,
            failover_tick_s=0.05,
            artifact_dir=cfg.artifact_dir or None,
            remote_addr=self.plane_addr or "",
            remote_opts=(
                dict(
                    budget_s=cfg.remote_budget_s,
                    breaker_fails=cfg.remote_breaker_fails,
                    probe_period_s=cfg.remote_probe_period_s,
                    probation_ok=cfg.probation_ok,
                    backoff_s=0.05,
                )
                if cfg.remote_plane else None
            ),
        )
        if cfg.data_plane == "fake" and not cfg.remote_plane:
            real = VerifyService._make_verifier.__get__(self.svc)
            # fake device for TPU mode only: cpu_fallback must exercise
            # the PRODUCTION _HostBatchVerifier routing.  (Remote mode
            # never fakes: the data plane under test IS the wire +
            # verifyd host path + breaker fallback.)
            self.svc._make_verifier = (
                lambda mode: _FakeDeviceBV()
                if self.svc.backend_mode == MODE_TPU else real(mode)
            )
        self.t0 = 0.0
        self.stop_ev = threading.Event()
        self.flood_on = threading.Event()
        self._mtx = threading.Lock()
        # consensus latency samples: (t_offset, latency_s, tenant)
        self.cs_samples: dict[str, list[tuple[float, float]]] = {
            c.name: [] for c in self.chains
        }
        self.cs_timeouts: dict[str, int] = {c.name: 0 for c in self.chains}
        # per-tenant consensus backpressure observations: in remote mode
        # a victim seeing ANY is a server-side quota isolation failure
        self.cs_backpressure: dict[str, int] = {c.name: 0 for c in self.chains}
        self.checktx_stats: dict[str, dict[str, int]] = {
            c.name: {"attempts": 0, "mismatches": 0} for c in self.chains
        }
        self.flood_stats = {
            "submitted": 0, "rejected": 0, "timeouts": 0, "slow_collects": 0,
        }
        self.drift = {"checked": 0, "mismatches": 0}
        self.wedge_windows: list[dict] = []  # {armed, tripped, cleared, restored}
        self.chaos_results: list[dict] = []
        self._chaos_threads: list[threading.Thread] = []
        self.watermarks = leaktest.ResourceWatermarks(
            gauges={
                "inflight": lambda: len(self.svc._inflight),
                "queued_sigs": lambda: sum(
                    self.svc._class_sigs[k] for k in Klass
                ),
            }
        )
        self.errors: list[str] = []

    # --------------------------------------------------------- plumbing

    def _spawn_plane(self) -> None:
        from ..verifysvc import server as vserver

        from ..utils import tracing

        addr = self.plane_addr or f"127.0.0.1:{self.cfg.verifyd_port}"
        log = os.path.join(
            self.cfg.artifact_dir or os.getcwd(), "soak-verifyd.log"
        ) if self.cfg.artifact_dir else None
        env = dict(self._verifyd_env)
        if tracing.enabled() and self.cfg.artifact_dir:
            # each incarnation exports its own trace (mid-soak kill -9
            # cycles lose theirs — only clean exits flush); the run
            # epilogue merges whatever landed
            self._plane_gen += 1
            env["COMETBFT_TPU_TRACE"] = os.path.join(
                self.cfg.artifact_dir,
                f"soak-verifyd{self._plane_gen}.trace.json",
            )
        self._verifyd, self.plane_addr = vserver.spawn_verifyd(
            addr, extra_env=env, log_path=log,
        )
        _log.info(
            f"soak verifyd at {self.plane_addr} (pid {self._verifyd.pid})"
        )

    def _merge_traces(self) -> dict | None:
        """Tracing armed + an artifact dir: export this process's span
        ring and stitch it with whatever plane incarnations flushed into
        ONE ``merged.trace.json`` (utils/tracemerge).  None when tracing
        is off or there's nowhere to put it."""
        import glob

        from ..utils import tracemerge, tracing

        if not (tracing.enabled() and self.cfg.artifact_dir):
            return None
        own = os.path.join(self.cfg.artifact_dir, "soak.trace.json")
        try:
            tracing.export_chrome_trace(own)
        except Exception as e:  # noqa: BLE001 — tracing must never fail the soak
            _log.warning(f"soak trace export: {e!r}")
            return {"error": repr(e)}
        paths = [own] + sorted(glob.glob(
            os.path.join(self.cfg.artifact_dir, "soak-verifyd*.trace.json")
        ))
        out = os.path.join(self.cfg.artifact_dir, "merged.trace.json")
        try:
            rep = tracemerge.merge_files(paths, out)
        except tracemerge.MergeError as e:
            return {"error": str(e), "exports": paths}
        return {
            "merged": out,
            "processes": len(rep["processes"]),
            "events": rep["total_events"],
            "skipped": [s["label"] for s in rep.get("skipped", [])],
        }

    def _plane_stats(self) -> dict | None:
        from ..verifysvc import remote as vremote

        if self.plane_addr is None:
            return None
        return vremote.plane_status(self.plane_addr)

    @staticmethod
    def _probe(_timeout_s: float) -> ProbeResult:
        """Probation probe stub: healthy iff the wedge fault is not
        armed — deterministic, no subprocess, honest about the injected
        incident (healthmon.probe_devices behaves the same way when the
        fault is armed, minus the subprocess)."""
        wedged = fail.armed("wedge_device") is not None
        return ProbeResult(not wedged, "soak-probe", 0.0, timed_out=wedged)

    def _now(self) -> float:
        return time.monotonic() - self.t0

    def _record_drift(self, per, expected, where: str) -> None:
        with self._mtx:
            self.drift["checked"] += 1
            if list(per) != list(expected):
                self.drift["mismatches"] += 1
                if len(self.errors) < 32:
                    self.errors.append(
                        f"verdict drift at {where}: got {per} want {expected}"
                    )

    # ------------------------------------------------------- load loops

    def _consensus_loop(self, chain: TenantChain) -> None:
        i = 0
        while not self.stop_ev.is_set():
            tpl = chain.commit(i)
            i += 1
            t_submit = self._now()
            t0 = time.monotonic()
            try:
                ticket = self.svc.submit(
                    tpl.items, Klass.CONSENSUS, tenant=chain.name
                )
                _ok, per = ticket.collect(self.cfg.collect_timeout_s)
            except VerifyServiceBackpressure:
                # counted here AND by the (local or plane-side) tenant
                # tallies; the quota-isolation assertion fails the run
                # if a victim sees this
                with self._mtx:
                    self.cs_backpressure[chain.name] += 1
                continue
            except TimeoutError:
                with self._mtx:
                    self.cs_timeouts[chain.name] += 1
                continue
            lat = time.monotonic() - t0
            self._record_drift(
                per, tpl.expected, f"{chain.name}/consensus/{tpl.height}"
            )
            with self._mtx:
                self.cs_samples[chain.name].append((t_submit, lat))
            if self.cfg.commit_pause_s:
                self.stop_ev.wait(self.cfg.commit_pause_s)

    def _checktx_loop(self, chain: TenantChain) -> None:
        j = 0
        while not self.stop_ev.is_set():
            tx, expect_good = chain.tx(j)
            j += 1
            got = checktx.verify_tx_signature(
                tx, service=self.svc, tenant=chain.name
            )
            with self._mtx:
                st = self.checktx_stats[chain.name]
                st["attempts"] += 1
                if got is not bool(expect_good):
                    st["mismatches"] += 1
                    if len(self.errors) < 32:
                        self.errors.append(
                            f"checktx drift {chain.name}/{j}: "
                            f"got {got} want {expect_good}"
                        )
            self.stop_ev.wait(self.cfg.checktx_period_s)

    def _flood_loop(self, chain: TenantChain, idx: int) -> None:
        """Rogue mempool flood: bursts of wide batches, far faster than
        the plane drains, so the tenant quota MUST reject some — the
        backpressure that must stay confined to this tenant.  Pending
        tickets are swept with a SHORT wait and retried: under strict
        class priority an over-quota flooder's batches legitimately
        languish behind every tenant's consensus work while the plane
        is saturated (counted as ``slow_collects``, not lost — they
        resolve once the flood lifts, asserted by the final drain)."""
        items, expected = chain.flood_items(self.cfg.flood_batch_sigs)
        pending: list = []

        def sweep(wait_s: float) -> None:
            still = []
            for t in pending:
                try:
                    _ok, per = t.collect(wait_s)
                    self._record_drift(per, expected, f"{chain.name}/flood")
                except TimeoutError:
                    still.append(t)
                except VerifyServiceBackpressure as e:
                    # remote mode: the PLANE's quota rejected the batch
                    # after local admission — a settled (not lost)
                    # ticket, attributed to this tenant
                    self._count_flood_reject(chain, e)
            pending[:] = still

        while not self.stop_ev.is_set():
            if not self.flood_on.wait(0.1):
                if pending:
                    sweep(0.2)
                continue
            for _ in range(self.cfg.flood_burst):
                if self.stop_ev.is_set() or not self.flood_on.is_set():
                    break
                try:
                    pending.append(
                        self.svc.submit(items, Klass.MEMPOOL, tenant=chain.name)
                    )
                    with self._mtx:
                        self.flood_stats["submitted"] += 1
                except VerifyServiceBackpressure as e:
                    self._count_flood_reject(chain, e)
            before = len(pending)
            sweep(0.05)
            if pending and len(pending) == before:
                # nothing resolved this round: the flooder's accepted
                # backlog is languishing behind every tenant's consensus
                # work — strict class priority doing its job (the
                # backlog is bounded by the tenant quota, and the final
                # drain below proves nothing is ever lost)
                with self._mtx:
                    self.flood_stats["slow_collects"] += 1
        # final drain: every remaining flood ticket must resolve once
        # the flood has lifted — an unresolved one IS a lost ticket
        deadline = time.monotonic() + self.cfg.collect_timeout_s
        for t in pending:
            try:
                _ok, per = t.collect(max(0.1, deadline - time.monotonic()))
                self._record_drift(per, expected, f"{chain.name}/flood-drain")
            except TimeoutError:
                with self._mtx:
                    self.flood_stats["timeouts"] += 1
            except VerifyServiceBackpressure as e:
                self._count_flood_reject(chain, e)  # settled, not lost

    def _count_flood_reject(self, chain: TenantChain, e) -> None:
        with self._mtx:
            self.flood_stats["rejected"] += 1
        if e.tenant != chain.name and len(self.errors) < 32:
            with self._mtx:
                self.errors.append(
                    f"flood backpressure misattributed: {e.tenant!r}"
                )

    # ------------------------------------------------------ fault plane

    def _wedge_cycle(self, tag: str) -> dict:
        """One sentinel-style device-wedge incident: arm → the failover
        watchdog trips the service to cpu_fallback (in-flight batch past
        the device deadline; the probation probe honors the fault) →
        hold while degraded traffic keeps flowing → clear → probation
        restores TPU mode."""
        ev = {"tag": tag, "armed_at": self._now(), "tripped": False,
              "restored": False}
        fail.arm("wedge_device")
        deadline = time.monotonic() + max(20.0, 4 * self.cfg.batch_deadline_s)
        while time.monotonic() < deadline and not self.stop_ev.is_set():
            if self.svc.backend_mode == MODE_CPU_FALLBACK:
                ev["tripped"] = True
                ev["tripped_at"] = self._now()
                break
            time.sleep(0.02)
        self.stop_ev.wait(self.cfg.wedge_hold_s)
        fail.clear("wedge_device")
        ev["cleared_at"] = self._now()
        deadline = time.monotonic() + max(
            20.0, 10 * self.cfg.probe_period_s * self.cfg.probation_ok
        )
        while time.monotonic() < deadline and not self.stop_ev.is_set():
            if self.svc.backend_mode == MODE_TPU:
                ev["restored"] = True
                ev["restored_at"] = self._now()
                break
            time.sleep(0.02)
        with self._mtx:
            self.wedge_windows.append(ev)
        _log.info(f"soak wedge cycle {tag}: {ev}")
        return ev

    def _plane_crash_cycle(self, tag: str) -> dict:
        """Remote mode's fault cycle: kill -9 the verifyd with batches
        in flight → the client breaker must trip (host fallback keeps
        every ticket settling bit-identically) → hold degraded → restart
        the plane at the same address → probation must restore the
        remote path.  Recorded in wedge_windows so the starvation SLO's
        clean-window filter excludes the crash windows the same way."""
        ev = {"tag": tag, "kind": "plane_crash", "armed_at": self._now(),
              "tripped": False, "restored": False}
        # accumulate rejected-by-tenant tallies BEFORE the kill wipes
        # the plane's counters (quota isolation is asserted server-side)
        self._accumulate_plane_tallies()
        self._verifyd.kill()
        try:
            self._verifyd.wait(timeout=20)
        except Exception as e:  # noqa: BLE001 — a zombie is the OS's problem now
            _log.warning(f"soak verifyd wait after kill: {e!r}")
        deadline = time.monotonic() + max(20.0, 4 * self.cfg.remote_budget_s)
        while time.monotonic() < deadline and not self.stop_ev.is_set():
            st = self.svc.stats().get("remote") or {}
            if st.get("breaker") == "open":
                ev["tripped"] = True
                ev["tripped_at"] = self._now()
                break
            time.sleep(0.02)
        self.stop_ev.wait(self.cfg.wedge_hold_s)
        self._spawn_plane()
        ev["cleared_at"] = self._now()
        deadline = time.monotonic() + max(
            20.0, 20 * self.cfg.remote_probe_period_s * self.cfg.probation_ok
        )
        while time.monotonic() < deadline and not self.stop_ev.is_set():
            st = self.svc.stats().get("remote") or {}
            if st.get("breaker") == "closed":
                ev["restored"] = True
                ev["restored_at"] = self._now()
                break
            time.sleep(0.02)
        with self._mtx:
            self.wedge_windows.append(ev)
        _log.info(f"soak plane-crash cycle {tag}: {ev}")
        return ev

    def _accumulate_plane_tallies(self) -> None:
        """Fold the current plane's per-tenant reject/dispatch tallies
        into a run-wide accumulator — each kill -9 resets the plane's
        own counters, and quota isolation must be judged over the WHOLE
        run, not just the last incarnation."""
        st = self._plane_stats()
        if not st:
            return
        with self._mtx:
            acc = getattr(self, "_plane_tally_acc", None)
            if acc is None:
                acc = self._plane_tally_acc = {
                    "requests": 0, "rejected": 0, "deduped": 0,
                    "tenants": {},
                }
            srv = st.get("server", {})
            acc["requests"] += srv.get("requests", 0)
            acc["rejected"] += srv.get("rejected", 0)
            acc["deduped"] += srv.get("deduped", 0)
            for tenant, tallies in (
                st.get("service", {}).get("tenants", {}) or {}
            ).items():
                t = acc["tenants"].setdefault(
                    tenant, {"dispatched_batches": 0, "rejected": 0}
                )
                t["dispatched_batches"] += tallies.get("dispatched_batches", 0)
                t["rejected"] += tallies.get("rejected", 0)

    def _chaos_subprocess(self, scenario: str, slot: int = 0) -> None:
        """Run a full chaos scenario (real node processes — this is the
        node-crash + WAL-replay fault of the soak) concurrently with the
        in-process load, via the scripts/chaos.py driver."""
        repo = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)
        )))
        out = os.path.join(
            self.cfg.artifact_dir or os.getcwd(), f"soak-chaos-{scenario}"
        )
        os.makedirs(out, exist_ok=True)
        verdict_path = os.path.join(out, "verdict.json")
        # concurrent scenarios each get a disjoint port range (chaos.py
        # scenarios span < 200 ports)
        cmd = [
            sys.executable, os.path.join(repo, "scripts", "chaos.py"),
            "--scenario", scenario, "--seed", str(self.cfg.seed),
            "--json", verdict_path, "--out", out,
            "--base-port", str(self.cfg.chaos_base_port + slot * 200),
        ]
        try:
            proc = subprocess.run(
                cmd, capture_output=True, timeout=max(600, self.cfg.duration_s)
            )
            with open(verdict_path) as f:
                verdict = json.load(f)
            verdict["exit_code"] = proc.returncode
        except Exception as e:  # noqa: BLE001 — a dead chaos child is a finding, not a crash
            _log.warning(f"soak chaos subprocess {scenario} failed: {e!r}")
            verdict = {"ok": False, "error": repr(e), "scenario": scenario}
        with self._mtx:
            self.chaos_results.append(verdict)

    def _fault_schedule_loop(self) -> None:
        """Fire the wedge cycles at planned offsets: half in baseline,
        half mid-flood, so drift is checked across failover under both
        calm and contended load.  The chaos subprocess (real node
        processes — heavy CPU neighbors) is kicked at RECOVERY start
        instead: the run then extends, load still flowing, until it
        completes, so its host-level contention never pollutes the
        baseline-vs-flood starvation comparison."""
        plan = self.cfg.phase_plan()
        b0, b1 = plan["baseline"]
        f0, f1 = plan["flood"]
        r0 = plan["recovery"][0]
        cycles = max(0, self.cfg.wedge_cycles)
        times = []
        n_base = cycles // 2
        n_flood = cycles - n_base
        for i in range(n_base):
            times.append(b0 + (b1 - b0) * (i + 1) / (n_base + 1))
        for i in range(n_flood):
            times.append(f0 + (f1 - f0) * (i + 1) / (n_flood + 1))
        chaos_started = False
        cycle = (
            self._plane_crash_cycle if self.cfg.remote_plane
            else self._wedge_cycle
        )
        for i, at in enumerate(sorted(times)):
            while self._now() < at and not self.stop_ev.is_set():
                self.stop_ev.wait(0.1)
            if self.stop_ev.is_set():
                return
            cycle(f"cycle{i}")
        while not self.stop_ev.is_set():
            if not chaos_started and self._now() >= r0:
                chaos_started = self._start_chaos()
            self.stop_ev.wait(0.2)

    def _start_chaos(self) -> bool:
        for slot, scenario in enumerate(self.cfg.chaos_scenarios):
            t = threading.Thread(
                target=self._chaos_subprocess, args=(scenario, slot),
                name=f"soak-chaos-{scenario}", daemon=True,
            )
            t.start()
            self._chaos_threads.append(t)
        return True

    def _sampler_loop(self) -> None:
        period = max(0.5, self.cfg.duration_s / 120.0)
        while not self.stop_ev.is_set():
            self.watermarks.sample()
            self.stop_ev.wait(period)

    # ------------------------------------------------------------- run

    def run(self) -> dict:
        cfg = self.cfg
        plan = cfg.phase_plan()
        self.t0 = time.monotonic()
        started_unix = time.time()
        threads = [
            threading.Thread(
                target=self._consensus_loop, args=(c,),
                name=f"soak-cs-{c.name}", daemon=True,
            )
            for c in self.chains
        ] + [
            threading.Thread(
                target=self._checktx_loop, args=(c,),
                name=f"soak-tx-{c.name}", daemon=True,
            )
            for c in self.chains
        ]
        rogue_chain = next(c for c in self.chains if c.name == self.rogue)
        threads += [
            threading.Thread(
                target=self._flood_loop, args=(rogue_chain, i),
                name=f"soak-flood-{i}", daemon=True,
            )
            for i in range(cfg.flood_senders)
        ]
        threads.append(
            threading.Thread(
                target=self._fault_schedule_loop, name="soak-faults",
                daemon=True,
            )
        )
        threads.append(
            threading.Thread(
                target=self._sampler_loop, name="soak-sampler", daemon=True
            )
        )
        for t in threads:
            t.start()
        _log.info(
            f"soak started: {cfg.tenants} tenants x "
            f"{cfg.validators_per_chain} validators, {cfg.duration_s:.0f}s, "
            f"rogue={self.rogue}, plane={cfg.data_plane}"
        )
        try:
            f0, f1 = plan["flood"]
            while self._now() < cfg.duration_s:
                now = self._now()
                if f0 <= now < f1:
                    self.flood_on.set()
                else:
                    self.flood_on.clear()
                time.sleep(0.05)
            # extended window: the chaos subprocess (node crash + WAL
            # replay under real processes) may still be running — keep
            # the tenant load flowing until it completes so the fault
            # fires against a BUSY plane, without its host-level CPU
            # contention polluting the baseline/flood SLO windows above
            for t in self._chaos_threads:
                while t.is_alive():
                    t.join(timeout=2.0)
                    if self._now() > cfg.duration_s + 900:
                        _log.warning("chaos subprocess overran; stopping soak")
                        break
        finally:
            self.flood_on.clear()
            self.stop_ev.set()
            fail.clear_all()  # un-wedge parked workers before joining
            for t in threads:
                t.join(timeout=max(30.0, cfg.collect_timeout_s + 5))
        # drain: queues/in-flight must return to zero (part of no-leak)
        drained = False
        deadline = time.monotonic() + 30.0
        while time.monotonic() < deadline:
            with self.svc._cond:
                queued = sum(self.svc._class_sigs[k] for k in Klass)
            if queued == 0 and not self.svc._inflight:
                drained = True
                break
            time.sleep(0.1)
        self.watermarks.sample()
        # fold the final plane incarnation's tallies in BEFORE teardown
        # (the report reads the run-wide accumulator)
        if self.cfg.remote_plane:
            self._accumulate_plane_tallies()
        report = self._report(plan, started_unix, drained)
        self.svc.stop()
        if self._verifyd is not None:
            try:
                # SIGTERM first: the plane's clean exit flushes its
                # atexit trace export (mid-soak crash cycles SIGKILL and
                # forfeit theirs by design)
                self._verifyd.terminate()
                self._verifyd.wait(timeout=15)
            except Exception as e:  # noqa: BLE001 — teardown of a maybe-dead child
                _log.warning(f"soak verifyd teardown: {e!r}")
                try:
                    self._verifyd.kill()
                    self._verifyd.wait(timeout=10)
                except Exception as e2:  # noqa: BLE001 — already force-killing
                    _log.warning(f"soak verifyd force-kill: {e2!r}")
        trace = self._merge_traces()
        if trace is not None:
            report["trace"] = trace
        if cfg.json_path:
            os.makedirs(
                os.path.dirname(os.path.abspath(cfg.json_path)), exist_ok=True
            )
            with open(cfg.json_path, "w") as f:
                json.dump(report, f, indent=1, default=str)
            _log.info(f"soak SLO artifact written to {cfg.json_path}")
        return report

    # --------------------------------------------------------- verdict

    def _clean_window_samples(
        self, tenant: str, window: tuple[float, float]
    ) -> list[float]:
        """Latency samples submitted inside ``window`` but OUTSIDE any
        wedge incident (arm -> restore + margin): the starvation SLO
        compares flood vs baseline under the same (healthy) backend."""
        margin = 0.5
        spans = [
            (w["armed_at"] - margin,
             w.get("restored_at", w.get("cleared_at", w["armed_at"]))
             + margin)
            for w in self.wedge_windows
        ]
        lo, hi = window
        out = []
        for t, lat in self.cs_samples[tenant]:
            if not (lo <= t < hi):
                continue
            if any(a <= t <= b for a, b in spans):
                continue
            out.append(lat)
        return out

    def _report(self, plan, started_unix: float, drained: bool) -> dict:
        cfg = self.cfg
        svc_stats = self.svc.stats(lock_timeout=2.0)
        tenants_report = {}
        victims_ok = True
        starvation_detail = {}
        for c in self.chains:
            base = self._clean_window_samples(c.name, plan["baseline"])
            flood = self._clean_window_samples(c.name, plan["flood"])
            allsamp = [lat for _t, lat in self.cs_samples[c.name]]
            base_p99 = _percentile(base, 0.99)
            flood_p99 = _percentile(flood, 0.99)
            entry = {
                "consensus": {
                    "samples": len(allsamp),
                    "p50_ms": _r(_percentile(allsamp, 0.5)),
                    "p99_ms": _r(_percentile(allsamp, 0.99)),
                    "baseline_p99_ms": _r(base_p99),
                    "flood_p99_ms": _r(flood_p99),
                    "baseline_samples": len(base),
                    "flood_samples": len(flood),
                    "collect_timeouts": self.cs_timeouts[c.name],
                },
                "checktx": dict(self.checktx_stats[c.name]),
                "service_tallies": svc_stats.get("tenants", {}).get(
                    c.name, {}
                ),
                "rogue": c.name == self.rogue,
            }
            if c.name != self.rogue:
                if base_p99 is None or flood_p99 is None:
                    ok = False
                    why = "insufficient clean samples"
                else:
                    allowed = max(
                        cfg.starvation_factor * base_p99,
                        base_p99 + cfg.starvation_floor_ms / 1e3,
                    )
                    ok = flood_p99 <= allowed
                    why = (
                        f"flood p99 {flood_p99 * 1e3:.1f}ms vs allowed "
                        f"{allowed * 1e3:.1f}ms "
                        f"(baseline {base_p99 * 1e3:.1f}ms)"
                    )
                starvation_detail[c.name] = {"ok": ok, "detail": why}
                victims_ok = victims_ok and ok
            tenants_report[c.name] = entry

        # quota isolation from the admission controller's own per-tenant
        # tallies: the local service in-process, the PLANE (run-wide
        # accumulator across kill -9 incarnations) in remote mode —
        # plus, in remote mode, the client-side observation that no
        # victim consensus loop ever saw a backpressure
        if cfg.remote_plane:
            plane_acc = getattr(self, "_plane_tally_acc", None) or {
                "tenants": {}
            }
            tallies = plane_acc["tenants"]
        else:
            tallies = svc_stats.get("tenants", {})
        rogue_rejected = tallies.get(self.rogue, {}).get("rejected", 0)
        victim_rejected = {
            c.name: tallies.get(c.name, {}).get("rejected", 0)
            for c in self.chains if c.name != self.rogue
        }
        victim_bp = {
            c.name: self.cs_backpressure[c.name]
            for c in self.chains if c.name != self.rogue
        }
        quota_ok = (
            rogue_rejected > 0
            and not any(victim_rejected.values())
            and not any(victim_bp.values())
        )

        leak = (
            self.watermarks.flat() if cfg.leak_check
            else {"ok": True, "skipped": True}
        )
        leak["drained"] = drained
        leak_ok = bool(leak["ok"]) and drained

        drift_ok = (
            self.drift["mismatches"] == 0 and self.drift["checked"] > 0
            and not any(
                st["mismatches"] for st in self.checktx_stats.values()
            )
        )
        cycles = list(self.wedge_windows)
        faults_ok = (
            len(cycles) >= cfg.wedge_cycles
            and all(w["tripped"] and w["restored"] for w in cycles)
        )
        chaos_ok = all(r.get("ok") for r in self.chaos_results)
        lost = sum(self.cs_timeouts.values()) + self.flood_stats["timeouts"]
        if cfg.remote_plane:
            # the trip/restore tallies live in the remote breaker, and
            # the plane must genuinely have served wire traffic
            remote_stats = svc_stats.get("remote") or {}
            trips = remote_stats.get("trips", 0)
            restores = remote_stats.get("restores", 0)
            plane_acc = getattr(self, "_plane_tally_acc", None) or {}
            plane_served = plane_acc.get("requests", 0)
            faults_ok = faults_ok and plane_served > 0
        else:
            trips = svc_stats["failover"]["trips"]
            restores = svc_stats["failover"]["restores"]
            plane_acc = None

        assertions = {
            "no_starvation": {"ok": victims_ok, "per_tenant": starvation_detail},
            "quota_isolation": {
                "ok": quota_ok,
                "rogue_rejected": rogue_rejected,
                "victim_rejected": victim_rejected,
                "victim_backpressure": victim_bp,
                "enforced": "server-side" if cfg.remote_plane else "in-process",
                "flood": dict(self.flood_stats),
            },
            "no_leak": {"ok": leak_ok, **leak},
            "no_drift": {"ok": drift_ok, **self.drift},
            "fault_endurance": {
                "ok": faults_ok and chaos_ok,
                "wedge_cycles": cycles,
                "trips": trips,
                "restores": restores,
                "chaos": self.chaos_results,
            },
            "zero_lost_tickets": {"ok": lost == 0, "lost": lost},
        }
        ok = all(a["ok"] for a in assertions.values()) and not self.errors
        return {
            "ok": ok,
            "started_unix": started_unix,
            "duration_s": round(self._now(), 1),
            "config": asdict(cfg),
            "remote_plane": (
                {"addr": self.plane_addr, "tallies": plane_acc}
                if cfg.remote_plane else None
            ),
            "rogue": self.rogue,
            "phases": {k: [round(a, 1), round(b, 1)] for k, (a, b) in plan.items()},
            "tenants": tenants_report,
            "assertions": assertions,
            "errors": list(self.errors),
            "service": svc_stats,
            "watermark_samples": len(self.watermarks.samples),
        }


def _r(v, scale: float = 1e3, nd: int = 2):
    """Seconds -> rounded ms (None-safe)."""
    return None if v is None else round(v * scale, nd)


def run_soak(cfg: SoakConfig) -> dict:
    """Build and execute one soak; returns the SLO report dict (also
    written to cfg.json_path when set)."""
    return SoakRun(cfg).run()
