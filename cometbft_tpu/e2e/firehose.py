"""CheckTx firehose soak: production-shaped secp ingest at volume.

The acceptance proof of the Ethereum-rate ingest lane (PAPERS.md
arXiv:2112.02229): >=100k signed transactions — all three
secp wire shapes (Cosmos 33/64, Ethereum 65/65, ecrecover 20/65)
interleaved with repeat senders, exactly the shape a public mempool
sees — pushed through ONE verify service by concurrent sender threads,
with periodic adversarial STORM windows (tampered signatures, high-s
rewrites, wrong recover addresses, r >= n, truncated envelopes) mixed
into the stream.  One machine-readable SLO artifact, soak.py-shaped:

  * **slo_latency** — per-key-type CheckTx latency percentiles, p99
    bounded per key type (the Ethereum-shaped ingest claim, measured
    end-to-end through checktx.verify_tx_signature: parse -> schedule
    -> coalesce -> dispatch -> settle);
  * **zero_drift** — every verdict, storm rows included, bit-identical
    to its construction-time host-oracle expectation
    (models/secp_verifier._host_verify_one — the gauntlet the kernel
    is pinned against);
  * **cache_hit_rate** — repeat senders must actually hit the decoded-
    pubkey cache: the ``verify_svc_secp_pubkey_cache_total`` counter's
    hit share over the run must clear ``cache_hit_min`` (ecrecover
    rows never decode, so they are outside the denominator by
    construction);
  * **no_leak** — RSS / thread / queue-depth watermarks flat across
    the run (utils/leaktest.ResourceWatermarks) and the service
    drained to zero afterwards;
  * **completed** — every scheduled tx was processed (a silently
    dropped tx is a lost verdict).

Sender pools are PRE-SIGNED (signing is ~ms-per-tx of pure-Python
bigint work — signing inline would rate-limit the firehose below the
plane's capacity) and replayed round-robin, which is also what makes
the repeat-sender cache claim honest: the pool's sender count, not the
tx count, bounds the distinct-key working set.

Driven by ``scripts/firehose_soak.py`` (full >=100k run, knobs
COMETBFT_TPU_SECP_FIREHOSE_TXS / _SENDERS); tests/test_firehose.py
runs a host-path smoke in tier-1 and a reduced device-path soak in the
slow tier.
"""

from __future__ import annotations

import json
import os
import threading
import time
from dataclasses import asdict, dataclass

from ..crypto import secp256k1 as host_secp
from ..crypto import secp256k1eth as host_eth
from ..models import secp_verifier as sv
from ..utils import envknobs, leaktest
from ..utils.log import get_logger
from ..utils.metrics import hub as _mhub
from ..verifysvc import checktx
from ..verifysvc.service import Klass, VerifyService

_log = get_logger("e2e.firehose")

KEY_TYPES = ("secp256k1", "secp256k1eth", "ecrecover")


@dataclass
class FirehoseConfig:
    """Knobs of one firehose run.  Zeros defer to the env knobs
    (COMETBFT_TPU_SECP_FIREHOSE_TXS / _SENDERS) so the scripts/ driver
    and the acceptance run share one source of defaults; the test
    smoke overrides with small explicit values."""

    total_txs: int = 0  # 0 -> COMETBFT_TPU_SECP_FIREHOSE_TXS
    senders_per_type: int = 0  # 0 -> COMETBFT_TPU_SECP_FIREHOSE_SENDERS
    txs_per_sender: int = 8  # pre-signed pool depth per sender
    workers: int = 8
    storm_every: int = 5000  # a storm window every N scheduled txs
    storm_len: int = 128  # adversarial txs per window
    seed: int = 16
    batch_max: int = 16
    queue_max: int = 1 << 16
    slo_p99_ms: float = 500.0  # per key type
    cache_hit_min: float = 0.9
    cache_check: bool = True  # off for host-path smokes: the decode
    # cache (and its counter) only runs in the device assembly loop
    leak_check: bool = True
    json_path: str = ""


def _storm_pool(cfg: FirehoseConfig, rng) -> list[tuple[bytes, object]]:
    """Adversarial envelopes with construction-known verdicts: every
    invalid class the PR-15/16 corpora pin, as WIRE txs."""
    out: list[tuple[bytes, object]] = []
    ck = host_secp.PrivKey.from_seed(rng.bytes(32))
    ek = host_eth.PrivKey.from_seed(rng.bytes(32))
    rk = host_eth.RecoverPrivKey.from_seed(rng.bytes(32))
    n_ = host_secp.N

    # tampered signature byte (valid envelope, False verdict)
    tx = bytearray(checktx.make_signed_tx(ck, b"storm tamper"))
    tx[len(checktx.MAGIC_V2) + 1 + 33 + 5] ^= 1
    out.append((bytes(tx), False))
    # high-s + flipped v rewrite of a valid eth signature
    sig = ek.sign(b"storm highs")
    s_ = int.from_bytes(sig[32:64], "big")
    hs = sig[:32] + (n_ - s_).to_bytes(32, "big") + bytes([sig[64] ^ 1])
    ktb = bytes([checktx.KEY_TYPE_BYTES["secp256k1eth"]])
    out.append((
        checktx.MAGIC_V2 + ktb + ek.pub_key().data + hs + b"storm highs",
        False,
    ))
    # ecrecover with the wrong sender address
    tx = bytearray(checktx.make_signed_tx(rk, b"storm addr"))
    off = len(checktx.MAGIC_V2) + 1
    tx[off:off + 20] = b"\x42" * 20
    out.append((bytes(tx), False))
    # r >= n
    sig = ck.sign(b"storm range")
    bad = (n_ + 1).to_bytes(32, "big") + sig[32:64]
    ktb = bytes([checktx.KEY_TYPE_BYTES["secp256k1"]])
    out.append((
        checktx.MAGIC_V2 + ktb + ck.pub_key().data + bad + b"storm range",
        False,
    ))
    # truncated envelope: parses as UNSIGNED (None), never an error
    tx = checktx.make_signed_tx(ek, b"storm trunc")
    out.append((tx[: len(checktx.MAGIC_V2) + 1 + 10], None))
    # and one VALID tx per wire shape inside the storm — poison rows
    # must not bleed into neighbors sharing the coalesced batch
    for sk in (ck, ek, rk):
        out.append((checktx.make_signed_tx(sk, b"storm valid"), True))
    # cross-check every expectation against the host oracle
    for tx, want in out:
        parsed = checktx.parse_signed_tx(tx)
        if want is None:
            assert parsed is None, "truncated storm tx must parse unsigned"
        else:
            kt, pub, sig, payload = parsed
            got = sv._host_verify_one(
                pub, checktx.SIGN_DOMAIN + payload, sig
            )
            assert got is want, (kt, got, want)
    return out


def _percentile(vals: list[float], q: float):
    if not vals:
        return None
    s = sorted(vals)
    return s[min(len(s) - 1, int(q * len(s)))]


def run_firehose(cfg: FirehoseConfig) -> dict:
    """Execute one firehose; returns the SLO report dict (also written
    to cfg.json_path when set)."""
    import numpy as np

    total = cfg.total_txs or envknobs.get_int(envknobs.SECP_FIREHOSE_TXS)
    senders = cfg.senders_per_type or envknobs.get_int(
        envknobs.SECP_FIREHOSE_SENDERS
    )
    rng = np.random.default_rng(cfg.seed)

    # ---- pre-signed replay pools, one per wire shape
    mk = {
        "secp256k1": host_secp.PrivKey.from_seed,
        "secp256k1eth": host_eth.PrivKey.from_seed,
        "ecrecover": host_eth.RecoverPrivKey.from_seed,
    }
    pools: dict[str, list[bytes]] = {}
    t0 = time.monotonic()
    for kt in KEY_TYPES:
        keys = [mk[kt](rng.bytes(32)) for _ in range(senders)]
        pools[kt] = [
            checktx.make_signed_tx(sk, b"%s tx %d" % (kt.encode(), j))
            for j in range(cfg.txs_per_sender)
            for sk in keys
        ]
    storm = _storm_pool(cfg, rng)
    _log.info(
        f"firehose pools signed in {time.monotonic() - t0:.1f}s: "
        f"{senders} senders x {cfg.txs_per_sender} txs x "
        f"{len(KEY_TYPES)} key types (+{len(storm)} storm shapes); "
        f"run = {total} txs"
    )

    svc = VerifyService(batch_max=cfg.batch_max, queue_max=cfg.queue_max)
    watermarks = leaktest.ResourceWatermarks(
        gauges={
            "inflight": lambda: len(svc._inflight),
            "queued_sigs": lambda: sum(svc._class_sigs[k] for k in Klass),
        }
    )
    cache0 = {
        r: _mhub().secp_pubkey_cache.value(result=r) for r in ("hit", "miss")
    }

    lat: dict[str, list[float]] = {kt: [] for kt in KEY_TYPES}
    drift: list[str] = []
    storm_seen = [0]
    processed = [0]
    next_idx = [0]
    mtx = threading.Lock()
    stop_ev = threading.Event()

    def is_storm(i: int) -> bool:
        return cfg.storm_every > 0 and (
            i % cfg.storm_every >= cfg.storm_every - cfg.storm_len
        )

    def worker() -> None:
        while not stop_ev.is_set():
            with mtx:
                i = next_idx[0]
                if i >= total:
                    return
                next_idx[0] += 1
            if is_storm(i):
                tx, want = storm[i % len(storm)]
                got = checktx.verify_tx_signature(tx, service=svc)
                with mtx:
                    processed[0] += 1
                    storm_seen[0] += 1
                    if got is not want and len(drift) < 32:
                        drift.append(
                            f"storm tx {i}: got {got} want {want}"
                        )
                continue
            kt = KEY_TYPES[i % len(KEY_TYPES)]
            pool = pools[kt]
            tx = pool[(i // len(KEY_TYPES)) % len(pool)]
            t = time.perf_counter()
            got = checktx.verify_tx_signature(tx, service=svc)
            dt = (time.perf_counter() - t) * 1e3
            with mtx:
                processed[0] += 1
                lat[kt].append(dt)
                if got is not True and len(drift) < 32:
                    drift.append(f"{kt} tx {i}: got {got} want True")

    def sampler() -> None:
        while not stop_ev.is_set():
            watermarks.sample()
            stop_ev.wait(0.5)

    started_unix = time.time()
    t0 = time.monotonic()
    threads = [
        threading.Thread(target=worker, name=f"firehose-{i}", daemon=True)
        for i in range(cfg.workers)
    ]
    threads.append(
        threading.Thread(target=sampler, name="firehose-sampler", daemon=True)
    )
    for t in threads:
        t.start()
    for t in threads[:-1]:
        t.join()
    stop_ev.set()
    threads[-1].join(timeout=5)
    wall_s = time.monotonic() - t0

    # drain: the service must return to zero queued/in-flight
    drained = False
    deadline = time.monotonic() + 30.0
    while time.monotonic() < deadline:
        with svc._cond:
            queued = sum(svc._class_sigs[k] for k in Klass)
        if queued == 0 and not svc._inflight:
            drained = True
            break
        time.sleep(0.05)
    watermarks.sample()
    svc_stats = svc.stats(lock_timeout=2.0)
    svc.stop()

    cache1 = {
        r: _mhub().secp_pubkey_cache.value(result=r) for r in ("hit", "miss")
    }
    hits = cache1["hit"] - cache0["hit"]
    lookups = hits + cache1["miss"] - cache0["miss"]
    hit_rate = (hits / lookups) if lookups else None

    per_kt = {
        kt: {
            "count": len(v),
            "p50_ms": _percentile(v, 0.5),
            "p95_ms": _percentile(v, 0.95),
            "p99_ms": _percentile(v, 0.99),
        }
        for kt, v in lat.items()
    }
    slo_ok = all(
        st["count"] > 0 and st["p99_ms"] is not None
        and st["p99_ms"] <= cfg.slo_p99_ms
        for st in per_kt.values()
    )
    leak = (
        watermarks.flat() if cfg.leak_check else {"ok": True, "skipped": True}
    )
    leak["drained"] = drained
    if cfg.cache_check:
        cache_ok = hit_rate is not None and hit_rate >= cfg.cache_hit_min
    else:
        cache_ok = True

    assertions = {
        "slo_latency": {
            "ok": slo_ok, "p99_bound_ms": cfg.slo_p99_ms, "per_key_type": per_kt,
        },
        "zero_drift": {
            "ok": not drift, "storm_txs": storm_seen[0], "drift": drift,
        },
        "cache_hit_rate": {
            "ok": cache_ok,
            "hit_rate": None if hit_rate is None else round(hit_rate, 4),
            "lookups": lookups,
            "min": cfg.cache_hit_min,
            "checked": cfg.cache_check,
        },
        "no_leak": {"ok": bool(leak["ok"]) and drained, **leak},
        "completed": {
            "ok": processed[0] == total, "processed": processed[0],
            "scheduled": total,
        },
    }
    report = {
        "ok": all(a["ok"] for a in assertions.values()),
        "started_unix": started_unix,
        "wall_s": round(wall_s, 1),
        "txs_per_s": round(total / wall_s, 1) if wall_s else None,
        "config": {**asdict(cfg), "total_txs": total,
                   "senders_per_type": senders},
        "assertions": assertions,
        "service": {
            "dispatched_batches": svc_stats["dispatched_batches"],
            "rejected": svc_stats["rejected"],
        },
        "watermark_samples": len(watermarks.samples),
    }
    if cfg.json_path:
        os.makedirs(
            os.path.dirname(os.path.abspath(cfg.json_path)), exist_ok=True
        )
        with open(cfg.json_path, "w") as f:
            json.dump(report, f, indent=1, default=str)
        _log.info(f"firehose SLO artifact written to {cfg.json_path}")
    return report
