"""E2E harness: process-level testnets with perturbations + the ABCI
conformance grammar (reference: test/e2e/)."""

from .grammar import GrammarError, RecordingApp, check_execution
from .runner import E2ENode, Manifest, NodeSpec, Runner
from .scenarios import SCENARIOS, ScenarioResult, run_scenario

__all__ = [
    "Runner",
    "Manifest",
    "NodeSpec",
    "E2ENode",
    "RecordingApp",
    "check_execution",
    "GrammarError",
    "SCENARIOS",
    "ScenarioResult",
    "run_scenario",
]
