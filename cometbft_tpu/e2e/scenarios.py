"""Named chaos scenarios: multi-process localnets under load with
injected faults, each asserting LIVENESS (heights keep advancing) and
SAFETY (no conflicting commits) and leaving a diagnosable artifact trail
(per-node flight-recorder dumps, health snapshots, verify-service stats,
node logs) — so a failed run is debuggable from the artifact directory
alone, without a rerun.

The scenarios extend the e2e :class:`~cometbft_tpu.e2e.runner.Runner`
(real node processes, real sockets) with the PR-8 fault registry
(utils/fail.py, armed over RPC via ``COMETBFT_TPU_FAULT_RPC=1``):

========================== ==============================================
``wedge_smoke``            1 node, fast (tier-1): injected device wedge
                           mid-run trips the verify service to CPU
                           fallback, commits continue, clearing the
                           fault restores TPU mode via probation.
``wedge``                  3 nodes under load: same trip/restore cycle
                           on one node while the network keeps
                           committing and stays fork-free.
``crash_replay``           kill -9 a node mid-run; WAL + handshake
                           replay must recover it past the crash height.
``partition_heal``         sever one node's p2p sockets (SIGUSR1), heal,
                           assert it catches up with no fork.
``double_sign``            a byzantine node broadcasts one conflicting
                           prevote; honest nodes form
                           DuplicateVoteEvidence, commit it, and the
                           kvstore app docks the equivocator's power.
``valset_rotation_blocksync``  rotate a validator's power while a late
                           joiner is blocksyncing through the rotation
                           heights; the joiner must converge.
``plane_crash``            3 nodes consume ONE shared out-of-process
                           verify plane (verifyd); kill -9 it
                           mid-height with traffic flowing — every
                           node's breaker must trip to the in-process
                           host path (heights keep advancing), and
                           restarting the plane must probation-restore
                           the remote path on every node.
``trace_smoke``            1 node + verifyd with span tracing armed in
                           both processes: after clean shutdown the
                           per-process exports must merge into ONE
                           timeline in which a node-side span and the
                           plane's server span share a trace_id, and
                           /height_timeline must cover >= 5 heights.
========================== ==============================================

Driven by ``scripts/chaos.py`` (``--json`` emits a machine-readable
pass/fail artifact per scenario); the fast ``wedge_smoke`` also runs in
tier-1 (tests/test_chaos_scenarios.py), the multi-node scenarios in the
slow tier.
"""

from __future__ import annotations

import base64
import json
import os
import time
from dataclasses import dataclass, field

from ..utils.log import get_logger
from .runner import Manifest, NodeSpec, Runner

_log = get_logger("e2e.chaos")

# Env for a node that will have faults injected: fault RPC on, the
# health sentinel probing fast (so an armed wedge is judged `wedged`
# within seconds, not the production minute), and the verify-service
# failover plane on a tight leash.  Values are strings (subprocess env).
CHAOS_FAULT_ENV = {
    "COMETBFT_TPU_FAULT_RPC": "1",
    "COMETBFT_TPU_HEALTH": "1",
    "COMETBFT_TPU_HEALTH_PERIOD_MS": "2000",
    "COMETBFT_TPU_HEALTH_PROBE_TIMEOUT_MS": "8000",
    "COMETBFT_TPU_HEALTH_WEDGE_AFTER": "2",
    "COMETBFT_TPU_FAILOVER_BATCH_DEADLINE_MS": "4000",
    "COMETBFT_TPU_FAILOVER_PROBE_PERIOD_MS": "1000",
    "COMETBFT_TPU_FAILOVER_PROBE_TIMEOUT_MS": "8000",
    "COMETBFT_TPU_FAILOVER_PROBATION_OK": "2",
}


@dataclass
class ScenarioResult:
    """Machine-readable verdict for one scenario (the per-scenario
    artifact ``scripts/chaos.py --json`` emits)."""

    name: str
    ok: bool = False
    liveness: bool = False
    safety: bool = False
    # a scenario that RAISED (harness bug / environment breakage) is a
    # different verdict from one that ran and failed its assertions —
    # scripts/chaos.py exits 3 for crashes vs 1 for failures
    crashed: bool = False
    problems: list[str] = field(default_factory=list)
    details: dict = field(default_factory=dict)
    artifact_dir: str = ""
    elapsed_s: float = 0.0

    def to_dict(self) -> dict:
        return {
            "name": self.name,
            "ok": self.ok,
            "liveness": self.liveness,
            "safety": self.safety,
            "crashed": self.crashed,
            "problems": list(self.problems),
            "details": dict(self.details),
            "artifact_dir": self.artifact_dir,
            "elapsed_s": round(self.elapsed_s, 1),
        }


def _wait_for(pred, timeout: float, poll: float = 0.5, desc: str = ""):
    """Poll pred() until truthy; returns the value or None on timeout.
    pred exceptions are treated as not-yet (nodes restart mid-scenario)."""
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        try:
            v = pred()
        except Exception as e:  # noqa: BLE001 — mid-scenario RPC gaps are expected
            _log.debug(f"waiting for {desc or 'condition'}: {e!r}")
            v = None
        if v:
            return v
        time.sleep(poll)
    return None


# deterministic load-round numbering: run_scenario(seed=...) pins the
# starting round id so repeated runs (scripts/chaos.py --repeat --seed,
# the soak's mid-run injections) submit identical tx streams
_SEED: int | None = None


def _round_id_base() -> int:
    if _SEED is not None:
        return (_SEED * 1009) % 100000
    return int(time.monotonic() * 10) % 100000


def _drive_load_until(
    runner: Runner, pred, timeout: float, desc: str = "", extra=None
):
    """Like :func:`_wait_for` but keeps transaction load flowing — the
    scenarios assert liveness UNDER LOAD, not on an idle chain.
    ``extra`` (optional) runs once per round for scenario-specific
    traffic (signed CheckTx envelopes, valset txs)."""
    deadline = time.monotonic() + timeout
    round_id = _round_id_base()
    while time.monotonic() < deadline:
        try:
            v = pred()
        except Exception as e:  # noqa: BLE001 — mid-scenario RPC gaps are expected
            _log.debug(f"load-waiting for {desc or 'condition'}: {e!r}")
            v = None
        if v:
            return v
        runner.load(round_id)
        if extra is not None:
            try:
                extra(round_id)
            except Exception as e:  # noqa: BLE001 — extra load rides out node restarts
                _log.debug(f"extra load round {round_id}: {e!r}")
        round_id += 1
        runner.start_late_nodes()
        time.sleep(0.7)
    return None


def _signed_tx_sender(node, tag: str):
    """Per-round signed-envelope CheckTx traffic (verifysvc/checktx):
    exercises the verify service's MEMPOOL class on a live node — with
    the wedge armed, these must keep being admitted through the CPU
    fallback path."""
    from ..crypto import ed25519 as host
    from ..verifysvc import checktx

    keys = [host.PrivKey.from_seed(bytes([41 + i]) * 32) for i in range(3)]

    def send(round_id: int) -> None:
        for i, key in enumerate(keys):
            tx = checktx.make_signed_tx(
                key, f"{tag}-{round_id}-{i}".encode()
            )
            node.rpc("broadcast_tx_sync", tx=base64.b64encode(tx).decode())

    return send


def _min_height(runner: Runner) -> int:
    hs = runner._heights(only_running=True)
    return min(hs) if hs else 0


def _collect_artifacts(runner: Runner, out_dir: str) -> dict:
    """Pull every node's diagnosis surfaces into the artifact dir: the
    flight-recorder dump (where the failover/health/chaos events live),
    /tpu_health, /verify_svc_status, /faults — a failed scenario is
    diagnosed from these files plus the node logs already in each home."""
    os.makedirs(out_dir, exist_ok=True)
    index = {}
    for node in runner.nodes:
        if node.proc is None:
            index[node.name] = "not running"
            continue
        dumps = {}
        for route in ("tpu_health", "verify_svc_status",
                      "dump_consensus_trace", "height_timeline",
                      "faults", "status"):
            try:
                dumps[route] = node.rpc(route)
            except Exception as e:  # noqa: BLE001 — partial artifacts beat none
                dumps[route] = {"error": repr(e)}
        path = os.path.join(out_dir, f"{node.name}.json")
        with open(path, "w") as f:
            json.dump(dumps, f, indent=1, default=str)
        index[node.name] = path
    return index


def _finish(
    res: ScenarioResult, runner: Runner, t0: float, upto: int
) -> ScenarioResult:
    """Shared epilogue: safety invariants + watchdog parity + artifacts."""
    problems = runner.check_invariants(upto=upto)
    if any("divergence" in p for p in problems):
        # the latest-app-hash check polls heights and hashes in separate
        # RPC rounds, so a node committing between them reads as a
        # same-height divergence; a REAL divergence persists (it forks
        # the next header), a race clears on a re-check
        time.sleep(1.5)
        problems = runner.check_invariants(upto=upto)
    res.safety = not [p for p in problems if "fork" in p or "divergence" in p]
    res.problems.extend(problems)
    fires = runner.check_watchdog_fires()
    if fires:
        res.problems.extend(fires)
    res.details["heights"] = runner._heights(only_running=True)
    res.details["artifacts"] = _collect_artifacts(runner, res.artifact_dir)
    res.ok = res.liveness and res.safety and not res.problems
    res.elapsed_s = time.monotonic() - t0
    return res


def _trace_armed() -> bool:
    """Is COMETBFT_TPU_TRACE truthy in the harness env?  When it is,
    every spawned node/verifyd exports its own trace file (see
    e2e/runner E2ENode.start) and the scenario epilogue merges them."""
    from ..utils import envknobs, tracing

    return envknobs.get_str(envknobs.TRACE).lower() not in tracing._OFF_VALUES


def _merge_scenario_traces(res: ScenarioResult) -> None:
    """After the nodes have exited (their atexit exports flushed),
    stitch every per-process trace export under the artifact dir into
    ONE Perfetto timeline — <artifact_dir>/merged.trace.json."""
    import glob

    from ..utils import tracemerge

    if "merged_trace" in res.details:
        return  # the scenario already merged (trace_smoke asserts on it)
    paths = sorted(
        glob.glob(os.path.join(res.artifact_dir, "net", "*", "trace.json"))
        + glob.glob(os.path.join(res.artifact_dir, "*.trace.json"))
    )
    out = os.path.join(res.artifact_dir, "merged.trace.json")
    paths = [p for p in paths if os.path.abspath(p) != os.path.abspath(out)]
    if not paths:
        return
    try:
        report = tracemerge.merge_files(paths, out)
    except tracemerge.MergeError as e:
        res.details["trace_merge_error"] = str(e)
        return
    res.details["merged_trace"] = out
    res.details["trace_processes"] = len(report["processes"])
    _log.info(
        f"merged {report['total_events']} trace events from "
        f"{len(report['processes'])} process(es) -> {out}"
    )


def _failover_events(node) -> list[dict]:
    entries = node.rpc("dump_consensus_trace").get("entries", [])
    return [e for e in entries if e.get("kind") == "verifysvc_failover"]


# ------------------------------------------------------------- scenarios


def scenario_wedge_smoke(out_dir: str, base_port: int = 26000) -> ScenarioResult:
    """Single-node wedge/trip/probation round trip — the fast (tier-1)
    smoke of the whole failover plane against a REAL node process."""
    res = ScenarioResult("wedge_smoke", artifact_dir=os.path.join(out_dir, "wedge_smoke"))
    t0 = time.monotonic()
    m = Manifest(
        chain_id="chaos-wedge-smoke",
        nodes=[NodeSpec("solo", env=dict(CHAOS_FAULT_ENV))],
        target_height=2,
        load_tx_per_round=1,
    )
    r = Runner(m, os.path.join(out_dir, "wedge_smoke", "net"), base_port=base_port)
    r.setup()
    r.start()
    node = r.nodes[0]
    signed_load = _signed_tx_sender(node, "smoke")
    try:
        if not _drive_load_until(
            r, lambda: _min_height(r) >= 2, 90, "baseline height",
            extra=signed_load,
        ):
            res.problems.append("node never reached height 2 (pre-fault)")
            return _finish(res, r, t0, upto=2)

        node.arm_fault("wedge_device")
        trip = _drive_load_until(
            r, lambda: node.verify_svc()["backend_mode"] == "cpu_fallback",
            45, desc="failover trip", extra=signed_load,
        )
        if not trip:
            res.problems.append("verify service never tripped to cpu_fallback")
            return _finish(res, r, t0, upto=2)
        res.details["tripped"] = True

        # liveness IN degraded mode: the wedged node keeps committing
        # under mixed load (plain txs + signed CheckTx envelopes)
        h0 = _min_height(r)
        if not _drive_load_until(
            r, lambda: _min_height(r) >= h0 + 2, 90, "degraded-mode commits",
            extra=signed_load,
        ):
            res.problems.append(
                f"no commits while wedged (stuck at {_min_height(r)})"
            )
            return _finish(res, r, t0, upto=h0)
        res.liveness = True

        st = node.verify_svc()
        fo = st.get("failover", {})
        res.details["trip_reason"] = fo.get("last_trip_reason")
        res.details["forensics_artifact"] = fo.get("last_artifact")
        res.details["trips"] = fo.get("trips")
        if not fo.get("last_artifact"):
            res.problems.append("trip emitted no forensics artifact")
        events = _failover_events(node)
        res.details["failover_events"] = events
        if len([e for e in events
                if e.get("detail", {}).get("direction") == "to_cpu"]) != 1:
            res.problems.append(
                f"expected exactly one to_cpu flightrec event, got {events}"
            )

        # heal: clearing the fault must restore TPU mode via probation
        node.clear_fault("wedge_device")
        restored = _wait_for(
            lambda: node.verify_svc()["backend_mode"] == "tpu",
            60, desc="probation restore",
        )
        if not restored:
            res.problems.append("probation never restored TPU mode")
        res.details["restored"] = bool(restored)
        h1 = _min_height(r)
        if not _drive_load_until(
            r, lambda: _min_height(r) >= h1 + 1, 60, "post-restore commit"
        ):
            res.problems.append("no commits after restore")
            res.liveness = False
        return _finish(res, r, t0, upto=max(2, h1))
    finally:
        r.stop_all()


def scenario_wedge(out_dir: str, base_port: int = 26200) -> ScenarioResult:
    """3-node net under load; one node's device wedges mid-run.  The
    network must keep committing (the wedged node trips to CPU fallback
    and keeps its validator seat live), stay fork-free, and the wedged
    node must restore TPU mode after the heal."""
    res = ScenarioResult("wedge", artifact_dir=os.path.join(out_dir, "wedge"))
    t0 = time.monotonic()
    m = Manifest(
        chain_id="chaos-wedge",
        nodes=[
            NodeSpec("wedged", env=dict(CHAOS_FAULT_ENV)),
            NodeSpec("b"),
            NodeSpec("c"),
        ],
        target_height=8,
        load_tx_per_round=2,
    )
    r = Runner(m, os.path.join(out_dir, "wedge", "net"), base_port=base_port)
    r.setup()
    r.start()
    node = r.nodes[0]
    try:
        if not _drive_load_until(r, lambda: _min_height(r) >= 3, 180, "baseline"):
            res.problems.append("net never reached height 3 (pre-fault)")
            return _finish(res, r, t0, upto=3)

        node.arm_fault("wedge_device")
        if not _wait_for(
            lambda: node.verify_svc()["backend_mode"] == "cpu_fallback",
            60, desc="failover trip",
        ):
            res.problems.append("wedged node never tripped to cpu_fallback")
            return _finish(res, r, t0, upto=3)
        h0 = _min_height(r)
        if not _drive_load_until(
            r, lambda: _min_height(r) >= h0 + 3, 180, "degraded commits"
        ):
            res.problems.append(f"net stalled while wedged ({_min_height(r)})")
            return _finish(res, r, t0, upto=h0)
        res.liveness = True
        fo = node.verify_svc().get("failover", {})
        res.details["trip_reason"] = fo.get("last_trip_reason")
        res.details["forensics_artifact"] = fo.get("last_artifact")
        node.clear_fault("wedge_device")
        restored = _wait_for(
            lambda: node.verify_svc()["backend_mode"] == "tpu",
            90, desc="probation restore",
        )
        if not restored:
            res.problems.append("probation never restored TPU mode")
        res.details["restored"] = bool(restored)
        _drive_load_until(
            r, lambda: _min_height(r) >= m.target_height, 120, "target height"
        )
        return _finish(res, r, t0, upto=max(3, _min_height(r)))
    finally:
        r.stop_all()


def scenario_crash_replay(out_dir: str, base_port: int = 26400) -> ScenarioResult:
    """kill -9 one node mid-run, restart it, and require WAL + handshake
    replay to bring it back past the crash height (validated once in
    PR 3; now a standing scenario)."""
    res = ScenarioResult(
        "crash_replay", artifact_dir=os.path.join(out_dir, "crash_replay")
    )
    t0 = time.monotonic()
    m = Manifest(
        chain_id="chaos-crash",
        nodes=[
            NodeSpec("a"),
            NodeSpec("victim", perturbations=["kill"]),
            NodeSpec("c"),
        ],
        target_height=7,
        load_tx_per_round=2,
    )
    r = Runner(m, os.path.join(out_dir, "crash_replay", "net"), base_port=base_port)
    r.setup()
    r.start()
    try:
        if not _drive_load_until(r, lambda: _min_height(r) >= 3, 180, "baseline"):
            res.problems.append("net never reached height 3 (pre-crash)")
            return _finish(res, r, t0, upto=3)
        crash_h = _min_height(r)
        r.perturb()  # kill -9 + restart + wait_ready
        res.details["crash_height"] = crash_h
        if not _drive_load_until(
            r,
            lambda: _min_height(r) >= crash_h + 3
            and len(r._heights(only_running=True)) == 3,
            240, "post-crash convergence",
        ):
            res.problems.append(
                f"victim never recovered past crash height {crash_h} "
                f"({r._heights(only_running=True)})"
            )
            return _finish(res, r, t0, upto=crash_h)
        res.liveness = True
        return _finish(res, r, t0, upto=crash_h + 2)
    finally:
        r.stop_all()


def scenario_partition_heal(out_dir: str, base_port: int = 26600) -> ScenarioResult:
    """Sever one node's p2p sockets (SIGUSR1 toggle), heal after a few
    seconds, assert it catches back up and nobody forked."""
    res = ScenarioResult(
        "partition_heal", artifact_dir=os.path.join(out_dir, "partition_heal")
    )
    t0 = time.monotonic()
    # FOUR validators: severing one leaves 3/4 = 75% > 2/3, so the
    # majority side keeps committing through the partition (a 3-node
    # net would sit at exactly 2/3 and legitimately halt — quorum needs
    # strictly more)
    m = Manifest(
        chain_id="chaos-partition",
        nodes=[
            NodeSpec("a"),
            NodeSpec("b"),
            NodeSpec("c"),
            NodeSpec("isolated", perturbations=["disconnect"]),
        ],
        target_height=7,
        load_tx_per_round=2,
    )
    r = Runner(
        m, os.path.join(out_dir, "partition_heal", "net"), base_port=base_port
    )
    r.setup()
    r.start()
    try:
        if not _drive_load_until(r, lambda: _min_height(r) >= 3, 180, "baseline"):
            res.problems.append("net never reached height 3 (pre-partition)")
            return _finish(res, r, t0, upto=3)
        h0 = _min_height(r)
        r.perturb()  # partition + heal (blocks ~4s inside)
        if not _drive_load_until(
            r, lambda: _min_height(r) >= h0 + 3, 240, "post-heal convergence"
        ):
            res.problems.append(
                f"isolated node never caught up ({r._heights(only_running=True)})"
            )
            return _finish(res, r, t0, upto=h0)
        res.liveness = True
        return _finish(res, r, t0, upto=h0 + 2)
    finally:
        r.stop_all()


def scenario_double_sign(out_dir: str, base_port: int = 26800) -> ScenarioResult:
    """One byzantine equivocation: a 4-validator net where one node
    broadcasts a conflicting prevote.  Honest nodes must capture the
    conflict as DuplicateVoteEvidence, commit it in a block, and the
    kvstore app docks the equivocator's power (kvstore.go:316-334
    parity) — asserted via /validators, which every node must agree on."""
    res = ScenarioResult(
        "double_sign", artifact_dir=os.path.join(out_dir, "double_sign")
    )
    t0 = time.monotonic()
    m = Manifest(
        chain_id="chaos-equivocation",
        nodes=[
            NodeSpec("a"),
            NodeSpec("b"),
            NodeSpec("c"),
            NodeSpec("byz", env={"COMETBFT_TPU_FAULT_RPC": "1"}),
        ],
        target_height=8,
        load_tx_per_round=2,
    )
    r = Runner(m, os.path.join(out_dir, "double_sign", "net"), base_port=base_port)
    r.setup()
    r.start()
    byz = r.nodes[3]
    try:
        if not _drive_load_until(r, lambda: _min_height(r) >= 2, 180, "baseline"):
            res.problems.append("net never reached height 2 (pre-fault)")
            return _finish(res, r, t0, upto=2)

        # the byzantine validator's address, to watch its power
        byz_val = byz.rpc("status")["validator_info"]
        byz.arm_fault("double_sign", 1)
        res.details["byz_address"] = byz_val["address"]

        def _docked():
            # evidence committed -> FinalizeBlock misbehavior -> kvstore
            # docks one power; visible in the ACTIVE validator set.
            # Returns the height the punished set is live at (truthy).
            h = r.nodes[0].height()
            vals = r.nodes[0].rpc("validators", height=h)["validators"]
            for v in vals:
                if v["address"] == byz_val["address"]:
                    if int(v["voting_power"]) < int(byz_val["voting_power"]):
                        return h
            return 0

        h_docked = _drive_load_until(r, _docked, 240, "evidence committed")
        if not h_docked:
            res.problems.append(
                "equivocator's power was never docked (evidence not "
                "formed/committed?)"
            )
            return _finish(res, r, t0, upto=_min_height(r))
        res.details["power_docked_at"] = h_docked
        res.liveness = True

        # all honest nodes agree on the punished set — compared AT ONE
        # height (validator sets are height-indexed; latest-height
        # queries race block application across nodes)
        if not _drive_load_until(
            r, lambda: _min_height(r) >= h_docked, 120, "height convergence"
        ):
            res.problems.append(
                f"nodes never converged to height {h_docked}"
            )
            return _finish(res, r, t0, upto=_min_height(r))
        powers = set()
        for node in r.nodes[:3]:
            vals = node.rpc("validators", height=h_docked)["validators"]
            powers.add(
                tuple(sorted((v["address"], v["voting_power"]) for v in vals))
            )
        if len(powers) != 1:
            res.problems.append(
                f"validator sets diverge at height {h_docked}: {powers}"
            )
        return _finish(res, r, t0, upto=_min_height(r))
    finally:
        r.stop_all()


def scenario_valset_rotation_blocksync(
    out_dir: str, base_port: int = 27000
) -> ScenarioResult:
    """Rotate a validator's power (kvstore `val=` txs) while a late
    joiner is blocksyncing through exactly those heights: the joiner
    must track the validator-set changes block by block and converge."""
    res = ScenarioResult(
        "valset_rotation_blocksync",
        artifact_dir=os.path.join(out_dir, "valset_rotation_blocksync"),
    )
    t0 = time.monotonic()
    m = Manifest(
        chain_id="chaos-valset",
        nodes=[
            NodeSpec("a"),
            NodeSpec("b"),
            NodeSpec("c"),
            NodeSpec("joiner", start_at=4),
        ],
        target_height=10,
        load_tx_per_round=2,
    )
    r = Runner(
        m,
        os.path.join(out_dir, "valset_rotation_blocksync", "net"),
        base_port=base_port,
    )
    r.setup()
    r.start()
    try:
        # the rotated validator: node c's key, read from the shared
        # genesis (which stores pubkeys HEX-encoded; the kvstore val tx
        # wants base64 — a raw copy is valid base64 of the WRONG bytes,
        # the poison pill parse_validator_tx now rejects)
        with open(os.path.join(r.out, "node0", "config", "genesis.json")) as f:
            genesis = json.load(f)
        target_val = genesis["validators"][2]
        pub_b64 = base64.b64encode(
            bytes.fromhex(target_val["pub_key"]["value"])
        ).decode()
        res.details["rotated_pubkey"] = pub_b64

        def _val_tx(power: int) -> str:
            tx = f"val=ed25519!{pub_b64}!{power}".encode()
            return base64.b64encode(tx).decode()

        if not _drive_load_until(r, lambda: _min_height(r) >= 2, 180, "baseline"):
            res.problems.append("net never reached height 2")
            return _finish(res, r, t0, upto=2)

        # first rotation BEFORE the joiner starts (so it blocksyncs
        # through a valset change), second while it is syncing
        r.nodes[0].rpc("broadcast_tx_sync", tx=_val_tx(7))
        if not _drive_load_until(
            r, lambda: _min_height(r) >= 5, 180, "joiner start window"
        ):
            res.problems.append("net never reached height 5")
            return _finish(res, r, t0, upto=2)
        r.nodes[0].rpc("broadcast_tx_sync", tx=_val_tx(12))

        def _converged():
            hs = r._heights(only_running=True)
            return (
                len(hs) == 4
                and min(hs) >= m.target_height
                and all(n.proc is not None for n in r.nodes)
            )

        if not _drive_load_until(r, _converged, 300, "joiner convergence"):
            res.problems.append(
                "joiner never converged through the rotation "
                f"({r._heights(only_running=True)})"
            )
            return _finish(res, r, t0, upto=_min_height(r))
        res.liveness = True

        # every node (joiner included) agrees the second rotation landed.
        # Validator updates take effect at commit height + 2, which can
        # postdate the convergence check — keep the chain moving until
        # the rotated power is live everywhere.
        def _rotated_power(node):
            for v in node.rpc("validators")["validators"]:
                if v["pub_key"]["value"] == pub_b64:
                    return v["voting_power"]
            return None

        def _rotation_live():
            return all(_rotated_power(n) == "12" for n in r.nodes)

        if not _drive_load_until(r, _rotation_live, 120, "rotation visible"):
            final = sorted({str(_rotated_power(n)) for n in r.nodes})
            res.problems.append(f"rotation not applied everywhere: {final}")
            res.details["final_rotated_power"] = final
        else:
            res.details["final_rotated_power"] = "12"
        return _finish(res, r, t0, upto=m.target_height)
    finally:
        r.stop_all()


def scenario_plane_crash(out_dir: str, base_port: int = 27200) -> ScenarioResult:
    """Shared out-of-process verify plane, killed and revived: 3 nodes
    all point COMETBFT_TPU_VERIFYRPC_ADDR at ONE verifyd; the harness
    kill -9s it mid-height under load.  Liveness must resume via every
    node's circuit breaker (remote -> in-process host fallback, heights
    keep advancing), and restarting the plane must probation-restore
    the remote path — asserted from each node's /verify_svc_status
    `remote` section plus the plane's own served-request tallies."""
    from ..verifysvc import remote as vremote
    from ..verifysvc import server as vserver

    res = ScenarioResult(
        "plane_crash", artifact_dir=os.path.join(out_dir, "plane_crash")
    )
    t0 = time.monotonic()
    plane_addr = f"127.0.0.1:{base_port + 900}"
    os.makedirs(res.artifact_dir, exist_ok=True)
    plane_log = os.path.join(res.artifact_dir, "verifyd.log")
    plane_env = {}
    if _trace_armed():
        plane_env["COMETBFT_TPU_TRACE"] = os.path.join(
            res.artifact_dir, "verifyd.trace.json"
        )
    plane, plane_addr = vserver.spawn_verifyd(
        plane_addr, extra_env=plane_env, log_path=plane_log
    )
    res.details["plane_addr"] = plane_addr
    # a tight breaker leash so the scenario's windows stay short: small
    # request budget, a couple of connection failures to trip, fast
    # probation probing back
    remote_env = {
        "COMETBFT_TPU_VERIFYRPC_ADDR": plane_addr,
        "COMETBFT_TPU_VERIFYRPC_BUDGET_MS": "4000",
        "COMETBFT_TPU_VERIFYRPC_BREAKER_FAILS": "2",
        "COMETBFT_TPU_VERIFYRPC_PROBE_PERIOD_MS": "500",
        "COMETBFT_TPU_VERIFYRPC_PROBATION_OK": "2",
    }
    m = Manifest(
        chain_id="chaos-plane-crash",
        nodes=[
            NodeSpec("a", env=dict(remote_env)),
            NodeSpec("b", env=dict(remote_env)),
            NodeSpec("c", env=dict(remote_env)),
        ],
        target_height=8,
        load_tx_per_round=2,
    )
    r = Runner(m, os.path.join(out_dir, "plane_crash", "net"), base_port=base_port)
    r.setup()
    r.start()
    signed_load = _signed_tx_sender(r.nodes[0], "plane")

    def _breakers() -> list[str]:
        out = []
        for n in r.nodes:
            try:
                out.append(
                    (n.verify_svc().get("remote") or {}).get("breaker", "?")
                )
            except Exception as e:  # noqa: BLE001 — mid-scenario RPC gaps
                _log.debug(f"breaker probe of {n.name}: {e!r}")
                out.append("?")
        return out

    try:
        if not _drive_load_until(
            r, lambda: _min_height(r) >= 3, 180, "baseline height",
            extra=signed_load,
        ):
            res.problems.append("net never reached height 3 (plane alive)")
            return _finish(res, r, t0, upto=3)
        st = vremote.plane_status(plane_addr)
        served = (st or {}).get("server", {}).get("requests", 0)
        res.details["plane_requests_before_crash"] = served
        if not served:
            res.problems.append(
                "plane served zero requests pre-crash: nodes never "
                "actually consumed the remote plane"
            )
            return _finish(res, r, t0, upto=3)

        # ---- kill -9 the plane mid-height, load still flowing
        crash_h = _min_height(r)
        plane.kill()
        plane.wait(timeout=20)
        res.details["crash_height"] = crash_h

        # liveness THROUGH the outage: the breakers trip and commits
        # continue on the in-process host path
        if not _drive_load_until(
            r, lambda: _min_height(r) >= crash_h + 2, 180,
            "commits with the plane dead", extra=signed_load,
        ):
            res.problems.append(
                f"net stalled after plane kill (stuck at {_min_height(r)}, "
                f"breakers {_breakers()})"
            )
            return _finish(res, r, t0, upto=crash_h)
        res.liveness = True
        tripped = _wait_for(
            lambda: all(b == "open" for b in _breakers()), 30,
            desc="all breakers open",
        )
        res.details["breakers_after_crash"] = _breakers()
        if not tripped:
            res.problems.append(
                f"not every node tripped its breaker: {_breakers()}"
            )

        # ---- revive the plane at the same address; probation restores
        if plane_env:
            # the revived plane gets its own export — re-using the first
            # incarnation's path would overwrite its (crashed) trace
            plane_env["COMETBFT_TPU_TRACE"] = os.path.join(
                res.artifact_dir, "verifyd2.trace.json"
            )
        plane, _ = vserver.spawn_verifyd(
            plane_addr, extra_env=plane_env, log_path=plane_log
        )
        restored = _drive_load_until(
            r, lambda: all(b == "closed" for b in _breakers()), 120,
            "breakers closed after restart", extra=signed_load,
        )
        res.details["breakers_after_restart"] = _breakers()
        if not restored:
            res.problems.append(
                f"breakers never restored after plane restart: {_breakers()}"
            )
            return _finish(res, r, t0, upto=crash_h + 2)
        h1 = _min_height(r)
        if not _drive_load_until(
            r,
            lambda: _min_height(r) >= h1 + 2
            and (vremote.plane_status(plane_addr) or {})
            .get("server", {}).get("requests", 0) > 0,
            120, "remote-served commits after restart", extra=signed_load,
        ):
            res.problems.append(
                "no remote-served progress after plane restart"
            )
            return _finish(res, r, t0, upto=h1)
        res.details["plane_requests_after_restart"] = (
            vremote.plane_status(plane_addr) or {}
        ).get("server", {}).get("requests")
        return _finish(res, r, t0, upto=h1)
    finally:
        r.stop_all()
        try:
            plane.kill()
        except OSError as e:
            _log.debug(f"plane teardown kill: {e!r}")


def _linked_cross_process_trace_ids(events: list[dict]) -> list[str]:
    """trace_ids that link a server-side plane span (verify.rpc.serve)
    in one process to any span/instant in a DIFFERENT process — the
    cross-process stitch the whole propagation machinery exists for."""
    server_pids: dict[str, set] = {}
    other_pids: dict[str, set] = {}
    for e in events:
        tid = (e.get("args") or {}).get("trace_id")
        if not tid:
            continue
        bucket = (
            server_pids if e.get("name") == "verify.rpc.serve" else other_pids
        )
        bucket.setdefault(tid, set()).add(e.get("pid"))
    return sorted(
        tid for tid, spids in server_pids.items()
        if other_pids.get(tid, set()) - spids
    )


def scenario_trace_smoke(out_dir: str, base_port: int = 27600) -> ScenarioResult:
    """End-to-end distributed-tracing smoke: one node consumes a REAL
    out-of-process verify plane (verifyd) with span tracing armed in
    both processes.  After >=6 committed heights under signed CheckTx
    load, the node's /height_timeline must report per-phase wall times
    for >=5 heights, and — after both processes exit cleanly and their
    atexit trace exports flush — the merged Perfetto timeline must span
    both processes with at least one client-side span sharing a
    trace_id with the plane's server-side verify.rpc.serve span."""
    from ..verifysvc import server as vserver

    res = ScenarioResult(
        "trace_smoke", artifact_dir=os.path.join(out_dir, "trace_smoke")
    )
    t0 = time.monotonic()
    os.makedirs(res.artifact_dir, exist_ok=True)
    plane_env = {
        "COMETBFT_TPU_TRACE": os.path.join(
            res.artifact_dir, "verifyd.trace.json"
        )
    }
    plane, plane_addr = vserver.spawn_verifyd(
        f"127.0.0.1:{base_port + 900}",
        extra_env=plane_env,
        log_path=os.path.join(res.artifact_dir, "verifyd.log"),
    )
    m = Manifest(
        chain_id="chaos-trace-smoke",
        nodes=[
            NodeSpec("solo", env={
                # truthy-not-a-path: the runner redirects it to the
                # node's own <home>/trace.json export
                "COMETBFT_TPU_TRACE": "1",
                "COMETBFT_TPU_VERIFYRPC_ADDR": plane_addr,
            })
        ],
        target_height=6,
        load_tx_per_round=1,
    )
    r = Runner(
        m, os.path.join(out_dir, "trace_smoke", "net"), base_port=base_port
    )
    r.setup()
    r.start()
    node = r.nodes[0]
    signed_load = _signed_tx_sender(node, "trace")
    try:
        if not _drive_load_until(
            r, lambda: _min_height(r) >= 6, 240, "six committed heights",
            extra=signed_load,
        ):
            res.problems.append(
                f"node never reached height 6 (at {_min_height(r)})"
            )
            return _finish(res, r, t0, upto=6)
        res.liveness = True

        ht = node.rpc("height_timeline")
        timed = [
            h for h in ht.get("heights", [])
            if h.get("phase_seconds") and "commit" in h.get("phases_wall_ns", {})
        ]
        res.details["timeline_heights"] = len(timed)
        if len(timed) < 5:
            res.problems.append(
                f"/height_timeline has {len(timed)} committed heights "
                "with phase deltas, want >= 5"
            )

        res.details["remote_section"] = node.verify_svc().get("remote")
        res = _finish(res, r, t0, upto=6)

        # clean shutdown (SIGTERM) so both atexit exports hit disk,
        # then stitch and assert the cross-process link
        r.stop_all()
        plane.terminate()
        try:
            plane.wait(timeout=20)
        except Exception:  # noqa: BLE001
            plane.kill()
        _merge_scenario_traces(res)
        merged_path = res.details.get("merged_trace")
        if not merged_path:
            res.problems.append(
                "no merged timeline produced "
                f"({res.details.get('trace_merge_error', 'no exports found')})"
            )
        else:
            with open(merged_path) as f:
                doc = json.load(f)
            events = doc.get("traceEvents", [])
            pids = {e.get("pid") for e in events if e.get("ph") != "M"}
            linked = _linked_cross_process_trace_ids(events)
            res.details["trace_pids"] = len(pids)
            res.details["linked_trace_ids"] = len(linked)
            if len(pids) < 2:
                res.problems.append(
                    f"merged timeline spans {len(pids)} process(es), want >= 2"
                )
            if not linked:
                res.problems.append(
                    "no client-side span shares a trace_id with a "
                    "server-side verify.rpc.serve span"
                )
        res.ok = res.liveness and res.safety and not res.problems
        res.elapsed_s = time.monotonic() - t0
        return res
    finally:
        r.stop_all()
        try:
            plane.terminate()
            plane.wait(timeout=10)
        except Exception:  # noqa: BLE001
            try:
                plane.kill()
            except OSError as e:
                _log.debug(f"plane teardown kill: {e!r}")


# ------------------------------------------------------------- registry

SCENARIOS = {
    "wedge_smoke": scenario_wedge_smoke,
    "wedge": scenario_wedge,
    "crash_replay": scenario_crash_replay,
    "partition_heal": scenario_partition_heal,
    "double_sign": scenario_double_sign,
    "valset_rotation_blocksync": scenario_valset_rotation_blocksync,
    "plane_crash": scenario_plane_crash,
    "trace_smoke": scenario_trace_smoke,
}

# the six "full" scenarios scripts/chaos.py runs by default (the smoke
# is tier-1's fast stand-in, subsumed by `wedge`)
DEFAULT_SCENARIOS = [
    "wedge",
    "crash_replay",
    "partition_heal",
    "double_sign",
    "valset_rotation_blocksync",
    "plane_crash",
]


def run_scenario(
    name: str,
    out_dir: str,
    base_port: int | None = None,
    seed: int | None = None,
) -> ScenarioResult:
    global _SEED
    fn = SCENARIOS.get(name)
    if fn is None:
        raise ValueError(
            f"unknown scenario {name!r} (known: {', '.join(SCENARIOS)})"
        )
    _SEED = seed
    _log.info(
        f"chaos scenario {name} starting (artifacts under {out_dir}"
        + (f", seed={seed}" if seed is not None else "") + ")"
    )
    try:
        res = fn(out_dir) if base_port is None else fn(out_dir, base_port)
    except Exception as e:  # noqa: BLE001 — a crashed scenario is a failed scenario
        import traceback

        res = ScenarioResult(
            name,
            ok=False,
            crashed=True,
            problems=[f"scenario raised {type(e).__name__}: {e}"],
            details={
                # the RPC artifact sweep needs live nodes, which a crash
                # may have taken down — preserve what a triager needs:
                # the traceback here, and the node logs that survive
                # under <artifact_dir>/net/node*/node.log
                "traceback": traceback.format_exc(),
                "note": (
                    "scenario crashed before RPC artifact collection; "
                    "node logs remain under artifact_dir/net/"
                ),
            },
            artifact_dir=os.path.join(out_dir, name),
        )
    if _trace_armed():
        # every node process has exited (stop_all in the scenario's
        # finally), so the per-process atexit exports are on disk
        try:
            _merge_scenario_traces(res)
        except Exception as e:  # noqa: BLE001 — merging must never fail a run
            _log.warning(f"trace merge failed: {e!r}")
            res.details.setdefault("trace_merge_error", repr(e))
    _log.info(
        f"chaos scenario {name}: {'PASS' if res.ok else 'FAIL'} "
        f"({res.elapsed_s:.1f}s, problems={res.problems})"
    )
    return res
