"""Load generation + latency reporting (reference: test/loadtime —
payload/payload.go timestamped payloads, cmd/load broadcaster,
cmd/report latency aggregation keyed by the tx-embedded timestamps).

Payloads embed their creation time, a connection index, a rate tag, and
zero padding up to the requested size; the reporter recovers latency as
(block time - payload time) for every committed payload, grouped by the
generation parameters — so a report can be produced from the chain
alone, with no shared clock between generator and reporter beyond the
nodes' own block timestamps.
"""

from __future__ import annotations

import json
import statistics
import threading
import time
import uuid
from dataclasses import dataclass, field

_MAGIC = b"lt1"  # loadtime payload, version 1


def payload_bytes(
    size: int,
    conn: int = 0,
    rate: int = 0,
    experiment_id: str = "",
    now_ns: int | None = None,
    seq: int = 0,
) -> bytes:
    """A self-describing tx of exactly `size` bytes (payload.go NewBytes),
    shaped as `lt1<hex(json)>=<padding>` so it passes kv-style apps that
    demand a single key=value separator (the metadata is hex to keep the
    JSON's colons out of the tx).  seq keeps concurrently-generated
    payloads distinct so the mempool cache never dedups two load txs."""
    body = {
        "t": now_ns if now_ns is not None else time.time_ns(),
        "c": conn,
        "r": rate,
        "id": experiment_id,
        "s": seq,
    }
    raw = (
        _MAGIC
        + json.dumps(body, separators=(",", ":")).encode().hex().encode()
        + b"="
    )
    if len(raw) >= size:
        return raw + b"0"  # never truncate metadata; value must be non-empty
    return raw + b"0" * (size - len(raw))


def payload_from_bytes(tx: bytes) -> dict | None:
    """Parse a loadtime payload, or None (payload.go FromBytes).  Strict:
    anything lt1-prefixed that does not decode to a payload dict is not a
    payload — report() trusts the returned shape."""
    if not tx.startswith(_MAGIC) or b"=" not in tx:
        return None
    try:
        p = json.loads(bytes.fromhex(tx[len(_MAGIC):].split(b"=")[0].decode()))
    except (ValueError, UnicodeDecodeError):
        return None
    if not isinstance(p, dict) or not isinstance(p.get("t"), int):
        return None
    return p


@dataclass
class LoadResult:
    sent: int = 0
    accepted: int = 0
    rejected: int = 0
    errors: list[str] = field(default_factory=list)


class LoadGenerator:
    """Broadcasts timestamped payloads at a target rate over N
    connections (cmd/load with -c/-r/-T flags)."""

    def __init__(
        self,
        rpc_client_factory,
        connections: int = 1,
        rate: int = 100,
        size: int = 1024,
        experiment_id: str | None = None,
    ):
        self.factory = rpc_client_factory
        self.connections = connections
        self.rate = rate
        self.size = size
        self.experiment_id = experiment_id or uuid.uuid4().hex[:12]
        self._seq = 0
        self._seq_mtx = threading.Lock()

    def _next_seq(self) -> int:
        with self._seq_mtx:
            self._seq += 1
            return self._seq

    def run(self, duration_s: float) -> LoadResult:
        result = LoadResult()
        res_mtx = threading.Lock()

        def conn_worker(conn_idx: int) -> None:
            try:
                rpc = self.factory()
            except Exception as e:  # noqa: BLE001 — surface, don't vanish
                with res_mtx:
                    if len(result.errors) < 10:
                        result.errors.append(f"conn {conn_idx}: {e}")
                return
            deadline = time.monotonic() + duration_s
            interval = 1.0 / max(self.rate, 1)
            next_send = time.monotonic()
            while time.monotonic() < deadline:
                tx = payload_bytes(
                    self.size,
                    conn=conn_idx,
                    rate=self.rate,
                    experiment_id=self.experiment_id,
                    seq=self._next_seq(),
                )
                try:
                    resp = rpc.broadcast_tx_sync(tx)
                    with res_mtx:
                        result.sent += 1
                        if resp.get("code", 0) == 0:
                            result.accepted += 1
                        else:
                            result.rejected += 1
                except Exception as e:  # noqa: BLE001 — load must not stop
                    with res_mtx:
                        result.sent += 1
                        result.rejected += 1
                        if len(result.errors) < 10:
                            result.errors.append(str(e))
                next_send += interval
                sleep = next_send - time.monotonic()
                if sleep > 0:
                    time.sleep(sleep)

        threads = [
            threading.Thread(
                target=conn_worker, args=(i,), daemon=True,
                name=f"load-conn-{i}",
            )
            for i in range(self.connections)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        return result


def report(rpc, from_height: int = 1, to_height: int = 0) -> dict:
    """Scan committed blocks and aggregate payload latencies per
    experiment id (cmd/report: mean/min/max/stddev, all from chain data).
    """
    status = rpc.status()["sync_info"]
    if to_height == 0:
        to_height = int(status["latest_block_height"])
    # pruned chains: blocks below the store base are gone (the Go
    # reporter likewise iterates from store.Base())
    earliest = int(status.get("earliest_block_height", 1) or 1)
    from_height = max(from_height, earliest)
    per_exp: dict[str, list[float]] = {}
    tx_count = 0
    first_t = None
    last_t = None
    import base64
    import datetime

    for h in range(from_height, to_height + 1):
        blk = rpc.block(h)["block"]
        bt = blk["header"]["time"]
        base_s, _, frac = bt.rstrip("Z").partition(".")
        dt = datetime.datetime.strptime(base_s, "%Y-%m-%dT%H:%M:%S").replace(
            tzinfo=datetime.timezone.utc
        )
        block_ns = int(dt.timestamp()) * 10**9 + int((frac or "0").ljust(9, "0")[:9])
        for tx_b64 in blk["data"]["txs"]:
            p = payload_from_bytes(base64.b64decode(tx_b64))
            if p is None:
                continue
            tx_count += 1
            lat_s = (block_ns - p["t"]) / 1e9
            per_exp.setdefault(p.get("id", ""), []).append(lat_s)
            first_t = min(first_t, p["t"]) if first_t else p["t"]
            last_t = max(last_t, block_ns) if last_t else block_ns
    experiments = {}
    for exp, lats in per_exp.items():
        experiments[exp] = {
            "count": len(lats),
            "min_s": round(min(lats), 4),
            "max_s": round(max(lats), 4),
            "avg_s": round(statistics.fmean(lats), 4),
            "stddev_s": round(statistics.pstdev(lats), 4) if len(lats) > 1 else 0.0,
        }
    wall = (last_t - first_t) / 1e9 if first_t and last_t and last_t > first_t else 0
    return {
        "from_height": from_height,
        "to_height": to_height,
        "payload_txs": tx_count,
        "throughput_txs_per_s": round(tx_count / wall, 2) if wall else 0.0,
        "experiments": experiments,
    }
