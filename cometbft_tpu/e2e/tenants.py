"""In-process multi-tenant chains sharing ONE verify plane.

The production consolidation shape (ROADMAP item 5) is N independent
appchains sharing a single accelerator verify plane.  This module builds
the in-process version of that testnet: each :class:`TenantChain` is a
small chain's verification workload — a validator set, pre-signed commit
batches (with known-tampered rows so blame order is checkable), and
signed CheckTx envelopes — submitted through the SHARED
:class:`~cometbft_tpu.verifysvc.service.VerifyService` under the chain's
own tenant id.  Every template carries its expected per-signature
verdict bitmap from construction, so a soak can assert bit-exact
verdicts (no drift) without re-running host crypto in the hot loop.

Used by the soak harness (e2e/soak.py, scripts/soak.py) and the
multi-tenant fairness tests; process-level chains claim a tenant the
same way via ``NodeSpec.tenant`` (e2e/runner.py), which sets
``COMETBFT_TPU_VERIFYSVC_TENANT`` in the node's environment.
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass, field

from ..crypto import ed25519 as host
from ..verifysvc import checktx


def _seed_bytes(*parts) -> bytes:
    return hashlib.sha256("/".join(str(p) for p in parts).encode()).digest()


@dataclass
class CommitTemplate:
    """One pre-signed commit's verification payload: (pub, msg, sig)
    triples in validator order plus the expected per-signature verdicts
    (False rows are deliberately tampered at construction)."""

    height: int
    items: list = field(default_factory=list)
    expected: list = field(default_factory=list)

    @property
    def all_ok(self) -> bool:
        return bool(self.expected) and all(self.expected)


class TenantChain:
    """One small chain's verify workload, bound to a tenant id.

    Templates are pre-signed at construction (pure-python signing is
    ~0.6 ms/sig — fine at setup, too slow for a hot loop) and cycled by
    index, so the load loops do zero crypto: submit, collect, compare
    against the expected bitmap.
    """

    def __init__(
        self,
        name: str,
        n_validators: int = 8,
        seed: int = 0,
        commit_pool: int = 16,
        tx_pool: int = 24,
        tamper_every: int = 5,
        tx_tamper_every: int = 8,
    ):
        self.name = name
        self.n_validators = n_validators
        self._keys = [
            host.PrivKey.from_seed(_seed_bytes("val", name, seed, i))
            for i in range(n_validators)
        ]
        self.pubkeys = [k.pub_key().data for k in self._keys]

        # pre-signed commit templates; every tamper_every'th has one
        # corrupted signature row so blame-order plumbing stays honest
        self.commits: list[CommitTemplate] = []
        for h in range(commit_pool):
            tpl = CommitTemplate(height=h + 1)
            bad = (h % n_validators) if (
                tamper_every and (h + 1) % tamper_every == 0
            ) else None
            for i, sk in enumerate(self._keys):
                msg = b"%s|commit|%d|val%d" % (name.encode(), h + 1, i)
                sig = sk.sign(msg)
                if i == bad:
                    msg += b"!"  # tampered: must verify False
                tpl.items.append((self.pubkeys[i], msg, sig))
                tpl.expected.append(i != bad)
            self.commits.append(tpl)

        # signed CheckTx envelopes; every tx_tamper_every'th is corrupted
        # (payload byte flip after signing -> must verify False)
        self._tx_keys = [
            host.PrivKey.from_seed(_seed_bytes("tx", name, seed, i))
            for i in range(4)
        ]
        self.txs: list[tuple[bytes, bool]] = []
        for j in range(tx_pool):
            sk = self._tx_keys[j % len(self._tx_keys)]
            tx = checktx.make_signed_tx(
                sk, b"%s|tx|%d" % (name.encode(), j)
            )
            good = not (tx_tamper_every and (j + 1) % tx_tamper_every == 0)
            if not good:
                tx = tx[:-1] + bytes([tx[-1] ^ 1])
            self.txs.append((tx, good))

    def commit(self, i: int) -> CommitTemplate:
        return self.commits[i % len(self.commits)]

    def tx(self, i: int) -> tuple[bytes, bool]:
        return self.txs[i % len(self.txs)]

    def flood_items(self, n_sigs: int) -> tuple[list, list]:
        """A reusable n_sigs-wide mempool batch (valid envelope-domain
        signatures) for rogue-flood load, with its expected bitmap."""
        items = []
        for i in range(n_sigs):
            sk = self._tx_keys[i % len(self._tx_keys)]
            msg = b"%s|flood|%d" % (self.name.encode(), i)
            items.append((sk.pub_key().data, msg, sk.sign(msg)))
        return items, [True] * n_sigs


def build_chains(
    n: int, n_validators: int = 8, seed: int = 0, **kw
) -> list[TenantChain]:
    """N chains named ``chain0..chainN-1`` sharing one plane."""
    return [
        TenantChain(f"chain{i}", n_validators=n_validators, seed=seed, **kw)
        for i in range(n)
    ]
