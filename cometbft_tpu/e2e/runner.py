"""E2E testnet runner: multi-process localnets with perturbations
(reference: test/e2e/runner — setup/start/load/perturb/wait/test stages
over docker-compose; here the nodes are OS processes driven through the
CLI, which exercises the same real binaries + sockets without docker).
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import time
import urllib.request
from dataclasses import dataclass, field

from ..cli import main as cli_main
from ..config import load_config, save_config
from ..utils.log import get_logger

_log = get_logger("e2e.runner")


@dataclass
class NodeSpec:
    """One manifest entry (test/e2e/pkg/manifest.go)."""

    name: str
    start_at: int = 0  # height to join at (0 = genesis)
    # kill|pause|restart|disconnect|wedge|double_sign (disconnect =
    # network partition via SIGUSR1 toggle, the runner/perturb.go
    # docker-disconnect analogue; wedge/double_sign arm the fault
    # registry over RPC — utils/fail.py — and require the node to run
    # with COMETBFT_TPU_FAULT_RPC=1 in its env)
    perturbations: list[str] = field(default_factory=list)
    # extra environment for the node process (chaos scenarios set
    # COMETBFT_TPU_FAULT_RPC / COMETBFT_TPU_HEALTH / failover knobs here)
    env: dict[str, str] = field(default_factory=dict)
    # verify-plane tenant this chain's node claims
    # (COMETBFT_TPU_VERIFYSVC_TENANT): how process-level chains share a
    # multi-tenant verify plane; "" keeps the default tenant
    tenant: str = ""
    # per-node validator key type ("" = the manifest-wide key_type).
    # A mix of key types across nodes produces a MIXED validator set in
    # genesis (e.g. ed25519 + bls12_381): commit verification then takes
    # the sequential fallback (types/validation.should_batch_verify
    # requires a homogeneous set), and the genesis/proto encode paths
    # must round-trip every key type (crypto/encoding)
    key_type: str = ""
    # per-link shaping (runner/latency_emulation.go analogue): outbound
    # delay +- jitter applied at this node's sockets (utils/netutil)
    latency_ms: float = 0.0
    latency_jitter_ms: float = 0.0
    # generator axes (generator/generate.go): ABCI transport and DB
    # backend; "" = the config default
    abci: str = "local"  # "local" | "socket" | "grpc" (external app)
    db_backend: str = ""  # "" | "native" | "sqlite" | "memdb"
    # join mid-run via statesync (requires start_at > 0): the runner
    # fetches trust height/hash from a running node right before launch
    # (manifest.go StateSync)
    state_sync: bool = False


@dataclass
class Manifest:
    chain_id: str = "e2e-chain"
    nodes: list[NodeSpec] = field(default_factory=list)
    load_tx_per_round: int = 5
    target_height: int = 12
    # validator key type for the whole net (generate.go's keyType axis);
    # non-ed25519 nets exercise the sequential verify fallback
    key_type: str = "ed25519"


class E2ENode:
    def __init__(self, name: str, home: str, rpc_port: int,
                 latency_ms: float = 0.0, latency_jitter_ms: float = 0.0,
                 abci_port: int = 0, abci_scheme: str = "tcp",
                 extra_env: dict[str, str] | None = None):
        self.name = name
        self.home = home
        self.rpc_port = rpc_port
        self.latency_ms = latency_ms
        self.latency_jitter_ms = latency_jitter_ms
        self.abci_port = abci_port  # non-zero: external app process
        self.abci_scheme = abci_scheme  # "tcp" (socket) | "grpc"
        self.extra_env = dict(extra_env or {})
        self.proc: subprocess.Popen | None = None
        self.app_proc: subprocess.Popen | None = None

    def start(self) -> None:
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        # Keep e2e nodes OFF the real device tunnel: the axon sitecustomize
        # (keyed on PALLAS_AXON_POOL_IPS) contacts the device relay at
        # interpreter start and OVERRIDES JAX_PLATFORMS; kill/restart
        # perturbations then SIGKILL mid-session clients, which wedges the
        # one-client-at-a-time tunnel for every later process (the round-3/4
        # driver benches died exactly this way).  CPU is forced above, so
        # the plugin has nothing to offer these nodes anyway.
        env.pop("PALLAS_AXON_POOL_IPS", None)
        # the test conftest forces the device threshold to 1 so kernel
        # tests exercise the device paths; a NODE inheriting that would
        # compile an XLA program to verify a 2-signature commit — scrub
        # back to the production default (host path at localnet scale)
        env.pop("COMETBFT_TPU_DEVICE_BATCH_MIN", None)
        if self.latency_ms or self.latency_jitter_ms:
            env["COMETBFT_TPU_TEST_LATENCY_MS"] = (
                f"{self.latency_ms}:{self.latency_jitter_ms}"
            )
        env.update(self.extra_env)
        from ..utils import tracing as _tracing

        _tv = env.get("COMETBFT_TPU_TRACE", "").lower()
        _tv_explicit_path = (
            "COMETBFT_TPU_TRACE" in self.extra_env
            and (os.sep in _tv or _tv.endswith(".json"))
        )
        if _tv not in _tracing._OFF_VALUES and not _tv_explicit_path:
            # tracing armed (parent env or node spec): every node exports
            # its OWN trace file at exit — a shared inherited path would
            # be torn by concurrent atexit writers; the chaos/soak
            # epilogues merge the per-process exports into one timeline
            # (utils/tracemerge).  Only an explicit per-node path in the
            # spec's env is left alone.
            env["COMETBFT_TPU_TRACE"] = os.path.join(self.home, "trace.json")
        if self.abci_port and self.app_proc is None:
            # external app rides the ABCI socket or gRPC transport (the
            # generator's abci axis); it outlives node restarts the way
            # the reference's app container does
            self.app_proc = subprocess.Popen(
                [
                    sys.executable, "-m", "cometbft_tpu", "kvstore",
                    "--addr", f"{self.abci_scheme}://127.0.0.1:{self.abci_port}",
                    "--snapshot-interval", "2",
                ],
                env=env,
                stdout=open(os.path.join(self.home, "app.log"), "ab"),
                stderr=subprocess.STDOUT,
            )
        self.proc = subprocess.Popen(
            [
                sys.executable, "-m", "cometbft_tpu",
                "--home", self.home, "start",
                "--rpc-laddr", f"tcp://127.0.0.1:{self.rpc_port}",
            ],
            env=env,
            stdout=open(os.path.join(self.home, "node.log"), "ab"),
            stderr=subprocess.STDOUT,
        )

    def rpc(self, method: str, **params):
        payload = json.dumps(
            {"jsonrpc": "2.0", "id": 1, "method": method, "params": params}
        ).encode()
        req = urllib.request.Request(
            f"http://127.0.0.1:{self.rpc_port}",
            data=payload,
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req, timeout=10) as resp:
            out = json.loads(resp.read())
        if "error" in out:
            raise RuntimeError(out["error"])
        return out["result"]

    def height(self) -> int:
        return int(self.rpc("status")["sync_info"]["latest_block_height"])

    def wait_ready(self, timeout: float = 30.0) -> bool:
        """Poll /tpu_health until the node answers AND is not wedged —
        the readiness wait that replaces bare fixed sleeps wherever the
        runner holds a node handle.  The route answers even with the
        sentinel off (`{"enabled": false}`), so on a plain node this
        degrades to 'the RPC listener is up', which is exactly the old
        sleep's (unchecked) assumption."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if self.proc is None or self.proc.poll() is not None:
                return False  # process gone: readiness can never arrive
            try:
                h = self.rpc("tpu_health")
            except Exception as e:  # noqa: BLE001 — RPC not up yet, keep polling
                _log.debug(f"tpu_health poll of {self.name}: {e!r}")
                time.sleep(0.25)
                continue
            if not h.get("enabled", False) or h.get("ready", True):
                return True
            time.sleep(0.25)
        return False

    def arm_fault(self, name: str, value: float = 1.0) -> dict:
        """Arm a fault in the running node via the fault registry's RPC
        endpoint (utils/fail.py; needs COMETBFT_TPU_FAULT_RPC=1 in the
        node's env — NodeSpec.env)."""
        return self.rpc("arm_fault", name=name, value=value)

    def clear_fault(self, name: str | None = None) -> dict:
        return self.rpc("clear_fault", **({"name": name} if name else {}))

    def verify_svc(self) -> dict:
        return self.rpc("verify_svc_status")

    def kill(self) -> None:
        """kill -9: the crash-recovery perturbation (runner/perturb.go)."""
        if self.proc:
            self.proc.kill()
            self.proc.wait(timeout=20)
            self.proc = None

    def pause(self) -> None:
        if self.proc:
            self.proc.send_signal(signal.SIGSTOP)

    def resume(self) -> None:
        if self.proc:
            self.proc.send_signal(signal.SIGCONT)

    def partition_toggle(self) -> None:
        """SIGUSR1: toggle severing the node's p2p sockets (cli.py
        cmd_start's hook)."""
        if self.proc:
            self.proc.send_signal(signal.SIGUSR1)

    def terminate(self) -> None:
        if self.proc:
            try:
                self.proc.terminate()
                self.proc.wait(timeout=15)
            except subprocess.TimeoutExpired:
                self.proc.kill()
            self.proc = None
        if self.app_proc:
            try:
                self.app_proc.terminate()
                self.app_proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                self.app_proc.kill()
            self.app_proc = None


class Runner:
    """setup → start → load → perturb → wait → test
    (test/e2e/runner/main.go stages)."""

    def __init__(self, manifest: Manifest, out_dir: str, base_port: int = 28000):
        self.m = manifest
        self.out = out_dir
        self.base_port = base_port
        self.nodes: list[E2ENode] = []

    # ------------------------------------------------------------- stages

    def setup(self) -> None:
        n = len(self.m.nodes)
        assert cli_main(
            [
                "testnet", "--v", str(n), "--o", self.out,
                "--chain-id", self.m.chain_id,
                "--starting-port", str(self.base_port),
                "--key-type", self.m.key_type,
            ]
        ) == 0
        if any(spec.key_type for spec in self.m.nodes):
            self._apply_node_key_types()
        for i, spec in enumerate(self.m.nodes):
            home = os.path.join(self.out, f"node{i}")
            cfg = load_config(home)
            cfg.consensus.timeout_propose = 1.0
            cfg.consensus.timeout_propose_delta = 0.3
            cfg.consensus.timeout_prevote = 0.5
            cfg.consensus.timeout_prevote_delta = 0.3
            cfg.consensus.timeout_precommit = 0.5
            cfg.consensus.timeout_precommit_delta = 0.3
            # thread-dump endpoint: when a node wedges mid-testnet the
            # runner (and a human) can pull /debug/threads (perturb.go's
            # cometbft debug equivalent)
            cfg.instrumentation.pprof_laddr = (
                f"127.0.0.1:{self.base_port + 2000 + i}"
            )
            # frequent snapshots so a statesync joiner always finds one
            # (the reference e2e app config sets snapshot_interval=3 the
            # same way)
            cfg.base.app_snapshot_interval = 2
            abci_port = 0
            if spec.abci == "socket":
                abci_port = self.base_port + 3000 + i
                cfg.base.proxy_app = f"tcp://127.0.0.1:{abci_port}"
            elif spec.abci == "grpc":
                abci_port = self.base_port + 3000 + i
                cfg.base.proxy_app = f"grpc://127.0.0.1:{abci_port}"
            if spec.db_backend:
                cfg.base.db_backend = spec.db_backend
            save_config(cfg)
            if spec.tenant:
                spec.env.setdefault(
                    "COMETBFT_TPU_VERIFYSVC_TENANT", spec.tenant
                )
            self.nodes.append(
                E2ENode(
                    spec.name,
                    home,
                    self.base_port + 1000 + i,
                    latency_ms=spec.latency_ms,
                    latency_jitter_ms=spec.latency_jitter_ms,
                    abci_port=abci_port,
                    abci_scheme="grpc" if spec.abci == "grpc" else "tcp",
                    extra_env=spec.env,
                )
            )

    def _apply_node_key_types(self) -> None:
        """Regenerate the privval key of every node with a per-spec
        ``key_type`` override and rewrite the SHARED genesis (validator
        list + ConsensusParams.validator.pub_key_types) across all
        homes — a mixed-key-type validator set must round-trip through
        the same genesis.json every node loads."""
        from ..privval.file_pv import FilePV
        from ..types.genesis import GenesisDoc, GenesisValidator

        cfgs = [
            load_config(os.path.join(self.out, f"node{i}"))
            for i in range(len(self.m.nodes))
        ]
        pvs = []
        for cfg, spec in zip(cfgs, self.m.nodes):
            if spec.key_type and spec.key_type != self.m.key_type:
                os.remove(cfg.priv_validator_key_file())
                # the last-sign state belongs to the deleted key: a new
                # key inheriting old height/round/signbytes would trip
                # (or wrongly pass) the double-sign guard
                if os.path.exists(cfg.priv_validator_state_file()):
                    os.remove(cfg.priv_validator_state_file())
                pv = FilePV.load_or_generate(
                    cfg.priv_validator_key_file(),
                    cfg.priv_validator_state_file(),
                    key_type=spec.key_type,
                )
            else:
                pv = FilePV.load_or_generate(
                    cfg.priv_validator_key_file(),
                    cfg.priv_validator_state_file(),
                )
            pvs.append(pv)
        with open(cfgs[0].genesis_file()) as f:
            doc = GenesisDoc.from_json(f.read())
        doc.validators = [
            GenesisValidator(
                pub_key_type=pv.key.pub_key.type,
                pub_key_bytes=pv.key.pub_key.bytes(),
                power=10,
            )
            for pv in pvs
        ]
        doc.consensus_params.validator.pub_key_types = sorted(
            {pv.key.pub_key.type for pv in pvs}
        )
        for cfg in cfgs:
            doc.save_as(cfg.genesis_file())

    def start(self) -> None:
        for node, spec in zip(self.nodes, self.m.nodes):
            if spec.start_at == 0:
                node.start()
        # readiness, not a fixed grace sleep: the first load round used
        # to race the RPC listeners coming up
        for node, spec in zip(self.nodes, self.m.nodes):
            if spec.start_at == 0 and not node.wait_ready():
                _log.warning(f"{node.name} not ready after start")

    def start_late_nodes(self) -> None:
        started_heights = self._heights(only_running=True)
        tip = max(started_heights) if started_heights else 0
        for node, spec in zip(self.nodes, self.m.nodes):
            if spec.start_at > 0 and node.proc is None and tip >= spec.start_at:
                if spec.state_sync:
                    try:
                        self._configure_statesync(node, spec)
                    except Exception as e:  # noqa: BLE001 — retried next round
                        # usually just "trust root not available yet", but a
                        # persistent failure (config write error) must be
                        # findable, not an eternally silent non-start
                        _log.debug(
                            f"statesync config for {node.name} not ready, "
                            f"will retry: {e!r}"
                        )
                        continue
                node.start()

    def _configure_statesync(self, node: E2ENode, spec: NodeSpec) -> None:
        """Write the joiner's trust root + rpc_servers right before
        launch (runner/setup.go does this from the seed node's /commit —
        the trust hash can only exist once the chain is running)."""
        running = [n for n in self.nodes if n.proc is not None and n is not node]
        if len(running) < 1:
            raise RuntimeError("no running nodes to trust")
        trust_h = max(1, spec.start_at - 2)
        cm = running[0].rpc("commit", height=trust_h)
        trust_hash = cm["signed_header"]["commit"]["block_id"]["hash"]
        cfg = load_config(node.home)
        cfg.statesync.enable = True
        cfg.statesync.trust_height = trust_h
        cfg.statesync.trust_hash = trust_hash
        cfg.statesync.discovery_time = 2.0  # localnet: peers are right there
        cfg.statesync.rpc_servers = ",".join(
            f"127.0.0.1:{n.rpc_port}" for n in running[:2]
        )
        save_config(cfg)

    def load(self, round_id: int) -> None:
        """Submit txs through a random running node (runner/load.go)."""
        for node in self.nodes:
            if node.proc is None:
                continue
            failed = 0
            last_err: Exception | None = None
            for j in range(self.m.load_tx_per_round):
                tx = f"load-{round_id}-{j}={node.name}".encode()
                try:
                    import base64

                    node.rpc("broadcast_tx_sync", tx=base64.b64encode(tx).decode())
                except Exception as e:  # noqa: BLE001 — load-gen rides out node restarts
                    failed += 1
                    last_err = e
            if failed:
                _log.warning(
                    f"load round {round_id} via {node.name}: {failed}/"
                    f"{self.m.load_tx_per_round} submissions failed "
                    f"(last: {last_err!r})"
                )
            break

    def perturb(self) -> None:
        """Apply each node's scripted perturbations (runner/perturb.go)."""
        for node, spec in zip(self.nodes, self.m.nodes):
            for p in spec.perturbations:
                if node.proc is None:
                    continue
                if p == "kill":
                    node.kill()
                    time.sleep(1.0)  # downtime under test, not readiness
                    node.start()
                    if not node.wait_ready():
                        _log.warning(
                            f"{node.name} not ready after kill+restart"
                        )
                elif p == "pause":
                    node.pause()
                    time.sleep(3.0)
                    node.resume()
                elif p == "restart":
                    node.terminate()
                    time.sleep(0.5)  # downtime under test, not readiness
                    node.start()
                    if not node.wait_ready():
                        _log.warning(f"{node.name} not ready after restart")
                elif p == "disconnect":
                    # network partition: sever sockets, not processes
                    # (runner/perturb.go:47-60); heal after a few seconds
                    node.partition_toggle()
                    time.sleep(4.0)
                    node.partition_toggle()
                elif p == "wedge":
                    # inject a device wedge via the fault registry's RPC
                    # arm endpoint: the verify plane must trip to CPU
                    # fallback and keep the node committing, then
                    # restore via probation once healed
                    try:
                        node.arm_fault("wedge_device")
                        time.sleep(6.0)  # wedged window under test
                        node.clear_fault("wedge_device")
                    except Exception as e:  # noqa: BLE001 — fault RPC may be disabled
                        _log.warning(
                            f"wedge perturbation of {node.name} failed "
                            f"(is COMETBFT_TPU_FAULT_RPC=1 set?): {e!r}"
                        )
                elif p == "double_sign":
                    # one byzantine equivocation: the next signed
                    # non-nil prevote is accompanied by a conflicting
                    # broadcast, feeding the evidence pool
                    try:
                        node.arm_fault("double_sign", 1)
                    except Exception as e:  # noqa: BLE001 — fault RPC may be disabled
                        _log.warning(
                            f"double_sign perturbation of {node.name} "
                            f"failed (is COMETBFT_TPU_FAULT_RPC=1 set?): {e!r}"
                        )

    def wait_for_height(self, h: int, timeout: float = 240.0) -> bool:
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            self.start_late_nodes()
            hs = self._heights(only_running=True)
            if hs and min(hs) >= h and len(hs) == sum(
                1 for n in self.nodes if n.proc is not None
            ):
                if all(n.proc is not None for n in self.nodes):
                    return True
            time.sleep(1.0)
        return False

    # ------------------------------------------------------------- checks

    def check_invariants(self, upto: int) -> list[str]:
        """Black-box invariants over RPC (test/e2e/tests/*_test.go):
        identical blocks, app hashes, and validator sets everywhere."""
        problems = []
        hashes: dict[int, set[str]] = {}
        for node in self.nodes:
            if node.proc is None:
                continue
            try:
                base = int(
                    node.rpc("status")["sync_info"]["earliest_block_height"]
                )
                for h in range(max(base, 1), upto + 1):
                    b = node.rpc("block", height=h)
                    hashes.setdefault(h, set()).add(b["block_id"]["hash"])
            except Exception as e:  # noqa: BLE001
                problems.append(f"{node.name}: rpc failed: {e}")
        for h, hs in hashes.items():
            if len(hs) > 1:
                problems.append(f"fork at height {h}: {hs}")
        apps = set()
        for node in self.nodes:
            if node.proc is None:
                continue
            try:
                apps.add(node.rpc("status")["sync_info"]["latest_app_hash"])
            except Exception as e:  # noqa: BLE001 — probing possibly-dead nodes
                _log.debug(f"status probe of {node.name} failed: {e!r}")
        # nodes may be at different heights; only flag if everyone reports
        # the same height but different app hashes
        heights = set(self._heights(only_running=True))
        if len(heights) == 1 and len(apps) > 1:
            problems.append(f"app hash divergence at height {heights}: {apps}")
        return problems

    def check_watchdog_fires(self) -> list[str]:
        """A consensus-watchdog re-kick in any node means a scheduled
        timeout evaporated — a state-machine bug the watchdog papered
        over.  The reference runs with no watchdog at all
        (internal/consensus/state.go:795-884), so perturbed runs must
        show zero fires to claim parity."""
        from ..consensus.state import ConsensusState

        token = ConsensusState.WATCHDOG_LOG_TOKEN.encode()
        problems = []
        for node in self.nodes:
            log = os.path.join(node.home, "node.log")
            try:
                with open(log, "rb") as f:
                    for line in f:
                        if token in line:
                            problems.append(
                                f"{node.name}: {line.decode(errors='replace').strip()}"
                            )
            except OSError as e:
                # a node that ran but left no log can't be checked — that
                # is a finding, not a vacuous pass
                problems.append(f"{node.name}: node.log unreadable: {e}")
        return problems

    def dump_stalled(self, target_height: int) -> None:
        """Print /debug/threads of every node behind target — turns a
        CI stall into an actionable trace (debug kill's goroutine dump)."""
        for i, node in enumerate(self.nodes):
            if node.proc is None:
                print(f"[dump] {node.name}: not running")
                continue
            try:
                h = node.height()
            except Exception as e:  # noqa: BLE001
                print(f"[dump] {node.name}: rpc dead: {e}")
                h = -1
            if h >= target_height:
                continue
            try:
                url = f"http://127.0.0.1:{self.base_port + 2000 + i}/debug/threads"
                with urllib.request.urlopen(url, timeout=5) as f:
                    print(f"[dump] {node.name} stalled at {h}:\n{f.read().decode()}")
            except Exception as e:  # noqa: BLE001
                print(f"[dump] {node.name}: pprof unreachable: {e}")

    def stop_all(self) -> None:
        for node in self.nodes:
            node.terminate()

    def _heights(self, only_running: bool = False) -> list[int]:
        out = []
        for node in self.nodes:
            if only_running and node.proc is None:
                continue
            try:
                out.append(node.height())
            except Exception as e:  # noqa: BLE001 — probing possibly-dead nodes
                _log.debug(f"height probe of {node.name} failed: {e!r}")
        return out
