"""Lane-aware concurrent mempool (reference: mempool/clist_mempool.go).

Txs are admitted through the app's CheckTx on the mempool ABCI connection
and queued into priority lanes (lane = app-defined tx class; CheckTx
assigns it, clist_mempool.go:57-94).  Iteration — for both block reaping
and gossip — interleaves lanes with Interleaved Weighted Round-Robin so a
lane of priority p yields p entries per p-round cycle
(mempool/iterators.go:38-44).  An LRU cache short-circuits repeated
CheckTx for recently seen txs.

Python threading notes: one RLock guards the lanes (the reference's
per-CList fine-grained locking buys nothing under the GIL); update()
runs with the consensus engine holding lock() exactly like the
reference's Lock/Update/Unlock window.
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from dataclasses import dataclass, field
from typing import Callable, Iterable

from ..utils.log import get_logger
from ..wire import abci_pb as pb
from ..wire.proto import encode_varint
from .cache import LRUTxCache, NopTxCache
from .mempool import (
    AppCheckError,
    InvalidTxSignatureError,
    Mempool,
    MempoolFullError,
    TxInCacheError,
    TxInMempoolError,
    key_of,
)


@dataclass
class MempoolConfig:
    """config.MempoolConfig defaults (config/config.go mempool section)."""

    size: int = 5000
    max_tx_bytes: int = 1024 * 1024
    max_txs_bytes: int = 64 * 1024 * 1024
    cache_size: int = 10000
    keep_invalid_txs_in_cache: bool = False
    recheck: bool = True
    broadcast: bool = True


@dataclass
class TxEntry:
    tx: bytes
    key: bytes
    height: int
    gas_wanted: int
    lane: str
    senders: set[str] = field(default_factory=set)

    def size(self) -> int:
        return len(self.tx)


def proto_tx_overhead(tx: bytes) -> int:
    """Wire size of one tx as a repeated-bytes field in Data
    (types.ComputeProtoSizeForTxs): tag byte + length varint + payload."""
    return 1 + len(encode_varint(len(tx))) + len(tx)


class IWRRIterator:
    """Interleaved weighted round-robin over lane snapshots
    (iterators.go IWRRIterator)."""

    def __init__(self, lanes: dict[str, list[TxEntry]], priorities: dict[str, int]):
        # highest priority first; stable for equal priorities
        self._sorted = sorted(priorities.items(), key=lambda kv: -kv[1])
        self._queues = {lane: list(entries) for lane, entries in lanes.items()}
        self._pos = {lane: 0 for lane in lanes}
        self._round = 1
        self._lane_index = 0

    def __iter__(self):
        return self

    def __next__(self) -> TxEntry:
        if not self._sorted:
            raise StopIteration
        empty = 0
        while True:
            lane, priority = self._sorted[self._lane_index]
            q, p = self._queues.get(lane, []), self._pos.get(lane, 0)
            if p >= len(q):
                empty += 1
                if empty >= len(self._sorted):
                    raise StopIteration
                self._advance()
                continue
            if priority < self._round:
                empty = 0
                self._advance()
                continue
            break
        entry = q[p]
        self._pos[lane] = p + 1
        self._advance()
        return entry

    def _advance(self) -> None:
        self._lane_index += 1
        if self._lane_index >= len(self._sorted):
            self._lane_index = 0
            self._round += 1
            max_p = self._sorted[0][1] if self._sorted else 1
            if self._round > max_p:
                self._round = 1


class CListMempool(Mempool):
    def __init__(
        self,
        config: MempoolConfig,
        proxy_app,  # abci Client on the mempool connection
        height: int = 0,
        lane_priorities: dict[str, int] | None = None,
        default_lane: str = "",
        pre_check: Callable[[bytes], None] | None = None,
    ):
        self.config = config
        self.proxy_app = proxy_app
        self.height = height
        self.logger = get_logger("mempool")
        if not lane_priorities:
            lane_priorities, default_lane = {"": 1}, ""
        if default_lane not in lane_priorities:
            raise ValueError(f"default lane {default_lane!r} not in lane set")
        # IWRRIterator clamps its round counter to 1..max_priority, so a
        # lane with priority < 1 would be skipped on every pass while
        # resetting the empty counter — an infinite loop in reap.  The app's
        # Info response is untrusted input; reject bad priorities up front.
        for lane, priority in lane_priorities.items():
            if priority < 1:
                raise ValueError(
                    f"lane {lane!r} priority {priority} must be >= 1"
                )
        self.lane_priorities = dict(lane_priorities)
        self.default_lane = default_lane
        self.lanes: dict[str, OrderedDict[bytes, TxEntry]] = {
            lane: OrderedDict() for lane in lane_priorities
        }
        self._tx_index: dict[bytes, str] = {}  # key -> lane
        self._bytes = 0
        self._mtx = threading.RLock()
        self._update_mtx = threading.RLock()  # the consensus Lock/Unlock
        self.cache = (
            LRUTxCache(config.cache_size) if config.cache_size > 0 else NopTxCache()
        )
        self.pre_check = pre_check
        self._txs_available = threading.Event()
        self._notify_available = False
        self._notified_this_height = False
        # change feed for the gossip reactor's blocking iterators: bumped
        # on every admitted tx (the analogue of clist's WaitChan wakeup)
        self._add_seq = 0
        self._add_cond = threading.Condition(self._mtx)

    # ------------------------------------------------------------ admission

    def check_tx(self, tx: bytes, sender: str = "") -> None:
        if len(tx) > self.config.max_tx_bytes:
            raise AppCheckError(
                code=-1, log=f"tx too large: {len(tx)} > {self.config.max_tx_bytes}"
            )
        if self.pre_check:
            self.pre_check(tx)
        from ..utils.metrics import hub as _mhub

        key = key_of(tx)
        if not self.cache.push(key):
            _mhub().mp_already_received_txs.inc()
            # record the additional sender for dedup accounting, then reject
            with self._mtx:
                lane = self._tx_index.get(key)
                if lane is not None:
                    entry = self.lanes[lane].get(key)
                    if entry is not None and sender:
                        entry.senders.add(sender)
                    raise TxInMempoolError
            raise TxInCacheError
        # Signed-envelope admission gate — the verify service's mempool
        # client (verifysvc/checktx): per-tx ed25519 checks from
        # concurrent senders coalesce into one device batch; unsigned
        # txs pass through untouched.  Runs AFTER the cache dedup (a
        # replayed tx never re-verifies) and BEFORE the app round trip.
        try:
            self._check_tx_signature(tx, key)
        except InvalidTxSignatureError:
            raise  # cache already handled per keep_invalid_txs_in_cache
        except Exception:
            # transient verify-plane failure: the tx was never judged —
            # same contract as an app-connection error below, the key
            # must leave the cache or the tx is unsubmittable until
            # LRU eviction
            self.cache.remove(key)
            raise
        try:
            res = self.proxy_app.check_tx(
                pb.CheckTxRequest(tx=tx, type=pb.CHECK_TX_TYPE_CHECK)
            )
        except Exception:
            self.cache.remove(key)
            raise
        self._handle_check_result(tx, key, sender, res)

    def _check_tx_signature(self, tx: bytes, key: bytes) -> None:
        from ..utils import envknobs
        from ..utils.metrics import hub as _mhub

        if not envknobs.get_bool(envknobs.VERIFYSVC_CHECKTX):
            return
        from ..verifysvc import checktx as _checktx

        sig_ok = _checktx.verify_tx_signature(tx)
        if sig_ok is False:
            _mhub().mp_failed_txs.inc()
            if not self.config.keep_invalid_txs_in_cache:
                self.cache.remove(key)
            raise InvalidTxSignatureError()

    def _handle_check_result(
        self, tx: bytes, key: bytes, sender: str, res: pb.CheckTxResponse
    ) -> None:
        from ..utils.metrics import hub as _mhub

        if res.code != 0:
            _mhub().mp_failed_txs.inc()
            if not self.config.keep_invalid_txs_in_cache:
                self.cache.remove(key)
            raise AppCheckError(code=res.code, log=res.log, codespace=res.codespace)
        lane = res.lane_id or self.default_lane
        if lane not in self.lanes:
            lane = self.default_lane
        with self._mtx:
            if key in self._tx_index:
                raise TxInMempoolError
            if (
                len(self._tx_index) >= self.config.size
                or self._bytes + len(tx) > self.config.max_txs_bytes
            ):
                self.cache.remove(key)
                _mhub().mp_evicted_txs.inc()
                raise MempoolFullError(len(self._tx_index), self._bytes)
            _mhub().mp_tx_size_bytes.observe(len(tx))
            entry = TxEntry(
                tx=tx,
                key=key,
                height=self.height,
                gas_wanted=res.gas_wanted,
                lane=lane,
                senders={sender} if sender else set(),
            )
            self.lanes[lane][key] = entry
            self._tx_index[key] = lane
            self._bytes += len(tx)
            self._add_seq += 1
            self._add_cond.notify_all()
            self._maybe_notify()

    # ------------------------------------------------------------- queries

    def size(self) -> int:
        with self._mtx:
            return len(self._tx_index)

    def size_bytes(self) -> int:
        with self._mtx:
            return self._bytes

    def contains(self, key: bytes) -> bool:
        with self._mtx:
            return key in self._tx_index

    def get_entry(self, key: bytes) -> TxEntry | None:
        with self._mtx:
            lane = self._tx_index.get(key)
            return self.lanes[lane].get(key) if lane else None

    def _snapshot_iter(self) -> IWRRIterator:
        with self._mtx:
            return IWRRIterator(
                {lane: list(q.values()) for lane, q in self.lanes.items()},
                self.lane_priorities,
            )

    def iter_txs(self) -> Iterable[bytes]:
        return (e.tx for e in self._snapshot_iter())

    def iter_entries(self) -> Iterable[TxEntry]:
        return self._snapshot_iter()

    # -------------------------------------------------------------- reaping

    def reap_max_bytes_max_gas(self, max_bytes: int, max_gas: int) -> list[bytes]:
        """Collect txs in IWRR order under byte/gas budgets
        (clist_mempool.go ReapMaxBytesMaxGas)."""
        total_bytes = 0
        total_gas = 0
        out: list[bytes] = []
        for entry in self._snapshot_iter():
            sz = proto_tx_overhead(entry.tx)
            if max_bytes > -1 and total_bytes + sz > max_bytes:
                break
            if max_gas > -1 and total_gas + entry.gas_wanted > max_gas:
                break
            total_bytes += sz
            total_gas += entry.gas_wanted
            out.append(entry.tx)
        return out

    def reap_max_txs(self, max_txs: int) -> list[bytes]:
        out = []
        for entry in self._snapshot_iter():
            if max_txs > -1 and len(out) >= max_txs:
                break
            out.append(entry.tx)
        return out

    # ------------------------------------------------------ commit protocol

    def lock(self) -> None:
        self._update_mtx.acquire()

    def unlock(self) -> None:
        self._update_mtx.release()

    def flush_app_conn(self) -> None:
        self.proxy_app.flush()

    def flush(self) -> None:
        with self._mtx:
            for q in self.lanes.values():
                q.clear()
            self._tx_index.clear()
            self._bytes = 0
        self.cache.reset()

    def remove_tx_by_key(self, key: bytes) -> None:
        with self._mtx:
            self._remove_locked(key)

    def _remove_locked(self, key: bytes) -> None:
        lane = self._tx_index.pop(key, None)
        if lane is None:
            return
        entry = self.lanes[lane].pop(key, None)
        if entry is not None:
            self._bytes -= len(entry.tx)

    def update(
        self,
        height: int,
        txs: list[bytes],
        tx_results: list[pb.ExecTxResult],
        pre_check: Callable[[bytes], None] | None = None,
    ) -> None:
        """Remove committed txs, refresh the cache, recheck what remains
        (clist_mempool.go Update; caller holds lock())."""
        self.height = height
        self._notified_this_height = False
        if pre_check is not None:
            self.pre_check = pre_check
        with self._mtx:
            for tx, res in zip(txs, tx_results):
                key = key_of(tx)
                if res.code == 0:
                    self.cache.push(key)  # committed: never re-admit
                elif not self.config.keep_invalid_txs_in_cache:
                    self.cache.remove(key)
                self._remove_locked(key)
            remaining = [e for q in self.lanes.values() for e in q.values()]
        if self.config.recheck and remaining:
            self._recheck(remaining)
        with self._mtx:
            if self._tx_index:
                self._maybe_notify()
            else:
                self._txs_available.clear()

    def _recheck(self, entries: list[TxEntry]) -> None:
        from ..utils import healthmon
        from ..utils.metrics import hub as _mhub

        _mhub().mp_recheck_times.inc(len(entries))
        for entry in entries:
            # event-driven loop: registered informational (no deadline)
            # in the health registry — the per-entry beat makes a recheck
            # wedged on the app connection visible by its growing age
            healthmon.beat("mempool-recheck")
            try:
                res = self.proxy_app.check_tx(
                    pb.CheckTxRequest(tx=entry.tx, type=pb.CHECK_TX_TYPE_RECHECK)
                )
            except Exception as e:  # noqa: BLE001 - conn failure drops recheck
                self.logger.error(f"recheck failed: {e}")
                return
            if res.code != 0:
                with self._mtx:
                    self._remove_locked(entry.key)
                if not self.config.keep_invalid_txs_in_cache:
                    self.cache.remove(entry.key)

    # -------------------------------------------------------- notifications

    def wait_new_tx(self, last_seq: int, timeout: float) -> int:
        """Block until a tx has been admitted after sequence point
        last_seq (or timeout); returns the current sequence point."""
        with self._add_cond:
            if self._add_seq == last_seq:
                self._add_cond.wait(timeout)
            return self._add_seq

    def add_seq(self) -> int:
        with self._mtx:
            return self._add_seq

    def txs_available(self) -> threading.Event:
        return self._txs_available

    def enable_txs_available(self) -> None:
        self._notify_available = True

    def _maybe_notify(self) -> None:
        if self._notify_available and not self._notified_this_height:
            self._notified_this_height = True
            self._txs_available.set()
