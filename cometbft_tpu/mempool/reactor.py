"""Mempool reactor: transaction gossip between nodes.

Reference: mempool/reactor.go (broadcastTxRoutine :331) and
mempool/iterators.go (BlockingIterator).  Same protocol — one stream
carrying `Txs` batches, one broadcast routine per peer, sender dedup,
lag-aware throttling, wait-sync gating released by the blocksync handoff
(EnableInOutTxs) — but the iteration is redesigned: instead of the
reference's concurrent-linked-list cursors, each peer routine walks IWRR
snapshots of the lanes and tracks what it already offered, blocking on
the mempool's admission sequence point when it drains.  Snapshots fit the
GIL-serialized runtime better than fine-grained clist locking, and keep
the mempool's internals free of per-peer state.
"""

from __future__ import annotations

import threading
import time

from ..p2p.conn.connection import StreamDescriptor
from ..p2p.reactor import Reactor
from ..types.msg_validation import validate_mempool_message
from ..utils.log import get_logger
from ..wire import mempool_pb as pb
from .clist_mempool import CListMempool, TxEntry
from .mempool import MempoolError

MEMPOOL_STREAM = 0x30

PEER_CATCHUP_SLEEP = 0.1  # reactor.go PeerCatchupSleepIntervalMS
SEND_RETRY_SLEEP = 0.05
DRAIN_WAIT = 0.5


class BlockingTxIterator:
    """Per-peer blocking IWRR iteration (iterators.go BlockingIterator,
    snapshot-based).  next() yields each live entry once, in lane-priority
    order, blocking on the mempool's admission feed when everything
    current has been offered."""

    def __init__(self, mempool: CListMempool):
        self._mempool = mempool
        self._offered: set[bytes] = set()
        self._seq = mempool.add_seq() - 1  # there may be pre-existing txs
        self._snap = None  # current IWRR snapshot iterator

    def __iter_snapshot(self):
        self._snap = self._mempool.iter_entries()

    def next(self, keep_going) -> TxEntry | None:
        """Return the next not-yet-offered entry; None when keep_going()
        turns false.  Blocks while the mempool has nothing new.

        One snapshot is walked to exhaustion (O(1) amortized per tx, like
        the reference's clist cursor) and re-cut only on the drain/wait
        path — never per yielded entry."""
        while keep_going():
            if self._snap is None:
                self.__iter_snapshot()
            for entry in self._snap:
                if entry.key not in self._offered:
                    self._offered.add(entry.key)
                    return entry
            # snapshot exhausted: prune bookkeeping to live txs, then wait
            # for the next admission before re-cutting
            self._snap = None
            with self._mempool._mtx:
                self._offered &= set(self._mempool._tx_index)
            self._seq = self._mempool.wait_new_tx(self._seq, DRAIN_WAIT)
        return None

    def retract(self, key: bytes) -> None:
        """Forget that an entry was offered (send failed; retry later)."""
        self._offered.discard(key)


class MempoolReactor(Reactor):
    def __init__(self, mempool: CListMempool, wait_sync: bool = False):
        super().__init__("MempoolReactor")
        self.mempool = mempool
        self.logger = get_logger("mempool-reactor")
        self._wait_sync = wait_sync
        self._in_out_enabled = threading.Event()
        if not wait_sync:
            self._in_out_enabled.set()

    # ------------------------------------------------------------- config

    def stream_descriptors(self) -> list[StreamDescriptor]:
        return [
            StreamDescriptor(
                id=MEMPOOL_STREAM, priority=5, send_queue_capacity=100
            )
        ]

    def wait_sync(self) -> bool:
        return self._wait_sync

    def enable_in_out_txs(self) -> None:
        """Blocksync/statesync caught up: open the tx firehose
        (reactor.go EnableInOutTxs)."""
        if not self._wait_sync:
            return
        self.logger.info("enabling inbound and outbound transactions")
        self._wait_sync = False
        self._in_out_enabled.set()

    # -------------------------------------------------------------- peers

    def add_peer(self, peer) -> None:
        if self.mempool.config.broadcast:
            threading.Thread(
                target=self._broadcast_tx_routine, args=(peer,), daemon=True,
                name=f"mp-broadcast-{peer.id[:8]}",
            ).start()

    # ------------------------------------------------------------ receive

    def receive(self, stream_id: int, peer, msg_bytes: bytes) -> None:
        if self._wait_sync:
            return  # syncing: inbound txs would only be rechecked away
        msg = pb.MempoolMessage.decode(msg_bytes)
        # validate-before-use: empty batches and oversized batches are
        # protocol violations; a raise here disconnects the peer
        validate_mempool_message(msg)
        for tx in msg.txs.txs:
            try:
                self.mempool.check_tx(tx, sender=peer.id)
            except MempoolError:
                pass  # duplicate / full / app-rejected: normal gossip noise
            except Exception as e:  # noqa: BLE001
                self.logger.error(f"check_tx from {peer.id}: {e}")

    # ---------------------------------------------------------- broadcast

    def _broadcast_tx_routine(self, peer) -> None:
        """One per peer (reactor.go:331): stream every mempool entry the
        peer hasn't sent us, pacing by the peer's consensus height."""
        if not peer.has_channel(MEMPOOL_STREAM):
            return  # peer runs no mempool reactor: nothing to stream
        while self._wait_sync:
            if not self._in_out_enabled.wait(timeout=0.5):
                if not (self.is_running() and peer.is_running()):
                    return

        alive = lambda: self.is_running() and peer.is_running()
        it = BlockingTxIterator(self.mempool)
        while alive():
            entry = it.next(alive)
            if entry is None:
                return
            # lag gating (RFC 103): hold txs for peers >1 block behind the
            # height the tx entered at, so catching-up peers aren't flooded
            while alive():
                ps = peer.get("consensus_peer_state")
                if ps is None or ps.height + 1 >= entry.height:
                    break
                time.sleep(PEER_CATCHUP_SLEEP)
            if not alive():
                return
            if peer.id in entry.senders:
                continue  # the peer gave us this tx
            if not self.mempool.contains(entry.key):
                continue  # committed/evicted since the snapshot
            wire = pb.MempoolMessage(txs=pb.Txs(txs=[entry.tx])).encode()
            if not peer.send(MEMPOOL_STREAM, wire):
                it.retract(entry.key)
                time.sleep(SEND_RETRY_SLEEP)
