"""Mempool interface + errors (reference: mempool/mempool.go:27-90,
mempool/errors.go).
"""

from __future__ import annotations

from typing import Callable, Iterable

from ..types.tx import tx_hash


class MempoolError(Exception):
    pass


class TxInCacheError(MempoolError):
    def __init__(self):
        super().__init__("tx already exists in cache")


class TxInMempoolError(MempoolError):
    def __init__(self):
        super().__init__("tx already exists in mempool")


class MempoolFullError(MempoolError):
    def __init__(self, num_txs: int, total_bytes: int):
        super().__init__(
            f"mempool is full: number of txs {num_txs}, total bytes {total_bytes}"
        )
        self.num_txs = num_txs
        self.total_bytes = total_bytes


class PreCheckError(MempoolError):
    pass


class InvalidTxSignatureError(MempoolError):
    """The tx carries the signed-tx envelope (verifysvc/checktx) and its
    ed25519 signature does not verify — rejected before the app ever
    sees it."""

    def __init__(self):
        super().__init__("invalid tx signature (ed25519 envelope)")
        self.code = -2  # node-side rejection, distinct from app codes


class AppCheckError(MempoolError):
    """CheckTx returned a non-OK code (mempool.ErrInvalidTx)."""

    def __init__(self, code: int, log: str = "", codespace: str = ""):
        super().__init__(f"application rejected tx: code {code} log {log!r}")
        self.code = code
        self.log = log
        self.codespace = codespace


def PreCheckMaxBytes(max_bytes: int) -> Callable[[bytes], None]:
    """Pre-check rejecting txs larger than the per-tx byte cap
    (mempool.PreCheckMaxBytes)."""

    def check(tx: bytes) -> None:
        if len(tx) > max_bytes:
            raise PreCheckError(f"tx size {len(tx)} exceeds max {max_bytes}")

    return check


class Mempool:
    """The interface the consensus engine consumes (mempool.go:27)."""

    def check_tx(self, tx: bytes, sender: str = "") -> None:
        """Validate tx against the app and admit it; raises MempoolError."""
        raise NotImplementedError

    def remove_tx_by_key(self, key: bytes) -> None:
        raise NotImplementedError

    def reap_max_bytes_max_gas(self, max_bytes: int, max_gas: int) -> list[bytes]:
        raise NotImplementedError

    def reap_max_txs(self, max_txs: int) -> list[bytes]:
        raise NotImplementedError

    def lock(self) -> None:
        raise NotImplementedError

    def unlock(self) -> None:
        raise NotImplementedError

    def update(
        self,
        height: int,
        txs: list[bytes],
        tx_results: list,
        pre_check: Callable[[bytes], None] | None = None,
    ) -> None:
        """Called by the executor with the committed block's txs while the
        mempool is locked."""
        raise NotImplementedError

    def flush_app_conn(self) -> None:
        raise NotImplementedError

    def flush(self) -> None:
        raise NotImplementedError

    def size(self) -> int:
        raise NotImplementedError

    def size_bytes(self) -> int:
        raise NotImplementedError

    def txs_available(self):
        """threading.Event fired once per height when txs become available."""
        raise NotImplementedError

    def enable_txs_available(self) -> None:
        raise NotImplementedError

    def contains(self, key: bytes) -> bool:
        raise NotImplementedError

    def iter_txs(self) -> Iterable[bytes]:
        """Snapshot iteration in gossip order (lane-aware)."""
        raise NotImplementedError


def key_of(tx: bytes) -> bytes:
    return tx_hash(tx)
