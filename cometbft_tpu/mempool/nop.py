"""No-op mempool for apps that disseminate txs themselves
(reference: mempool/nop_mempool.go).
"""

from __future__ import annotations

import threading

from .mempool import Mempool, MempoolError


class TxsNotAvailableError(MempoolError):
    def __init__(self):
        super().__init__("mempool does not support tx availability")


class NopMempool(Mempool):
    def check_tx(self, tx: bytes, sender: str = "") -> None:
        raise MempoolError("tx rejected: nop mempool does not accept txs")

    def remove_tx_by_key(self, key: bytes) -> None:
        pass

    def reap_max_bytes_max_gas(self, max_bytes: int, max_gas: int) -> list[bytes]:
        return []

    def reap_max_txs(self, max_txs: int) -> list[bytes]:
        return []

    def lock(self) -> None:
        pass

    def unlock(self) -> None:
        pass

    def update(self, height, txs, tx_results, pre_check=None) -> None:
        pass

    def flush_app_conn(self) -> None:
        pass

    def flush(self) -> None:
        pass

    def size(self) -> int:
        return 0

    def size_bytes(self) -> int:
        return 0

    def txs_available(self) -> threading.Event:
        return threading.Event()  # never set

    def enable_txs_available(self) -> None:
        pass

    def contains(self, key: bytes) -> bool:
        return False

    def iter_txs(self):
        return iter(())
