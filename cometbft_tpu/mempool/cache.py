"""Tx caches (reference: mempool/cache.go).

LRUTxCache remembers recently seen tx keys so repeated broadcasts don't
hit the app's CheckTx again; NopTxCache disables caching.
"""

from __future__ import annotations

import threading
from collections import OrderedDict


class LRUTxCache:
    def __init__(self, size: int):
        self._size = size
        self._map: OrderedDict[bytes, None] = OrderedDict()
        self._mtx = threading.Lock()

    def reset(self) -> None:
        with self._mtx:
            self._map.clear()

    def push(self, key: bytes) -> bool:
        """Returns False if the key was already present (it is refreshed)."""
        with self._mtx:
            if key in self._map:
                self._map.move_to_end(key)
                return False
            self._map[key] = None
            if len(self._map) > self._size:
                self._map.popitem(last=False)
            return True

    def remove(self, key: bytes) -> None:
        with self._mtx:
            self._map.pop(key, None)

    def has(self, key: bytes) -> bool:
        with self._mtx:
            return key in self._map

    def __len__(self):
        with self._mtx:
            return len(self._map)


class NopTxCache:
    def reset(self) -> None:
        pass

    def push(self, key: bytes) -> bool:
        return True

    def remove(self, key: bytes) -> None:
        pass

    def has(self, key: bytes) -> bool:
        return False

    def __len__(self):
        return 0
