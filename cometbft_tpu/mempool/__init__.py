"""Mempool: pending-transaction pool with priority lanes
(reference: mempool/).
"""

from .mempool import (
    InvalidTxSignatureError,
    Mempool,
    MempoolError,
    TxInCacheError,
    MempoolFullError,
    PreCheckMaxBytes,
)
from .clist_mempool import CListMempool, MempoolConfig
from .reactor import MempoolReactor, MEMPOOL_STREAM
from .nop import NopMempool
from .cache import LRUTxCache, NopTxCache

__all__ = [
    "InvalidTxSignatureError",
    "Mempool",
    "MempoolError",
    "TxInCacheError",
    "MempoolFullError",
    "PreCheckMaxBytes",
    "CListMempool",
    "MempoolConfig",
    "MempoolReactor",
    "MEMPOOL_STREAM",
    "NopMempool",
    "LRUTxCache",
    "NopTxCache",
]
