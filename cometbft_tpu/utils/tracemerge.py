"""Stitch per-process Chrome trace exports into ONE Perfetto timeline.

Every process's tracer (utils/tracing) exports timestamps from its own
``perf_counter_ns`` epoch — two processes' exports cannot be overlaid
directly.  But each export leads with a ``wall_clock_anchor`` metadata
record: one (wall_time_ns, perf_counter_ns) pair sampled at export
time, giving the correlation

    wall_ns(event) = wall_time_ns + (event.ts * 1000 - perf_counter_ns)

This module rebases every export onto the wall clock, shifts the merged
timeline to start at zero (Perfetto dislikes 53-bit microsecond
timestamps), namespaces each export under its own pid (collisions —
pid reuse, or the same process exported twice — are remapped to a
synthetic pid), labels each process track, and reports the per-export
**anchor skew**: on one host ``wall_time_ns - perf_counter_ns`` should
be (nearly) the same constant in every process, so the spread between
exports measures wall-clock adjustment/jitter between their export
moments — a large skew means cross-process span alignment is only
trustworthy to that bound.

Cross-process *causality* doesn't rely on timestamps at all: spans
recorded under a propagated :class:`~.tracing.SpanContext` carry
``trace_id`` args, so a consensus-side verify span and the plane's
server-side span link by id however the clocks sit.

``scripts/trace_merge.py`` is the CLI; the chaos scenarios and the soak
engine call :func:`merge_files` directly when ``COMETBFT_TPU_TRACE`` is
armed.
"""

from __future__ import annotations

import json
import os

ANCHOR_NAME = "wall_clock_anchor"


class MergeError(ValueError):
    """An input export is unusable (no events / no anchor)."""


def _load_events(path: str) -> list[dict]:
    with open(path) as f:
        doc = json.load(f)
    if isinstance(doc, dict):
        events = doc.get("traceEvents", [])
    else:
        events = doc  # bare-array form of the trace-event format
    if not isinstance(events, list):
        raise MergeError(f"{path}: traceEvents is not a list")
    return events


def _find_anchor(events: list[dict], path: str) -> tuple[int, int]:
    for e in events:
        if e.get("ph") == "M" and e.get("name") == ANCHOR_NAME:
            args = e.get("args", {})
            try:
                return int(args["wall_time_ns"]), int(args["perf_counter_ns"])
            except (KeyError, TypeError, ValueError):
                raise MergeError(f"{path}: malformed {ANCHOR_NAME} record")
    raise MergeError(f"{path}: no {ANCHOR_NAME} record (not a tracing.py export?)")


def merge_exports(
    exports: list[tuple[str, list[dict]]],
) -> tuple[dict, dict]:
    """Merge ``[(label, events), ...]`` into one timeline.

    Returns ``(merged_doc, report)``: ``merged_doc`` is a Perfetto-
    loadable ``{"traceEvents": [...]}`` dict; ``report`` carries per-
    label pid assignment, event counts, and anchor skew in ns relative
    to the earliest-offset export."""
    if not exports:
        raise MergeError("nothing to merge")
    prepared = []
    for label, events in exports:
        wall_ns, perf_ns = _find_anchor(events, label)
        pid = None
        for e in events:
            if "pid" in e:
                pid = e["pid"]
                break
        prepared.append({
            "label": label,
            "events": events,
            "offset_ns": wall_ns - perf_ns,  # perf epoch -> wall epoch
            "pid": pid if pid is not None else 0,
        })
    base_offset = min(p["offset_ns"] for p in prepared)
    # zero point: the earliest rebased event start across all exports
    t0_ns = None
    for p in prepared:
        for e in p["events"]:
            if e.get("ph") == "M":
                continue
            wall = p["offset_ns"] + int(e.get("ts", 0) * 1000)
            if t0_ns is None or wall < t0_ns:
                t0_ns = wall
    if t0_ns is None:
        raise MergeError("no span/instant events in any export")

    used_pids: set[int] = set()
    out: list[dict] = []
    report: dict = {"processes": [], "t0_wall_ns": t0_ns}
    synth = 1 << 20  # synthetic pid range, above any real Linux pid
    for p in prepared:
        pid = p["pid"]
        remapped = pid in used_pids
        if remapped:
            while synth in used_pids:
                synth += 1
            pid = synth
        used_pids.add(pid)
        name = os.path.basename(p["label"])
        out.append({
            "ph": "M", "name": "process_name", "pid": pid, "tid": 0,
            "args": {"name": name + (" (pid remapped)" if remapped else "")},
        })
        n = 0
        for e in p["events"]:
            e = dict(e)
            e["pid"] = pid
            if e.get("ph") == "M":
                if e.get("name") == ANCHOR_NAME:
                    continue  # superseded by the merge's common timeline
                out.append(e)
                continue
            wall = p["offset_ns"] + int(e.get("ts", 0) * 1000)
            e["ts"] = (wall - t0_ns) / 1e3
            out.append(e)
            n += 1
        report["processes"].append({
            "label": p["label"],
            "pid": pid,
            "pid_remapped": remapped,
            "events": n,
            "anchor_skew_ns": p["offset_ns"] - base_offset,
        })
    out.sort(key=lambda e: (e.get("ph") != "M", e.get("ts", 0)))
    merged = {
        "traceEvents": out,
        "displayTimeUnit": "ms",
        "otherData": {
            "merged_from": [p["label"] for p in prepared],
            "anchor_skew_ns": {
                r["label"]: r["anchor_skew_ns"] for r in report["processes"]
            },
        },
    }
    return merged, report


def merge_files(paths: list[str], out_path: str) -> dict:
    """Merge export files into ``out_path``; returns the report.  Files
    that fail to load/anchor are skipped and listed under
    ``report["skipped"]`` — a crashed process's torn half-written export
    must not cost the timeline of every healthy one."""
    exports = []
    skipped = []
    for path in paths:
        try:
            exports.append((path, _load_events(path)))
        except (OSError, ValueError) as e:
            skipped.append({"label": path, "error": str(e)})
    merged, report = merge_exports(exports)
    report["skipped"] = skipped
    with open(out_path, "w") as f:
        json.dump(merged, f)
    report["out"] = out_path
    report["total_events"] = sum(p["events"] for p in report["processes"])
    return report
