"""Flow-rate measurement and throttling (reference: internal/flowrate).

Monitor tracks transfer progress over a sliding exponentially-weighted
window and reports the current rate; Limiter adds a blocking throttle to a
target rate.  Used by blocksync peer health checks (pool.go minRecvRate)
and MConnection send/recv rate caps (connection.go:40-41).
"""

from __future__ import annotations

import threading
import time


class Monitor:
    """EWMA transfer-rate monitor (flowrate.Monitor, simplified: the
    reference resamples at a fixed period; we fold each update into an
    exponential moving average over `window` seconds)."""

    def __init__(self, window: float = 1.0):
        self._window = window
        self._mtx = threading.Lock()
        self.reset()

    def reset(self) -> None:
        with getattr(self, "_mtx", threading.Lock()):
            self._start = time.monotonic()
            self._last = self._start
            self._total = 0
            self._rate = 0.0  # bytes/sec EWMA

    def set_rate(self, rate: float) -> None:
        """Seed the EWMA (pool.go resetMonitor SetREMA equivalent)."""
        with self._mtx:
            self._rate = rate

    def update(self, n: int) -> None:
        now = time.monotonic()
        with self._mtx:
            dt = now - self._last
            self._last = now
            self._total += n
            if dt <= 0:
                return
            inst = n / dt
            alpha = min(1.0, dt / self._window)
            self._rate += alpha * (inst - self._rate)

    @property
    def total(self) -> int:
        with self._mtx:
            return self._total

    def rate(self) -> float:
        """Current bytes/sec estimate, decayed if no recent updates."""
        now = time.monotonic()
        with self._mtx:
            idle = now - self._last
            if idle > self._window:
                # no traffic for over a window: decay toward zero
                return self._rate * self._window / idle
            return self._rate


class Limiter(Monitor):
    """Monitor + blocking throttle to `limit` bytes/sec (flowrate's
    Limit(want, rate, block=true) usage in MConnection send/recv loops).

    Token bucket with ~one second of burst capacity: idle time earns
    credit only up to `limit` bytes, so a connection that sat quiet for
    an hour cannot cash the backlog in as an unthrottled flood (the
    since-start quota the first version used had exactly that hole)."""

    def __init__(self, limit: int, window: float = 1.0):
        super().__init__(window)
        self.limit = limit
        self._tokens = float(limit)
        self._refill_at = time.monotonic()

    def throttle(self, n: int) -> None:
        """Account n bytes; sleep until the bucket covers them."""
        if self.limit <= 0:  # unlimited
            self.update(n)
            return
        now = time.monotonic()
        with self._mtx:
            self._tokens = min(
                float(self.limit),
                self._tokens + (now - self._refill_at) * self.limit,
            )
            self._refill_at = now
            self._tokens -= n
            self._total += n
            self._last = now
            sleep = -self._tokens / self.limit if self._tokens < 0 else 0.0
            self._rate = float(self.limit) if sleep > 0 else self._rate
        if sleep > 0:
            time.sleep(min(sleep, 10.0))
