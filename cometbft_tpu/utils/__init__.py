"""L0 base utilities (reference: libs/ — service lifecycle, logging,
pubsub, bit arrays)."""

from .service import Service, ServiceError
from .log import get_logger
