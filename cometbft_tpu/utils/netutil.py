"""Socket helpers shared by every socket-owning service."""

from __future__ import annotations

import socket


def close_socket(sock: socket.socket | None) -> None:
    """shutdown(SHUT_RDWR) then close().  close() alone does not wake a
    thread blocked in accept()/recv() on Linux — the fd stays blocked
    until traffic arrives — so every service teardown must shutdown
    first or it strands its IO threads."""
    if sock is None:
        return
    try:
        sock.shutdown(socket.SHUT_RDWR)
    except OSError:
        pass
    try:
        sock.close()
    except OSError:
        pass


class LatencyConn:
    """Test-only link shaping: delays every outbound write by
    delay_ms ± jitter_ms before it reaches the wrapped connection,
    preserving pipelining (writes are queued with delivery deadlines and
    drained by a pump thread, so latency does not serialize bandwidth).
    The e2e runner's analogue of the reference's tc-netem emulation
    (test/e2e/runner/latency_emulation.go), applied at the socket layer
    because the multi-process localnet shares one network namespace.
    Sender-side-only delay: a link's RTT is the sum of both ends'
    configured delays.
    """

    def __init__(self, inner, delay_ms: float, jitter_ms: float = 0.0):
        import queue
        import random
        import threading
        import time

        self._inner = inner
        self._delay = max(0.0, delay_ms) / 1e3
        self._jitter = max(0.0, jitter_ms) / 1e3
        self._rand = random.Random()
        self._q: "queue.Queue" = queue.Queue()
        self._time = time
        self._closed = False
        self._dead = False  # pump hit a write error: surface it to senders
        self._pump_thread = threading.Thread(
            target=self._pump, daemon=True, name="latency-conn"
        )
        self._pump_thread.start()

    def _pump(self) -> None:
        while True:
            item = self._q.get()
            if item is None:
                return
            deliver_at, data = item
            wait = deliver_at - self._time.monotonic()
            if wait > 0:
                self._time.sleep(wait)
            try:
                self._inner.write(data)
            except Exception:  # noqa: BLE001 — conn died; senders must see it
                self._dead = True
                return

    def write(self, data: bytes) -> int:
        if self._closed or self._dead:
            raise OSError("connection closed")
        deliver_at = self._time.monotonic() + self._delay + (
            self._rand.random() * self._jitter
        )
        self._q.put((deliver_at, bytes(data)))
        return len(data)

    def read(self, n: int) -> bytes:
        return self._inner.read(n)

    def close(self) -> None:
        # flush: writes already acknowledged to the caller must reach the
        # wire before the inner conn closes (bounded by the max shaping
        # delay; a dead pump skips the wait)
        self._closed = True
        self._q.put(None)
        if not self._dead:
            self._pump_thread.join(timeout=self._delay + self._jitter + 1.0)
        self._inner.close()


def maybe_shape_latency(conn):
    """Wrap conn in LatencyConn when COMETBFT_TPU_TEST_LATENCY_MS is set
    (value 'delay' or 'delay:jitter', milliseconds).  Production nodes
    never set it; the e2e runner sets it per node process."""
    from . import envknobs

    spec = envknobs.get_str(envknobs.TEST_LATENCY_MS)
    if not spec:
        return conn
    try:
        if ":" in spec:
            d, j = spec.split(":", 1)
            return LatencyConn(conn, float(d), float(j))
        return LatencyConn(conn, float(spec))
    except ValueError:
        return conn
