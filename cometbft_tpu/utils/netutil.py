"""Socket helpers shared by every socket-owning service."""

from __future__ import annotations

import socket


def close_socket(sock: socket.socket | None) -> None:
    """shutdown(SHUT_RDWR) then close().  close() alone does not wake a
    thread blocked in accept()/recv() on Linux — the fd stays blocked
    until traffic arrives — so every service teardown must shutdown
    first or it strands its IO threads."""
    if sock is None:
        return
    try:
        sock.shutdown(socket.SHUT_RDWR)
    except OSError:
        pass
    try:
        sock.close()
    except OSError:
        pass
