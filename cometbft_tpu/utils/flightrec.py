"""Consensus flight recorder: a bounded ring of recent state-machine
events — step transitions, vote/proposal arrivals, timeout fires,
watchdog re-kicks — tagged with height/round/step and wall-clock time.

The recorder is always on (recording is one lock + one dict + one
bounded append; the consensus loop already pays a WAL write per input)
so that when a node wedges or crashes, the last N events are available
without having had to anticipate the incident: on demand via the
`/dump_consensus_trace` RPC (rpc/core.py) and automatically in the
crash report utils/debugdump.crash_report writes when the consensus
receive routine dies.

This is the black-box analogue of the reference's `dump_consensus_state`
deep-dump, but *temporal*: not "where is the machine now" but "what were
the last 1024 things that happened to it".
"""

from __future__ import annotations

import threading
import time
from collections import deque


class FlightRecorder:
    """Bounded rings of consensus events.  Thread-safe; eviction counts
    are kept so a dump says how much history scrolled away.

    High-rate per-signature events (vote arrivals: ~2·V per height, so
    ~20k/height at the 10k-validator target scale) go to their OWN ring —
    otherwise one height of votes would evict every step/timeout/
    proposal/watchdog entry and the black-box would be blind to exactly
    the state-machine transitions it exists to capture."""

    HIGH_RATE_KINDS = frozenset({"vote"})

    def __init__(self, capacity: int = 1024, vote_capacity: int | None = None):
        self._ring: deque[dict] = deque(maxlen=max(1, capacity))
        self._votes: deque[dict] = deque(
            maxlen=max(1, capacity if vote_capacity is None else vote_capacity)
        )
        self._mtx = threading.Lock()
        self._seq = 0
        self._evicted = 0
        self._votes_evicted = 0

    @property
    def capacity(self) -> int:
        return self._ring.maxlen

    def record(
        self,
        kind: str,
        height: int = 0,
        round: int = -1,
        step: int = -1,
        **detail,
    ) -> None:
        e = {
            "kind": kind,
            "height": height,
            "round": round,
            "step": step,
            "wall_ns": time.time_ns(),
            "mono_ns": time.perf_counter_ns(),
        }
        if detail:
            e["detail"] = detail
        with self._mtx:
            self._seq += 1
            e["seq"] = self._seq
            if kind in self.HIGH_RATE_KINDS:
                if len(self._votes) == self._votes.maxlen:
                    self._votes_evicted += 1
                self._votes.append(e)
            else:
                if len(self._ring) == self._ring.maxlen:
                    self._evicted += 1
                self._ring.append(e)

    def dump(self) -> dict:
        """Snapshot, oldest first (both rings merged in arrival order):
        {"entries": [...], "count", "evicted", "votes_evicted",
        "capacity", "vote_capacity"} — JSON-serializable as-is (the RPC
        handler returns it verbatim)."""
        with self._mtx:
            entries = sorted(
                list(self._ring) + list(self._votes), key=lambda e: e["seq"]
            )
            return {
                "entries": entries,
                "count": len(entries),
                "evicted": self._evicted,
                "votes_evicted": self._votes_evicted,
                "capacity": self._ring.maxlen,
                "vote_capacity": self._votes.maxlen,
            }

    def clear(self) -> None:
        with self._mtx:
            self._ring.clear()
            self._votes.clear()
            self._evicted = 0
            self._votes_evicted = 0


def _capacity_from_env() -> int:
    from . import envknobs

    return max(1, envknobs.get_int(envknobs.FLIGHTREC))


_REC = FlightRecorder(_capacity_from_env())


def recorder() -> FlightRecorder:
    """The process-global recorder.  Multi-node test processes share it
    (like the metrics hub); entries carry height/round so interleaved
    nodes remain distinguishable, and the multi-process e2e harness
    gives each node its own."""
    return _REC
