"""Amino-compatible JSON with a type registry
(reference: libs/json/{types,encoder,decoder}.go).

Registered Go-style interface implementations serialize as
    {"type": "<registered name>", "value": <json>}
so genesis docs, privval files, and RPC payloads stay byte-compatible
with the reference's tooling.  Unregistered values pass through the
plain JSON encoder.
"""

from __future__ import annotations

import base64
import json
from typing import Any, Callable

_BY_NAME: dict[str, tuple[type, Callable, Callable]] = {}
_BY_TYPE: dict[type, str] = {}


class AminoJSONError(Exception):
    pass


def register_type(
    cls: type, name: str, encode: Callable[[Any], Any], decode: Callable[[Any], Any]
) -> None:
    """libs/json RegisterType: bind cls <-> its amino type name."""
    if name in _BY_NAME:
        raise AminoJSONError(f"type name {name!r} already registered")
    if cls in _BY_TYPE:
        raise AminoJSONError(f"class {cls.__name__} already registered")
    _BY_NAME[name] = (cls, encode, decode)
    _BY_TYPE[cls] = name


def marshal(value: Any, indent: int | None = None) -> str:
    return json.dumps(_encode(value), indent=indent)


def unmarshal(data: str | bytes) -> Any:
    return _decode(json.loads(data))


def _encode(value: Any) -> Any:
    t = type(value)
    if t in _BY_TYPE:
        name = _BY_TYPE[t]
        _, enc, _ = _BY_NAME[name]
        return {"type": name, "value": _encode(enc(value))}
    if isinstance(value, bytes):
        return base64.b64encode(value).decode()
    if isinstance(value, dict):
        return {k: _encode(v) for k, v in value.items()}
    if isinstance(value, (list, tuple)):
        return [_encode(v) for v in value]
    return value


def _decode(value: Any) -> Any:
    if isinstance(value, dict):
        if set(value.keys()) == {"type", "value"} and value["type"] in _BY_NAME:
            _, _, dec = _BY_NAME[value["type"]]
            return dec(_decode(value["value"]))
        return {k: _decode(v) for k, v in value.items()}
    if isinstance(value, list):
        return [_decode(v) for v in value]
    return value


# ---------------------------------------------------------------- registry
# The names the reference registers (crypto/encoding/codec.go + privval):


def _register_crypto() -> None:
    from ..crypto import ed25519

    register_type(
        ed25519.PubKey,
        "tendermint/PubKeyEd25519",
        lambda k: base64.b64encode(k.data).decode(),
        lambda v: ed25519.PubKey(base64.b64decode(v)),
    )
    register_type(
        ed25519.PrivKey,
        "tendermint/PrivKeyEd25519",
        lambda k: base64.b64encode(k.data).decode(),
        lambda v: ed25519.PrivKey(base64.b64decode(v)),
    )
    try:
        from ..crypto import secp256k1

        register_type(
            secp256k1.PubKey,
            "tendermint/PubKeySecp256k1",
            lambda k: base64.b64encode(k.data).decode(),
            lambda v: secp256k1.PubKey(base64.b64decode(v)),
        )
        register_type(
            secp256k1.PrivKey,
            "tendermint/PrivKeySecp256k1",
            lambda k: base64.b64encode(k.data).decode(),
            lambda v: secp256k1.PrivKey(base64.b64decode(v)),
        )
    except ImportError:
        pass
    try:
        from ..crypto import secp256k1eth

        register_type(
            secp256k1eth.PubKey,
            "cometbft/PubKeySecp256k1eth",
            lambda k: base64.b64encode(k.data).decode(),
            lambda v: secp256k1eth.PubKey(base64.b64decode(v)),
        )
        register_type(
            secp256k1eth.PrivKey,
            "cometbft/PrivKeySecp256k1eth",
            lambda k: base64.b64encode(k.data).decode(),
            lambda v: secp256k1eth.PrivKey(base64.b64decode(v)),
        )
    except ImportError:
        pass
    try:
        from ..crypto import bls12381

        register_type(
            bls12381.PubKey,
            "cometbft/PubKeyBls12_381",
            lambda k: base64.b64encode(k.data).decode(),
            lambda v: bls12381.PubKey(base64.b64decode(v)),
        )
        register_type(
            bls12381.PrivKey,
            "cometbft/PrivKeyBls12_381",
            lambda k: base64.b64encode(k.bytes()).decode(),
            lambda v: bls12381.PrivKey.from_bytes(base64.b64decode(v)),
        )
    except ImportError:
        pass


_register_crypto()
