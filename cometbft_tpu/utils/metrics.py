"""Metrics registry + Prometheus text exposition
(reference: libs/metrics + scripts/metricsgen codegen output, e.g.
internal/consensus/metrics.go:19).

A process-global Registry of counters/gauges/histograms with label
support; subsystems declare their metric sets declaratively (the
analogue of the reference's struct-tag codegen) and the node exposes
/metrics in the Prometheus text format.
"""

from __future__ import annotations

import threading
from bisect import bisect_left

_DEFAULT_BUCKETS = (
    0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0
)


class _Metric:
    def __init__(self, name: str, help_: str, registry: "Registry"):
        self.name = name
        self.help = help_
        self._mtx = threading.Lock()
        if registry is not None:
            registry._register(self)

    @staticmethod
    def _label_key(labels: dict | None) -> tuple:
        return tuple(sorted((labels or {}).items()))

    @staticmethod
    def _esc_label(v) -> str:
        """Label-value escaping per the Prometheus text format: backslash,
        double-quote, and line feed must be escaped or the exposition is
        unparseable (backslash FIRST, or the other escapes double up)."""
        return (
            str(v)
            .replace("\\", "\\\\")
            .replace('"', '\\"')
            .replace("\n", "\\n")
        )

    @classmethod
    def _fmt_labels(cls, key: tuple) -> str:
        if not key:
            return ""
        inner = ",".join(f'{k}="{cls._esc_label(v)}"' for k, v in key)
        return "{" + inner + "}"


class Counter(_Metric):
    TYPE = "counter"

    def __init__(self, name, help_="", registry=None):
        super().__init__(name, help_, registry)
        self._values: dict[tuple, float] = {}

    def inc(self, n: float = 1.0, **labels) -> None:
        k = self._label_key(labels)
        with self._mtx:
            self._values[k] = self._values.get(k, 0.0) + n

    def value(self, **labels) -> float:
        with self._mtx:
            return self._values.get(self._label_key(labels), 0.0)

    def expose(self) -> list[str]:
        with self._mtx:
            items = sorted(self._values.items())
        return [
            f"{self.name}{self._fmt_labels(k)} {v}"
            for k, v in (items or [((), 0.0)])
        ]


class Gauge(_Metric):
    TYPE = "gauge"

    def __init__(self, name, help_="", registry=None):
        super().__init__(name, help_, registry)
        self._values: dict[tuple, float] = {}

    def set(self, v: float, **labels) -> None:
        with self._mtx:
            self._values[self._label_key(labels)] = float(v)

    def add(self, n: float, **labels) -> None:
        k = self._label_key(labels)
        with self._mtx:
            self._values[k] = self._values.get(k, 0.0) + n

    def value(self, **labels) -> float:
        with self._mtx:
            return self._values.get(self._label_key(labels), 0.0)

    def remove(self, **labels) -> None:
        """Drop one labeled series (e.g. a retired loop's beat-age): a
        gauge for an entity that no longer exists must leave the
        exposition, not freeze at its last value forever."""
        with self._mtx:
            self._values.pop(self._label_key(labels), None)

    def expose(self) -> list[str]:
        with self._mtx:
            items = sorted(self._values.items())
        return [
            f"{self.name}{self._fmt_labels(k)} {v}"
            for k, v in (items or [((), 0.0)])
        ]


class Histogram(_Metric):
    TYPE = "histogram"

    def __init__(self, name, help_="", buckets=_DEFAULT_BUCKETS, registry=None):
        super().__init__(name, help_, registry)
        self.buckets = tuple(sorted(buckets))
        self._counts: dict[tuple, list[int]] = {}
        self._sums: dict[tuple, float] = {}
        self._totals: dict[tuple, int] = {}

    def observe(self, v: float, **labels) -> None:
        k = self._label_key(labels)
        with self._mtx:
            counts = self._counts.setdefault(k, [0] * len(self.buckets))
            # per-bucket increments; the cumulative form is produced at
            # expose time.  bisect_left = first bucket bound >= v; values
            # above every bound only count toward +Inf/sum/count.
            idx = bisect_left(self.buckets, v)
            if idx < len(self.buckets):
                counts[idx] += 1
            self._sums[k] = self._sums.get(k, 0.0) + v
            self._totals[k] = self._totals.get(k, 0) + 1

    def expose(self) -> list[str]:
        out = []
        with self._mtx:
            keys = sorted(self._counts) or [()]
            for k in keys:
                counts = self._counts.get(k, [0] * len(self.buckets))
                cum = 0
                for b, c in zip(self.buckets, counts):
                    cum += c
                    lk = k + (("le", str(b)),)
                    out.append(f"{self.name}_bucket{self._fmt_labels(lk)} {cum}")
                lk = k + (("le", "+Inf"),)
                out.append(
                    f"{self.name}_bucket{self._fmt_labels(lk)} "
                    f"{self._totals.get(k, 0)}"
                )
                out.append(
                    f"{self.name}_sum{self._fmt_labels(k)} {self._sums.get(k, 0.0)}"
                )
                out.append(
                    f"{self.name}_count{self._fmt_labels(k)} {self._totals.get(k, 0)}"
                )
        return out


class LabelGuard:
    """Bounded admission of label VALUES for one label dimension.

    Prometheus label values are unbounded series: a metric labeled by a
    caller-supplied id (the verify service's tenant) would let an
    unbounded id stream allocate one series per id and blow up the
    exposition.  The guard admits the first ``max_values`` distinct
    values verbatim and maps everything after onto the single
    ``__overflow__`` bucket, so the series count is capped no matter
    what ids arrive.  Admission is first-come sticky: a value once
    admitted keeps its own series for the life of the process.
    """

    OVERFLOW = "__overflow__"

    def __init__(self, max_values: int = 32):
        self.max_values = max(1, int(max_values))
        self._seen: set[str] = set()
        self._mtx = threading.Lock()
        self._overflowed = 0

    def bound(self, value) -> str:
        v = str(value)
        with self._mtx:
            if v in self._seen:
                return v
            if len(self._seen) < self.max_values:
                self._seen.add(v)
                return v
            self._overflowed += 1
            return self.OVERFLOW

    def overflowed(self) -> int:
        with self._mtx:
            return self._overflowed

    def admitted(self) -> int:
        with self._mtx:
            return len(self._seen)


class Registry:
    def __init__(self, namespace: str = "cometbft"):
        self.namespace = namespace
        self._metrics: list[_Metric] = []
        self._by_name: dict[str, _Metric] = {}
        self._mtx = threading.Lock()

    def _register(self, m: _Metric) -> None:
        """Direct registration (Metric(..., registry=r)): a duplicate name
        is a programming error — two instances exposing the same series
        with conflicting values produce an unscrapable /metrics."""
        with self._mtx:
            if m.name in self._by_name:
                raise ValueError(f"metric {m.name!r} already registered")
            self._by_name[m.name] = m
            self._metrics.append(m)

    def _get_or_make(self, full_name: str, cls, help_: str, **kw) -> _Metric:
        """The factory helpers are get-or-create: re-declaring a metric
        (e.g. two subsystems sharing one registry, or a re-constructed
        metric set on a shared hub) returns the ONE existing instance so
        the exposition never carries the name twice.  A re-declaration
        under a different metric type is a conflict and raises."""
        with self._mtx:
            existing = self._by_name.get(full_name)
            if existing is not None:
                if type(existing) is not cls:
                    raise ValueError(
                        f"metric {full_name!r} already registered as "
                        f"{type(existing).__name__}, not {cls.__name__}"
                    )
                if "buckets" in kw and existing.buckets != tuple(
                    sorted(kw["buckets"])
                ):
                    # silently keeping the first declaration's bounds would
                    # bin the second caller's observations wrongly
                    raise ValueError(
                        f"histogram {full_name!r} re-declared with different "
                        f"buckets: {existing.buckets} vs {kw['buckets']}"
                    )
                return existing
            m = cls(full_name, help_, registry=None, **kw)
            self._by_name[full_name] = m
            self._metrics.append(m)
            return m

    def counter(self, name: str, help_: str = "") -> Counter:
        return self._get_or_make(f"{self.namespace}_{name}", Counter, help_)

    def gauge(self, name: str, help_: str = "") -> Gauge:
        return self._get_or_make(f"{self.namespace}_{name}", Gauge, help_)

    def histogram(self, name: str, help_: str = "", buckets=_DEFAULT_BUCKETS) -> Histogram:
        return self._get_or_make(
            f"{self.namespace}_{name}", Histogram, help_, buckets=buckets
        )

    def expose_text(self) -> str:
        """Prometheus text format v0.0.4."""
        lines = []
        with self._mtx:
            metrics = list(self._metrics)
        for m in metrics:
            if m.help:
                lines.append(f"# HELP {m.name} {m.help}")
            lines.append(f"# TYPE {m.name} {m.TYPE}")
            lines.extend(m.expose())
        return "\n".join(lines) + "\n"


class Hub:
    """Process-global per-package metric sets, mirroring the reference's
    metricsgen output per package (internal/consensus/metrics.go:33,
    mempool/metrics.go, p2p/metrics.go, store metrics).  Subsystems call
    sites hit these directly — no constructor plumbing — and the node
    exposes the hub's registry on /metrics.  In multi-node test
    processes the nodes share one hub (the multi-process e2e harness
    gives each node its own process, hence its own hub).
    """

    def __init__(self, registry: Registry | None = None):
        self.registry = registry if registry is not None else Registry()
        r = self.registry
        # ---- consensus (internal/consensus/metrics.go:33)
        self.cs_round_duration = r.histogram(
            "consensus_round_duration_seconds",
            "Time spent in a consensus round",
            buckets=(0.1, 0.5, 1, 2, 4, 8, 16, 32, 64),
        )
        self.cs_validators_power = r.gauge(
            "consensus_validators_power", "Total voting power of the validator set"
        )
        self.cs_missing_validators = r.gauge(
            "consensus_missing_validators",
            "Validators absent from the last commit",
        )
        self.cs_missing_validators_power = r.gauge(
            "consensus_missing_validators_power",
            "Voting power absent from the last commit",
        )
        self.cs_proposal_create_count = r.counter(
            "consensus_proposal_create_count", "Proposals created by this node"
        )
        self.cs_proposal_receive_count = r.counter(
            "consensus_proposal_receive_count",
            "Proposals received (label status=accepted|rejected)",
        )
        self.cs_block_size_bytes = r.gauge(
            "consensus_block_size_bytes", "Size of the latest block"
        )
        self.cs_late_votes = r.counter(
            "consensus_late_votes", "Votes for earlier heights (label vote_type)"
        )
        self.cs_duplicate_vote = r.counter(
            "consensus_duplicate_vote", "Exact-duplicate votes received"
        )
        self.cs_duplicate_block_part = r.counter(
            "consensus_duplicate_block_part", "Duplicate block parts received"
        )
        # ---- mempool (mempool/metrics.go)
        self.mp_tx_size_bytes = r.histogram(
            "mempool_tx_size_bytes",
            "Accepted tx sizes",
            buckets=(32, 128, 512, 1024, 4096, 16384, 65536, 262144, 1048576),
        )
        self.mp_failed_txs = r.counter(
            "mempool_failed_txs", "Txs rejected by CheckTx"
        )
        self.mp_evicted_txs = r.counter(
            "mempool_evicted_txs", "Txs evicted (full mempool / TTL)"
        )
        self.mp_recheck_times = r.counter(
            "mempool_recheck_times", "Txs re-checked after a block"
        )
        self.mp_already_received_txs = r.counter(
            "mempool_already_received_txs", "Duplicate txs offered"
        )
        # ---- p2p (p2p/metrics.go)
        self.p2p_send_bytes = r.counter(
            "p2p_message_send_bytes_total", "Bytes sent (label ch_id)"
        )
        self.p2p_recv_bytes = r.counter(
            "p2p_message_receive_bytes_total", "Bytes received (label ch_id)"
        )
        self.p2p_send_count = r.counter(
            "p2p_message_send_count", "Complete messages sent (label ch_id)"
        )
        self.p2p_recv_count = r.counter(
            "p2p_message_receive_count",
            "Complete messages received (label ch_id)",
        )
        self.p2p_errors = r.counter(
            "p2p_errors_total",
            "Non-fatal p2p errors that were logged and swallowed "
            "(label site=peer_stop|mconn_stop|...)",
        )
        # ---- consensus control plane
        self.cs_timeout_fired = r.counter(
            "consensus_timeout_fired_total",
            "Consensus timeouts fired by the ticker (label step)",
        )
        self.cs_height_phase = r.histogram(
            "consensus_height_phase_seconds",
            "Wall time between a height's consecutive timeline phases "
            "(label phase=proposal|full_block|prevote_23|precommit_23|"
            "commit|apply) — fed by the per-height ledger "
            "(utils/heightline); 'why was height H slow' reads here "
            "first, then /height_timeline for the per-height detail",
            buckets=(0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1, 2, 4, 8, 16),
        )
        # ---- stores (store/metrics.go BlockStore access durations)
        self.store_access_seconds = r.histogram(
            "store_block_store_access_duration_seconds",
            "Block/state store op latency (label method)",
            buckets=(0.0005, 0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0),
        )
        # ---- verification plane (ours: the TPU VerifyCommit pipeline)
        self.verify_submit_queue_depth = r.gauge(
            "verify_submit_queue_depth",
            "VerifyCommit submissions queued or staging on the comb "
            "staging thread",
        )
        self.verify_slab_requests = r.counter(
            "verify_slab_requests_total",
            "Staging-slab acquisitions (label result=hit|miss; hit = "
            "recycled from the per-entry pool, no allocation)",
        )
        self.verify_batch_width = r.histogram(
            "verify_batch_width_sigs",
            "Signatures per batch-verifier submission",
            buckets=(1, 4, 16, 64, 256, 1024, 4096, 16384),
        )
        self.verify_staging_busy = r.counter(
            "verify_staging_busy_seconds_total",
            "Cumulative busy time of the comb staging thread (ratio to "
            "wall clock = staging-thread occupancy)",
        )
        self.comb_table_cache = r.counter(
            "verify_comb_table_cache_total",
            "Valset comb-table cache lookups (label result=hit|miss|"
            "building; building = async build in flight, batch routed "
            "to the uncached kernel)",
        )
        self.secp_pubkey_cache = r.counter(
            "verify_svc_secp_pubkey_cache_total",
            "Decoded-secp256k1-pubkey cache lookups in the MODE_SECP "
            "lane (label result=hit|miss); CheckTx ingest repeats "
            "senders, so the firehose soak asserts the hit rate from "
            "this counter instead of inferring it",
        )
        # ---- verify service scheduler (verifysvc/service.py)
        self.verify_svc_queue_depth = r.gauge(
            "verify_svc_queue_depth",
            "Signatures (or proof queries) queued per verify-service "
            "priority class (label class=consensus|blocksync|mempool|"
            "background|proof)",
        )
        self.verify_svc_flush = r.counter(
            "verify_svc_flush_total",
            "Verify-service batch flushes (labels class, reason=full|"
            "deadline: full = batch width reached, deadline = class "
            "flush deadline expired first)",
        )
        self.verify_svc_rejected = r.counter(
            "verify_svc_rejected_total",
            "Verify-service submissions rejected with backpressure "
            "(label class); callers fall back to host verification",
        )
        self.verify_svc_queue_wait = r.histogram(
            "verify_svc_queue_wait_seconds",
            "Time a request spent queued in the verify service before "
            "dispatch (label class) — consensus should pin the lowest "
            "buckets regardless of mempool load",
            buckets=(
                0.0001, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
                0.1, 0.5,
            ),
        )
        # ---- verify-service tenancy (verifysvc/service.py, (tenant,
        # class) scheduling).  Tenant label values MUST pass through
        # self.tenant_labels.bound() — an unbounded tenant-id stream
        # would otherwise allocate unbounded series (beyond the bound
        # they aggregate under "__overflow__").
        from . import envknobs as _envknobs

        self.tenant_labels = LabelGuard(
            _envknobs.get_int(_envknobs.VERIFYSVC_TENANT_LABEL_MAX)
        )
        self.verify_svc_tenant_queue_depth = r.gauge(
            "verify_svc_tenant_queue_depth",
            "Signatures queued per (tenant, class) in the verify "
            "service (labels tenant, class; tenant set bounded by "
            "COMETBFT_TPU_VERIFYSVC_TENANT_LABEL_MAX, overflow bucket "
            "__overflow__)",
        )
        self.verify_svc_tenant_dispatched = r.counter(
            "verify_svc_tenant_dispatched_total",
            "Verify-service batches dispatched per (tenant, class) "
            "(labels tenant, class)",
        )
        self.verify_svc_tenant_rejected = r.counter(
            "verify_svc_tenant_rejected_total",
            "Verify-service submissions rejected with backpressure per "
            "(tenant, class) (labels tenant, class, scope=tenant|class: "
            "which bound was hit)",
        )
        self.verify_svc_collect_timeout = r.counter(
            "verify_svc_collect_timeout_total",
            "Client-side Ticket.collect() deadlines that expired "
            "(label class); the client host-verified its batch inline "
            "and left stall forensics",
        )
        # ---- verify-service degraded-mode failover (verifysvc/service.py)
        self.verify_svc_backend_mode = r.gauge(
            "verify_svc_backend_mode",
            "Verify-service backend mode (0=tpu, 1=cpu_fallback); flips "
            "on every failover trip/restore",
        )
        self.verify_svc_failover = r.counter(
            "verify_svc_failover_total",
            "Verify-service failover transitions (label direction="
            "to_cpu|to_tpu)",
        )
        self.verify_svc_host_reverify = r.counter(
            "verify_svc_host_reverify_total",
            "Batches re-verified on the host path by the failover plane "
            "(label cause=wedge|dispatch_error|submit_error|"
            "collect_error)",
        )
        # ---- out-of-process verify plane client (verifysvc/remote.py)
        self.verify_rpc_requests = r.counter(
            "verify_rpc_requests_total",
            "Remote verify-plane request outcomes (label result=ok|"
            "deduped|backpressure|timeout|error); deduped = answered "
            "from the plane's idempotency window after a retry",
        )
        self.verify_rpc_resends = r.counter(
            "verify_rpc_resends_total",
            "Idempotent resends of in-flight remote verify requests "
            "after a reconnect (same request_id+digest; the plane's "
            "dedup window makes repeats safe)",
        )
        self.verify_rpc_reconnects = r.counter(
            "verify_rpc_reconnects_total",
            "Reconnects to the remote verify plane after a connection "
            "death (jittered exponential backoff)",
        )
        self.verify_rpc_breaker_state = r.gauge(
            "verify_rpc_breaker_state",
            "Remote verify-plane circuit breaker (0=closed: batches "
            "route remotely, 1=open: in-process host fallback, "
            "probation probing)",
        )
        self.verify_rpc_breaker_transitions = r.counter(
            "verify_rpc_breaker_transitions_total",
            "Remote-plane breaker transitions (label state=open|closed)",
        )
        # ---- proof serving plane (models/proof_server.py)
        self.verify_proof_queries = r.counter(
            "verify_proof_queries_total",
            "Merkle proof queries answered by the PROOF serving class "
            "(label route=device|host|remote: which data plane produced "
            "the proofs — all routes bit-identical to "
            "crypto/merkle.proofs_from_byte_slices by construction)",
        )
        self.verify_proof_tree_cache = r.counter(
            "verify_proof_tree_cache_total",
            "Proof-server tree-cache lookups by digest (label "
            "result=hit|miss); a miss yields a typed None row for the "
            "query, never a wrong proof",
        )
        # ---- health sentinel (utils/healthmon)
        self.health_state = r.gauge(
            "health_state",
            "Node health state from the sentinel "
            "(0=ok, 1=degraded, 2=wedged)",
        )
        self.health_probe_seconds = r.histogram(
            "health_probe_seconds",
            "Accelerator probe latency (subprocess jax.devices(); a "
            "hang is clamped at the probe deadline)",
            buckets=(0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0,
                     120.0, 240.0),
        )
        self.health_probe_total = r.counter(
            "health_probe_total",
            "Sentinel probe attempts (label result=ok|fail|hang)",
        )
        self.health_probe_consec_failures = r.gauge(
            "health_consecutive_probe_failures",
            "Consecutive failed sentinel probes (resets on success)",
        )
        self.health_beat_age = r.gauge(
            "health_beat_age_seconds",
            "Age of each registered loop's last heartbeat (label loop)",
        )
        self.health_transitions = r.counter(
            "health_transitions_total",
            "Health state transitions (label state = the state entered)",
        )
        self.health_forensics = r.counter(
            "health_forensics_artifacts_total",
            "Stall-forensics artifacts written by the sentinel",
        )
        self.verify_phase_seconds = r.histogram(
            "verify_phase_seconds",
            "Per-phase VerifyCommit pipeline latency (label phase="
            "assembly|h2d_dispatch|staging_wait|device_wait; first call "
            "at a new shape carries the XLA compile in h2d_dispatch)",
            buckets=(
                0.0001, 0.0005, 0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0, 2.5,
            ),
        )


_HUB: Hub | None = None
_HUB_MTX = threading.Lock()


def hub() -> Hub:
    global _HUB
    if _HUB is None:
        with _HUB_MTX:
            if _HUB is None:
                _HUB = Hub()
    return _HUB


class NodeMetrics:
    """The node's metric set (the named subset of the reference's
    per-package metricsgen output that the QA dashboards read)."""

    def __init__(self, registry: Registry):
        r = registry
        # consensus (internal/consensus/metrics.go:19)
        self.consensus_height = r.gauge("consensus_height", "Current height")
        self.consensus_rounds = r.gauge("consensus_rounds", "Round of the current height")
        self.consensus_validators = r.gauge("consensus_validators", "Validator set size")
        self.consensus_block_interval = r.histogram(
            "consensus_block_interval_seconds",
            "Time between this and the last block",
            buckets=(0.5, 1, 2, 3, 5, 7, 10, 15, 30),
        )
        self.consensus_num_txs = r.gauge("consensus_num_txs", "Txs in the latest block")
        self.consensus_total_txs = r.counter("consensus_total_txs", "Total committed txs")
        # mempool
        self.mempool_size = r.gauge("mempool_size", "Pending txs")
        self.mempool_size_bytes = r.gauge("mempool_size_bytes", "Pending tx bytes")
        # p2p
        self.p2p_peers = r.gauge("p2p_peers", "Connected peers")
        # verification plane (ours: the TPU hot path)
        self.verify_commit_seconds = r.histogram(
            "verify_commit_seconds",
            "VerifyCommit latency (batch verifier path)",
            buckets=(0.001, 0.005, 0.01, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5),
        )
