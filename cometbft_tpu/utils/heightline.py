"""Per-height consensus timeline ledger — the answer to "why was
height H slow".

A bounded registry of the last N committed-or-in-progress heights, each
carrying the wall-clock time its consensus pipeline reached every
phase:

    start         entered the height (round-0 propose step)
    proposal      proposal message accepted
    full_block    every block part assembled (Block decoded)
    prevote_23    2/3 prevote majority observed
    precommit_23  2/3 precommit majority observed
    commit        entered commit step
    apply         block executed + state persisted

plus the height's verify attribution: how many verify-service batches
settled while the height was current, their total signature width, and
the wall time spent inside their collects — the vote/verify pipeline
dominates committee-based consensus latency (arXiv:2302.00418), so
"slow height" almost always decomposes into one of these phases plus
its verify wait.

Feeds: consensus/state marks the consensus phases, blocksync/reactor
marks full_block/commit/apply for fast-synced heights, and the verify
service's collector reports settled CONSENSUS-class batches (attributed
to the registry's *current* height — batch tickets don't carry heights;
blocksync attributes its own waits explicitly by height).

Every mark is cross-recorded into the consensus flight recorder (kind
``heightline``), which makes the ledger reconstructible: a fresh
registry replays the recorder ring (:func:`restore_from_flightrec`)
after a restart or a dump-driven post-mortem, so the timeline survives
the process that produced it losing its in-memory state.

Surfaces: ``consensus_height_phase_seconds{phase}`` Hub histogram
observations (the delta between consecutive phase marks), the
``/height_timeline`` RPC route, and the per-height summary in
``BENCH_WORKLOAD=mixed`` output.

Bounded by ``COMETBFT_TPU_HEIGHTLINE_CAP`` heights; disabled entirely
with ``COMETBFT_TPU_HEIGHTLINE=0`` (marks become no-ops, the RPC
answers empty).
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict

from . import envknobs

# canonical phase order: a phase's histogram observation measures the
# delta from the latest EARLIER phase that was marked for the height
PHASES = (
    "start",
    "proposal",
    "full_block",
    "prevote_23",
    "precommit_23",
    "commit",
    "apply",
)
_PHASE_IDX = {p: i for i, p in enumerate(PHASES)}

# phases whose deltas are observed into the Hub histogram ("start" is
# the reference point, not a duration)
METRIC_PHASES = PHASES[1:]


class HeightlineRegistry:
    """Bounded height -> timeline map.  Thread-safe: consensus,
    blocksync, and the verify collector threads all feed it."""

    def __init__(self, capacity: int | None = None, enabled: bool | None = None):
        if capacity is None:
            capacity = envknobs.get_int(envknobs.HEIGHTLINE_CAP)
        self.capacity = max(8, int(capacity))
        self.enabled = (
            envknobs.get_bool(envknobs.HEIGHTLINE)
            if enabled is None else bool(enabled)
        )
        self._mtx = threading.Lock()
        self._heights: OrderedDict[int, dict] = OrderedDict()
        self._current: int = 0
        self._evicted = 0

    # ------------------------------------------------------------ feeding

    def _entry_locked(self, height: int) -> dict:
        e = self._heights.get(height)
        if e is None:
            e = {
                "height": height,
                "phases": {},  # phase -> wall_ns of FIRST occurrence
                "round": 0,
                "verify": {"batches": 0, "sigs": 0, "wait_s": 0.0},
            }
            self._heights[height] = e
            while len(self._heights) > self.capacity:
                self._heights.popitem(last=False)
                self._evicted += 1
        return e

    def mark(
        self,
        height: int,
        phase: str,
        wall_ns: int | None = None,
        round_: int = 0,
        _record: bool = True,
    ) -> None:
        """Record that ``height`` reached ``phase`` (first mark wins —
        a re-proposal after a round bump doesn't rewind the timeline,
        but the max round is kept).  Observes the phase-delta histogram
        and cross-records into the flight recorder unless replaying."""
        if not self.enabled or height <= 0 or phase not in _PHASE_IDX:
            return
        if wall_ns is None:
            wall_ns = time.time_ns()
        idx = _PHASE_IDX[phase]
        with self._mtx:
            e = self._entry_locked(height)
            if round_ > e["round"]:
                e["round"] = round_
            if phase in e["phases"]:
                return
            e["phases"][phase] = wall_ns
            prev_ns = None
            for p, t in e["phases"].items():
                if _PHASE_IDX[p] < idx and (prev_ns is None or t > prev_ns):
                    prev_ns = t
        if not _record:
            return
        if phase in METRIC_PHASES and prev_ns is not None:
            from .metrics import hub as _mhub

            _mhub().cs_height_phase.observe(
                max(0.0, (wall_ns - prev_ns) / 1e9), phase=phase
            )
        from .flightrec import recorder as _flightrec

        _flightrec().record(
            "heightline", height=height, round=round_,
            phase=phase, t_wall_ns=wall_ns,
        )

    def set_current(self, height: int) -> None:
        """The height consensus is working on NOW — the attribution
        target for verify batches (whose tickets don't carry heights)."""
        if self.enabled:
            self._current = height

    @property
    def current(self) -> int:
        return self._current

    def note_verify(
        self, nsigs: int, wait_s: float, height: int | None = None
    ) -> None:
        """Attribute one settled verify batch (``nsigs`` wide, its
        collect blocked ``wait_s``) to ``height`` — or to the current
        height when the caller doesn't know one (the service collector).
        Unattributable batches (no current height yet) are dropped."""
        if not self.enabled:
            return
        h = self._current if height is None else height
        if h <= 0:
            return
        with self._mtx:
            v = self._entry_locked(h)["verify"]
            v["batches"] += 1
            v["sigs"] += int(nsigs)
            v["wait_s"] += float(wait_s)

    # ------------------------------------------------------------ reading

    def snapshot(self, limit: int | None = None) -> dict:
        """JSON-ready view, heights ascending: per height the absolute
        wall_ns of each phase, per-phase deltas in seconds, and the
        verify attribution.  ``limit`` keeps only the newest N."""
        with self._mtx:
            entries = list(self._heights.values())
            current = self._current
            evicted = self._evicted
        entries.sort(key=lambda e: e["height"])
        if limit is not None and limit >= 0:
            entries = entries[len(entries) - min(limit, len(entries)):]
        out = []
        for e in entries:
            phases = dict(e["phases"])
            deltas = {}
            marked = sorted(phases.items(), key=lambda kv: _PHASE_IDX[kv[0]])
            for (p0, t0), (p1, t1) in zip(marked, marked[1:]):
                deltas[p1] = max(0.0, (t1 - t0) / 1e9)
            total = None
            if len(marked) >= 2:
                total = max(0.0, (marked[-1][1] - marked[0][1]) / 1e9)
            out.append({
                "height": e["height"],
                "round": e["round"],
                "phases_wall_ns": phases,
                "phase_seconds": deltas,
                "total_seconds": total,
                "verify": dict(e["verify"]),
            })
        return {
            "heights": out,
            "count": len(out),
            "current_height": current,
            "capacity": self.capacity,
            "evicted": evicted,
            "enabled": self.enabled,
        }

    def clear(self) -> None:
        with self._mtx:
            self._heights.clear()
            self._current = 0
            self._evicted = 0


def restore_from_flightrec(
    registry: HeightlineRegistry, rec=None
) -> int:
    """Rebuild a registry's phase marks from flight-recorder
    ``heightline`` entries (the live global recorder by default, or any
    dumped ``{"entries": [...]}`` trace) — original wall times, no
    re-observation into metrics, no re-recording.  Returns the number
    of marks replayed."""
    if rec is None:
        from .flightrec import recorder

        rec = recorder()
    entries = rec["entries"] if isinstance(rec, dict) else rec.dump()["entries"]
    n = 0
    top = 0
    for e in entries:
        if e.get("kind") != "heightline":
            continue
        d = e.get("detail", {})
        phase = d.get("phase")
        if phase not in _PHASE_IDX:
            continue
        registry.mark(
            e.get("height", 0), phase,
            wall_ns=d.get("t_wall_ns", e.get("wall_ns")),
            round_=e.get("round", 0) or 0,
            _record=False,
        )
        top = max(top, e.get("height", 0))
        n += 1
    if top:
        registry.set_current(top)
    return n


_REG = HeightlineRegistry()


def registry() -> HeightlineRegistry:
    """The process-global ledger (same sharing model as the flight
    recorder: multi-node test processes share one; entries carry
    heights, so interleaved nodes stay distinguishable)."""
    return _REG
