"""Central registry of every COMETBFT_TPU_* environment knob.

Each knob is declared exactly once — name, type, default, and a one-line
doc — and read through the typed getters below.  Reading a knob that was
never declared raises ``KeyError`` loudly: the registry IS the inventory,
and the static linter (analysis/raw_env) rejects any
``os.environ``/``getenv`` read of a ``COMETBFT_TPU_*`` name outside this
module, so a knob cannot exist without documentation.

``docs/knobs.md`` is generated from this registry
(``python -m cometbft_tpu.utils.envknobs``); a test asserts the checked-in
copy matches, so the doc cannot drift.

Parsing is deliberately forgiving (malformed values fall back to the
declared default) because knobs are operator input read on hot-path
module imports — a typo must degrade to the default, never crash a node.
This module imports only the stdlib so every subsystem (logging included)
can depend on it without cycles.
"""

from __future__ import annotations

import os
from dataclasses import dataclass

_TRUE = frozenset({"1", "true", "yes", "on"})
_FALSE = frozenset({"0", "false", "no", "off"})


@dataclass(frozen=True)
class Knob:
    name: str
    type: str  # "str" | "int" | "bool" | "int?"
    default: object
    doc: str


_REGISTRY: dict[str, Knob] = {}


def _declare(name: str, type_: str, default, doc: str) -> str:
    if name in _REGISTRY:
        raise ValueError(f"knob {name!r} declared twice")
    _REGISTRY[name] = Knob(name, type_, default, doc)
    return name


# --------------------------------------------------------------- knobs
# (grouped by subsystem; order is the order docs/knobs.md renders)

# crypto / verification plane
CRYPTO_BACKEND = _declare(
    "COMETBFT_TPU_CRYPTO_BACKEND", "str", "auto",
    "Batch-verifier backend: `tpu` | `cpu` | `auto` "
    "(auto = accelerator kernel whenever JAX is importable).",
)
COMB_MIN = _declare(
    "COMETBFT_TPU_COMB_MIN", "int", 512,
    "Minimum validator-set size for the device-resident comb-table path; "
    "below it the table build + per-set compiled program don't pay off.",
)
COMB_ASYNC_MIN = _declare(
    "COMETBFT_TPU_COMB_ASYNC_MIN", "int", 2048,
    "Set size at/above which a missing comb table builds in the background "
    "while verification proceeds through the uncached kernel.",
)
COMB_HOST_BUILD_MAX = _declare(
    "COMETBFT_TPU_COMB_HOST_BUILD_MAX", "int", 2048,
    "Largest validator-set (or churn-bucket) size whose comb A-tables "
    "are precomputed on HOST (exact bigint, bit-identical to the jitted "
    "kernel, ~10 ms/validator, NO XLA program) and `device_put` straight "
    "into their sharded layout — a cold pod never pays the table-build "
    "compile.  Bigger builds use the scan-rolled jitted kernel (persistent "
    "compile cache amortizes it).  0 = always the device kernel.",
)
COMB_TREE = _declare(
    "COMETBFT_TPU_COMB_TREE", "bool", True,
    "`0` selects the sequential fori_loop comb accumulation (the bit-exact "
    "cross-check path) instead of the log-depth tree reduction.",
)
BTAB_CACHE = _declare(
    "COMETBFT_TPU_BTAB_CACHE", "str", "",
    "Path (`.npy` appended if missing) disk-caching the constant "
    "basepoint comb tables across processes.",
)
MESH = _declare(
    "COMETBFT_TPU_MESH", "int", 0,
    "Shard comb tables + signature rows over the first N devices (N > 1); "
    "unset/0/1 keeps the single-device program.",
)
DEVICE_BATCH_MIN = _declare(
    "COMETBFT_TPU_DEVICE_BATCH_MIN", "int?", None,
    "Batch width at/above which signatures route to the device kernels; "
    "unset = link-aware default (2048 through the axon tunnel, else 32).",
)
COMPILE_CACHE = _declare(
    "COMETBFT_TPU_COMPILE_CACHE", "str", "",
    "Directory for JAX's persistent compilation cache "
    "(utils/compilecache, enabled by `python -m cometbft_tpu` and "
    "bench.py): a warm pod restart loads compiled executables from disk "
    "instead of re-running XLA — the cold-start half of the multi-chip "
    "plane.  Empty = cache disabled.",
)
BLS_DEVICE = _declare(
    "COMETBFT_TPU_BLS_DEVICE", "bool", False,
    "`1` tree-reduces BLS pubkey aggregation on the accelerator "
    "(ops/bls381); pairings always run on host.",
)
BLS_VALIDATE_DEVICE_MIN = _declare(
    "COMETBFT_TPU_BLS_VALIDATE_DEVICE_MIN", "int", 8,
    "Minimum count of not-yet-cached BLS pubkeys for which the batched "
    "on-curve/subgroup validation runs on the accelerator "
    "(ops/bls381.validate_g1); below it the ~4 ms/key host check wins "
    "over dispatch overhead.  The verdict is bit-identical either way.",
)
BLS_AGG_DEVICE_MIN = _declare(
    "COMETBFT_TPU_BLS_AGG_DEVICE_MIN", "int", 256,
    "Minimum pubkey count per aggregate unit for which the tree-reduced "
    "G1 sum runs on the accelerator (ops/bls381.aggregate_g1); smaller "
    "units sum on host.  The aggregate point is identical either way.",
)
BLS_PUBKEY_CACHE = _declare(
    "COMETBFT_TPU_BLS_PUBKEY_CACHE", "int", 65536,
    "Entries in the validated-BLS-pubkey cache (models/bls_verifier): "
    "decompression + subgroup membership are per-key facts, so a "
    "validator set pays validation once, not once per commit.  0 "
    "disables caching.",
)
SECP_DEVICE_MIN = _declare(
    "COMETBFT_TPU_SECP_DEVICE_MIN", "int", 8,
    "Minimum batch width at/above which secp256k1 ECDSA batches run on "
    "the accelerator (ops/secp256k1.verify_batch: Shamir double-scalar "
    "kernels + Montgomery batch inversion); below it the per-row host "
    "verify wins over dispatch overhead.  The verdict is bit-identical "
    "either way (models/secp_verifier).",
)
SECP_PUBKEY_CACHE = _declare(
    "COMETBFT_TPU_SECP_PUBKEY_CACHE", "int", 65536,
    "Entries in the decoded-secp256k1-pubkey cache "
    "(models/secp_verifier): decompressing a 33-byte key costs a field "
    "square root, and CheckTx ingest repeats senders, so decode is "
    "paid once per key, not once per transaction.  0 disables caching.",
)
SECP_GLV = _declare(
    "COMETBFT_TPU_SECP_GLV", "bool", True,
    "`0` selects the plain 66-window Shamir double-scalar walk (the "
    "bit-exactness witness path) instead of the GLV endomorphism "
    "quad-scalar walk over 33 windows in ops/secp256k1.verify_batch.  "
    "The verdict is bit-identical either way (tests/test_secp_glv.py "
    "pins it); GLV roughly halves the shared doubling chain that "
    "dominates the kernel.",
)
SECP_HASH_DEVICE_MIN = _declare(
    "COMETBFT_TPU_SECP_HASH_DEVICE_MIN", "int", 64,
    "Minimum secp batch width at/above which message hashing (SHA-256 "
    "for cosmos rows, Keccak-256 for eth/ecrecover rows) fuses into "
    "the device dispatch (ops/secp256k1.hash_verify_batch) instead of "
    "running as a per-row host loop; 0 disables the fused path.  Only "
    "batches whose every message fits COMETBFT_TPU_SECP_HASH_MAX_LEN "
    "take it — the verdict is bit-identical either way.",
)
SECP_HASH_MAX_LEN = _declare(
    "COMETBFT_TPU_SECP_HASH_MAX_LEN", "int", 119,
    "Longest message (bytes) eligible for on-device hashing in the "
    "fused secp dispatch: 119 keeps every row inside one Keccak rate "
    "block (136 - pad) and two SHA-256 blocks — the CheckTx envelope "
    "shape.  A batch with any longer message hashes on host.",
)
SECP_FIREHOSE_TXS = _declare(
    "COMETBFT_TPU_SECP_FIREHOSE_TXS", "int", 100000,
    "Signed-tx count scripts/firehose_soak.py drives through the "
    "CheckTx secp firehose (>= 100k is the acceptance shape).",
)
SECP_FIREHOSE_SENDERS = _declare(
    "COMETBFT_TPU_SECP_FIREHOSE_SENDERS", "int", 32,
    "Distinct repeat senders per key type in the firehose pool — small "
    "enough that the decoded-pubkey cache must earn its > 0.9 hit-rate "
    "SLO, large enough to exercise eviction-free steady state.",
)

# verify service (verifysvc/ — priority-scheduled device batching)
VERIFYSVC_BATCH_MAX = _declare(
    "COMETBFT_TPU_VERIFYSVC_BATCH_MAX", "int", 4096,
    "Verify-service batch width: a class's queue flushes as `full` once "
    "this many signatures are pending (clamped to >= 1).",
)
VERIFYSVC_QUEUE_MAX = _declare(
    "COMETBFT_TPU_VERIFYSVC_QUEUE_MAX", "int", 16384,
    "Per-class queue bound in signatures; a submit beyond it is rejected "
    "with backpressure and the caller falls back to host verification.",
)
VERIFYSVC_DEADLINE_CONSENSUS_MS = _declare(
    "COMETBFT_TPU_VERIFYSVC_DEADLINE_CONSENSUS_MS", "int", 0,
    "Flush deadline (ms) for the consensus class: 0 = dispatch the "
    "moment the scheduler sees a request.",
)
VERIFYSVC_DEADLINE_BLOCKSYNC_MS = _declare(
    "COMETBFT_TPU_VERIFYSVC_DEADLINE_BLOCKSYNC_MS", "int", 2,
    "Flush deadline (ms) for the blocksync class.",
)
VERIFYSVC_DEADLINE_MEMPOOL_MS = _declare(
    "COMETBFT_TPU_VERIFYSVC_DEADLINE_MEMPOOL_MS", "int", 5,
    "Flush deadline (ms) for the mempool class — the coalescing window "
    "that merges per-tx CheckTx signature checks from concurrent "
    "senders into one device batch.",
)
VERIFYSVC_DEADLINE_BACKGROUND_MS = _declare(
    "COMETBFT_TPU_VERIFYSVC_DEADLINE_BACKGROUND_MS", "int", 25,
    "Flush deadline (ms) for the background class (light client, "
    "evidence).",
)
VERIFYSVC_WEIGHTS = _declare(
    "COMETBFT_TPU_VERIFYSVC_WEIGHTS", "str", "",
    "Optional weighted interleave of READY classes, e.g. "
    "`consensus=8,blocksync=4,mempool=2,background=1`; empty/malformed "
    "= strict priority (consensus > blocksync > mempool > background).",
)
VERIFYSVC_CHECKTX = _declare(
    "COMETBFT_TPU_VERIFYSVC_CHECKTX", "bool", True,
    "`0` disables the mempool CheckTx ed25519 envelope gate "
    "(verifysvc/checktx); unsigned txs always pass through untouched.",
)
VERIFYSVC_TENANT = _declare(
    "COMETBFT_TPU_VERIFYSVC_TENANT", "str", "default",
    "Tenant id this process submits verify-service work under (how a "
    "chain claims its slice of a shared multi-tenant verify plane).  "
    "Single-chain deployments keep the `default` tenant and see no "
    "behavior change.",
)
VERIFYSVC_TENANT_QUOTA = _declare(
    "COMETBFT_TPU_VERIFYSVC_TENANT_QUOTA", "int", 0,
    "Per-(tenant, class) bound on OUTSTANDING signatures (queued + "
    "dispatched-but-unsettled, released when each request's ticket "
    "settles) — one tenant's mempool flood hits ITS quota and "
    "backpressures while other tenants stay admissible, no matter how "
    "fast the scheduler drains the queue into the device or wire "
    "pipeline.  0 (default) = the class-wide "
    "COMETBFT_TPU_VERIFYSVC_QUEUE_MAX, i.e. no extra per-tenant bound.",
)
VERIFYSVC_TENANT_WEIGHTS = _declare(
    "COMETBFT_TPU_VERIFYSVC_TENANT_WEIGHTS", "str", "",
    "Weighted-fair interleave of READY tenants within one priority "
    "class, e.g. `chain-a=4,chain-b=1`; unlisted tenants weigh 1.  "
    "Classes still dispatch in strict priority (consensus first) — "
    "weights only order tenants competing inside the same class.",
)
VERIFYSVC_TENANT_LABEL_MAX = _declare(
    "COMETBFT_TPU_VERIFYSVC_TENANT_LABEL_MAX", "int", 32,
    "Bound on distinct tenant label values the metrics hub exposes "
    "(utils/metrics.LabelGuard); tenants beyond it aggregate under the "
    "`__overflow__` label so an unbounded tenant-id stream cannot blow "
    "up the /metrics exposition.",
)
VERIFYSVC_COLLECT_TIMEOUT_MS = _declare(
    "COMETBFT_TPU_VERIFYSVC_COLLECT_TIMEOUT_MS", "int", 120000,
    "Deadline (ms) a verify-service client waits in Ticket.collect() "
    "before declaring the scheduler stuck: the wait is abandoned with "
    "stall forensics and the client verifies its own batch inline on "
    "the host (first-wins ticket settlement discards the late device "
    "result).  0 = wait forever (the pre-PR-12 contract).",
)

# out-of-process verify plane (verifysvc/server.py + remote.py + verifyd)
VERIFYRPC_ADDR = _declare(
    "COMETBFT_TPU_VERIFYRPC_ADDR", "str", "",
    "host:port of a shared out-of-process verify plane (verifyd, "
    "`scripts/verifyd.py`).  When set, the local verify service routes "
    "every batch over the wire instead of to a local device verifier "
    "(comb binds are bypassed — device-resident state is the plane's), "
    "falling back to the in-process host path whenever the circuit "
    "breaker is open.  Empty (default) = the in-process plane.",
)
VERIFYRPC_BUDGET_MS = _declare(
    "COMETBFT_TPU_VERIFYRPC_BUDGET_MS", "int", 10000,
    "Per-request deadline budget (ms) for remote verify RPCs.  The "
    "REMAINING budget — never a wall-clock deadline — crosses the wire "
    "on every send and idempotent resend; a request that exhausts its "
    "budget is a deadline breach, which trips the circuit breaker.",
)
VERIFYRPC_CONNECT_TIMEOUT_MS = _declare(
    "COMETBFT_TPU_VERIFYRPC_CONNECT_TIMEOUT_MS", "int", 2000,
    "TCP connect timeout (ms) for the remote verify plane (dials and "
    "probation probes).",
)
VERIFYRPC_RETRY_MAX = _declare(
    "COMETBFT_TPU_VERIFYRPC_RETRY_MAX", "int", 4,
    "Max send attempts per remote verify request (first send + "
    "idempotent resends after reconnects); beyond it the request fails "
    "and the batch is re-verified on the host path.",
)
VERIFYRPC_BREAKER_FAILS = _declare(
    "COMETBFT_TPU_VERIFYRPC_BREAKER_FAILS", "int", 3,
    "Consecutive connection-level failures (connect/send/recv) that "
    "trip the remote-plane circuit breaker to the in-process host "
    "path.  A request deadline breach trips it immediately.",
)
VERIFYRPC_BACKOFF_MS = _declare(
    "COMETBFT_TPU_VERIFYRPC_BACKOFF_MS", "int", 50,
    "Initial reconnect backoff (ms) toward the remote verify plane; "
    "jittered exponential, capped at 40x.",
)
VERIFYRPC_PROBE_PERIOD_MS = _declare(
    "COMETBFT_TPU_VERIFYRPC_PROBE_PERIOD_MS", "int", 1000,
    "Probation probe period (ms) while the remote-plane breaker is "
    "open: one ping round-trip per period.",
)
VERIFYRPC_PROBATION_OK = _declare(
    "COMETBFT_TPU_VERIFYRPC_PROBATION_OK", "int", 2,
    "Consecutive successful probation pings required before the "
    "remote-plane breaker closes and batches route remotely again.",
)
VERIFYRPC_DEDUP_WINDOW_S = _declare(
    "COMETBFT_TPU_VERIFYRPC_DEDUP_WINDOW_S", "int", 120,
    "Server-side idempotency window (seconds): verifyd remembers "
    "(request_id, digest) -> response this long, so a retried batch is "
    "answered from cache — never re-verified into a different blame "
    "order — and a retry racing the original attaches to the in-flight "
    "verification instead of duplicating it.",
)

# proof serving plane (models/proof_server.py + verifysvc PROOF class)
PROOF_DEADLINE_MS = _declare(
    "COMETBFT_TPU_PROOF_DEADLINE_MS", "int", 5,
    "PROOF-class coalescing window (ms): how long the verify-service "
    "scheduler holds a proof request open for more light-client queries "
    "before dispatching the batch.  Proof traffic is read-only fan-out, "
    "so it tolerates a longer window than consensus work in exchange "
    "for wider device batches.  0 = dispatch immediately.",
)
PROOF_QUEUE_MAX = _declare(
    "COMETBFT_TPU_PROOF_QUEUE_MAX", "int", 8192,
    "PROOF-class queue bound (queries) in the verify service, separate "
    "from COMETBFT_TPU_VERIFYSVC_QUEUE_MAX: light-client fan-out is the "
    "one workload expected to arrive thousands-wide, and its backlog "
    "must backpressure without consuming the signature classes' "
    "headroom.  0 = use the class-wide queue bound.",
)
PROOF_DEVICE_MIN = _declare(
    "COMETBFT_TPU_PROOF_DEVICE_MIN", "int", 64,
    "Below this many coalesced queries against one tree the proof "
    "prover answers on host (crypto/merkle.proofs_from_byte_slices — "
    "bit-identical by construction); at or above it the batched one-hot "
    "gather kernel takes the dispatch.",
)
PROOF_TREE_CACHE = _declare(
    "COMETBFT_TPU_PROOF_TREE_CACHE", "int", 256,
    "Entries in the proof server's digest -> leaves tree cache "
    "(models/proof_server).  Proof queries reference trees by digest; "
    "a query against an evicted/unknown digest gets a None row (typed "
    "miss), never a wrong proof.  LRU, bounded.",
)
PROOF_QUERY_MAX = _declare(
    "COMETBFT_TPU_PROOF_QUERY_MAX", "int", 1024,
    "Per-request index cap on the merkle_proof RPC route: one JSON-RPC "
    "call may ask for at most this many leaf indices (invalid-params "
    "error beyond it), bounding what a single client can pin into one "
    "PROOF-class submit.",
)

# verify-service degraded-mode failover (verifysvc/service.py)
FAILOVER = _declare(
    "COMETBFT_TPU_FAILOVER", "bool", True,
    "`0` disables automatic TPU->CPU verify-plane failover: a wedged "
    "device then strands in-flight batches instead of tripping the "
    "service to host verification.",
)
FAILOVER_BATCH_DEADLINE_MS = _declare(
    "COMETBFT_TPU_FAILOVER_BATCH_DEADLINE_MS", "int", 30000,
    "An in-flight batch older than this while dispatched to (or "
    "awaiting results from) the device trips the verify service to CPU "
    "mode; host-side submit work (cold compiles) is exempt.",
)
FAILOVER_PROBATION_OK = _declare(
    "COMETBFT_TPU_FAILOVER_PROBATION_OK", "int", 2,
    "Consecutive successful probation probes required before a tripped "
    "verify service restores TPU mode.",
)
FAILOVER_PROBE_PERIOD_MS = _declare(
    "COMETBFT_TPU_FAILOVER_PROBE_PERIOD_MS", "int", 15000,
    "Probation probe period (ms) while the verify service is in CPU "
    "fallback mode.",
)
FAILOVER_PROBE_TIMEOUT_MS = _declare(
    "COMETBFT_TPU_FAILOVER_PROBE_TIMEOUT_MS", "int", 10000,
    "Hard deadline (ms) for one probation probe (the hang-proof "
    "subprocess probe, utils/healthmon.probe_devices).",
)

# fault injection registry (utils/fail.py; chaos harness only — never
# set in production)
FAULT_WEDGE_DEVICE = _declare(
    "COMETBFT_TPU_FAULT_WEDGE_DEVICE", "str", "",
    "Non-empty arms the `wedge_device` fault at process start: device "
    "result waits block and the accelerator probe reports a hang until "
    "the fault is cleared.",
)
FAULT_SLOW_COLLECT = _declare(
    "COMETBFT_TPU_FAULT_SLOW_COLLECT", "str", "",
    "Arms the `slow_collect` fault: device result waits take an extra "
    "<value> seconds.",
)
FAULT_FAIL_DISPATCH = _declare(
    "COMETBFT_TPU_FAULT_FAIL_DISPATCH", "str", "",
    "Arms the `fail_dispatch` fault: verify-service dispatches raise "
    "InjectedFault (failover re-verifies the batch on host).",
)
FAULT_DROP_P2P_PCT = _declare(
    "COMETBFT_TPU_FAULT_DROP_P2P_PCT", "str", "",
    "Arms the `drop_p2p_pct` fault: <value> percent of outbound p2p "
    "messages are silently dropped at the MConnection send seam.",
)
FAULT_DELAY_P2P_MS = _declare(
    "COMETBFT_TPU_FAULT_DELAY_P2P_MS", "str", "",
    "Arms the `delay_p2p_ms` fault: outbound p2p writes are delayed "
    "<value> ms (±50% jitter) at the MConnection send routine — a "
    "laggy link, composable with `drop_p2p_pct` for flaky-network "
    "soaks.",
)
FAULT_DOUBLE_SIGN = _declare(
    "COMETBFT_TPU_FAULT_DOUBLE_SIGN", "str", "",
    "Arms the `double_sign` fault: the next <value> signed non-nil "
    "prevotes are accompanied by a conflicting broadcast-only vote "
    "(byzantine equivocation feeding the evidence pool).",
)
FAULT_PLANE_CRASH = _declare(
    "COMETBFT_TPU_FAULT_PLANE_CRASH", "str", "",
    "Arms the `plane_crash` fault in a verifyd process: the <value>'th "
    "verify request SIGKILLs the plane mid-batch (no response, no "
    "cleanup) — the deterministic kill -9-with-batches-in-flight.",
)
FAULT_PLANE_STALL = _declare(
    "COMETBFT_TPU_FAULT_PLANE_STALL", "str", "",
    "Arms the `plane_stall` fault in a verifyd process: the <value>'th "
    "verify request SIGSTOPs the plane mid-batch (connections stay "
    "open, nothing answers) until an external SIGCONT.",
)
FAULT_RPC_DELAY_MS = _declare(
    "COMETBFT_TPU_FAULT_RPC_DELAY_MS", "str", "",
    "Arms the `rpc_delay_ms` fault: verifyd delays every response "
    "<value> ms (±50% jitter) before the socket write.",
)
FAULT_RPC_DROP_PCT = _declare(
    "COMETBFT_TPU_FAULT_RPC_DROP_PCT", "str", "",
    "Arms the `rpc_drop_pct` fault: verifyd silently drops <value> "
    "percent of responses (the batch WAS verified; the client's "
    "deadline machinery must recover).",
)
FAULT_RPC = _declare(
    "COMETBFT_TPU_FAULT_RPC", "bool", False,
    "`1` exposes the `arm_fault` / `clear_fault` RPC routes so the "
    "chaos harness can inject faults into a live node; off (the "
    "default) those routes reject.",
)

# blocksync
VERIFY_AHEAD = _declare(
    "COMETBFT_TPU_VERIFY_AHEAD", "int?", None,
    "Blocksync verify-ahead pipeline depth; unset = "
    "BlocksyncReactor.VERIFY_AHEAD_DEPTH (2).  Clamped to >= 1.",
)

# observability
LOG_LEVEL = _declare(
    "COMETBFT_TPU_LOG_LEVEL", "str", "INFO",
    "Root level for the `cometbft_tpu` logger tree.",
)
TRACE = _declare(
    "COMETBFT_TPU_TRACE", "str", "",
    "Span tracer switch: any truthy value records; a path value "
    "(contains the os separator or ends in `.json`) also auto-exports "
    "Chrome trace JSON at interpreter exit.",
)
TRACE_RING = _declare(
    "COMETBFT_TPU_TRACE_RING", "int", 65536,
    "Tracer ring capacity in events (clamped to >= 1).",
)
TRACE_CTX = _declare(
    "COMETBFT_TPU_TRACE_CTX", "bool", True,
    "`0` disables span-context propagation: no trace_id/span_id args on "
    "recorded events and no traceparent field on verify-plane RPC "
    "requests (the per-process tracer itself stays governed by "
    "COMETBFT_TPU_TRACE).",
)
FLIGHTREC = _declare(
    "COMETBFT_TPU_FLIGHTREC", "int", 1024,
    "Consensus flight-recorder ring capacity (clamped to >= 1).",
)
HEIGHTLINE_CAP = _declare(
    "COMETBFT_TPU_HEIGHTLINE_CAP", "int", 512,
    "Per-height consensus timeline ledger capacity in heights (clamped "
    "to >= 8); the oldest heights are evicted as new ones commit.",
)
HEIGHTLINE = _declare(
    "COMETBFT_TPU_HEIGHTLINE", "bool", True,
    "`0` disables the per-height timeline ledger (utils/heightline): "
    "no phase recording, an empty `/height_timeline` RPC answer, and "
    "no `consensus_height_phase_seconds` observations.",
)

# health sentinel (utils/healthmon)
HEALTH = _declare(
    "COMETBFT_TPU_HEALTH", "bool", False,
    "`1` starts the node health sentinel (utils/healthmon) at node "
    "start: periodic hang-proof accelerator probes, heartbeat audits of "
    "the long-lived loops, and automatic stall forensics.  Off = "
    "`healthmon.beat()` stays a zero-overhead no-op.",
)
HEALTH_PERIOD_MS = _declare(
    "COMETBFT_TPU_HEALTH_PERIOD_MS", "int", 60000,
    "Sentinel probe period (ms): how often `jax.devices()` is probed in "
    "a throwaway subprocess.",
)
HEALTH_PROBE_TIMEOUT_MS = _declare(
    "COMETBFT_TPU_HEALTH_PROBE_TIMEOUT_MS", "int", 20000,
    "Hard deadline (ms) for one sentinel probe; a probe past it is "
    "SIGKILLed (whole process group) and counted as a failure.",
)
HEALTH_WEDGE_AFTER = _declare(
    "COMETBFT_TPU_HEALTH_WEDGE_AFTER", "int", 2,
    "Consecutive probe failures at/above which the health state is "
    "`wedged` (below it: `degraded`); a success snaps back to `ok`.",
)
HEALTH_ARTIFACT_MIN_INTERVAL_MS = _declare(
    "COMETBFT_TPU_HEALTH_ARTIFACT_MIN_INTERVAL_MS", "int", 300000,
    "Floor (ms) between two stall-forensics artifacts: one artifact is "
    "captured per incident, and never more often than this however the "
    "state flaps.",
)
HEALTH_DIR = _declare(
    "COMETBFT_TPU_HEALTH_DIR", "str", "",
    "Directory for stall-forensics artifacts; empty = `$TMPDIR`.",
)

# analysis / correctness tooling
LOCKCHECK = _declare(
    "COMETBFT_TPU_LOCKCHECK", "bool", False,
    "`1` installs the runtime lock-order witness "
    "(analysis/lockwitness): lock acquisitions build an order graph and "
    "inversions/blocking-while-locked are reported with both stacks.  "
    "The special value `raise` additionally raises in the acquiring "
    "thread (read raw by `maybe_install`, not via `get_bool`, which "
    "treats it as unset).  The test conftest turns the witness on for "
    "every suite run.",
)

# test-only
TEST_LATENCY_MS = _declare(
    "COMETBFT_TPU_TEST_LATENCY_MS", "str", "",
    "Inject `delay` or `delay:jitter` milliseconds on every p2p "
    "connection (e2e perturbation harness only; never set in production).",
)


# -------------------------------------------------------------- getters

def knob(name: str) -> Knob:
    return _REGISTRY[name]


def all_knobs() -> list[Knob]:
    return list(_REGISTRY.values())


def raw(name: str) -> str | None:
    """The raw env value, or None when unset.  For the rare reader whose
    semantics don't fit the typed getters (e.g. the tracer's
    truthy-or-path switch); the knob must still be declared."""
    _REGISTRY[name]  # undeclared knob = programming error
    return os.environ.get(name)


def get_str(name: str) -> str:
    k = _REGISTRY[name]
    v = os.environ.get(name)
    return v if v is not None else k.default


def get_int(name: str) -> int:
    k = _REGISTRY[name]
    v = os.environ.get(name, "")
    if v:
        try:
            return int(v)
        except ValueError:
            pass
    return k.default


def get_opt_int(name: str) -> int | None:
    """None when unset/empty/malformed — the caller owns the fallback
    (used for knobs whose default is computed, not constant)."""
    _REGISTRY[name]
    v = os.environ.get(name, "")
    if v:
        try:
            return int(v)
        except ValueError:
            pass
    return None


def get_bool(name: str) -> bool:
    k = _REGISTRY[name]
    v = os.environ.get(name)
    if v is None or not v.strip():
        # set-but-empty (`KNOB= cmd` shell idiom) means "default", not
        # False — flipping a kernel-path knob on an empty string would
        # silently select a different compiled program
        return k.default
    s = v.strip().lower()
    if s in _TRUE:
        return True
    if s in _FALSE:
        return False
    return k.default


# --------------------------------------------------------- doc generation

def to_markdown() -> str:
    """Render docs/knobs.md — regenerate with
    ``python -m cometbft_tpu.utils.envknobs > docs/knobs.md``."""
    lines = [
        "# Environment knobs",
        "",
        "Generated from `cometbft_tpu/utils/envknobs.py` — do not edit by "
        "hand; regenerate with `python -m cometbft_tpu.utils.envknobs > "
        "docs/knobs.md`.  Every `COMETBFT_TPU_*` knob is declared in that "
        "registry and read through its typed getters; the static linter "
        "(`scripts/lint.py`, check `raw-env-read`) rejects reads anywhere "
        "else, so this table is the complete inventory.",
        "",
        "| Knob | Type | Default | Description |",
        "|---|---|---|---|",
    ]
    for k in all_knobs():
        default = "*(unset)*" if k.default is None else f"`{k.default!r}`"
        doc = k.doc.replace("|", "\\|")
        lines.append(f"| `{k.name}` | {k.type} | {default} | {doc} |")
    lines.append("")
    return "\n".join(lines)


if __name__ == "__main__":
    print(to_markdown(), end="")
