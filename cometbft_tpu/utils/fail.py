"""Env-indexed crash points (reference: internal/fail/fail.go:47).

Each call to fail_point() increments a process-global counter; when the
counter reaches ``FAIL_TEST_INDEX`` the process exits immediately with
status 75 (os._exit — no cleanup, no flushes: a real crash).  Sprinkled
through the commit path (consensus/state.py, state/execution.py) so the
crash-at-every-step recovery tests can kill a node between any two
persistence operations and assert WAL + handshake replay recover it
(reference sites: state.go:1872,1889,1912, execution.go:267,274;
exercised by replay_test.go).

Zero cost when FAIL_TEST_INDEX is unset (one env read at import).
"""

from __future__ import annotations

import os
import sys

EXIT_CODE = 75  # distinct from normal exits so tests can assert the crash

_target = int(os.environ.get("FAIL_TEST_INDEX", "-1"))
_counter = 0


def fail_point(label: str = "") -> None:
    """Crash here if this is the FAIL_TEST_INDEX'th fail point."""
    global _counter
    if _target < 0:
        return
    _counter += 1
    if _counter == _target:
        print(f"FAIL_TEST_INDEX={_target} hit at {label!r}", file=sys.stderr)
        sys.stderr.flush()
        os._exit(EXIT_CODE)


def points_hit() -> int:
    return _counter
