"""Fault injection: crash points and an armable runtime fault registry.

Two generations of failure tooling share this module:

* **Crash points** (:func:`fail_point`, reference: internal/fail/fail.go:47):
  each call increments a process-global counter; when it reaches
  ``FAIL_TEST_INDEX`` the process exits immediately with status 75
  (os._exit — no cleanup, no flushes: a real crash).  Sprinkled through
  the commit path (consensus/state.py, state/execution.py) so the
  crash-at-every-step recovery tests can kill a node between any two
  persistence operations and assert WAL + handshake replay recover it
  (reference sites: state.go:1872,1889,1912, execution.go:267,274).

* **Fault registry** (:func:`arm` / :func:`clear` / :func:`armed`): named,
  parameterized faults the chaos harness arms at runtime — via the
  ``COMETBFT_TPU_FAULT_*`` env knobs at process start, or live over RPC
  (``arm_fault`` / ``clear_fault``, gated on ``COMETBFT_TPU_FAULT_RPC``).
  Seams in the verify service, the health probe, consensus vote signing,
  and the p2p send path check the registry and misbehave deterministically
  while a fault is armed, so a backend wedge mid-batch (or a byzantine
  double-sign, or a lossy link) is injectable in-process on CPU-only CI.

  Known faults:

  ====================  ====================================================
  ``wedge_device``      Device result waits block (the verify-service
                        settle seam parks until the fault clears) and the
                        accelerator probe reports a hang — the in-process
                        twin of the BENCH r03-r05 wedged tunnel.
  ``slow_collect``      Device result waits take an extra <value> seconds.
  ``fail_dispatch``     Verify-service dispatch raises InjectedFault.
  ``drop_p2p_pct``      <value> percent of outbound p2p messages are
                        silently dropped at the MConnection send seam.
  ``delay_p2p_ms``      Outbound p2p writes are delayed <value> ms ±50%
                        jitter at the MConnection send routine (the wire
                        write, never a caller thread) — a laggy link
                        without tc/netem, composable with the drop fault
                        for genuinely flaky-network soaks.
  ``double_sign``       The next <value> signed non-nil prevotes are
                        accompanied by a conflicting broadcast-only vote
                        (byzantine equivocation feeding evidence/).
  ``plane_crash``       Armed in a verifyd process (verifysvc/server):
                        the <value>'th verify request kill -9s the plane
                        mid-batch (os._exit semantics via SIGKILL — no
                        response, no cleanup).  Deterministic "the plane
                        died with THIS batch in flight".
  ``plane_stall``       Like ``plane_crash`` but SIGSTOP: the plane
                        freezes mid-batch (connections stay open, nothing
                        answers) until an external SIGCONT.
  ``rpc_delay_ms``      verifyd responses are delayed <value> ms ±50%
                        jitter before hitting the socket.
  ``rpc_drop_pct``      <value> percent of verifyd responses are silently
                        dropped (the request WAS verified; the client's
                        deadline/retry machinery must recover).
  ====================  ====================================================

Zero cost when nothing is armed: every seam's first check is one
module-level bool read (the tracing/healthmon contract).  Crash points
stay zero-cost when ``FAIL_TEST_INDEX`` is unset (one env read at import).
"""

from __future__ import annotations

import os
import random
import sys
import threading
import time

EXIT_CODE = 75  # distinct from normal exits so tests can assert the crash

_target = int(os.environ.get("FAIL_TEST_INDEX", "-1"))
_counter = 0


def fail_point(label: str = "") -> None:
    """Crash here if this is the FAIL_TEST_INDEX'th fail point."""
    global _counter
    if _target < 0:
        return
    _counter += 1
    if _counter == _target:
        print(f"FAIL_TEST_INDEX={_target} hit at {label!r}", file=sys.stderr)
        sys.stderr.flush()
        os._exit(EXIT_CODE)


def points_hit() -> int:
    return _counter


# ------------------------------------------------------- fault registry

FAULTS = (
    "wedge_device",
    "slow_collect",
    "fail_dispatch",
    "drop_p2p_pct",
    "delay_p2p_ms",
    "double_sign",
    "plane_crash",
    "plane_stall",
    "rpc_delay_ms",
    "rpc_drop_pct",
)

_ANY_ARMED = False  # fast-path bool: every seam checks this first
_MTX = threading.Lock()
_ARMED: dict[str, float] = {}
_FIRED: dict[str, int] = {}
# cleared-or-armed notification so wedge_wait() wakes promptly
_CHANGED = threading.Event()
_RAND = random.Random()


class InjectedFault(RuntimeError):
    """Raised by a seam whose fault is armed (e.g. ``fail_dispatch``)."""


def arm(name: str, value: float = 1.0) -> None:
    """Arm a fault.  ``value`` parameterizes it (seconds for
    ``slow_collect``, a percentage for ``drop_p2p_pct``, a shot count for
    ``double_sign``); unknown names raise so a typo'd chaos scenario
    fails loudly instead of injecting nothing."""
    global _ANY_ARMED
    if name not in FAULTS:
        raise ValueError(f"unknown fault {name!r} (known: {', '.join(FAULTS)})")
    with _MTX:
        _ARMED[name] = float(value)
        _ANY_ARMED = True
        _CHANGED.set()
        _CHANGED.clear()


def clear(name: str) -> None:
    global _ANY_ARMED
    with _MTX:
        _ARMED.pop(name, None)
        _ANY_ARMED = bool(_ARMED)
        _CHANGED.set()
        _CHANGED.clear()


def clear_all() -> None:
    global _ANY_ARMED
    with _MTX:
        _ARMED.clear()
        _ANY_ARMED = False
        _CHANGED.set()
        _CHANGED.clear()


def armed(name: str) -> float | None:
    """The fault's armed value, or None.  One bool read when nothing is
    armed — safe on every hot path."""
    if not _ANY_ARMED:
        return None
    with _MTX:
        v = _ARMED.get(name)
        if v is not None:
            _FIRED[name] = _FIRED.get(name, 0) + 1
    return v


def consume(name: str) -> float | None:
    """Like :func:`armed` but decrements a shot count: a fault armed with
    value N fires N times then disarms itself (``double_sign`` arms one
    equivocation, not an equivocation per height forever)."""
    global _ANY_ARMED
    if not _ANY_ARMED:
        return None
    with _MTX:
        v = _ARMED.get(name)
        if v is None:
            return None
        _FIRED[name] = _FIRED.get(name, 0) + 1
        if v <= 1.0:
            _ARMED.pop(name, None)
            _ANY_ARMED = bool(_ARMED)
        else:
            _ARMED[name] = v - 1.0
    return v


def active() -> dict[str, float]:
    """Snapshot of armed faults (the ``faults`` RPC payload)."""
    with _MTX:
        return dict(_ARMED)


def fired() -> dict[str, int]:
    """How many times each fault's seam has observed it armed."""
    with _MTX:
        return dict(_FIRED)


def _peek(name: str) -> float | None:
    """armed() without bumping the fire tally — for poll loops, so the
    ``faults`` RPC's per-fault counts mean 'times a seam bit', not
    'times a parked seam re-checked'."""
    if not _ANY_ARMED:
        return None
    with _MTX:
        return _ARMED.get(name)


def wedge_wait(name: str = "wedge_device", poll_s: float = 0.05) -> float:
    """Block while ``name`` is armed — the injected analogue of a device
    result wait that never completes.  Returns the seconds blocked (0.0
    on the unarmed fast path).  The wait polls a shared change event so
    clearing the fault releases every parked seam within ``poll_s``.
    Counts as ONE fire however long it parks."""
    if not _ANY_ARMED or armed(name) is None:
        return 0.0
    t0 = time.monotonic()
    while _peek(name) is not None:
        _CHANGED.wait(poll_s)
    return time.monotonic() - t0


def should_drop(pct: float) -> bool:
    """One Bernoulli roll for the percentage faults (``drop_p2p_pct``,
    ``rpc_drop_pct``; clamped to [0, 100])."""
    if pct <= 0:
        return False
    if pct >= 100:
        return True
    return _RAND.random() * 100.0 < pct


def jittered_sleep(ms: float) -> float:
    """Sleep ``ms`` milliseconds ±50% uniform jitter (the latency faults
    ``delay_p2p_ms`` / ``rpc_delay_ms``); returns the seconds slept."""
    if ms <= 0:
        return 0.0
    d = (ms / 1e3) * (0.5 + _RAND.random())
    time.sleep(d)
    return d


def _arm_from_env() -> None:
    """Arm faults named by the declared COMETBFT_TPU_FAULT_* knobs — the
    e2e runner sets them per node process; production never does.  Read
    through the envknobs registry so the knob inventory stays complete
    (envknobs is stdlib-only, so this import adds nothing to the crash-
    point fast path)."""
    from . import envknobs

    for name, knob in (
        ("wedge_device", envknobs.FAULT_WEDGE_DEVICE),
        ("slow_collect", envknobs.FAULT_SLOW_COLLECT),
        ("fail_dispatch", envknobs.FAULT_FAIL_DISPATCH),
        ("drop_p2p_pct", envknobs.FAULT_DROP_P2P_PCT),
        ("delay_p2p_ms", envknobs.FAULT_DELAY_P2P_MS),
        ("double_sign", envknobs.FAULT_DOUBLE_SIGN),
        ("plane_crash", envknobs.FAULT_PLANE_CRASH),
        ("plane_stall", envknobs.FAULT_PLANE_STALL),
        ("rpc_delay_ms", envknobs.FAULT_RPC_DELAY_MS),
        ("rpc_drop_pct", envknobs.FAULT_RPC_DROP_PCT),
    ):
        spec = envknobs.get_str(knob).strip()
        if not spec:
            continue
        try:
            arm(name, float(spec))
        except ValueError:
            arm(name, 1.0)  # any non-numeric truthy spec arms with 1


_arm_from_env()
