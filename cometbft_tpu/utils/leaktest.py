"""Thread-leak detection (reference: go.mod:20 fortytw2/leaktest — the
goroutine-leak analogue; Go's -race has no Python equivalent, so the
raceable surface is covered by leak checks + the deadlock watchdog).

check_threads() snapshots live threads around a block and fails if new
ones outlive it; watchdog() dumps every thread's stack if a block runs
past its deadline (faulthandler), turning silent deadlocks into
actionable tracebacks in CI.
"""

from __future__ import annotations

import contextlib
import threading
import time


class ThreadLeakError(AssertionError):
    pass


@contextlib.contextmanager
def check_threads(grace_s: float = 3.0, allow: tuple[str, ...] = ()):
    """Fail if threads started inside the block are still alive after it
    (after up to grace_s of settling — stop() paths run on timeouts).

    allow: name prefixes exempt from the check (e.g. interpreter-owned
    pools)."""
    # hold strong references to the Thread OBJECTS — idents (and ids of
    # collected objects) are reused after a thread exits, so an ident set
    # can mistake a leak for a pre-existing thread
    before = list(threading.enumerate())
    yield
    deadline = time.monotonic() + grace_s
    leaked: list[threading.Thread] = []
    while time.monotonic() < deadline:
        leaked = [
            t
            for t in threading.enumerate()
            if t not in before
            and t.is_alive()
            and not any(t.name.startswith(p) for p in allow)
        ]
        if not leaked:
            return
        time.sleep(0.1)
    names = ", ".join(f"{t.name}({t.ident})" for t in leaked)
    raise ThreadLeakError(f"{len(leaked)} thread(s) leaked: {names}")


def rss_bytes() -> int:
    """This process's resident set size.  /proc when available (Linux),
    else ru_maxrss (peak, not current — still monotone-usable for a
    "did it keep growing" check); 0 when neither source exists."""
    try:
        with open("/proc/self/status") as f:
            for line in f:
                if line.startswith("VmRSS:"):
                    return int(line.split()[1]) * 1024
    except OSError:
        pass
    try:
        import resource

        ru = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
        # Linux reports KiB, macOS bytes; either way it's a watermark
        return ru * 1024 if ru < 1 << 34 else ru
    except (ImportError, OSError, ValueError):
        return 0


class ResourceWatermarks:
    """Periodic RSS/thread/custom-gauge sampling for endurance runs (the
    soak harness's no-leak assertion): sample() appends one row; flat()
    judges whether the tail of the run grew past the head by more than
    the allowed tolerance.  Gauges are zero-arg callables (e.g. a lambda
    over the verify service's queue depths) sampled alongside the
    built-ins."""

    def __init__(self, gauges: dict | None = None):
        self.gauges = dict(gauges or {})
        self.samples: list[dict] = []

    def sample(self) -> dict:
        row = {
            "t": time.monotonic(),
            "rss_bytes": rss_bytes(),
            "threads": threading.active_count(),
        }
        for name, fn in self.gauges.items():
            try:
                row[name] = fn()
            except Exception:  # noqa: BLE001 — a dead gauge must not kill the soak
                row[name] = None
        self.samples.append(row)
        return row

    def _window_avg(self, key: str, rows: list[dict]) -> float | None:
        vals = [r[key] for r in rows if isinstance(r.get(key), (int, float))]
        return sum(vals) / len(vals) if vals else None

    def flat(
        self,
        rss_tolerance_bytes: int = 64 << 20,
        rss_tolerance_frac: float = 0.2,
        thread_tolerance: int = 4,
        window_frac: float = 0.2,
    ) -> dict:
        """Compare the average of the FIRST window_frac of samples to
        the LAST: RSS may grow by at most max(tolerance_bytes,
        frac * head) and the thread count by thread_tolerance.  Returns
        a verdict dict ({"ok": bool, ...per-resource detail}) rather
        than raising — the soak folds it into its SLO artifact."""
        n = len(self.samples)
        out: dict = {"ok": False, "samples": n}
        if n < 4:
            out["detail"] = "not enough samples"
            return out
        w = max(2, int(n * window_frac))
        head, tail = self.samples[:w], self.samples[-w:]
        rss0 = self._window_avg("rss_bytes", head)
        rss1 = self._window_avg("rss_bytes", tail)
        thr0 = self._window_avg("threads", head)
        thr1 = self._window_avg("threads", tail)
        rss_allow = max(rss_tolerance_bytes, (rss0 or 0) * rss_tolerance_frac)
        rss_ok = rss0 is None or rss1 is None or (rss1 - rss0) <= rss_allow
        thr_ok = thr0 is None or thr1 is None or (thr1 - thr0) <= thread_tolerance
        out.update(
            ok=bool(rss_ok and thr_ok),
            rss_head_bytes=None if rss0 is None else int(rss0),
            rss_tail_bytes=None if rss1 is None else int(rss1),
            rss_grew_bytes=(
                None if (rss0 is None or rss1 is None) else int(rss1 - rss0)
            ),
            rss_allowed_bytes=int(rss_allow),
            rss_ok=bool(rss_ok),
            threads_head=thr0, threads_tail=thr1, threads_ok=bool(thr_ok),
        )
        return out


@contextlib.contextmanager
def watchdog(timeout_s: float = 60.0):
    """Dump all thread stacks to stderr if the block exceeds timeout_s
    (the hung-test analogue of `cometbft debug kill`'s goroutine dump)."""
    import faulthandler

    faulthandler.dump_traceback_later(timeout_s, exit=False)
    try:
        yield
    finally:
        faulthandler.cancel_dump_traceback_later()
