"""Thread-leak detection (reference: go.mod:20 fortytw2/leaktest — the
goroutine-leak analogue; Go's -race has no Python equivalent, so the
raceable surface is covered by leak checks + the deadlock watchdog).

check_threads() snapshots live threads around a block and fails if new
ones outlive it; watchdog() dumps every thread's stack if a block runs
past its deadline (faulthandler), turning silent deadlocks into
actionable tracebacks in CI.
"""

from __future__ import annotations

import contextlib
import threading
import time


class ThreadLeakError(AssertionError):
    pass


@contextlib.contextmanager
def check_threads(grace_s: float = 3.0, allow: tuple[str, ...] = ()):
    """Fail if threads started inside the block are still alive after it
    (after up to grace_s of settling — stop() paths run on timeouts).

    allow: name prefixes exempt from the check (e.g. interpreter-owned
    pools)."""
    # hold strong references to the Thread OBJECTS — idents (and ids of
    # collected objects) are reused after a thread exits, so an ident set
    # can mistake a leak for a pre-existing thread
    before = list(threading.enumerate())
    yield
    deadline = time.monotonic() + grace_s
    leaked: list[threading.Thread] = []
    while time.monotonic() < deadline:
        leaked = [
            t
            for t in threading.enumerate()
            if t not in before
            and t.is_alive()
            and not any(t.name.startswith(p) for p in allow)
        ]
        if not leaked:
            return
        time.sleep(0.1)
    names = ", ".join(f"{t.name}({t.ident})" for t in leaked)
    raise ThreadLeakError(f"{len(leaked)} thread(s) leaked: {names}")


@contextlib.contextmanager
def watchdog(timeout_s: float = 60.0):
    """Dump all thread stacks to stderr if the block exceeds timeout_s
    (the hung-test analogue of `cometbft debug kill`'s goroutine dump)."""
    import faulthandler

    faulthandler.dump_traceback_later(timeout_s, exit=False)
    try:
        yield
    finally:
        faulthandler.cancel_dump_traceback_later()
