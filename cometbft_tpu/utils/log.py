"""Structured logging with per-module level filtering (reference:
libs/log/, filter.go)."""

from __future__ import annotations

import logging
import sys

from . import envknobs

_CONFIGURED = False


def _configure() -> None:
    global _CONFIGURED
    if _CONFIGURED:
        return
    level = envknobs.get_str(envknobs.LOG_LEVEL).upper()
    handler = logging.StreamHandler(sys.stderr)
    handler.setFormatter(
        logging.Formatter(
            "%(asctime)s %(levelname).1s %(name)s: %(message)s", "%H:%M:%S"
        )
    )
    root = logging.getLogger("cometbft_tpu")
    root.setLevel(getattr(logging, level, logging.INFO))
    root.addHandler(handler)
    root.propagate = False
    _CONFIGURED = True


def get_logger(module: str) -> logging.Logger:
    _configure()
    return logging.getLogger(f"cometbft_tpu.{module}")
