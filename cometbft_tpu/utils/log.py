"""Structured logging with per-module level filtering (reference:
libs/log/, filter.go)."""

from __future__ import annotations

import logging
import os
import sys

_CONFIGURED = False


def _configure() -> None:
    global _CONFIGURED
    if _CONFIGURED:
        return
    level = os.environ.get("COMETBFT_TPU_LOG_LEVEL", "INFO").upper()
    handler = logging.StreamHandler(sys.stderr)
    handler.setFormatter(
        logging.Formatter(
            "%(asctime)s %(levelname).1s %(name)s: %(message)s", "%H:%M:%S"
        )
    )
    root = logging.getLogger("cometbft_tpu")
    root.setLevel(getattr(logging, level, logging.INFO))
    root.addHandler(handler)
    root.propagate = False
    _CONFIGURED = True


def get_logger(module: str) -> logging.Logger:
    _configure()
    return logging.getLogger(f"cometbft_tpu.{module}")
