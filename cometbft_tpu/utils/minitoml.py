"""Minimal TOML reader for Python < 3.11 hosts without `tomllib`.

Covers exactly the subset this framework emits (config._emit) and its
tests write by hand: one level of `[section]` tables, `key = value`
lines with bool / int / float / double-quoted string (\\ and \" escapes)
/ single-line array values, and `#` comments.  Anything richer (dotted
keys, multiline strings, datetimes, nested tables) raises ValueError —
better loud than silently misread configuration.
"""

from __future__ import annotations


class TOMLDecodeError(ValueError):
    pass


def load(fp) -> dict:
    data = fp.read()
    if isinstance(data, bytes):
        data = data.decode("utf-8")
    return loads(data)


def loads(text: str) -> dict:
    root: dict = {}
    table = root
    for lineno, raw in enumerate(text.splitlines(), 1):
        line = _strip_comment(raw).strip()
        if not line:
            continue
        if line.startswith("["):
            if not line.endswith("]"):
                raise TOMLDecodeError(f"line {lineno}: malformed table header")
            name = line[1:-1].strip()
            if not name or "[" in name or '"' in name:
                raise TOMLDecodeError(f"line {lineno}: unsupported table {name!r}")
            table = root.setdefault(name, {})
            if not isinstance(table, dict):
                raise TOMLDecodeError(f"line {lineno}: {name!r} redefined")
            continue
        if "=" not in line:
            raise TOMLDecodeError(f"line {lineno}: expected key = value")
        key, _, val = line.partition("=")
        key = key.strip()
        if key.startswith('"') and key.endswith('"') and len(key) >= 2:
            key = key[1:-1]
        if not key or "." in key or " " in key:
            raise TOMLDecodeError(f"line {lineno}: unsupported key {key!r}")
        table[key] = _value(val.strip(), lineno)
    return root


def _strip_comment(line: str) -> str:
    """Drop a trailing # comment, respecting double-quoted strings."""
    out = []
    in_str = False
    i = 0
    while i < len(line):
        c = line[i]
        if in_str and c == "\\" and i + 1 < len(line):
            out.append(line[i : i + 2])
            i += 2
            continue
        if c == '"':
            in_str = not in_str
        elif c == "#" and not in_str:
            break
        out.append(c)
        i += 1
    return "".join(out)


def _value(tok: str, lineno: int):
    if tok == "true":
        return True
    if tok == "false":
        return False
    if tok.startswith('"'):
        return _string(tok, lineno)
    if tok.startswith("[") and tok.endswith("]"):
        inner = tok[1:-1].strip()
        if not inner:
            return []
        return [_value(p.strip(), lineno) for p in _split_array(inner, lineno)]
    try:
        return int(tok, 0) if not any(c in tok for c in ".eE") else float(tok)
    except ValueError:
        pass
    try:
        return float(tok)
    except ValueError:
        raise TOMLDecodeError(f"line {lineno}: unsupported value {tok!r}") from None


def _string(tok: str, lineno: int) -> str:
    if len(tok) < 2 or not tok.endswith('"'):
        raise TOMLDecodeError(f"line {lineno}: unterminated string {tok!r}")
    body = tok[1:-1]
    out = []
    i = 0
    while i < len(body):
        c = body[i]
        if c == "\\":
            if i + 1 >= len(body):
                raise TOMLDecodeError(f"line {lineno}: dangling escape")
            nxt = body[i + 1]
            mapped = {"\\": "\\", '"': '"', "n": "\n", "t": "\t", "r": "\r"}.get(nxt)
            if mapped is None:
                raise TOMLDecodeError(f"line {lineno}: unsupported escape \\{nxt}")
            out.append(mapped)
            i += 2
            continue
        if c == '"':
            raise TOMLDecodeError(f"line {lineno}: stray quote in {tok!r}")
        out.append(c)
        i += 1
    return "".join(out)


def _split_array(inner: str, lineno: int) -> list[str]:
    parts = []
    depth = 0
    in_str = False
    cur = []
    i = 0
    while i < len(inner):
        c = inner[i]
        if in_str and c == "\\":
            cur.append(inner[i : i + 2])
            i += 2
            continue
        if c == '"':
            in_str = not in_str
        elif not in_str:
            if c == "[":
                depth += 1
            elif c == "]":
                depth -= 1
            elif c == "," and depth == 0:
                parts.append("".join(cur))
                cur = []
                i += 1
                continue
        cur.append(c)
        i += 1
    if cur:
        parts.append("".join(cur))
    return parts
