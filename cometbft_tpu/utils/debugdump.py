"""Process introspection for the debug endpoints and `debug dump`
(reference: cmd/cometbft/commands/debug — goroutine/heap profiles via
net/http/pprof; the Python equivalents are frame dumps over
sys._current_frames and gc/tracemalloc summaries).
"""

from __future__ import annotations

import gc
import json
import os
import sys
import threading
import time
import traceback


def thread_dump() -> str:
    """Stack trace of every live thread — the goroutine-profile
    analogue."""
    frames = sys._current_frames()
    by_id = {t.ident: t for t in threading.enumerate()}
    out = [f"{len(frames)} threads\n"]
    for tid, frame in frames.items():
        t = by_id.get(tid)
        name = t.name if t else "?"
        daemon = " daemon" if (t and t.daemon) else ""
        out.append(f"--- thread {tid} [{name}]{daemon} ---")
        out.extend(line.rstrip() for line in traceback.format_stack(frame))
        out.append("")
    return "\n".join(out)


def heap_summary(top: int = 25) -> str:
    """Heap profile analogue: tracemalloc top allocations when tracing
    is on (PYTHONTRACEMALLOC=1), else gc object-type census."""
    import tracemalloc

    if tracemalloc.is_tracing():
        snap = tracemalloc.take_snapshot()
        stats = snap.statistics("lineno")[:top]
        total = sum(s.size for s in snap.statistics("filename"))
        out = [f"tracemalloc: {total / 1e6:.1f} MB traced\n"]
        out.extend(str(s) for s in stats)
        return "\n".join(out)
    counts: dict[str, int] = {}
    for obj in gc.get_objects():
        name = type(obj).__name__
        counts[name] = counts.get(name, 0) + 1
    top_types = sorted(counts.items(), key=lambda kv: -kv[1])[:top]
    out = [
        "tracemalloc off (set PYTHONTRACEMALLOC=1 for allocation sites); "
        f"gc census of {sum(counts.values())} objects:\n"
    ]
    out.extend(f"{n:>9}  {t}" for t, n in top_types)
    return "\n".join(out)


def flight_record_text() -> str:
    """The consensus flight recorder's ring as pretty JSON (the same
    payload /dump_consensus_trace serves)."""
    from .flightrec import recorder

    return json.dumps(recorder().dump(), indent=1, default=str)


def stall_report(
    reason: str,
    extra_sections: list[tuple[str, str]] | None = None,
    directory: str | None = None,
) -> str:
    """Write a stall-forensics bundle — reason, caller-supplied sections
    (the health sentinel passes its snapshot, the verify-service stats
    with in-flight batch ages, and a trace-ring drain), flight-recorder
    dump, all-thread stacks — and return its path.  The crash_report
    sibling for a node that is WEDGED rather than dead: called by
    utils/healthmon on a probe deadline breach or stale heartbeat; must
    never raise (the node is already in trouble)."""
    import tempfile

    directory = directory or tempfile.gettempdir()
    path = os.path.join(
        directory, f"cometbft-health-{os.getpid()}-{time.time_ns()}.txt"
    )
    sections = [
        f"=== stall forensics ===\nreason: {reason}\nwall_ns: {time.time_ns()}\n"
    ]
    for title, body in extra_sections or []:
        sections.append(f"=== {title} ===")
        sections.append(body)
    sections.extend(
        [
            "=== consensus flight recorder ===",
            flight_record_text(),
            "=== threads ===",
            thread_dump(),
        ]
    )
    with open(path, "w") as f:
        f.write("\n".join(sections))
    return path


def crash_report(reason: str, directory: str | None = None) -> str:
    """Write a post-mortem bundle — reason, consensus flight-recorder
    dump, all-thread stack dump — to a file and return its path.  Called
    from the consensus receive routine's fatal-error branch so the last
    N state-machine events survive the crash; must never raise (it runs
    inside an exception handler)."""
    import tempfile

    directory = directory or tempfile.gettempdir()
    path = os.path.join(
        directory, f"cometbft-crash-{os.getpid()}-{time.time_ns()}.txt"
    )
    sections = [
        f"=== crash report ===\nreason: {reason}\nwall_ns: {time.time_ns()}\n",
        "=== consensus flight recorder ===",
        flight_record_text(),
        "=== threads ===",
        thread_dump(),
    ]
    with open(path, "w") as f:
        f.write("\n".join(sections))
    return path
