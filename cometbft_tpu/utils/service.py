"""Service lifecycle base class (reference: libs/service/service.go:26).

start/stop-once semantics with overridable on_start/on_stop hooks; every
long-running component (reactors, stores, the node itself) extends this.
"""

from __future__ import annotations

import threading

from .log import get_logger


class ServiceError(Exception):
    pass


class AlreadyStartedError(ServiceError):
    pass


class AlreadyStoppedError(ServiceError):
    pass


class NotStartedError(ServiceError):
    pass


class Service:
    def __init__(self, name: str | None = None):
        self._name = name or type(self).__name__
        self._started = False
        self._stopped = False
        self._mtx = threading.Lock()
        self._quit = threading.Event()
        self.logger = get_logger(self._name)

    @property
    def name(self) -> str:
        return self._name

    def start(self) -> None:
        with self._mtx:
            if self._started:
                raise AlreadyStartedError(f"{self._name} already started")
            if self._stopped:
                raise AlreadyStoppedError(f"{self._name} already stopped")
            self._started = True
        self.logger.info("service start")
        try:
            self.on_start()
        except Exception:
            with self._mtx:
                self._started = False
            raise

    def stop(self) -> None:
        with self._mtx:
            if self._stopped:
                return
            if not self._started:
                raise NotStartedError(f"{self._name} not started")
            self._stopped = True
        self.logger.info("service stop")
        self._quit.set()
        self.on_stop()

    def is_running(self) -> bool:
        with self._mtx:
            return self._started and not self._stopped

    def wait(self, timeout: float | None = None) -> None:
        self._quit.wait(timeout)

    @property
    def quit_event(self) -> threading.Event:
        return self._quit

    # hooks
    def on_start(self) -> None: ...

    def on_stop(self) -> None: ...
