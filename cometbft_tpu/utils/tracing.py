"""In-process span tracer for the verification plane and consensus core.

A low-overhead tracer in the spirit of the Chrome trace-event profile
format: call sites open monotonic-clock spans (`with tracing.span("x")`)
or drop instant markers (`tracing.instant("y")`); finished events land in
a per-thread buffer (appends touch no lock) that drains in chunks into
one process-global bounded ring, and the whole ring exports as Chrome
trace-event JSON — open the file in Perfetto (ui.perfetto.dev) or
chrome://tracing to see the VerifyCommit pipeline (slab fill, H2D,
kernel dispatch, device wait, collect) laid out across the caller,
staging, and blocksync threads.

Cost model: tracing is OFF by default and the disabled path is a single
module-bool check returning a shared no-op context manager — no
allocation, no clock read — so the hot paths stay instrumented in
production builds.  Enabled, a span is two perf_counter_ns reads plus a
tuple append; the ring bounds total memory however long the run.

Enable with COMETBFT_TPU_TRACE=1 (drain via export_chrome_trace / the
API) or COMETBFT_TPU_TRACE=/path/to/out.trace.json to also auto-export
at interpreter exit.  COMETBFT_TPU_TRACE_RING sizes the ring (events,
default 65536).

Cross-process correlation: a :class:`SpanContext` (W3C-traceparent-
shaped trace_id/span_id pair) can be installed as the thread's current
context (:func:`context_scope`); every event recorded under a scope
carries ``trace_id``/``span_id`` args, and the context serializes to /
parses from a ``traceparent`` string so it can ride a wire field — the
verify plane's RPC layer propagates it, and ``scripts/trace_merge.py``
stitches the per-process exports into one timeline where client and
server spans of a remote verify share a trace_id.
COMETBFT_TPU_TRACE_CTX=0 turns propagation off (events stay local).
"""

from __future__ import annotations

import json
import os
import threading
import time
import weakref

from . import envknobs

_OFF_VALUES = ("", "0", "false", "off", "no")
_ON_VALUES = ("1", "true", "on", "yes")

# events drain from thread-local buffers to the ring in chunks this big;
# small enough that an export misses at most a few dozen in-flight events
_CHUNK = 64
_DEFAULT_RING = 65536

_ENABLED = False
_CTX_ENABLED = True  # COMETBFT_TPU_TRACE_CTX — span-context propagation
_EXPORT_PATH: str | None = None

_ring_mtx = threading.Lock()
_ring: list = []  # bounded manually (deque has no atomic bulk-swap)
_ring_cap = _DEFAULT_RING
_dropped = 0

_bufs_mtx = threading.Lock()
_bufs: list = []  # [(weakref-to-thread, buf list, tid), ...]
_thread_names: dict[int, str] = {}
# registration-time pruning threshold: beyond this many registered
# buffers, dead threads' buffers are flushed and dropped so per-peer
# thread churn can't grow _bufs/_thread_names for the process lifetime
_PRUNE_AT = 256

_tls = threading.local()


def enabled() -> bool:
    return _ENABLED


def set_enabled(on: bool, ring_capacity: int | None = None) -> None:
    """Runtime switch (tests, the trace script, bench).  Turning tracing
    on never clears previously collected events; call reset() for a
    clean capture window."""
    global _ENABLED, _ring_cap
    if ring_capacity is not None:
        with _ring_mtx:
            _ring_cap = max(1, int(ring_capacity))
            del _ring[: max(0, len(_ring) - _ring_cap)]
    _ENABLED = bool(on)


def reset() -> None:
    """Drop every buffered event (thread-local and ring)."""
    global _dropped
    with _bufs_mtx:
        entries = list(_bufs)
    for _tref, buf, _tid in entries:
        del buf[:]
    with _ring_mtx:
        del _ring[:]
        _dropped = 0


def dropped_count() -> int:
    """Events evicted from the ring since the last reset()."""
    return _dropped


# ----------------------------------------------------------- span context


class SpanContext:
    """Propagable identity of one distributed trace: a 16-byte trace_id
    shared by every span of the trace (across processes) and an 8-byte
    span_id naming this hop.  Shaped after the W3C traceparent header
    (version 00, sampled flag always 01) so the wire form is a plain
    printable string any tracing stack recognizes."""

    __slots__ = ("trace_id", "span_id")

    def __init__(self, trace_id: str, span_id: str):
        self.trace_id = trace_id
        self.span_id = span_id

    def child(self) -> "SpanContext":
        """Same trace, fresh hop id — what a server installs so its
        spans link to the client's without claiming its span_id."""
        return SpanContext(self.trace_id, os.urandom(8).hex())

    def to_traceparent(self) -> str:
        return f"00-{self.trace_id}-{self.span_id}-01"

    @classmethod
    def from_traceparent(cls, header: str) -> "SpanContext | None":
        """Parse a traceparent string; None on anything malformed — a
        bad context from a peer must degrade to 'unlinked', never raise
        into the request path."""
        parts = header.split("-")
        if len(parts) != 4:
            return None
        _ver, tid, sid, _flags = parts
        if len(tid) != 32 or len(sid) != 16:
            return None
        try:
            int(tid, 16)
            int(sid, 16)
        except ValueError:
            return None
        if tid == "0" * 32 or sid == "0" * 16:
            return None
        return cls(tid, sid)

    def __eq__(self, other):
        return (
            isinstance(other, SpanContext)
            and self.trace_id == other.trace_id
            and self.span_id == other.span_id
        )

    def __repr__(self):
        return f"SpanContext({self.to_traceparent()!r})"


def new_context() -> SpanContext:
    """A fresh root context (random trace_id + span_id)."""
    return SpanContext(os.urandom(16).hex(), os.urandom(8).hex())


def current_context() -> SpanContext | None:
    """The calling thread's installed context, if any."""
    return getattr(_tls, "ctx", None)


class _CtxScope:
    __slots__ = ("_ctx", "_prev", "_installed")

    def __init__(self, ctx):
        self._ctx = ctx
        self._installed = ctx is not None

    def __enter__(self):
        if self._installed:
            self._prev = getattr(_tls, "ctx", None)
            _tls.ctx = self._ctx
        return self._ctx

    def __exit__(self, *exc) -> bool:
        if self._installed:
            _tls.ctx = self._prev
        return False


def context_scope(ctx: SpanContext | None):
    """Install ``ctx`` as the thread's current context for the block:
    every span/instant recorded inside carries its trace_id/span_id
    args.  ``None`` leaves the current context untouched (so call sites
    can pass an optional context unconditionally)."""
    return _CtxScope(ctx if propagation_enabled() else None)


def propagation_enabled() -> bool:
    return _ENABLED and _CTX_ENABLED


# ------------------------------------------------------------- recording


_tid_counter = 0


def _buf() -> list:
    b = getattr(_tls, "buf", None)
    if b is None:
        global _tid_counter
        b = _tls.buf = []
        t = threading.current_thread()
        with _bufs_mtx:
            if len(_bufs) >= _PRUNE_AT:
                _prune_dead_locked()
            # synthetic per-thread track id: OS thread idents are recycled
            # after thread exit, which would merge a dead thread's events
            # onto a new thread's track in the export
            _tid_counter += 1
            _tls.tid = _tid_counter
            _bufs.append((weakref.ref(t), b, _tls.tid))
            _thread_names[_tls.tid] = t.name
    return b


def _prune_dead_locked() -> None:
    """Flush and drop buffers (and name entries) of exited threads —
    caller holds _bufs_mtx.  Ring events from pruned threads keep their
    synthetic tid; only the name label for the track is lost."""
    keep = []
    for tref, b, tid in _bufs:
        if tref() is not None:
            keep.append((tref, b, tid))
        else:
            if b:
                _flush(b)
            _thread_names.pop(tid, None)
    _bufs[:] = keep


def _flush(b: list) -> None:
    """Move a buffer's events into the bounded ring.  The copy+delete and
    the ring extend happen under ONE lock: the owner thread's chunk flush
    and an exporter's drain may race on the same buffer, and an unlocked
    copy would insert the same chunk twice."""
    global _dropped
    with _ring_mtx:
        items = b[:]
        del b[: len(items)]
        _ring.extend(items)
        overflow = len(_ring) - _ring_cap
        if overflow > 0:
            del _ring[:overflow]
            _dropped += overflow


def _emit(ph: str, name: str, ts_ns: int, dur_ns: int, labels) -> None:
    ctx = getattr(_tls, "ctx", None)
    if ctx is not None:
        # events recorded under a context scope carry the trace identity
        # as args — the cross-process link trace_merge.py stitches on
        merged = dict(labels) if labels else {}
        merged.setdefault("trace_id", ctx.trace_id)
        merged.setdefault("span_id", ctx.span_id)
        labels = merged
    b = _buf()
    b.append((ph, name, ts_ns, dur_ns, _tls.tid, labels))
    if len(b) >= _CHUNK:
        _flush(b)


class _Span:
    """One 'X' (complete) trace event, recorded at __exit__."""

    __slots__ = ("_name", "_labels", "_t0")

    def __init__(self, name: str, labels: dict | None):
        self._name = name
        self._labels = labels

    def __enter__(self) -> "_Span":
        self._t0 = time.perf_counter_ns()
        return self

    def __exit__(self, *exc) -> bool:
        t0 = self._t0
        _emit("X", self._name, t0, time.perf_counter_ns() - t0, self._labels)
        return False


class _NopSpan:
    __slots__ = ()

    def __enter__(self) -> "_NopSpan":
        return self

    def __exit__(self, *exc) -> bool:
        return False


_NOP = _NopSpan()


def span(name: str, labels: dict | None = None):
    """Context manager timing one pipeline phase.  Disabled: returns a
    shared no-op — the call site pays one bool check and no allocation
    (pass labels as a prebuilt dict, not kwargs, to keep that true)."""
    if not _ENABLED:
        return _NOP
    return _Span(name, labels)


def instant(name: str, labels: dict | None = None) -> None:
    """A zero-duration marker (step transitions, timeout fires)."""
    if not _ENABLED:
        return
    _emit("i", name, time.perf_counter_ns(), 0, labels)


# --------------------------------------------------------------- export


def _drain_all() -> tuple[list, dict]:
    """Flush every thread buffer into the ring, prune buffers AND name
    entries of dead threads, and return (ring snapshot, thread-name
    snapshot).  The name snapshot is taken before the prune, so the
    export in progress still labels just-exited threads' tracks; later
    exports show their remaining ring events on an unnamed track — the
    cosmetic price of keeping _thread_names bounded under thread churn.
    The ring itself is not cleared: repeat exports see a superset."""
    with _bufs_mtx:
        entries = list(_bufs)
        names = dict(_thread_names)
        live = [(tr, b, tid) for tr, b, tid in entries if tr() is not None]
        for tr, _b, tid in entries:
            if tr() is None:
                _thread_names.pop(tid, None)
        _bufs[:] = live
    for _tref, buf, _tid in entries:
        if buf:
            _flush(buf)
    with _ring_mtx:
        return list(_ring), names


def chrome_trace_events() -> list[dict]:
    """The buffered events as Chrome trace-event dicts (plus thread-name
    metadata records), timestamp-sorted."""
    events, names = _drain_all()
    pid = os.getpid()
    # Wall-clock anchor: every event timestamp in this export is pure
    # perf_counter_ns, while flight-recorder entries and log lines carry
    # wall-clock time — one (wall_ns, perf_ns) pair sampled at export
    # time lets a consumer line all three up on one timeline:
    #   wall_ns(event) = wall_time_ns + (event.ts * 1000 - perf_counter_ns)
    wall_anchor_ns = time.time_ns()
    perf_anchor_ns = time.perf_counter_ns()
    out: list[dict] = [
        {
            "ph": "M",
            "name": "wall_clock_anchor",
            "pid": pid,
            "tid": 0,
            "args": {
                "wall_time_ns": wall_anchor_ns,
                "perf_counter_ns": perf_anchor_ns,
            },
        }
    ]
    out += [
        {
            "ph": "M",
            "name": "thread_name",
            "pid": pid,
            "tid": tid,
            "args": {"name": tname},
        }
        for tid, tname in sorted(names.items())
    ]
    for ph, name, ts_ns, dur_ns, tid, labels in sorted(
        events, key=lambda e: e[2]
    ):
        e = {
            "ph": ph,
            "name": name,
            "cat": "cometbft",
            "pid": pid,
            "tid": tid,
            "ts": ts_ns / 1e3,  # trace-event timestamps are microseconds
        }
        if ph == "X":
            e["dur"] = dur_ns / 1e3
        elif ph == "i":
            e["s"] = "t"  # thread-scoped instant
        if labels:
            e["args"] = {k: _jsonable(v) for k, v in labels.items()}
        out.append(e)
    return out


def _jsonable(v):
    if isinstance(v, (bool, int, float, str)) or v is None:
        return v
    return str(v)


def export_chrome_trace(path: str) -> int:
    """Write {"traceEvents": [...]} JSON; returns the number of span /
    instant events written (metadata records excluded)."""
    events = chrome_trace_events()
    with open(path, "w") as f:
        json.dump({"traceEvents": events, "displayTimeUnit": "ms"}, f)
    return sum(1 for e in events if e["ph"] != "M")


# --------------------------------------------------- env-var resolution

def _atexit_export() -> None:
    try:
        export_chrome_trace(_EXPORT_PATH)
    except Exception:  # noqa: BLE001 — never traceback on interpreter exit
        pass


_v = envknobs.get_str(envknobs.TRACE)
if _v.lower() not in _OFF_VALUES:
    _ENABLED = True
    if _v.lower() not in _ON_VALUES and (os.sep in _v or _v.endswith(".json")):
        # unambiguously a path: auto-export the ring at process exit.
        # Other truthy values ("2", "debug", ...) just enable recording —
        # they must not turn into a stray file named after themselves.
        _EXPORT_PATH = _v
        import atexit

        atexit.register(_atexit_export)
_ring_cap = max(1, envknobs.get_int(envknobs.TRACE_RING))
_CTX_ENABLED = envknobs.get_bool(envknobs.TRACE_CTX)
del _v
