"""JAX persistent compilation cache, behind COMETBFT_TPU_COMPILE_CACHE.

The multi-chip cold-start problem (ROADMAP item 1, MULTICHIP_r05) is
dominated by XLA: the fused Ed25519 kernel compiles in minutes on the
CPU backend and tens of seconds on TPU, and the sharded comb programs
re-pay it per (shape, mesh).  With the persistent cache pointed at a
durable directory, a warm pod restart deserializes the executables
instead — compile once per image, not once per process.

``maybe_enable()`` is wired into the production entry (``__main__.py``)
and ``bench.py``.  It is deliberately forgiving: an unusable directory
or a jax too old for the config keys degrades to "no cache", never a
startup failure.  The knob must name a DURABLE, per-host directory —
a corrupt entry (e.g. a process killed mid-write on shared storage)
can crash jax's cache read path, which is why there is no default dir:
opting in is an operator decision.

Call it before the first compile; flipping the config later in the
process is a no-op for programs already compiled.
"""

from __future__ import annotations

import os

from . import envknobs
from .log import get_logger

logger = get_logger("compilecache")


def maybe_enable(default_dir: str | None = None) -> str | None:
    """Point jax's persistent compilation cache at the knob's directory
    (or ``default_dir`` when the knob is unset).  Returns the directory
    on success, None when disabled or unusable."""
    cache_dir = envknobs.get_str(envknobs.COMPILE_CACHE) or default_dir
    if not cache_dir:
        return None
    cache_dir = os.path.abspath(cache_dir)
    try:
        os.makedirs(cache_dir, exist_ok=True)
        import jax

        jax.config.update("jax_compilation_cache_dir", cache_dir)
        # every kernel of the verify plane is worth persisting: the
        # small ones are milliseconds to write, the comb/sharded ones
        # are the minutes this cache exists to kill
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
    except Exception as e:  # noqa: BLE001 - the cache is an optimization only
        logger.warning("persistent compile cache unusable at %s: %s",
                       cache_dir, e)
        return None
    logger.info("persistent compile cache enabled at %s", cache_dir)
    return cache_dir
