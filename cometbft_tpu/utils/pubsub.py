"""In-process pubsub with event queries (reference: libs/pubsub/ + the
query language in libs/pubsub/query/).

Subscribers register a Query; published (message, events) pairs are
matched and delivered over per-subscriber queues.  The query language
covers the subset the RPC layer uses: `tm.event='NewBlock'`,
`tx.height=5`, conjunction with AND, =, <, >, <=, >=, CONTAINS, EXISTS.
"""

from __future__ import annotations

import queue
import re
import threading
from dataclasses import dataclass, field


class Query:
    """Parsed event query (reference: libs/pubsub/query/query.go)."""

    _COND_RE = re.compile(
        r"\s*([\w.]+)\s*(=|<=|>=|<|>|CONTAINS|EXISTS)\s*('(?:[^']*)'|[\w.\-]+)?\s*"
    )

    def __init__(self, expr: str):
        self.expr = expr
        self.conditions: list[tuple[str, str, str | None]] = []
        if expr.strip():
            for part in expr.split(" AND "):
                m = self._COND_RE.fullmatch(part)
                if not m:
                    raise ValueError(f"invalid query condition: {part!r}")
                key, op, val = m.group(1), m.group(2), m.group(3)
                if val is not None and val.startswith("'"):
                    val = val[1:-1]
                if op != "EXISTS" and val is None:
                    raise ValueError(f"operator {op} requires a value: {part!r}")
                self.conditions.append((key, op, val))

    def matches(self, events: dict[str, list[str]]) -> bool:
        for key, op, want in self.conditions:
            values = events.get(key)
            if values is None:
                return False
            if op == "EXISTS":
                continue
            ok = False
            for v in values:
                if op == "=":
                    ok = v == want
                elif op == "CONTAINS":
                    ok = want in v
                else:
                    try:
                        fv, fw = float(v), float(want)
                    except ValueError:
                        continue
                    ok = {
                        "<": fv < fw,
                        ">": fv > fw,
                        "<=": fv <= fw,
                        ">=": fv >= fw,
                    }[op]
                if ok:
                    break
            if not ok:
                return False
        return True

    def __eq__(self, other):
        return isinstance(other, Query) and self.expr == other.expr

    def __hash__(self):
        return hash(self.expr)

    def __repr__(self):
        return f"Query({self.expr!r})"


ALL = Query("")


@dataclass
class Subscription:
    subscriber: str
    query: Query
    out: queue.Queue = field(default_factory=lambda: queue.Queue(maxsize=1000))
    cancelled: threading.Event = field(default_factory=threading.Event)

    def get(self, timeout: float | None = None):
        return self.out.get(timeout=timeout)


class PubSub:
    """Thread-safe pubsub server (libs/pubsub/pubsub.go)."""

    def __init__(self):
        self._subs: dict[tuple[str, str], Subscription] = {}
        self._mtx = threading.RLock()

    def subscribe(
        self, subscriber: str, query: Query | str, unbuffered: bool = False
    ) -> Subscription:
        """unbuffered=True gives an unbounded queue for subscribers that
        must never shed (the indexer; pubsub.go SubscribeUnbuffered)."""
        if isinstance(query, str):
            query = Query(query)
        key = (subscriber, query.expr)
        with self._mtx:
            if key in self._subs:
                raise ValueError(f"already subscribed: {key}")
            sub = Subscription(subscriber, query)
            if unbuffered:
                sub.out = queue.Queue(maxsize=0)
            self._subs[key] = sub
            return sub

    def unsubscribe(self, subscriber: str, query: Query | str) -> None:
        if isinstance(query, str):
            query = Query(query)
        with self._mtx:
            sub = self._subs.pop((subscriber, query.expr), None)
            if sub is None:
                raise KeyError("subscription not found")
            sub.cancelled.set()

    def unsubscribe_all(self, subscriber: str) -> None:
        with self._mtx:
            for key in [k for k in self._subs if k[0] == subscriber]:
                self._subs.pop(key).cancelled.set()

    def publish(self, msg, events: dict[str, list[str]] | None = None) -> None:
        events = events or {}
        with self._mtx:
            subs = list(self._subs.values())
        for sub in subs:
            if sub.query.matches(events):
                try:
                    sub.out.put_nowait((msg, events))
                except queue.Full:
                    pass  # slow subscriber: drop (reference cancels; we shed)

    def num_clients(self) -> int:
        with self._mtx:
            return len({k[0] for k in self._subs})
