"""Node health sentinel: hang-proof accelerator probes, a heartbeat
registry, and automatic stall forensics.

BENCH r03-r05 lost three consecutive perf rounds because ``jax.devices()``
hung for minutes inside a wedged device tunnel — and the node had no way
to even *notice* that state: the wedge blocks backend init without ever
raising, so any in-process probe hangs with it.  This module is the
observability plane that makes device wedges, stalled scheduler loops,
and hung consensus routines first-class signals:

* **Hang-proof accelerator probe** (:func:`probe_devices`): runs
  ``jax.devices()`` in a throwaway subprocess (own session, killpg
  escalation, poll-don't-communicate) with a hard deadline — extracted
  from ``bench.py``, which now imports it, so the library and the
  benchmark share one implementation.  The sentinel additionally wraps
  whatever probe function it is given in a worker thread with its own
  deadline, so even a misbehaving probe (or a stubbed one in tests) can
  never hang the sentinel itself.

* **Tri-state health machine**: ``ok → degraded → wedged`` driven by
  consecutive probe failures (``COMETBFT_TPU_HEALTH_WEDGE_AFTER``) and
  by heartbeat staleness; a recovered probe snaps back to ``ok``.

* **Heartbeat registry**: long-lived loops call ``healthmon.beat(name)``
  each iteration; the sentinel audits beat ages against per-loop
  deadlines (:data:`DEFAULT_LOOPS`) and blames the exact loop that went
  quiet.  Loops that exit cleanly call :func:`retire` so a finished
  blocksync is never mistaken for a stalled one.  With monitoring off
  (the default) ``beat()`` is one module-bool check — zero overhead, the
  same contract as ``utils/tracing``.

* **Automatic stall forensics**: on a probe deadline breach or a stale
  heartbeat the sentinel captures ONE rate-limited diagnosis artifact
  per incident (``utils/debugdump.stall_report``: all-thread stacks,
  verify-service ``stats()`` snapshot with in-flight batch ages,
  flight-recorder dump, recent trace-ring events) to ``$TMPDIR``, plus a
  flight-recorder event and hub metrics (``health_state`` gauge, probe
  latency histogram, consecutive-failure gauge, per-loop beat-age
  gauges) on every transition.

Liveness vs readiness (load-balancer wiring): the wire-compatible
``/health`` RPC stays ``{}`` — it answers iff the RPC thread is alive
(**liveness**).  The new ``/tpu_health`` RPC serves this module's
snapshot; route traffic away when ``state`` is ``wedged``
(**readiness**) and restart the process when ``/health`` itself stops
answering.

The sentinel thread itself must never hang on a wedged tunnel: it only
ever *waits with timeouts* (probe results are read from a worker thread,
the verify-service snapshot uses a bounded lock acquire), and the
subprocess probe never touches this process's JAX state.
"""

from __future__ import annotations

import os
import subprocess
import sys
import threading
import time
from dataclasses import dataclass

from . import envknobs
from .log import get_logger

STATE_OK = "ok"
STATE_DEGRADED = "degraded"
STATE_WEDGED = "wedged"
_STATE_CODE = {STATE_OK: 0, STATE_DEGRADED: 1, STATE_WEDGED: 2}

# Per-loop heartbeat deadlines (seconds).  A loop is stale when its last
# beat is older than its deadline; None = informational only (the loop
# legitimately blocks indefinitely — socket accept, event-driven work —
# so age is reported in /tpu_health but never audited).  Deadlines leave
# generous headroom over each loop's worst legitimate iteration:
# cs-receive processes one input under the consensus lock (a commit
# verification), verifysvc-collect blocks on a device result, and
# verifysvc-host may run a cold-bucket XLA compile.
DEFAULT_LOOPS: dict[str, float | None] = {
    "cs-receive": 15.0,
    "cs-watchdog": 35.0,
    "verifysvc-sched": 10.0,
    "verifysvc-collect": 60.0,
    "verifysvc-host": 300.0,
    # informational: the failover watchdog legitimately blocks for a
    # whole probation probe (subprocess, its own hard deadline)
    "verifysvc-failover": None,
    "blocksync-events": 15.0,
    "blocksync-pool": 60.0,
    "blockpool": 15.0,
    "metrics-pump": 15.0,
    "metrics-sample": 30.0,
    "mempool-recheck": None,
    "switch-accept": None,
}


# ----------------------------------------------------------------- probe


@dataclass
class ProbeResult:
    """Outcome of one accelerator probe attempt."""

    ok: bool
    detail: str
    latency_s: float
    timed_out: bool = False

    def to_dict(self) -> dict:
        return {
            "ok": self.ok,
            "detail": self.detail,
            "latency_s": round(self.latency_s, 3),
            "timed_out": self.timed_out,
        }


def probe_devices(timeout_s: float) -> ProbeResult:
    """Probe the accelerator backend in a throwaway subprocess.

    THE single wedge-safe device probe (bench.py imports this).  Runs
    ``jax.devices()`` in a subprocess with a hard deadline: a wedged
    tunnel blocks forever in backend init (no exception), which is
    unkillable in-process.  The subprocess exits before this process
    attaches, so the device is never held by two processes at once.
    Popen + poll deadline rather than ``subprocess.run(timeout=...)``:
    run() reaps the killed child with an unbounded communicate(), and a
    child wedged in uninterruptible device I/O would hang the reap — the
    exact failure this probe exists to detect.  The child runs in its
    own session so the kill escalation (SIGKILL to the whole group) also
    takes out any plugin helper processes it spawned; nothing here ever
    blocks on the child's pipes after a kill.
    """
    import signal

    from . import fail

    if fail.armed("wedge_device") is not None:
        # injected wedge (utils/fail): report the hang the real tunnel
        # would produce, immediately and deterministically — the chaos
        # harness's in-process stand-in for a >timeout_s jax.devices()
        # block, honored here so the sentinel and the failover
        # probation loop both see the same wedged world
        return ProbeResult(
            False,
            "injected fault: wedge_device (probe reported as hung)",
            float(timeout_s),
            timed_out=True,
        )

    code = "import jax; print(jax.devices()[0].platform)"
    t0 = time.monotonic()
    with open(os.devnull, "wb") as devnull:
        proc = subprocess.Popen(
            [sys.executable, "-c", code],
            stdout=subprocess.PIPE,
            stderr=devnull,
            text=True,
            start_new_session=True,
        )
        deadline = t0 + timeout_s
        step = min(0.5, max(timeout_s / 10.0, 0.01))
        while proc.poll() is None and time.monotonic() < deadline:
            time.sleep(step)
        if proc.poll() is None:
            try:
                os.killpg(os.getpgid(proc.pid), signal.SIGKILL)
            except (OSError, ProcessLookupError):
                proc.kill()
            return ProbeResult(
                False,
                f"jax.devices() hung >{timeout_s:g}s (wedged device tunnel)",
                time.monotonic() - t0,
                timed_out=True,
            )
        out = proc.stdout.read() if proc.stdout else ""
        latency = time.monotonic() - t0
        if proc.returncode != 0:
            return ProbeResult(
                False, f"probe exited {proc.returncode}", latency
            )
    detail = out.strip().splitlines()[-1] if out.strip() else "?"
    return ProbeResult(True, detail, latency)


# -------------------------------------------------------------- monitor


class HealthMonitor:
    """The sentinel: periodic hang-proof probes + heartbeat audits.

    Construction reads the ``COMETBFT_TPU_HEALTH_*`` knobs once;
    explicit constructor arguments override them (tests).  ``probe_fn``
    takes a timeout in seconds and returns a :class:`ProbeResult`; the
    default is :func:`probe_devices`.  Whatever it is, it runs on a
    dedicated worker thread and the sentinel judges it from outside with
    ``deadline + grace`` — a probe that blocks forever is recorded as a
    hang (one failure per period) without the sentinel ever blocking.
    """

    def __init__(
        self,
        probe_fn=None,
        probe_period_s: float | None = None,
        probe_timeout_s: float | None = None,
        probe_grace_s: float = 2.0,
        wedge_after: int | None = None,
        artifact_min_interval_s: float | None = None,
        artifact_dir: str | None = None,
        loops: dict[str, float | None] | None = None,
    ):
        self._probe_fn = probe_fn if probe_fn is not None else probe_devices
        self.probe_period_s = (
            probe_period_s if probe_period_s is not None
            else max(1, envknobs.get_int(envknobs.HEALTH_PERIOD_MS)) / 1e3
        )
        self.probe_timeout_s = (
            probe_timeout_s if probe_timeout_s is not None
            else max(1, envknobs.get_int(envknobs.HEALTH_PROBE_TIMEOUT_MS)) / 1e3
        )
        self.probe_grace_s = max(0.0, probe_grace_s)
        self.wedge_after = max(
            1, wedge_after if wedge_after is not None
            else envknobs.get_int(envknobs.HEALTH_WEDGE_AFTER)
        )
        self.artifact_min_interval_s = (
            artifact_min_interval_s if artifact_min_interval_s is not None
            else max(
                0, envknobs.get_int(envknobs.HEALTH_ARTIFACT_MIN_INTERVAL_MS)
            ) / 1e3
        )
        self.artifact_dir = (
            artifact_dir if artifact_dir is not None
            else (envknobs.get_str(envknobs.HEALTH_DIR) or None)
        )
        self.logger = get_logger("healthmon")

        self._mtx = threading.Lock()
        # heartbeat registry: name -> last beat (monotonic); deadlines
        # separate so beat() stays a single dict store
        self._beats: dict[str, float] = {}
        self._deadlines: dict[str, float | None] = dict(
            DEFAULT_LOOPS if loops is None else loops
        )
        self._stale: set[str] = set()

        # probe bookkeeping (all guarded by _mtx)
        self._state = STATE_OK
        self._consec_failures = 0
        self._last_result: ProbeResult | None = None
        self._last_result_at: float | None = None
        self._probe_attempts = 0
        self._transitions = 0
        self._last_artifact: str | None = None
        self._last_artifact_at: float | None = None
        self._incident_active = False

        # in-flight probe attempt: (generation, started_at monotonic);
        # None when no attempt outstanding.  judged=True once the
        # sentinel counted it as a hang — a late completion of a judged
        # attempt is discarded.
        self._attempt: dict | None = None
        self._attempt_gen = 0

        self._stop_ev = threading.Event()
        self._thread: threading.Thread | None = None
        self._next_probe = 0.0  # fire immediately on start

    # ---------------------------------------------------------- lifecycle

    @property
    def state(self) -> str:
        """Current tri-state health (atomic str read, no lock: the
        verify service's failover watchdog polls this every tick)."""
        return self._state

    @property
    def last_probe_at(self) -> float | None:
        """Monotonic time of the last ingested probe result (atomic
        read).  The failover watchdog compares this against its own
        last restore so a sentinel verdict that predates the restore —
        the sentinel probes far less often than probation — can't
        immediately re-trip a just-restored service."""
        return self._last_result_at

    def start(self) -> None:
        if self._thread is not None and self._thread.is_alive():
            return
        self._stop_ev.clear()
        self._thread = threading.Thread(
            target=self._sentinel_loop, name="healthmon-sentinel", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop_ev.set()
        t = self._thread
        if t is not None:
            t.join(timeout=5.0)
        self._thread = None

    # --------------------------------------------------------- heartbeats

    def register_loop(self, name: str, deadline_s: float | None) -> None:
        with self._mtx:
            self._deadlines[name] = deadline_s

    def beat(self, name: str) -> None:
        # one dict store, no lock: under the GIL a float store is atomic
        # and the sentinel reading a torn-by-a-tick value is harmless —
        # this is the hot path every loop iteration pays
        self._beats[name] = time.monotonic()

    def retire(self, name: str) -> None:
        """A loop is exiting cleanly: stop auditing it.  A blocksync
        pool that handed off to consensus must not read as stalled.
        The whole removal holds _mtx so it serializes with the
        sentinel's audit — an unlocked remove could lose to a
        concurrent audit's set() and resurrect the gauge series,
        frozen forever."""
        from .metrics import hub as _mhub

        with self._mtx:
            self._beats.pop(name, None)
            self._stale.discard(name)
            # drop the exported series too: a frozen age for a dead loop
            # reads on a dashboard as a live loop that stopped aging
            _mhub().health_beat_age.remove(loop=name)

    # ------------------------------------------------------------- probing

    def _kick_probe_locked(self, now: float) -> None:
        """Start a probe attempt on a fresh worker thread — unless the
        previous worker is still stuck inside the probe, in which case
        the stuck attempt keeps being judged instead (at most ONE probe
        thread exists however wedged the tunnel is)."""
        if self._attempt is not None:
            return
        self._attempt_gen += 1
        gen = self._attempt_gen
        self._attempt = {"gen": gen, "started": now, "judged": False}

        def run():
            try:
                res = self._probe_fn(self.probe_timeout_s)
            except BaseException as e:  # noqa: BLE001 — a probe bug is a failed probe
                res = ProbeResult(
                    False, f"probe raised {type(e).__name__}: {e}", 0.0
                )
            with self._mtx:
                att = self._attempt
                if att is None or att["gen"] != gen:
                    return  # superseded
                if att["judged"]:
                    # already counted as a hang; a (late) answer just
                    # clears the slot so the next period can probe again
                    self._attempt = None
                    return
                self._attempt = None
                self._ingest_probe_locked(res)

        threading.Thread(target=run, name="healthmon-probe", daemon=True).start()

    def _ingest_probe_locked(self, res: ProbeResult) -> None:
        from .metrics import hub as _mhub

        self._probe_attempts += 1
        self._last_result = res
        self._last_result_at = time.monotonic()
        m = _mhub()
        # synthetic hang results carry the cumulative blocked duration in
        # latency_s (useful in /tpu_health); the histogram promises "a
        # hang is clamped at the probe deadline", so clamp here
        m.health_probe_seconds.observe(min(res.latency_s, self.probe_timeout_s))
        m.health_probe_total.inc(
            result="ok" if res.ok else ("hang" if res.timed_out else "fail")
        )
        if res.ok:
            self._consec_failures = 0
        else:
            self._consec_failures += 1
        m.health_probe_consec_failures.set(self._consec_failures)

    def _judge_attempt_locked(self, now: float) -> None:
        """A probe attempt past deadline+grace is a hang — count it
        without waiting for the worker (which may be stuck forever)."""
        att = self._attempt
        if att is None or att["judged"]:
            return
        if now - att["started"] > self.probe_timeout_s + self.probe_grace_s:
            att["judged"] = True
            self._ingest_probe_locked(
                ProbeResult(
                    False,
                    "probe thread still blocked past "
                    f"{self.probe_timeout_s:g}s deadline",
                    now - att["started"],
                    timed_out=True,
                )
            )

    # -------------------------------------------------------------- audit

    def _audit_beats_locked(self, now: float) -> None:
        """Recompute the stale set and export per-loop beat ages."""
        from .metrics import hub as _mhub

        m = _mhub()
        for name, last in list(self._beats.items()):
            age = now - last
            m.health_beat_age.set(age, loop=name)
            deadline = self._deadlines.get(name)
            if deadline is None:
                continue
            if age > deadline:
                self._stale.add(name)
            else:
                self._stale.discard(name)

    def _device_state_locked(self) -> str:
        if self._consec_failures >= self.wedge_after:
            return STATE_WEDGED
        if self._consec_failures > 0:
            return STATE_DEGRADED
        return STATE_OK

    def tick(self, now: float | None = None) -> None:
        """One sentinel cycle: kick/judge the probe, audit beats, run the
        state machine, capture forensics.  The sentinel thread calls this
        periodically; tests call it directly for determinism.  Never
        blocks: every interaction with possibly-wedged machinery is
        judged from outside with deadlines."""
        now = time.monotonic() if now is None else now
        capture_reason: str | None = None
        with self._mtx:
            if now >= self._next_probe:
                self._next_probe = now + self.probe_period_s
                att = self._attempt
                if att is not None and att["judged"]:
                    # the worker is STILL stuck inside an already-judged
                    # probe: no new probe can start (one worker max), but
                    # every elapsed period is another failure — a tunnel
                    # wedged hard enough to trap the thread forever must
                    # still walk degraded -> wedged
                    self._ingest_probe_locked(
                        ProbeResult(
                            False,
                            "probe thread still blocked "
                            f"({now - att['started']:.1f}s since attempt "
                            "start)",
                            now - att["started"],
                            timed_out=True,
                        )
                    )
                else:
                    self._kick_probe_locked(now)
            self._judge_attempt_locked(now)
            self._audit_beats_locked(now)
            new_state = self._device_state_locked()
            if new_state == STATE_OK and self._stale:
                new_state = STATE_DEGRADED
            transitioned = new_state != self._state
            prev = self._state
            if transitioned:
                self._state = new_state
                self._transitions += 1
            # one artifact per incident: the first transition out of ok
            # (or a stale loop appearing while otherwise ok) opens an
            # incident, recovery to ok closes it
            if new_state == STATE_OK:
                self._incident_active = False
            elif not self._incident_active:
                self._incident_active = True
                rate_limited = (
                    self._last_artifact_at is not None
                    and now - self._last_artifact_at
                    < self.artifact_min_interval_s
                )
                if not rate_limited:
                    self._last_artifact_at = now
                    capture_reason = self._incident_reason_locked()
            if transitioned:
                self._record_transition_locked(prev, new_state)
        if capture_reason is not None:
            path = self._capture_forensics(capture_reason)
            with self._mtx:
                self._last_artifact = path

    def _incident_reason_locked(self) -> str:
        parts = []
        if self._consec_failures:
            detail = self._last_result.detail if self._last_result else "?"
            parts.append(
                f"{self._consec_failures} consecutive probe failure(s): "
                f"{detail}"
            )
        if self._stale:  # audit ran under this same lock hold
            parts.append(
                f"stale heartbeat(s): {', '.join(sorted(self._stale))}"
            )
        return "; ".join(parts) or "unknown"

    def _record_transition_locked(self, prev: str, new: str) -> None:
        from .flightrec import recorder as _flightrec
        from .metrics import hub as _mhub

        m = _mhub()
        m.health_state.set(_STATE_CODE[new])
        m.health_transitions.inc(state=new)
        detail = self._last_result.detail if self._last_result else ""
        _flightrec().record(
            "health",
            state=new,
            prev=prev,
            consec_failures=self._consec_failures,
            stale_loops=sorted(self._stale),
            probe=detail,
        )
        log = self.logger.warning if new != STATE_OK else self.logger.info
        log(
            f"health state {prev} -> {new} "
            f"(probe failures={self._consec_failures}, "
            f"stale={sorted(self._stale) or '[]'} {detail})"
        )

    # ----------------------------------------------------------- forensics

    def _capture_forensics(self, reason: str) -> str | None:
        """One diagnosis artifact: snapshot + verifysvc stats (bounded
        lock wait) + flight recorder + trace ring + all-thread stacks.
        Runs OUTSIDE self._mtx (beat() never contends) and must never
        raise — it runs while the node is already in trouble."""
        import json as _json

        from . import debugdump, tracing
        from .metrics import hub as _mhub

        try:
            sections: list[tuple[str, str]] = [
                (
                    "health snapshot",
                    _json.dumps(self.snapshot(), indent=1, default=str),
                )
            ]
            try:
                # peek the module global, never global_service(): the
                # accessor CONSTRUCTS a service on demand, and a
                # diagnostic path must not install fresh global state
                # (nor report a fabricated empty scheduler as real)
                from ..verifysvc import service as _vsvc

                svc = _vsvc._GLOBAL
                stats = (
                    svc.stats(lock_timeout=0.5)
                    if svc is not None
                    else "not running (no verify service in this process)"
                )
                sections.append(
                    ("verify service", _json.dumps(stats, indent=1, default=str))
                )
            except Exception as e:  # noqa: BLE001 — partial forensics beat none
                sections.append(("verify service", f"unavailable: {e!r}"))
            if tracing.enabled():
                events = tracing.chrome_trace_events()[-256:]
                sections.append(
                    ("trace ring (newest 256)", _json.dumps(events, default=str))
                )
            path = debugdump.stall_report(
                reason, sections, directory=self.artifact_dir
            )
            _mhub().health_forensics.inc()
            self.logger.warning(f"stall forensics written to {path}")
            return path
        except Exception as e:  # noqa: BLE001 — forensics must never hurt the node
            self.logger.warning(f"stall forensics capture failed: {e!r}")
            return None

    # ------------------------------------------------------------ sentinel

    def _sentinel_loop(self) -> None:
        # tick fast enough to honor small test periods, slow enough to
        # be invisible in production (<=4 wakeups/s worst case)
        step = max(0.05, min(1.0, self.probe_period_s / 4.0))
        while not self._stop_ev.wait(step):
            try:
                self.tick()
            except Exception as e:  # noqa: BLE001 — the sentinel outlives one bad cycle
                self.logger.warning(f"sentinel tick failed: {e!r}")

    # ------------------------------------------------------------ snapshot

    def snapshot(self) -> dict:
        """The /tpu_health payload (JSON-serializable)."""
        now = time.monotonic()
        beats = dict(self._beats)  # racy-read safe: atomic dict copy
        with self._mtx:
            last = self._last_result
            attempt = self._attempt
            out = {
                "enabled": True,
                "state": self._state,
                "ready": self._state != STATE_WEDGED,
                "consecutive_probe_failures": self._consec_failures,
                "wedge_after": self.wedge_after,
                "probe_period_s": self.probe_period_s,
                "probe_timeout_s": self.probe_timeout_s,
                "probe_attempts": self._probe_attempts,
                "last_probe": (
                    {
                        **last.to_dict(),
                        "age_s": (
                            round(now - self._last_result_at, 3)
                            if self._last_result_at is not None
                            else None
                        ),
                    }
                    if last is not None
                    else None
                ),
                "probe_in_flight_s": (
                    round(now - attempt["started"], 3) if attempt else None
                ),
                "stale_loops": sorted(self._stale),
                "transitions": self._transitions,
                "last_artifact": self._last_artifact,
            }
            deadlines = dict(self._deadlines)
        out["loops"] = {
            name: {
                "age_s": round(now - t, 3),
                "deadline_s": deadlines.get(name),
                "stale": name in out["stale_loops"],
            }
            for name, t in sorted(beats.items())
        }
        return out

    def wedge_report(self) -> dict:
        """Compact structured view for embedding in artifacts/bench
        lines: state + last probe + stale loops."""
        with self._mtx:
            return {
                "state": self._state,
                "consecutive_probe_failures": self._consec_failures,
                "last_probe": (
                    self._last_result.to_dict() if self._last_result else None
                ),
                "stale_loops": sorted(self._stale),
                "last_artifact": self._last_artifact,
            }


# ------------------------------------------------------- module plumbing

_ENABLED = False
_MON: HealthMonitor | None = None
_MON_MTX = threading.Lock()


def beat(name: str) -> None:
    """Heartbeat from a long-lived loop.  Off by default: one module-bool
    check, no allocation, no lock — safe on every hot loop."""
    if not _ENABLED:
        return
    mon = _MON
    if mon is not None:
        mon.beat(name)


def retire(name: str) -> None:
    """A loop is exiting cleanly; stop auditing its heartbeat."""
    if not _ENABLED:
        return
    mon = _MON
    if mon is not None:
        mon.retire(name)


def monitor() -> HealthMonitor | None:
    return _MON


def install(mon: HealthMonitor) -> HealthMonitor:
    """Make ``mon`` the process monitor and enable beats (tests and
    :func:`maybe_start`).  Does not start the sentinel thread."""
    global _MON, _ENABLED
    with _MON_MTX:
        _MON = mon
        _ENABLED = True
    return mon


def uninstall() -> None:
    """Stop and drop the process monitor; beats go back to no-ops."""
    global _MON, _ENABLED
    with _MON_MTX:
        mon, _MON = _MON, None
        _ENABLED = False
    if mon is not None:
        mon.stop()


def maybe_start() -> HealthMonitor | None:
    """Knob-gated production entry (node.start): installs and starts the
    sentinel when ``COMETBFT_TPU_HEALTH=1``; returns None (and keeps the
    zero-overhead no-op path) otherwise."""
    if not envknobs.get_bool(envknobs.HEALTH):
        return None
    with _MON_MTX:
        if _MON is not None:
            return _MON
    mon = install(HealthMonitor())
    mon.start()
    return mon


def snapshot() -> dict:
    """The /tpu_health payload; a disabled monitor still answers (the
    RPC responding at all is the liveness half of the contract)."""
    mon = _MON
    if mon is None:
        return {
            "enabled": False,
            "state": "unknown",
            "ready": True,
            "loops": {},
            "stale_loops": [],
            "last_probe": None,
            "last_artifact": None,
        }
    return mon.snapshot()
