"""cometbft_tpu — a TPU-native BFT state-machine-replication framework.

Built from scratch with the capabilities of CometBFT (Tendermint consensus,
ABCI, gossip p2p, block/state sync, light clients, WAL crash recovery,
evidence, RPC).  The host-side control plane is ordinary Python/C++ systems
code; the verification data plane (Ed25519 batch signature verification,
SHA-256/SHA-512 and Merkle hashing) runs on TPU as vectorized JAX kernels
behind a pluggable BatchVerifier seam (reference: crypto/crypto.go:47-55,
crypto/batch/batch.go:10).

Layer map (mirrors SURVEY.md §1):
  utils/     L0 base utilities (service lifecycle, logging, pubsub, events)
  ops/       TPU kernels: GF(2^255-19) limbs, Edwards25519, SHA-2, Merkle
  parallel/  device-mesh sharding of verification batches (pjit/shard_map)
  crypto/    L1 host crypto API: keys, batch verifier seam, merkle, hashing
  wire/      L2 deterministic protobuf codec + canonical sign-bytes
  types/     L3 domain types: Block, Vote, ValidatorSet, VoteSet, params
  store/     L4 KV DB + block store
  state/     L4/L6 state store + block executor
  abci/      L5 application interface + clients/servers + kvstore example
  mempool/   L7 lane-aware mempool
  consensus/ L7 Tendermint state machine + WAL + replay
  privval/   L7 validator signing w/ double-sign protection
  evidence/  L7 evidence pool + verification
  blocksync/ L7 fast sync
  statesync/ L7 snapshot sync
  p2p/       L8 authenticated multiplexed gossip transport
  light/     L9 light client
  rpc/       L10 JSON-RPC surface
  node/      L11 node assembly
  config/    L12 config + CLI support
  models/    flagship verification-plane pipelines (graft/bench entry)
"""

__version__ = "0.1.0"
