"""Proxy: the engine's four named ABCI connections (reference: proxy/).

AppConns multiplexes one client-creator into consensus / mempool / query /
snapshot connections (multi_app_conn.go), so CheckTx traffic can run
concurrently with block execution — the reference's ABCI pipeline
parallelism.  Local apps share one mutex across all four (the reference's
NewLocalClientCreator connection-synchronized default); socket apps get
four independent pipelined connections.
"""

from __future__ import annotations

import threading
from typing import Callable

from .abci.client import Client, LocalClient, SocketClient
from .abci.types import Application
from .utils.service import Service

ClientCreator = Callable[[], Client]


def local_client_creator(app: Application) -> ClientCreator:
    """All four connections share one mutex (proxy/client.go
    NewLocalClientCreator)."""
    mtx = threading.RLock()
    return lambda: LocalClient(app, mtx)


def unsync_local_client_creator(app: Application) -> ClientCreator:
    from .abci.client import UnsyncLocalClient

    return lambda: UnsyncLocalClient(app)


def remote_client_creator(addr: str, must_connect: bool = True) -> ClientCreator:
    return lambda: SocketClient(addr, must_connect=must_connect)


class AppConns(Service):
    """Four connections, started/stopped as one service
    (proxy/multi_app_conn.go)."""

    def __init__(self, creator: ClientCreator):
        super().__init__("AppConns")
        self._creator = creator
        self.consensus: Client | None = None
        self.mempool: Client | None = None
        self.query: Client | None = None
        self.snapshot: Client | None = None

    def on_start(self) -> None:
        conns = []
        try:
            for name in ("query", "snapshot", "mempool", "consensus"):
                c = self._creator()
                c.start()
                conns.append(c)
                setattr(self, name, c)
        except Exception:
            for c in conns:
                c.stop()
            raise

    def on_stop(self) -> None:
        for c in (self.consensus, self.mempool, self.query, self.snapshot):
            if c and c.is_running():
                c.stop()


def new_app_conns(creator: ClientCreator) -> AppConns:
    return AppConns(creator)
