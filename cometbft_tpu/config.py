"""Node configuration (reference: config/config.go:93 + config/toml.go).

A TOML file under <home>/config/config.toml, decoded into nested
dataclasses.  Consensus-critical parameters are NOT here — they live
on-chain as ConsensusParams (types/params.py); this file holds only
operator-local knobs, exactly like the reference split.
"""

from __future__ import annotations

import os

try:
    import tomllib
except ImportError:  # Python < 3.11: the framework's minimal reader
    from .utils import minitoml as tomllib
from dataclasses import dataclass, field, fields

from .consensus.config import ConsensusConfig

DEFAULT_HOME = os.path.expanduser("~/.cometbft-tpu")


@dataclass
class BaseConfig:
    moniker: str = "node"
    proxy_app: str = "kvstore"  # "kvstore" | "noop" | tcp://addr (socket)
    # "native" = the C++ log-structured engine (native/kvstore.cc, the
    # analogue of the reference's pebble backend); "sqlite" | "memdb"
    db_backend: str = "native"
    block_sync: bool = True
    genesis_file: str = "config/genesis.json"
    priv_validator_key_file: str = "config/priv_validator_key.json"
    priv_validator_state_file: str = "data/priv_validator_state.json"
    # when set (host:port), the node listens here for a remote signer
    # instead of using the file privval (config.go PrivValidatorListenAddr)
    priv_validator_laddr: str = ""
    node_key_file: str = "config/node_key.json"
    log_level: str = "info"
    # snapshot cadence for the built-in kvstore apps (the reference e2e
    # app's snapshot_interval); statesync peers can only serve snapshots
    # taken at these heights
    app_snapshot_interval: int = 100
    tx_index: str = "kv"  # "kv" | "null" | "psql" (config.go TxIndexConfig)
    # for tx_index = "psql": a DB conn string — postgres when psycopg2 is
    # installed, or "sqlite:///path" (indexer/sink.py SQLEventSink)
    psql_conn: str = ""


@dataclass
class P2PConfig:
    laddr: str = "tcp://0.0.0.0:26656"
    external_address: str = ""
    persistent_peers: str = ""  # comma-separated id@host:port
    seeds: str = ""  # comma-separated id@host:port
    pex: bool = True
    seed_mode: bool = False
    addr_book_file: str = "config/addrbook.json"
    max_num_inbound_peers: int = 40
    max_num_outbound_peers: int = 10
    send_rate: int = 5_120_000  # bytes/sec (connection.go:40)
    recv_rate: int = 5_120_000
    handshake_timeout: float = 20.0
    dial_timeout: float = 3.0


@dataclass
class MempoolConfig:
    size: int = 5000
    max_tx_bytes: int = 1024 * 1024
    max_txs_bytes: int = 64 * 1024 * 1024
    cache_size: int = 10000
    keep_invalid_txs_in_cache: bool = False
    recheck: bool = True
    broadcast: bool = True


@dataclass
class StatesyncConfig:
    enable: bool = False
    # comma-separated full-node RPC endpoints the light-client state
    # provider verifies against (config.go StateSyncConfig.RPCServers;
    # first = primary, rest = witnesses)
    rpc_servers: str = ""
    trust_height: int = 0
    trust_hash: str = ""
    trust_period: float = 168 * 3600.0  # seconds
    discovery_time: float = 15.0
    chunk_request_timeout: float = 10.0


@dataclass
class RPCConfig:
    laddr: str = "tcp://127.0.0.1:26657"
    max_open_connections: int = 900
    # enables dial_seeds/dial_peers (reference config.go RPCConfig.Unsafe)
    unsafe: bool = False
    # data-companion services — the reference's grpc_laddr (public
    # block/block-results/version) and grpc_privileged_laddr (pruning
    # retain-height API), served over the varint-proto socket transport
    # (rpc/services.py).  Separate listeners so the pruning API can be
    # firewalled independently of the read-only services.
    companion_laddr: str = ""
    companion_privileged_laddr: str = ""


@dataclass
class InstrumentationConfig:
    prometheus: bool = False
    prometheus_listen_addr: str = ":26660"
    # separate opt-in listener for /debug/threads + /debug/heap — kept
    # off the metrics port so scraping never exposes stack/heap contents
    # (the reference likewise gates pprof behind its own pprof_laddr,
    # config.go pprof_laddr)
    pprof_laddr: str = ""


@dataclass
class Config:
    home: str = DEFAULT_HOME
    base: BaseConfig = field(default_factory=BaseConfig)
    p2p: P2PConfig = field(default_factory=P2PConfig)
    mempool: MempoolConfig = field(default_factory=MempoolConfig)
    consensus: ConsensusConfig = field(default_factory=ConsensusConfig)
    statesync: StatesyncConfig = field(default_factory=StatesyncConfig)
    rpc: RPCConfig = field(default_factory=RPCConfig)
    instrumentation: InstrumentationConfig = field(
        default_factory=InstrumentationConfig
    )

    # ------------------------------------------------------------- paths

    def _abs(self, rel: str) -> str:
        return rel if os.path.isabs(rel) else os.path.join(self.home, rel)

    def genesis_file(self) -> str:
        return self._abs(self.base.genesis_file)

    def node_key_file(self) -> str:
        return self._abs(self.base.node_key_file)

    def priv_validator_key_file(self) -> str:
        return self._abs(self.base.priv_validator_key_file)

    def priv_validator_state_file(self) -> str:
        return self._abs(self.base.priv_validator_state_file)

    def db_dir(self) -> str:
        return self._abs("data")

    def wal_file(self) -> str:
        return self._abs(self.consensus.wal_path)

    def config_file(self) -> str:
        return self._abs("config/config.toml")

    def validate_basic(self) -> None:
        if self.base.db_backend not in ("native", "sqlite", "memdb"):
            raise ValueError(f"unknown db_backend {self.base.db_backend!r}")
        if self.base.tx_index not in ("kv", "null", "psql"):
            raise ValueError(f"unknown tx_index {self.base.tx_index!r}")
        if self.base.tx_index == "psql" and not self.base.psql_conn:
            raise ValueError("tx_index = \"psql\" requires psql_conn")
        if self.statesync.enable and not (
            self.statesync.trust_height > 0 and self.statesync.trust_hash
        ):
            raise ValueError(
                "statesync.enable requires trust_height and trust_hash"
            )


# --------------------------------------------------------------- loading

_SECTIONS = {
    "p2p": P2PConfig,
    "mempool": MempoolConfig,
    "consensus": ConsensusConfig,
    "statesync": StatesyncConfig,
    "rpc": RPCConfig,
    "instrumentation": InstrumentationConfig,
}


def load_config(home: str) -> Config:
    """Read <home>/config/config.toml over the defaults."""
    cfg = Config(home=home)
    path = cfg.config_file()
    if not os.path.exists(path):
        return cfg
    with open(path, "rb") as f:
        data = tomllib.load(f)
    data, _ = _apply_renames(data)  # old configs load with values intact
    _apply(cfg.base, data)  # top-level keys are the base section
    for name, cls in _SECTIONS.items():
        if name in data:
            _apply(getattr(cfg, name), data[name])
    cfg.validate_basic()
    return cfg


def _apply(obj, data: dict) -> None:
    for f in fields(obj):
        if f.name in data:
            setattr(obj, f.name, data[f.name])


# Cross-version key renames (internal/confix/migrations.go's per-version
# plans): "old key" -> "new key", applied before the known/obsolete split
# so an old config carries its VALUES across a rename instead of dropping
# them.  Keys are dotted ("" section = top level); a None target deletes.
# The entries mirror the reference's own history (fast_sync -> block_sync
# and the [fastsync] section in v0.37, config.go).
_RENAMES: dict[str, str | None] = {
    "fast_sync": "block_sync",
    "fastsync.version": None,  # folded into the engine; no knob survives
    "blocksync.version": None,
    # order matters: psql-conn must leave the [tx_index] section BEFORE
    # the indexer key collapses the section into a top-level scalar
    "tx_index.psql-conn": "psql_conn",
    "tx_index.indexer": "tx_index",
}


def _apply_renames(raw: dict) -> tuple[dict, list[str]]:
    """Flatten-rename pass: returns (rewritten raw, renamed-key report)."""
    renamed: list[str] = []
    out: dict = {k: (dict(v) if isinstance(v, dict) else v) for k, v in raw.items()}

    def pop_dotted(key: str):
        if "." in key:
            sec, k = key.split(".", 1)
            if isinstance(out.get(sec), dict) and k in out[sec]:
                v = out[sec].pop(k)
                if not out[sec]:
                    del out[sec]
                return True, v
            return False, None
        if key in out and not isinstance(out[key], dict):
            return True, out.pop(key)
        return False, None

    def set_dotted(key: str, v) -> None:
        if "." in key:
            sec, k = key.split(".", 1)
            out.setdefault(sec, {})[k] = v
            return
        prev = out.get(key)
        if isinstance(prev, dict):
            # a section collapsing into a scalar (old [tx_index] table ->
            # top-level key): surface any leftover keys rather than
            # silently burying them under the new scalar
            renamed.extend(f"{key}.{k} (retired)" for k in prev)
        out[key] = v

    for old, new in _RENAMES.items():
        if old == new:
            continue
        found, v = pop_dotted(old)
        if not found:
            continue
        if new is None:
            renamed.append(f"{old} (retired)")
        else:
            set_dotted(new, v)
            renamed.append(f"{old} -> {new}")
    return out, renamed


def migrate_report(home: str) -> dict:
    """confix-style migration summary (internal/confix): compare the
    on-disk TOML against the current schema and report what a rewrite
    would rename (old keys whose values carry over), add (new keys at
    defaults), drop (obsolete keys), and keep.  Pure analysis — the
    caller decides whether to rewrite."""
    cfg = Config(home=home)
    path = cfg.config_file()
    raw: dict = {}
    if os.path.exists(path):
        with open(path, "rb") as f:
            raw = tomllib.load(f)
    raw, renamed = _apply_renames(raw)

    known: dict[str, set[str]] = {
        "": {f.name for f in fields(cfg.base)},
    }
    for name, _cls in _SECTIONS.items():
        known[name] = {f.name for f in fields(getattr(cfg, name))}

    kept: list[str] = []
    dropped: list[str] = []
    present: dict[str, set[str]] = {"": set()}
    for key, val in raw.items():
        if isinstance(val, dict):
            present[key] = set(val)
            if key not in known:
                dropped.extend(f"{key}.{k}" for k in val)
                continue
            for k in val:
                (kept if k in known[key] else dropped).append(f"{key}.{k}")
        else:
            present[""].add(key)
            (kept if key in known[""] else dropped).append(key)

    added = []
    for section, names in known.items():
        have = present.get(section, set())
        for k in sorted(names - have):
            added.append(f"{section}.{k}" if section else k)
    return {
        "added": added,
        "dropped": sorted(dropped),
        "kept": sorted(kept),
        "renamed": renamed,
    }


def save_config(cfg: Config) -> None:
    """Write the TOML template with current values (config/toml.go)."""
    path = cfg.config_file()
    os.makedirs(os.path.dirname(path), exist_ok=True)
    out = ["# CometBFT-TPU node configuration", ""]
    out.extend(_emit(cfg.base))
    for name in _SECTIONS:
        out.append("")
        out.append(f"[{name}]")
        out.extend(_emit(getattr(cfg, name)))
    with open(path, "w") as f:
        f.write("\n".join(out) + "\n")


def _emit(obj) -> list[str]:
    lines = []
    for f in fields(obj):
        v = getattr(obj, f.name)
        if isinstance(v, bool):
            tv = "true" if v else "false"
        elif isinstance(v, (int, float)):
            tv = repr(v)
        else:
            tv = '"' + str(v).replace("\\", "\\\\").replace('"', '\\"') + '"'
        lines.append(f"{f.name} = {tv}")
    return lines
