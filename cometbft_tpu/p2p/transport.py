"""TCP transport: listen/dial producing authenticated, version-checked
connections (reference: p2p/transport/tcp/tcp.go + p2p/handshake.go).

dial/accept: TCP connect → SecretConnection STS handshake (identity) →
NodeInfo exchange (varint-delimited proto over the encrypted link) →
compatibility check → verified (conn, NodeInfo) pair for the Switch.
"""

from __future__ import annotations

import socket
import threading

from ..utils.log import get_logger
from ..wire import p2p_pb
from ..wire.proto import decode_varint, encode_varint
from .conn.secret_connection import SecretConnection, make_secret_connection
from .key import NodeKey
from .node_info import NodeInfo, NodeInfoError

HANDSHAKE_TIMEOUT = 20.0

#: Cap on the peer-supplied NodeInfo length prefix: it sizes the
#: read_exact() below, so an unbounded value is an attacker-driven
#: allocation (reference p2p/handshake.go reads via a bounded protoio
#: reader).
MAX_NODE_INFO_SIZE = 10240


class TransportError(Exception):
    pass


def _exchange_node_info(conn: SecretConnection, our: NodeInfo) -> NodeInfo:
    """(p2p/handshake.go:162): both sides send, then read."""
    payload = our.to_proto().encode()
    conn.write(encode_varint(len(payload)) + payload)
    # read varint prefix byte-by-byte off the decrypted stream
    prefix = b""
    while True:
        prefix += conn.read_exact(1)
        try:
            length, _ = decode_varint(prefix)
            break
        except ValueError as e:
            if "truncated" not in str(e) or len(prefix) > 10:
                raise TransportError("bad nodeinfo length prefix")
    if length > MAX_NODE_INFO_SIZE:
        raise TransportError("oversized nodeinfo")
    theirs = NodeInfo.from_proto(p2p_pb.NodeInfoProto.decode(conn.read_exact(length)))
    theirs.validate_basic()
    return theirs


class TCPTransport:
    def __init__(self, node_key: NodeKey, node_info: NodeInfo):
        self.node_key = node_key
        self.node_info = node_info
        self.logger = get_logger("transport")
        self._listener: socket.socket | None = None

    # --------------------------------------------------------- listening

    def listen(self, addr: str) -> str:
        host, port = addr.rsplit(":", 1)
        self._listener = socket.create_server((host, int(port)))
        host, port = self._listener.getsockname()[:2]
        self.node_info.listen_addr = f"{host}:{port}"
        return self.node_info.listen_addr

    def accept(self) -> tuple[SecretConnection, NodeInfo]:
        """Blocks for one inbound connection; raises on listener close."""
        if self._listener is None:
            raise TransportError("transport is not listening")
        sock, _ = self._listener.accept()
        return self._upgrade(sock)

    def dial(self, addr: str, timeout: float = 10.0) -> tuple[SecretConnection, NodeInfo]:
        """Dial `host:port` or `id@host:port`.

        With the id form the secret-connection-authenticated key must hash
        to the expected node ID, or the connection is dropped — without the
        pin an on-path attacker (or hijacked DNS/IP) could impersonate a
        configured persistent peer (reference: p2p/transport/tcp/tcp.go Dial
        + netaddr.NetAddr ID checks).
        """
        expected_id = ""
        if "@" in addr:
            expected_id, addr = addr.split("@", 1)
            expected_id = expected_id.lower()
        host, port = addr.rsplit(":", 1)
        sock = socket.create_connection((host, int(port)), timeout=timeout)
        sock.settimeout(HANDSHAKE_TIMEOUT)
        conn, info = self._upgrade(sock)
        if expected_id and conn.remote_pub.address().hex() != expected_id:
            conn.close()
            raise TransportError(
                f"dialed {expected_id} but remote authenticated as "
                f"{conn.remote_pub.address().hex()}"
            )
        return conn, info

    def _upgrade(self, sock: socket.socket) -> tuple[SecretConnection, NodeInfo]:
        sock.settimeout(HANDSHAKE_TIMEOUT)
        try:
            conn = make_secret_connection(sock, self.node_key.priv_key)
            theirs = _exchange_node_info(conn, self.node_info)
            # the authenticated identity must match the claimed node id
            if conn.remote_pub.address().hex() != theirs.node_id:
                raise TransportError(
                    f"node id {theirs.node_id} doesn't match authenticated key"
                )
            self.node_info.compatible_with(theirs)
        except (NodeInfoError, TransportError):
            sock.close()
            raise
        except Exception as e:  # noqa: BLE001
            sock.close()
            raise TransportError(f"handshake failed: {e}")
        sock.settimeout(None)
        return conn, theirs

    def close(self) -> None:
        if self._listener is not None:
            self._listener.close()
            self._listener = None
