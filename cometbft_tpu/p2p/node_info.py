"""Node identity/version info + compatibility check
(reference: p2p/internal/nodeinfo/nodeinfo.go).
"""

from __future__ import annotations

from dataclasses import dataclass, field

from ..wire import p2p_pb

MAX_NUM_CHANNELS = 16


class NodeInfoError(Exception):
    pass


@dataclass
class NodeInfo:
    node_id: str = ""
    listen_addr: str = ""
    network: str = ""  # chain id
    version: str = "cometbft-tpu/0.1.0"
    channels: bytes = b""
    moniker: str = "node"
    p2p_version: int = 9
    block_version: int = 11
    app_version: int = 0
    tx_index: str = "on"
    rpc_address: str = ""

    def validate_basic(self) -> None:
        if not self.node_id:
            raise NodeInfoError("no node ID")
        if len(self.channels) > MAX_NUM_CHANNELS:
            raise NodeInfoError("too many channels")
        if len(set(self.channels)) != len(self.channels):
            raise NodeInfoError("duplicate channel id")

    def compatible_with(self, other: "NodeInfo") -> None:
        """(nodeinfo.go CompatibleWith): same block version, same network,
        at least one common channel."""
        if self.block_version != other.block_version:
            raise NodeInfoError(
                f"peer block version {other.block_version} != {self.block_version}"
            )
        if self.network != other.network:
            raise NodeInfoError(f"peer network {other.network!r} != {self.network!r}")
        if not set(self.channels) & set(other.channels):
            raise NodeInfoError("no common channels")

    def to_proto(self) -> p2p_pb.NodeInfoProto:
        return p2p_pb.NodeInfoProto(
            protocol_version=p2p_pb.ProtocolVersion(
                p2p=self.p2p_version, block=self.block_version, app=self.app_version
            ),
            node_id=self.node_id,
            listen_addr=self.listen_addr,
            network=self.network,
            version=self.version,
            channels=self.channels,
            moniker=self.moniker,
            other=p2p_pb.NodeInfoOther(
                tx_index=self.tx_index, rpc_address=self.rpc_address
            ),
        )

    @classmethod
    def from_proto(cls, m: p2p_pb.NodeInfoProto) -> "NodeInfo":
        pv = m.protocol_version or p2p_pb.ProtocolVersion()
        other = m.other or p2p_pb.NodeInfoOther()
        return cls(
            node_id=m.node_id,
            listen_addr=m.listen_addr,
            network=m.network,
            version=m.version,
            channels=m.channels,
            moniker=m.moniker,
            p2p_version=pv.p2p,
            block_version=pv.block,
            app_version=pv.app,
            tx_index=other.tx_index,
            rpc_address=other.rpc_address,
        )
