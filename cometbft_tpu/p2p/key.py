"""Persistent node identity key (reference: p2p/internal/nodekey/nodekey.go).

The node ID is the 20-byte address of the Ed25519 identity key, hex
encoded — the same derivation as validator addresses.
"""

from __future__ import annotations

import base64
import json
import os

from ..crypto import ed25519


class NodeKey:
    def __init__(self, priv_key: ed25519.PrivKey):
        self.priv_key = priv_key

    @property
    def pub_key(self) -> ed25519.PubKey:
        return self.priv_key.pub_key()

    def id(self) -> str:
        return self.pub_key.address().hex()

    @classmethod
    def generate(cls, seed: bytes | None = None) -> "NodeKey":
        priv = ed25519.PrivKey.from_seed(seed) if seed else ed25519.PrivKey.generate()
        return cls(priv)

    def save_as(self, path: str) -> None:
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        fd = os.open(path, os.O_WRONLY | os.O_CREAT | os.O_TRUNC, 0o600)
        with os.fdopen(fd, "w") as f:
            json.dump(
                {
                    "priv_key": {
                        "type": "tendermint/PrivKeyEd25519",
                        "value": base64.b64encode(self.priv_key.data).decode(),
                    }
                },
                f,
                indent=2,
            )

    @classmethod
    def load(cls, path: str) -> "NodeKey":
        with open(path) as f:
            d = json.load(f)
        return cls(ed25519.PrivKey(base64.b64decode(d["priv_key"]["value"])))

    @classmethod
    def load_or_gen(cls, path: str) -> "NodeKey":
        if os.path.exists(path):
            return cls.load(path)
        nk = cls.generate()
        nk.save_as(path)
        return nk
