"""Peer: a connected, authenticated remote node
(reference: p2p/peer.go:533).

Wraps the MConnection, routes inbound messages to the reactor that owns
each stream, and carries per-peer key/value state for the reactors
(consensus PeerState, mempool seen-set live under .data).
"""

from __future__ import annotations

import threading
from typing import Callable

from ..utils.log import get_logger
from ..utils.service import Service
from .conn.connection import MConnection, StreamDescriptor
from .node_info import NodeInfo


class Peer(Service):
    def __init__(
        self,
        conn,  # SecretConnection
        node_info: NodeInfo,
        stream_descs: list[StreamDescriptor],
        on_receive: Callable[[int, "Peer", bytes], None],
        on_error: Callable[["Peer", Exception], None],
        outbound: bool = False,
        persistent: bool = False,
        send_rate: int | None = None,
        recv_rate: int | None = None,
    ):
        super().__init__(f"peer-{node_info.node_id[:8]}")
        self.node_info = node_info
        # streams the REMOTE declared: sends to anything else are dropped
        # (peer.go hasChannel — a node without, say, the consensus reactor
        # must not receive consensus gossip, or it kills the connection)
        self._remote_channels = set(node_info.channels)
        self.outbound = outbound
        self.persistent = persistent
        self.data: dict = {}  # reactor-attached per-peer state
        self._data_mtx = threading.Lock()
        self.logger = get_logger(f"peer.{node_info.node_id[:8]}")
        extra = {}
        if send_rate is not None:
            extra["send_rate"] = send_rate
        if recv_rate is not None:
            extra["recv_rate"] = recv_rate
        from ..utils.netutil import maybe_shape_latency

        self.mconn = MConnection(
            maybe_shape_latency(conn),
            stream_descs,
            on_receive=lambda sid, msg: on_receive(sid, self, msg),
            on_error=lambda e: on_error(self, e),
            **extra,
        )

    @property
    def id(self) -> str:
        return self.node_info.node_id

    def on_start(self) -> None:
        self.mconn.start()

    def on_stop(self) -> None:
        if self.mconn.is_running():
            self.mconn.stop()

    def has_channel(self, stream_id: int) -> bool:
        # an empty declaration means a pre-channels peer: stay permissive
        return not self._remote_channels or stream_id in self._remote_channels

    def send(self, stream_id: int, msg: bytes) -> bool:
        if not self.has_channel(stream_id):
            return False
        return self.mconn.send(stream_id, msg)

    def try_send(self, stream_id: int, msg: bytes) -> bool:
        if not self.has_channel(stream_id):
            return False
        return self.mconn.try_send(stream_id, msg)

    def get(self, key: str):
        with self._data_mtx:
            return self.data.get(key)

    def set(self, key: str, value) -> None:
        with self._data_mtx:
            self.data[key] = value


class PeerSet:
    """(p2p/peer_set.go)."""

    def __init__(self):
        self._by_id: dict[str, Peer] = {}
        self._mtx = threading.RLock()

    def add(self, peer: Peer) -> None:
        with self._mtx:
            if peer.id in self._by_id:
                raise ValueError(f"duplicate peer {peer.id}")
            self._by_id[peer.id] = peer

    def remove(self, peer: Peer) -> bool:
        with self._mtx:
            return self._by_id.pop(peer.id, None) is not None

    def has(self, peer_id: str) -> bool:
        with self._mtx:
            return peer_id in self._by_id

    def get(self, peer_id: str) -> Peer | None:
        with self._mtx:
            return self._by_id.get(peer_id)

    def list(self) -> list[Peer]:
        with self._mtx:
            return list(self._by_id.values())

    def size(self) -> int:
        with self._mtx:
            return len(self._by_id)
