"""Switch: the peer lifecycle hub (reference: p2p/switch.go:64).

Owns the transport, accepts inbound and dials outbound peers, registers
reactors and their streams, routes received messages to the owning
reactor, reconnects persistent peers with exponential backoff, and
broadcasts to all peers.
"""

from __future__ import annotations

import random
import threading
import time

from ..utils import healthmon
from ..utils.log import get_logger
from ..utils.metrics import hub as _metrics_hub
from ..utils.service import Service
from .conn.connection import StreamDescriptor
from .peer import Peer, PeerSet
from .reactor import Reactor
from .transport import TCPTransport, TransportError

RECONNECT_ATTEMPTS = 20
RECONNECT_BASE_DELAY = 1.0
MAX_PEERS = 50


class SwitchError(Exception):
    pass


class Switch(Service):
    def __init__(
        self,
        transport: TCPTransport,
        max_peers: int = MAX_PEERS,
        send_rate: int | None = None,
        recv_rate: int | None = None,
    ):
        self.send_rate = send_rate
        self.recv_rate = recv_rate
        super().__init__("Switch")
        self.transport = transport
        self.reactors: dict[str, Reactor] = {}
        self.stream_descs: list[StreamDescriptor] = []
        self._reactor_by_stream: dict[int, Reactor] = {}
        self.peers = PeerSet()
        self.max_peers = max_peers
        self.persistent_addrs: set[str] = set()
        self._dialing: set[str] = set()
        self._partitioned = False
        self._mtx = threading.Lock()
        self.logger = get_logger("switch")
        self._accept_thread: threading.Thread | None = None
        # node_info.channels must list every registered stream
        self._sync_channels()

    # ----------------------------------------------------------- reactors

    def add_reactor(self, name: str, reactor: Reactor) -> None:
        for desc in reactor.stream_descriptors():
            if desc.id in self._reactor_by_stream:
                raise SwitchError(f"stream id {desc.id} already claimed")
            self._reactor_by_stream[desc.id] = reactor
            self.stream_descs.append(desc)
        self.reactors[name] = reactor
        reactor.set_switch(self)
        self._sync_channels()

    def _sync_channels(self) -> None:
        self.transport.node_info.channels = bytes(
            d.id for d in self.stream_descs
        )

    # ---------------------------------------------------------- lifecycle

    def on_start(self) -> None:
        for reactor in self.reactors.values():
            reactor.start()
        self._accept_thread = threading.Thread(
            target=self._accept_routine, name="switch-accept", daemon=True
        )
        self._accept_thread.start()

    def on_stop(self) -> None:
        self.transport.close()
        for peer in self.peers.list():
            self.stop_peer(peer, "switch stopping")
        for reactor in self.reactors.values():
            if reactor.is_running():
                reactor.stop()

    # ------------------------------------------------------------ accept

    def _accept_routine(self) -> None:
        try:
            self._accept_loop()
        finally:
            healthmon.retire("switch-accept")

    def _accept_loop(self) -> None:
        while self.is_running():
            # accept() legitimately blocks until a peer dials, so this
            # loop is registered informational (no staleness deadline):
            # /tpu_health reports the age, the sentinel never audits it
            healthmon.beat("switch-accept")
            if self.transport._listener is None:
                return  # dial-only node (or listener closed)
            try:
                conn, info = self.transport.accept()
            except OSError as e:
                if self.transport._listener is None or not self.is_running():
                    return  # listener closed
                # transient (EMFILE, ECONNABORTED, ...): keep accepting
                self.logger.error(f"accept error (retrying): {e}")
                time.sleep(0.1)
                continue
            except TransportError as e:
                self.logger.info(f"inbound handshake rejected: {e}")
                continue
            except Exception as e:  # noqa: BLE001
                if self.is_running():
                    self.logger.error(f"accept error: {e}")
                    continue
                return
            if self._partitioned:
                conn.close()  # network-partition perturbation active
                continue
            if info.node_id == self.transport.node_info.node_id:
                self.logger.info("rejecting inbound connection claiming our id")
                conn.close()
                continue
            if self.peers.size() >= self.max_peers:
                self.logger.info("rejecting inbound peer: full")
                conn.close()
                continue
            self._add_peer_conn(conn, info, outbound=False)

    # ------------------------------------------------------------ dialing

    def dial_peer_async(self, addr: str, persistent: bool = False) -> None:
        with self._mtx:
            if addr in self._dialing:
                return
            self._dialing.add(addr)
        if persistent:
            self.persistent_addrs.add(addr)
        threading.Thread(
            target=self._dial_routine, args=(addr, persistent), daemon=True,
            name=f"switch-dial-{addr}",
        ).start()

    def dial_peers_async(self, addrs: list[str], persistent: bool = False) -> None:
        for addr in addrs:
            self.dial_peer_async(addr, persistent)

    def _dial_routine(self, addr: str, persistent: bool) -> None:
        attempts = 0
        try:
            while self.is_running():
                if self._partitioned:
                    return  # healing redials persistent addrs
                try:
                    conn, info = self.transport.dial(addr)
                except Exception as e:  # noqa: BLE001
                    attempts += 1
                    if not persistent or attempts > RECONNECT_ATTEMPTS:
                        self.logger.info(f"dial {addr} failed: {e}")
                        return
                    delay = min(
                        RECONNECT_BASE_DELAY * (2 ** min(attempts, 6)), 60.0
                    ) * (0.75 + random.random() / 2)
                    time.sleep(delay)
                    continue
                if info.node_id == self.transport.node_info.node_id:
                    self.logger.info("dialed self; dropping")
                    conn.close()
                    return
                existing = self.peers.get(info.node_id)
                if existing is not None:
                    # already connected (e.g. they dialed us first): keep the
                    # persistence intent on the surviving peer so a later
                    # disconnect still redials
                    if persistent:
                        existing.persistent = True
                        existing.set("dial_addr", addr)
                    conn.close()
                    return
                self._add_peer_conn(
                    conn, info, outbound=True, persistent=persistent, addr=addr
                )
                return
        finally:
            with self._mtx:
                self._dialing.discard(addr)

    # ------------------------------------------------------- peer plumbing

    def _add_peer_conn(
        self, conn, info, outbound: bool, persistent: bool = False, addr: str = ""
    ) -> None:
        if self._partitioned:
            # a dial/accept already past the earlier checks can land here
            # after set_partitioned(True) severed everything — the
            # partition must hold until healed
            conn.close()
            return
        peer = Peer(
            conn,
            info,
            self.stream_descs,
            on_receive=self._on_peer_receive,
            on_error=self._on_peer_error,
            outbound=outbound,
            persistent=persistent,
            send_rate=self.send_rate,
            recv_rate=self.recv_rate,
        )
        if addr:
            peer.set("dial_addr", addr)
        if self.peers.has(peer.id):
            # duplicate (e.g. simultaneous dial+accept): cheap pre-check
            # before spending a peer.start(); the authoritative dedup is
            # the add() below
            conn.close()
            return
        for reactor in self.reactors.values():
            reactor.init_peer(peer)
        # start BEFORE registering in the PeerSet: a peer must never be
        # visible to broadcast() until its mconn is running, or an
        # immediate best-effort broadcast try_sends into a stopped mconn
        # and is silently dropped (the add-before-start race PR 3 could
        # only harden a test against)
        peer.start()
        try:
            self.peers.add(peer)
        except ValueError:
            # lost a simultaneous-connect race after start: tear down
            # ours, the registered winner carries the traffic
            try:
                peer.stop()
            except Exception as e:  # noqa: BLE001 — same contract as stop_peer
                self.logger.warning(
                    f"duplicate peer {peer.id[:8]} stop failed: {e!r}"
                )
                _metrics_hub().p2p_errors.inc(site="peer_stop")
            return
        if not peer.is_running() or not peer.mconn.is_running():
            # died between start() and add() (remote hung up instantly):
            # its on_error fired while the peer was unregistered, so
            # stop_peer() no-opped — finish the teardown now that it IS
            # registered, reaching every reactor's remove_peer.  The
            # mconn check matters on its own: an mconn error stops only
            # the mconn (suppressing further callbacks), leaving the
            # Peer service "running" but permanently undeliverable
            self.stop_peer(peer, "peer died during handshake")
            return
        for reactor in self.reactors.values():
            reactor.add_peer(peer)
        self.logger.info(
            f"added peer {info.node_id[:8]} ({'out' if outbound else 'in'}bound), "
            f"total {self.peers.size()}"
        )

    def _on_peer_receive(self, stream_id: int, peer: Peer, msg: bytes) -> None:
        reactor = self._reactor_by_stream.get(stream_id)
        if reactor is None:
            self.logger.error(f"message on unclaimed stream {stream_id}")
            return
        try:
            reactor.receive(stream_id, peer, msg)
        except Exception as e:  # noqa: BLE001 - a bad message never kills the switch
            self.logger.error(f"reactor {reactor.name} receive error: {e}")
            self.stop_peer(peer, f"reactor error: {e}")

    def _on_peer_error(self, peer: Peer, err: Exception) -> None:
        self.logger.info(f"peer {peer.id[:8]} error: {err}")
        self.stop_peer(peer, str(err))
        # reconnect persistent outbound peers
        addr = peer.get("dial_addr")
        if peer.persistent and addr and self.is_running():
            self.dial_peer_async(addr, persistent=True)

    def set_partitioned(self, on: bool) -> None:
        """Network-partition perturbation (reference: e2e runner
        `disconnect`, test/e2e/runner/perturb.go:47-60, which severs the
        docker network).  Severs every peer socket and refuses new
        connections while on; healing redials the persistent peers and
        lets PEX/reconnect rebuild the rest."""
        self._partitioned = on
        if on:
            for peer in self.peers.list():
                self.stop_peer(peer, "network partition (e2e perturbation)")
        else:
            self.dial_peers_async(list(self.persistent_addrs), persistent=True)

    def stop_peer_for_error(self, peer: Peer, reason: str) -> None:
        """Disconnect a misbehaving peer (switch.go StopPeerForError);
        persistent peers are NOT redialed — they earned the boot."""
        self.logger.error(f"stopping peer {peer.id[:8]} for error: {reason}")
        self.stop_peer(peer, reason)

    def stop_peer(self, peer: Peer, reason: str = "") -> None:
        if not self.peers.remove(peer):
            return
        try:
            if peer.is_running():
                peer.stop()
        except Exception as e:  # noqa: BLE001 — teardown must reach every reactor
            # a peer that fails to stop cleanly still leaves the PeerSet;
            # dropping the error silently would hide socket/thread leaks
            self.logger.warning(
                f"peer {peer.id[:8]} stop failed "
                f"(reason={reason or 'unspecified'!s}): {e!r}"
            )
            _metrics_hub().p2p_errors.inc(site="peer_stop")
        for reactor in self.reactors.values():
            try:
                reactor.remove_peer(peer, reason)
            except Exception as e:  # noqa: BLE001
                self.logger.error(f"remove_peer error in {reactor.name}: {e}")

    # ----------------------------------------------------------- messaging

    def broadcast(self, stream_id: int, msg: bytes) -> None:
        """Queue msg to every peer (switch.go:250 Broadcast)."""
        for peer in self.peers.list():
            peer.try_send(stream_id, msg)

    def num_peers(self) -> int:
        return self.peers.size()
