"""Address book: known peer addresses in hashed new/old buckets
(reference: p2p/pex/addrbook.go, 921 LoC).

Same structure as the reference — addresses enter "new" buckets keyed by
(source, address) hashing, get promoted to "old" buckets when a
connection succeeds, and are evicted bucket-locally when full — with the
file format simplified to one JSON document.
"""

from __future__ import annotations

import hashlib
import json
import os
import random
import threading
import time
from dataclasses import dataclass, field

NEW_BUCKET_COUNT = 256
OLD_BUCKET_COUNT = 64
BUCKET_SIZE = 64
# how often a mostly-old book still answers with new addresses
BIAS_TOWARDS_NEW = 0.3
MAX_ATTEMPTS = 3


@dataclass
class KnownAddress:
    """addrbook.go knownAddress."""

    addr: str  # id@host:port
    src: str  # peer id that told us
    attempts: int = 0
    last_attempt: float = 0.0
    last_success: float = 0.0
    bucket_type: str = "new"  # "new" | "old"
    bucket: int = -1

    @property
    def peer_id(self) -> str:
        return self.addr.split("@", 1)[0] if "@" in self.addr else ""

    def is_bad(self) -> bool:
        """Too many failed attempts without a success (knownAddress.isBad)."""
        return self.attempts >= MAX_ATTEMPTS and self.last_success == 0


class AddrBook:
    def __init__(self, file_path: str = "", key: bytes | None = None):
        self.file_path = file_path
        self.key = key or os.urandom(24)
        self._mtx = threading.Lock()
        self._addrs: dict[str, KnownAddress] = {}  # peer id -> record
        self._new: list[set[str]] = [set() for _ in range(NEW_BUCKET_COUNT)]
        self._old: list[set[str]] = [set() for _ in range(OLD_BUCKET_COUNT)]
        self._rng = random.Random()
        if file_path and os.path.exists(file_path):
            self._load()

    # ------------------------------------------------------------- writes

    def add_address(self, addr: str, src: str = "") -> bool:
        """A peer (or config) told us about addr (addrbook.go AddAddress)."""
        pid = addr.split("@", 1)[0] if "@" in addr else ""
        if not pid or ":" not in addr:
            return False
        with self._mtx:
            ka = self._addrs.get(pid)
            if ka is not None:
                if ka.bucket_type == "old":
                    return False  # a vetted address sticks until it fails
                if ka.addr != addr:
                    # the peer moved: adopt the fresh address, reset history
                    ka.addr = addr
                    ka.src = src
                    ka.attempts = 0
                    return True
                return False
            ka = KnownAddress(addr=addr, src=src)
            b = self._bucket_for(addr, src, NEW_BUCKET_COUNT)
            ka.bucket = b
            self._addrs[pid] = ka
            self._evict_if_full(self._new[b], "new")
            self._new[b].add(pid)
            return True

    def mark_attempt(self, addr: str) -> None:
        with self._mtx:
            ka = self._lookup(addr)
            if ka:
                ka.attempts += 1
                ka.last_attempt = time.time()

    def mark_good(self, addr: str) -> None:
        """Successful handshake: promote to an old bucket
        (addrbook.go MarkGood)."""
        with self._mtx:
            ka = self._lookup(addr)
            if ka is None:
                return
            ka.attempts = 0
            ka.last_success = time.time()
            if ka.bucket_type == "new":
                self._new[ka.bucket].discard(ka.peer_id)
                b = self._bucket_for(ka.addr, "", OLD_BUCKET_COUNT)
                self._evict_if_full(self._old[b], "old")
                self._old[b].add(ka.peer_id)
                ka.bucket_type, ka.bucket = "old", b

    def mark_bad(self, addr: str) -> None:
        with self._mtx:
            ka = self._lookup(addr)
            if ka is not None and ka.is_bad():
                self._remove(ka)

    def remove_address(self, addr: str) -> None:
        with self._mtx:
            ka = self._lookup(addr)
            if ka is not None:
                self._remove(ka)

    # -------------------------------------------------------------- reads

    def size(self) -> int:
        with self._mtx:
            return len(self._addrs)

    def is_empty(self) -> bool:
        return self.size() == 0

    def pick_address(self, new_bias: float = BIAS_TOWARDS_NEW) -> str | None:
        """Random address for dialing, biased between new/old
        (addrbook.go PickAddress)."""
        with self._mtx:
            news = [a for a in self._addrs.values() if a.bucket_type == "new" and not a.is_bad()]
            olds = [a for a in self._addrs.values() if a.bucket_type == "old" and not a.is_bad()]
            pool = None
            if news and (not olds or self._rng.random() < new_bias):
                pool = news
            elif olds:
                pool = olds
            if not pool:
                return None
            return self._rng.choice(pool).addr

    def get_selection(self, max_count: int = 30) -> list[str]:
        """Random selection to answer a PEX request
        (addrbook.go GetSelection)."""
        with self._mtx:
            good = [a.addr for a in self._addrs.values() if not a.is_bad()]
            self._rng.shuffle(good)
            return good[:max_count]

    def has(self, addr: str) -> bool:
        with self._mtx:
            return self._lookup(addr) is not None

    # ---------------------------------------------------------- internals

    def _lookup(self, addr: str) -> KnownAddress | None:
        pid = addr.split("@", 1)[0] if "@" in addr else addr
        return self._addrs.get(pid)

    def _remove(self, ka: KnownAddress) -> None:
        (self._new if ka.bucket_type == "new" else self._old)[ka.bucket].discard(
            ka.peer_id
        )
        self._addrs.pop(ka.peer_id, None)

    def _bucket_for(self, addr: str, src: str, n: int) -> int:
        h = hashlib.sha256(self.key + addr.encode() + b"|" + src.encode()).digest()
        return int.from_bytes(h[:8], "big") % n

    def _evict_if_full(self, bucket: set[str], kind: str) -> None:
        if len(bucket) < BUCKET_SIZE:
            return
        # evict the worst: bad first, then oldest attempt
        members = [self._addrs[p] for p in bucket if p in self._addrs]
        members.sort(key=lambda a: (not a.is_bad(), a.last_success, -a.attempts))
        victim = members[0]
        bucket.discard(victim.peer_id)
        self._addrs.pop(victim.peer_id, None)

    # ---------------------------------------------------------- persistence

    def save(self) -> None:
        if not self.file_path:
            return
        with self._mtx:
            data = {
                "key": self.key.hex(),
                "addrs": [
                    {
                        "addr": a.addr,
                        "src": a.src,
                        "attempts": a.attempts,
                        "last_success": a.last_success,
                        "bucket_type": a.bucket_type,
                    }
                    for a in self._addrs.values()
                ],
            }
        os.makedirs(os.path.dirname(self.file_path) or ".", exist_ok=True)
        with open(self.file_path, "w") as f:
            json.dump(data, f)

    def _load(self) -> None:
        try:
            with open(self.file_path) as f:
                data = json.load(f)
        except (OSError, json.JSONDecodeError):
            return
        # the book file is on-disk input: a corrupt or type-confused
        # document must raise a typed error, not a KeyError/TypeError
        # from half-read records
        try:
            self.key = bytes.fromhex(data.get("key", self.key.hex()))
            for rec in data.get("addrs", []):
                self.add_address(rec["addr"], rec.get("src", ""))
                ka = self._lookup(rec["addr"])
                if ka and rec.get("bucket_type") == "old":
                    self.mark_good(rec["addr"])
                    ka.last_success = rec.get("last_success", time.time())
        except ValueError:
            raise
        except Exception as e:  # noqa: BLE001 — malformed document shape
            raise ValueError(f"corrupt addrbook file {self.file_path}: {e!r}") from e
