"""Peer exchange (reference: p2p/pex/)."""

from .addrbook import AddrBook, KnownAddress
from .reactor import PEX_STREAM, PexReactor

__all__ = ["AddrBook", "KnownAddress", "PexReactor", "PEX_STREAM"]
