"""PEX reactor: peer-address exchange + dialing to keep the switch full
(reference: p2p/pex/pex_reactor.go).

Every peer gets asked for addresses on an interval; requests are
answered from the address book; an ensure-peers loop dials book picks
while the switch is below its outbound target.  Seed-mode crawling is a
config flag on the same machinery: answer and hang up.
"""

from __future__ import annotations

import threading
import time

from ...types.msg_validation import validate_pex_message
from ...utils.log import get_logger
from ...wire import p2p_pb as pb
from ..conn.connection import StreamDescriptor
from ..reactor import Reactor
from .addrbook import AddrBook

PEX_STREAM = 0x00

REQUEST_INTERVAL = 120.0  # pex_reactor.go defaultEnsurePeersPeriod-ish
ENSURE_PEERS_PERIOD = 30.0
MIN_REQUEST_INTERVAL = 20.0  # rate-limit incoming requests per peer


class PexReactor(Reactor):
    def __init__(
        self,
        book: AddrBook,
        seed_mode: bool = False,
        ensure_period: float = ENSURE_PEERS_PERIOD,
        request_interval: float = REQUEST_INTERVAL,
        target_outbound: int = 10,
    ):
        super().__init__("PexReactor")
        self.book = book
        self.seed_mode = seed_mode
        self.ensure_period = ensure_period
        self.request_interval = request_interval
        self.target_outbound = target_outbound
        self.logger = get_logger("pex")
        self._last_request_from: dict[str, float] = {}
        self._requested: set[str] = set()
        self._mtx = threading.Lock()

    def stream_descriptors(self) -> list[StreamDescriptor]:
        return [StreamDescriptor(id=PEX_STREAM, priority=1, send_queue_capacity=10)]

    # ------------------------------------------------------------ lifecycle

    def on_start(self) -> None:
        threading.Thread(
            target=self._ensure_peers_routine, daemon=True, name="pex-ensure"
        ).start()

    # --------------------------------------------------------------- peers

    def add_peer(self, peer) -> None:
        # learn the peer's self-reported address; dialed peers are vetted
        addr = peer.get("dial_addr")
        if addr:
            self.book.add_address(addr, src=peer.id)
            self.book.mark_good(addr)
        elif peer.node_info.listen_addr:
            # inbound peer: record its claimed listen address as unvetted
            host = peer.node_info.listen_addr
            host = host[len("tcp://"):] if host.startswith("tcp://") else host
            if not host.startswith("0.0.0.0") and ":" in host:
                self.book.add_address(f"{peer.id}@{host}", src=peer.id)
        if peer.has_channel(PEX_STREAM):
            threading.Thread(
                target=self._request_routine, args=(peer,), daemon=True,
                name=f"pex-request-{peer.id[:8]}",
            ).start()

    def remove_peer(self, peer, reason: str = "") -> None:
        with self._mtx:
            self._last_request_from.pop(peer.id, None)
            self._requested.discard(peer.id)

    # ------------------------------------------------------------- receive

    def receive(self, stream_id: int, peer, msg_bytes: bytes) -> None:
        msg = pb.PexMessage.decode(msg_bytes)
        # validate-before-use: bound the address count and require every
        # URL to parse as id@host:port before anything reaches the book —
        # a raise here makes the switch disconnect the peer
        validate_pex_message(msg)
        if msg.pex_request is not None:
            now = time.monotonic()
            with self._mtx:
                last = self._last_request_from.get(peer.id, 0.0)
                if now - last < MIN_REQUEST_INTERVAL:
                    self.logger.info(f"peer {peer.id[:8]} over-requests PEX")
                    return
                self._last_request_from[peer.id] = now
            selection = self.book.get_selection()
            peer.try_send(
                PEX_STREAM,
                pb.PexMessage(
                    pex_addrs=pb.PexAddrs(
                        addrs=[pb.PexAddress(url=a) for a in selection]
                    )
                ).encode(),
            )
            if self.seed_mode and self.switch is not None:
                # seeds serve addresses then disconnect (pex_reactor.go
                # seed mode)
                self.switch.stop_peer(peer, "seed: served addresses")
        elif msg.pex_addrs is not None:
            with self._mtx:
                solicited = peer.id in self._requested
                self._requested.discard(peer.id)
            if not solicited:
                return  # unsolicited address dumps are spam
            for a in msg.pex_addrs.addrs or []:
                if a.url:
                    self.book.add_address(a.url, src=peer.id)

    # ------------------------------------------------------------ routines

    def _request_routine(self, peer) -> None:
        while self.is_running() and peer.is_running():
            with self._mtx:
                self._requested.add(peer.id)
            peer.try_send(
                PEX_STREAM,
                pb.PexMessage(pex_request=pb.PexRequest()).encode(),
            )
            deadline = time.monotonic() + self.request_interval
            while time.monotonic() < deadline:
                if not (self.is_running() and peer.is_running()):
                    return
                time.sleep(0.5)

    def _ensure_peers_routine(self) -> None:
        """Dial book addresses while below the outbound target
        (pex_reactor.go ensurePeers)."""
        while self.is_running():
            try:
                self._ensure_peers()
                self.book.save()  # addrbook.go dumpAddressInterval
            except Exception as e:  # noqa: BLE001
                self.logger.error(f"ensure peers: {e}")
            deadline = time.monotonic() + self.ensure_period
            while time.monotonic() < deadline:
                if not self.is_running():
                    return
                time.sleep(0.5)

    def _ensure_peers(self) -> None:
        if self.switch is None:
            return
        out = sum(1 for p in self.switch.peers.list() if p.outbound)
        need = self.target_outbound - out
        if need <= 0:
            return
        connected = {p.id for p in self.switch.peers.list()}
        tried = set()
        for _ in range(need * 3):
            addr = self.book.pick_address()
            if addr is None:
                break
            if addr in tried:
                continue  # re-picked: keep spending the dial budget
            tried.add(addr)
            pid = addr.split("@", 1)[0]
            if pid in connected or pid == self.switch.transport.node_key.id():
                continue
            self.logger.info(f"pex dialing {addr}")
            self.book.mark_attempt(addr)
            self.switch.dial_peer_async(addr)
            need -= 1
            if need <= 0:
                break
