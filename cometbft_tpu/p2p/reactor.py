"""Reactor interface (reference: p2p/base_reactor.go:15-44).

A reactor registers stream descriptors with the Switch, gets told about
peers joining/leaving, and receives complete messages per stream.
"""

from __future__ import annotations

from ..utils.service import Service
from .conn.connection import StreamDescriptor


class Reactor(Service):
    def __init__(self, name: str):
        super().__init__(name)
        self.switch = None

    def set_switch(self, sw) -> None:
        self.switch = sw

    def stream_descriptors(self) -> list[StreamDescriptor]:
        return []

    def init_peer(self, peer) -> None:
        """Called before the peer starts (setup per-peer state)."""

    def add_peer(self, peer) -> None:
        """Called once the peer is running (start gossip routines)."""

    def remove_peer(self, peer, reason: str = "") -> None:
        pass

    def receive(self, stream_id: int, peer, msg_bytes: bytes) -> None:
        pass

    def on_start(self) -> None:
        pass

    def on_stop(self) -> None:
        pass
