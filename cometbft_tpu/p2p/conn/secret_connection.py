"""SecretConnection: authenticated encryption for peer links
(reference: p2p/transport/tcp/conn/secret_connection.go:67).

Station-to-Station protocol with the reference's construction:
  1. exchange ephemeral X25519 keys
  2. ECDH → HKDF-SHA256 → two ChaCha20-Poly1305 keys (one per direction,
     lexicographic ephemeral-key order decides which is whose) + a
     challenge transcript hash
  3. exchange Ed25519 identity proofs: sig over the challenge; the
     authenticated remote pubkey becomes the peer's verified identity
  4. all subsequent traffic in 1024-byte sealed frames with u64-LE nonce
     counters (secret_connection.go:33-50)

The reference hashes the transcript with Merlin/STROBE; this
implementation uses HKDF-SHA256 over the sorted ephemeral keys — same
security shape (the two sides derive identical keys and a shared
challenge bound to the DH result), not byte-compatible with Go peers.

DECISION (round 5, explicit): keep the HKDF transcript permanently.
Merlin requires a STROBE/Keccak-duplex implementation whose only value
here would be byte-level interop with Go peers for mixed-fleet
differential testing — which this environment cannot run anyway (no Go
toolchain), and which the framework does not need: both ends of every
link run this stack, and the protocol-level wire format (frames,
nonces, proofs) matches the reference. The deviation is confined to
this file's key-schedule; swapping in a STROBE transcript later would
not change any other layer.
"""

from __future__ import annotations

import os
import struct
import threading

try:
    from cryptography.hazmat.primitives import hashes, serialization
    from cryptography.hazmat.primitives.asymmetric.x25519 import (
        X25519PrivateKey,
        X25519PublicKey,
    )
    from cryptography.hazmat.primitives.ciphers.aead import ChaCha20Poly1305
    from cryptography.hazmat.primitives.kdf.hkdf import HKDF

    _HAVE_OPENSSL = True
except ImportError:  # no OpenSSL bindings: RFC-exact pure-Python fallback
    from ...crypto._purecrypto import ChaCha20Poly1305  # noqa: F401

    _HAVE_OPENSSL = False

from ...crypto import ed25519

DATA_LEN_SIZE = 4
DATA_MAX_SIZE = 1024  # secret_connection.go totalFrameSize 1028 - 4
TOTAL_FRAME_SIZE = DATA_MAX_SIZE + DATA_LEN_SIZE
AEAD_TAG_SIZE = 16
SEALED_FRAME_SIZE = TOTAL_FRAME_SIZE + AEAD_TAG_SIZE


class SecretConnectionError(Exception):
    pass


class _NonceCounter:
    """96-bit nonce: 4 zero bytes + u64 little-endian counter."""

    def __init__(self):
        self._n = 0

    def next(self) -> bytes:
        nonce = b"\x00\x00\x00\x00" + struct.pack("<Q", self._n)
        self._n += 1
        if self._n >= 1 << 64:
            raise SecretConnectionError("nonce exhausted")
        return nonce


def _read_exact(sock, n: int) -> bytes:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise SecretConnectionError("connection closed during read")
        buf += chunk
    return buf


class SecretConnection:
    """Wraps a socket; construct via make_secret_connection."""

    def __init__(self, sock, send_key: bytes, recv_key: bytes, remote_pub: ed25519.PubKey):
        self._sock = sock
        self._send_aead = ChaCha20Poly1305(send_key)
        self._recv_aead = ChaCha20Poly1305(recv_key)
        self._send_nonce = _NonceCounter()
        self._recv_nonce = _NonceCounter()
        self._send_mtx = threading.Lock()
        self._recv_mtx = threading.Lock()
        self._recv_buf = b""
        self.remote_pub = remote_pub

    # --------------------------------------------------------------- io

    def write(self, data: bytes) -> int:
        """Frame + seal + send (secret_connection.go Write)."""
        total = 0
        view = memoryview(data)
        with self._send_mtx:
            out = bytearray()
            while view:
                chunk = bytes(view[:DATA_MAX_SIZE])
                view = view[len(chunk):]
                frame = struct.pack("<I", len(chunk)) + chunk
                frame += b"\x00" * (TOTAL_FRAME_SIZE - len(frame))
                out += self._send_aead.encrypt(self._send_nonce.next(), frame, None)
                total += len(chunk)
            self._sock.sendall(bytes(out))
        return total

    def read(self, n: int) -> bytes:
        """Read up to n plaintext bytes (one frame at a time)."""
        with self._recv_mtx:
            if not self._recv_buf:
                sealed = _read_exact(self._sock, SEALED_FRAME_SIZE)
                try:
                    frame = self._recv_aead.decrypt(
                        self._recv_nonce.next(), sealed, None
                    )
                except Exception:
                    raise SecretConnectionError("frame authentication failed")
                (length,) = struct.unpack_from("<I", frame)
                if length > DATA_MAX_SIZE:
                    raise SecretConnectionError("invalid frame length")
                self._recv_buf = frame[DATA_LEN_SIZE : DATA_LEN_SIZE + length]
            out, self._recv_buf = self._recv_buf[:n], self._recv_buf[n:]
            return out

    def read_exact(self, n: int) -> bytes:
        buf = b""
        while len(buf) < n:
            chunk = self.read(n - len(buf))
            if not chunk:
                raise SecretConnectionError("short read")
            buf += chunk
        return buf

    def close(self) -> None:
        import socket as _socket

        # shutdown() wakes any thread blocked in recv() (ours and the
        # remote's) — close() alone leaves them stuck
        try:
            self._sock.shutdown(_socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self._sock.close()
        except OSError:
            pass


def _gen_ephemeral() -> tuple[object, bytes]:
    """X25519 keypair: (handle for _exchange, raw 32-byte public)."""
    if _HAVE_OPENSSL:
        eph_priv = X25519PrivateKey.generate()
        return eph_priv, eph_priv.public_key().public_bytes(
            serialization.Encoding.Raw, serialization.PublicFormat.Raw
        )
    from ...crypto import _purecrypto

    seed = os.urandom(32)
    return seed, _purecrypto.x25519_public(seed)


def _exchange(eph_priv, remote_eph: bytes) -> bytes:
    if _HAVE_OPENSSL:
        return eph_priv.exchange(X25519PublicKey.from_public_bytes(remote_eph))
    from ...crypto import _purecrypto

    return _purecrypto.x25519(eph_priv, remote_eph)


def _hkdf_derive(shared: bytes, info: bytes, length: int) -> bytes:
    if _HAVE_OPENSSL:
        return HKDF(
            algorithm=hashes.SHA256(), length=length, salt=None, info=info
        ).derive(shared)
    from ...crypto import _purecrypto

    return _purecrypto.hkdf_sha256(shared, length, info)


def make_secret_connection(sock, priv_key: ed25519.PrivKey) -> SecretConnection:
    """Perform the STS handshake over sock (blocking)."""
    eph_priv, eph_pub = _gen_ephemeral()

    # 1. exchange ephemerals (raw 32 bytes each way)
    sock.sendall(eph_pub)
    remote_eph = _read_exact(sock, 32)

    if remote_eph == eph_pub:
        # an echo of our own ephemeral key is a reflection attack: both
        # directions would share one key/nonce stream and our own auth
        # frame would "prove" our identity back to us
        sock.close()
        raise SecretConnectionError("reflected ephemeral key")

    lo, hi = sorted([eph_pub, remote_eph])
    we_are_lo = eph_pub == lo

    # 2. shared secret -> directional keys + challenge
    shared = _exchange(eph_priv, remote_eph)
    okm = _hkdf_derive(
        shared,
        b"COMETBFT_TPU_SECRET_CONNECTION_KEY_AND_CHALLENGE_GEN" + lo + hi,
        96,
    )
    key_lo, key_hi, challenge = okm[:32], okm[32:64], okm[64:]
    send_key, recv_key = (key_lo, key_hi) if we_are_lo else (key_hi, key_lo)

    conn = SecretConnection(sock, send_key, recv_key, remote_pub=None)

    # 3. authenticate: send our pubkey + signature over the challenge
    sig = priv_key.sign(challenge)
    conn.write(priv_key.pub_key().data + sig)
    auth = conn.read_exact(32 + 64)
    remote_pub = ed25519.PubKey(auth[:32])
    if not remote_pub.verify_signature(challenge, auth[32:]):
        conn.close()
        raise SecretConnectionError("peer identity proof failed")
    conn.remote_pub = remote_pub
    return conn
