"""MConnection: N prioritized streams multiplexed over one connection
(reference: p2p/transport/tcp/conn/connection.go:68).

Messages are chunked into ≤1024-byte PacketMsg frames (EOF bit marks the
last chunk); the send routine picks the next stream by lowest
sent-bytes/priority ratio (connection.go sendPacketMsg), throttles
flushes, and exchanges ping/pong keepalives.  Each stream reassembles
its own incoming message buffer and hands complete messages to the
reactor's receive callback.
"""

from __future__ import annotations

import queue
import struct
import threading
import time
from dataclasses import dataclass
from typing import Callable

from ...utils.log import get_logger
from ...utils.metrics import hub as _metrics_hub
from ...utils.service import Service
from ...wire import p2p_pb
from ...wire.proto import decode_varint, encode_varint

MAX_PACKET_PAYLOAD_SIZE = 1024  # connection.go:28
MAX_PACKET_WIRE_SIZE = 4096  # sanity cap on one framed packet
DEFAULT_SEND_QUEUE_CAPACITY = 1
DEFAULT_RECV_MESSAGE_CAPACITY = 22020096  # 21MB (connection.go)
FLUSH_THROTTLE = 0.010  # 10ms (connection.go:38)
PING_INTERVAL = 60.0
PONG_TIMEOUT = 45.0
SEND_RATE = 5_120_000  # bytes/sec (connection.go:40 defaultSendRate)
RECV_RATE = 5_120_000  # bytes/sec (connection.go:41 defaultRecvRate)


@dataclass
class StreamDescriptor:
    """(reference: p2p/base_reactor.go StreamDescriptor / ChannelDescriptor)."""

    id: int
    priority: int = 1
    send_queue_capacity: int = 100
    recv_message_capacity: int = DEFAULT_RECV_MESSAGE_CAPACITY


class _Stream:
    def __init__(self, desc: StreamDescriptor):
        self.desc = desc
        self.send_queue: queue.Queue[bytes] = queue.Queue(
            maxsize=max(desc.send_queue_capacity, 1)
        )
        self.sending: bytes | None = None
        self.sent_pos = 0
        self.recently_sent = 0
        self.recv_buf = bytearray()

    def ratio(self) -> float:
        return self.recently_sent / max(self.desc.priority, 1)

    def next_packet(self) -> p2p_pb.PacketMsg | None:
        if self.sending is None:
            try:
                self.sending = self.send_queue.get_nowait()
                self.sent_pos = 0
            except queue.Empty:
                return None
        chunk = self.sending[self.sent_pos : self.sent_pos + MAX_PACKET_PAYLOAD_SIZE]
        self.sent_pos += len(chunk)
        eof = self.sent_pos >= len(self.sending)
        pkt = p2p_pb.PacketMsg(channel_id=self.desc.id, eof=eof, data=chunk)
        if eof:
            self.sending = None
            self.sent_pos = 0
        self.recently_sent += len(chunk)
        return pkt

    def has_data(self) -> bool:
        return self.sending is not None or not self.send_queue.empty()


class MConnection(Service):
    """conn must expose write(bytes), read(n)->bytes, close()
    (a SecretConnection or any socket-like duplex)."""

    def __init__(
        self,
        conn,
        stream_descs: list[StreamDescriptor],
        on_receive: Callable[[int, bytes], None],
        on_error: Callable[[Exception], None] | None = None,
        flush_throttle: float = FLUSH_THROTTLE,
        ping_interval: float = PING_INTERVAL,
        pong_timeout: float = PONG_TIMEOUT,
        send_rate: int = SEND_RATE,
        recv_rate: int = RECV_RATE,
    ):
        super().__init__("MConnection")
        self.conn = conn
        self.streams = {d.id: _Stream(d) for d in stream_descs}
        self.on_receive = on_receive
        self.on_error = on_error or (lambda e: None)
        self.flush_throttle = flush_throttle
        self.ping_interval = ping_interval
        self.pong_timeout = pong_timeout
        # flow control (connection.go:40-41): one noisy peer must not
        # saturate the node; throttling blocks the per-conn IO threads only
        from ...utils.flowrate import Limiter

        self.send_monitor = Limiter(send_rate)
        self.recv_monitor = Limiter(recv_rate)
        self.logger = get_logger("mconn")
        self._send_signal = threading.Event()
        self._pong_pending = threading.Event()
        self._last_pong = time.monotonic()
        self._send_thread: threading.Thread | None = None
        self._recv_thread: threading.Thread | None = None
        self._errored = False

    def on_start(self) -> None:
        self._send_thread = threading.Thread(
            target=self._send_routine, name="mconn-send", daemon=True
        )
        self._recv_thread = threading.Thread(
            target=self._recv_routine, name="mconn-recv", daemon=True
        )
        self._send_thread.start()
        self._recv_thread.start()

    def on_stop(self) -> None:
        # deliberate stop: suppress the error callbacks the dying reader/
        # writer threads are about to fire
        self._errored = True
        self._send_signal.set()
        self.conn.close()

    def _error(self, e: Exception) -> None:
        if not self._errored:
            self._errored = True
            try:
                self.stop()
            except Exception as stop_err:  # noqa: BLE001 — on_error must still fire
                # the teardown failing is secondary to the original error
                # `e`, but a silent drop here hides leaked sockets/threads
                self.logger.warning(
                    f"mconn stop failed while handling {e!r}: {stop_err!r}"
                )
                _metrics_hub().p2p_errors.inc(site="mconn_stop")
            self.on_error(e)

    # ------------------------------------------------------------ sending

    def send(self, stream_id: int, msg: bytes, timeout: float | None = 10.0) -> bool:
        """Queue msg on the stream; False if the queue stayed full
        (connection.go Send)."""
        st = self.streams.get(stream_id)
        if st is None or not self.is_running():
            return False
        if self._fault_drop():
            return True  # injected loss: swallowed, reported delivered
        try:
            st.send_queue.put(msg, timeout=timeout)
        except queue.Full:
            return False
        self._send_signal.set()
        return True

    def try_send(self, stream_id: int, msg: bytes) -> bool:
        st = self.streams.get(stream_id)
        if st is None or not self.is_running():
            return False
        if self._fault_drop():
            return True  # injected loss: swallowed, reported delivered
        try:
            st.send_queue.put_nowait(msg)
        except queue.Full:
            return False
        self._send_signal.set()
        return True

    @staticmethod
    def _fault_drop() -> bool:
        """Chaos seam (utils/fail, fault ``drop_p2p_pct``): silently
        drop a percentage of outbound messages — a lossy link without
        tc/netem, exercising the gossip retransmission paths.  One
        module-bool check when unarmed."""
        from ...utils import fail

        pct = fail.armed("drop_p2p_pct")
        return pct is not None and fail.should_drop(pct)

    @staticmethod
    def _fault_delay() -> None:
        """Chaos seam (utils/fail, fault ``delay_p2p_ms``): delay the
        wire write by the armed milliseconds ±50% jitter — a laggy link
        next to the drop seam's lossy one, so network-flaky soaks can
        shape latency as well as loss.  Runs on the send ROUTINE (the
        dedicated writer thread), never a caller: reactors keep queueing
        at full speed while the link itself lags, exactly like real
        latency.  One module-bool check when unarmed."""
        from ...utils import fail

        ms = fail.armed("delay_p2p_ms")
        if ms:
            fail.jittered_sleep(ms)

    def _pick_stream(self) -> _Stream | None:
        """Lowest sent/priority ratio wins (connection.go sendPacketMsg)."""
        best = None
        for st in self.streams.values():
            if not st.has_data():
                continue
            if best is None or st.ratio() < best.ratio():
                best = st
        return best

    def _send_routine(self) -> None:
        last_ping = time.monotonic()
        self._last_pong = time.monotonic()
        out = bytearray()
        try:
            while self.is_running():
                self._send_signal.wait(timeout=self.flush_throttle)
                self._send_signal.clear()
                now = time.monotonic()
                if now - self._last_pong > self.ping_interval + self.pong_timeout:
                    raise ConnectionError("pong timeout: peer unresponsive")
                if now - last_ping > self.ping_interval:
                    out += self._frame(p2p_pb.Packet(ping=p2p_pb.PacketPing()))
                    last_ping = now
                # drain up to a batch of packets each pass
                for _ in range(64):
                    st = self._pick_stream()
                    if st is None:
                        break
                    pkt = st.next_packet()
                    if pkt is None:
                        break
                    frame = self._frame(p2p_pb.Packet(msg=pkt))
                    m = _metrics_hub()
                    m.p2p_send_bytes.inc(len(frame), ch_id=str(pkt.channel_id))
                    if pkt.eof:
                        # count MESSAGES on the eof chunk, not packets —
                        # the count counter pairs with the byte counter
                        # the way the reference's MessageSendBytes does
                        m.p2p_send_count.inc(ch_id=str(pkt.channel_id))
                    out += frame
                if out:
                    self._fault_delay()
                    self.send_monitor.throttle(len(out))
                    self.conn.write(bytes(out))
                    del out[:]
                # decay the ratio counters so long-lived conns stay fair
                for st in self.streams.values():
                    st.recently_sent = int(st.recently_sent * 0.8)
        except Exception as e:  # noqa: BLE001
            self._error(e)

    @staticmethod
    def _frame(pkt: p2p_pb.Packet) -> bytes:
        payload = pkt.encode()
        return encode_varint(len(payload)) + payload

    # ---------------------------------------------------------- receiving

    def _recv_routine(self) -> None:
        try:
            while self.is_running():
                pkt = self._read_packet()
                which = pkt.which()
                if which == "ping":
                    self.conn.write(self._frame(p2p_pb.Packet(pong=p2p_pb.PacketPong())))
                elif which == "pong":
                    self._last_pong = time.monotonic()
                elif which == "msg":
                    _metrics_hub().p2p_recv_bytes.inc(
                        len(pkt.msg.data or b""), ch_id=str(pkt.msg.channel_id)
                    )
                    self._recv_msg(pkt.msg)
                else:
                    raise ValueError("empty packet")
        except Exception as e:  # noqa: BLE001
            self._error(e)

    def _read_packet(self) -> p2p_pb.Packet:
        # varint length prefix, then payload
        prefix = b""
        while True:
            b = self.conn.read(1)
            if not b:
                raise ConnectionError("connection closed")
            prefix += b
            try:
                length, _ = decode_varint(prefix)
                break
            except ValueError as e:
                if "truncated" not in str(e):
                    raise
                if len(prefix) > 10:
                    raise ValueError("bad packet length prefix")
        if length > MAX_PACKET_WIRE_SIZE:
            raise ValueError(f"packet length {length} exceeds cap")
        payload = self._read_exact(length)
        self.recv_monitor.throttle(len(prefix) + length)
        return p2p_pb.Packet.decode(payload)

    def _read_exact(self, n: int) -> bytes:
        buf = b""
        while len(buf) < n:
            chunk = self.conn.read(n - len(buf))
            if not chunk:
                raise ConnectionError("connection closed mid-packet")
            buf += chunk
        return buf

    def _recv_msg(self, pkt: p2p_pb.PacketMsg) -> None:
        st = self.streams.get(pkt.channel_id)
        if st is None:
            raise ValueError(f"unknown stream {pkt.channel_id}")
        st.recv_buf += pkt.data
        if len(st.recv_buf) > st.desc.recv_message_capacity:
            raise ValueError(
                f"stream {pkt.channel_id} message exceeds "
                f"{st.desc.recv_message_capacity} bytes"
            )
        if pkt.eof:
            msg = bytes(st.recv_buf)
            st.recv_buf = bytearray()
            _metrics_hub().p2p_recv_count.inc(ch_id=str(pkt.channel_id))
            self.on_receive(pkt.channel_id, msg)
