"""Authenticated encrypted multiplexed connections
(reference: p2p/transport/tcp/conn/).
"""
