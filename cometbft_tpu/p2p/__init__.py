"""P2P: the distributed communication backend (reference: p2p/).

Host-side TCP between validators (different trust domains — ICI/DCN
never cross nodes); inside one node the verification plane uses XLA
collectives instead (parallel/).
"""

from .key import NodeKey
from .conn.secret_connection import SecretConnection
from .conn.connection import MConnection, StreamDescriptor
from .peer import Peer
from .switch import Switch
from .transport import TCPTransport

__all__ = [
    "NodeKey",
    "SecretConnection",
    "MConnection",
    "StreamDescriptor",
    "Peer",
    "Switch",
    "TCPTransport",
]
