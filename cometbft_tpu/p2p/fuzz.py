"""Probabilistic connection fuzzer for resilience testing
(reference: p2p/internal/fuzz/fuzz.go).

Wraps any duplex conn (write/read/close) and randomly delays, drops, or
corrupts traffic.  Used by tests to confirm that peers survive (or
cleanly drop) garbage links — never in production paths.
"""

from __future__ import annotations

import random
import time

MODE_DROP = "drop"
MODE_DELAY = "delay"
MODE_CORRUPT = "corrupt"


class FuzzedConnection:
    def __init__(
        self,
        conn,
        prob_drop_rw: float = 0.0,
        prob_corrupt: float = 0.0,
        prob_sleep: float = 0.0,
        max_sleep: float = 0.1,
        start_after: float = 0.0,
        seed: int | None = None,
    ):
        self.conn = conn
        self.prob_drop_rw = prob_drop_rw
        self.prob_corrupt = prob_corrupt
        self.prob_sleep = prob_sleep
        self.max_sleep = max_sleep
        self._active_at = time.monotonic() + start_after
        self._rng = random.Random(seed)

    def _fuzzing(self) -> bool:
        return time.monotonic() >= self._active_at

    def _maybe_sleep(self) -> None:
        if self.prob_sleep and self._rng.random() < self.prob_sleep:
            time.sleep(self._rng.uniform(0, self.max_sleep))

    def _maybe_corrupt(self, data: bytes) -> bytes:
        if self.prob_corrupt and self._rng.random() < self.prob_corrupt and data:
            i = self._rng.randrange(len(data))
            flipped = bytes([data[i] ^ (1 << self._rng.randrange(8))])
            return data[:i] + flipped + data[i + 1:]
        return data

    # ------------------------------------------------------------- duplex

    def write(self, data: bytes):
        if self._fuzzing():
            if self.prob_drop_rw and self._rng.random() < self.prob_drop_rw:
                return len(data)  # silently swallowed
            self._maybe_sleep()
            data = self._maybe_corrupt(data)
        return self.conn.write(data)

    def read(self, n: int) -> bytes:
        data = self.conn.read(n)
        if self._fuzzing():
            if self.prob_drop_rw and self._rng.random() < self.prob_drop_rw:
                return b""  # reads as a closed/idle conn
            self._maybe_sleep()
            data = self._maybe_corrupt(data)
        return data

    def close(self) -> None:
        self.conn.close()
